"""North-star benchmark: score + bind 100k pending pods against a 10k-node
snapshot (BASELINE.md: < 2 s on a TPU v5e-4; this runs on however many chips
are visible — on >1 device the node axis is sharded over the mesh).

Prints ONE JSON line:
  {"metric": ..., "value": <seconds>, "unit": "s", "vs_baseline": <2.0/value>}

Method: the pod queue lives on device as [num_chunks, CHUNK, ...] stacked
columns; ONE jitted program lax.scans the full scheduling pipeline over the
chunks — LoadAware filter+score over each [CHUNK, N] matrix, quota
admission, top-k commit with priority-ordered conflict resolution — carrying
the snapshot between chunks. Stragglers are retried device-side: a fixed
number of tail passes pack the still-unplaced pod indices (argsort),
re-schedule them with more rounds and fall-through choices, and scatter the
results back into the assignment vector. The host never enters the loop;
the only device->host transfer is the final assignment readback (the bind
log). This is the TPU-native shape of the reference's scheduling cycle:
the per-pod Go loop became a resident device program, and "unschedulable
queue retry" (scheduleOne error path) became two more enqueued kernels.
"""

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# overridable for mesh smoke tests on small/virtual device counts; the
# driver-run configuration is the defaults
NUM_NODES = int(os.environ.get("BENCH_NODES", 10_000))
NUM_PODS = int(os.environ.get("BENCH_PODS", 100_000))
CHUNK = int(os.environ.get("BENCH_CHUNK", 2_000))
TAIL_PASSES = 2     # each retries up to CHUNK leftovers with a wider search
BASELINE_SECONDS = 2.0


def ensure_platform(probe_timeout: float = None) -> None:
    """Honor JAX_PLATFORMS and guard non-cpu targets with a subprocess
    probe (hard timeout): a wedged TPU tunnel hangs even trivial
    compiles at 0% CPU (observed 2026-07-30, a multi-hour outage), and a
    bench that hangs forever records nothing — on probe failure fall
    back to CPU and SAY so. An explicit helper, not an import side
    effect: callers pay the probe only when they run a bench."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    if plat == "cpu":
        return
    import subprocess

    if probe_timeout is None:
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
    ok = True
    try:
        # DEVNULL, not pipes: the platform plugin can spawn a tunnel
        # grandchild that would keep captured pipes open after the
        # timeout kill, wedging run() in communicate() forever
        probe = subprocess.run(
            [sys.executable, "-c",
             # the child must pin the SAME platform the parent will run
             # on (site config silently overrides the env var otherwise)
             "import os, jax;"
             "p = os.environ.get('JAX_PLATFORMS');"
             "p and jax.config.update('jax_platforms', p);"
             "import jax.numpy as jnp;"
             "jax.jit(lambda a: (a @ a.T).sum())(jnp.ones((64, 8)))"
             ".block_until_ready()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=probe_timeout)
        ok = probe.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        print("bench: WARNING: platform probe failed; falling back to "
              "CPU — the recorded number is NOT a TPU result",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")


def main():
    from koordinator_tpu.parallel import mesh as meshlib
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    if NUM_PODS % CHUNK:
        raise SystemExit(f"BENCH_PODS={NUM_PODS} must be a multiple of "
                         f"BENCH_CHUNK={CHUNK}")
    pods = synthetic.synthetic_pods(NUM_PODS, seed=1, num_quotas=32)
    cfg = LoadAwareConfig.make()

    # the queue as [C, CHUNK, ...] per-pod columns (scan operand)
    stacked = synthetic.stack_pod_chunks(pods, CHUNK)

    devices = jax.devices()
    if len(devices) > 1:
        # multi-chip: node columns sharded over the mesh (ICI); the pod
        # queue and quota/gang state replicate. GSPMD turns the top-k
        # select into a shard-local reduce + cross-chip merge.
        mesh = meshlib.make_mesh(devices)
        repl = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        put_snap = functools.partial(meshlib.shard_snapshot, mesh=mesh)
        put_repl = functools.partial(jax.device_put, device=repl)
    else:
        put_snap = jax.device_put
        put_repl = jax.device_put

    snap0 = put_snap(synthetic.synthetic_cluster(
        NUM_NODES, num_quotas=32, seed=0))
    stacked = put_repl(stacked)
    pods_dev = put_repl(pods)
    cfg = put_repl(cfg)

    # enable_numa=False: no pod in this workload requests CPU binding, the
    # batched analogue of the reference's state.skip NUMA fast path
    # (nodenumaresource scoring.go skipTheNode); workloads with bound pods
    # compile the enable_numa=True variant instead.
    step = functools.partial(core.schedule_batch, num_rounds=2, k_choices=8,
                             score_dims=(0, 1), approx_topk=True,
                             tie_break=True, enable_numa=False,
                             quota_depth=2, fit_dims=(0, 1, 2, 3))
    tail_step = functools.partial(core.schedule_batch, num_rounds=4,
                                  k_choices=32, score_dims=(0, 1),
                                  approx_topk=True, tie_break=True,
                                  enable_numa=False, quota_depth=2,
                                  fit_dims=(0, 1, 2, 3))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def sweep(snap, stacked, pods_dev, cfg):
        def body(snap, cols):
            # selector_match is batch-global; every per-pod column comes
            # from the scanned chunk
            chunk = pods_dev.replace(**cols)
            res = step(snap, chunk, cfg)
            return res.snapshot, res.assignment
        snap, assign = jax.lax.scan(body, snap, stacked)
        return snap, assign.reshape(-1)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def tail_pass(snap, assign, tried, pods_dev, cfg):
        """Retry up to CHUNK unplaced pods, packed device-side.

        Selection prefers NEVER-RETRIED leftovers (sort key 0) over
        already-retried ones (key 1), so the TAIL_PASSES*CHUNK capacity is
        genuinely exhausted: without the `tried` mask, a pass that placed
        nothing would re-select the same window and silently starve the
        rest. The gathered retry batch marks only true leftovers valid,
        so a pass with nothing left is a no-op on the snapshot.
        """
        bad = pods_dev.valid & (assign < 0)
        key = jnp.where(bad & ~tried, 0, jnp.where(bad, 1, 2))
        order = jnp.argsort(key, stable=True)
        idx = order[:CHUNK]
        retry = pods_dev.replace(
            **{f: getattr(pods_dev, f)[idx]
               for f in synthetic.PER_POD_FIELDS if f != "valid"},
            valid=bad[idx])
        tried = tried.at[idx].set(tried[idx] | bad[idx])
        res = tail_step(snap, retry, cfg)
        got = bad[idx] & (res.assignment >= 0)
        assign = assign.at[idx].set(
            jnp.where(got, res.assignment, assign[idx]))
        return res.snapshot, assign, tried

    @jax.jit
    def count_left(assign, pods_dev):
        return (pods_dev.valid & (assign < 0)).sum()

    @jax.jit
    def count_never_retried(assign, tried, pods_dev):
        return (pods_dev.valid & (assign < 0) & ~tried).sum()

    def full_pass(snap):
        snap, assign = sweep(snap, stacked, pods_dev, cfg)
        # device scalars, read back with the final assignment — no extra
        # sync in the timed region; they observe the bounded
        # TAIL_PASSES*CHUNK retry capacity
        left_after_sweep = count_left(assign, pods_dev)
        tried = jnp.zeros((NUM_PODS,), bool)
        for _ in range(TAIL_PASSES):
            snap, assign, tried = tail_pass(snap, assign, tried,
                                            pods_dev, cfg)
        never_retried = count_never_retried(assign, tried, pods_dev)
        # the ONLY device->host transfer: the bind log (+ two scalars)
        return (snap, np.asarray(assign), int(left_after_sweep),
                int(never_retried))

    # warmup/compile (both programs always run — no cold path in the timed
    # region regardless of how many stragglers the warm data produces)
    snap, assign, _, _ = full_pass(snap0)
    del snap

    # timed steady-state pass on a fresh snapshot
    snap1 = put_snap(synthetic.synthetic_cluster(
        NUM_NODES, num_quotas=32, seed=7))
    t0 = time.perf_counter()
    snap, assign, left_after_sweep, never_retried = full_pass(snap1)
    elapsed = time.perf_counter() - t0

    placed = int((assign >= 0).sum())
    retry_capacity = TAIL_PASSES * CHUNK
    if never_retried > 0:
        # the bound is real: these pods were reported unschedulable
        # without ever entering a retry pass — surface it
        print(f"bench: WARNING: {never_retried} stragglers were never "
              f"retried (tail retry capacity {retry_capacity} = "
              f"TAIL_PASSES={TAIL_PASSES} x CHUNK={CHUNK}, "
              f"{left_after_sweep} stragglers after the sweep); raise "
              f"TAIL_PASSES or CHUNK to widen the retry capacity",
              file=sys.stderr)
    result = {
        "metric": "score_bind_100k_pods_10k_nodes",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 2),
        "pods_per_sec": round(NUM_PODS / elapsed),
        "placed": placed,
        "stragglers_after_sweep": left_after_sweep,
        "never_retried": never_retried,
        "tail_retry_capacity": retry_capacity,
        "devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    ensure_platform()
    main()
