"""North-star benchmark: score + bind 100k pending pods against a 10k-node
snapshot (BASELINE.md: < 2 s on a TPU v5e-4; this runs on however many chips
are visible).

Prints ONE JSON line:
  {"metric": ..., "value": <seconds>, "unit": "s", "vs_baseline": <2.0/value>}

Method: the pod queue is processed in fixed-size chunks (static shapes, one
XLA program compiled once); each chunk runs the full pipeline — LoadAware
filter+score over the [chunk, N] matrix, quota admission, top-k commit with
priority-ordered conflict resolution — and the returned snapshot (device
-resident, donated) feeds the next chunk. One warmup pass compiles; the
timed pass measures steady-state scheduling throughput.
"""

import functools
import json
import time

import jax
import numpy as np

NUM_NODES = 10_000
NUM_PODS = 100_000
CHUNK = 2_000
BASELINE_SECONDS = 2.0


def main():
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    snap0 = synthetic_snapshot = synthetic.synthetic_cluster(
        NUM_NODES, num_quotas=32, seed=0)
    pods = synthetic.synthetic_pods(NUM_PODS, seed=1, num_quotas=32)
    cfg = LoadAwareConfig.make()

    snap0 = jax.device_put(snap0)
    chunks = [jax.device_put(synthetic.slice_batch(pods, i, CHUNK))
              for i in range(0, NUM_PODS, CHUNK)]

    # enable_numa=False: no pod in this workload requests CPU binding, the
    # batched analogue of the reference's state.skip NUMA fast path
    # (nodenumaresource scoring.go skipTheNode); chunks containing bound
    # pods would compile the enable_numa=True variant instead.
    step = jax.jit(
        functools.partial(core.schedule_batch, num_rounds=2, k_choices=8,
                          score_dims=(0, 1), approx_topk=True,
                          tie_break=True, enable_numa=False,
                          quota_depth=2, fit_dims=(0, 1, 2, 3)),
        donate_argnums=(0,))

    # tail cleanup: pods the fast passes left behind are retried once with
    # more rounds and fall-through choices (the reference's unschedulable-
    # queue retry, amortized into one extra chunk; still approx top-k —
    # exact lax.top_k is a full 20M-element sort on TPU)
    tail_step = jax.jit(
        functools.partial(core.schedule_batch, num_rounds=4, k_choices=32,
                          score_dims=(0, 1), approx_topk=True,
                          tie_break=True, enable_numa=False, quota_depth=2,
                          fit_dims=(0, 1, 2, 3)),
        donate_argnums=(0,))

    def full_pass(snap):
        assignments = []
        for chunk in chunks:
            res = step(snap, chunk, cfg)
            snap = res.snapshot
            assignments.append(res.assignment)
        # gather stragglers (one small D2H per chunk result) into a final
        # exact-retry batch, padded to the static chunk shape
        host_assign = [np.array(a) for a in assignments]
        leftovers = np.concatenate(
            [np.nonzero(a < 0)[0] + i * CHUNK
             for i, a in enumerate(host_assign)])
        if 0 < len(leftovers) <= CHUNK:
            idx = np.zeros((CHUNK,), np.int64)
            idx[:len(leftovers)] = leftovers
            retry = jax.tree_util.tree_map(
                lambda x: x, synthetic.slice_batch(pods, 0, CHUNK))
            retry = retry.replace(
                **{f: getattr(pods, f)[idx]
                   for f in synthetic.PER_POD_FIELDS if f != "valid"},
                valid=np.arange(CHUNK) < len(leftovers))
            res = tail_step(snap, jax.device_put(retry), cfg)
            snap = res.snapshot
            tail = np.asarray(res.assignment)
            for j, src in enumerate(leftovers):
                host_assign[src // CHUNK][src % CHUNK] = tail[j]
        else:
            np.asarray(assignments[-1])
        return snap, host_assign

    # warmup/compile
    snap, assignments = full_pass(snap0)
    placed_warm = sum(int((np.asarray(a) >= 0).sum()) for a in assignments)

    # timed steady-state pass on a fresh snapshot
    snap1 = jax.device_put(synthetic.synthetic_cluster(
        NUM_NODES, num_quotas=32, seed=7))
    t0 = time.perf_counter()
    snap, assignments = full_pass(snap1)
    elapsed = time.perf_counter() - t0

    placed = sum(int((np.asarray(a) >= 0).sum()) for a in assignments)
    result = {
        "metric": "score_bind_100k_pods_10k_nodes",
        "value": round(elapsed, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 2),
        "pods_per_sec": round(NUM_PODS / elapsed),
        "placed": placed,
        "devices": len(jax.devices()),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
