"""North-star benchmark: score + bind 100k pending pods against a 10k-node
snapshot (BASELINE.md: < 2 s on a TPU v5e-4; this runs on however many chips
are visible — on >1 device the node axis is sharded over the mesh).

Prints one JSON line per measured config; the CANONICAL north-star line is
LAST:
  {"metric": ..., "value": <seconds>, "unit": "s", "vs_baseline": <2.0/value>}
Preceding lines (driver-captured per round, BENCH_EXTRAS=0 to skip): the
BASELINE config 2/3/4/5 paths (bench_configs.py) and the FULL-GATE flagship
run — the same 100k x 10k scale with every plugin gate compiled in (NUMA
binding, GPU pods, taints, spread, anti/affinity), the faithful analogue of
the reference hot loop running every registered plugin for every pod
(framework_extender.go:204-259).

Method: the pod queue lives on device as [num_chunks, CHUNK, ...] stacked
columns; ONE jitted program lax.scans the full scheduling pipeline over the
chunks — LoadAware filter+score over each [CHUNK, N] matrix, quota
admission, top-k commit with priority-ordered conflict resolution — carrying
the snapshot AND the topology (group x domain) counts between chunks, so
spread/anti/affinity placements in one chunk constrain the next (the
cross-batch count rule in core.domain_machinery). The full-gate paths
additionally run the Filter->Score gate cascade (scheduler/cascade.py,
BENCH_CASCADE overrides): a cheap stage-1 candidate mask prunes the pair
space before the heavy per-pair gates run, bit-identically. Stragglers are
retried device-side: tail passes pack the still-unplaced pod indices
(argsort), re-schedule them with more rounds and fall-through choices, and
scatter the results back into the assignment vector. The tail ADAPTS: at
least MIN_TAIL_PASSES always run, then passes repeat while the straggler
count improves or never-retried windows remain, bounded by
BENCH_MAX_TAIL_PASSES — no fixed retry-capacity cliff. The adaptive loop
itself is DEVICE-RESIDENT by default (core.tail_compaction_loop, a
lax.while_loop over the compacted retry batches): sweep + tail are one
program, and the only device->host transfers are the final assignment
readback (the bind log) and ONE packed stats vector after the tail —
regardless of straggler count. BENCH_TAIL_MODE=host keeps the previous
host-driven orchestration (one straggler-count readback per adaptive
decision) as the conformance oracle for A/B runs; every emitted line
records `cascade` and `tail_mode` so runs are self-describing.

Multichip flagship (promoted from the __graft_entry__ dryrun): with >1
visible device the node axis of the snapshot is sharded over the mesh
and the SAME chunked sweep + device tail runs under GSPMD — stage-1
masks stay shard-local, the top-k select merges per-shard candidates
over ICI, and the tail keeps its single packed stats readback.
BENCH_DEVICES=n pins the device count (the virtual CPU mesh in CI, a
slice on hardware); BENCH_MESH_PODS=m folds the devices into a 2D
pods x nodes mesh (parallel/mesh.py). Node counts indivisible by the
mesh are padded with provably-unschedulable zero-capacity rows
(parallel.pad_nodes_to_mesh), and multi-device lines additionally stamp
the mesh axis sizes. Placements are bit-identical to the single-device
program (exact top-k path) — tools/mesh_flagship_smoke.py and the slow
mesh conformance test pin it, placement-for-placement.

Warm-start + packing (round 11): every line stamps `compile_s` (summed
XLA compile-or-retrieve wall time of the warmup), `warm_start_s` (full
warmup wall), and `cache=cold|miss|hit` — cold means no cache dir,
miss means real compiles happened, hit means the persistent cache
served everything. BENCH_COMPILE_CACHE=<dir> opts into the
contract-keyed compile cache (SAME HOST only — see compilecache/);
BENCH_PRECOMPILE=1 first warms the enumerated working set through it
(koordinator_tpu/compilecache/precompile.py) so the measured run
starts warm; BENCH_PACK_SNAPSHOT=1 routes snapshot + batch through
the bf16 score-column round-trip (snapshot/packing.py) and stamps
`pack=bf16` + `pack_saved_bytes` — placements stay bit-identical (the
packing tests pin it), so A/B lines differ only in bandwidth.
"""

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# overridable for mesh smoke tests on small/virtual device counts; the
# driver-run configuration is the defaults
NUM_NODES = int(os.environ.get("BENCH_NODES", 10_000))
NUM_PODS = int(os.environ.get("BENCH_PODS", 100_000))
CHUNK = int(os.environ.get("BENCH_CHUNK", 2_000))
FULL_CHUNK = int(os.environ.get("BENCH_FULL_CHUNK", CHUNK))
MIN_TAIL_PASSES = 2   # always run (keeps the tail program warm)
DEFAULT_MAX_TAIL_PASSES = 6
# the narrower full-gate tail needs more adaptive passes to cover the
# same straggler pool (3160 at the 100k capture > 6 x 512)
FULL_GATE_MAX_TAIL_PASSES = 10


def max_tail_passes(full_gate: bool) -> int:
    """THE single parse of BENCH_MAX_TAIL_PASSES. It used to be read
    TWICE with different semantics — once at import into a module
    constant (so a value set after import was ignored by one reader)
    and once as a raw truthiness check at run_northstar (so an empty
    string crashed the import-time int() but flipped the run-time
    branch). One call-time parse: an explicit value wins verbatim on
    BOTH the slim and full-gate paths; unset or empty falls to the
    per-path default. Pinned by tests/test_bench_tail.py."""
    raw = (os.environ.get("BENCH_MAX_TAIL_PASSES") or "").strip()
    if raw:
        return max(int(raw), 0)
    return FULL_GATE_MAX_TAIL_PASSES if full_gate else DEFAULT_MAX_TAIL_PASSES


# Protocol note (round 4 -> 5): since round 4 the timed region includes the
# ADAPTIVE tail's host readbacks (round 3 ran a fixed TAIL_PASSES count with
# no mid-region sync), so cross-round comparisons against BENCH_r03 and
# earlier are not strictly apples-to-apples; `tail_passes` is recorded in
# every line so a reader can normalize.  Round 5 kept the adaptive
# semantics but batched the sweep + MIN-pass counts into ONE device->host
# transfer (each blocking scalar readback costs a full tunnel round-trip,
# ~100 ms; round 4 paid five of them).  Round 6 moves the whole adaptive
# loop on device (core.tail_compaction_loop): the timed region now holds
# exactly ONE straggler-stats readback however many passes run, and
# `tail_mode` in every line says which protocol produced it.  The 2 s
# target itself is unchanged (BASELINE.json).
BASELINE_SECONDS = 2.0

# mid-round TPU capture stamped by tools/tpu_capture.py; surfaced on the
# degraded CPU fallback so a round-end tunnel outage no longer erases
# evidence captured while the tunnel was healthy (rounds 3+4 lesson)
CAPTURE_ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "bench_tpu_capture.json")


def host_fields() -> dict:
    """Host fingerprint recorded in every bench line: these CI hosts
    live-migrate and resize mid-session (observed nproc 8 -> 1), and
    without cores/host in the artifact a degraded-host number is
    indistinguishable from a kernel regression (VERDICT r4 weak #3)."""
    from koordinator_tpu.utils.hostinfo import host_fields as hf
    return hf()


_COMPILE_CACHE = None


def compile_cache():
    """The bench's opt-in AOT compile cache (BENCH_COMPILE_CACHE=dir):
    activated once per process and shared by every emitted line, so a
    second run against the same dir retrieves every program instead of
    compiling it (the warm-start stamps below record which happened).
    SAME-HOST ONLY — XLA:CPU artifacts don't survive the live-migrating
    CI hosts (see koordinator_tpu/compilecache)."""
    global _COMPILE_CACHE
    cdir = (os.environ.get("BENCH_COMPILE_CACHE") or "").strip()
    if not cdir:
        return None
    if _COMPILE_CACHE is None:
        from koordinator_tpu.compilecache import CompileCache
        _COMPILE_CACHE = CompileCache(cdir).activate()
    return _COMPILE_CACHE



def _probe_once(timeout: float) -> bool:
    """One subprocess probe (hard timeout): a wedged TPU tunnel hangs
    even trivial compiles at 0% CPU, and a bench that hangs forever
    records nothing. DEVNULL, not pipes: the platform plugin can spawn
    a tunnel grandchild that would keep captured pipes open after the
    timeout kill, wedging run() in communicate() forever."""
    import subprocess
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             # the child must pin the SAME platform the parent will run
             # on (site config silently overrides the env var otherwise)
             "import os, jax;"
             "p = os.environ.get('JAX_PLATFORMS');"
             "p and jax.config.update('jax_platforms', p);"
             "import jax.numpy as jnp;"
             "jax.jit(lambda a: (a @ a.T).sum())(jnp.ones((64, 8)))"
             ".block_until_ready()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=timeout)
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def ensure_platform(probe_timeout: float = None) -> bool:
    """Honor JAX_PLATFORMS and guard non-cpu targets with RETRIED
    subprocess probes before any CPU fallback: tunnel outages are often
    transient, and a single-shot probe converts any blip into a lost
    round (round-3 lesson). BENCH_PROBE_ATTEMPTS probes run
    BENCH_PROBE_RETRY_DELAY seconds apart; only when ALL fail does the
    bench fall back to CPU — loudly, and the recorded `platform` field
    stays honest either way. Returns True when the requested platform
    is healthy (or explicitly cpu), False on the degraded fallback. An
    explicit helper, not an import side effect: callers pay the probes
    only when they run a bench."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    if plat == "cpu":
        return True
    if probe_timeout is None:
        probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
    attempts = max(int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3")), 1)
    delay = float(os.environ.get("BENCH_PROBE_RETRY_DELAY", "90"))
    for i in range(attempts):
        if _probe_once(probe_timeout):
            return True
        if i + 1 < attempts:
            print(f"bench: platform probe {i + 1}/{attempts} failed; "
                  f"retrying in {delay:.0f}s", file=sys.stderr)
            time.sleep(delay)
    print(f"bench: WARNING: all {attempts} platform probes failed; "
          "falling back to CPU — the recorded number is NOT a TPU result",
          file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return False


def run_northstar(full_gate: bool = False, num_pods: int = None,
                  num_nodes: int = None, chunk: int = None,
                  metric: str = None, degraded: str = None,
                  num_devices: int = None, recovered: str = None) -> dict:
    from koordinator_tpu.parallel import mesh as meshlib
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    num_pods = NUM_PODS if num_pods is None else num_pods
    num_nodes = NUM_NODES if num_nodes is None else num_nodes
    if chunk is None:
        chunk = FULL_CHUNK if full_gate else CHUNK
    if num_pods % chunk:
        raise SystemExit(f"BENCH_PODS={num_pods} must be a multiple of "
                         f"the chunk size {chunk}")
    if full_gate:
        pods = synthetic.full_gate_pods(num_pods, num_nodes, seed=1,
                                        num_quotas=32)
        # gate-class prefix packing: ~17% of the workload carries a
        # spread/anti/aff term, ~11% is CPU-bind, ~10% requests
        # devices; packing each class into a (nested) static chunk
        # prefix shrinks the per-inner-step [P, P] machinery of the
        # topology, topology-manager and GPU gates quadratically
        # (core.schedule_batch topo/numa/gpu prefix contracts)
        pods, prefixes, masks = synthetic.pack_gate_prefixes(pods, chunk)
        topo_prefix, topo_mask = prefixes["topo"], masks["topo"]
        make_snap = functools.partial(synthetic.full_gate_cluster,
                                      num_nodes, num_quotas=32)
        metric = metric or "score_bind_100k_pods_10k_nodes_full_gate"
        step_kw = dict(enable_numa=True, enable_devices=True,
                       topo_prefix=topo_prefix,
                       dom_classes=synthetic.dom_classes(pods),
                       numa_prefix=prefixes["numa"],
                       gpu_prefix=prefixes["gpu"])
        # the numa_prefix contract needs a policy-free snapshot; checked
        # against the real cluster below (see after make_snap)
        tail_kw_override = dict(numa_prefix=None, gpu_prefix=None)
    else:
        topo_prefix, topo_mask = None, None
        tail_kw_override = {}
        pods = synthetic.synthetic_pods(num_pods, seed=1, num_quotas=32)
        make_snap = functools.partial(synthetic.synthetic_cluster,
                                      num_nodes, num_quotas=32)
        metric = metric or "score_bind_100k_pods_10k_nodes"
        # no pod in the slim workload requests CPU binding or devices —
        # the batched analogue of the reference's state.skip fast paths
        step_kw = dict(enable_numa=False)
    cfg = LoadAwareConfig.make()

    # --- device / mesh selection (the multichip flagship path) -----------
    # BENCH_DEVICES=n runs on the first n visible devices (the virtual
    # CPU mesh in CI, a real slice on hardware); unset = all visible.
    # BENCH_MESH_PODS=m folds the devices into a 2D (pods x nodes) mesh.
    devices = jax.devices()
    ndev_env = (os.environ.get("BENCH_DEVICES") or "").strip()
    if num_devices is not None:
        # an explicit count wins over the env: run_with_ladder's
        # device-lost rung retries on a SHRUNK device set
        ndev = int(num_devices)
        if not 1 <= ndev <= len(devices):
            raise SystemExit(f"num_devices={ndev} but "
                             f"{len(devices)} devices are visible")
        devices = devices[:ndev]
    elif ndev_env:
        ndev = int(ndev_env)
        if not 1 <= ndev <= len(devices):
            raise SystemExit(f"BENCH_DEVICES={ndev} but "
                             f"{len(devices)} devices are visible")
        devices = devices[:ndev]
    mesh_pods = int((os.environ.get("BENCH_MESH_PODS") or "1").strip())
    mesh = None
    if len(devices) > 1:
        # multi-chip: node columns sharded over the mesh (ICI); the pod
        # queue and quota/gang state replicate on the 1D node mesh and
        # shard over the pods axis on the 2D one. GSPMD turns the top-k
        # select into a shard-local reduce + cross-chip merge, and the
        # cascade's stage-1 mask stays shard-local (zero collectives —
        # tools/mesh_flagship_smoke.py pins that on the compiled HLO).
        mesh = meshlib.make_mesh(devices, pods_axis=mesh_pods)
        if mesh_pods > 1 and (num_pods % mesh_pods or chunk % mesh_pods):
            raise SystemExit(f"BENCH_MESH_PODS={mesh_pods} must divide "
                             f"both BENCH_PODS={num_pods} and the chunk "
                             f"{chunk}")
        # node counts indivisible by the mesh get zero-capacity pad rows
        # (provably unschedulable; excluded from the overcommit checks)
        n_pad = meshlib.padded_node_count(num_nodes, mesh)
        repl = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec())
        snap_shardings = meshlib.snapshot_sharding(mesh)

        def put_snap(s):
            return meshlib.shard_snapshot(
                meshlib.pad_nodes_to_mesh(s, mesh), mesh)

        put_repl = functools.partial(jax.device_put, device=repl)
        if mesh_pods > 1:
            put_batch = functools.partial(meshlib.shard_batch, mesh=mesh)
            put_stacked = functools.partial(
                jax.device_put,
                device=jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(
                        None, meshlib.POD_AXIS)))
        else:
            put_batch = put_repl
            put_stacked = put_repl
        # the batch's node-indexed domain matrices follow the padded
        # snapshot (pad columns are -1 = "node lacks the key")
        pods = meshlib.pad_batch_nodes(pods, n_pad)
    else:
        put_snap = jax.device_put
        put_repl = jax.device_put
        put_batch = jax.device_put
        put_stacked = jax.device_put

    # bf16 columnar packing (snapshot/packing.py): quantize the
    # score/metric columns through the packed representation, so the
    # run measures exactly the values a packed snapshot feeds the
    # kernels; placements stay bit-identical to the f32 oracle
    # (tests/test_packing.py) and the line stamps `pack` + the bytes
    # the packed layout saves
    pack_on = os.environ.get("BENCH_PACK_SNAPSHOT", "0") \
        not in ("0", "false", "")
    if pack_on:
        from koordinator_tpu.snapshot import packing
        pods = packing.roundtrip_pods(pods)

    # the queue as [C, CHUNK, ...] per-pod columns (scan operand)
    stacked = synthetic.stack_pod_chunks(pods, chunk)

    def checked_snap(seed):
        """Build a snapshot and enforce the numa_prefix contract on THE
        snapshot being scheduled (every seed, not just warmup): a
        policy node would engage pods beyond the prefix whose gates
        were sliced away."""
        snap_host = make_snap(seed=seed)
        if full_gate and step_kw.get("numa_prefix") is not None \
                and np.asarray(snap_host.nodes.numa_policy).any():
            raise ValueError("numa_prefix needs a policy-free snapshot "
                             "(core.schedule_batch contract)")
        if pack_on:
            from koordinator_tpu.snapshot import packing
            snap_host = packing.roundtrip_snapshot(snap_host)
        return snap_host

    snap0 = put_snap(checked_snap(0))
    stacked = put_stacked(stacked)
    pods_dev = put_batch(pods)
    cfg = put_repl(cfg)
    counts0 = put_repl(tuple(getattr(pods, f) for f in core.COUNT_FIELDS))

    # Candidate selection defaults to EXACT lax.top_k since round 5:
    # the hardware capture measured exact FASTER than approx_max_k at
    # the canonical shape (0.980 s vs 1.082 s, same session, fuller
    # placements at no recall loss), so the partial reduction buys
    # nothing here — k=8..32 over 10k columns is far below the regime
    # approx_max_k targets. BENCH_APPROX=1 re-enables it for
    # comparison runs (tests/test_approx_topk.py pins the quality
    # bound either way; on CPU both lower to the exact reduction), and
    # every emitted line records which mode ran.
    approx = os.environ.get("BENCH_APPROX", "0") not in ("0", "false", "")
    # sweep/tail shape knobs, hardware-sweepable without code edits
    # (defaults = the recorded protocol): rounds scale the per-chunk
    # [P, N] matrix cost, k the inner fall-through steps, and CHUNK the
    # quadratic [P, P] prefix machinery
    rounds = int(os.environ.get("BENCH_ROUNDS", "2"))
    kch = int(os.environ.get("BENCH_K", "8"))
    tail_rounds = int(os.environ.get("BENCH_TAIL_ROUNDS", "4"))
    tail_k = int(os.environ.get("BENCH_TAIL_K", "32"))
    # the Filter->Score gate cascade: ON by default for the full-gate
    # paths (where the heavy per-pair gates it narrows exist), off on
    # the slim path so the canonical protocol stays byte-stable;
    # BENCH_CASCADE overrides either way. cascade=False is the
    # conformance oracle — placements are bit-identical (test_cascade).
    cascade_env = os.environ.get("BENCH_CASCADE")
    cascade_on = (full_gate if cascade_env is None
                  else cascade_env not in ("0", "false", ""))
    # tail orchestration: "device" = the lax.while_loop compaction loop
    # (one straggler-stats readback total); "host" = the previous
    # per-pass host-driven loop, kept as the conformance oracle
    tail_mode = (os.environ.get("BENCH_TAIL_MODE") or "device").strip()
    if tail_mode not in ("device", "host"):
        raise SystemExit(f"BENCH_TAIL_MODE={tail_mode!r}: "
                         "must be 'device' or 'host'")
    step = functools.partial(core.schedule_batch, num_rounds=rounds,
                             k_choices=kch,
                             score_dims=(0, 1), approx_topk=approx,
                             tie_break=True, quota_depth=2,
                             fit_dims=(0, 1, 2, 3), cascade=cascade_on,
                             **step_kw)
    # the tail's retry batches are gathered device-side, so only the
    # topo contract (budgeted selection below) can be re-established
    # there — the numa/gpu prefixes apply to the host-packed sweep only
    tail_step = functools.partial(core.schedule_batch,
                                  num_rounds=tail_rounds,
                                  k_choices=tail_k, score_dims=(0, 1),
                                  approx_topk=approx, tie_break=True,
                                  quota_depth=2, fit_dims=(0, 1, 2, 3),
                                  cascade=cascade_on,
                                  **dict(step_kw, **tail_kw_override))
    # tail retry width, decoupled from the sweep chunk: stragglers
    # don't need a sweep-wide retry program (the [P, P] prefix
    # machinery scales quadratically with this width); smaller widths
    # trade more adaptive passes (one readback each) for much cheaper
    # passes. Both paths default to 512: the full-gate's heavy gate
    # set makes a 2000-wide pass ~16x a 512-wide one (20k x 2k CPU:
    # 9.1 s -> 5.8 s), and the canonical's ~510 stragglers fit inside
    # the two MANDATORY passes either way (captured 501-516 at 100k),
    # so the slim path pays no extra readbacks for a ~15% CPU-measured
    # saving (3.5 s -> 2.2 s at 20k x 2k). A non-default width is
    # stamped into the emitted line as a knob.
    default_tail = min(chunk, 512)
    tail_chunk = max(min(int(os.environ.get("BENCH_TAIL_CHUNK",
                                            default_tail)),
                         num_pods), 1)
    max_tail = max_tail_passes(full_gate)
    if topo_mask is not None:
        topo_mask = put_repl(jnp.asarray(topo_mask))

    def charge_all(counts, batch, assignment):
        """Thread placed topology charges into the carried counts (the
        cross-batch count rule, core.charge_all_counts; no-op
        compile-out on the slim path)."""
        if not full_gate:
            return counts
        return core.charge_all_counts(counts, batch, assignment)

    def with_counts(batch, counts):
        return batch.replace(**dict(zip(core.COUNT_FIELDS, counts)))

    def run_sweep(snap, counts, stacked, pods_dev, cfg):
        def body(carry, cols):
            snap, counts = carry
            # selector_match and the (group x domain) matrices are
            # batch-global; every per-pod column comes from the chunk
            batch = with_counts(pods_dev.replace(**cols), counts)
            res = step(snap, batch, cfg)
            counts = charge_all(counts, batch, res.assignment)
            return (res.snapshot, counts), res.assignment
        (snap, counts), assign = jax.lax.scan(body, (snap, counts),
                                              stacked)
        return snap, counts, assign.reshape(-1)

    # on a mesh the jitted programs pin their output placements (the
    # carried snapshot stays node-sharded across chunks/passes instead
    # of wherever GSPMD's cost model lands it; donation then aliases
    # shard-for-shard): (snap, counts, assign[, stats/tried]) outputs
    if mesh is not None:
        counts_sh = tuple(repl for _ in core.COUNT_FIELDS)
        sweep_jit = functools.partial(
            jax.jit, donate_argnums=(0, 1),
            out_shardings=(snap_shardings, counts_sh, repl))
        tail4_out = (snap_shardings, counts_sh, repl, repl)
        sweep_tail_jit = functools.partial(
            jax.jit, donate_argnums=(0, 1), out_shardings=tail4_out)
        tail_pass_jit = functools.partial(
            jax.jit, donate_argnums=(0, 1, 2, 3), out_shardings=tail4_out)
    else:
        sweep_jit = functools.partial(jax.jit, donate_argnums=(0, 1))
        sweep_tail_jit = sweep_jit
        tail_pass_jit = functools.partial(jax.jit,
                                          donate_argnums=(0, 1, 2, 3))

    @sweep_jit
    def sweep(snap, counts, stacked, pods_dev, cfg):
        return run_sweep(snap, counts, stacked, pods_dev, cfg)

    @sweep_tail_jit
    def sweep_and_tail(snap, counts, stacked, pods_dev, cfg):
        """tail_mode=device: sweep + the adaptive tail compaction loop
        (core.tail_compaction_loop, a lax.while_loop over compacted
        retry batches) are ONE program — stragglers are gathered,
        retried, and scattered back entirely on device, and the host
        reads back a single packed stats vector after the loop."""
        snap, counts, assign = run_sweep(snap, counts, stacked,
                                         pods_dev, cfg)
        return core.tail_compaction_loop(
            tail_step, snap, counts, assign, pods_dev, cfg,
            tail_chunk=tail_chunk, min_passes=MIN_TAIL_PASSES,
            max_passes=max_tail, charge_counts=full_gate,
            topo_prefix=topo_prefix, topo_mask=topo_mask)

    @tail_pass_jit
    def tail_pass(snap, counts, assign, tried, pods_dev, cfg):
        """tail_mode=host: one retry pass (core.tail_pass — the same
        gather/compact/retry/scatter program the device loop runs, so
        host mode is the conformance oracle for it). Selection and
        budgeted-constrained semantics live in core.tail_select."""
        return core.tail_pass(
            tail_step, snap, counts, assign, tried, pods_dev, cfg,
            tail_chunk=tail_chunk, charge_counts=full_gate,
            topo_prefix=topo_prefix, topo_mask=topo_mask)

    @jax.jit
    def pass_stats(assign, tried, pods_dev):
        """[left, never_retried] as ONE device array: one transfer per
        adaptive decision instead of two tunnel round-trips. The
        post-sweep count reuses it with an all-false `tried` so a
        single program serves every readback site."""
        bad = pods_dev.valid & (assign < 0)
        return jnp.stack([bad.sum(), (bad & ~tried).sum()])

    def full_pass(snap, counts):
        if tail_mode == "device":
            snap, counts, assign, stats = sweep_and_tail(
                snap, counts, stacked, pods_dev, cfg)
            # the run's ONE straggler-count readback, after the whole
            # adaptive loop ([after_sweep, final, never_retried,
            # passes] packed); the assignment transfer is the bind log
            stats = np.asarray(stats)
            return (snap, counts, np.asarray(assign), int(stats[0]),
                    int(stats[1]), int(stats[2]), int(stats[3]))
        # tail_mode=host — the previous protocol, kept as the
        # conformance oracle. The sweep and the MIN mandatory tail
        # passes are issued back-to-back with NO host readback between
        # them: each blocking scalar transfer pays a full tunnel
        # round-trip (~100 ms on the axon setup), and five of them
        # inside the timed region more than doubled the round-4
        # canonical time. All the counts the adaptive decision needs
        # are stacked device-side and read in ONE transfer after the
        # mandatory passes.
        snap, counts, assign = sweep(snap, counts, stacked, pods_dev, cfg)
        tried = jnp.zeros((num_pods,), bool)
        pair_hist = [pass_stats(assign, tried, pods_dev)]
        passes = 0
        # the mandatory passes honor the MAX cap too (BENCH_MAX_TAIL_PASSES
        # below MIN is a legitimate quick-run knob)
        for _ in range(min(MIN_TAIL_PASSES, max_tail)):
            snap, counts, assign, tried = tail_pass(
                snap, counts, assign, tried, pods_dev, cfg)
            passes += 1
            # pass_stats is the SAME program the adaptive loop reads, so
            # the mandatory passes keep it warm — no cold compile can
            # land inside the adaptive region
            pair_hist.append(pass_stats(assign, tried, pods_dev))
        stats = np.asarray(jnp.concatenate(pair_hist))
        left_after_sweep = int(stats[0])
        hist = [int(x) for x in stats[2::2]]
        left = hist[-1] if hist else left_after_sweep
        prev = hist[-2] if passes >= 2 else left_after_sweep
        improved = left < prev
        never_retried = int(stats[2 * passes + 1])
        # passes continue while the straggler count improves OR fresh
        # (never-retried) windows remain — a pass that placed nothing
        # must not strand disjoint windows that were never tried. Only
        # the MAX cap can leave never_retried > 0.
        while (passes < max_tail and left > 0
               and (improved or never_retried > 0)):
            snap, counts, assign, tried = tail_pass(
                snap, counts, assign, tried, pods_dev, cfg)
            passes += 1
            # the oracle's per-pass blocking readback IS the cost the
            # device loop deletes (koordlint HS006 guards the bug
            # class; this one marked instance is the measured baseline)
            pair = np.asarray(  # koordlint: disable=HS006
                pass_stats(assign, tried, pods_dev))
            new_left, never_retried = int(pair[0]), int(pair[1])
            improved = new_left < left
            left = new_left
        # final device->host transfer: the bind log
        return (snap, counts, np.asarray(assign), left_after_sweep,
                left, never_retried, passes)

    # BENCH_COST=1: static cost stamps for the flagship program this
    # line actually runs (obs/costmodel.py over the SAME jitted
    # callable) — flops, bytes accessed, static HBM peak, flops/pod.
    # Opt-in because it pays one extra AOT lower+compile of the
    # flagship (the persistent cache absorbs it when configured);
    # lowering happens BEFORE the warmup so the donated buffers are
    # still live to trace against.
    cost_stamp = {}
    if os.environ.get("BENCH_COST", "0") not in ("0", "false", ""):
        from koordinator_tpu.obs import costmodel
        cost_target = sweep_and_tail if tail_mode == "device" else sweep
        cost_compiled = cost_target.lower(snap0, counts0, stacked,
                                          pods_dev, cfg).compile()
        stamp = costmodel.flagship_stamp(cost_compiled, num_pods)
        cost_stamp = {
            "flops": stamp["flops"],
            "bytes_accessed": stamp["bytes_accessed"],
            "hbm_peak_bytes": stamp["hbm_peak_bytes"],
            "flops_per_pod": round(stamp["flops_per_pod"], 1),
        }
        del cost_compiled

    # warmup/compile (sweep + tail always run at least MIN passes — no
    # cold path in the timed region regardless of the warm data). The
    # compile watcher around it feeds the warm-start stamps: what
    # compilation (or persistent-cache retrieval) cost this line, and
    # whether the opt-in compile cache served it
    cache = compile_cache()
    pack_stats = None
    if pack_on:
        from koordinator_tpu.snapshot import packing
        pack_stats = packing.packed_savings(snap0, pods)
    # BENCH_TRACE=<dir>: koordtrace capture of this line. The warmup and
    # every timed pass become spans in one ring (obs.trace.Tracer), the
    # Chrome/JSONL dump lands in <dir>, and the line stamps the trace
    # path + cycle p50/p99 computed from the SAME span records the dump
    # contains — the stamped latency and the Perfetto view can't drift.
    trace_dir = (os.environ.get("BENCH_TRACE") or "").strip()
    tracer = None
    if trace_dir:
        from koordinator_tpu.obs.trace import Tracer
        tracer = Tracer()

    def bench_span(name):
        if tracer is None:
            from koordinator_tpu.obs.trace import NOOP_SPAN
            return NOOP_SPAN
        return tracer.span(name)

    from koordinator_tpu.obs import phases as obs_phases
    from koordinator_tpu.compilecache import counters as compile_counters
    warm_t0 = time.perf_counter()
    with compile_counters.watch() as warm_watch:
        with bench_span(obs_phases.SPAN_BENCH_WARMUP):
            out = full_pass(snap0, counts0)
    warm_start_s = time.perf_counter() - warm_t0
    del out
    if cache is None:
        cache_status = "cold"     # no cache dir configured
    elif warm_watch.cache_misses == 0:
        cache_status = "hit"      # every program retrieved, zero compiles
    else:
        cache_status = "miss"     # at least one real XLA compile

    # timed steady-state pass on a fresh snapshot
    snap1 = put_snap(checked_snap(7))
    counts1 = put_repl(tuple(getattr(pods, f) for f in core.COUNT_FIELDS))
    t0 = time.perf_counter()
    with bench_span(obs_phases.SPAN_BENCH_CYCLE):
        (snap, counts, assign, left_after_sweep, left_final, never_retried,
         passes) = full_pass(snap1, counts1)
    elapsed = time.perf_counter() - t0

    # traced runs may ask for extra steady-state reps (fresh snapshot
    # each; the donated buffers are consumed per pass) so the stamped
    # p50/p99 rest on more than one sample. `elapsed` stays the FIRST
    # pass — the protocol metric is untouched by the rep knob.
    trace_stamp = {}
    if tracer is not None:
        for rep in range(max(int(os.environ.get("BENCH_TRACE_REPS",
                                                "1")), 1) - 1):
            snap_r = put_snap(checked_snap(11 + rep))
            counts_r = put_repl(tuple(getattr(pods, f)
                                      for f in core.COUNT_FIELDS))
            with bench_span(obs_phases.SPAN_BENCH_CYCLE):
                full_pass(snap_r, counts_r)
        durs = tracer.durations_s(obs_phases.SPAN_BENCH_CYCLE)
        from koordinator_tpu.obs import export as obs_export
        paths = obs_export.dump(tracer, out_dir=trace_dir,
                                prefix=f"bench_{metric}",
                                formats=("chrome", "jsonl"))
        trace_stamp = {
            "trace": paths[0],
            "cycle_p50": round(float(np.quantile(durs, 0.5)), 4),
            "cycle_p99": round(float(np.quantile(durs, 0.99)), 4),
        }

    placed = int((assign >= 0).sum())
    if never_retried > 0:
        # every straggler should get at least one retry before the
        # adaptive loop gives up — surface any that never did
        print(f"bench: WARNING: {never_retried} stragglers were never "
              f"retried after {passes} adaptive tail passes "
              f"(tail_chunk={tail_chunk}); raise BENCH_MAX_TAIL_PASSES",
              file=sys.stderr)
    # non-default shape knobs are stamped into the line: a sweep run
    # must never be mistaken for the canonical protocol (the module
    # protocol note relies on every variable being readable off the
    # line)
    knob_tags = {}
    for name, val, default in (("rounds", rounds, 2), ("k", kch, 8),
                               ("tail_rounds", tail_rounds, 4),
                               ("tail_k", tail_k, 32),
                               ("tail_chunk", tail_chunk, default_tail),
                               # 2000 is the PROTOCOL chunk (BASELINE);
                               # smoke/sweep shapes stamp their width
                               ("chunk", chunk, 2000)):
        if val != default:
            knob_tags[name] = val
    result = {
        "metric": metric,
        "value": round(elapsed, 4),
        "unit": "s",
        **({"knobs": knob_tags} if knob_tags else {}),
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 2),
        "pods_per_sec": round(num_pods / elapsed),
        "placed": placed,
        "stragglers_after_sweep": left_after_sweep,
        "stragglers_final": left_final,
        "never_retried": never_retried,
        "tail_passes": passes,
        "approx_topk": approx,
        # A/B protocol knobs, stamped on EVERY line (not only when
        # non-default): a cascade-off or host-tail run must be
        # self-describing without consulting the code's defaults
        "cascade": cascade_on,
        "tail_mode": tail_mode,
        # warm-start stamps (every line): wall time of the warmup pass
        # (trace + compile-or-retrieve + one untimed execution), the
        # XLA compile-or-retrieve seconds inside it, and whether the
        # opt-in persistent compile cache (BENCH_COMPILE_CACHE) served
        # it — "cold" = no cache dir, "hit" = zero compiles
        "compile_s": round(warm_watch.compile_seconds, 4),
        "warm_start_s": round(warm_start_s, 4),
        "cache": cache_status,
        # present ONLY on a BENCH_COST=1 run: static cost/memory of the
        # flagship program this line ran (obs/costmodel.py) — joins the
        # measured trajectory to the AOT cost model
        **cost_stamp,
        # present ONLY on a bf16-packed run (BENCH_PACK_SNAPSHOT): the
        # kernels consumed packed score/metric columns and the line
        # says what the packed layout saves on the wire
        **({"pack": "bf16",
            "pack_saved_bytes": pack_stats["bytes_saved"]}
           if pack_stats is not None else {}),
        # present ONLY on a run the bench ladder re-ran degraded
        # (run_with_ladder): the classified failure class + the retried
        # chunk, so a degraded number can never pass as the protocol
        **({"degraded": degraded} if degraded else {}),
        # present ONLY after the ladder recovered a DEVICE_LOST run on
        # a shrunk device set (the bench mirror of the service's
        # mesh-shrink rung); `devices`/`mesh` below then carry the
        # SHRUNK size, so the line is self-describing
        **({"recovered": recovered} if recovered else {}),
        # present ONLY on a traced run (BENCH_TRACE=dir): where the
        # Chrome dump landed + cycle p50/p99 from the same span records
        **trace_stamp,
        "devices": len(devices),
        # the mesh stamp makes a 4-device line self-describing (1x4 vs
        # 2x2); absent on single-device lines so trajectories stay
        # byte-comparable with earlier rounds
        **({"mesh": meshlib.mesh_axis_sizes(mesh)}
           if mesh is not None else {}),
        "platform": devices[0].platform,
        **host_fields(),
    }
    print(json.dumps(result))
    # non-serialized conformance surfaces (tests + the CI mesh smoke
    # compare sharded placements against the single-device oracle and
    # check the overcommit invariant on the real rows): attached AFTER
    # the line is emitted so the artifact stays line-parseable
    result["arrays"] = {
        "assignment": assign,
        "requested": np.asarray(snap.nodes.requested),
        "allocatable": np.asarray(snap.nodes.allocatable),
        "num_nodes": num_nodes,
    }
    return result


def run_with_ladder(max_halvings: int = 2, _run=None, **kw) -> dict:
    """The bench's rung of the degradation ladder: a run whose failure
    classifies as RESOURCE_EXHAUSTED retries with the chunk halved (up
    to `max_halvings` times) and the retried line carries a `degraded`
    stamp (failure class + the chunk that survived); one that
    classifies as DEVICE_LOST retries on a device set shrunk by one —
    the bench mirror of the service's mesh-shrink rung — and the
    retried line carries a `recovered` stamp plus the shrunk
    `devices`/`mesh` size. Either way a non-protocol number is
    self-describing and can never pass as the canonical protocol. Any
    other failure class propagates — the caller's evidence guards own
    those. `_run` is the injectable run function (tests)."""
    from koordinator_tpu.scheduler.errorhandler import (
        FailureClass,
        classify_failure,
    )

    run = _run if _run is not None else run_northstar
    chunk = kw.pop("chunk", None)
    num_devices = kw.pop("num_devices", None)
    degraded = None
    recovered = None
    for retries in range(max_halvings + 1):
        try:
            return run(chunk=chunk, degraded=degraded,
                       num_devices=num_devices, recovered=recovered,
                       **kw)
        except Exception as exc:
            fc = classify_failure(exc)
            cur = chunk if chunk is not None \
                else (FULL_CHUNK if kw.get("full_gate", False) else CHUNK)
            cur_dev = num_devices if num_devices is not None \
                else int((os.environ.get("BENCH_DEVICES") or "").strip()
                         or len(jax.devices()))
            if retries == max_halvings:
                raise
            if fc is FailureClass.RESOURCE_EXHAUSTED and cur >= 2:
                chunk = cur // 2
                degraded = f"{fc.value}:chunk={chunk}"
                print(f"bench: {fc.value}; retrying with chunk {cur} "
                      f"-> {chunk}", file=sys.stderr)
            elif fc is FailureClass.DEVICE_LOST and cur_dev >= 2:
                num_devices = cur_dev - 1
                recovered = f"{fc.value}:devices={num_devices}"
                print(f"bench: {fc.value}; retrying on {cur_dev} -> "
                      f"{num_devices} device(s)", file=sys.stderr)
            else:
                # out of rungs (or an unabsorbable class): the REAL
                # exception propagates, never a synthetic stand-in
                raise


def _stamped_line(line: dict, captured_at: str, age: float,
                  stale_after: float) -> dict:
    """The ONE constructor for surfaced stamped lines: every line gets
    the full provenance set — stamped_capture, captured_at,
    stamped_age_seconds AND stale_capture — unconditionally. BENCH_r05's
    tail surfaced 10 h-old stamped captures (stamped_age_seconds 36196)
    with no stale marker on the metric lines; routing every emission
    through this helper makes the invariant structural instead of a
    per-call-site convention (tests/test_lint.py pins that every line
    of a multi-line artifact carries it)."""
    out = dict(line)
    out["stamped_capture"] = True
    out["captured_at"] = captured_at
    out["stamped_age_seconds"] = round(age)
    out["stale_capture"] = age > stale_after
    return out


def surface_stamped_capture() -> bool:
    """Re-emit the mid-round TPU capture (tools/tpu_capture.py) on the
    degraded fallback, each line labeled stamped_capture + captured_at so
    the driver tail records TPU evidence even when the round-end tunnel is
    wedged.  The LIVE canonical line still prints last (and is the one the
    driver parses); these stamped lines are the documented evidence trail,
    never presented as the live run.

    Best-effort by construction: NOTHING here may crash the degraded
    bench run (that would destroy the round's only remaining evidence),
    and an artifact older than BENCH_STAMP_MAX_AGE (default 16 h) is
    rejected — a leftover from a previous round must not be presented
    as this round's capture.  16 h, not 12: a capture frozen minutes
    into a 12 h round is ~12 h old when the driver runs the round-end
    bench, and a bound at exactly one round length would reject the
    round's OWN evidence; inter-round judge/advisor time keeps a
    previous round's artifact well past 16 h.  The artifact is also
    gitignored for the same reason.

    Surfaced lines additionally carry `stale_capture`: true once the
    stamp is older than BENCH_STAMP_STALE_AFTER (default 1 h), so a
    reader of the evidence trail can tell a fresh mid-round capture
    from one that predates most of the round (BENCH_r05 surfaced a
    stamped_age_seconds of 36196 with nothing marking it stale)."""
    max_age = float(os.environ.get("BENCH_STAMP_MAX_AGE", "57600"))
    stale_after = float(os.environ.get("BENCH_STAMP_STALE_AFTER", "3600"))
    try:
        with open(CAPTURE_ARTIFACT) as f:
            art = json.load(f)
        lines = [l for l in art["lines"] if isinstance(l, dict)]
        captured_at = str(art["captured_at"])
        import datetime
        age = (datetime.datetime.now(datetime.timezone.utc)
               - datetime.datetime.fromisoformat(captured_at)
               ).total_seconds()
        if not lines:
            return False
        if not (0 <= age < max_age):
            print(f"bench: ignoring stamped capture from {captured_at} "
                  f"(age {age:.0f}s exceeds BENCH_STAMP_MAX_AGE "
                  f"{max_age:.0f}s)", file=sys.stderr)
            return False
        print(f"bench: surfacing {len(lines)} stamped TPU line(s) "
              f"captured mid-round at {captured_at} (age {age:.0f}s, "
              "tools/tpu_capture.py)", file=sys.stderr)
        for line in lines:
            print(json.dumps(_stamped_line(line, captured_at, age,
                                           stale_after)))
        return True
    except FileNotFoundError:
        return False  # no mid-round capture happened — the normal case
    except Exception as exc:  # noqa: BLE001 — see docstring
        print(f"bench: stamped capture unreadable ({exc!r}); continuing",
              file=sys.stderr)
        return False


def main(platform_healthy: bool = True):
    if os.environ.get("BENCH_PRECOMPILE", "0") not in ("0", "false", ""):
        # BENCH_PRECOMPILE=1: run the AOT warmer against the configured
        # cache dir BEFORE any measured line, so the registry-enumerated
        # flagship programs (service cycle + tail forms) are persisted
        # and a service starting against the same dir warm-starts
        cache = compile_cache()
        if cache is None:
            print("bench: BENCH_PRECOMPILE=1 needs BENCH_COMPILE_CACHE "
                  "(a same-host cache dir); skipping the warmer",
                  file=sys.stderr)
        else:
            from koordinator_tpu.compilecache import precompile
            report = precompile.warm(
                cache, precompile.WorkSet(devices=len(jax.devices())))
            print(f"bench: precompile warmed {report['programs']} "
                  f"program(s) in {report['seconds']:.1f}s "
                  f"(hit={report['hit']} warm={report['warm']} "
                  f"miss={report['miss']})", file=sys.stderr)
    extras = os.environ.get("BENCH_EXTRAS", "1") not in ("0", "false", "")
    if extras and not platform_healthy \
            and os.environ.get("BENCH_EXTRAS") != "force":
        # degraded CPU fallback: the extra configs would take many
        # minutes on host and record nothing a TPU round can use —
        # keep the fallback bounded to the canonical line (the r3
        # wedged-tunnel lesson). BENCH_EXTRAS=force overrides.
        print("bench: skipping extra configs on the degraded CPU "
              "fallback (BENCH_EXTRAS=force to override)",
              file=sys.stderr)
        extras = False
    if not platform_healthy:
        # any mid-round TPU capture is the round's real evidence
        surface_stamped_capture()
        if os.environ.get("BENCH_FULL_DEGRADED", "1") not in ("0", "false"):
            # scaled-down full-gate regression line: without it a wedged
            # tunnel means the full plugin chain records NOTHING at scale
            # for the whole round (VERDICT r4 weak #1); 20k x 2k is cheap
            # enough for the 1-core fallback hosts. Best-effort like the
            # stamped surfacing: a failure here must not abort the run
            # before the canonical fallback line prints.
            try:
                run_with_ladder(
                    full_gate=True, num_pods=20_000, num_nodes=2_000,
                    chunk=2_000,
                    metric="score_bind_20k_pods_2k_nodes_full_gate_degraded")
            except Exception as exc:  # noqa: BLE001 — evidence guard
                from koordinator_tpu.scheduler.errorhandler import (
                    classify_failure,
                )
                print(f"bench: degraded full-gate line failed "
                      f"(class={classify_failure(exc).value}: {exc!r}); "
                      "continuing to the canonical line", file=sys.stderr)
    if extras:
        # BASELINE configs 1-5 + the full-gate flagship, driver-captured
        # per round (VERDICT r3: self-reported tables don't count)
        import bench_configs
        bench_configs.config_1_spark()
        bench_configs.config_2_numa()
        bench_configs.config_3_gangs()
        bench_configs.config_4_quota()
        bench_configs.config_5_descheduler()
        run_with_ladder(full_gate=True)
    # the canonical north-star line, LAST (ladder-wrapped: an OOM on a
    # smaller-memory host retries with the chunk halved and the line
    # stamps `degraded` instead of recording nothing for the round)
    run_with_ladder(full_gate=False)


if __name__ == "__main__":
    main(platform_healthy=ensure_platform())
