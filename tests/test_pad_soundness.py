"""koordpad pins (ISSUE 16): the pad-predicate grammar, the two-copy
vocabulary lock between the linter and the runtime schema, the pad-fill
algebra Tier A reasons with, the Tier B differential harness, the
machine-readable lint output formats, and the repo-clean gates.

The slow-marked tests at the bottom are the full Tier B gate and the
dual-tier seeded-mutation smoke — the same ground tools/ci.sh runs as a
dedicated stage.
"""

import json
import math
import os

import numpy as np
import pytest

from koordinator_tpu.snapshot import schema
from tools import padcheck
from tools.lint import runner
from tools.lint.framework import DEFAULT_EXCLUDES, Finding, cached_project
from tools.lint.shapes import pads
from tools.lint.shapes import spec as lspec

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- the two-copy vocabulary lock -------------------------------------------

def test_pad_vocab_pinned_between_linter_and_schema():
    """The linter ships its own copy of the pad vocabulary (it must not
    import the runtime tree it analyzes); dict equality makes drift a
    test failure instead of a silent analysis gap."""
    assert dict(lspec.PAD_VOCAB) == dict(schema.PAD_VOCAB)
    assert set(lspec.PADDED_DIMS) == set(schema.PADDED_DIMS)
    assert set(lspec.PAD_FILLS) == set(lspec.PAD_VOCAB)
    assert set(schema.PAD_FILL_VALUES) == set(schema.PAD_VOCAB)


def test_pad_fills_agree_with_runtime_fill_values():
    """Tier A's canonical fill and Tier B's concrete fill must describe
    the same value for every predicate (or both abstain)."""
    for pred in lspec.PAD_VOCAB:
        canon = lspec.PAD_FILLS[pred]
        concrete = schema.PAD_FILL_VALUES[pred]
        if canon is None:
            assert concrete is None, pred
        else:
            assert concrete is not None, pred
            assert pads.FILL_VALUES[canon] == float(concrete), pred


# --- the spec grammar -------------------------------------------------------

def test_parse_spec_pad_grammar():
    leaf = lspec.parse_spec("f32[P~pad:zero,R]")
    assert leaf.dims == ("P", "R")
    assert leaf.pads == ("zero", None)
    assert leaf.pad_for(0) == "zero" and leaf.pad_for(1) is None
    # pad-free specs keep the pre-koordpad () sentinel (LeafSpec
    # literals in older tests stay equal)
    bare = lspec.parse_spec("f32[P,R]")
    assert bare.pads == ()
    assert bare.pad_for(0) is None
    assert lspec.parse_spec("bool[N~pad:false]").pads == ("false",)
    assert lspec.parse_spec("i32[P~pad:-1]").pads == ("-1",)


@pytest.mark.parametrize("raw", [
    "f32[P~pad:seven]",       # predicate outside the vocabulary
    "f32[P~fill:zero]",       # wrong annotation keyword
    "f32[P~pad:]",            # empty predicate
    "q7[P~pad:zero]",         # unknown dtype
    "f32[WAT~pad:zero]",      # undeclared dim symbol
])
def test_parse_spec_rejects_malformed_pads(raw):
    with pytest.raises(lspec.SpecError):
        lspec.parse_spec(raw)


# --- the pad-fill algebra (Tier A's reasoning core) -------------------------

def test_canonical_and_fill_of_value():
    assert pads.canonical("false") == "zero"
    assert pads.canonical("unschedulable") == "zero"
    assert pads.canonical("invalid") is None
    assert pads.canonical(None) is None
    assert pads.fill_of_value(0.0) == "zero"
    assert pads.fill_of_value(-1) == "-1"
    assert pads.fill_of_value(math.inf) == "inf"
    assert pads.fill_of_value(2.0) is None       # outside the space
    assert pads.fill_of_value(math.nan) is None
    assert pads.fill_of_value("x") is None


def test_combine_annihilators_beat_unknown_operands():
    """x * 0 -> 0 and mask & False -> False even when the other side is
    statically unknown — the rules that let zero-masking prove
    inertness through arbitrary score pipelines."""
    assert pads.combine("mult", None, ("lit", 0.0)) == "zero"
    assert pads.combine("bitand", ("fill", 0.0), None) == "zero"
    assert pads.combine("bitor", None, ("fill", 1.0)) == "one"
    assert pads.combine("maximum", ("lit", math.inf), None) == "inf"
    # no annihilator: unknown stays unknown (never-guess)
    assert pads.combine("add", None, ("lit", 0.0)) is None
    # both known: computed, but only canonical values survive
    assert pads.combine("sub", ("fill", 1.0), ("lit", 1.0)) == "zero"
    assert pads.combine("add", ("fill", 1.0), ("lit", 1.0)) is None
    assert pads.combine("div", ("fill", 1.0), ("lit", 0.0)) is None


def test_where_fill_branch_selection():
    t, f = ("lit", 1.0), ("lit", 0.0)
    assert pads.where_fill(("fill", 1.0), t, f) == "one"   # cond true
    assert pads.where_fill(("fill", 0.0), t, f) == "zero"  # cond false
    assert pads.where_fill(None, t, t) == "one"            # agree
    assert pads.where_fill(None, t, f) is None             # disagree
    assert pads.where_fill(None, t, None) is None


def test_reduction_neutrality_table():
    assert pads.reduction_neutral("sum", "zero") is True
    assert pads.reduction_neutral("sum", "one") is False
    assert pads.reduction_neutral("max", "-1") is True     # scores >= 0
    assert pads.reduction_neutral("min", "inf") is True
    assert pads.reduction_neutral("mean", "zero") is False # shifts mean
    assert pads.reduction_neutral("sum", None) is None     # silent
    assert pads.reduction_neutral("cumsum", "zero") is None


def test_reduce_surviving_and_cast_fill():
    assert pads.reduce_surviving("max", "-1") == "-1"
    assert pads.reduce_surviving("sum", "zero") == "zero"
    assert pads.reduce_surviving("sum", "one") is None     # extent symbolic
    assert pads.reduce_surviving("all", "one") == "one"
    assert pads.reduce_surviving("any", "zero") == "zero"
    assert pads.reduce_surviving("argmax", "inf") == "zero"
    assert pads.cast_fill("bool_", "-1") == "one"          # truthiness
    assert pads.cast_fill("int32", "inf") is None          # UB cast
    assert pads.cast_fill("uint32", "-1") is None          # wraps
    assert pads.cast_fill("int32", "-1") == "-1"


# --- repo-clean gates (doubles as PS004 totality over the registry) ---------

def test_repo_is_pad_sound_with_empty_baseline():
    new, suppressed = runner.run_lint(REPO_ROOT,
                                      analyzers=["pad-soundness"])
    assert new == [], [f.render() for f in new]
    assert suppressed == []


def test_repo_is_determinism_clean():
    new, _ = runner.run_lint(REPO_ROOT, analyzers=["determinism"])
    assert new == [], [f.render() for f in new]


# --- the Tier B harness -----------------------------------------------------

def _pair_for(raw, key="t"):
    real = padcheck._sizes(padded=False)
    padded = padcheck._sizes(padded=True)
    rng = padcheck._rng(key, padcheck.BASE_SEED)
    grng = padcheck._rng(key + "/garbage", padcheck.BASE_SEED)
    leaf = lspec.parse_spec(raw)
    a0, ax = padcheck.build_pair(leaf, real, padded, rng, grng,
                                 index_cap=min(real.values()))
    return leaf, real, a0, ax


def test_build_pair_real_regions_identical_and_bands_filled():
    leaf, real, a0, ax = _pair_for("f32[P~pad:one,R]")
    p = real["P"]
    assert ax.shape[0] > p                     # P actually pads
    np.testing.assert_array_equal(ax[:p], a0)  # draw-for-draw identical
    assert (ax[p:] == 1.0).all()               # declared fill


def test_build_pair_garbage_band_for_any():
    leaf, real, a0, ax = _pair_for("f32[P~pad:any]")
    p = real["P"]
    np.testing.assert_array_equal(ax[:p], a0)
    # `any` bands are seeded garbage from the same draw range — NOT a
    # fixed fill, so a kernel relying on their content fails loudly
    assert (ax[p:] >= 0.5).all() and (ax[p:] <= 2.0).all()


def test_compare_leaf_detects_real_region_leak():
    leaf, real, a0, ax = _pair_for("f32[P~pad:zero,R]")
    bad = ax.copy()
    bad[0, 0] += 1.0                           # pad perturbed a real cell
    errors = []
    padcheck._compare_leaf(leaf, a0, bad, real, "t", errors)
    assert len(errors) == 1 and "pad leak" in errors[0]


def test_compare_leaf_detects_pad_band_drift():
    leaf, real, a0, ax = _pair_for("f32[P~pad:zero,R]")
    bad = ax.copy()
    bad[real["P"]:, :] = 7.0                   # fill no longer held
    errors = []
    padcheck._compare_leaf(leaf, a0, bad, real, "t", errors)
    assert len(errors) == 1 and "pad-band drift" in errors[0]
    # clean pair: no errors at all
    errors = []
    padcheck._compare_leaf(leaf, a0, ax, real, "t", errors)
    assert errors == []


def test_statics_may_not_name_padded_dims():
    """A static arg bakes its dim into the compiled program, so a
    static that names a padded dim can't follow the pad — run_contract
    refuses rather than silently checking the wrong shape."""
    from koordinator_tpu.snapshot.schema import SHAPE_CONTRACTS
    import importlib
    for mod in padcheck.CONTRACT_MODULES:
        importlib.import_module(mod)
    for key, contract in SHAPE_CONTRACTS.items():
        for name, value in contract.static.items():
            if isinstance(value, str):
                assert value not in lspec.PADDED_DIMS, (key, name)


# --- machine-readable output formats ----------------------------------------

_F = Finding(analyzer="pad-soundness", code="PS001",
             path="koordinator_tpu/ops/x.py", line=12,
             message="non-neutral sum over ~pad:one axis, 100%\nsure",
             key="sum:x")


def test_github_annotation_escaping():
    assert runner._github_line(_F) == (
        "::error file=koordinator_tpu/ops/x.py,line=12,"
        "title=PS001 [pad-soundness]"
        "::non-neutral sum over ~pad:one axis, 100%25%0Asure")
    # property values additionally escape , and : (free text doesn't)
    assert runner._github_escape("a,b:c\n", properties=True) == \
        "a%2Cb%3Ac%0A"
    assert runner._github_escape("a,b:c\r\n%") == "a,b:c%0D%0A%25"


def test_sarif_document_shape_and_suppressions():
    doc = runner._sarif_doc([_F], [_F])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "koordlint"
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == ["PS001"]
    new, suppressed = run["results"]
    assert "suppressions" not in new
    assert suppressed["suppressions"][0]["justification"] == "baseline"
    for r in (new, suppressed):
        assert r["partialFingerprints"]["koordlint/v1"] == _F.fingerprint
        assert r["locations"][0]["physicalLocation"][
            "region"]["startLine"] == 12
    json.dumps(doc)                            # serializable end to end


# --- the per-process Project cache ------------------------------------------

def test_cached_project_reuses_then_invalidates(tmp_path):
    (tmp_path / "a.py").write_text("x = 1\n")
    p1 = cached_project(str(tmp_path), excludes=DEFAULT_EXCLUDES)
    p2 = cached_project(str(tmp_path), excludes=DEFAULT_EXCLUDES)
    assert p1 is p2                            # unchanged tree: one parse
    (tmp_path / "a.py").write_text("x = 2\n")
    os.utime(tmp_path / "a.py", ns=(1, 1))    # force a visible stat delta
    p3 = cached_project(str(tmp_path), excludes=DEFAULT_EXCLUDES)
    assert p3 is not p1
    assert p3.modules[0].source == "x = 2\n"


# --- the full Tier B gate + the dual-tier mutation smoke (slow) -------------

@pytest.mark.slow
def test_padcheck_full_gate_green():
    assert padcheck.run_all() == 0


@pytest.mark.slow
def test_dual_tier_mutation_smoke():
    """Both koordpad tiers prove themselves live: a planted pad leak is
    caught by the differential gate, a planted clamp-drop by the static
    pass."""
    assert padcheck.self_test_mutation() == 0
