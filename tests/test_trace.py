"""koordtrace battery: the span tracer's structural contracts (ring
overflow, nesting, thread safety, monotonic timestamps), the Chrome
trace-event export schema Perfetto loads, `Histogram.percentile`
against numpy.quantile, and the zero-overhead-when-disabled pin on the
service dispatch path."""

import json
import threading
import time

import numpy as np
import pytest

from koordinator_tpu.metrics import Registry
from koordinator_tpu.obs import phases
from koordinator_tpu.obs.export import dump, jsonl_to_chrome
from koordinator_tpu.obs.trace import (
    NOOP_SPAN,
    SpanRecord,
    Tracer,
    jsonl_record,
)


# --- span lifecycle ---------------------------------------------------------


def test_span_records_duration_and_attrs():
    tr = Tracer()
    with tr.span("cycle", cycle=3) as a:
        a["attempt"] = 1
        time.sleep(0.002)
    (rec,) = tr.records()
    assert rec.name == "cycle" and rec.cycle == 3
    assert rec.attrs == {"attempt": 1}
    assert rec.t_end_ns > rec.t_start_ns
    assert rec.duration_s >= 0.002


def test_nested_spans_record_parent_and_inherit_cycle():
    tr = Tracer()
    with tr.span("cycle", cycle=7):
        with tr.span("dispatch"):
            with tr.span("device_wait"):
                pass
    by_name = {r.name: r for r in tr.records()}
    assert by_name["device_wait"].parent == "dispatch"
    assert by_name["dispatch"].parent == "cycle"
    assert by_name["cycle"].parent is None
    # cycle id flows down to every nested span
    assert {r.cycle for r in tr.records()} == {7}


def test_event_is_instant_and_inherits_enclosing_span():
    tr = Tracer()
    with tr.span("cycle", cycle=2):
        tr.event("quarantine", attrs={"word": 5})
    ev = [r for r in tr.records() if r.name == "quarantine"][0]
    assert ev.t_start_ns == ev.t_end_ns
    assert ev.parent == "cycle" and ev.cycle == 2


def test_exception_marks_span_and_unwinds_stack():
    tr = Tracer()
    with pytest.raises(RuntimeError):
        with tr.span("cycle", cycle=0):
            with tr.span("dispatch"):
                raise RuntimeError("boom")
    by_name = {r.name: r for r in tr.records()}
    assert by_name["dispatch"].attrs["error"] == "RuntimeError"
    assert by_name["cycle"].attrs["error"] == "RuntimeError"
    # the thread-local stack fully unwound: a fresh span is a root
    with tr.span("next", cycle=1):
        pass
    assert {r.name: r for r in tr.records()}["next"].parent is None


def test_observer_fires_per_close_with_duration():
    seen = []
    tr = Tracer(observer=lambda name, dur: seen.append((name, dur)))
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    assert [n for n, _ in seen] == ["inner", "outer"]
    assert all(d >= 0 for _, d in seen)


# --- ring overflow ----------------------------------------------------------


def test_ring_overflow_drops_oldest_and_counts():
    drops = []
    tr = Tracer(capacity=4, on_drop=lambda: drops.append(1))
    for i in range(10):
        tr.record_span(f"s{i}", 0, 1)
    recs = tr.records()
    assert len(recs) == 4
    # the NEWEST four survive, oldest first
    assert [r.name for r in recs] == ["s6", "s7", "s8", "s9"]
    assert tr.dropped == 6 and len(drops) == 6


def test_capacity_validated():
    with pytest.raises(ValueError):
        Tracer(capacity=0)


# --- monotonic timestamps / thread safety -----------------------------------


def test_timestamps_monotonic_within_thread():
    tr = Tracer()
    for _ in range(50):
        with tr.span("tick"):
            pass
    recs = tr.records()
    assert all(r.t_end_ns >= r.t_start_ns for r in recs)
    starts = [r.t_start_ns for r in recs]
    assert starts == sorted(starts)
    # the anchor pair lets post-hoc analysis map monotonic -> epoch
    assert tr.anchor_monotonic_ns <= recs[0].t_start_ns
    assert tr.anchor_unix_ns > 0


def test_threaded_spans_nest_independently():
    tr = Tracer()
    n_threads, n_spans = 8, 200

    def worker(tid):
        for i in range(n_spans):
            with tr.span(f"outer_t{tid}", cycle=tid):
                with tr.span(f"inner_t{tid}"):
                    pass

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = tr.records()
    assert len(recs) == n_threads * n_spans * 2 and tr.dropped == 0
    # parent attribution never crosses threads: every inner span's
    # parent is its OWN thread's outer span, and cycle ids match
    for r in recs:
        if r.name.startswith("inner_t"):
            tid = int(r.name[len("inner_t"):])
            assert r.parent == f"outer_t{tid}"
            assert r.cycle == tid


# --- Chrome export schema ---------------------------------------------------


def test_chrome_export_schema():
    tr = Tracer()
    with tr.span("cycle", cycle=1, attrs={"attempt": 0}):
        with tr.span("dispatch"):
            pass
        tr.event("retry", attrs={"failure_class": "XLA_TRANSIENT"})
    doc = json.loads(json.dumps(tr.to_chrome()))   # JSON-serializable
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"cycle", "dispatch", "retry"}
    for e in evs:
        assert e["cat"] == "koordtrace"
        assert e["pid"] == tr.pid and isinstance(e["tid"], int)
        assert e["args"]["cycle"] == 1
        if e["name"] == "retry":
            assert e["ph"] == "i" and e["s"] == "t" and "dur" not in e
            assert e["args"]["failure_class"] == "XLA_TRANSIENT"
        else:
            assert e["ph"] == "X" and e["dur"] >= 0
            assert isinstance(e["ts"], float)
    other = doc["otherData"]
    assert other["tracer"] == "koordtrace" and other["dropped"] == 0
    assert other["anchor_unix_ns"] > 0


def test_jsonl_roundtrips_to_chrome():
    tr = Tracer()
    with tr.span("cycle", cycle=4):
        tr.event("quarantine")
    lines = tr.to_jsonl().splitlines()
    assert all(json.loads(l) for l in lines)
    doc = jsonl_to_chrome(lines)
    assert {e["name"] for e in doc["traceEvents"]} == \
        {"cycle", "quarantine"}
    inst = [e for e in doc["traceEvents"] if e["name"] == "quarantine"][0]
    assert inst["ph"] == "i" and inst["args"]["parent"] == "cycle"


def test_jsonl_record_synthetic_span():
    line = jsonl_record(phases.PHASE_STAGE2_NUMA, 0.25,
                        attrs={"gate": "numa"})
    r = json.loads(line)
    assert r["span"] == phases.PHASE_STAGE2_NUMA
    assert r["t_start_ns"] == 0 and r["t_end_ns"] == 250_000_000
    # negative deltas (timing noise) clamp to an instant, not a crash
    r2 = json.loads(jsonl_record("x", -0.1))
    assert r2["t_end_ns"] == 0


def test_dump_writes_requested_formats(tmp_path):
    tr = Tracer()
    with tr.span("cycle", cycle=0):
        pass
    reg = Registry()
    reg.counter("c_total").inc()
    paths = dump(tr, registry=reg, out_dir=str(tmp_path), prefix="t",
                 formats=("chrome", "jsonl", "prom"))
    assert [p.rsplit("/", 1)[-1] for p in paths] == \
        ["t.trace.json", "t.jsonl", "t.prom"]
    chrome = json.loads((tmp_path / "t.trace.json").read_text())
    assert chrome["traceEvents"]
    assert "c_total 1" in (tmp_path / "t.prom").read_text()
    # absent sources skip silently: no tracer -> prom only
    only = dump(None, registry=reg, out_dir=str(tmp_path), prefix="p",
                formats=("chrome", "jsonl", "prom"))
    assert [p.rsplit("/", 1)[-1] for p in only] == ["p.prom"]


# --- phase table ------------------------------------------------------------


def test_phase_table_check():
    assert phases.check_phase(phases.PHASE_TOPK) == phases.PHASE_TOPK
    with pytest.raises(ValueError):
        phases.check_phase("koord/not_a_phase")
    assert set(phases.CYCLE_SKELETON) <= phases.HOST_SPANS
    assert phases.ALL_PHASES == phases.KERNEL_PHASES | phases.HOST_SPANS


# --- Histogram.percentile vs numpy ------------------------------------------


def test_histogram_percentile_tracks_numpy_quantile():
    from koordinator_tpu.scheduler.metrics_defs import PHASE_BUCKETS

    r = Registry()
    h = r.histogram("lat_seconds", labels=("phase",),
                    buckets=PHASE_BUCKETS)
    rng = np.random.default_rng(42)
    draws = rng.uniform(0.0005, 0.4, size=2000)
    for d in draws:
        h.labels("dispatch").observe(float(d))
    for q in (0.5, 0.9, 0.99):
        est = h.percentile(q, "dispatch")
        exact = float(np.quantile(draws, q))
        # bucketed estimate is exact only to the enclosing bucket's
        # width: the estimate and the true quantile share a bucket
        bounds = [0.0] + [b for b in PHASE_BUCKETS]
        idx_est = np.searchsorted(bounds, est, side="left")
        idx_exact = np.searchsorted(bounds, exact, side="left")
        assert abs(idx_est - idx_exact) <= 1, (q, est, exact)
        lo = bounds[max(min(idx_exact, len(bounds) - 1) - 1, 0)]
        hi = bounds[min(idx_exact + 1, len(bounds) - 1)]
        assert lo <= est <= hi, (q, est, exact)


def test_histogram_percentile_edge_cases():
    r = Registry()
    h = r.histogram("x_seconds", buckets=(0.1, 1.0))
    assert h.percentile(0.5) is None          # empty child
    h.observe(0.05)
    assert 0.0 <= h.percentile(0.5) <= 0.1    # first-bucket lower bound 0
    h2 = r.histogram("y_seconds", buckets=(0.1,))
    h2.observe(5.0)                           # lands in +Inf
    assert h2.percentile(0.99) == 0.1         # clamps to last finite bound
    with pytest.raises(ValueError):
        h.percentile(1.5)


# --- zero overhead when disabled --------------------------------------------


def test_noop_span_is_shared_and_stateless():
    assert NOOP_SPAN.__enter__() is None
    with NOOP_SPAN as a:
        assert a is None


def test_disabled_service_span_path_allocates_nothing():
    """trace=None must keep the dispatch path allocation-free in
    obs/trace.py: `_span` returns the shared NOOP_SPAN singleton and a
    full schedule() makes no allocation attributable to the tracer
    module (tracemalloc filtered to obs/trace.py)."""
    import tracemalloc

    from koordinator_tpu.obs import trace as trace_mod
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.utils import synthetic

    svc = SchedulerService(num_rounds=1, k_choices=4)
    assert svc.tracer is None
    assert svc._span("cycle") is NOOP_SPAN
    assert svc._span("dispatch", cycle=3) is NOOP_SPAN
    svc.publish(synthetic.synthetic_cluster(16, num_quotas=4))
    svc.schedule(synthetic.synthetic_pods(16, num_quotas=4))  # warm

    filt = tracemalloc.Filter(True, trace_mod.__file__)
    tracemalloc.start()
    try:
        svc.schedule(synthetic.synthetic_pods(16, seed=5, num_quotas=4))
        snap = tracemalloc.take_snapshot().filter_traces([filt])
    finally:
        tracemalloc.stop()
    stats = snap.statistics("lineno")
    assert stats == [], [str(s) for s in stats]


def test_enabled_service_cycle_carries_skeleton():
    """The flip side of the zero-overhead pin: trace=True records the
    full committed-cycle span skeleton with one shared cycle id."""
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.utils import synthetic

    svc = SchedulerService(num_rounds=1, k_choices=4, trace=True)
    svc.publish(synthetic.synthetic_cluster(16, num_quotas=4))
    svc.schedule(synthetic.synthetic_pods(16, num_quotas=4))
    recs = svc.tracer.records()
    names = {r.name for r in recs}
    # journal_append only appears on journaled services
    assert set(phases.CYCLE_SKELETON) - {phases.SPAN_JOURNAL_APPEND} \
        <= names
    cycles = {r.cycle for r in recs if r.name == phases.SPAN_CYCLE}
    assert cycles == {0}
    for r in recs:
        assert r.name in phases.ALL_PHASES
