"""Golden parity tests: LoadAware filter/score kernels vs sequential oracle.

Mirrors the reference's load_aware_test.go strategy (fake NodeMetrics, exact
filter statuses and scores) with a randomized cluster.
"""

import time

import numpy as np
import pytest

from koordinator_tpu.api.extension import PriorityClass, ResourceKind as RK
from koordinator_tpu.api.types import (
    AggregatedUsage, Node, NodeMetric, ObjectMeta, Pod,
)
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot.builder import SnapshotBuilder

from oracle import OracleArgs, make_oracle_nodes, oracle_filter, oracle_score

NOW = 1_700_000_000.0


def make_cluster(rng, num_nodes=24, stale_every=5, agg_every=3):
    b = SnapshotBuilder(max_nodes=num_nodes)
    for i in range(num_nodes):
        cpu = float(rng.choice([16000, 32000, 64000]))
        mem = float(rng.choice([32, 64, 128])) * 1024
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}", labels={"zone": f"z{i % 2}"}),
                        allocatable={RK.CPU: cpu, RK.MEMORY: mem}))
        if i % 7 == 6:
            continue  # no koordlet on this node (no NodeMetric at all)
        update = NOW - 1000.0 if i % stale_every == stale_every - 1 else NOW - 5.0
        usage = {RK.CPU: float(rng.integers(0, cpu // 100) * 100),
                 RK.MEMORY: float(rng.integers(0, mem // 256) * 256)}
        metric = NodeMetric(node_name=f"n{i}", update_time=update,
                            node_usage=usage)
        if i % agg_every == 0:
            metric.aggregated = [AggregatedUsage(
                usages={"p95": {RK.CPU: usage[RK.CPU] * 1.2,
                                RK.MEMORY: usage[RK.MEMORY] * 1.1},
                        "p50": usage},
                duration_seconds=300.0)]
        b.set_node_metric(metric)
    return b


def make_pods(rng, count=40):
    pods = []
    for j in range(count):
        prio = int(rng.choice([9100, 7100, 5100, 3100]))
        pods.append(Pod(
            meta=ObjectMeta(name=f"p{j}"),
            requests={RK.CPU: float(rng.integers(1, 16) * 500),
                      RK.MEMORY: float(rng.integers(1, 32) * 512)},
            limits={},
            priority=prio,
            is_daemonset=bool(j % 11 == 10),
        ))
    return pods


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("agg_filter,score_prod", [(False, False), (True, False),
                                                   (False, True)])
def test_filter_score_parity(seed, agg_filter, score_prod):
    rng = np.random.default_rng(seed)
    b = make_cluster(rng)
    pods = make_pods(rng)
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(pods, ctx)

    kwargs = dict(score_according_prod_usage=score_prod)
    oargs = OracleArgs.default()
    oargs.score_according_prod_usage = score_prod
    if agg_filter:
        kwargs.update(filter_agg_type="p95",
                      agg_usage_thresholds={RK.CPU: 70.0, RK.MEMORY: 95.0})
        oargs.filter_agg_type = "p95"
        oargs.agg_usage_thresholds = {RK.CPU: 70, RK.MEMORY: 95}
    cfg = loadaware.LoadAwareConfig.make(**kwargs)

    mask = np.asarray(loadaware.filter_mask(snap.nodes, batch, cfg))
    scores = np.asarray(loadaware.score_matrix(snap.nodes, batch, cfg))

    onodes = make_oracle_nodes(b, NOW)
    for p, pod in enumerate(pods):
        for n, on in enumerate(onodes):
            want = oracle_filter(on, pod, oargs)
            assert mask[p, n] == want, (p, n, pod.meta.name, on.node.meta.name)
            got, want_s = scores[p, n], oracle_score(on, pod, oargs)
            assert abs(got - want_s) <= 1.0, (p, n, got, want_s)


def test_prod_threshold_gate():
    """Prod pods are gated on prod-tier usage when ProdUsageThresholds set
    (load_aware.go:151-160)."""
    b = SnapshotBuilder(max_nodes=2)
    b.add_node(Node(meta=ObjectMeta(name="hot"),
                    allocatable={RK.CPU: 10000, RK.MEMORY: 32768}))
    b.add_node(Node(meta=ObjectMeta(name="cool"),
                    allocatable={RK.CPU: 10000, RK.MEMORY: 32768}))
    from koordinator_tpu.api.types import PodMetricInfo
    b.set_node_metric(NodeMetric(
        node_name="hot", update_time=NOW,
        node_usage={RK.CPU: 1000.0},
        pods_metric=[PodMetricInfo(namespace="d", name="x",
                                   priority_class=PriorityClass.PROD,
                                   usage={RK.CPU: 8000.0})]))
    b.set_node_metric(NodeMetric(node_name="cool", update_time=NOW,
                                 node_usage={RK.CPU: 1000.0}))
    snap, ctx = b.build(now=NOW)

    prod_pod = Pod(meta=ObjectMeta(name="prod"), priority=9500,
                   requests={RK.CPU: 100.0})
    batch_pod = Pod(meta=ObjectMeta(name="batch"), priority=5500,
                    requests={RK.CPU: 100.0})
    batch = b.build_pod_batch([prod_pod, batch_pod], ctx)
    cfg = loadaware.LoadAwareConfig.make(
        prod_usage_thresholds={RK.CPU: 60.0})
    mask = np.asarray(loadaware.filter_mask(snap.nodes, batch, cfg))
    assert not mask[0, 0]   # prod pod rejected: prod usage 80% >= 60%
    assert mask[0, 1]       # cool node fine
    assert mask[1, 0]       # batch pod not subject to prod gate
    assert mask[1, 1]


def test_missing_metric_passes_filter_scores_zero():
    b = SnapshotBuilder(max_nodes=1)
    b.add_node(Node(meta=ObjectMeta(name="bare"),
                    allocatable={RK.CPU: 1000, RK.MEMORY: 1024}))
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch([Pod(meta=ObjectMeta(name="p"),
                                   requests={RK.CPU: 100.0})], ctx)
    cfg = loadaware.LoadAwareConfig.make()
    assert np.asarray(loadaware.filter_mask(snap.nodes, batch, cfg))[0, 0]
    assert np.asarray(loadaware.score_matrix(snap.nodes, batch, cfg))[0, 0] == 0
