"""Admission dispatch framework (pkg/webhook/server.go handler
registry): kind routing, gate behavior, mutate-then-validate phase
order, and the quota topology guard."""

import json

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_NODE_AMPLIFICATION_RATIOS,
    ResourceKind as RK,
)
from koordinator_tpu.features import new_default_gate
from koordinator_tpu.webhook import QuotaTopology
from koordinator_tpu.webhook.framework import AdmissionDispatcher
from koordinator_tpu.webhook.pod_mutating import PodMutator


def mk_dispatcher(**kw):
    kw.setdefault("quota_topology", QuotaTopology())
    return AdmissionDispatcher(**kw)


def test_framework_gate_disables_everything():
    gate = new_default_gate()
    gate.set("WebhookFramework", False)
    d = mk_dispatcher(gate=gate)
    # a node with a broken annotation would normally be rejected
    node = api.Node(meta=api.ObjectMeta(name="n0", annotations={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: "not json"}))
    resp = d.admit("Node", node)
    assert resp.allowed and not resp.mutated


def test_pod_mutate_then_validate():
    mutator = PodMutator(
        [api.ClusterColocationProfile(
            meta=api.ObjectMeta(name="colo"), selector={"app": "spark"},
            qos_class="BE", priority_class_name="koord-batch")],
        priority_classes={"koord-batch": 5500})
    d = mk_dispatcher(mutator=mutator)
    pod = api.Pod(meta=api.ObjectMeta(name="p", labels={"app": "spark"}),
                  requests={RK.CPU: 1000.0, RK.MEMORY: 512.0})
    resp = d.admit("Pod", pod)
    assert resp.allowed and resp.mutated
    assert RK.BATCH_CPU in pod.requests  # mutation ran before validation


def test_pod_validating_gate_respected():
    gate = new_default_gate()
    gate.set("PodValidatingWebhook", False)
    d = mk_dispatcher(gate=gate)
    # an invalid pod passes when the validating gate is off
    bad = api.Pod(meta=api.ObjectMeta(name="p"), qos_label="LSE",
                  priority=5500)  # LSE + batch priority is invalid
    assert d.admit("Pod", bad).allowed
    assert not mk_dispatcher().admit("Pod", bad).allowed


def test_node_reject_on_bad_annotation():
    d = mk_dispatcher()
    node = api.Node(meta=api.ObjectMeta(name="n0", annotations={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": "abc"}'}),
        allocatable={RK.CPU: 1000.0})
    resp = d.admit("Node", node)
    assert not resp.allowed and resp.errors


def test_node_mutates_amplification():
    d = mk_dispatcher()
    node = api.Node(meta=api.ObjectMeta(name="n0", annotations={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 2.0}'}),
        allocatable={RK.CPU: 1000.0, RK.MEMORY: 1024.0})
    resp = d.admit("Node", node)
    assert resp.allowed and resp.mutated
    assert node.allocatable[RK.CPU] == 2000.0


def test_configmap_routing():
    d = mk_dispatcher()
    assert d.admit("ConfigMap", {
        "colocation-config": json.dumps({"enable": True})}).allowed
    resp = d.admit("ConfigMap", {"no-such-key": "{}"})
    assert not resp.allowed


def test_quota_lifecycle_through_dispatcher():
    topo = QuotaTopology()
    d = mk_dispatcher(quota_topology=topo)
    q = api.ElasticQuota(meta=api.ObjectMeta(name="team-a"),
                        min={RK.CPU: 1000.0}, max={RK.CPU: 2000.0})
    assert d.admit("ElasticQuota", q, "Create").allowed
    assert "team-a" in topo.quotas
    # duplicate add rejected
    q2 = api.ElasticQuota(meta=api.ObjectMeta(name="team-a"),
                         min={RK.CPU: 1.0}, max={RK.CPU: 2.0})
    assert not d.admit("ElasticQuota", q2, "Create").allowed
    assert d.admit("ElasticQuota", q, "Delete").allowed
    assert "team-a" not in topo.quotas


def test_unregistered_kind_passes():
    assert mk_dispatcher().admit("Unknown", object()).allowed


def test_delete_skips_validation_for_non_quota_kinds():
    """A pre-existing invalid object must stay deletable: validation
    (and mutation) never gate Delete except the quota topology checks."""
    d = mk_dispatcher()
    bad_pod = api.Pod(meta=api.ObjectMeta(name="p"), qos_label="LSE",
                      priority=5500)
    assert not d.admit("Pod", bad_pod, "Update").allowed
    resp = d.admit("Pod", bad_pod, "Delete")
    assert resp.allowed and not resp.mutated and not resp.errors
    bad_node = api.Node(meta=api.ObjectMeta(name="n", annotations={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: "not json"}))
    assert d.admit("Node", bad_node, "Delete").allowed


def test_annotation_override_after_int_valued_configmap_override():
    """Declared-type dispatch: a ConfigMap override that left an int in a
    float field must not make later float annotations get dropped."""
    from koordinator_tpu.api.extension import (
        ANNOTATION_NODE_COLOCATION_STRATEGY,
    )
    from koordinator_tpu.slo_controller.config import (
        ColocationConfig,
        ColocationStrategy,
        ColocationStrategyOverride,
    )
    cfg = ColocationConfig(
        cluster_strategy=ColocationStrategy(),
        node_overrides=[ColocationStrategyOverride(
            node_selector={"pool": "x"},
            fields={"cpu_reclaim_threshold_percent": 70})])  # int!
    s = cfg.strategy_for({"pool": "x"}, {
        ANNOTATION_NODE_COLOCATION_STRATEGY:
        json.dumps({"cpuReclaimThresholdPercent": 80.0})})
    assert s.cpu_reclaim_threshold_percent == 80.0


def test_quota_mutated_reflects_actual_defaulting():
    topo = QuotaTopology()
    d = mk_dispatcher(quota_topology=topo)
    # a quota needing defaults (parent unset) reports mutated
    q = api.ElasticQuota(meta=api.ObjectMeta(name="a"),
                        min={RK.CPU: 1.0}, max={RK.CPU: 2.0})
    assert d.admit("ElasticQuota", q, "Create").mutated
    # an update where defaulting changes nothing reports unmutated
    resp = d.admit("ElasticQuota", q, "Update")
    assert resp.allowed and not resp.mutated


def test_manager_mutator_slot_is_shared(tmp_path):
    """Assigning proc.mutator must make admission apply it — a second
    disconnected slot would silently skip profile translation."""
    from koordinator_tpu.cmd import manager as cmd_manager

    class Src:
        def nodes(self): return []
        def node_metrics(self): return {}
        def pods_by_node(self): return {}
        def quota_profiles(self): return []

    proc = cmd_manager.ManagerProcess(
        cmd_manager.ManagerConfig(lease_file=str(tmp_path / "m2.lease")),
        Src())
    proc.mutator = PodMutator(
        [api.ClusterColocationProfile(
            meta=api.ObjectMeta(name="c"), selector={"app": "spark"},
            qos_class="BE", priority_class_name="koord-batch")],
        priority_classes={"koord-batch": 5500})
    pod = api.Pod(meta=api.ObjectMeta(name="p", labels={"app": "spark"}),
                  requests={RK.CPU: 1000.0, RK.MEMORY: 512.0})
    resp = proc.admission.admit("Pod", pod)
    assert resp.mutated and RK.BATCH_CPU in pod.requests


def test_manager_hosts_the_dispatcher(tmp_path):
    from koordinator_tpu.cmd import manager as cmd_manager

    class Src:
        def nodes(self): return []
        def node_metrics(self): return {}
        def pods_by_node(self): return {}
        def quota_profiles(self): return []

    proc = cmd_manager.ManagerProcess(
        cmd_manager.ManagerConfig(lease_file=str(tmp_path / "m.lease")),
        Src())
    q = api.ElasticQuota(meta=api.ObjectMeta(name="t"),
                        min={RK.CPU: 1.0}, max={RK.CPU: 2.0})
    assert proc.admission.admit("ElasticQuota", q, "Create").allowed
    # the dispatcher guards the SAME topology the profile reconciler uses
    assert "t" in proc.quota_reconciler.topology.quotas
