"""koordshape test battery: the contract registry, the spec grammar,
the static (AST) tier's repo-wide cleanliness, and the dynamic
(eval_shape) tier's detectors — dtype promotion, weak-type leaks,
output-shape drift, and the two-assignment dim-coupling trap.

Per-SH-code pos/neg fixture coverage lives in test_lint.py's
parametrized fixture battery (tests/fixtures/lint/shape_contract/);
this file covers everything the fixtures can't: the grammar itself,
the vocabulary pin between the two tiers, and Tier B's checkers
against deliberately broken kernels that are NEVER registered (the
global registry stays clean for the full-registry gate test).
"""

import os

import jax
import jax.numpy as jnp
import pytest

from koordinator_tpu.snapshot import schema
from tools import shapecheck
from tools.lint.runner import REPO_ROOT, run_lint
from tools.lint.shapes import spec as lint_spec
from tools.lint.shapes.spec import (
    DimProp,
    LeafSpec,
    SpecError,
    StructRef,
    broadcast_join,
    parse_spec,
)

SIZES_A = shapecheck._sizes(shapecheck.ASSIGNMENT_A)
SIZES_B = shapecheck._sizes(shapecheck.ASSIGNMENT_B)


def _contract(fn, args, returns, static=None):
    """An AD-HOC contract (never registered: the registry feeds the CI
    gate and must not accumulate test debris)."""
    return schema.ShapeContract(
        name=fn.__name__, module="tests.adhoc", fn=fn, args=args,
        returns=returns, static=static or {}, callables={}, pad="")


# --- the two tiers share one vocabulary -----------------------------------

def test_dim_vocab_pinned_between_tiers():
    assert lint_spec.DIM_VOCAB == schema.DIM_VOCAB, \
        "tools/lint/shapes/spec.py and snapshot/schema.py must carry " \
        "the SAME dim vocabulary"
    assert set(lint_spec.FIXED_DIM_SYMBOLS) == set(schema.FIXED_DIMS), \
        "fixed-dim symbols drifted between the tiers"


def test_vocab_disjoint_from_fixed():
    assert not set(lint_spec.DIM_VOCAB) & set(schema.FIXED_DIMS)


# --- spec grammar ---------------------------------------------------------

def test_parse_leaf_scalar_optional_struct_prop():
    leaf = parse_spec("f32[P,N]")
    assert leaf == LeafSpec("f32", ("P", "N"))
    assert parse_spec("bool[]") == LeafSpec("bool", ())
    assert parse_spec("f32[N,Z,2]") == LeafSpec("f32", ("N", "Z", 2))
    opt = parse_spec("?f32[P,N]")
    assert opt.optional
    assert parse_spec("PodBatch") == StructRef("PodBatch")
    assert parse_spec("N") == DimProp("N")
    assert parse_spec(("i32[P]", "bool[P]")) == \
        (LeafSpec("i32", ("P",)), LeafSpec("bool", ("P",)))


@pytest.mark.parametrize("bad", [
    "f33[P]",            # unknown dtype
    "f32[XY]",           # undeclared dim
    "f32[P,]",           # empty dim
    "lowercase",         # neither dim, struct, nor leaf
    "f32[P][N]",         # malformed bracket
    123,                 # not a string at all
])
def test_parse_rejects_malformed(bad):
    with pytest.raises(SpecError):
        parse_spec(bad)


def test_broadcast_join_semantics():
    j = broadcast_join(("P", "R"), ("N", "R"))
    assert j.conflicts == [("P", "N")]
    j = broadcast_join(("P", 1), (1, "N"))
    assert j.dims == ("P", "N") and not j.conflicts
    j = broadcast_join(("P", "N"), ("N",))
    assert j.rank_growth and not j.conflicts
    j = broadcast_join(("P", None), ("P", "N"))
    assert j.dims == ("P", None) and not j.conflicts
    assert broadcast_join(None, ("P",)).dims is None


# --- static tier: per-code fixtures ---------------------------------------
# (test_lint.py's parametrized battery also walks these trees; the
# per-code assertions here keep the koordshape suite self-contained)

_SH_FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint",
                            "shape_contract")


@pytest.mark.parametrize("code", ["SH001", "SH002", "SH003", "SH004",
                                  "SH005"])
def test_positive_fixture_per_code(code, tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text('{"suppressions": []}')
    new, _ = run_lint(os.path.join(_SH_FIXTURES, "pos"),
                      analyzers=["shape-contract"],
                      baseline_path=str(bl))
    assert code in {f.code for f in new}, \
        [f.render() for f in new]


def test_negative_fixture_clean(tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text('{"suppressions": []}')
    new, _ = run_lint(os.path.join(_SH_FIXTURES, "neg"),
                      analyzers=["shape-contract"],
                      baseline_path=str(bl))
    assert new == [], [f.render() for f in new]


# --- static tier: the repo itself is contract-clean -----------------------

def test_repo_shape_contract_clean_and_registry_total():
    """The in-repo instance of the acceptance pin: every jitted entry
    point in koordinator_tpu/ carries a contract (no SH004), the
    abstract interpretation of every contract body is conflict-free,
    AND the RUNTIME registry (what Tier B drives) names every
    koordinator_tpu jit entry the AST tier sees — one repo scan serves
    both assertions."""
    import ast as _ast
    import importlib

    new, suppressed = run_lint(
        REPO_ROOT, analyzers=["shape-contract"],
        baseline_path=os.path.join(REPO_ROOT, "tools", "lint",
                                   "baseline.json"))
    assert new == [] and suppressed == [], \
        [f.render() for f in new + suppressed]

    from tools.lint.framework import Project
    from tools.lint.callgraph import project_index

    for mod in shapecheck.CONTRACT_MODULES:
        importlib.import_module(mod)
    keys = set(schema.SHAPE_CONTRACTS)
    project = Project(REPO_ROOT)
    for entry in project_index(project).jit_entries():
        rel = entry.fn.module.relpath
        if not rel.startswith("koordinator_tpu/"):
            continue
        if not isinstance(entry.fn.scope_chain[-1], _ast.Module):
            continue
        dotted = entry.fn.module.dotted + "." + entry.fn.node.name
        assert dotted in keys, f"{dotted} jitted but not registered"


# --- dynamic tier: the eval_shape detectors -------------------------------

@pytest.mark.slow
def test_eval_shape_full_registry_clean():
    """Tier B end-to-end over the real registry, both assignments.
    Marked slow: tools/ci.sh runs the SAME invocation as its own
    shapecheck stage on every push, so tier-1 need not pay the ~8s
    twice; the detector unit tests below stay in the fast battery."""
    assert shapecheck.run_all() == 0


def test_eval_shape_catches_dtype_promotion():
    def promoting(x):
        return x + 1.0            # f32 in, f32 out — fine

    def flipped(x):
        return (x > 0).astype(jnp.int32)   # declared bool, returns i32

    ok = _contract(promoting, {"x": "f32[N]"}, "f32[N]")
    assert shapecheck.run_contract(ok, SIZES_A, "ok") == []
    bad = _contract(flipped, {"x": "f32[N]"}, "bool[N]")
    errs = shapecheck.run_contract(bad, SIZES_A, "bad")
    assert errs and "dtype drift" in errs[0]


def test_eval_shape_catches_dim_coupling():
    """A kernel that uses one dim where the contract declares another
    only survives an assignment where the sizes collide — the second
    assignment (P/N flipped, all-distinct) must catch it."""
    def coupled(alloc, req):
        # claims [P] but actually produces [N]
        return jnp.sum(alloc, axis=-1)

    c = _contract(coupled, {"alloc": "f32[N,R]", "req": "f32[P,R]"},
                  "f32[P]")
    errs_a = shapecheck.run_contract(c, SIZES_A, "A")
    errs_b = shapecheck.run_contract(c, SIZES_B, "B")
    assert errs_a or errs_b, "dim coupling escaped both assignments"
    assert any("shape drift" in e for e in errs_a + errs_b)


def test_eval_shape_catches_weak_type_leak():
    def leaky(x):
        del x
        return jnp.asarray(1.0)   # weak f32 scalar

    c = _contract(leaky, {"x": "f32[N]"}, "f32[]")
    errs = shapecheck.run_contract(c, SIZES_A, "leaky")
    assert errs and "weak-type" in errs[0]


def test_eval_shape_catches_optional_and_none():
    def gated(x):
        return x * 2.0, None

    ok = _contract(gated, {"x": "f32[P,N]"}, ("f32[P,N]", "?f32[P,N]"))
    assert shapecheck.run_contract(ok, SIZES_A, "ok") == []
    strict = _contract(gated, {"x": "f32[P,N]"},
                       ("f32[P,N]", "f32[P,N]"))
    errs = shapecheck.run_contract(strict, SIZES_A, "strict")
    assert errs and "None" in errs[0]


def test_eval_shape_static_dim_binding():
    """A _static value naming a dim symbol resolves to that dim's
    assigned size (the tail_chunk -> TC binding)."""
    def windowed(x, width):
        return x[:width]

    c = _contract(windowed, {"x": "i32[P]"}, "i32[TC]",
                  static={"width": "TC"})
    assert shapecheck.run_contract(c, SIZES_A, "w") == []


def test_build_value_structs_and_x64_guard():
    snap = shapecheck.build_value(parse_spec("ClusterSnapshot"), SIZES_A)
    assert isinstance(snap, schema.ClusterSnapshot)
    assert snap.nodes.allocatable.shape == (SIZES_A["N"], SIZES_A["R"])
    assert snap.quotas.depth_ancestor.shape == \
        (SIZES_A["Q"], schema.MAX_QUOTA_DEPTH)
    assert str(snap.nodes.metric_fresh.dtype) == "bool"
    assert not jax.config.jax_enable_x64, \
        "the contracts pin 32-bit layouts; tier-1 must run x64-off"


@pytest.mark.slow
def test_seeded_mutation_smoke():
    """Gate liveness: the dtype flip in a temp copy of
    ops/feasibility.py must make shapecheck FAIL. Marked slow (a
    subprocess re-imports jax over the mutated tree, ~13s); tools/ci.sh
    runs the same smoke as its own stage on every push, so the gate's
    liveness is still proven per-push."""
    assert shapecheck.self_test_mutation() == 0
