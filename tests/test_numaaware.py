"""NodeNUMAResource: device zone kernels + host cpuset accumulator.

Mirrors plugins/nodenumaresource/ semantics (topology_hint.go,
cpu_accumulator.go, scoring.go).
"""

import numpy as np
import pytest

from koordinator_tpu.api.extension import QoSClass, ResourceKind as RK
from koordinator_tpu.api.types import (
    Node, NodeMetric, NodeResourceTopology, NUMAZone, ObjectMeta, Pod,
)
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.scheduler.plugins.cpu_accumulator import (
    CPUAllocationError, CPUTopology, take_cpus, take_preferred_cpus,
)
from koordinator_tpu.snapshot.builder import SnapshotBuilder

NOW = 1_700_000_000.0
CFG = loadaware.LoadAwareConfig.make()


def numa_node(name, zone_cpu=8000.0, zone_mem=16384.0, zones=2):
    return Node(
        meta=ObjectMeta(name=name),
        allocatable={RK.CPU: zone_cpu * zones, RK.MEMORY: zone_mem * zones},
        topology=NodeResourceTopology(
            zones=[NUMAZone(cpus_milli=zone_cpu, memory_mib=zone_mem)
                   for _ in range(zones)]))


def bind_pod(name, cpu, mem, priority=9100):
    return Pod(meta=ObjectMeta(name=name),
               requests={RK.CPU: cpu, RK.MEMORY: mem},
               priority=priority, qos_label="LSR", required_cpu_bind=True)


def build(nodes, pods, **kw):
    b = SnapshotBuilder(max_nodes=len(nodes))
    for n in nodes:
        b.add_node(n)
        b.set_node_metric(NodeMetric(node_name=n.meta.name,
                                     update_time=NOW - 2,
                                     node_usage={RK.CPU: 0.0}))
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(pods, ctx)
    return core.schedule_batch(snap, batch, CFG, **{"num_rounds": 3, **kw})


def test_single_numa_fit_gate():
    # pod needs 6000m in ONE zone; node zones are 4000m each though the
    # node total (8000m) would fit -> unschedulable on that node.
    small = numa_node("small", zone_cpu=4000.0)
    big = numa_node("big", zone_cpu=8000.0)
    res = build([small, big], [bind_pod("p", 6000.0, 1024.0)])
    assert int(res.assignment[0]) == 1
    assert int(res.numa_zone[0]) >= 0


def test_zone_accounting_and_contention():
    # zones hold 8000m each; three 5000m bound pods -> only two fit (one
    # per zone), third is revoked by zone exactness.
    n = numa_node("n0", zone_cpu=8000.0, zones=2)
    pods = [bind_pod(f"p{i}", 5000.0, 1024.0, priority=9500 - i)
            for i in range(3)]
    res = build([n], pods)
    a = np.asarray(res.assignment)
    z = np.asarray(res.numa_zone)
    assert (a[:2] == 0).all() and a[2] == -1
    assert z[0] != z[1]  # each took its own zone
    free = np.asarray(res.snapshot.nodes.numa_free)[0]
    np.testing.assert_allclose(sorted(free[:2, 0]), [3000.0, 3000.0])


def test_most_allocated_packs_zones():
    # strategy "most": second small pod should pack into the same zone.
    n = numa_node("n0", zone_cpu=8000.0, zones=2)
    pods = [bind_pod("a", 2000.0, 1024.0, priority=9500),
            bind_pod("b", 2000.0, 1024.0, priority=9400)]
    res = build([n], pods, numa_strategy="most")
    z = np.asarray(res.numa_zone)
    assert z[0] == z[1]


def test_least_allocated_spreads_zones_sequentially():
    # LeastAllocated spreading is sequential-exact at chunk size 1
    # (choose_zone docstring): feed pods one at a time.
    b = SnapshotBuilder(max_nodes=1)
    n = numa_node("n0", zone_cpu=8000.0, zones=2)
    b.add_node(n)
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW - 2,
                                 node_usage={RK.CPU: 0.0}))
    snap, ctx = b.build(now=NOW)
    zones = []
    for name in ("a", "b"):
        batch = b.build_pod_batch([bind_pod(name, 2000.0, 1024.0)], ctx)
        res = core.schedule_batch(snap, batch, CFG, num_rounds=1,
                                  numa_strategy="least")
        zones.append(int(res.numa_zone[0]))
        snap = res.snapshot
    assert zones[0] != zones[1]


def test_unbound_pods_ignore_numa():
    n = numa_node("n0", zone_cpu=2000.0, zones=2)  # tiny zones
    p = Pod(meta=ObjectMeta(name="p"), requests={RK.CPU: 3000.0},
            priority=9000)  # exceeds any zone but fits the node
    res = build([n], [p])
    assert int(res.assignment[0]) == 0
    assert int(res.numa_zone[0]) == -1


# --- host cpuset accumulator -------------------------------------------------

TOPO = CPUTopology.uniform(num_sockets=2, nodes_per_socket=1,
                           cores_per_node=4, threads_per_core=2)
ALL = {c.cpu for c in TOPO.cpus}


def test_full_pcpus_whole_cores():
    got = take_cpus(TOPO, ALL, {}, 4, bind_policy="FullPCPUs")
    cores = {TOPO.by_cpu[c].core for c in got}
    assert len(got) == 4 and len(cores) == 2  # two whole cores
    # sibling pairs complete
    for core in cores:
        assert all(m.cpu in got for m in TOPO.cores[core])


def test_spread_by_pcpus_distinct_cores():
    got = take_cpus(TOPO, ALL, {}, 4, bind_policy="SpreadByPCPUs")
    cores = [TOPO.by_cpu[c].core for c in got]
    assert len(set(cores)) == 4  # one per physical core


def test_most_allocated_packs_numa_node():
    # node 0 partially used -> "most" strategy fills node 0 first
    allocated = {0: 1, 1: 1}
    avail = ALL - {0, 1}
    got = take_cpus(TOPO, avail, allocated, 4, bind_policy="FullPCPUs",
                    numa_strategy="most")
    assert all(TOPO.by_cpu[c].node == 0 for c in got)
    got_least = take_cpus(TOPO, avail, allocated, 4,
                          bind_policy="FullPCPUs", numa_strategy="least")
    assert all(TOPO.by_cpu[c].node == 1 for c in got_least)


def test_max_ref_count_sharing_and_exhaustion():
    allocated = {c: 1 for c in ALL}
    with pytest.raises(CPUAllocationError):
        take_cpus(TOPO, ALL, allocated, 2, max_ref_count=1)
    got = take_cpus(TOPO, ALL, allocated, 2, max_ref_count=2)
    assert len(got) == 2


def test_pcpu_exclusive_avoids_marked_cores():
    got = take_cpus(TOPO, ALL, {}, 2, bind_policy="SpreadByPCPUs",
                    exclusive_policy="PCPULevel", exclusive_cores={0, 1})
    assert all(TOPO.by_cpu[c].core not in {0, 1} for c in got)


def test_preferred_reservation_cpus_first():
    got = take_preferred_cpus(TOPO, ALL, preferred={4, 5}, allocated={},
                              num_needed=4, bind_policy="FullPCPUs")
    assert {4, 5}.issubset(got) and len(got) == 4
