"""NodeNUMAResource: device zone kernels + host cpuset accumulator.

Mirrors plugins/nodenumaresource/ semantics (topology_hint.go,
cpu_accumulator.go, scoring.go).
"""

import numpy as np
import pytest

from koordinator_tpu.api.extension import QoSClass, ResourceKind as RK
from koordinator_tpu.api.types import (
    Node, NodeMetric, NodeResourceTopology, NUMAZone, ObjectMeta, Pod,
)
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.scheduler.plugins.cpu_accumulator import (
    CPUAllocationError, CPUTopology, take_cpus, take_preferred_cpus,
)
from koordinator_tpu.snapshot.builder import SnapshotBuilder

NOW = 1_700_000_000.0
CFG = loadaware.LoadAwareConfig.make()


def numa_node(name, zone_cpu=8000.0, zone_mem=16384.0, zones=2):
    return Node(
        meta=ObjectMeta(name=name),
        allocatable={RK.CPU: zone_cpu * zones, RK.MEMORY: zone_mem * zones},
        topology=NodeResourceTopology(
            zones=[NUMAZone(cpus_milli=zone_cpu, memory_mib=zone_mem)
                   for _ in range(zones)]))


def bind_pod(name, cpu, mem, priority=9100):
    return Pod(meta=ObjectMeta(name=name),
               requests={RK.CPU: cpu, RK.MEMORY: mem},
               priority=priority, qos_label="LSR", required_cpu_bind=True)


def build(nodes, pods, **kw):
    b = SnapshotBuilder(max_nodes=len(nodes))
    for n in nodes:
        b.add_node(n)
        b.set_node_metric(NodeMetric(node_name=n.meta.name,
                                     update_time=NOW - 2,
                                     node_usage={RK.CPU: 0.0}))
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(pods, ctx)
    return core.schedule_batch(snap, batch, CFG, **{"num_rounds": 3, **kw})


def test_single_numa_fit_gate():
    # pod needs 6000m in ONE zone; node zones are 4000m each though the
    # node total (8000m) would fit -> unschedulable on that node.
    small = numa_node("small", zone_cpu=4000.0)
    big = numa_node("big", zone_cpu=8000.0)
    res = build([small, big], [bind_pod("p", 6000.0, 1024.0)])
    assert int(res.assignment[0]) == 1
    assert int(res.numa_zone[0]) >= 0


def test_zone_accounting_and_contention():
    # zones hold 8000m each; three 5000m bound pods -> only two fit (one
    # per zone), third is revoked by zone exactness.
    n = numa_node("n0", zone_cpu=8000.0, zones=2)
    pods = [bind_pod(f"p{i}", 5000.0, 1024.0, priority=9500 - i)
            for i in range(3)]
    res = build([n], pods)
    a = np.asarray(res.assignment)
    z = np.asarray(res.numa_zone)
    assert (a[:2] == 0).all() and a[2] == -1
    assert z[0] != z[1]  # each took its own zone
    free = np.asarray(res.snapshot.nodes.numa_free)[0]
    np.testing.assert_allclose(sorted(free[:2, 0]), [3000.0, 3000.0])


def test_most_allocated_packs_zones():
    # strategy "most": second small pod should pack into the same zone.
    n = numa_node("n0", zone_cpu=8000.0, zones=2)
    pods = [bind_pod("a", 2000.0, 1024.0, priority=9500),
            bind_pod("b", 2000.0, 1024.0, priority=9400)]
    res = build([n], pods, numa_strategy="most")
    z = np.asarray(res.numa_zone)
    assert z[0] == z[1]


def test_least_allocated_spreads_zones_sequentially():
    # LeastAllocated spreading is sequential-exact at chunk size 1
    # (choose_zone docstring): feed pods one at a time.
    b = SnapshotBuilder(max_nodes=1)
    n = numa_node("n0", zone_cpu=8000.0, zones=2)
    b.add_node(n)
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW - 2,
                                 node_usage={RK.CPU: 0.0}))
    snap, ctx = b.build(now=NOW)
    zones = []
    for name in ("a", "b"):
        batch = b.build_pod_batch([bind_pod(name, 2000.0, 1024.0)], ctx)
        res = core.schedule_batch(snap, batch, CFG, num_rounds=1,
                                  numa_strategy="least")
        zones.append(int(res.numa_zone[0]))
        snap = res.snapshot
    assert zones[0] != zones[1]


def test_unbound_pods_ignore_numa():
    n = numa_node("n0", zone_cpu=2000.0, zones=2)  # tiny zones
    p = Pod(meta=ObjectMeta(name="p"), requests={RK.CPU: 3000.0},
            priority=9000)  # exceeds any zone but fits the node
    res = build([n], [p])
    assert int(res.assignment[0]) == 0
    assert int(res.numa_zone[0]) == -1


# --- topology-manager policies end-to-end -----------------------------------


def policy_node(name, policy, zone_cpu=2000.0, zone_mem=4096.0, zones=2):
    return Node(
        meta=ObjectMeta(name=name),
        allocatable={RK.CPU: zone_cpu * zones, RK.MEMORY: zone_mem * zones},
        topology=NodeResourceTopology(
            policy=policy,
            zones=[NUMAZone(cpus_milli=zone_cpu, memory_mib=zone_mem)
                   for _ in range(zones)]))


def plain_pod(name, cpu, mem, priority=9000):
    return Pod(meta=ObjectMeta(name=name),
               requests={RK.CPU: cpu, RK.MEMORY: mem}, priority=priority)


def test_policy_none_node_does_not_engage_plain_pods():
    # cross-zone pod on a none-policy node: placed, no zone charge
    n = policy_node("n0", "None")
    res = build([n], [plain_pod("p", 3000.0, 1024.0)])
    assert int(res.assignment[0]) == 0
    np.testing.assert_allclose(np.asarray(res.numa_take[0]).sum(), 0.0)
    np.testing.assert_allclose(np.asarray(res.snapshot.nodes.numa_free),
                               np.asarray(res.snapshot.nodes.numa_cap))


def test_best_effort_charges_zones_cross_zone():
    # 3000m needs both 2000m zones; best-effort admits and splits the take
    n = policy_node("n0", "BestEffort")
    res = build([n], [plain_pod("p", 3000.0, 1024.0)])
    assert int(res.assignment[0]) == 0
    take = np.asarray(res.numa_take[0])
    np.testing.assert_allclose(take[:, 0].sum(), 3000.0)
    assert (take[:, 0] > 0).sum() == 2  # genuinely split across zones
    free = np.asarray(res.snapshot.nodes.numa_free)[0]
    np.testing.assert_allclose(free[:, 0].sum(), 1000.0)


def test_restricted_rejects_unpreferred_merge():
    # restricted node whose zones each fit the pod singly -> single-zone
    # preferred merge -> admitted on one zone
    ok_node = policy_node("ok", "Restricted", zone_cpu=4000.0)
    res = build([ok_node], [plain_pod("p", 3000.0, 1024.0)])
    assert int(res.assignment[0]) == 0
    take = np.asarray(res.numa_take[0])
    assert (take[:, 0] > 0).sum() == 1


def test_single_numa_node_policy_applies_to_plain_pods():
    # a plain (non-cpu-bind) pod that only fits across zones is rejected
    # by a SingleNUMANode-policy node but accepted by a BestEffort one
    strict = policy_node("strict", "SingleNUMANode")
    soft = policy_node("soft", "BestEffort")
    res = build([strict, soft], [plain_pod("p", 3000.0, 1024.0)])
    assert int(res.assignment[0]) == 1
    res2 = build([strict], [plain_pod("p", 3000.0, 1024.0)])
    assert int(res2.assignment[0]) == -1


def test_policy_zone_capacity_is_exact_under_contention():
    # two 1500m pods fit (one per 2000m zone); a third 1500m pod cannot
    # (500m + 500m left but best-effort still needs the combined free)
    n = policy_node("n0", "BestEffort")
    pods = [plain_pod(f"p{i}", 1500.0, 512.0, priority=9500 - i)
            for i in range(3)]
    res = build([n], pods)
    a = np.asarray(res.assignment)
    assert (a[:2] == 0).all() and a[2] == -1
    free = np.asarray(res.snapshot.nodes.numa_free)[0]
    np.testing.assert_allclose(free[:, 0].sum(), 1000.0)


def test_gpu_pod_on_restricted_node_aligns_instances():
    # GPU in zone 1 only; cpu fits either zone; restricted policy must
    # land the pod's cpu/mem take in zone 1 with the GPU
    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=2)
    from koordinator_tpu.api.types import Device, DeviceInfo
    n = policy_node("n0", "Restricted", zone_cpu=4000.0)
    b.add_node(n)
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW - 2,
                                 node_usage={RK.CPU: 0.0}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=0, type="gpu",
                   resources={RK.GPU_CORE: 100.0, RK.GPU_MEMORY: 1000.0},
                   numa_node=1),
        DeviceInfo(minor=1, type="gpu",
                   resources={RK.GPU_CORE: 100.0, RK.GPU_MEMORY: 1000.0},
                   numa_node=1)]))
    snap, ctx = b.build(now=NOW)
    pod = Pod(meta=ObjectMeta(name="g"), priority=9000,
              requests={RK.CPU: 1000.0, RK.MEMORY: 512.0,
                        RK.GPU_CORE: 50.0, RK.GPU_MEMORY: 500.0})
    batch = b.build_pod_batch([pod], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=3)
    assert int(res.assignment[0]) == 0
    take = np.asarray(res.numa_take[0])
    assert take[1, 0] == 1000.0 and take[0, 0] == 0.0
    assert np.asarray(res.gpu_take[0]).any()


# --- host cpuset accumulator -------------------------------------------------

TOPO = CPUTopology.uniform(num_sockets=2, nodes_per_socket=1,
                           cores_per_node=4, threads_per_core=2)
ALL = {c.cpu for c in TOPO.cpus}


def test_full_pcpus_whole_cores():
    got = take_cpus(TOPO, ALL, {}, 4, bind_policy="FullPCPUs")
    cores = {TOPO.by_cpu[c].core for c in got}
    assert len(got) == 4 and len(cores) == 2  # two whole cores
    # sibling pairs complete
    for core in cores:
        assert all(m.cpu in got for m in TOPO.cores[core])


def test_spread_by_pcpus_distinct_cores():
    got = take_cpus(TOPO, ALL, {}, 4, bind_policy="SpreadByPCPUs")
    cores = [TOPO.by_cpu[c].core for c in got]
    assert len(set(cores)) == 4  # one per physical core


def test_most_allocated_packs_numa_node():
    # node 0 partially used -> "most" strategy fills node 0 first
    allocated = {0: 1, 1: 1}
    avail = ALL - {0, 1}
    got = take_cpus(TOPO, avail, allocated, 4, bind_policy="FullPCPUs",
                    numa_strategy="most")
    assert all(TOPO.by_cpu[c].node == 0 for c in got)
    got_least = take_cpus(TOPO, avail, allocated, 4,
                          bind_policy="FullPCPUs", numa_strategy="least")
    assert all(TOPO.by_cpu[c].node == 1 for c in got_least)


def test_max_ref_count_sharing_and_exhaustion():
    allocated = {c: 1 for c in ALL}
    with pytest.raises(CPUAllocationError):
        take_cpus(TOPO, ALL, allocated, 2, max_ref_count=1)
    got = take_cpus(TOPO, ALL, allocated, 2, max_ref_count=2)
    assert len(got) == 2


def test_pcpu_exclusive_avoids_marked_cores():
    got = take_cpus(TOPO, ALL, {}, 2, bind_policy="SpreadByPCPUs",
                    exclusive_policy="PCPULevel", exclusive_cores={0, 1})
    assert all(TOPO.by_cpu[c].core not in {0, 1} for c in got)


def test_preferred_reservation_cpus_first():
    got = take_preferred_cpus(TOPO, ALL, preferred={4, 5}, allocated={},
                              num_needed=4, bind_policy="FullPCPUs")
    assert {4, 5}.issubset(got) and len(got) == 4


# --- amplified CPU (filterAmplifiedCPUs, plugin.go:336-373) -----------------


def amplified_node(name, zone_cpu=8000.0, zones=2, ratio=2.0):
    """A node the webhook amplified: allocatable = raw x ratio, with the
    ratio annotation alongside (resource_amplification.go)."""
    import json

    from koordinator_tpu.api.extension import (
        ANNOTATION_NODE_AMPLIFICATION_RATIOS,
    )

    n = numa_node(name, zone_cpu=zone_cpu, zones=zones)
    n.allocatable[RK.CPU] = zone_cpu * zones * ratio
    n.meta.annotations[ANNOTATION_NODE_AMPLIFICATION_RATIOS] = json.dumps(
        {"cpu": ratio})
    return n


def test_amplified_cpu_bind_pod_costs_ratio():
    """On a ratio-2 node with 32000m amplified allocatable (16000m raw),
    a CPU-bind pod asking 10000m costs 20000m; two of them cannot share
    the node even though raw requests (20000m) fit the amplified 32000m."""
    n = amplified_node("amp", zone_cpu=8000.0, zones=2, ratio=2.0)
    # zones hold 8000m raw each -> a 10000m bind pod can never fit one
    # zone; use 6000m pods instead (zone-fit ok, node amplified-fit tight)
    pods = [bind_pod(f"p{i}", 6000.0, 1024.0) for i in range(3)]
    res = build([n], pods, enable_amplification=True)
    a = np.asarray(res.assignment)
    # each costs 12000m amplified: 2 fit in 32000m, the third (24000+12000
    # > 32000) is rejected; unamplified all three (18000m raw) would fit
    assert (a >= 0).sum() == 2, a
    req = np.asarray(res.snapshot.nodes.requested)
    assert req[0, int(RK.CPU)] == pytest.approx(24000.0)


def test_amplified_shared_pod_unaffected():
    """Non-bind pods are checked raw against the amplified allocatable
    (only state.requestCPUBind amplifies, plugin.go:352-354)."""
    n = amplified_node("amp", zone_cpu=8000.0, zones=2, ratio=2.0)
    shared = [Pod(meta=ObjectMeta(name=f"s{i}"), priority=9000,
                  requests={RK.CPU: 10000.0, RK.MEMORY: 512.0})
              for i in range(3)]
    res = build([n], shared, enable_amplification=True)
    assert (np.asarray(res.assignment) >= 0).sum() == 3  # 30000 <= 32000


def test_amplified_running_pod_and_forget_roundtrip():
    """A running CPU-bind pod charges amplified at build; forget returns
    exactly the amplified charge of an in-cycle bind pod."""
    from koordinator_tpu.snapshot.delta import forget_pods

    n = amplified_node("amp", zone_cpu=8000.0, zones=2, ratio=2.0)
    b = SnapshotBuilder(max_nodes=1)
    b.add_node(n)
    b.set_node_metric(NodeMetric(node_name="amp", update_time=NOW - 2,
                                 node_usage={RK.CPU: 0.0}))
    running = Pod(meta=ObjectMeta(name="r"), requests={RK.CPU: 4000.0},
                  qos_label="LSR", required_cpu_bind=True, phase="Running",
                  node_name="amp", allocated_numa_zone=0)
    b.add_running_pod(running)
    snap, ctx = b.build(now=NOW)
    req0 = np.asarray(snap.nodes.requested)[0, int(RK.CPU)]
    assert req0 == pytest.approx(8000.0)          # 4000 x 2
    pods = [bind_pod("p", 6000.0, 1024.0)]
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=2,
                              enable_amplification=True)
    assert int(np.asarray(res.assignment)[0]) == 0
    after = np.asarray(res.snapshot.nodes.requested)[0, int(RK.CPU)]
    assert after == pytest.approx(8000.0 + 12000.0)
    # no explicit flag: the reversal must follow result.amplified
    back = forget_pods(res.snapshot, batch, res,
                       np.ones((batch.valid.shape[0],), bool))
    reverted = np.asarray(back.nodes.requested)[0, int(RK.CPU)]
    assert reverted == pytest.approx(8000.0)


def test_amplification_respects_fit_dims():
    """Regression: fit_dims excluding CPU must keep CPU unchecked even
    with the amplified gates compiled in."""
    n = amplified_node("amp", zone_cpu=8000.0, zones=2, ratio=2.0)
    over = [Pod(meta=ObjectMeta(name="big"), priority=9000,
                requests={RK.CPU: 50_000.0, RK.MEMORY: 512.0})]
    res = build([n], over, enable_amplification=True,
                fit_dims=(int(RK.MEMORY),))
    assert int(np.asarray(res.assignment)[0]) == 0  # CPU ignored
