"""Scheduler sidecar RPC (SURVEY §7 step 10, the BASELINE north-star
edge): publish/ingest/schedule over the framed unix socket must match
in-process scheduling exactly."""

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.scheduler.sidecar import (
    SchedulerSidecarClient,
    SchedulerSidecarServer,
)
from koordinator_tpu.snapshot import SnapshotBuilder

NOW = 1e9


@pytest.fixture
def cluster():
    b = SnapshotBuilder(max_nodes=4)
    for i in range(4):
        b.add_node(api.Node(meta=api.ObjectMeta(name=f"n{i}"),
                            allocatable={RK.CPU: 16000.0,
                                         RK.MEMORY: 32768.0}))
        b.set_node_metric(api.NodeMetric(node_name=f"n{i}", update_time=NOW,
                                         node_usage={RK.CPU: 1000.0}))
    snap, ctx = b.build(now=NOW)
    return b, snap, ctx


def mk_pods(b, ctx, n=8):
    pods = [api.Pod(meta=api.ObjectMeta(name=f"p{j}"), priority=9000,
                    requests={RK.CPU: 1000.0, RK.MEMORY: 512.0})
            for j in range(n)]
    return b.build_pod_batch(pods, ctx)


def test_schedule_over_socket_matches_local(tmp_path, cluster):
    b, snap, ctx = cluster
    batch = mk_pods(b, ctx)

    # local reference run
    local = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make())
    local_assign = np.asarray(local.assignment)

    service = SchedulerService()
    server = SchedulerSidecarServer(service, str(tmp_path / "sidecar.sock"))
    try:
        client = SchedulerSidecarClient(server.sock_path)
        v = client.publish(snap)
        assert v == 1
        out = client.schedule(batch, pod_names=[f"p{j}" for j in range(8)])
        np.testing.assert_array_equal(out["assignment"], local_assign)
        assert out["snapshot_version"] == 2  # post-commit publish
        assert out["elapsed_seconds"] > 0
        assert not out["gang_failed"].any()

        # a second batch schedules against the POST-COMMIT snapshot:
        # capacity consumed by batch 1 is visible
        out2 = client.schedule(mk_pods(b, ctx))
        assert (out2["assignment"] >= 0).all()
        req = np.asarray(service.store.current().nodes.requested)
        assert req[:, 0].sum() == pytest.approx(16000.0)  # 16 x 1000m

        summary = client.summary()
        assert summary["batches"] == 2 and summary["podsPlaced"] == 16
    finally:
        server.close()


def test_delta_ingest_over_socket(tmp_path, cluster):
    b, snap, ctx = cluster
    service = SchedulerService()
    server = SchedulerSidecarServer(service, str(tmp_path / "s.sock"))
    try:
        client = SchedulerSidecarClient(server.sock_path)
        client.publish(snap)
        # node 0 re-reports heavy usage; ingest the O(K) delta
        b.set_node_metric(api.NodeMetric(node_name="n0", update_time=NOW,
                                         node_usage={RK.CPU: 15000.0}))
        v = client.ingest(b.metric_delta(["n0"], now=NOW, pad_to=4))
        assert v == 2
        usage = np.asarray(service.store.current().nodes.usage)
        assert usage[0, 0] == pytest.approx(15000.0)

        # node churn rides the wire too: an upgraded node arrives as an
        # O(K) topology delta through the CLIENT method
        b.add_node(api.Node(meta=api.ObjectMeta(name="n1"),
                            allocatable={RK.CPU: 48000.0,
                                         RK.MEMORY: 131072.0}))
        v = client.ingest_topology(
            b.topology_delta(["n1"], now=NOW, pad_to=4))
        assert v == 3
        alloc = np.asarray(service.store.current().nodes.allocatable)
        assert alloc[1, 0] == pytest.approx(48000.0)
    finally:
        server.close()


def test_wire_preserves_dtypes_and_shapes(tmp_path, cluster):
    """flax msgpack round-trip: every column of the published snapshot
    must arrive with identical dtype, shape, and content."""
    import jax

    b, snap, ctx = cluster
    service = SchedulerService()
    server = SchedulerSidecarServer(service, str(tmp_path / "w.sock"))
    try:
        SchedulerSidecarClient(server.sock_path).publish(snap)
        got = service.store.current()
        sent_leaves = jax.tree_util.tree_leaves(snap)
        got_leaves = jax.tree_util.tree_leaves(got)
        assert len(sent_leaves) == len(got_leaves)
        for s, g in zip(sent_leaves, got_leaves):
            s, g = np.asarray(s), np.asarray(g)
            assert s.dtype == g.dtype and s.shape == g.shape
            np.testing.assert_array_equal(s, g)
    finally:
        server.close()


def test_gate_flags_survive_the_wire(tmp_path):
    """Regression: the STATIC gate switches (aux data, not msgpack
    leaves) ride the proto — a taint-gated batch scheduled over the
    socket must still reject untolerated nodes."""
    from koordinator_tpu.api.types import Taint

    service = SchedulerService()
    sock = str(tmp_path / "s.sock")
    server = SchedulerSidecarServer(service, sock)
    try:
        b = SnapshotBuilder(max_nodes=1)
        b.add_node(api.Node(meta=api.ObjectMeta(name="n0"),
                            allocatable={RK.CPU: 8000.0,
                                         RK.MEMORY: 16384.0},
                            taints=[Taint(key="x", effect="NoSchedule")]))
        b.set_node_metric(api.NodeMetric(node_name="n0", update_time=1e9,
                                         node_usage={}))
        snap, ctx = b.build(now=1e9)
        client = SchedulerSidecarClient(sock, timeout=120.0)
        client.publish(snap)
        batch = b.build_pod_batch(
            [api.Pod(meta=api.ObjectMeta(name="p"), priority=9000,
                     requests={RK.CPU: 100.0})], ctx)
        assert batch.has_taints
        out = client.schedule(batch)
        assert int(out["assignment"][0]) == -1  # gate held over the wire
    finally:
        server.close()


def test_concurrent_topology_and_schedule_over_socket(tmp_path, cluster):
    """The RPC server is threaded: topology ingests racing Schedule
    calls must serialize under the commit lock — versions stay
    monotonic, no commit is lost, and the final snapshot reflects every
    ingest."""
    import threading

    b, snap, ctx = cluster
    service = SchedulerService(num_rounds=1, k_choices=2)
    server = SchedulerSidecarServer(service, str(tmp_path / "c.sock"))
    try:
        client = SchedulerSidecarClient(server.sock_path, timeout=120.0)
        client.publish(snap)
        errors = []
        versions = []

        def churner():
            try:
                for i in range(8):
                    b.add_node(api.Node(
                        meta=api.ObjectMeta(name="n3"),
                        allocatable={RK.CPU: 16000.0 + i * 100,
                                     RK.MEMORY: 32768.0}))
                    versions.append(client.ingest_topology(
                        b.topology_delta(["n3"], now=NOW, pad_to=4)))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def scheduler_loop():
            try:
                for i in range(8):
                    versions.append(int(client.schedule(
                        mk_pods(b, ctx, n=2))["snapshot_version"]))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=churner),
                   threading.Thread(target=scheduler_loop)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert not errors, errors
        # 1 publish + 8 ingests + 8 schedules, every commit distinct
        assert sorted(versions) == list(range(2, 18))
        alloc = np.asarray(service.store.current().nodes.allocatable)
        assert alloc[3, 0] == 16700.0  # the LAST ingest won row 3
    finally:
        server.close()
