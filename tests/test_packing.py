"""bf16 columnar packing (ISSUE 17): the PACKABLE table is proven
against the registry (pad fills bf16-exact, f32-only, score/metric
surfaces only), the round-trip touches exactly the packed columns, and
the equivalence pins — placements on integer surfaces BIT-IDENTICAL to
the f32 oracle at pinned seeds, float outputs inside the documented
PACK_RTOL/PACK_ATOL envelope.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.snapshot import packing, schema
from koordinator_tpu.utils import synthetic

N, P = 16, 32


def inputs(seed=0):
    snap = synthetic.synthetic_cluster(N, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.synthetic_pods(P, seed=seed + 3, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


def make_service():
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, guards=False)
    svc._sleep = lambda _s: None
    return svc


# --- the packing contract --------------------------------------------------

def test_packable_table_validates_against_live_registry():
    packing.validate_packable()  # raises on any violation


def test_every_packable_column_is_a_score_surface():
    """Membership pin: the exact fit/commit surfaces must never appear
    in PACKABLE — halving their mantissa moves feasibility
    boundaries."""
    exact = {("NodeState", f) for f in
             ("allocatable", "requested", "numa_cap", "numa_free")} | \
            {("PodBatch", "requests")}
    packed = {(s, f) for s, fields in packing.PACKABLE.items()
              for f in fields}
    assert not (packed & exact), packed & exact


def test_unknown_column_fails_validation(monkeypatch):
    monkeypatch.setattr(packing, "_validated", False)
    monkeypatch.setitem(packing.PACKABLE, "NodeState",
                        packing.PACKABLE["NodeState"] + ("no_such",))
    with pytest.raises(ValueError, match="no_such"):
        packing.validate_packable()


def test_non_f32_column_fails_validation(monkeypatch):
    monkeypatch.setattr(packing, "_validated", False)
    monkeypatch.setitem(packing.PACKABLE, "NodeState",
                        ("label_group",))  # i32: ids must never pack
    with pytest.raises(ValueError, match="not f32"):
        packing.validate_packable()


def test_declared_pad_fills_are_bf16_exact():
    """Every concrete pad fill the registry can promise (0/1/-1/inf)
    must survive the bf16 round-trip bit-exactly, or masked reductions
    meeting pad rows break under packing."""
    for pred, fill in schema.PAD_FILL_VALUES.items():
        if fill is None:
            continue
        rt = np.asarray(fill, np.float32).astype(jnp.bfloat16) \
            .astype(np.float32)
        if np.isinf(np.float32(fill)):
            assert np.isinf(rt) and rt > 0, pred
        else:
            assert rt == np.float32(fill), pred


# --- round-trip mechanics --------------------------------------------------

def test_pack_touches_exactly_the_packable_columns():
    snap, pods = inputs(0)
    packed = packing.pack_snapshot(snap)
    for field in packing.PACKABLE["NodeState"]:
        col = getattr(packed.nodes, field)
        if col is not None:
            assert col.dtype == jnp.bfloat16, field
    # exact surfaces ride through UNTOUCHED (same arrays, not copies)
    assert packed.nodes.allocatable is snap.nodes.allocatable
    assert packed.nodes.requested is snap.nodes.requested
    assert packed.nodes.label_group is snap.nodes.label_group
    assert packed.quotas is snap.quotas

    ppods = packing.pack_pods(pods)
    assert ppods.estimated.dtype == jnp.bfloat16
    assert ppods.requests is pods.requests

    back = packing.unpack_snapshot(packed)
    for field in packing.PACKABLE["NodeState"]:
        col = getattr(back.nodes, field)
        if col is not None:
            assert col.dtype == jnp.float32, field
            np.testing.assert_allclose(
                np.asarray(col),
                np.asarray(getattr(snap.nodes, field)),
                rtol=packing.PACK_RTOL, atol=packing.PACK_ATOL)


def test_roundtrip_tree_finds_structs_inside_pytrees():
    snap, pods = inputs(1)
    tree = {"snap": snap, "pods": pods, "other": jnp.ones(3)}
    rt = packing.roundtrip_tree(tree)
    want = np.asarray(packing.roundtrip_pods(pods).estimated)
    np.testing.assert_array_equal(np.asarray(rt["pods"].estimated), want)
    np.testing.assert_array_equal(np.asarray(rt["other"]), np.ones(3))
    np.testing.assert_array_equal(
        np.asarray(rt["snap"].nodes.usage),
        np.asarray(packing.roundtrip_snapshot(snap).nodes.usage))


def test_packed_savings_counts_half_the_packable_bytes():
    snap, pods = inputs(2)
    stats = packing.packed_savings(snap, pods)
    want = sum(getattr(snap.nodes, f).nbytes // 2
               for f in packing.PACKABLE["NodeState"]
               if getattr(snap.nodes, f) is not None) + \
        pods.estimated.nbytes // 2
    assert stats["bytes_saved"] == want > 0
    assert stats["bytes_total"] > stats["bytes_saved"]


# --- equivalence pins ------------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_placements_bit_identical_to_f32_oracle(seed):
    """The acceptance pin: scheduling a bf16-round-tripped snapshot
    and batch places every pod on exactly the node the f32 oracle
    picks — integer surfaces carry no tolerance — and the committed
    float state stays inside the documented envelope."""
    snap, pods = inputs(seed)
    oracle = make_service()
    oracle.publish(snap)
    want = oracle.schedule(pods)

    svc = make_service()
    svc.publish(packing.roundtrip_snapshot(snap))
    got = svc.schedule(packing.roundtrip_pods(pods))

    np.testing.assert_array_equal(np.asarray(got.assignment),
                                  np.asarray(want.assignment))
    np.testing.assert_allclose(
        np.asarray(svc.store.current().nodes.requested),
        np.asarray(oracle.store.current().nodes.requested),
        rtol=packing.PACK_RTOL, atol=packing.PACK_ATOL)
