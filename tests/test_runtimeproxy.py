"""Runtime-proxy tests: framed RPC wire protocol, CRI interposition with
hook merging over a REAL unix socket, failure policies, and the metadata
checkpoint (SURVEY.md 2.5; reference runtimeproxy/server + proxyserver)."""

import json
import os

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_RESOURCE_STATUS,
    LABEL_POD_QOS,
    ResourceKind as RK,
)
from koordinator_tpu.koordlet.proxyserver import ProxyHookService
from koordinator_tpu.koordlet.runtimehooks import default_hook_server
from koordinator_tpu.koordlet.statesinformer import StatesInformer
from koordinator_tpu.runtimeproxy import (
    FailurePolicy,
    MetaStore,
    RpcClient,
    RpcError,
    RuntimeProxy,
)
from koordinator_tpu.runtimeproxy import api_pb2 as pb
from koordinator_tpu.runtimeproxy.rpc import RpcServer
from koordinator_tpu.runtimeproxy.server import (
    ContainerRequest,
    PodSandboxRequest,
)


class FakeRuntime:
    """Records forwarded CRI calls (containerd stand-in)."""

    def __init__(self):
        self.calls = []

    def __getattr__(self, name):
        def record(req):
            self.calls.append((name, req))
        return record


@pytest.fixture
def hook_endpoint(tmp_path):
    informer = StatesInformer()
    service = ProxyHookService(default_hook_server(informer))
    sock = str(tmp_path / "hooks.sock")
    server = service.serve(sock)
    yield sock
    server.close()


def be_sandbox():
    return PodSandboxRequest(
        sandbox_id="sb1", name="spark-1", namespace="default", uid="u1",
        labels={LABEL_POD_QOS: "BE"},
        cgroup_parent="kubepods/besteffort/podu1")


def test_rpc_roundtrip_and_errors(tmp_path):
    sock = str(tmp_path / "t.sock")

    def echo(req):
        resp = pb.PodSandboxHookResponse()
        resp.labels.update(req.labels)
        return resp

    def boom(req):
        raise RuntimeError("hook exploded")

    server = RpcServer(sock, {
        "Echo": (pb.PodSandboxHookRequest, echo),
        "Boom": (pb.PodSandboxHookRequest, boom)})
    try:
        client = RpcClient(sock)
        req = pb.PodSandboxHookRequest()
        req.labels["k"] = "v"
        resp = client.call("Echo", req, pb.PodSandboxHookResponse)
        assert dict(resp.labels) == {"k": "v"}
        with pytest.raises(RpcError, match="hook exploded"):
            client.call("Boom", req, pb.PodSandboxHookResponse)
        with pytest.raises(RpcError, match="unknown method"):
            client.call("Nope", req, pb.PodSandboxHookResponse)
    finally:
        server.close()


def test_proxy_interposes_be_pod_lifecycle(hook_endpoint):
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    proxy.run_pod_sandbox(be_sandbox())
    # container of a BE pod with batch resources + cpuset + gpu allocation
    pod_annotations = {
        ANNOTATION_RESOURCE_STATUS: json.dumps(
            {"cpuset": "4-7", "numaNodes": [1]}),
        "scheduling.koordinator.sh/device-allocated": json.dumps(
            {"gpu": [{"minor": 2}, {"minor": 3}]}),
    }
    proxy.store.pods["sb1"].annotations.update(pod_annotations)
    creq = ContainerRequest(container_id="c1", sandbox_id="sb1",
                            name="main", cpu_shares=1024)
    proxy.create_container(creq)
    assert [name for name, _ in runtime.calls] == ["run_pod_sandbox",
                                                   "create_container"]
    fwd = runtime.calls[1][1]
    # cpuset hook output merged into the forwarded CRI request
    assert fwd.cpuset_cpus == "4-7"
    assert fwd.unified["cpuset.mems"] == "1"
    # gpu hook env injection
    assert fwd.envs["NVIDIA_VISIBLE_DEVICES"] == "2,3"


def test_sandbox_creation_carries_pod_stage_cgroup_updates(hook_endpoint):
    # BE group identity computed at PreRunPodSandbox must ride the CREATED
    # sandbox, not wait for a later update call
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    req = be_sandbox()
    proxy.run_pod_sandbox(req)
    fwd = runtime.calls[0][1]
    assert fwd.unified["cpu.bvt_warp_ns"] == "-1"


def test_rpc_server_restart_on_stale_socket(tmp_path):
    sock = str(tmp_path / "s.sock")
    handlers = {"Echo": (pb.PodSandboxHookRequest,
                         lambda req: pb.PodSandboxHookResponse())}
    first = RpcServer(sock, handlers)
    # simulate a crash: the socket file stays behind
    first._server.shutdown()
    first._server.server_close()
    assert os.path.exists(sock)
    second = RpcServer(sock, handlers)
    RpcClient(sock).call("Echo", pb.PodSandboxHookRequest(),
                         pb.PodSandboxHookResponse)
    second.close()
    assert not os.path.exists(sock)


def test_failed_create_leaves_no_phantom_container(tmp_path):
    runtime = FakeRuntime()
    dead = str(tmp_path / "dead.sock")
    proxy = RuntimeProxy(runtime, RpcClient(dead), FailurePolicy.FAIL)
    with pytest.raises(OSError):
        proxy.create_container(ContainerRequest(container_id="c1",
                                                sandbox_id="sb1",
                                                name="main"))
    assert "c1" not in proxy.store.containers


def test_proxy_update_applies_batch_resources(hook_endpoint):
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    sb = be_sandbox()
    proxy.run_pod_sandbox(sb)
    # hooks derive batch limits from the pod labels only in proxy mode;
    # the batchresource hook needs requests — carried via annotations is
    # not modeled, so drive the typed path: BE pod label -> bvt in unified
    ureq = ContainerRequest(container_id="c1", sandbox_id="sb1", name="main")
    proxy.update_container_resources(ureq)
    fwd = runtime.calls[-1][1]
    assert fwd.unified["cpu.bvt_warp_ns"] == "-1"  # BE group identity


def test_failure_policy_fail_rejects_and_ignore_forwards(tmp_path):
    runtime = FakeRuntime()
    dead_sock = str(tmp_path / "nobody.sock")
    strict = RuntimeProxy(runtime, RpcClient(dead_sock), FailurePolicy.FAIL)
    with pytest.raises(OSError):
        strict.run_pod_sandbox(be_sandbox())
    assert runtime.calls == []
    lenient = RuntimeProxy(runtime, RpcClient(dead_sock),
                           FailurePolicy.IGNORE)
    lenient.run_pod_sandbox(be_sandbox())
    assert [name for name, _ in runtime.calls] == ["run_pod_sandbox"]


def test_proxy_without_hook_client_passthrough():
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime)
    proxy.run_pod_sandbox(be_sandbox())
    proxy.stop_pod_sandbox(be_sandbox())
    assert len(runtime.calls) == 2
    assert "sb1" not in proxy.store.pods


def test_post_stop_hooks_never_fail_completed_ops(tmp_path, hook_endpoint):
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    proxy.run_pod_sandbox(be_sandbox())
    # hook server dies between start and stop: the stop must still
    # succeed (backend already stopped it) and the store must clean up
    proxy.hooks = RpcClient(str(tmp_path / "gone.sock"))
    proxy.stop_pod_sandbox(PodSandboxRequest(sandbox_id="sb1"))
    assert "sb1" not in proxy.store.pods
    assert [n for n, _ in runtime.calls] == ["run_pod_sandbox",
                                             "stop_pod_sandbox"]


def test_stop_sandbox_restores_metadata_from_store(hook_endpoint):
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    proxy.run_pod_sandbox(be_sandbox())
    # CRI StopPodSandbox carries only the id; the forwarded request is
    # enriched from the checkpoint so teardown hooks see the QoS label
    proxy.stop_pod_sandbox(PodSandboxRequest(sandbox_id="sb1"))
    fwd = runtime.calls[-1][1]
    assert fwd.labels[LABEL_POD_QOS] == "BE"
    assert fwd.uid == "u1"


def test_failed_sandbox_creation_leaves_no_phantom_pod(hook_endpoint):
    class ExplodingRuntime(FakeRuntime):
        def run_pod_sandbox(self, req):
            raise RuntimeError("runtime rejected sandbox")

    proxy = RuntimeProxy(ExplodingRuntime(), RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    with pytest.raises(RuntimeError):
        proxy.run_pod_sandbox(be_sandbox())
    assert "sb1" not in proxy.store.pods


def test_store_checkpoint_roundtrip(tmp_path):
    path = str(tmp_path / "meta.json")
    store = MetaStore(path)
    from koordinator_tpu.runtimeproxy.store import ContainerInfo, PodSandboxInfo
    store.put_pod("sb1", PodSandboxInfo(name="p", uid="u",
                                        labels={"a": "b"}))
    store.put_container("c1", ContainerInfo(name="main",
                                            pod_sandbox_id="sb1"))
    restored = MetaStore(path)
    restored.load()
    assert restored.pods["sb1"].labels == {"a": "b"}
    assert restored.pod_of_container("c1").name == "p"
    restored.delete_pod("sb1")
    assert restored.pod_of_container("c1") is None


def test_schedule_to_runtime_annotation_loop(hook_endpoint):
    """Scheduler result -> bind annotations -> proxy hook -> forwarded CRI
    request: the full loop from the TPU kernel's instance masks to the
    cgroup/env adjustments containerd would receive."""
    import numpy as np

    from koordinator_tpu.api.types import (
        Device, DeviceInfo, Node, NodeMetric, NodeResourceTopology,
        NUMAZone, ObjectMeta, Pod,
    )
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.bind import (
        device_allocation_annotation,
        resource_status_annotation,
    )
    from koordinator_tpu.scheduler.plugins.cpu_accumulator import CPUTopology
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.snapshot import SnapshotBuilder

    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=4)
    b.add_node(Node(
        meta=ObjectMeta(name="n0"),
        allocatable={RK.CPU: 16000.0, RK.MEMORY: 65536.0},
        topology=NodeResourceTopology(node_name="n0", zones=[
            NUMAZone(cpus_milli=8000.0, memory_mib=32768.0),
            NUMAZone(cpus_milli=8000.0, memory_mib=32768.0)])))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=1e9,
                                 node_usage={RK.CPU: 500.0,
                                             RK.MEMORY: 1000.0}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=m, type="gpu",
                   resources={RK.GPU_CORE: 100.0, RK.GPU_MEMORY: 1000.0},
                   numa_node=m // 2, pcie_id=f"p{m//2}")
        for m in range(4)]))
    snap, ctx = b.build(now=1e9)
    pod = Pod(meta=ObjectMeta(name="train", labels={LABEL_POD_QOS: "LSR"}),
              requests={RK.CPU: 2000.0, RK.MEMORY: 4096.0,
                        RK.GPU_CORE: 200.0},
              priority=9100, gpu_memory_ratio=200.0, qos_label="LSR",
              required_cpu_bind=True)
    res = core.schedule_batch(snap, b.build_pod_batch([pod], ctx),
                              LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) == 0

    topo = CPUTopology.uniform(num_sockets=1, nodes_per_socket=2,
                               cores_per_node=4, threads_per_core=2)
    annotations = {}
    annotations.update(resource_status_annotation(res, 0, topo,
                                                  cpus_needed=2))
    annotations.update(device_allocation_annotation(snap,
                                                    b.build_pod_batch(
                                                        [pod], ctx),
                                                    res, 0))
    assert ANNOTATION_RESOURCE_STATUS in annotations
    status = json.loads(annotations[ANNOTATION_RESOURCE_STATUS])
    zone = status["numaNodes"][0]
    minors = [d["minor"] for d in json.loads(
        annotations["scheduling.koordinator.sh/device-allocated"])["gpu"]]
    assert all(m // 2 == zone for m in minors)  # GPUs on the cpuset zone

    # the annotations drive the runtime hooks through the proxy
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    sreq = PodSandboxRequest(sandbox_id="sb", name="train", uid="u",
                             labels={LABEL_POD_QOS: "LSR"},
                             annotations=annotations,
                             cgroup_parent="kubepods/podu")
    proxy.run_pod_sandbox(sreq)
    proxy.create_container(ContainerRequest(container_id="c",
                                            sandbox_id="sb", name="main"))
    fwd = runtime.calls[-1][1]
    assert fwd.cpuset_cpus == status["cpuset"]
    assert fwd.envs["NVIDIA_VISIBLE_DEVICES"] == ",".join(
        str(m) for m in minors)


def test_stop_container_cleans_store(hook_endpoint):
    runtime = FakeRuntime()
    proxy = RuntimeProxy(runtime, RpcClient(hook_endpoint),
                         FailurePolicy.FAIL)
    proxy.run_pod_sandbox(be_sandbox())
    proxy.create_container(ContainerRequest(container_id="c1",
                                            sandbox_id="sb1", name="main"))
    assert "c1" in proxy.store.containers
    proxy.stop_container(ContainerRequest(container_id="c1",
                                          sandbox_id="sb1", name="main"))
    assert "c1" not in proxy.store.containers


# --- docker engine variant (runtimeproxy/server/docker) ---------------------


class FakeDockerd:
    """Records forwarded Docker Engine calls."""

    def __init__(self):
        self.calls = []
        self._next = 0

    def create(self, body):
        self._next += 1
        cid = f"d{self._next}"
        self.calls.append(("create", cid, body))
        return cid

    def start(self, cid):
        self.calls.append(("start", cid, None))

    def update(self, cid, body):
        self.calls.append(("update", cid, body))

    def stop(self, cid):
        self.calls.append(("stop", cid, None))


def test_docker_proxy_interposes_lifecycle(hook_endpoint):
    """A BE pod created through the docker API shape gets the same QoS
    adjustments the CRI path applies (docker/handler.go), with routing by
    the reference's path regexes (docker/server.go:63-66)."""
    from koordinator_tpu.runtimeproxy.docker import DockerProxy

    dockerd = FakeDockerd()
    proxy = DockerProxy(dockerd, RpcClient(hook_endpoint),
                        FailurePolicy.FAIL)
    sandbox_body = {
        "Labels": {
            "io.kubernetes.docker.type": "podsandbox",
            "io.kubernetes.pod.name": "spark-1",
            "io.kubernetes.pod.namespace": "default",
            "io.kubernetes.pod.uid": "u1",
            LABEL_POD_QOS: "BE",
        },
        "HostConfig": {"CgroupParent": "kubepods/besteffort/podu1"},
    }
    resp = proxy.handle("/v1.41/containers/create", sandbox_body)
    assert resp.ok
    sb_id = resp.container_id
    # BE group identity rides the created sandbox HostConfig
    assert sandbox_body["HostConfig"]["Unified"]["cpu.bvt_warp_ns"] == "-1"
    # container pointing at the sandbox; cpuset annotation applies
    container_body = {
        "Labels": {
            "io.kubernetes.docker.type": "container",
            "io.kubernetes.container.name": "main",
            "io.kubernetes.sandbox.id": sb_id,
        },
        "HostConfig": {"CpuShares": 1024},
    }
    proxy.store.pods[sb_id].annotations[ANNOTATION_RESOURCE_STATUS] = \
        json.dumps({"cpuset": "4-7", "numaNodes": [1]})
    resp = proxy.handle("/v1.41/containers/create", container_body)
    assert resp.ok
    cid = resp.container_id
    assert container_body["HostConfig"]["CpusetCpus"] == "4-7"
    assert proxy.store.pod_of_container(cid).name == "spark-1"
    proxy.handle(f"/v1.41/containers/{cid}/start")
    # update bodies are bare resource sets
    upd = {"CpuShares": 512}
    assert proxy.handle(f"/v1.41/containers/{cid}/update", upd).ok
    proxy.handle(f"/v1.41/containers/{cid}/stop?t=10")
    proxy.handle(f"/v1.41/containers/{sb_id}/stop?t=10")
    assert [c[0] for c in dockerd.calls] == [
        "create", "create", "start", "update", "stop", "stop"]
    assert not proxy.store.pods and not proxy.store.containers
    # unmatched paths pass through untouched
    assert proxy.handle("/v1.41/images/json").ok


def test_docker_proxy_annotation_prefix_split():
    from koordinator_tpu.runtimeproxy.docker import (
        split_labels_and_annotations,
    )

    labels, annos = split_labels_and_annotations({
        "annotation.scheduling.koordinator.sh/resource-status": "{}",
        "io.kubernetes.pod.name": "p"})
    assert labels == {"io.kubernetes.pod.name": "p"}
    assert annos == {"scheduling.koordinator.sh/resource-status": "{}"}


def test_docker_proxy_routes_by_container_name(hook_endpoint):
    """Regression: docker references with '-'/'.' (by-name addressing)
    must hit the routes, not fall through to pass-through."""
    from koordinator_tpu.runtimeproxy.docker import DockerProxy

    dockerd = FakeDockerd()
    proxy = DockerProxy(dockerd, RpcClient(hook_endpoint))
    assert proxy.handle("/v1.41/containers/my-app.1/stop?t=5").ok
    assert dockerd.calls == [("stop", "my-app.1", None)]


def test_docker_proxy_create_with_query_and_by_name_lifecycle(hook_endpoint):
    """Regression: dockershim creates with ?name=k8s_... — the create
    route must interpose it, and the name must resolve to the docker id
    for later by-name lifecycle calls (store/_bodies stay consistent)."""
    from koordinator_tpu.runtimeproxy.docker import DockerProxy

    dockerd = FakeDockerd()
    proxy = DockerProxy(dockerd, RpcClient(hook_endpoint))
    body = {"Labels": {"io.kubernetes.docker.type": "podsandbox",
                       "io.kubernetes.pod.name": "spark-1",
                       LABEL_POD_QOS: "BE"},
            "HostConfig": {}}
    r = proxy.handle("/v1.41/containers/create?name=k8s_POD_spark-1", body)
    assert r.ok
    # interposed despite the query string
    assert body["HostConfig"]["Unified"]["cpu.bvt_warp_ns"] == "-1"
    assert r.container_id in proxy.store.pods
    # stop BY NAME: classified as a sandbox, store + bodies cleaned up
    proxy.handle("/v1.41/containers/k8s_POD_spark-1/stop?t=10")
    assert dockerd.calls[-1] == ("stop", r.container_id, None)
    assert not proxy.store.pods and not proxy._bodies and not proxy._names
