"""Constrained-prefix packing (core.schedule_batch topo_prefix).

The packing contract: every spread/anti/aff member or carrier pod sits
in batch rows [0, topo_prefix). Under that contract the prefix-sliced
in-step machinery must be BIT-IDENTICAL to the full-width gates — the
slices drop only rows that can neither charge nor be gated. The packer
(synthetic.pack_topo_prefix) establishes the contract host-side; these
tests pin both the packer and the equivalence.

Ref: the reference's hot loop runs the vanilla spread/affinity plugins
for every pod (/root/reference/pkg/scheduler/frameworkext/
framework_extender.go:204-226); the prefix is a batching-layer
optimization with no semantic counterpart there, so equivalence against
the unpacked program IS the parity statement.
"""

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P, N, CHUNK = 1_024, 200, 256


def _packed_workload(seed=1):
    pods = synthetic.full_gate_pods(P, N, seed=seed, num_quotas=8,
                                    num_gangs=8)
    return synthetic.pack_topo_prefix(pods, CHUNK)


def test_packer_establishes_the_contract():
    pods, prefix, mask = _packed_workload()
    assert prefix % 128 == 0 and 0 < prefix <= CHUNK
    cons = synthetic.topo_constrained_mask(pods)
    np.testing.assert_array_equal(cons, mask)
    for s in range(0, P, CHUNK):
        chunk_mask = mask[s:s + CHUNK]
        assert not chunk_mask[prefix:].any()
        # stable within the two classes: constrained pods keep their
        # relative order, as do unconstrained ones
        assert (np.diff(np.flatnonzero(chunk_mask)) > 0).all()


def test_packer_preserves_the_multiset_of_pods():
    pods = synthetic.full_gate_pods(P, N, seed=3, num_quotas=8,
                                    num_gangs=8)
    packed, _, _ = synthetic.pack_topo_prefix(pods, CHUNK)
    for f in ("priority", "quota_id", "gang_id", "spread_id", "anti_id",
              "aff_id"):
        a = np.sort(np.asarray(getattr(pods, f)))
        b = np.sort(np.asarray(getattr(packed, f)))
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(
        np.asarray(pods.requests).sum(0), np.asarray(packed.requests).sum(0))


def test_prefix_program_is_bit_identical_to_full_width():
    """The parity pin: same packed chunk, topo_prefix on vs off."""
    pods, prefix, _ = _packed_workload()
    snap = synthetic.full_gate_cluster(N, seed=0, num_quotas=8,
                                       num_gangs=8)
    cfg = LoadAwareConfig.make()
    batch = synthetic.slice_batch(pods, 0, CHUNK)
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True)
    full = core.schedule_batch(snap, batch, cfg, **kw)
    pref = core.schedule_batch(snap, batch, cfg, topo_prefix=prefix, **kw)
    np.testing.assert_array_equal(np.asarray(full.assignment),
                                  np.asarray(pref.assignment))
    np.testing.assert_array_equal(np.asarray(full.chosen_score),
                                  np.asarray(pref.chosen_score))
    np.testing.assert_array_equal(np.asarray(full.numa_zone),
                                  np.asarray(pref.numa_zone))
    np.testing.assert_array_equal(np.asarray(full.gpu_take),
                                  np.asarray(pref.gpu_take))
    for a, b in zip(jax.tree_util.tree_leaves(full.snapshot),
                    jax.tree_util.tree_leaves(pref.snapshot)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int((full.assignment >= 0).sum()) > 0


def test_prefix_equivalence_across_carried_chunks():
    """Chunked scheduling with carried topology counts: the packed
    prefix program and the full-width program must agree chunk by
    chunk when counts thread through charge_all_counts (the bench
    sweep contract)."""
    pods, prefix, _ = _packed_workload(seed=5)
    snap_a = synthetic.full_gate_cluster(N, seed=2, num_quotas=8,
                                         num_gangs=8)
    snap_b = snap_a
    cfg = LoadAwareConfig.make()
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True)
    counts_a = tuple(jnp.asarray(getattr(pods, f))
                     for f in core.COUNT_FIELDS)
    counts_b = counts_a
    for s in range(0, P, CHUNK):
        batch = synthetic.slice_batch(pods, s, CHUNK)
        batch_a = batch.replace(**dict(zip(core.COUNT_FIELDS, counts_a)))
        batch_b = batch.replace(**dict(zip(core.COUNT_FIELDS, counts_b)))
        res_a = core.schedule_batch(snap_a, batch_a, cfg, **kw)
        res_b = core.schedule_batch(snap_b, batch_b, cfg,
                                    topo_prefix=prefix, **kw)
        np.testing.assert_array_equal(np.asarray(res_a.assignment),
                                      np.asarray(res_b.assignment))
        counts_a = core.charge_all_counts(counts_a, batch_a,
                                          res_a.assignment)
        counts_b = core.charge_all_counts(counts_b, batch_b,
                                          res_b.assignment)
        snap_a, snap_b = res_a.snapshot, res_b.snapshot
    for a, b in zip(counts_a, counts_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dom_class_batching_is_bit_identical():
    """Groups sharing a domain row batch into one per-class matmul;
    the sums are 0/1 floats so the result must be BIT-identical to the
    per-group loop (dom_classes=None), with and without the packing
    prefix."""
    pods, prefix, _ = _packed_workload(seed=11)
    classes = synthetic.dom_classes(pods)
    # the bench workload genuinely exercises multi-group classes
    assert max(len(c) for fam in classes for c in fam) > 1
    snap = synthetic.full_gate_cluster(N, seed=6, num_quotas=8,
                                       num_gangs=8)
    cfg = LoadAwareConfig.make()
    batch = synthetic.slice_batch(pods, 0, CHUNK)
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True)
    per_group = core.schedule_batch(snap, batch, cfg, **kw)
    batched = core.schedule_batch(snap, batch, cfg, dom_classes=classes,
                                  **kw)
    both = core.schedule_batch(snap, batch, cfg, dom_classes=classes,
                               topo_prefix=prefix, **kw)
    for got in (batched, both):
        np.testing.assert_array_equal(np.asarray(per_group.assignment),
                                      np.asarray(got.assignment))
        np.testing.assert_array_equal(np.asarray(per_group.chosen_score),
                                      np.asarray(got.chosen_score))
        for a, b in zip(jax.tree_util.tree_leaves(per_group.snapshot),
                        jax.tree_util.tree_leaves(got.snapshot)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int((per_group.assignment >= 0).sum()) > 0


def test_dom_classes_must_partition_the_groups():
    pods, _, _ = _packed_workload()
    snap = synthetic.full_gate_cluster(N, seed=0, num_quotas=8,
                                       num_gangs=8)
    batch = synthetic.slice_batch(pods, 0, CHUNK)
    bad = (((0, 1),), ((0,),), ((0,),))  # drops groups; must be rejected
    import pytest
    with pytest.raises(ValueError, match="partition"):
        core.schedule_batch(snap, batch, LoadAwareConfig.make(),
                            dom_classes=bad, enable_numa=True,
                            enable_devices=True)


def test_gate_prefixes_nest_and_cover_their_classes():
    pods = synthetic.full_gate_pods(P, N, seed=13, num_quotas=8,
                                    num_gangs=8)
    packed, prefixes, masks = synthetic.pack_gate_prefixes(pods, CHUNK)
    assert prefixes["topo"] <= prefixes["numa"] <= prefixes["gpu"]
    for key in ("topo", "numa", "gpu"):
        assert prefixes[key] % 128 == 0 or prefixes[key] == CHUNK
        m = masks[key]
        for s in range(0, P, CHUNK):
            assert not m[s + prefixes[key]:s + CHUNK].any()
    np.testing.assert_array_equal(
        masks["topo"], synthetic.topo_constrained_mask(packed))
    np.testing.assert_array_equal(masks["numa"],
                                  np.asarray(packed.numa_single))
    from koordinator_tpu.scheduler.plugins import deviceshare
    np.testing.assert_array_equal(
        masks["gpu"], np.asarray(deviceshare.has_device_request(packed)))


def test_numa_gpu_prefixes_are_bit_identical_to_full_width():
    """The three packing contracts together: same packed chunk with and
    without numa/gpu prefixes (plus topo + classes) must agree bit for
    bit — including zone takes, GPU instance identity, and the
    post-commit snapshot."""
    pods = synthetic.full_gate_pods(P, N, seed=17, num_quotas=8,
                                    num_gangs=8)
    packed, prefixes, _ = synthetic.pack_gate_prefixes(pods, CHUNK)
    classes = synthetic.dom_classes(packed)
    snap = synthetic.full_gate_cluster(N, seed=8, num_quotas=8,
                                       num_gangs=8)
    assert not np.asarray(snap.nodes.numa_policy).any()  # contract
    cfg = LoadAwareConfig.make()
    batch = synthetic.slice_batch(packed, 0, CHUNK)
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True,
              topo_prefix=prefixes["topo"], dom_classes=classes)
    full = core.schedule_batch(snap, batch, cfg, **kw)
    pref = core.schedule_batch(snap, batch, cfg,
                               numa_prefix=prefixes["numa"],
                               gpu_prefix=prefixes["gpu"], **kw)
    for field in ("assignment", "chosen_score", "numa_zone", "numa_take",
                  "gpu_take", "aux_inst", "res_slot"):
        np.testing.assert_array_equal(
            np.asarray(getattr(full, field)),
            np.asarray(getattr(pref, field)), err_msg=field)
    for a, b in zip(jax.tree_util.tree_leaves(full.snapshot),
                    jax.tree_util.tree_leaves(pref.snapshot)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the workload must actually exercise the gates being sliced
    assert int((np.asarray(full.numa_zone) >= 0).sum()) > 0
    assert bool(np.asarray(full.gpu_take).any())


def test_full_gate_reservations_are_live_and_consumed():
    """The flagship workload exercises the reservation gate for real:
    live owner-restricted slots, consumed by owner pods, with the
    AllocateOnce single-winner ordering enforced among competing
    owners (plugin.go:509-510 semantics)."""
    pods = synthetic.full_gate_pods(P, N, seed=21, num_quotas=8,
                                    num_gangs=8)
    snap = synthetic.full_gate_cluster(N, seed=9, num_quotas=8,
                                       num_gangs=8)
    v = synthetic.full_gate_reservations(N)
    assert v > 0
    assert bool(np.asarray(snap.reservations.valid).all())
    # every slot has an owner; at the flagship shapes two compete per
    # slot (the pool shrinks gracefully at small P when few pods fit
    # the hold)
    owner = np.asarray(pods.reservation_owner)
    owners_per_slot = np.bincount(owner[owner >= 0], minlength=v)
    assert (owners_per_slot >= 1).all()
    assert (owners_per_slot == 2).any()
    res = core.schedule_batch(
        snap, pods, LoadAwareConfig.make(), num_rounds=2, k_choices=8,
        score_dims=(0, 1), tie_break=True, quota_depth=2,
        fit_dims=(0, 1, 2, 3), enable_numa=True, enable_devices=True)
    slot = np.asarray(res.res_slot)
    taken = slot[slot >= 0]
    once = np.asarray(snap.reservations.allocate_once)
    per_slot = np.bincount(taken, minlength=v)
    # owners fit the hold by construction, and slots outscore any node
    # (nominator preference), so the gate must be exercised broadly —
    # not just on a token slot
    assert (per_slot > 0).sum() >= v // 2, \
        f"only {(per_slot > 0).sum()}/{v} slots consumed"
    assert (per_slot[once] <= 1).all(), \
        "AllocateOnce slot admitted more than one consumer"


def test_full_width_default_untouched_by_unpacked_order():
    """topo_prefix=None on an UNPACKED batch (constrained pods anywhere)
    stays the exact reference behavior — the new argument must not
    perturb the default path."""
    pods = synthetic.full_gate_pods(P, N, seed=9, num_quotas=8,
                                    num_gangs=8)
    snap = synthetic.full_gate_cluster(N, seed=4, num_quotas=8,
                                       num_gangs=8)
    cfg = LoadAwareConfig.make()
    batch = synthetic.slice_batch(pods, 0, CHUNK)
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True)
    res = core.schedule_batch(snap, batch, cfg, **kw)
    res2 = core.schedule_batch(snap, batch, cfg, topo_prefix=CHUNK, **kw)
    # prefix == chunk width is the same program by construction
    np.testing.assert_array_equal(np.asarray(res.assignment),
                                  np.asarray(res2.assignment))
