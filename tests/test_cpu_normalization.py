"""CPU normalization (slo-controller plugin + koordlet hook) and the
per-node colocation-strategy metadata overrides
(plugins/cpunormalization/plugin.go, hooks/cpunormalization/,
sloconfig/colocation_config.go:102-155)."""

import json

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_NODE_COLOCATION_STRATEGY,
    ANNOTATION_NODE_CPU_NORMALIZATION_RATIO,
    LABEL_CPU_RECLAIM_RATIO,
    QoSClass,
    ResourceKind as RK,
)
from koordinator_tpu.koordlet.runtimehooks import (
    CPUNormalizationHook,
    HookContext,
    Stage,
)
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
from koordinator_tpu.slo_controller.config import (
    ColocationConfig,
    ColocationStrategy,
    ColocationStrategyOverride,
)
from koordinator_tpu.slo_controller.cpu_normalization import (
    CPUNormalizationPlugin,
    CPUNormalizationStrategy,
    compute_ratio,
    node_ratio,
)


# --- plugin ------------------------------------------------------------------

def test_ratio_model_lookup_and_clamp():
    s = CPUNormalizationStrategy(enable=True,
                                 ratio_model={"FastChip": 1.5,
                                              "WarpChip": 9.0,
                                              "SlowChip": 0.5},
                                 default_ratio=1.1)
    assert compute_ratio(s, "FastChip") == 1.5
    assert compute_ratio(s, "WarpChip") == 5.0   # clamped to max
    assert compute_ratio(s, "SlowChip") == 1.0   # below basic unsupported
    assert compute_ratio(s, "Unknown") == pytest.approx(1.1)


def test_plugin_annotates_and_clears():
    node = api.Node(meta=api.ObjectMeta(name="n0"))
    p = CPUNormalizationPlugin(CPUNormalizationStrategy(
        enable=True, ratio_model={"FastChip": 1.5}))
    assert p.reconcile(node, "FastChip")
    assert node.meta.annotations[
        ANNOTATION_NODE_CPU_NORMALIZATION_RATIO] == "1.50"
    assert not p.reconcile(node, "FastChip")  # idempotent
    # feature off -> annotation cleared
    p.strategy.enable = False
    assert p.reconcile(node, "FastChip")
    assert ANNOTATION_NODE_CPU_NORMALIZATION_RATIO not in \
        node.meta.annotations


def test_node_ratio_parse_guards():
    n = api.Node(meta=api.ObjectMeta(annotations={
        ANNOTATION_NODE_CPU_NORMALIZATION_RATIO: "2.00"}))
    assert node_ratio(n) == 2.0
    assert node_ratio(None) == 1.0
    n.meta.annotations[ANNOTATION_NODE_CPU_NORMALIZATION_RATIO] = "bogus"
    assert node_ratio(n) == 1.0
    n.meta.annotations[ANNOTATION_NODE_CPU_NORMALIZATION_RATIO] = "99.0"
    assert node_ratio(n) == 1.0  # outside [1, 5] distrusted


# --- hook --------------------------------------------------------------------

def mk_ctx():
    pod = PodMeta(pod=api.Pod(meta=api.ObjectMeta(uid="p1", name="p1"),
                              qos_label="BE"))
    return HookContext(pod=pod, stage=Stage.PRE_CREATE_CONTAINER)


def test_hook_scales_quota_down():
    informer = StatesInformer()
    informer.set_node(api.Node(meta=api.ObjectMeta(
        name="n0", annotations={
            ANNOTATION_NODE_CPU_NORMALIZATION_RATIO: "2.00"})))
    ctx = mk_ctx()
    ctx.add_update("cpu.cfs_quota_us", "100001")
    ctx.add_update("cpu.shares", "1024")       # untouched
    ctx.add_update("cpu.cfs_quota_us", "-1")   # unlimited untouched
    CPUNormalizationHook(informer).apply(ctx)
    values = [(u.resource, u.value) for u in ctx.cgroup_updates]
    assert values == [("cpu.cfs_quota_us", "50001"),  # ceil(100001/2)
                      ("cpu.shares", "1024"),
                      ("cpu.cfs_quota_us", "-1")]


def test_hook_noop_without_ratio():
    informer = StatesInformer()
    informer.set_node(api.Node(meta=api.ObjectMeta(name="n0")))
    ctx = mk_ctx()
    ctx.add_update("cpu.cfs_quota_us", "100000")
    CPUNormalizationHook(informer).apply(ctx)
    assert ctx.cgroup_updates[0].value == "100000"


# --- node colocation strategy overrides -------------------------------------

def test_strategy_precedence_annotation_and_labels():
    cfg = ColocationConfig(
        cluster_strategy=ColocationStrategy(
            cpu_reclaim_threshold_percent=60.0,
            memory_reclaim_threshold_percent=65.0),
        node_overrides=[ColocationStrategyOverride(
            node_selector={"pool": "batch"},
            fields={"cpu_reclaim_threshold_percent": 70.0})])

    # selector override only
    s = cfg.strategy_for({"pool": "batch"})
    assert s.cpu_reclaim_threshold_percent == 70.0

    # annotation partial wins over the selector override
    s = cfg.strategy_for(
        {"pool": "batch"},
        {ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
            {"cpuReclaimThresholdPercent": 80.0, "unknownField": 1})})
    assert s.cpu_reclaim_threshold_percent == 80.0

    # reclaim-ratio label wins over everything
    s = cfg.strategy_for(
        {"pool": "batch", LABEL_CPU_RECLAIM_RATIO: "0.9"},
        {ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
            {"cpuReclaimThresholdPercent": 80.0})})
    assert s.cpu_reclaim_threshold_percent == pytest.approx(90.0)
    assert s.memory_reclaim_threshold_percent == 65.0

    # illegal metadata ignored, never fatal: bad JSON, non-dict JSON,
    # wrong-typed values, bogus policy strings, out-of-range ratios
    for labels, anns in (
            ({LABEL_CPU_RECLAIM_RATIO: "abc"},
             {ANNOTATION_NODE_COLOCATION_STRATEGY: "{{{"}),
            ({}, {ANNOTATION_NODE_COLOCATION_STRATEGY: "[1,2]"}),
            ({}, {ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
                {"cpuReclaimThresholdPercent": "70"})}),
            ({}, {ANNOTATION_NODE_COLOCATION_STRATEGY: json.dumps(
                {"memoryCalculatePolicy": "warp-speed"})}),
            ({LABEL_CPU_RECLAIM_RATIO: "1.5"}, {})):
        s = cfg.strategy_for(labels, anns)
        assert s.cpu_reclaim_threshold_percent == 60.0
    # a VALID policy string does coerce into the enum
    from koordinator_tpu.slo_controller.config import CalculatePolicy
    s = cfg.strategy_for({}, {ANNOTATION_NODE_COLOCATION_STRATEGY:
                              json.dumps({"memoryCalculatePolicy":
                                          "request"})})
    assert s.memory_calculate_policy is CalculatePolicy.REQUEST
    # the cluster strategy object itself is never mutated
    assert cfg.cluster_strategy.cpu_reclaim_threshold_percent == 60.0
