"""Wire-format conformance for the scheduler sidecar (VERDICT r3 #5).

The BASELINE architecture has the GO control plane calling this sidecar
(framework_extender.go:167-292 is the seam being replaced), so the wire
must be implementable without Python. These tests pin it three ways
against FROZEN byte fixtures in tests/fixtures/sidecar/:

1. decode: the frozen request frames — bytes exactly as a foreign
   client would put them on the socket — parse with the documented
   framing rules (re-implemented here, independent of rpc.py) and
   decode to the expected semantic values;
2. encode: serializing the same canonical objects today reproduces the
   frozen bytes bit-for-bit — any library/layout change that would
   break a non-Python peer fails loudly;
3. serve: the frozen frames drive a LIVE SchedulerSidecarServer over a
   raw unix socket and yield well-formed responses.

The framing and payload layout are documented for implementers in
docs/SIDECAR_WIRE.md. Regenerate fixtures (after a DELIBERATE wire
change) with:  python tests/test_sidecar_wire.py --regen
"""

import json
import os
import socket
import struct

import flax.serialization
import numpy as np

from koordinator_tpu.api.extension import NUM_RESOURCES
from koordinator_tpu.snapshot.delta import NodeMetricDelta
from koordinator_tpu.snapshot.schema import (
    NUM_AGG,
    PodBatch,
    zeros_snapshot,
)

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "sidecar")
R = NUM_RESOURCES


# --- canonical objects (hand-built, zero randomness) ------------------------


def canonical_snapshot():
    """2 nodes, 2 quotas, 1 gang: every capacity axis tiny but real."""
    snap = zeros_snapshot(num_nodes=2, num_quotas=2, num_gangs=1,
                          num_reservations=1, num_zones=2)
    alloc = np.zeros((2, R), np.float32)
    alloc[:, 0] = (16000.0, 8000.0)   # cpu milli
    alloc[:, 1] = (32768.0, 16384.0)  # memory MiB
    usage = np.zeros((2, R), np.float32)
    usage[:, 0] = (2000.0, 1000.0)
    nodes = snap.nodes.replace(
        allocatable=alloc, usage=usage,
        metric_fresh=np.array([True, True]),
        schedulable=np.array([True, True]))
    quotas = snap.quotas.replace(valid=np.array([True, False]))
    return snap.replace(nodes=nodes, quotas=quotas)


def canonical_delta():
    z = np.zeros((1, R), np.float32)
    usage = z.copy()
    usage[0, 0] = 3000.0
    return NodeMetricDelta(
        idx=np.array([0], np.int32),
        metric_fresh=np.array([True]),
        usage=usage, prod_usage=z.copy(),
        agg_usage=np.zeros((1, NUM_AGG, R), np.float32),
        has_agg=np.array([False]),
        assigned_estimated=z.copy(), assigned_correction=z.copy(),
        prod_assigned_estimated=z.copy(),
        prod_assigned_correction=z.copy())


def canonical_topology_delta():
    """One row: node 1 upgraded to a 48-core box (identity + nested
    metric columns share idx)."""
    from koordinator_tpu.snapshot.delta import NodeTopologyDelta

    f32 = np.float32
    alloc = np.zeros((1, R), f32)
    alloc[0, 0] = 48000.0
    alloc[0, 1] = 131072.0
    return NodeTopologyDelta(
        idx=np.array([1], np.int32),
        allocatable=alloc,
        requested=np.zeros((1, R), f32),
        schedulable=np.array([True]),
        label_group=np.zeros((1,), np.int32),
        taint_group=np.zeros((1,), np.int32),
        numa_cap=np.zeros((1, 2, 2), f32),
        numa_free=np.zeros((1, 2, 2), f32),
        numa_valid=np.zeros((1, 2), bool),
        numa_policy=np.zeros((1,), np.int32),
        cpu_amplification=np.ones((1,), f32),
        gpu_total=np.zeros((1, 3), f32),
        gpu_free=np.zeros((1, 0, 3), f32),
        gpu_valid=np.zeros((1, 0), bool),
        gpu_numa=np.full((1, 0), -1, np.int32),
        gpu_pcie=np.full((1, 0), -1, np.int32),
        aux_free=np.zeros((1, 2, 0), f32),
        aux_valid=np.zeros((1, 2, 0), bool),
        metric=canonical_delta().replace(
            idx=np.array([1], np.int32)))


def canonical_pods():
    """2 pods; has_taints=True pins bit 0 of the gate_flags transport."""
    p = 2
    f32, i32 = np.float32, np.int32
    requests = np.zeros((p, R), f32)
    requests[:, 0] = (1000.0, 2000.0)
    requests[:, 1] = (512.0, 1024.0)
    estimated = np.zeros((p, R), f32)
    estimated[:, 0] = (850.0, 1700.0)
    estimated[:, 1] = (512.0, 1024.0)
    return PodBatch(
        requests=requests, estimated=estimated,
        qos=np.array([4, 4], np.int8),
        priority_class=np.array([4, 4], np.int8),
        priority=np.array([9100, 9050], i32),
        gang_id=np.full((p,), -1, i32),
        quota_id=np.array([0, -1], i32),
        selector_id=np.full((p,), -1, i32),
        selector_match=np.zeros((1, 1), bool),
        reservation_owner=np.full((p,), -1, i32),
        gpu_ratio=np.zeros((p,), f32),
        numa_single=np.zeros((p,), bool),
        daemonset=np.zeros((p,), bool),
        toleration_id=np.zeros((p,), i32),
        tol_forbid=np.zeros((1, 1), bool),
        tol_prefer=np.zeros((1, 1), f32),
        spread_id=np.full((p,), -1, i32),
        spread_carrier=np.zeros((p, 1), bool),
        spread_member=np.zeros((p, 1), bool),
        spread_max_skew=np.ones((1,), f32),
        spread_domain=np.full((1, 1), -1, i32),
        spread_count0=np.zeros((1, 1), f32),
        spread_dvalid=np.zeros((1, 1), bool),
        anti_id=np.full((p,), -1, i32),
        anti_member=np.zeros((p, 1), bool),
        anti_carrier=np.zeros((p, 1), bool),
        anti_domain=np.full((1, 1), -1, i32),
        anti_count0=np.zeros((1, 1), f32),
        anti_carrier_count0=np.zeros((1, 1), f32),
        aff_id=np.full((p,), -1, i32),
        aff_carrier=np.zeros((p, 1), bool),
        aff_member=np.zeros((p, 1), bool),
        aff_domain=np.full((1, 1), -1, i32),
        aff_count0=np.zeros((1, 1), f32),
        valid=np.ones((p,), bool),
        has_taints=True)


# --- the documented framing, re-implemented independently of rpc.py ---------


def frame(method: str, proto_bytes: bytes) -> bytes:
    """request frame := u32_be(len) ++ u8(len(method)) ++ method ++ body"""
    name = method.encode()
    payload = bytes([len(name)]) + name + proto_bytes
    return struct.pack(">I", len(payload)) + payload


def unframe_request(buf: bytes):
    (length,) = struct.unpack(">I", buf[:4])
    payload = buf[4:4 + length]
    assert len(payload) == length, "frame length mismatch"
    mlen = payload[0]
    return payload[1:1 + mlen].decode(), payload[1 + mlen:]


def build_request_frames() -> dict:
    from koordinator_tpu.scheduler import sidecar_pb2 as pb
    from koordinator_tpu.scheduler.sidecar import (
        _delta_to_bytes,
        _pack_gate_flags,
    )

    pods = canonical_pods()
    return {
        "publish_request.bin": frame(
            "PublishSnapshot",
            pb.PublishSnapshotRequest(
                snapshot_msgpack=flax.serialization.to_bytes(
                    canonical_snapshot())).SerializeToString()),
        "ingest_request.bin": frame(
            "IngestDelta",
            pb.IngestDeltaRequest(
                delta_msgpack=_delta_to_bytes(
                    canonical_delta())).SerializeToString()),
        "ingest_topology_request.bin": frame(
            "IngestTopology",
            pb.IngestTopologyRequest(
                delta_msgpack=_delta_to_bytes(
                    canonical_topology_delta())).SerializeToString()),
        "schedule_request.bin": frame(
            "Schedule",
            pb.ScheduleRequest(
                pods_msgpack=flax.serialization.to_bytes(pods),
                pod_names=["pod-a", "pod-b"],
                gate_flags=_pack_gate_flags(pods)).SerializeToString()),
        "summary_request.bin": frame(
            "Summary", b""),
    }


def _read(name: str) -> bytes:
    with open(os.path.join(FIXDIR, name), "rb") as f:
        return f.read()


# --- 1. decode: frozen foreign bytes -> expected semantics ------------------


def test_frozen_publish_request_decodes():
    from koordinator_tpu.scheduler import sidecar_pb2 as pb

    method, body = unframe_request(_read("publish_request.bin"))
    assert method == "PublishSnapshot"
    req = pb.PublishSnapshotRequest.FromString(body)
    snap = flax.serialization.from_bytes(zeros_snapshot(num_nodes=1),
                                         req.snapshot_msgpack)
    alloc = np.asarray(snap.nodes.allocatable)
    assert alloc.shape == (2, R) and alloc.dtype == np.float32
    assert alloc[0, 0] == 16000.0 and alloc[1, 1] == 16384.0
    assert np.asarray(snap.quotas.valid).tolist() == [True, False]


def test_frozen_ingest_request_decodes():
    from koordinator_tpu.scheduler import sidecar_pb2 as pb
    from koordinator_tpu.scheduler.sidecar import (
        _delta_from_bytes,
        _flat_template,
    )

    method, body = unframe_request(_read("ingest_request.bin"))
    assert method == "IngestDelta"
    req = pb.IngestDeltaRequest.FromString(body)
    delta = _delta_from_bytes(_flat_template(NodeMetricDelta),
                              req.delta_msgpack)
    assert np.asarray(delta.idx).tolist() == [0]
    assert np.asarray(delta.usage)[0, 0] == 3000.0
    # a pre-version frame restores as UNVERSIONED (always applies)
    assert delta.source_version is None


def test_frozen_topology_request_decodes():
    from koordinator_tpu.scheduler import sidecar_pb2 as pb
    from koordinator_tpu.scheduler.sidecar import (
        _delta_from_bytes,
        _topology_template,
    )

    method, body = unframe_request(_read("ingest_topology_request.bin"))
    assert method == "IngestTopology"
    req = pb.IngestTopologyRequest.FromString(body)
    delta = _delta_from_bytes(_topology_template(),
                              req.delta_msgpack)
    assert np.asarray(delta.idx).tolist() == [1]
    assert np.asarray(delta.allocatable)[0, 0] == 48000.0
    assert bool(np.asarray(delta.schedulable)[0])
    # the nested metric rows share the row index
    assert np.asarray(delta.metric.idx).tolist() == [1]
    assert np.asarray(delta.metric.usage)[0, 0] == 3000.0


def test_frozen_schedule_request_decodes():
    from koordinator_tpu.scheduler import sidecar_pb2 as pb
    from koordinator_tpu.scheduler.sidecar import (
        _apply_gate_flags,
        _flat_template,
    )

    method, body = unframe_request(_read("schedule_request.bin"))
    assert method == "Schedule"
    req = pb.ScheduleRequest.FromString(body)
    assert list(req.pod_names) == ["pod-a", "pod-b"]
    assert req.gate_flags == 1  # bit0 = has_taints
    pods = _apply_gate_flags(
        flax.serialization.from_bytes(_flat_template(PodBatch),
                                      req.pods_msgpack),
        req.gate_flags)
    assert pods.has_taints and not pods.has_spread
    assert np.asarray(pods.requests)[1, 0] == 2000.0
    assert np.asarray(pods.priority).tolist() == [9100, 9050]


# --- 2. encode: today's serialization == frozen bytes -----------------------


def test_encoding_is_wire_stable():
    """Bit-for-bit: a library or layout change that would break a
    non-Python peer must fail HERE, not in production. Regenerate the
    fixtures only for a deliberate, documented wire change."""
    for name, data in build_request_frames().items():
        frozen = _read(name)
        assert data == frozen, (
            f"{name}: serialization drifted from the frozen wire bytes "
            f"({len(data)} vs {len(frozen)} bytes); if this change is "
            f"intentional, regenerate with "
            f"`python tests/test_sidecar_wire.py --regen` and document "
            f"it in docs/SIDECAR_WIRE.md")


def test_source_version_is_an_optional_wire_extension():
    """The delta replay guard's `source_version` rides the wire only
    when stamped: an UNVERSIONED delta encodes byte-identically to the
    pre-version format (pinned above against the frozen frames), and a
    stamped one round-trips the version into the decode — so a sidecar
    deployment gets replay protection without breaking older peers."""
    from koordinator_tpu.scheduler.sidecar import (
        _delta_from_bytes,
        _delta_to_bytes,
        _flat_template,
    )

    plain = _delta_to_bytes(canonical_delta())
    assert b"source_version" not in plain
    stamped_delta = canonical_delta().replace(
        source_version=np.asarray(7, np.int32))
    stamped = _delta_to_bytes(stamped_delta)
    assert b"source_version" in stamped
    back = _delta_from_bytes(_flat_template(NodeMetricDelta), stamped)
    assert int(np.asarray(back.source_version)) == 7


# --- 3. serve: the frozen frames drive a live server ------------------------


def test_frozen_frames_drive_a_live_server(tmp_path):
    from koordinator_tpu.scheduler import sidecar_pb2 as pb
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.scheduler.sidecar import SchedulerSidecarServer

    service = SchedulerService(num_rounds=2, k_choices=2)
    server = SchedulerSidecarServer(service, str(tmp_path / "s.sock"))
    try:
        def roundtrip(name):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(120.0)
            s.connect(server.sock_path)
            s.sendall(_read(name))
            (ln,) = struct.unpack(">I", _recv_exact(s, 4))
            raw = _recv_exact(s, ln)
            s.close()
            assert raw[0] == 0, raw[1:].decode(errors="replace")
            return raw[1:]

        resp = pb.PublishSnapshotResponse.FromString(
            roundtrip("publish_request.bin"))
        assert resp.version == 1
        resp = pb.IngestDeltaResponse.FromString(
            roundtrip("ingest_request.bin"))
        assert resp.version == 2
        resp = pb.IngestTopologyResponse.FromString(
            roundtrip("ingest_topology_request.bin"))
        assert resp.version == 3
        # the topology row landed: node 1 now reports the upgraded box
        alloc = np.asarray(
            service.store.current().nodes.allocatable)
        assert alloc[1, 0] == 48000.0
        sched = pb.ScheduleResponse.FromString(
            roundtrip("schedule_request.bin"))
        assert len(sched.assignment) == 2
        assert all(a in (0, 1) for a in sched.assignment)
        assert sched.snapshot_version == 4
        resp = pb.SummaryResponse.FromString(
            roundtrip("summary_request.bin"))
        assert json.loads(resp.json)["podsPlaced"] == sum(
            1 for a in sched.assignment if a >= 0)
    finally:
        server.close()


def test_error_frames_follow_the_status_byte_contract(tmp_path):
    """Foreign-client failure modes must come back as status-1 frames
    with utf-8 text (docs/SIDECAR_WIRE.md §1), never hangs or closed
    sockets: an unknown method and a malformed protobuf body."""
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.scheduler.sidecar import SchedulerSidecarServer

    server = SchedulerSidecarServer(SchedulerService(),
                                    str(tmp_path / "e.sock"))
    try:
        def roundtrip_raw(frame_bytes):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(30.0)
            s.connect(server.sock_path)
            s.sendall(frame_bytes)
            (ln,) = struct.unpack(">I", _recv_exact(s, 4))
            raw = _recv_exact(s, ln)
            s.close()
            return raw

        resp = roundtrip_raw(frame("NoSuchMethod", b""))
        assert resp[0] == 1
        assert "NoSuchMethod" in resp[1:].decode()

        resp = roundtrip_raw(frame("Schedule", b"\xff\xff\xff garbage"))
        assert resp[0] == 1 and len(resp) > 1
    finally:
        server.close()


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        assert chunk, "connection closed mid-frame"
        buf += chunk
    return buf


if __name__ == "__main__":
    import sys
    if "--regen" in sys.argv:
        os.makedirs(FIXDIR, exist_ok=True)
        for name, data in build_request_frames().items():
            with open(os.path.join(FIXDIR, name), "wb") as f:
                f.write(data)
            print(f"wrote {name} ({len(data)} bytes)")
    else:
        print(__doc__)


def test_cpp_client_roundtrips_the_wire(tmp_path):
    """A NON-PYTHON process speaks the wire: the C++ conformance client
    (native/sidecar_client.cpp, POSIX sockets only) replays the frozen
    frames against a live server and validates the responses — the
    second-language exercise of the Go-callable seam
    (framework_extender.go:167-292)."""
    import subprocess

    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.scheduler.sidecar import SchedulerSidecarServer

    native = os.path.join(os.path.dirname(__file__), "..",
                          "koordinator_tpu", "native")
    build = subprocess.run(["make", "-C", native, "sidecar_client"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr

    service = SchedulerService(num_rounds=2, k_choices=2)
    server = SchedulerSidecarServer(service, str(tmp_path / "s.sock"))
    try:
        run = subprocess.run(
            [os.path.join(native, "sidecar_client"), server.sock_path,
             FIXDIR],
            capture_output=True, text=True, timeout=300)
        assert run.returncode == 0, (run.stdout, run.stderr)
        assert "OK (5/5 RPCs round-tripped)" in run.stdout
        # the C++ client's schedule really committed on the server
        assert service.batches == 1 and service.pods_placed >= 1
    finally:
        server.close()
