"""Native perf-group shim tests (SURVEY.md 2.8 item 1: the C++ equivalent
of the libpfm4 cgo reader). Software perf events exercise the full grouped
open/reset/enable/read/scale path without requiring a hardware PMU; tests
skip where the sandbox denies perf_event_open entirely."""

import subprocess

import pytest

from koordinator_tpu import native


def _perf_works() -> bool:
    if not native.native_available():
        return False
    try:
        c = native.PerfGroupCollector(pid=0, events=("sw-task-clock",),
                                      cpus=[0])
        c.close()
        return True
    except OSError:
        return False


def test_shim_builds_and_loads():
    # make is idempotent; the .so must build from a clean tree with g++
    subprocess.run(["make", "-C", "koordinator_tpu/native", "-s"],
                   check=True, timeout=120)
    assert native.native_available(), native.last_error()


def test_unknown_event_rejected():
    if not native.native_available():
        pytest.skip("native shim unavailable")
    with pytest.raises(ValueError):
        native.PerfGroupCollector(pid=0, events=("no-such-event",))


def test_bad_cgroup_raises_oserror():
    if not _perf_works():
        pytest.skip("perf_event_open denied in sandbox")
    with pytest.raises(OSError):
        native.PerfGroupCollector(cgroup_dir="/nonexistent/cgroup/dir")


def test_grouped_software_counters_monotonic():
    if not _perf_works():
        pytest.skip("perf_event_open denied in sandbox")
    with native.PerfGroupCollector(
            pid=0, events=("sw-task-clock", "sw-page-faults")) as c:
        x = 0
        for i in range(1_000_000):
            x += i * i
        v1 = c.read()
        for i in range(1_000_000):
            x += i * i
        v2 = c.read()
    assert v1["sw-task-clock"] > 0
    assert v2["sw-task-clock"] > v1["sw-task-clock"]


def test_reader_factory_graceful():
    # returns a callable (PMU present) or None (no PMU / denied) — never
    # raises; this mirrors the Libpfm4 gate's degraded mode
    r = native.cycles_instructions_reader()
    assert r is None or callable(r)


def test_daemon_perf_gate_degrades(tmp_path):
    from koordinator_tpu.koordlet.agent import Daemon, DaemonConfig
    from koordinator_tpu.koordlet.testing import FakeHost

    d = Daemon(FakeHost(str(tmp_path)),
               DaemonConfig(enable_perf_group=True))
    d.tick(now=0)  # must not raise regardless of perf availability
