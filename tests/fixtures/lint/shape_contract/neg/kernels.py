"""Negative shape-contract fixtures: the same kernels written
honestly — explicit broadcasts, matching cross-calls, contracted jit."""

import functools

import jax
import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import register_struct, shape_contract


class Cols:
    """Stand-in columnar struct (the fixture never runs)."""


register_struct(Cols, {
    "alloc": "f32[N,R]",
    "req": "f32[P,R]",
    "valid": "bool[P]",
})


@shape_contract(cols="Cols", _returns="bool[P,N]")
def fit_mask(cols):
    pair = cols.req[:, None, :] + cols.alloc[None]     # explicit [P,N,R]
    return jnp.all(pair <= cols.alloc[None], axis=-1)


@shape_contract(cols="Cols", _returns="f32[P,N]")
def masked_fit(cols):
    fit = jnp.zeros((cols.req.shape[0], cols.alloc.shape[0]),
                    jnp.float32)
    return fit * cols.valid[:, None]                   # declared growth


@shape_contract(x="f32[N,R]", _returns="f32[N]")
def row_sums(x):
    return jnp.sum(x, axis=-1)


@shape_contract(cols="Cols", _returns="f32[N]")
def node_load(cols):
    return row_sums(cols.alloc)                        # [N,R] as declared


@shape_contract(x="f32[P,R]", _returns="f32[P]", _static={"lo": "R"})
@functools.partial(jax.jit, static_argnames=("lo",))
def contracted_jit(x, lo=1):
    return jnp.sum(x, axis=-1) * lo
