"""Positive shape-contract fixtures: one violation per SH code."""

import functools

import jax
import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import register_struct, shape_contract


class Cols:
    """Stand-in columnar struct (the fixture never runs)."""


register_struct(Cols, {
    "alloc": "f32[N,R]",
    "req": "f32[P,R]",
    "valid": "bool[P]",
})


@shape_contract(cols="Cols", _returns="bool[P,N]")
def mixed_dims(cols):
    bad = cols.req + cols.alloc            # SH001: [P,R] + [N,R]
    return jnp.all(bad[:, None, :] <= cols.alloc[None], axis=-1)


@shape_contract(cols="Cols", _returns="f32[P,N]")
def implicit_growth(cols):
    fit = jnp.zeros((cols.req.shape[0], cols.alloc.shape[0]),
                    jnp.float32)
    return fit * cols.valid                # SH002: [P,N] * [P] implicit


@shape_contract(x="f32[N,R]", _returns="f32[N]")
def row_sums(x):
    return jnp.sum(x, axis=-1)


@shape_contract(cols="Cols", _returns="f32[N]")
def drift(cols):
    return row_sums(cols.req)              # SH003: [P,R] into f32[N,R]


@shape_contract(cols="Cols", _returns="f32[N]")
def wrong_return(cols):
    return jnp.sum(cols.req, axis=-1)      # SH001: returns [P], not [N]


@shape_contract(cols="Cols", bogus="f33[N]", _returns="f32[XY]")
def bad_specs(cols, bogus):                # SH005 x2: dtype + dim symbol
    return bogus


@functools.partial(jax.jit, static_argnames=("flip",))
def uncontracted(x, flip=False):           # SH004: jit with no contract
    return -x if flip else x
