"""Negative host-sync fixtures: static operands, shape reads, and
device-side conversions must all pass, as must host syncs in functions
no jit entry reaches."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k", "dims"))
def entry(x, k, dims):
    n = x.shape[0]                       # shapes are static under trace
    idx = np.asarray(dims, dtype=np.int32)   # static operand: fine
    scale = int(k)                       # static coercion: fine
    y = helper(x) * scale
    return y + n + idx.sum()


def helper(x):
    return jnp.asarray(x)                # device-side conversion: fine


def host_only(x):
    # full of syncs, but no jit entry reaches it
    jax.block_until_ready(x)
    return float(np.asarray(x).item())
