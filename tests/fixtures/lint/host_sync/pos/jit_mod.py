"""Positive host-sync fixtures: every sink class, including one two
calls deep in the traced call graph."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def coerce_direct(x):
    return float(x) + 1.0          # HS005


@jax.jit
def syncy(x):
    jax.block_until_ready(x)       # HS002
    y = x.block_until_ready()      # HS002 (method form)
    return jax.device_get(y)       # HS003


@functools.partial(jax.jit, static_argnames=("k",))
def entry(x, k):
    return helper(x) * k


def helper(x):
    host = np.asarray(x)           # HS004 (reached from entry)
    return deep(host)


def deep(x):
    return x.item()                # HS001 (two levels deep)
