"""RB001 negatives: classified handlers, narrow handlers, and broad
handlers around host-only work."""

import json

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    return jnp.sum(x * 2.0)


def classify_failure(exc):
    return type(exc).__name__


def classified(x):
    try:
        return kernel(x)
    except Exception as exc:
        # routed through the typed model: not flagged
        return classify_failure(exc)


def narrow(x):
    try:
        return kernel(x)
    except ValueError:
        # a narrow handler is a deliberate, typed choice already
        return None


def host_only(path):
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        # no device-program call in the try body: out of scope
        return None
