"""RB001 positives: broad handlers around device-program calls with no
FailureClass classification."""

import jax
import jax.numpy as jnp


@jax.jit
def kernel(x):
    return jnp.sum(x * 2.0)


def sweep(x):
    # transitively device-reaching: sweep -> kernel (a jit entry)
    return kernel(x) + 1.0


def direct(x):
    try:
        return kernel(x)
    except Exception:  # RB001: untyped swallow of a device failure
        return None


def transitive(x):
    try:
        return sweep(x)
    except:  # noqa: E722  RB001: bare except, one call from the kernel
        return None


def via_alias(x):
    try:
        return fast(x)
    except BaseException:  # RB001: alias form g = jax.jit(f)
        return None


def _impl(x):
    return x + 1


fast = jax.jit(_impl)
