from fixtures.metrics.registry import ALPHA_NAME  # noqa: F401


class MetricsA:
    def __init__(self, r):
        self.alpha = r.counter(ALPHA_NAME, "fine")
