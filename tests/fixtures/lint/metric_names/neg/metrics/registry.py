"""Negative metric-registry fixture: every constant registered exactly
once."""

ALPHA_NAME = "comp_alpha_total"
BETA_NAME = "comp_beta_total"
