from fixtures.metrics.registry import BETA_NAME  # noqa: F401


class MetricsB:
    def __init__(self, r):
        self.beta = r.histogram(BETA_NAME, "fine")
