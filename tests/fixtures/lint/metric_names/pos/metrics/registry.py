"""Positive metric-registry fixture: shared name registry with one dead
constant."""

GOOD_NAME = "comp_good_total"
DEAD_NAME = "comp_dead_total"      # MN003: no catalog registers it
