from fixtures.metrics.registry import GOOD_NAME  # noqa: F401


class MetricsA:
    def __init__(self, r):
        self.good = r.counter(GOOD_NAME, "fine")
        self.bare = r.gauge("comp_bare_total", "MN002: bare literal")
        self.mystery = r.counter(UNKNOWN_NAME, "MN004")  # noqa: F821
