from fixtures.metrics.registry import GOOD_NAME  # noqa: F401


class MetricsB:
    def __init__(self, r):
        # MN001: comp_a already registered this family
        self.clash = r.counter(GOOD_NAME, "duplicate")
