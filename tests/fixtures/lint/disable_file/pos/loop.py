"""Positive disable-file fixture: the file-level marker names a
DIFFERENT code, and two markers for the RIGHT code hide inside string
literals (this docstring and a constant), so the HS006 tail-readback
finding must still fire. A doc line quoting the pragma verbatim:

    # koordlint: disable-file=HS006

must never silence anything — only real comment tokens count."""

# koordlint: disable-file=HS001

import numpy as np

DOC = "koordlint: disable-file=HS006"  # inside a string: must not count


def adaptive(step, snap, stats, budget):
    left = 1
    passes = 0
    while passes < budget and left > 0:
        snap, stats = retry_pass(step, snap)
        left = int(np.asarray(stats)[0])
        passes += 1
    return snap


def retry_pass(step, snap):
    return step(snap)
