"""Negative disable-file fixture: the file-level marker names the
HS006 code, silencing the tail-readback finding for the whole file
(the conformance-oracle use case the pragma exists for)."""

# koordlint: disable-file=HS006 host-tail conformance oracle

import numpy as np


def adaptive(step, snap, stats, budget):
    left = 1
    passes = 0
    while passes < budget and left > 0:
        snap, stats = retry_pass(step, snap)
        left = int(np.asarray(stats)[0])
        passes += 1
    return snap


def retry_pass(step, snap):
    return step(snap)
