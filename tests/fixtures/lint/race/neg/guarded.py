"""Negative race-guard fixtures: disciplined contracts the analyzer
must stay silent on — base-class lock inheritance, entry-held helper
resolution, the never-guess rule for unresolvable context managers,
the spanning-lock check-then-act exemption, copy-out returns, and the
declaration-only vocabulary guards."""

import threading

from koordinator_tpu.utils.sync import guard_module, guarded_by

_lock = threading.Lock()
_events = []

guard_module(__name__, _events="_lock")


def record(ev):
    with _lock:
        _events.append(ev)


def snapshot():
    with _lock:
        return list(_events)


@guarded_by(_epoch="_lock")
class _Base:
    def __init__(self):
        self._lock = threading.RLock()
        self._epoch = 0

    def tick(self):
        with self._lock:
            self._epoch += 1


@guarded_by(
    _items="_lock",                # inherited from _Base
    _stats="_lock",
    _sink="confined",
    capacity="publish-once",
    journal="external:Owner._commit_lock",
)
class Store(_Base):
    def __init__(self):
        super().__init__()
        self._ck = threading.Lock()
        self._items = []
        self._stats = {}
        self._sink = []
        self.capacity = 8
        self.journal = None
        self._warm()

    def _warm(self):
        # reachable only from construction: exempt from inheritance
        self._stats = {"n": 0}

    def add(self, x):
        with self._lock:
            self._append_locked(x)

    def extend(self, xs):
        with self._lock:
            for x in xs:
                self._append_locked(x)

    def _append_locked(self, x):
        # entry-held: every intra-class call site holds _lock
        self._items.append(x)
        self._stats = dict(self._stats, n=len(self._items))

    def drain(self):
        with self._lock:
            out = list(self._items)   # copy-out: no escaping reference
            self._items = []
        return out

    def checkpointed_trim(self, cap):
        # two _lock windows, but _ck spans both: the read cannot go
        # stale between them (the SnapshotStore.checkpoint pattern)
        with self._ck:
            with self._lock:
                n = self._stats["n"]
            keep = min(n, cap)
            with self._lock:
                self._stats = dict(self._stats, n=keep)

    def export(self, fh):
        with fh:
            # unresolvable context manager: never guess what it
            # synchronizes, report nothing inside it
            self._stats = dict(self._stats, exported=True)

    def sink(self, x):
        self._sink.append(x)       # confined: declaration-only

    def cap(self):
        return self.capacity       # publish-once: no lock needed

    def journal_ref(self):
        return self.journal        # external guard: owner enforces
