"""Positive race-guard fixtures: every GB code fires at least once —
unguarded accesses (class and module scope), a check-then-act window,
an escaping mutable reference, all three GB004 drift shapes, and a
malformed contract."""

import threading

from koordinator_tpu.utils.sync import guard_module, guarded_by

_lock = threading.Lock()
_pending = []

guard_module(__name__, _pending="_lock")


def enqueue(item):
    _pending.append(item)          # GB001: module global outside _lock


def drain_pending():
    with _lock:
        return list(_pending)


@guarded_by(_count="_lock", _items="_lock")
class Accounts:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._items = []

    def bump(self):
        self._count += 1           # GB001: write outside the lock

    def reserve(self, n):
        with self._lock:
            have = self._count
        if have < n:
            return False
        with self._lock:
            self._count = have - n  # GB002: acts on the stale read
        return True

    def items(self):
        with self._lock:
            return self._items     # GB003: live mutable ref escapes

    def put(self, x):
        with self._lock:
            self._items.append(x)
            self._count += 1


class NoContract:                  # GB004: lock-owning, no contract
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def set(self, v):
        with self._lock:
            self.value = v


@guarded_by(_data="_missing")      # GB004: guard names no real lock
class Drifted:
    def __init__(self):
        self._lock = threading.Lock()
        self._data = {}

    def get(self):
        with self._lock:
            return dict(self._data)


@guarded_by(_q="_qlock")           # GB004: guard never acquired
class DeadGuard:
    def __init__(self):
        self._qlock = threading.Lock()
        self._q = []

    def size_hint(self):
        return 0


@guarded_by(_x="not an identifier!")   # GB005: outside the grammar
class Malformed:
    def __init__(self):
        self._x = 0
