"""Negative pad-soundness fixtures: pad-correct kernels that exercise
the same shapes as pos/ without violating any PS rule."""

import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import register_struct, shape_contract


class Cols:
    """Stand-in columnar struct (the fixture never runs)."""


register_struct(Cols, {
    "usage": "f32[N~pad:zero]",
    "mask": "bool[N~pad:false]",
})


@shape_contract(x="f32[P~pad:zero,R]", _returns="f32[R]")
def sum_over_zeros(x):
    return jnp.sum(x, axis=0)             # zero-pads are sum-neutral


@shape_contract(idx="i32[P~pad:-1]", table="f32[Q~pad:zero]",
                _returns="f32[P~pad:any]")
def clamped_gather(idx, table):
    safe = jnp.maximum(idx, 0)            # clamp kills the -1 fill
    return table[safe]


@shape_contract(m="bool[N~pad:false]", _returns="f32[]")
def masked_total(m):
    return jnp.sum(m.astype(jnp.float32))


@shape_contract(m="bool[N~pad:false]", _returns="f32[]")
def straight_cross(m):
    return masked_total(m & m)            # pads stay False across the call


@shape_contract(cols="Cols", _returns="f32[N~pad:zero]")
def masked_usage(cols):
    return cols.usage * cols.mask         # & with false pads annihilates
