"""Positive pad-soundness fixtures: one violation per PS code."""

import jax.numpy as jnp

from koordinator_tpu.snapshot.schema import register_struct, shape_contract


class Cols:
    """Stand-in columnar struct (the fixture never runs)."""


register_struct(Cols, {
    "usage": "f32[N]",                    # PS004: padded dim, no ~pad:
    "mask": "bool[N~pad:false]",
})


@shape_contract(x="f32[P~pad:one,R]", _returns="f32[R]")
def sum_over_ones(x):
    return jnp.sum(x, axis=0)             # PS001: one-pads inflate sums


@shape_contract(idx="i32[P~pad:-1]", table="f32[Q~pad:zero]",
                _returns="f32[P~pad:any]")
def raw_sentinel_gather(idx, table):
    return table[idx]                     # PS002: -1 wraps to the last row


@shape_contract(m="bool[N~pad:false]", _returns="f32[]")
def masked_total(m):
    return jnp.sum(m.astype(jnp.float32))


@shape_contract(m="bool[N~pad:false]", _returns="f32[]")
def inverted_cross(m):
    return masked_total(~m)               # PS003: ~m pads are True


@shape_contract(w="f32[2~pad:zero]", _returns="i32[Q~pad:inf]")
def malformed_pads(w):                    # PS005: literal-dim pad + int inf
    return jnp.zeros((8,), jnp.int32)


@shape_contract(s="f32[S~pad:zero]", _returns="f32[S]")
def exempt_dim_pad(s):                    # PS005: S is sized exactly
    return s
