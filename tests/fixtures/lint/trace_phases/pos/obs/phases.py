"""Positive trace-phases fixture: the shared phase table (its presence
activates the pass; its own literals are exempt)."""

PHASE_GOOD = "fix/good_phase"
SPAN_CYCLE = "cycle"
