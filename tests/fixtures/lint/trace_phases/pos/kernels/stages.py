"""Positive trace-phases fixture: bare string-literal annotation labels
in every recognized callable form."""

import jax


def stage_scope(x):
    with jax.named_scope("fix/bare_scope"):       # OB001
        return x + 1


def stage_annotation(x):
    with jax.profiler.TraceAnnotation("fix/bare_anno"):   # OB001
        return x * 2


def stage_timer(hist, fn, x):
    with kernel_timer(hist, "fix/bare_timer"):    # OB001
        return fn(x)


def stage_keyword(x):
    with jax.named_scope(name="fix/bare_kw"):     # OB001: keyword form
        return x - 1


def kernel_timer(hist, annotation):
    return hist.labels(annotation)
