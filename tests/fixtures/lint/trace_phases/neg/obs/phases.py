"""Negative trace-phases fixture: the shared phase table (the pass is
active here, but every consumer routes through the constants)."""

PHASE_GOOD = "fix/good_phase"
SPAN_CYCLE = "cycle"
