"""Negative trace-phases fixture: annotation labels drawn from the
phase-table constants (or from variables) — nothing fires."""

import jax

from obs import phases


def stage_scope(x):
    with jax.named_scope(phases.PHASE_GOOD):
        return x + 1


def stage_annotation(x):
    with jax.profiler.TraceAnnotation(phases.SPAN_CYCLE):
        return x * 2


def stage_timer(hist, fn, x, label):
    with kernel_timer(hist, label):
        return fn(x)


def unrelated_call(x):
    # same tail name but a different arity slot left empty is ignored
    return jax.named_scope


def kernel_timer(hist, annotation):
    return hist.labels(annotation)
