"""Negative recompilation-hazard fixtures: scalars declared static,
branching only on static parameters."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("mode", "n"))
def ok(x, mode: str = "a", n: int = 4):
    if mode == "a":                # static branch: specialization is
        return x * n               # explicit in the signature
    return jnp.where(x > 0, x, -x)


@jax.jit
def arrays_only(x, mask):
    return jnp.where(mask, x, 0.0)


@jax.jit
def optional_guard(x, mask=None):
    # `param is None` is a concrete Python bool under trace — the
    # standard optional-argument idiom must not flag
    if mask is None:
        mask = jnp.ones_like(x)
    return jnp.where(mask, x, 0.0)


@jax.jit
def pytree_tuple(xs: tuple):
    # a tuple-annotated param is an ordinary traced pytree, not a
    # static-argnames candidate
    return xs[0] + xs[1]


@functools.partial(jax.jit, static_argnames=("k", "names"))
def hashable_statics(x, k: int = 4, names: tuple = ()):
    # int/tuple statics are hashable — no RC004
    return x * k


def run(x):
    # a literal into a STATIC param is exactly what static_argnames is
    # for, and a wrapped scalar into a traced param carries its dtype
    a = hashable_statics(x, 8, names=("cpu",))
    b = arrays_only(x, jnp.asarray(0.5))
    return a + b
