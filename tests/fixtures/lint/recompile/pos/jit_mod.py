"""Positive recompilation-hazard fixtures."""

import functools

import jax


@jax.jit
def scalar_params(x, mode: str, n: int = 4):
    # RC001 twice: mode (str) and n (int default) are not static
    return x * n


@functools.partial(jax.jit, static_argnames=("k",))
def branchy(x, flag, k):
    if flag:                       # RC002: truth value of a tracer
        return x * k
    if x.shape[0] > 2:             # RC003: per-shape specialization
        return x + 1
    return x
