"""Positive recompilation-hazard fixtures."""

import functools
import time

import jax


@jax.jit
def scalar_params(x, mode: str, n: int = 4):
    # RC001 twice: mode (str) and n (int default) are not static
    return x * n


@functools.partial(jax.jit, static_argnames=("k",))
def branchy(x, flag, k):
    if flag:                       # RC002: truth value of a tracer
        return x * k
    if x.shape[0] > 2:             # RC003: per-shape specialization
        return x + 1
    return x


@functools.partial(jax.jit, static_argnames=("opts", "seed"))
def keyed(x, opts: list, seed=()):
    # RC004 (signature): `opts` is static but annotated `list` —
    # jit's cache key raises on unhashable statics
    return x[0] * len(opts) + seed[0] if seed else x[0]


def run(x):
    # RC004 (call site): a static fed from time.* re-keys per call
    a = keyed(x, ("p",), seed=(time.monotonic(),))
    # RC005: bare float literal into traced `flag` — weak-typed scalar
    b = branchy(x, 0.5, k=3)
    return a + b
