"""Negative donation-aliasing fixtures: the rebind idiom, in and out of
loops."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, inc):
    return state + inc


def drive(state, inc):
    state = step(state, inc)       # rebinds: nothing stale
    total = jnp.sum(state)
    return state, total


def loop(state, inc):
    for _ in range(3):
        state = step(state, inc)   # rebind every iteration
    return state
