"""Positive donation-aliasing fixtures: reads of a donated buffer after
the call, straight-line and via loop wrap-around."""

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, inc):
    return state + inc


def drive(state, inc):
    out = step(state, inc)
    norm = jnp.sum(state)          # DA001: state was donated above
    return out, norm


def loop(state, inc):
    out = None
    for _ in range(3):
        out = step(state, inc)     # DA001: next iteration re-donates
    return out                     # the buffer iteration 1 consumed
