"""Positive fixture: per-pass host readbacks inside retry/tail loops."""

import jax
import numpy as np


def adaptive_tail(step, snap, stats):
    """The bug class: one blocking transfer per adaptive decision."""
    left = 10
    passes = 0
    while passes < 6 and left > 0:
        snap, stats = step(snap)
        pair = np.asarray(stats)               # HS006
        left = int(pair[0])
        jax.device_get(stats)                  # HS006
        stats.block_until_ready()              # HS006
        passes += 1
    return snap


def drain(step, snap, count, budget):
    # loop header never names the pattern; the callee does
    for _ in range(budget):
        snap, count = retry_pass(step, snap)
        left = count.item()                    # HS006
        if left == 0:
            break
    return snap


def retry_pass(step, snap):
    return step(snap)
