"""Negative fixture: device-resident tails and unrelated array walks."""

import numpy as np


def adaptive_tail(loop, snap, counts, assign):
    """The fixed shape: the whole adaptive loop runs on device and the
    host reads ONE stats vector after it."""
    snap, counts, assign, stats = loop(snap, counts, assign)
    final = np.asarray(stats)       # single readback AFTER the loop
    return snap, counts, assign, final


def mandatory_tail(step, snap, stats_fn, n):
    hist = []
    for _ in range(n):              # tail loop, but fully device-resident
        snap = step(snap)
        hist.append(stats_fn(snap))  # device values, no transfer
    return snap, hist


def column_sums(rows):
    out = []
    for r in rows:                  # ordinary data walk, not a tail loop
        out.append(np.asarray(r).sum())
    return out


def format_details(rows):
    # 'details', 'retailer', 'curtailed' contain the vocabulary only as
    # mid-word substrings — segment-boundary anchoring must not match
    out = []
    for retailer in rows:
        curtailed = np.asarray(retailer)
        out.append(curtailed.sum())
    return out
