"""Negative determinism fixtures: set consumption that is order-free
or explicitly sorted."""

import hashlib

import numpy as np

ACTIVE_KINDS = {"cpu", "memory", "gpu"}


def columnarize(nodes):
    names = {n.name for n in nodes}
    rows = sorted(names)                  # sorted(): deterministic
    return {name: i for i, name in enumerate(rows)}


def kind_columns():
    return np.asarray(sorted(ACTIVE_KINDS))


def digest(pods):
    seen = {p.uid for p in pods}
    h = hashlib.sha256()
    for uid in sorted(seen):
        h.update(uid.encode())
    return h.hexdigest()


def membership(kind, extra):
    allowed = ACTIVE_KINDS | set(extra)
    total = len(allowed)                  # order-free consumption
    return kind in allowed and total > 0


def extremes(weights):
    pool = set(weights)
    return min(pool), max(pool)
