"""Positive determinism fixtures: set order materialized into ordered
artifacts (the analyzer only scans koordinator_tpu/ paths, hence the
fixture package dir)."""

import hashlib

import numpy as np

ACTIVE_KINDS = {"cpu", "memory", "gpu"}


def columnarize(nodes):
    names = {n.name for n in nodes}
    rows = list(names)                    # ND001: list() of a set
    return {name: i for i, name in enumerate(rows)}


def kind_columns():
    return np.asarray([k for k in ACTIVE_KINDS])  # ND001: listcomp


def digest(pods):
    seen = set()
    for p in pods:
        seen.add(p.uid)
    h = hashlib.sha256()
    for uid in seen:                      # ND001: digest over set order
        h.update(uid.encode())
    return h.hexdigest()


def label_key(labels):
    tags = set(labels) | {"default"}
    return ",".join(tags)                 # ND001: join over set order
