"""Negative lock-discipline fixtures: one global order, blocking only
outside the critical sections."""

import threading
import time

_REGISTRY_LOCK = threading.Lock()


class Store:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def also_ab(self):
        # same order everywhere: no cycle
        with self._a:
            with self._b:
                return 2

    def snapshot_then_block(self):
        with self._a:
            state = 41
        time.sleep(0.01)           # after release: fine
        return state + 1

    def registry(self):
        with _REGISTRY_LOCK:
            return 3
