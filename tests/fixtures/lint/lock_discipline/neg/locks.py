"""Negative lock-discipline fixtures: one global order, blocking only
outside the critical sections."""

import threading
import time

_REGISTRY_LOCK = threading.Lock()


class Store:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def also_ab(self):
        # same order everywhere: no cycle
        with self._a:
            with self._b:
                return 2

    def snapshot_then_block(self):
        with self._a:
            state = 41
        time.sleep(0.01)           # after release: fine
        return state + 1

    def registry(self):
        with _REGISTRY_LOCK:
            return 3


class Queue:
    def __init__(self):
        self._cond = threading.Condition()
        self._items = []

    def get(self):
        # waiting under ONLY the condition's own lock is the normal
        # pattern: wait releases it while sleeping
        with self._cond:
            while not self._items:
                self._cond.wait()
            return self._items.pop()

    def put(self, item):
        with self._cond:
            self._items.append(item)
            self._cond.notify()


class CheckpointWriter:
    def __init__(self):
        self._commit_lock = threading.Lock()
        self._state = b""

    def commit(self, payload):
        # the negative shape LK005 demands: snapshot under the lock,
        # write OUTSIDE it
        with self._commit_lock:
            self._state = payload
        with open("/tmp/ck.bin", "wb") as f:
            f.write(self._state)

    def non_commit_io(self, payload):
        # file I/O under a NON-commit lock is out of LK005's scope
        # (LK002 owns genuinely blocking calls; plain writes are fine
        # under ordinary state locks)
        with _REGISTRY_LOCK:
            with open("/tmp/reg.bin", "wb") as f:
                f.write(payload)


class SharedLockQueue:
    def __init__(self):
        # the stdlib idiom: the condition WRAPS an existing lock, so
        # wait() releases self._lk — holding it while waiting is the
        # documented correct pattern, not LK004
        self._lk = threading.Lock()
        self._cond = threading.Condition(self._lk)
        self._items = []

    def get(self):
        with self._lk:
            while not self._items:
                self._cond.wait()
            return self._items.pop()
