"""Negative LK005 fixture: a module named journal.py IS the sanctioned
bounded append seam — commit-lock file I/O here is exempt (the real
one is koordinator_tpu/scheduler/journal.py, whose append-before-
publish ordering REQUIRES writing inside the commit critical section)."""

import os
import threading


class CommitJournal:
    def __init__(self, path):
        self.path = path
        self._commit_lock = threading.Lock()

    def append(self, payload):
        with self._commit_lock:
            with open(self.path, "ab") as f:   # exempt: the seam
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
