"""Positive lock-discipline fixtures: an order cycle, blocking under a
lock (direct and via a helper), and a manual acquire."""

import threading
import time


class Store:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ba(self):
        with self._b:
            with self._a:          # LK001: a->b and b->a form a cycle
                return 2

    def slow(self):
        with self._a:
            time.sleep(1.0)        # LK002: blocking under the lock

    def indirect(self):
        with self._b:
            return self._nap()     # LK002: helper blocks

    def _nap(self):
        time.sleep(0.1)
        return 3

    def manual(self):
        self._a.acquire()          # LK003: escapes the with analysis
        try:
            return 4
        finally:
            self._a.release()


class Service:
    """LK005: file I/O under a commit lock outside the journal seam."""

    def __init__(self):
        self._commit_lock = threading.Lock()

    def commit_direct(self, payload):
        with self._commit_lock:
            with open("/tmp/x.bin", "ab") as f:   # LK005: direct
                f.write(payload)

    def commit_indirect(self, payload):
        with self._commit_lock:
            return self._persist(payload)         # LK005: via helper

    def _persist(self, payload):
        import os
        with open("/tmp/x.bin", "ab") as f:
            f.write(payload)
        os.fsync(f.fileno())
        return True


class Feed:
    def __init__(self):
        self._state = threading.Lock()
        self._cond = threading.Condition()

    def drain(self):
        with self._state:
            with self._cond:
                self._cond.wait()  # LK004: _state stays pinned until
                return 5           # a notify arrives
