"""koordlet kernel-interface layer + executor + metriccache + collectors.

Hermetic: everything runs against the FakeHost temp tree (the reference's
NewFileTestUtil strategy, SURVEY.md 4)."""

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import QoSClass, ResourceKind
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import system
from koordinator_tpu.koordlet.metricsadvisor import default_advisor
from koordinator_tpu.koordlet.resourceexecutor import CgroupUpdate, Executor
from koordinator_tpu.koordlet.statesinformer import (
    CollectPolicy,
    NodeMetricReporter,
    PodMeta,
    StatesInformer,
)
from koordinator_tpu.koordlet.testing import FakeHost


@pytest.fixture
def host(tmp_path):
    return FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)


# --- system -----------------------------------------------------------------

def test_cpuset_roundtrip():
    assert system.parse_cpuset("0-2,5,7-8") == [0, 1, 2, 5, 7, 8]
    assert system.format_cpuset([5, 0, 1, 2, 8, 7]) == "0-2,5,7-8"
    assert system.parse_cpuset("") == []
    assert system.format_cpuset([]) == ""


def test_pod_cgroup_dir_drivers():
    d = system.pod_cgroup_dir("besteffort", "ab-12",
                              system.CgroupDriver.CGROUPFS)
    assert d == "kubepods/besteffort/podab-12"
    d = system.pod_cgroup_dir("guaranteed", "ab-12",
                              system.CgroupDriver.CGROUPFS)
    assert d == "kubepods/podab-12"
    d = system.pod_cgroup_dir("burstable", "ab-12",
                              system.CgroupDriver.SYSTEMD)
    assert d.endswith("kubepods-burstable-podab_12.slice")


def test_cgroup_read_write_and_validation(host):
    host.make_cgroup("kubepods/besteffort/podx")
    host.write_cgroup("kubepods/besteffort/podx", "cpu.shares", "2")
    assert host.read_cgroup("kubepods/besteffort/podx", "cpu.shares") == "2"
    with pytest.raises(ValueError):
        host.write_cgroup("kubepods/besteffort/podx", "cpu.shares", "1")
    with pytest.raises(ValueError):
        host.write_cgroup("kubepods/besteffort/podx", "cpu.bvt_warp_ns", "7")


def test_cgroup_v2_mapping(tmp_path):
    host = FakeHost(str(tmp_path), cgroup_version=system.CgroupVersion.V2)
    assert host.cgroup_version is system.CgroupVersion.V2
    p = host.cgroup_file("kubepods", "cpu.shares")
    assert p.endswith("kubepods/cpu.weight")
    # memory usage via memory.current + cpu via cpu.stat
    host.set_cgroup_cpu_ns("kubepods", 3_000_000_000)
    assert host.cpu_acct_usage_ns("kubepods") == 3_000_000_000


def test_psi_parse(host):
    host.set_psi("kubepods", "memory", some_avg10=1.5, full_avg10=0.7)
    psi = host.psi("kubepods", "memory")
    assert psi.some_avg10 == 1.5 and psi.full_avg10 == 0.7


def test_cpu_topology(host):
    topo = host.cpu_topology()
    assert len(topo) == 8
    assert topo[0].core_id == 0 and topo[1].core_id == 0  # HT siblings
    assert topo[2].core_id == 1


def test_resctrl_schemata(host):
    host.init_resctrl(l3_mask="fff", mb_percent=100)
    host.write_resctrl_schemata("BE", {"L3": "0=ff", "MB": "0=30"})
    got = host.resctrl_schemata("BE")
    assert got == {"L3": "0=ff", "MB": "0=30"}


# --- resourceexecutor -------------------------------------------------------

def test_executor_cacheable_skip(host):
    host.make_cgroup("kubepods/podx")
    ex = Executor(host)
    up = CgroupUpdate("kubepods/podx", "cpu.shares", "512")
    assert ex.update(up)
    # poke the file behind the cache; cacheable update sees cache hit and
    # skips the write
    host.write(host.cgroup_file("kubepods/podx", "cpu.shares"), "9999")
    assert ex.update(up)
    assert host.read_cgroup("kubepods/podx", "cpu.shares") == "9999"
    # non-cacheable forces the write through
    assert ex.update(up, cacheable=False)
    assert host.read_cgroup("kubepods/podx", "cpu.shares") == "512"


def test_leveled_update_shrink_cpuset(host):
    """Shrinking parent+child cpusets: merge pass keeps the parent a
    superset while children still reference old cpus (executor.go:32-42)."""
    host.make_cgroup("kubepods/besteffort", {"cpuset.cpus": "0-7"})
    host.make_cgroup("kubepods/besteffort/podx", {"cpuset.cpus": "0-7"})
    ex = Executor(host)
    n = ex.leveled_update_batch([
        CgroupUpdate("kubepods/besteffort", "cpuset.cpus", "0-3"),
        CgroupUpdate("kubepods/besteffort/podx", "cpuset.cpus", "2-3"),
    ])
    assert n == 2
    assert host.read_cgroup("kubepods/besteffort", "cpuset.cpus") == "0-3"
    assert host.read_cgroup("kubepods/besteffort/podx", "cpuset.cpus") == "2-3"


def test_leveled_update_memory_min(host):
    host.make_cgroup("kubepods", {"memory.min": "100"})
    host.make_cgroup("kubepods/podx", {"memory.min": "100"})
    ex = Executor(host)
    ex.leveled_update_batch([
        CgroupUpdate("kubepods/podx", "memory.min", "50"),
        CgroupUpdate("kubepods", "memory.min", "50"),
    ])
    assert host.read_cgroup("kubepods", "memory.min") == "50"
    assert host.read_cgroup("kubepods/podx", "memory.min") == "50"


# --- metriccache ------------------------------------------------------------

def test_metriccache_aggregations():
    cache = mc.MetricCache()
    for i in range(100):
        cache.append(mc.NODE_CPU_USAGE, float(i), float(i))
    assert cache.query(mc.NODE_CPU_USAGE, 0, 99, agg="avg") == pytest.approx(49.5)
    assert cache.query(mc.NODE_CPU_USAGE, 0, 99, agg="p50") == pytest.approx(49.5)
    assert cache.query(mc.NODE_CPU_USAGE, 0, 99, agg="p90") == pytest.approx(
        np.percentile(np.arange(100.0), 90))
    assert cache.query(mc.NODE_CPU_USAGE, 0, 99, agg="latest") == 99.0
    assert cache.query(mc.NODE_CPU_USAGE, 0, 99, agg="count") == 100.0
    # windowing
    assert cache.query(mc.NODE_CPU_USAGE, 90, 99, agg="avg") == pytest.approx(94.5)
    # unknown series
    assert cache.query(mc.POD_CPU_USAGE, 0, 99, {"pod_uid": "x"}) is None


def test_metriccache_ring_eviction():
    cache = mc.MetricCache(capacity_per_series=10)
    for i in range(25):
        cache.append(mc.NODE_CPU_USAGE, float(i), float(i))
    # only the last 10 survive
    assert cache.query(mc.NODE_CPU_USAGE, 0, 100, agg="count") == 10.0
    assert cache.query(mc.NODE_CPU_USAGE, 0, 100, agg="avg") == pytest.approx(19.5)


def test_metriccache_label_fanout():
    cache = mc.MetricCache()
    cache.append(mc.POD_CPU_USAGE, 1.0, 0.5, {"pod_uid": "a"})
    cache.append(mc.POD_CPU_USAGE, 1.0, 1.5, {"pod_uid": "b"})
    got = cache.query_all(mc.POD_CPU_USAGE, 0, 2)
    assert len(got) == 2
    assert sum(got.values()) == pytest.approx(2.0)


# --- collectors → NodeMetric report ----------------------------------------

def _make_pod(uid, qos=QoSClass.LS, priority=9500):
    return PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(uid=uid, name=uid, namespace="default"),
        requests={ResourceKind.CPU: 1000.0, ResourceKind.MEMORY: 1024.0},
        qos_label="LS" if qos == QoSClass.LS else qos.name,
        priority=priority))


def test_collectors_end_to_end(host):
    """Kernel counters -> collectors -> cache -> NodeMetric report."""
    cache = mc.MetricCache()
    informer = StatesInformer()
    informer.set_node(api.Node(
        meta=api.ObjectMeta(name="node-1"),
        allocatable={ResourceKind.CPU: 8000.0, ResourceKind.MEMORY: 16384.0}))
    pod = _make_pod("pod-a")
    host.make_cgroup(pod.cgroup_dir)
    informer.set_pods([pod])
    adv = default_advisor(host, cache, informer)

    # t=0 baseline
    adv.collect_once(now=0.0)
    # advance 10s: 4 of 8 cpus busy => 40 busy ticks vs 40 idle... ticks are
    # aggregate across cpus: total ticks delta = 8 cpus * 10s * 100Hz = 8000
    host.advance_cpu(busy_ticks=4000, idle_ticks=4000)
    host.set_meminfo(available=12 << 30)
    # pod used 2 cores for 10s = 2e10 ns
    host.set_cgroup_cpu_ns(pod.cgroup_dir, 20_000_000_000)
    host.set_cgroup_memory(pod.cgroup_dir, 3 << 30, inactive_file=1 << 30)
    adv.collect_once(now=10.0)

    assert cache.query(mc.NODE_CPU_USAGE, 0, 11, agg="latest") == pytest.approx(4.0)
    assert cache.query(mc.NODE_MEMORY_USAGE, 0, 11, agg="latest") == pytest.approx(
        float(4 << 30))
    assert cache.query(mc.POD_CPU_USAGE, 0, 11, {"pod_uid": "pod-a"},
                       "latest") == pytest.approx(2.0)
    assert cache.query(mc.POD_MEMORY_USAGE, 0, 11, {"pod_uid": "pod-a"},
                       "latest") == pytest.approx(float(2 << 30))
    # sys = node - pods = 2 cores
    assert cache.query(mc.SYS_CPU_USAGE, 0, 11, agg="latest") == pytest.approx(2.0)

    reporter = NodeMetricReporter(informer, cache, CollectPolicy())
    nm = reporter.collect(now=11.0)
    assert nm is not None and nm.node_name == "node-1"
    assert nm.node_usage[ResourceKind.CPU] == pytest.approx(4000.0)   # milli
    # memory averaged over the window: samples 0 GiB (t=0) and 4 GiB (t=10)
    assert nm.node_usage[ResourceKind.MEMORY] == pytest.approx(2048.0)  # MiB
    assert len(nm.pods_metric) == 1
    assert nm.pods_metric[0].usage[ResourceKind.CPU] == pytest.approx(2000.0)
    assert nm.aggregated, "percentile windows populated"
    assert "p90" in nm.aggregated[0].usages


def test_be_collector(host):
    cache = mc.MetricCache()
    from koordinator_tpu.koordlet.metricsadvisor import BEResourceCollector
    c = BEResourceCollector(host, cache)
    c.collect(now=0.0)
    host.set_cgroup_cpu_ns("kubepods/besteffort", 5_000_000_000)
    c.collect(now=10.0)
    assert cache.query(mc.BE_CPU_USAGE, 0, 11, agg="latest") == pytest.approx(0.5)


def test_psi_collector(host):
    cache = mc.MetricCache()
    informer = StatesInformer()
    informer.set_pods([])
    host.set_psi("kubepods", "cpu", some_avg10=12.5)
    from koordinator_tpu.koordlet.metricsadvisor import PSICollector
    PSICollector(host, cache, informer).collect(now=1.0)
    assert cache.query(mc.PSI_CPU_SOME_AVG10, 0, 2,
                       {"cgroup": "kubepods"}, "latest") == 12.5


def test_reporter_requires_metrics(host):
    informer = StatesInformer()
    informer.set_node(api.Node(meta=api.ObjectMeta(name="n")))
    reporter = NodeMetricReporter(informer, mc.MetricCache())
    assert reporter.collect(now=1.0) is None


def test_cgroup_v2_value_translation(tmp_path):
    """v2 files use different value syntax: cpu.max pairs, cpu.weight
    scale, memory 'max' sentinel — logical values stay v1-convention."""
    host = FakeHost(str(tmp_path), cgroup_version=system.CgroupVersion.V2)
    host.make_cgroup("kubepods/podx")
    # quota: unlimited reads back as -1
    assert host.read_cgroup("kubepods/podx", "cpu.cfs_quota_us") == "-1"
    host.write_cgroup("kubepods/podx", "cpu.cfs_quota_us", "250000")
    raw = host.read(host.cgroup_file("kubepods/podx", "cpu.cfs_quota_us"))
    assert raw.strip() == "250000 100000"
    assert host.read_cgroup("kubepods/podx", "cpu.cfs_quota_us") == "250000"
    # period write preserves quota
    host.write_cgroup("kubepods/podx", "cpu.cfs_period_us", "50000")
    assert host.read(host.cgroup_file(
        "kubepods/podx", "cpu.cfs_period_us")).strip() == "250000 50000"
    # back to unlimited
    host.write_cgroup("kubepods/podx", "cpu.cfs_quota_us", "-1")
    assert host.read_cgroup("kubepods/podx", "cpu.cfs_quota_us") == "-1"
    # shares <-> weight (kernel formula); 1024 shares ~ weight 39
    host.write_cgroup("kubepods/podx", "cpu.shares", "1024")
    assert host.read(host.cgroup_file(
        "kubepods/podx", "cpu.shares")).strip() == "39"
    back = int(host.read_cgroup("kubepods/podx", "cpu.shares"))
    assert abs(back - 1024) < 30  # integer rounding on the round trip
    # memory unlimited sentinel
    host.write_cgroup("kubepods/podx", "memory.limit_in_bytes", "-1")
    assert host.read(host.cgroup_file(
        "kubepods/podx", "memory.limit_in_bytes")).strip() == "max"
    assert host.read_cgroup("kubepods/podx", "memory.limit_in_bytes") == "-1"


def test_write_does_not_create_ghost_cgroups(host):
    """A write to a vanished pod cgroup fails (and is audited) instead of
    mkdir-ing a ghost cgroup."""
    from koordinator_tpu.koordlet.resourceexecutor import CgroupUpdate, Executor
    ex = Executor(host)
    up = CgroupUpdate("kubepods/podgone", "cpu.shares", "512")
    assert not ex.update(up, cacheable=False)
    import os
    assert not os.path.exists(
        os.path.dirname(host.cgroup_file("kubepods/podgone", "cpu.shares")))
