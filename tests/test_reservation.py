"""Reservation restore/consume semantics (plugins/reservation/,
transformer.go:240-291, plugin.go:509-613).

Invariants tested:
- reserved capacity is pre-charged to node requested and unusable by
  non-owner pods;
- a matching pod lands on the reservation's node without growing node
  requested, and the reservation's free capacity shrinks;
- AllocateOnce admits exactly one (highest-priority) consumer and is then
  exhausted; later matches schedule normally;
- shared (allocateOnce=false) reservations admit consumers in priority
  order up to free capacity;
- gang Permit rollback returns consumed reservation capacity.
"""

import numpy as np

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import (
    Node, NodeMetric, ObjectMeta, Pod, PodGroup, Reservation,
)
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot.builder import SnapshotBuilder

NOW = 1_700_000_000.0
CFG = loadaware.LoadAwareConfig.make()


def two_node_builder(cpu=10_000.0, mem=20_480.0):
    b = SnapshotBuilder(max_nodes=2)
    for i in range(2):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: cpu, RK.MEMORY: mem}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW - 2,
                                     node_usage={RK.CPU: 0.0, RK.MEMORY: 0.0}))
    return b


def owned_pod(name, cpu, mem, priority=9100, labels=None, gang=""):
    return Pod(meta=ObjectMeta(name=name,
                               labels=labels or {"team": "a"}),
               requests={RK.CPU: cpu, RK.MEMORY: mem},
               priority=priority, gang_name=gang)


def reserve(name, cpu, mem, node="n0", once=True):
    return Reservation(meta=ObjectMeta(name=name),
                       requests={RK.CPU: cpu, RK.MEMORY: mem},
                       owner_label_selector={"team": "a"},
                       allocate_once=once, node_name=node, phase="Available")


def run(b, pods, **kw):
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(pods, ctx)
    return snap, core.schedule_batch(snap, batch, CFG,
                                     **{"num_rounds": 3, **kw})


def test_reserved_capacity_blocked_for_non_owners():
    # n0 fully reserved; a non-owner pod must land on n1.
    b = two_node_builder()
    b.add_reservation(reserve("r0", 10_000, 20_480))
    stranger = owned_pod("s", 8_000, 8_192, labels={"team": "b"})
    snap, res = run(b, [stranger])
    assert int(res.assignment[0]) == 1
    # pre-charge visible in the snapshot
    np.testing.assert_allclose(np.asarray(snap.nodes.requested)[0, int(RK.CPU)],
                               10_000.0)


def test_matching_pod_consumes_without_recharging_node():
    b = two_node_builder()
    b.add_reservation(reserve("r0", 6_000, 8_192))
    pod = owned_pod("p", 4_000, 4_096)
    snap, res = run(b, [pod])
    assert int(res.assignment[0]) == 0
    # node requested unchanged: covered by the reservation's pre-charge
    np.testing.assert_allclose(np.asarray(res.snapshot.nodes.requested),
                               np.asarray(snap.nodes.requested), atol=0.5)
    free = np.asarray(res.snapshot.reservations.free)[0]
    # AllocateOnce: exhausted after its single consumer (valid=False gates
    # admission; the remainder is kept so forget can restore it exactly)
    assert free[int(RK.CPU)] == 2_000.0
    assert not bool(np.asarray(res.snapshot.reservations.valid)[0])
    assert float(res.chosen_score[0]) == core.MAX_NODE_SCORE


def test_allocate_once_single_highest_priority_consumer():
    b = two_node_builder()
    b.add_reservation(reserve("r0", 6_000, 8_192))
    lo = owned_pod("lo", 2_000, 2_048, priority=9001)
    hi = owned_pod("hi", 2_000, 2_048, priority=9500)
    snap, res = run(b, [lo, hi])
    a = np.asarray(res.assignment)
    assert a[1] == 0  # hi consumed the reservation
    assert a[0] >= 0  # lo scheduled normally elsewhere/same node free space
    # only hi skipped the node charge
    req = np.asarray(res.snapshot.nodes.requested)
    base = np.asarray(snap.nodes.requested)
    added = req.sum(0) - base.sum(0)
    np.testing.assert_allclose(added[int(RK.CPU)], 2_000.0, atol=0.5)


def test_shared_reservation_priority_order_fill():
    b = two_node_builder()
    b.add_reservation(reserve("r0", 5_000, 20_480, once=False))
    pods = [owned_pod(f"p{i}", 2_000, 1_024, priority=9000 + i)
            for i in range(4)]  # p3 > p2 > p1 > p0; only two fit in 5000m
    snap, res = run(b, pods)
    a = np.asarray(res.assignment)
    # the two highest-priority owners consume; others fall through to
    # normal scheduling (may still land anywhere with spare capacity)
    free = np.asarray(res.snapshot.reservations.free)[0]
    np.testing.assert_allclose(free[int(RK.CPU)], 1_000.0, atol=0.5)
    assert a[3] == 0 and a[2] == 0
    # node requested grew only by the fall-through pods placed on n0
    req_cpu = np.asarray(res.snapshot.nodes.requested)[0, int(RK.CPU)]
    base_cpu = np.asarray(snap.nodes.requested)[0, int(RK.CPU)]
    fallthrough_on_n0 = sum(2_000.0 for i in (0, 1) if a[i] == 0)
    np.testing.assert_allclose(req_cpu - base_cpu, fallthrough_on_n0, atol=0.5)


def test_gang_rollback_returns_reservation_capacity():
    # strict gang of 3, but cluster only fits the reservation consumer ->
    # whole gang revoked, reservation free restored.
    b = SnapshotBuilder(max_nodes=1)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={RK.CPU: 4_000, RK.MEMORY: 4_096}))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW - 2,
                                 node_usage={RK.CPU: 0.0}))
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=3))
    b.add_reservation(reserve("r0", 4_000, 4_096))
    pods = [owned_pod(f"p{i}", 3_000, 3_072, gang="g") for i in range(3)]
    snap, res = run(b, pods)
    a = np.asarray(res.assignment)
    assert (a == -1).all()
    free = np.asarray(res.snapshot.reservations.free)[0]
    np.testing.assert_allclose(free[int(RK.CPU)], 4_000.0)
    assert bool(np.asarray(res.snapshot.reservations.valid)[0])


def test_allocate_once_quota_rejected_winner_does_not_block():
    # hi-priority owner's quota is exhausted; lo-priority owner must still
    # consume the AllocateOnce reservation (sequential semantics: each pod
    # tries in turn).
    from koordinator_tpu.api.types import ElasticQuota
    b = two_node_builder()
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"),
                             max={RK.CPU: 20_000, RK.MEMORY: 40_960}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="full"), parent="root",
                             max={RK.CPU: 100, RK.MEMORY: 100}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="roomy"), parent="root",
                             max={RK.CPU: 10_000, RK.MEMORY: 10_240}))
    b.add_reservation(reserve("r0", 6_000, 8_192))
    hi = owned_pod("hi", 2_000, 2_048, priority=9500)
    hi.quota_name = "full"
    lo = owned_pod("lo", 2_000, 2_048, priority=9001)
    lo.quota_name = "roomy"
    snap, ctx = b.build(now=NOW)
    # runtime == max for this test (water-filling comes separately)
    snap = snap.replace(quotas=snap.quotas.replace(
        runtime=np.asarray(snap.quotas.max).copy()))
    batch = b.build_pod_batch([hi, lo], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=3)
    a = np.asarray(res.assignment)
    assert a[0] == -1          # hi blocked by quota everywhere
    assert a[1] == 0           # lo consumed the reservation on n0
    assert not bool(np.asarray(res.snapshot.reservations.valid)[0])


def test_shared_reservation_oversize_owner_does_not_block_smaller():
    # hi-priority owner requests more than the reservation's free capacity
    # (falls through to normal scheduling); the smaller lo-priority owner
    # must still consume — an eligible-but-unfitting pod is not charged
    # against the reservation.
    b = two_node_builder(cpu=20_000.0, mem=40_960.0)
    b.add_reservation(reserve("r0", 5_000, 20_480, once=False))
    hi = owned_pod("hi", 6_000, 2_048, priority=9500)
    lo = owned_pod("lo", 2_000, 2_048, priority=9001)
    snap, res = run(b, [hi, lo])
    a = np.asarray(res.assignment)
    assert a[0] >= 0 and a[1] == 0
    free = np.asarray(res.snapshot.reservations.free)[0]
    np.testing.assert_allclose(free[int(RK.CPU)], 3_000.0, atol=0.5)
    # hi was charged to the node, lo was not
    added = (np.asarray(res.snapshot.nodes.requested).sum(0)
             - np.asarray(snap.nodes.requested).sum(0))
    np.testing.assert_allclose(added[int(RK.CPU)], 6_000.0, atol=0.5)


def test_no_quota_priority_inversion_with_reservation():
    # quota has room for ONE pod; the hi-priority NON-owner pod must win the
    # quota over the lo-priority reservation consumer (sequential priority
    # order interleaves consumers with normal pods).
    from koordinator_tpu.api.types import ElasticQuota
    b = two_node_builder()
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="q"),
                             max={RK.CPU: 2_500, RK.MEMORY: 40_960}))
    b.add_reservation(reserve("r0", 6_000, 8_192))
    hi = owned_pod("hi", 2_000, 2_048, priority=9500, labels={"team": "b"})
    hi.quota_name = "q"
    lo = owned_pod("lo", 2_000, 2_048, priority=9001)
    lo.quota_name = "q"
    snap, ctx = b.build(now=NOW)
    snap = snap.replace(quotas=snap.quotas.replace(
        runtime=np.asarray(snap.quotas.max).copy()))
    batch = b.build_pod_batch([hi, lo], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=3)
    a = np.asarray(res.assignment)
    assert a[0] >= 0   # hi got the quota
    assert a[1] == -1  # lo (consumer) lost: quota exhausted by hi
    # reservation untouched
    free = np.asarray(res.snapshot.reservations.free)[0]
    np.testing.assert_allclose(free[int(RK.CPU)], 6_000.0)


def test_zero_reservation_capacity_schedules():
    # V=0 snapshots (max_reservations=0) must still schedule.
    b = SnapshotBuilder(max_nodes=2, max_reservations=0)
    for i in range(2):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 8_000, RK.MEMORY: 16_384}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW - 2,
                                     node_usage={RK.CPU: 0.0}))
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch([owned_pod("p", 2_000, 2_048)], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=2)
    assert int(res.assignment[0]) >= 0


# --- fine-grained restore: reserved GPU instances + NUMA cpuset -------------
# (transformer.go:240-291; deviceshare/nodenumaresource ReservationRestore)


def gpu_numa_builder():
    from koordinator_tpu.api.types import (
        Device, DeviceInfo, NodeResourceTopology, NUMAZone,
    )
    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=4)
    b.add_node(Node(
        meta=ObjectMeta(name="n0"),
        allocatable={RK.CPU: 16_000.0, RK.MEMORY: 32_768.0},
        topology=NodeResourceTopology(
            zones=[NUMAZone(cpus_milli=8_000.0, memory_mib=16_384.0)
                   for _ in range(2)])))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW - 2,
                                 node_usage={RK.CPU: 0.0}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=m, type="gpu",
                   resources={RK.GPU_CORE: 100.0, RK.GPU_MEMORY: 1000.0},
                   numa_node=m // 2)
        for m in range(4)]))
    return b


def test_consumer_gets_reserved_gpu_minors():
    # reservation holds minors 2,3 (zone 1); a non-owner GPU pod cannot
    # take them, the owner gets exactly those minors
    b = gpu_numa_builder()
    r = Reservation(meta=ObjectMeta(name="r0"),
                    requests={RK.CPU: 2_000.0, RK.MEMORY: 2_048.0,
                              RK.GPU_CORE: 200.0, RK.GPU_MEMORY: 2000.0},
                    owner_label_selector={"team": "a"},
                    allocate_once=True, node_name="n0", phase="Available",
                    allocated_gpu_minors=(2, 3))
    b.add_reservation(r)
    snap, ctx = b.build(now=NOW)
    # build moved the hold out of the node pool: minors 2,3 have no free
    gf = np.asarray(snap.devices.gpu_free)
    np.testing.assert_allclose(gf[0, 2:, 0], 0.0)
    rgf = np.asarray(snap.reservations.gpu_free)
    np.testing.assert_allclose(rgf[0, 2:, 0], 100.0)

    stranger = Pod(meta=ObjectMeta(name="x", labels={"team": "b"}),
                   requests={RK.CPU: 1_000.0, RK.MEMORY: 1_024.0,
                             RK.GPU_CORE: 300.0, RK.GPU_MEMORY: 3000.0},
                   priority=9500)
    owner = Pod(meta=ObjectMeta(name="o", labels={"team": "a"}),
                requests={RK.CPU: 1_000.0, RK.MEMORY: 1_024.0,
                          RK.GPU_CORE: 200.0, RK.GPU_MEMORY: 2000.0},
                priority=9100)
    batch = b.build_pod_batch([stranger, owner], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=3)
    a = np.asarray(res.assignment)
    take = np.asarray(res.gpu_take)
    # stranger needs 3 whole GPUs but only minors 0,1 are open -> rejected
    assert a[0] == -1
    # owner consumed the reservation and got exactly the reserved minors
    assert a[1] == 0
    assert take[1].tolist() == [False, False, True, True]
    rv = res.snapshot.reservations
    assert not bool(np.asarray(rv.valid)[0])  # AllocateOnce exhausted


def test_consumer_gets_reserved_zone_cpuset():
    # reservation holds a cpuset in zone 1; the CPU-bind owner lands on it
    # and its zone IS the reserved zone; node open zone capacity untouched
    b = gpu_numa_builder()
    r = Reservation(meta=ObjectMeta(name="r0"),
                    requests={RK.CPU: 4_000.0, RK.MEMORY: 4_096.0},
                    owner_label_selector={"team": "a"},
                    allocate_once=True, node_name="n0", phase="Available",
                    required_cpu_bind=True, allocated_numa_zone=1)
    b.add_reservation(r)
    snap, ctx = b.build(now=NOW)
    nf = np.asarray(snap.nodes.numa_free)[0]
    np.testing.assert_allclose(nf[1, 0], 4_000.0)  # 8000 - 4000 hold
    rnf = np.asarray(snap.reservations.numa_free)[0]
    np.testing.assert_allclose(rnf[1], [4_000.0, 4_096.0])

    owner = Pod(meta=ObjectMeta(name="o", labels={"team": "a"}),
                requests={RK.CPU: 3_000.0, RK.MEMORY: 2_048.0},
                priority=9100, qos_label="LSR", required_cpu_bind=True)
    batch = b.build_pod_batch([owner], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=3)
    assert int(res.assignment[0]) == 0
    assert int(res.numa_zone[0]) == 1          # the RESERVED zone
    take = np.asarray(res.numa_take[0])
    np.testing.assert_allclose(take[1], [3_000.0, 2_048.0])
    # node open pool untouched; the hold shrank instead
    nf2 = np.asarray(res.snapshot.nodes.numa_free)[0]
    np.testing.assert_allclose(nf2[1, 0], 4_000.0)
    # remainder is kept (valid=False gates admission; forget can restore)
    rnf2 = np.asarray(res.snapshot.reservations.numa_free)[0]
    np.testing.assert_allclose(rnf2[1], [1_000.0, 2_048.0])
    assert not bool(np.asarray(res.snapshot.reservations.valid)[0])


def test_shared_reservation_zone_hold_drains_across_consumers():
    b = gpu_numa_builder()
    r = Reservation(meta=ObjectMeta(name="r0"),
                    requests={RK.CPU: 4_000.0, RK.MEMORY: 4_096.0},
                    owner_label_selector={"team": "a"},
                    allocate_once=False, node_name="n0", phase="Available",
                    required_cpu_bind=True, allocated_numa_zone=0)
    b.add_reservation(r)
    pods = [Pod(meta=ObjectMeta(name=f"o{i}", labels={"team": "a"}),
                requests={RK.CPU: 1_500.0, RK.MEMORY: 1_024.0},
                priority=9500 - i, qos_label="LSR", required_cpu_bind=True)
            for i in range(3)]
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=3)
    a = np.asarray(res.assignment)
    z = np.asarray(res.numa_zone)
    assert (a == 0).all()
    # first two drain the hold (2x1500 <= 4000, third 1500 does not fit
    # the remaining 1000) -> third falls to the node's open zone pool
    assert z[0] == 0 and z[1] == 0
    rnf = np.asarray(res.snapshot.reservations.numa_free)[0]
    np.testing.assert_allclose(rnf[0, 0], 1_000.0)


def test_resize_reserve_pod_makes_ratio_concrete():
    """ResizePod (gated): a reserve pod requesting gpu-memory-ratio gets
    its Reservation spec rewritten to the CONCRETE core/memory of the
    chosen node's GPU model (deviceshare plugin.go:461-481)."""
    from koordinator_tpu.api.types import Device, DeviceInfo
    from koordinator_tpu.features import new_default_gate
    from koordinator_tpu.scheduler.bind import resize_reserve_pod
    from koordinator_tpu.scheduler.errorhandler import reserve_pod_for

    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=2)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={RK.CPU: 32000.0, RK.MEMORY: 64000.0}))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW,
                                 node_usage={}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=m, type="gpu",
                   resources={RK.GPU_CORE: 100.0, RK.GPU_MEMORY: 16000.0})
        for m in range(2)]))
    snap, ctx = b.build(now=NOW)
    r = Reservation(meta=ObjectMeta(name="r0", uid="u0"),
                    requests={RK.CPU: 1000.0, RK.MEMORY: 1024.0,
                              RK.GPU_CORE: 50.0},
                    gpu_memory_ratio=50.0)
    pod = reserve_pod_for(r)
    pod.gpu_memory_ratio = r.gpu_memory_ratio
    pod.priority = 9000
    batch = b.build_pod_batch([pod], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=2)
    assert int(np.asarray(res.assignment)[0]) == 0
    gate = new_default_gate()
    # gate off (default): spec untouched
    assert not resize_reserve_pod(snap, batch, res, 0, r, gate=gate)
    assert RK.GPU_MEMORY not in r.requests
    gate.set("ResizePod", True)
    assert resize_reserve_pod(snap, batch, res, 0, r, gate=gate)
    # ratio 50% of a 16000-MiB GPU = 8000 MiB, 50 core
    assert r.requests[RK.GPU_MEMORY] == 8000.0
    assert r.requests[RK.GPU_CORE] == 50.0
    assert r.gpu_memory_ratio == 0.0
