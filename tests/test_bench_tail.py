"""bench.py tail/cascade knob semantics (no device work — these pin the
host-side parsing and protocol selection that the heavy mesh tests rely
on).

The BENCH_MAX_TAIL_PASSES consolidation: the variable used to be read
TWICE with different semantics — once at import into a module constant
(post-import env changes invisible to it; an empty string crashed the
int() at import) and once as a raw truthiness check at run_northstar
(empty string flipped the full-gate default branch while the constant
kept the stale value). `bench.max_tail_passes` is now THE single
call-time parse; these tests pin its contract.
"""

import importlib

import pytest


@pytest.fixture()
def bench_mod(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    import bench
    return importlib.reload(bench)


def test_max_tail_passes_defaults(bench_mod, monkeypatch):
    monkeypatch.delenv("BENCH_MAX_TAIL_PASSES", raising=False)
    assert bench_mod.max_tail_passes(False) == 6
    # the narrower full-gate tail needs more passes to cover the same
    # straggler pool (3160 at the 100k capture > 6 x 512)
    assert bench_mod.max_tail_passes(True) == 10


def test_max_tail_passes_explicit_wins_both_paths(bench_mod, monkeypatch):
    # read at CALL time, not import time: this env var lands after the
    # module import and must still win on both paths
    monkeypatch.setenv("BENCH_MAX_TAIL_PASSES", "3")
    assert bench_mod.max_tail_passes(False) == 3
    assert bench_mod.max_tail_passes(True) == 3
    # 0 is the legitimate quick-run knob (skip the tail entirely)
    monkeypatch.setenv("BENCH_MAX_TAIL_PASSES", "0")
    assert bench_mod.max_tail_passes(False) == 0
    assert bench_mod.max_tail_passes(True) == 0
    # negative values clamp to 0 instead of producing a nonsense range
    monkeypatch.setenv("BENCH_MAX_TAIL_PASSES", "-2")
    assert bench_mod.max_tail_passes(True) == 0


def test_max_tail_passes_empty_string_is_unset(bench_mod, monkeypatch):
    # the old import-time `int(os.environ.get(...))` crashed on ""
    # while the run-time truthiness check treated it as unset; the
    # consolidated parse treats it as unset everywhere
    monkeypatch.setenv("BENCH_MAX_TAIL_PASSES", "")
    assert bench_mod.max_tail_passes(False) == 6
    assert bench_mod.max_tail_passes(True) == 10


def test_bench_has_single_max_tail_env_read(bench_mod):
    """Regression pin for the consolidation itself: exactly one source
    line reads the env var (the parse inside max_tail_passes)."""
    import inspect
    src = inspect.getsource(bench_mod)
    reads = [l for l in src.splitlines()
             if "BENCH_MAX_TAIL_PASSES" in l and "environ" in l]
    assert len(reads) == 1, reads


def test_stamped_line_always_carries_staleness(bench_mod):
    """The one constructor for surfaced stamped lines sets the full
    provenance set unconditionally (satellite: no stamped line without
    a stale marker ever again)."""
    out = bench_mod._stamped_line({"metric": "m", "value": 1.0},
                                  "2026-01-01T00:00:00+00:00",
                                  age=7200.0, stale_after=3600.0)
    assert out["stamped_capture"] is True
    assert out["stale_capture"] is True
    assert out["stamped_age_seconds"] == 7200
    fresh = bench_mod._stamped_line({"metric": "m"}, "t", age=10.0,
                                    stale_after=3600.0)
    assert fresh["stale_capture"] is False


# --- run_with_ladder: the bench's device-lost recovery rung (ISSUE 14) -----

def test_ladder_device_lost_retries_on_a_shrunk_device_set(bench_mod,
                                                           monkeypatch):
    """A DEVICE_LOST-classified failure retries with the device count
    shrunk by one, and the retried line carries the `recovered` stamp
    — the bench mirror of the service's mesh-shrink rung."""
    monkeypatch.delenv("BENCH_DEVICES", raising=False)
    calls = []

    def fake_run(chunk=None, degraded=None, num_devices=None,
                 recovered=None, **kw):
        calls.append((chunk, degraded, num_devices, recovered))
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: device lost; socket closed")
        return {"num_devices": num_devices, "recovered": recovered,
                "degraded": degraded}

    monkeypatch.setattr(bench_mod.jax, "devices", lambda: [0, 1, 2, 3])
    line = bench_mod.run_with_ladder(max_halvings=2, _run=fake_run)
    # 4 -> 3 -> 2 devices, each retry stamped as recovered
    assert [c[2] for c in calls] == [None, 3, 2]
    assert line["recovered"] == "device_lost:devices=2"
    assert line["num_devices"] == 2
    assert line["degraded"] is None


def test_ladder_oom_still_halves_the_chunk(bench_mod, monkeypatch):
    calls = []

    def fake_run(chunk=None, degraded=None, num_devices=None,
                 recovered=None, **kw):
        calls.append(chunk)
        if len(calls) < 2:
            raise RuntimeError("RESOURCE_EXHAUSTED: OOM")
        return {"chunk": chunk, "degraded": degraded,
                "recovered": recovered}

    line = bench_mod.run_with_ladder(max_halvings=2, chunk=8,
                                     _run=fake_run)
    assert calls == [8, 4]
    assert line["degraded"] == "resource_exhausted:chunk=4"
    assert line["recovered"] is None


def test_ladder_out_of_device_rungs_propagates(bench_mod, monkeypatch):
    def fake_run(chunk=None, degraded=None, num_devices=None,
                 recovered=None, **kw):
        raise RuntimeError("UNAVAILABLE: device lost; socket closed")

    monkeypatch.setattr(bench_mod.jax, "devices", lambda: [0])
    with pytest.raises(RuntimeError, match="device lost"):
        bench_mod.run_with_ladder(max_halvings=3, _run=fake_run)


# --- BENCH_COST=1: the flagship cost stamp (koordcost satellite) -----------

class _FakeMemStats:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 400
    temp_size_in_bytes = 300
    alias_size_in_bytes = 250
    generated_code_size_in_bytes = 0


class _FakeCompiled:
    """A device-free stand-in for jax's Compiled: the three methods
    costmodel.program_report reads, with known arithmetic."""

    def cost_analysis(self):
        # jax returns a LIST of per-computation dicts on CPU; the
        # stamp must read the first, and 'bytes accessed' has a space
        return [{"flops": 5000.0, "bytes accessed": 2000.0}]

    def memory_analysis(self):
        return _FakeMemStats()

    def as_text(self):
        return ('  %p.1 = f32[8]{0} parameter(0)\n'
                '  ROOT %add.2 = f32[8]{0} add(%p.1, %p.1), '
                'metadata={op_name="jit/koord/stage1_mask/add"}\n')


def test_flagship_stamp_keys_and_arithmetic():
    """The BENCH_COST stamp pins exactly the four bench-line keys, with
    hbm_peak_bytes = arg + out + tmp - alias (donation visible) and
    flops_per_pod = flops / P."""
    from koordinator_tpu.obs import costmodel

    stamp = costmodel.flagship_stamp(_FakeCompiled(), num_pods=100)
    assert set(stamp) == {"flops", "bytes_accessed", "hbm_peak_bytes",
                          "flops_per_pod"}
    assert stamp["flops"] == 5000.0
    assert stamp["bytes_accessed"] == 2000.0
    assert stamp["hbm_peak_bytes"] == 1000 + 400 + 300 - 250
    assert stamp["flops_per_pod"] == 50.0


def test_bench_cost_stamp_is_opt_in_and_spliced(bench_mod):
    """BENCH_COST is read at run time (one env read) and the stamp is
    spliced into the emitted line — absent entirely when off, so old
    trajectories and benchdiff joins see no phantom keys."""
    import inspect
    src = inspect.getsource(bench_mod)
    reads = [l for l in src.splitlines()
             if "BENCH_COST" in l and "environ" in l]
    assert len(reads) == 1, reads
    assert "**cost_stamp," in src
    assert "flagship_stamp" in src
