"""koordcost static accounting: the shared HLO attribution parser, the
per-program cost reports, and the drift gate's comparison semantics.

Everything here is device-free or compiles tiny throwaway programs —
the full registry walk (every contract + the flagship forms) runs as
the dedicated `tools/costcheck.py` ci.sh stage, and its self-test
mutation proof as another; only the gate's PURE logic (tolerances,
provenance, verdicts) is pinned at test speed.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

from koordinator_tpu.obs import costmodel, hloattrib
from koordinator_tpu.obs import phases as obs_phases

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- hloattrib: the one parser both views share -------------------------

SYNTH_HLO = """\
ENTRY %main (p.1: f32[64,32]) -> (f32[64,32], s32[64]) {
  %p.1 = f32[64,32]{1,0} parameter(0)
  %mul.2 = f32[64,32]{1,0} multiply(%p.1, %p.1), metadata={op_name="jit/koord/stage1_mask/mul"}
  %cvt.3 = bf16[64,32]{1,0} convert(%mul.2), metadata={op_name="jit/koord/stage1_mask/koord/topk_select/cvt"}
  %iota.4 = s32[64]{0} iota(), iota_dimension=0
  ROOT %tuple.5 = (f32[64,32]{1,0}, s32[64]{0}) tuple(%mul.2, %iota.4)
}
"""


def test_parse_instructions_bytes_and_innermost_scope():
    instrs = {i.name: i for i in hloattrib.parse_instructions(SYNTH_HLO)}
    # dtype width x element count, layout annotations ignored
    assert instrs["mul.2"].output_bytes == 64 * 32 * 4
    assert instrs["cvt.3"].output_bytes == 64 * 32 * 2
    # tuple result types sum their elements
    assert instrs["tuple.5"].output_bytes == 64 * 32 * 4 + 64 * 4
    # phase resolution: plain scope, no scope, innermost of nested
    assert instrs["mul.2"].phase == obs_phases.PHASE_STAGE1_MASK
    assert instrs["iota.4"].phase == hloattrib.UNATTRIBUTED
    # op_name records the scope PATH; the rightmost koord/ component is
    # the narrowest enclosing phase and must win
    assert instrs["cvt.3"].phase == obs_phases.PHASE_TOPK


def test_attribution_closure_and_coverage_on_synthetic_hlo():
    attribution = hloattrib.attribute_bytes(SYNTH_HLO)
    cov = hloattrib.coverage(attribution)
    # every parsed instruction lands in exactly one bucket
    assert cov["instructions_total"] == 5.0
    assert cov["instructions_mapped"] == 2.0
    assert cov["instruction_coverage"] == pytest.approx(0.4)
    total_b = sum(v["output_bytes"] for v in attribution.values())
    assert cov["output_bytes_total"] == float(total_b)
    # instruction_phases exposes only the mapped set (trace join map)
    mapping = hloattrib.instruction_phases(SYNTH_HLO)
    assert mapping == {"mul.2": obs_phases.PHASE_STAGE1_MASK,
                       "cvt.3": obs_phases.PHASE_TOPK}


def test_phase_of_event_two_step_join():
    instr2phase = {"fusion.9": obs_phases.PHASE_STAGE2_NUMA}
    # exact instruction-name join first (CPU captures)
    assert hloattrib.phase_of_event("fusion.9", [], instr2phase) \
        == obs_phases.PHASE_STAGE2_NUMA
    # scope-substring over args second (TPU-style captures), innermost
    # (longest) phase winning when scopes nest in the path
    hit = hloattrib.phase_of_event(
        "region", ["jit/koord/stage1_mask/koord/stage1_static_gates/x"],
        {})
    assert hit == obs_phases.PHASE_STAGE1_STATIC
    assert hloattrib.phase_of_event("add.1", ["nothing"], {}) is None


def test_trace_fullgate_uses_the_shared_parser():
    """The sampled view must join through obs.hloattrib — a private
    regex reappearing in trace_fullgate is exactly the drift this
    extraction exists to prevent."""
    with open(os.path.join(REPO, "tools", "trace_fullgate.py")) as f:
        src = f.read()
    assert "hloattrib.instruction_phases" in src
    assert "hloattrib.phase_of_event" in src
    assert "re.compile" not in src


# --- program_report on real (tiny) compiled programs --------------------

def _compile(fn, *avals, **jit_kw):
    return jax.jit(fn, **jit_kw).lower(*avals).compile()


def test_program_report_closure_on_a_scoped_program():
    def f(x):
        with jax.named_scope(obs_phases.PHASE_STAGE1_MASK):
            y = x * 2.0 + 1.0
        with jax.named_scope(obs_phases.PHASE_TOPK):
            z = jnp.sort(y)
        return y + z

    rep = costmodel.program_report(
        _compile(f, jax.ShapeDtypeStruct((64,), jnp.float32)))
    assert rep["flops"] > 0
    assert rep["bytes_accessed"] > 0
    # the named scopes actually reach op_name metadata
    assert obs_phases.PHASE_STAGE1_MASK in rep["phases"]
    # closure: per-phase attribution sums to the totals over the SAME
    # instruction set, unattributed bucket included
    assert sum(v["instructions"] for v in rep["phases"].values()) \
        == rep["hlo_instructions"]
    assert sum(v["output_bytes"] for v in rep["phases"].values()) \
        == rep["hlo_output_bytes"]
    assert rep["peak_bytes"] == (rep["argument_bytes"]
                                 + rep["output_bytes"]
                                 + rep["temp_bytes"]
                                 - rep["alias_bytes"])


def test_donation_visible_in_memory_analysis():
    """Donated inputs alias into the outputs and must show up as
    alias_bytes shrinking the static peak — the property the tail
    program's baseline entry relies on (buffer reuse is priced, not
    assumed)."""
    rep = costmodel.program_report(
        _compile(lambda x: x + 1.0,
                 jax.ShapeDtypeStruct((1024,), jnp.float32),
                 donate_argnums=0))
    assert rep["alias_bytes"] == 1024 * 4
    assert rep["peak_bytes"] < (rep["argument_bytes"]
                                + rep["output_bytes"]
                                + rep["temp_bytes"])


def test_flagship_stamp_normalizes_per_pod():
    def f(x):
        return x * 3.0

    compiled = _compile(f, jax.ShapeDtypeStruct((128,), jnp.float32))
    stamp = costmodel.flagship_stamp(compiled, num_pods=128)
    rep = costmodel.program_report(compiled)
    assert stamp["flops"] == rep["flops"]
    assert stamp["hbm_peak_bytes"] == float(rep["peak_bytes"])
    assert stamp["flops_per_pod"] == pytest.approx(rep["flops"] / 128)


def test_packing_report_prices_the_bf16_representation():
    """The packed snapshot must be strictly smaller than unpacked, with
    saved = unpacked - packed — this is the exact surface the costcheck
    self-test mutation (bf16 -> f32 upcast) moves."""
    rep = costmodel.packing_report()
    for key in ("packing/snapshot", "packing/pods"):
        entry = rep[key]
        assert entry["packed_bytes"] < entry["unpacked_bytes"]
        assert entry["saved_bytes"] == (entry["unpacked_bytes"]
                                        - entry["packed_bytes"])


# --- costcheck: baseline format, tolerances, verdicts -------------------

def _entry(**over):
    base = {"flops": 1000.0, "bytes_accessed": 500.0,
            "argument_bytes": 100, "output_bytes": 50, "temp_bytes": 30,
            "alias_bytes": 20, "peak_bytes": 160,
            "hlo_instructions": 40, "hlo_output_bytes": 2000}
    base.update(over)
    return base


def test_compare_entry_tolerance_and_exact_fields():
    from tools import costcheck

    # inside the 1% flops tolerance: no drift
    assert costcheck.compare_entry("p", _entry(),
                                   _entry(flops=1005.0)) == []
    # beyond it: drift, named field and magnitude
    drifts = costcheck.compare_entry("p", _entry(),
                                     _entry(flops=1100.0))
    assert len(drifts) == 1 and "flops" in drifts[0]
    # byte-exact fields have zero tolerance
    assert costcheck.compare_entry("p", _entry(),
                                   _entry(output_bytes=51))
    # lost donation gets the explicit callout
    drifts = costcheck.compare_entry(
        "p", _entry(), _entry(alias_bytes=0, peak_bytes=180))
    assert any("donation" in d for d in drifts)


def test_compare_flags_vanished_and_unstamped_programs():
    from tools import costcheck

    manifest = {"entries": {"a": _entry(), "b": _entry()}}
    problems = costcheck.compare(manifest, {"b": _entry(),
                                            "c": _entry()})
    joined = "\n".join(problems)
    assert "a" in joined      # vanished from the build
    assert "c" in joined      # present but not stamped
    assert costcheck.compare(manifest,
                             {"a": _entry(), "b": _entry()}) == []


def test_baseline_is_stamped_for_this_tree():
    """The checked-in manifest must carry the loud provenance triple
    and match the CURRENT contract fingerprint — a contract change
    without a restamp is exactly what the gate strict-fails on."""
    from koordinator_tpu.compilecache import keys
    from tools import costcheck

    with open(costcheck.baseline_path()) as f:
        manifest = json.load(f)
    assert manifest["version"] == costcheck.BASELINE_VERSION
    assert manifest["fingerprint"] == keys.contract_fingerprint()
    assert manifest["jax_version"] == jax.__version__
    assert manifest["entries"]


def test_mutation_anchor_still_present():
    """The self-test mutation plants a bf16 -> f32 upcast at a literal
    anchor in snapshot/packing.py; if the anchor drifts the self-test
    degrades to 'anchor not found' instead of proving anything."""
    from tools import costcheck

    path = os.path.join(REPO, "koordinator_tpu", "snapshot",
                        "packing.py")
    with open(path) as f:
        src = f.read()
    assert costcheck.PACKING_MUTATION_ANCHOR in src
    assert costcheck.PACKING_MUTATION_REPLACEMENT not in src


@pytest.mark.slow
def test_costcheck_packing_gate_passes():
    """Marked slow: tools/ci.sh runs the full costcheck gate as its own
    stage; this is the fast packing-only subset as a subprocess."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "costcheck.py"),
         "--only", "packing/"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
