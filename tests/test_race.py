"""koordrace battery: the guarded-by contract grammar, the race-guard
analyzer's per-code behavior over the fixture trees, repo-wide contract
totality with an EMPTY baseline, GB codes flowing through every output
format, and the Tier-B deterministic interleaving gate (scheduler
determinism fast; the full battery and the dual-tier mutation smoke
slow-marked, duplicating the CI stage)."""

import json
import os
import subprocess
import sys
import threading

import pytest

from koordinator_tpu.utils.sync import GUARD_VOCAB, guard_module, guarded_by
from tools import racecheck
from tools.lint.locks import guard_kind
from tools.lint.runner import REPO_ROOT, run_lint
from tools.racecheck import DeadlockError, DetScheduler, InstrumentedLock

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint", "race")


@pytest.fixture()
def empty_baseline(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text('{"suppressions": []}')
    return p


def _findings(tree, empty_baseline):
    new, suppressed = run_lint(os.path.join(FIXTURES, tree),
                               analyzers=["race-guard"],
                               baseline_path=str(empty_baseline))
    assert not suppressed
    return new


# --- contract grammar ----------------------------------------------------

def test_guard_kind_grammar():
    assert guard_kind("_lock") == "lock"
    assert guard_kind("commit_lock") == "lock"
    for vocab in GUARD_VOCAB:
        assert guard_kind(vocab) == "vocab"
    assert guard_kind("external:Owner._lock") == "external"
    assert guard_kind("external:pkg.Owner._lock") == "external"
    assert guard_kind("external:no_dot") == "bad"
    assert guard_kind("not an identifier!") == "bad"
    assert guard_kind("") == "bad"


def test_decorator_validates_at_decoration_time():
    @guarded_by(_x="_lock", _y="publish-once",
                _z="external:Owner._commit_lock")
    class Fine:
        pass

    assert Fine is not None
    with pytest.raises(ValueError, match="neither a lock attribute"):
        @guarded_by(_x="not an identifier!")
        class Bad:
            pass
    with pytest.raises(ValueError, match="empty contract"):
        @guarded_by()
        class Empty:
            pass
    with pytest.raises(ValueError, match="malformed external guard"):
        @guarded_by(_x="external:nodot")
        class BadExternal:
            pass


def test_duplicate_contract_rejected():
    @guarded_by(_a="_lock")
    class Once:
        pass

    with pytest.raises(ValueError, match="duplicate guarded_by"):
        guarded_by(_b="_lock")(Once)


def test_guard_module_requires_name():
    with pytest.raises(ValueError, match="module name required"):
        guard_module("", _x="_lock")


# --- analyzer per-code behavior over the fixtures ------------------------

def test_positive_fixture_keys(empty_baseline):
    """Each GB code fires at its designed site — keyed, so baseline
    fingerprints stay line-free."""
    got = {(f.code, f.key) for f in _findings("pos", empty_baseline)}
    assert ("GB001", "Accounts.bump:_count:write") in got
    assert ("GB001", "enqueue:_pending:read") in got
    assert ("GB002", "Accounts.reserve:_count:check-then-act") in got
    assert ("GB003", "Accounts.items:_items:escape") in got
    assert ("GB004", "NoContract:contract-missing") in got
    assert ("GB004", "Drifted:_missing:guard-unresolved") in got
    assert ("GB004", "DeadGuard:_qlock:guard-dead") in got
    assert ("GB005", "Malformed:_x:bad-guard") in got


def test_negative_fixture_silent(empty_baseline):
    """Inherited locks, entry-held helpers, unresolvable context
    managers, spanning locks, copy-outs, and the declaration-only
    vocabulary must all stay silent."""
    assert _findings("neg", empty_baseline) == []


def test_repo_contracts_total_with_empty_baseline(empty_baseline):
    """GB004 totality on the real tree: every lock-owning class and
    module declares its contract, every declared guard resolves and is
    practiced — with NOTHING frozen in a baseline."""
    new, _ = run_lint(REPO_ROOT, analyzers=["race-guard"],
                      baseline_path=str(empty_baseline))
    assert new == [], [f.render() for f in new]


# --- GB codes flow through every output format ---------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=600)


def test_gb_codes_in_sarif(tmp_path):
    bl = tmp_path / "b.json"
    bl.write_text('{"suppressions": []}')
    proc = _run_cli("--root", os.path.join(FIXTURES, "pos"),
                    "--baseline", str(bl),
                    "--analyzers", "race-guard", "--format", "sarif")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    run = doc["runs"][0]
    rules = {r["id"]: r for r in run["tool"]["driver"]["rules"]}
    hit = {r["ruleId"] for r in run["results"]}
    for code in ("GB001", "GB002", "GB003", "GB004", "GB005"):
        assert code in rules and code in hit, (code, sorted(hit))
    assert rules["GB001"]["name"] == "race-guard"
    assert "guarded-by" in rules["GB001"]["shortDescription"]["text"]


def test_gb_codes_in_github_annotations(tmp_path):
    bl = tmp_path / "b.json"
    bl.write_text('{"suppressions": []}')
    proc = _run_cli("--root", os.path.join(FIXTURES, "pos"),
                    "--baseline", str(bl),
                    "--analyzers", "race-guard", "--format", "github")
    assert proc.returncode == 1
    errors = [l for l in proc.stdout.splitlines()
              if l.startswith("::error ")]
    assert errors and all("[race-guard]" in l for l in errors)
    assert any("GB001" in l for l in errors)


# --- Tier B: scheduler + instrumented lock semantics ---------------------

def test_instrumented_lock_state_machine():
    sched = DetScheduler(seed=0)
    lk = InstrumentedLock(sched, "lk")
    rlk = InstrumentedLock(sched, "rlk", reentrant=True)
    with lk:
        with pytest.raises(DeadlockError, match="non-reentrant"):
            lk.acquire()
        contender = []
        t = threading.Thread(
            target=lambda: contender.append(lk.acquire(blocking=False)))
        t.start()
        t.join()
        assert contender == [False]
    with rlk:
        with rlk:
            pass
    assert rlk._owner is None
    lk.acquire()
    lk.release()
    with pytest.raises(RuntimeError, match="non-owner"):
        lk.release()


def test_scheduler_same_seed_same_schedule():
    """The determinism contract Tier B stands on: one seed is one
    schedule, replayable for debugging a red run."""
    f1, t1, _ = racecheck._run_one("trace", seed=11, mode="random")
    f2, t2, _ = racecheck._run_one("trace", seed=11, mode="random")
    assert f1 == [] and f2 == []
    assert t1 and t1 == t2
    _, rr1, _ = racecheck._run_one("trace", seed=0, mode="rr")
    _, rr2, _ = racecheck._run_one("trace", seed=0, mode="rr")
    assert rr1 == rr2


def test_scheduler_detects_starved_lock():
    """A worker spinning on a lock no live thread can release must be
    reported as a deadlock, not hung on."""
    sched = DetScheduler(seed=0)
    lk = InstrumentedLock(sched, "orphan")
    lk.acquire()  # main thread holds it; never releases

    def worker():
        with lk:
            pass

    sched.spawn(worker, "starved")
    with pytest.raises(RuntimeError, match="no other live thread"):
        sched.run(timeout=30)


def test_bounded_preemption_budget_respected():
    # the budget bounds forced preemptions only; contention yields
    # ("block") and exits stay free
    fails, trace, _ = racecheck._run_one("metrics", 5, "random", 3)
    assert fails == []
    preempts = [t for t in trace if t[0] == "preempt"]
    assert len(preempts) <= 3


def test_fast_scenarios_green():
    """The two jit-free scenarios stay green inline (the full battery
    is the slow-marked twin below)."""
    for name in ("trace", "metrics"):
        fails, trace, points = racecheck._run_one(name, 0, "rr")
        assert fails == [], fails
        assert points > 0 and trace


# --- the full gate + the dual-tier mutation smoke (slow) -----------------

@pytest.mark.slow
def test_racecheck_full_battery_green():
    assert racecheck.run_all(seed=0, n_seeds=3) == 0


@pytest.mark.slow
def test_dual_tier_race_mutation_smoke():
    """Both koordrace tiers prove themselves live AND complementary: a
    planted dropped-lock ingest races only the dynamic explorer can
    see, a planted cold-path unlock only the static contracts can."""
    assert racecheck.self_test_mutation() == 0
