"""Test harness: pin the platform *before* jax imports — by default an
8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (the driver separately dry-runs `__graft_entry__.
dryrun_multichip`). Mirrors the reference's hermetic strategy (SURVEY.md 4):
no cluster needed — fake state layers stand in for kernel/apiserver.
KOORD_TEST_PLATFORM overrides the pin for targeted hardware-validation
runs (see below); the default suite stays hermetically CPU-pinned.
"""

import os
import sys

# KOORD_TEST_PLATFORM escapes the CPU pin for hardware-validation runs
# (e.g. KOORD_TEST_PLATFORM=axon pytest tests/test_approx_topk.py pins
# the approx_max_k quality bound where it actually binds — on the TPU
# partial reduction the CPU lowering collapses to exact top_k). Meant
# for targeted files, not the whole suite: 8-device mesh tests only
# hold on the virtual CPU platform. `or` so an EMPTY value still pins
# cpu rather than silently enabling JAX auto-detect.
_plat = os.environ.get("KOORD_TEST_PLATFORM") or "cpu"
os.environ["JAX_PLATFORMS"] = _plat
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The env var alone is not enough on hosts whose site config pins
# jax_platforms (e.g. to a TPU tunnel platform); force the resolved
# platform explicitly.
jax.config.update("jax_platforms", _plat)
jax.config.update("jax_enable_x64", False)

# NO persistent compilation cache. It was enabled through round 3
# (/tmp/koord_tpu_jax_cache) and cut warm suite time to ~4 min, but the
# CI hosts live-migrate/resize between runs (observed mid-round-4:
# nproc and XLA's machine-feature probe changed), and XLA:CPU AOT
# artifacts deserialized on a different machine than the one that wrote
# them SEGFAULT the test process (jax compilation_cache
# get_executable_and_time) — even a CPU-feature-fingerprint-keyed dir
# was not sufficient. In-process compiles are always safe; paying the
# cold compile per run is the only configuration that cannot crash.
jax.config.update("jax_compilation_cache_dir", None)


def pytest_configure(config):
    # the tier-1 battery (ROADMAP.md / tools/ci.sh) runs -m 'not slow';
    # register the mark so --strict-markers stays an option and no
    # UnknownMarkWarning fires
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 battery; the equivalent check "
        "runs as a dedicated tools/ci.sh stage on every push")
