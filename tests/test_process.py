"""Process shape: entry points, lease-file leader election, graceful
shutdown, and the control-plane trio running in-process against fakes
(the cmd/ layer; reference: cmd/koord-manager/main.go leader election +
the five binaries' flag surface)."""

import threading
import time

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.cmd import FileLeaseLock, LeaderElector, StopHandle
from koordinator_tpu.cmd import descheduler as cmd_descheduler
from koordinator_tpu.cmd import koordlet as cmd_koordlet
from koordinator_tpu.cmd import manager as cmd_manager
from koordinator_tpu.cmd import scheduler as cmd_scheduler
from koordinator_tpu.descheduler import (
    LowNodeLoad,
    LowNodeLoadArgs,
    RecordingEvictor,
)
from koordinator_tpu.descheduler.framework import CycleRunner
from koordinator_tpu.koordlet.testing import FakeHost


# --- lease lock -------------------------------------------------------------

def test_lease_acquire_renew_release(tmp_path):
    lock = FileLeaseLock(str(tmp_path / "a.lease"), lease_duration=10.0)
    assert lock.try_acquire("p1", now=0.0)
    assert lock.holder(now=1.0) == "p1"
    # a contender cannot take a live lease
    assert not lock.try_acquire("p2", now=5.0)
    # the holder renews; contender still locked out past the original TTL
    assert lock.renew("p1", now=9.0)
    assert not lock.try_acquire("p2", now=12.0)
    # release frees it immediately
    lock.release("p1")
    assert lock.holder(now=12.0) == ""
    assert lock.try_acquire("p2", now=12.0)


def test_lease_steal_after_expiry(tmp_path):
    lock = FileLeaseLock(str(tmp_path / "a.lease"), lease_duration=10.0)
    assert lock.try_acquire("p1", now=0.0)
    # p1 dies silently; p2 must wait out the TTL then steal
    assert not lock.try_acquire("p2", now=9.9)
    assert lock.try_acquire("p2", now=10.1)
    # p1's renew now fails — it knows it lost leadership
    assert not lock.renew("p1", now=10.2)


def test_elector_single_active_and_failover(tmp_path):
    """Two electors on one lease: exactly one leads; when it stops, the
    other takes over."""
    path = str(tmp_path / "el.lease")
    leads = {"a": 0, "b": 0}
    active = []
    stop_a, stop_b = threading.Event(), threading.Event()

    def make(name, stop_ev):
        lock = FileLeaseLock(path, lease_duration=0.5)
        el = LeaderElector(lock, name, retry_period=0.02)

        def lead(should_stop):
            leads[name] += 1
            active.append(name)
            while not should_stop():
                time.sleep(0.01)
            active.remove(name)

        t = threading.Thread(target=el.run,
                             args=(lead, stop_ev.is_set), daemon=True)
        t.start()
        return t

    ta = make("a", stop_a)
    time.sleep(0.1)
    tb = make("b", stop_b)
    time.sleep(0.2)
    assert active == ["a"] and leads["a"] == 1 and leads["b"] == 0

    stop_a.set()
    ta.join(timeout=5.0)
    # b takes over once a releases
    deadline = time.monotonic() + 5.0
    while not active and time.monotonic() < deadline:
        time.sleep(0.01)
    assert active == ["b"]
    stop_b.set()
    tb.join(timeout=5.0)
    assert not active


# --- manager process --------------------------------------------------------

class FakeSource:
    def __init__(self, nodes, metrics, profiles=()):
        self._nodes = nodes
        self._metrics = metrics
        self._profiles = list(profiles)

    def nodes(self):
        return self._nodes

    def node_metrics(self):
        return self._metrics

    def pods_by_node(self):
        return {}

    def quota_profiles(self):
        return self._profiles


def mk_cluster(n=3, metric_time=1e9):
    nodes = [api.Node(meta=api.ObjectMeta(name=f"n{i}",
                                          labels={"pool": "colo"}),
                      allocatable={RK.CPU: 64000.0, RK.MEMORY: 256 * 1024.0})
             for i in range(n)]
    metrics = {n.meta.name: api.NodeMetric(
        node_name=n.meta.name, update_time=metric_time,
        node_usage={RK.CPU: 8000.0, RK.MEMORY: 32 * 1024.0})
        for n in nodes}
    return nodes, metrics


def test_manager_tick_reconciles_everything(tmp_path):
    nodes, metrics = mk_cluster()
    profile = api.ElasticQuotaProfile(
        meta=api.ObjectMeta(name="colo"), quota_name="colo-root",
        node_selector={"pool": "colo"})
    src = FakeSource(nodes, metrics, [profile])
    proc = cmd_manager.ManagerProcess(
        cmd_manager.ManagerConfig(lease_file=str(tmp_path / "m.lease")),
        src)
    proc.tick(now=1e9)
    # batch overcommit landed on the nodes
    assert all(n.allocatable.get(RK.BATCH_CPU, 0) > 0 for n in nodes)
    # NodeSLO rendered per node
    assert set(proc.sink.node_slos) == {n.meta.name for n in nodes}
    # quota tree provisioned from the profile
    root = proc.quota_reconciler.quotas["colo-root"]
    assert root.min[RK.CPU] == sum(64000.0 for _ in nodes)


def test_manager_leader_election_single_active(tmp_path):
    """Two manager replicas, one lease: only the leader ticks."""
    nodes, metrics = mk_cluster()
    src = FakeSource(nodes, metrics)
    lease = str(tmp_path / "m.lease")

    def mk(ident):
        # identity must be explicit in-process: both replicas share a pid,
        # so default_identity() would collide and both would "hold" it
        return cmd_manager.ManagerProcess(
            cmd_manager.ManagerConfig(
                lease_file=lease, reconcile_interval_seconds=0.02,
                lease_duration_seconds=1.0, retry_period_seconds=0.02,
                identity=ident),
            src)

    m1, m2 = mk("m1"), mk("m2")
    stop = threading.Event()
    t1 = threading.Thread(target=m1.run, args=(stop.is_set,), daemon=True)
    t1.start()
    time.sleep(0.1)
    t2 = threading.Thread(target=m2.run, args=(stop.is_set,), daemon=True)
    t2.start()
    time.sleep(0.3)
    stop.set()
    t1.join(timeout=5.0)
    t2.join(timeout=5.0)
    assert m1.ticks > 0
    assert m2.ticks == 0, "standby replica must not reconcile"


# --- descheduler process ----------------------------------------------------

def test_descheduler_process_cycles(tmp_path):
    nodes, metrics = mk_cluster()
    evictor = RecordingEvictor()
    runner = CycleRunner(limiters=[evictor.limiter])
    proc = cmd_descheduler.DeschedulerProcess(
        cmd_descheduler.DeschedulerConfig(
            lease_file=str(tmp_path / "d.lease"),
            descheduling_interval_seconds=0.02,
            retry_period_seconds=0.02),
        runner, get_nodes=lambda: nodes)
    stop = threading.Event()
    t = threading.Thread(target=proc.run, args=(stop.is_set,), daemon=True)
    t.start()
    time.sleep(0.25)
    stop.set()
    t.join(timeout=5.0)
    assert proc.cycles >= 2


# --- scheduler + koordlet entry points --------------------------------------

def test_scheduler_process_serves_sidecar(tmp_path):
    """--sidecar-socket makes the binary serve the RPC edge; a pod batch
    scheduled over the socket lands assignments."""
    import numpy as np

    from koordinator_tpu.scheduler.sidecar import SchedulerSidecarClient
    from koordinator_tpu.snapshot import SnapshotBuilder

    sock = str(tmp_path / "sched.sock")
    proc = cmd_scheduler.build(
        ["--metrics-port", "-1", "--sidecar-socket", sock,
         "--lease-file", str(tmp_path / "s.lease")])
    stop = threading.Event()
    t = threading.Thread(target=proc.run, args=(stop.is_set,), daemon=True)
    t.start()
    try:
        b = SnapshotBuilder(max_nodes=2)
        b.add_node(api.Node(meta=api.ObjectMeta(name="n0"),
                            allocatable={RK.CPU: 8000.0,
                                         RK.MEMORY: 16384.0}))
        b.set_node_metric(api.NodeMetric(node_name="n0", update_time=1e9,
                                         node_usage={}))
        snap, ctx = b.build(now=1e9)
        pod = api.Pod(meta=api.ObjectMeta(name="p"), priority=9000,
                      requests={RK.CPU: 1000.0, RK.MEMORY: 256.0})
        # the socket binds once the process serves
        import os
        deadline = time.monotonic() + 10
        while not os.path.exists(sock) and time.monotonic() < deadline:
            time.sleep(0.01)
        client = SchedulerSidecarClient(sock, timeout=120.0)
        client.publish(snap)
        out = client.schedule(b.build_pod_batch([pod], ctx))
        assert int(np.asarray(out["assignment"])[0]) == 0
    finally:
        stop.set()
        t.join(timeout=10)
    # stepping down released the socket
    import os
    assert not os.path.exists(sock)


def test_scheduler_process_serves_metrics(tmp_path):
    import json
    import urllib.request

    proc = cmd_scheduler.build(
        ["--metrics-port", "0",
         "--lease-file", str(tmp_path / "s.lease")])
    stop = threading.Event()
    t = threading.Thread(target=proc.run, args=(stop.is_set,), daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{proc.server.port}/apis/v1/plugins"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert "scheduler" in json.loads(r.read())["plugins"]
    finally:
        stop.set()
        t.join(timeout=5.0)


def test_koordlet_main_builds_from_flags(tmp_path):
    host = FakeHost(str(tmp_path / "hostroot"))
    daemon = cmd_koordlet.build(
        ["--feature-gates", "ColdPageCollector=true",
         "--report-interval-seconds", "30"], host=host)
    assert daemon.cfg.report_interval_seconds == 30.0
    assert daemon.cfg.enable_page_cache is True
    # one tick against the fake host must work end to end
    daemon.informer.set_node(api.Node(meta=api.ObjectMeta(name="n1")))
    daemon.tick(now=0.0)


def test_runtime_proxy_build_wires_hooks(tmp_path):
    """cmd/runtime_proxy: flags -> RuntimeProxy over an injected backend
    and the koordlet hook socket; a sandbox start flows hook adjustments
    into the backend call."""
    from koordinator_tpu.cmd import runtime_proxy as cmd_proxy
    from koordinator_tpu.koordlet.proxyserver import ProxyHookService
    from koordinator_tpu.koordlet.runtimehooks import default_hook_server
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.runtimeproxy.server import PodSandboxRequest

    informer = StatesInformer()
    sock = str(tmp_path / "koordlet.sock")
    server = ProxyHookService(default_hook_server(informer)).serve(sock)
    try:
        calls = []

        class Backend:
            def run_pod_sandbox(self, req):
                calls.append(req)

            def __getattr__(self, name):
                return lambda req: calls.append(req)

        proxy = cmd_proxy.build(
            ["--runtime-hooks-endpoint", sock,
             "--hook-failure-policy", "Fail"],
            backend=Backend())
        req = PodSandboxRequest(sandbox_id="s1", name="p1",
                                namespace="default", uid="u1")
        proxy.run_pod_sandbox(req)
        assert calls, "backend must receive the forwarded sandbox start"
    finally:
        server.close()

    with pytest.raises(SystemExit):
        cmd_proxy.build([])  # no backend injected


def test_trio_end_to_end_graceful_shutdown(tmp_path):
    """Launch manager + descheduler + scheduler together against shared
    fakes; all three come up, do work, and stop cleanly."""
    # processes run on the REAL clock: NodeMetrics must be fresh or the
    # noderesource controller degrades instead of computing batch capacity
    nodes, metrics = mk_cluster(metric_time=time.time())
    src = FakeSource(nodes, metrics)
    mgr = cmd_manager.ManagerProcess(
        cmd_manager.ManagerConfig(
            lease_file=str(tmp_path / "m.lease"),
            reconcile_interval_seconds=0.02, retry_period_seconds=0.02),
        src)
    evictor = RecordingEvictor()
    lnl = LowNodeLoad(LowNodeLoadArgs(), evictor,
                      get_metrics=lambda: metrics,
                      get_pods_by_node=lambda: {})
    runner = CycleRunner(balance_plugins=[lnl], limiters=[evictor.limiter])
    desched = cmd_descheduler.DeschedulerProcess(
        cmd_descheduler.DeschedulerConfig(
            lease_file=str(tmp_path / "d.lease"),
            descheduling_interval_seconds=0.02, retry_period_seconds=0.02),
        runner, get_nodes=lambda: nodes)
    sched = cmd_scheduler.build(
        ["--metrics-port", "-1", "--lease-file", str(tmp_path / "s.lease")])

    stop = StopHandle()
    threads = [threading.Thread(target=p.run, args=(stop.stopped,),
                                daemon=True)
               for p in (mgr, desched, sched)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.stop()
    for t in threads:
        t.join(timeout=5.0)
        assert not t.is_alive(), "process failed to shut down"
    assert mgr.ticks > 0 and desched.cycles > 0
    assert all(n.allocatable.get(RK.BATCH_CPU, 0) > 0 for n in nodes)


def test_koordlet_kubelet_pull_flag(tmp_path):
    """--kubelet-addr attaches the /pods pull edge; each tick resyncs
    pods from the kubelet into the informer."""
    import http.server
    import json as _json
    import threading

    from koordinator_tpu.cmd import koordlet as cmd_koordlet
    from koordinator_tpu.koordlet.testing import FakeHost

    podlist = {"items": [{
        "metadata": {"name": "w", "namespace": "d", "uid": "u1",
                     "labels": {"koordinator.sh/qosClass": "LS"}},
        "spec": {"priority": 9000, "nodeName": "n0", "containers":
                 [{"resources": {"requests": {"cpu": "1"}}}]},
        "status": {"phase": "Running"}}]}

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = _json.dumps(podlist).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tok = tmp_path / "token"
        tok.write_text("secret")
        host = FakeHost(str(tmp_path / "host"), num_cpus=4,
                        mem_bytes=8 << 30)
        daemon = cmd_koordlet.build(
            ["--kubelet-addr", "127.0.0.1",
             "--kubelet-port", str(srv.server_port),
             "--kubelet-scheme", "http",
             "--kubelet-token-file", str(tok)], host=host)
        assert daemon.pods_puller is not None
        daemon.tick(now=0.0)
        pods = daemon.informer.get_all_pods()
        assert len(pods) == 1 and pods[0].pod.meta.name == "w"
    finally:
        srv.shutdown()


def test_koordlet_metrics_endpoint(tmp_path):
    """--metrics-port serves the Prometheus scrape surface."""
    import urllib.request

    from koordinator_tpu.cmd import koordlet as cmd_koordlet
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), num_cpus=4, mem_bytes=8 << 30)
    daemon = cmd_koordlet.build(["--metrics-port", "0"], host=host)
    try:
        assert daemon.metrics_server is not None
        daemon.tick(now=0.0)
        url = f"http://127.0.0.1:{daemon.metrics_server.port}/metrics"
        with urllib.request.urlopen(url) as r:
            body = r.read().decode()
        assert "# TYPE" in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{daemon.metrics_server.port}/healthz") as r:
            assert r.status == 200
    finally:
        daemon.metrics_server.close()


def test_manager_and_descheduler_metrics_flag(tmp_path):
    import urllib.request

    from koordinator_tpu.cmd import descheduler as cmd_desched
    from koordinator_tpu.cmd import manager as cmd_manager
    from koordinator_tpu.snapshot import ClusterInformerHub

    hub = ClusterInformerHub()
    mgr = cmd_manager.build(["--lease-file", str(tmp_path / "m.lease"),
                             "--metrics-port", "0"], source=hub)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{mgr.metrics_server.port}/metrics") as r:
            assert r.status == 200
    finally:
        mgr.metrics_server.close()

    class Runner:
        def run_once(self, now):
            return None

    d = cmd_desched.build(["--lease-file", str(tmp_path / "d.lease"),
                           "--metrics-port", "0"],
                          runner=Runner(), get_nodes=lambda: [])
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{d.metrics_server.port}/metrics") as r:
            assert r.status == 200
    finally:
        d.metrics_server.close()
