"""slo-controller overcommit engine tests: batch/mid formulas, degrade,
diff-gate (reference semantics: batchresource/util.go:38-90, midresource
plugin.go:130-160, plugin.go:467-484)."""

import numpy as np

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import (
    Node,
    NodeMetric,
    ObjectMeta,
    Pod,
    PodMetricInfo,
)
from koordinator_tpu.api.extension import PriorityClass
from koordinator_tpu.slo_controller.config import (
    CalculatePolicy,
    ColocationConfig,
    ColocationStrategy,
    ColocationStrategyOverride,
    validate_colocation_config,
)
from koordinator_tpu.slo_controller.noderesource import (
    CPU,
    MEM,
    NodeResourceController,
    build_inputs,
    compute_node_resources,
    need_sync,
)


def mk_node(name="n0", cpu=100000.0, mem=400000.0):
    return Node(meta=ObjectMeta(name=name),
                allocatable={RK.CPU: cpu, RK.MEMORY: mem})


def mk_prod_pod(name, cpu, mem, node="n0"):
    return Pod(meta=ObjectMeta(name=name), priority=9500,
               requests={RK.CPU: cpu, RK.MEMORY: mem},
               node_name=node, phase="Running")


def test_batch_by_usage_formula():
    """Batch = Capacity − NodeReserved − max(SysUsed, SysReserved) − HPUsed."""
    node = mk_node(cpu=100000.0, mem=100000.0)
    metric = NodeMetric(
        node_name="n0", update_time=1000.0,
        system_usage={RK.CPU: 7000.0, RK.MEMORY: 5000.0},
        pods_metric=[PodMetricInfo(
            namespace="default", name="p0",
            priority_class=PriorityClass.PROD,
            usage={RK.CPU: 20000.0, RK.MEMORY: 30000.0})])
    pods = [mk_prod_pod("p0", 30000.0, 40000.0)]
    strategy = ColocationStrategy(
        enable=True, cpu_reclaim_threshold_percent=60.0,
        memory_reclaim_threshold_percent=65.0)
    inputs = build_inputs([node], {"n0": metric}, {"n0": pods}, now=1030.0)
    out = compute_node_resources(inputs, strategy)
    # cpu: 100000 − 40000(reserve 40%) − 7000 − 20000 = 33000
    assert out["batch"][0, CPU] == 33000.0
    # mem: 100000 − 35000(reserve 35%) − 5000 − 30000 = 30000
    assert out["batch"][0, MEM] == 30000.0
    assert not out["degraded"][0]


def test_pod_without_metric_counts_at_request():
    node = mk_node(cpu=100000.0, mem=100000.0)
    metric = NodeMetric(node_name="n0", update_time=1000.0,
                        system_usage={RK.CPU: 0.0, RK.MEMORY: 0.0})
    pods = [mk_prod_pod("p0", 30000.0, 40000.0)]  # no metric entry
    strategy = ColocationStrategy(cpu_reclaim_threshold_percent=100.0,
                                  memory_reclaim_threshold_percent=100.0)
    inputs = build_inputs([node], {"n0": metric}, {"n0": pods}, now=1000.0)
    out = compute_node_resources(inputs, strategy)
    assert out["batch"][0, CPU] == 70000.0   # charged at request
    assert out["batch"][0, MEM] == 60000.0


def test_dangling_metric_counts_at_usage():
    """A pod metric with no matching pod in the list still subtracts."""
    node = mk_node(cpu=100000.0, mem=100000.0)
    metric = NodeMetric(
        node_name="n0", update_time=0.0,
        pods_metric=[PodMetricInfo(
            namespace="default", name="ghost",
            priority_class=PriorityClass.PROD,
            usage={RK.CPU: 10000.0, RK.MEMORY: 15000.0})])
    strategy = ColocationStrategy(cpu_reclaim_threshold_percent=100.0,
                                  memory_reclaim_threshold_percent=100.0,
                                  degrade_time_minutes=1e9)
    inputs = build_inputs([node], {"n0": metric}, {"n0": []}, now=0.0)
    out = compute_node_resources(inputs, strategy)
    assert out["batch"][0, CPU] == 90000.0
    assert out["batch"][0, MEM] == 85000.0


def test_memory_by_request_policy():
    node = mk_node(cpu=100000.0, mem=100000.0)
    metric = NodeMetric(
        node_name="n0", update_time=1000.0,
        system_usage={RK.CPU: 0.0, RK.MEMORY: 9000.0},
        pods_metric=[PodMetricInfo(
            namespace="default", name="p0",
            priority_class=PriorityClass.PROD,
            usage={RK.CPU: 1000.0, RK.MEMORY: 20000.0})])
    pods = [mk_prod_pod("p0", 30000.0, 50000.0)]
    strategy = ColocationStrategy(
        cpu_reclaim_threshold_percent=100.0,
        memory_reclaim_threshold_percent=100.0,
        memory_calculate_policy=CalculatePolicy.REQUEST)
    inputs = build_inputs([node], {"n0": metric}, {"n0": pods}, now=1000.0)
    out = compute_node_resources(inputs, strategy)
    # request policy ignores system usage, uses system reserved (0 here)
    assert out["batch"][0, MEM] == 50000.0
    # cpu stays usage policy
    assert out["batch"][0, CPU] == 99000.0


def test_degrade_resets_batch():
    node = mk_node()
    metric = NodeMetric(node_name="n0", update_time=0.0)
    strategy = ColocationStrategy(degrade_time_minutes=15.0)
    inputs = build_inputs([node], {"n0": metric}, {"n0": []},
                          now=16.0 * 60.0)
    out = compute_node_resources(inputs, strategy)
    assert out["degraded"][0]
    assert (out["batch"][0] == -1.0).all()
    assert (out["mid"][0] == -1.0).all()


def test_mid_capped_by_threshold():
    node = mk_node(cpu=100000.0, mem=100000.0)
    metric = NodeMetric(node_name="n0", update_time=1000.0,
                        prod_reclaimable={RK.CPU: 50000.0,
                                          RK.MEMORY: 2000.0})
    strategy = ColocationStrategy(mid_cpu_threshold_percent=10.0,
                                  mid_memory_threshold_percent=10.0)
    inputs = build_inputs([node], {"n0": metric}, {"n0": []}, now=1000.0)
    out = compute_node_resources(inputs, strategy)
    assert out["mid"][0, CPU] == 10000.0   # capped at 10% of allocatable
    assert out["mid"][0, MEM] == 2000.0    # reclaimable below cap


def test_need_sync_diff_gate():
    old = np.array([[10000.0, 10000.0], [10000.0, 10000.0]], np.float32)
    new = np.array([[10500.0, 10000.0],    # 5% diff < 10% => no sync
                    [12000.0, 10000.0]], np.float32)  # 20% => sync
    mask = need_sync(old, new, 0.1)
    assert not mask[0] and mask[1]


def test_controller_sync_mask_and_state():
    nodes = [mk_node(f"n{i}") for i in range(3)]
    metrics = {f"n{i}": NodeMetric(node_name=f"n{i}", update_time=100.0)
               for i in range(3)}
    ctl = NodeResourceController()
    inputs = build_inputs(nodes, metrics, {}, now=100.0)
    out1 = ctl.reconcile(inputs)
    assert out1["sync_mask"].all()  # first round always syncs
    out2 = ctl.reconcile(inputs)
    assert not out2["sync_mask"].any()  # no change => no sync


def test_sync_gate_latches_applied_value():
    """Sub-threshold drift accumulates against the last APPLIED value and
    eventually syncs (reference diffs vs node status, plugin.go:101-112)."""
    node = mk_node(cpu=100000.0, mem=100000.0)
    ctl = NodeResourceController(strategy=ColocationStrategy(
        cpu_reclaim_threshold_percent=100.0,
        memory_reclaim_threshold_percent=100.0,
        resource_diff_threshold=0.1))

    def usage(v):
        m = NodeMetric(node_name="n0", update_time=0.0,
                       system_usage={RK.CPU: v, RK.MEMORY: 0.0})
        return build_inputs([node], {"n0": m}, {"n0": []}, now=0.0)

    ctl.reconcile(usage(0.0))                      # applied batch cpu 100000
    out = ctl.reconcile(usage(5000.0))             # 5% drift: below gate
    assert not out["sync_mask"][0]
    out = ctl.reconcile(usage(9000.0))             # 9% cumulative: still below
    assert not out["sync_mask"][0]
    out = ctl.reconcile(usage(12000.0))            # 12% vs applied: syncs
    assert out["sync_mask"][0]


def test_per_node_strategies():
    nodes = [mk_node("n0"), mk_node("n1")]
    mets = {n.meta.name: NodeMetric(node_name=n.meta.name, update_time=0.0)
            for n in nodes}
    inputs = build_inputs(nodes, mets, {}, now=0.0)
    base = ColocationStrategy(cpu_reclaim_threshold_percent=60.0,
                              memory_reclaim_threshold_percent=100.0)
    hot = ColocationStrategy(cpu_reclaim_threshold_percent=80.0,
                             memory_reclaim_threshold_percent=100.0)
    out = compute_node_resources(inputs, base, strategies=[base, hot])
    assert out["batch"][0, CPU] == 60000.0
    assert out["batch"][1, CPU] == 80000.0


def test_colocation_config_merge_and_validation():
    cfg = ColocationConfig(
        cluster_strategy=ColocationStrategy(cpu_reclaim_threshold_percent=60.0),
        node_overrides=[ColocationStrategyOverride(
            node_selector={"pool": "batch"},
            fields={"cpu_reclaim_threshold_percent": 80.0})])
    assert cfg.strategy_for({"pool": "batch"}).cpu_reclaim_threshold_percent == 80.0
    assert cfg.strategy_for({"pool": "other"}).cpu_reclaim_threshold_percent == 60.0
    assert validate_colocation_config(cfg) == []

    bad = ColocationConfig(
        cluster_strategy=ColocationStrategy(cpu_reclaim_threshold_percent=150.0))
    assert validate_colocation_config(bad)


def test_nodeslo_render():
    from koordinator_tpu.slo_controller.nodeslo import (
        SLOControllerConfig,
        StrategyOverride,
        render_node_slo,
    )
    from koordinator_tpu.api.types import ResourceThresholdStrategy

    cfg = SLOControllerConfig(
        threshold=ResourceThresholdStrategy(
            enable=True, cpu_suppress_threshold_percent=65.0),
        threshold_overrides=[StrategyOverride(
            node_selector={"tier": "gold"},
            fields={"cpu_suppress_threshold_percent": 50.0})])
    slo = render_node_slo(cfg, "n0", {"tier": "gold"})
    assert slo.threshold.cpu_suppress_threshold_percent == 50.0
    assert slo.threshold.enable
    slo2 = render_node_slo(cfg, "n1", {})
    assert slo2.threshold.cpu_suppress_threshold_percent == 65.0
