"""Quota-profile provisioning, overuse revocation, and quota-constrained
preemption (SURVEY.md 2.1/2.3; reference profile_controller_test.go /
quota_overuse_revoke_test.go / preempt_test.go scenarios)."""

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import NUM_RESOURCES, ResourceKind as RK
from koordinator_tpu.quota_controller import QuotaProfileReconciler
from koordinator_tpu.scheduler.plugins.quota_revoke import (
    QuotaOverUsedRevokeController,
    select_revoke_victims,
    select_victims_on_node,
)
from koordinator_tpu.snapshot.builder import resource_vec
from koordinator_tpu.webhook import QuotaTopology


def mk_node(name, labels=None, cpu=32000.0, mem=65536.0):
    return api.Node(meta=api.ObjectMeta(name=name, labels=labels or {}),
                    allocatable={RK.CPU: cpu, RK.MEMORY: mem})


def quota_pod(name, cpu, prio, quota="q", **kw):
    return api.Pod(meta=api.ObjectMeta(name=name),
                   requests={RK.CPU: cpu}, priority=prio,
                   quota_name=quota, **kw)


# --- profile controller -----------------------------------------------------


def test_profile_generates_root_quota_from_selected_nodes():
    rec = QuotaProfileReconciler(QuotaTopology())
    profile = api.ElasticQuotaProfile(
        meta=api.ObjectMeta(name="ml-pool"), quota_name="ml-root",
        node_selector={"pool": "ml"})
    nodes = [mk_node("n0", {"pool": "ml"}), mk_node("n1", {"pool": "ml"}),
             mk_node("n2", {"pool": "web"})]
    quota = rec.reconcile(profile, nodes)
    assert quota.min[RK.CPU] == 64000.0
    assert quota.min[RK.MEMORY] == 2 * 65536.0
    assert quota.is_parent and quota.tree_id
    # re-reconcile after node set change updates min in place
    quota2 = rec.reconcile(profile, nodes[:1])
    assert quota2.min[RK.CPU] == 32000.0


def test_profile_reconcile_validates_before_commit():
    """A rejected reconcile leaves both the reconciler cache and the
    topology holding the previously admitted quota (admission gates the
    apiserver write in the reference), and re-reconciles never mutate the
    previously returned object in place."""
    from koordinator_tpu.webhook.elasticquota import QuotaTopologyError

    topo = QuotaTopology()
    rec = QuotaProfileReconciler(topo)
    profile = api.ElasticQuotaProfile(
        meta=api.ObjectMeta(name="p"), quota_name="q", node_selector={})
    q1 = rec.reconcile(profile, [mk_node("n0"), mk_node("n1")])
    assert q1.min[RK.CPU] == 64000.0
    # a fresh object per reconcile: the first result must not alias-mutate
    q2 = rec.reconcile(profile, [mk_node("n0")])
    assert q2.min[RK.CPU] == 32000.0
    assert q1.min[RK.CPU] == 64000.0, "in-place mutation of admitted quota"
    # invalid update (negative min) is rejected and nothing diverges
    profile.resource_ratio = -1.0
    with pytest.raises(QuotaTopologyError):
        rec.reconcile(profile, [mk_node("n0")])
    assert rec.quotas["q"].min[RK.CPU] == 32000.0
    assert topo.quotas["q"].min[RK.CPU] == 32000.0


def test_profile_resource_ratio():
    rec = QuotaProfileReconciler()
    profile = api.ElasticQuotaProfile(
        meta=api.ObjectMeta(name="p"), quota_name="q",
        node_selector={}, resource_ratio=0.5)
    quota = rec.reconcile(profile, [mk_node("n0")])
    assert quota.min[RK.CPU] == 16000.0


# --- overuse revoke ---------------------------------------------------------


def _vec(cpu):
    v = np.zeros(NUM_RESOURCES)
    v[int(RK.CPU)] = cpu
    return v


def test_revoke_victims_minimal_set():
    # used 100, runtime 60: revoke walks p1(10),p2(30),p3(50) low->high
    # until under, then assigns back what still fits
    pods = [quota_pod("p3", 50.0, 9000), quota_pod("p2", 30.0, 7000),
            quota_pod("p1", 10.0, 5000)]
    victims = select_revoke_victims(pods, _vec(100.0), _vec(60.0))
    # tried: p1 (90), p2 (60) -> fits; assign back: p2 (90 > 60, keep
    # revoked), p1 (70 > 60, keep revoked)
    assert {p.meta.name for p in victims} == {"p1", "p2"}


def test_revoke_assign_back_reprieves_covered_pod():
    # used 100, runtime 55: tried p1(90), p2(60), p3(10)->fits.
    # back: p3? 10+50=60>55 keep; p2 10+30=40<=55 reprieve; p1 40+10=50 ok
    pods = [quota_pod("p3", 50.0, 9000), quota_pod("p2", 30.0, 7000),
            quota_pod("p1", 10.0, 5000)]
    victims = select_revoke_victims(pods, _vec(100.0), _vec(55.0))
    assert {p.meta.name for p in victims} == {"p3"}


def test_revoke_skips_non_preemptible():
    shielded = quota_pod("s", 80.0, 5000)
    shielded.meta.annotations["scheduling.koordinator.sh/preemptible"] = "false"
    pods = [shielded, quota_pod("p", 20.0, 7000)]
    victims = select_revoke_victims(pods, _vec(100.0), _vec(10.0))
    assert {p.meta.name for p in victims} == {"p"}


def test_overuse_controller_requires_sustained_overuse():
    ctl = QuotaOverUsedRevokeController(trigger_evict_duration_seconds=100.0)
    used = np.stack([_vec(100.0)])
    runtime = np.stack([_vec(60.0)])
    pods = {"q": [quota_pod("p", 50.0, 5000)]}
    assert ctl.revoke_pods(["q"], used, runtime, pods, now=0.0) == []
    assert ctl.revoke_pods(["q"], used, runtime, pods, now=50.0) == []
    out = ctl.revoke_pods(["q"], used, runtime, pods, now=150.0)
    assert [p.meta.name for p in out] == ["p"]
    # under-use resets the streak
    ctl2 = QuotaOverUsedRevokeController(trigger_evict_duration_seconds=100.0)
    ctl2.revoke_pods(["q"], used, runtime, pods, now=0.0)
    ctl2.revoke_pods(["q"], np.stack([_vec(10.0)]), runtime, pods, now=90.0)
    assert ctl2.revoke_pods(["q"], used, runtime, pods, now=150.0) == []


# --- preemption -------------------------------------------------------------


def test_preemption_same_quota_lower_priority_only():
    # non-candidates (high-same + low-other) use 80; preemptor needs 50:
    # fits the 150 node only when low-same's 40 stays gone
    alloc = _vec(150.0)
    alloc[int(RK.MEMORY)] = 1e9
    on_node = [quota_pod("low-same", 40.0, 5000),
               quota_pod("high-same", 40.0, 9500),
               quota_pod("low-other", 40.0, 5000, quota="other")]
    preemptor = quota_pod("p", 50.0, 9000)
    res = select_victims_on_node(
        preemptor, alloc, on_node,
        quota_used=_vec(80.0), quota_runtime=_vec(100.0))
    assert res is not None
    assert [v.meta.name for v in res.victims] == ["low-same"]


def test_preemption_respects_quota_runtime():
    # node has room but the quota doesn't: victims must free QUOTA too
    alloc = _vec(1000.0)
    alloc[int(RK.MEMORY)] = 1e9
    on_node = [quota_pod("v", 50.0, 5000)]
    preemptor = quota_pod("p", 50.0, 9000)
    # quota used 100 == runtime 100: preemptor's 50 fits only if v goes
    res = select_victims_on_node(preemptor, alloc, on_node,
                                 quota_used=_vec(100.0),
                                 quota_runtime=_vec(100.0))
    assert res is not None and [v.meta.name for v in res.victims] == ["v"]
    # runtime is too small even with all victims gone -> None
    res = select_victims_on_node(preemptor, alloc, on_node,
                                 quota_used=_vec(100.0),
                                 quota_runtime=_vec(40.0))
    assert res is None


def test_preemption_reprieves_unneeded_candidates():
    alloc = _vec(100.0)
    alloc[int(RK.MEMORY)] = 1e9
    on_node = [quota_pod("a", 30.0, 5000), quota_pod("b", 30.0, 6000),
               quota_pod("c", 30.0, 7000)]
    preemptor = quota_pod("p", 35.0, 9000)
    res = select_victims_on_node(preemptor, alloc, on_node,
                                 quota_used=_vec(90.0),
                                 quota_runtime=_vec(1000.0))
    # node 90/100 used; need 35 -> free >= 25: reprieve c (25+30 over?
    # base=0 after removing all; back c: 30+35=65<=100 ok; back b:
    # 60+35=95<=100 ok; back a: 90+35=125>100 -> a is the single victim
    assert res is not None
    assert [v.meta.name for v in res.victims] == ["a"]
