"""NRI delivery mode: event flow over a REAL unix socket, adjustment
semantics, failure policies, and the three-delivery-modes equivalence —
the same hook plugins produce the same cgroup state whether delivered via
NRI events, the runtime proxy, or the reconciler level-walk (reference:
nri/server.go:26,68-89; runtimehooks has one rule set, three transports).
"""

import json

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_EXTENDED_RESOURCE_SPEC,
    ANNOTATION_RESOURCE_STATUS,
    LABEL_POD_QOS,
    ResourceKind as RK,
    encode_extended_resource_spec,
)
from koordinator_tpu.koordlet import nri_pb2 as pb
from koordinator_tpu.koordlet.nri import (
    EVENTS,
    NriServer,
    POLICY_FAIL,
    POLICY_IGNORE,
    pod_to_nri,
)
from koordinator_tpu.koordlet.resourceexecutor import Executor
from koordinator_tpu.koordlet.runtimehooks import (
    HookContext,
    HookServer,
    Reconciler,
    Stage,
    default_hook_server,
)
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
from koordinator_tpu.koordlet.testing import FakeHost
from koordinator_tpu.runtimeproxy.rpc import RpcClient


def make_pod(uid, qos="BE", annotations=None, cgroup_dir=None):
    requests = {RK.BATCH_CPU: 2000.0, RK.BATCH_MEMORY: 1024.0}
    limits = {RK.BATCH_CPU: 2000.0, RK.BATCH_MEMORY: 1024.0}
    # every admitted pod with extended tiers carries the webhook-written
    # spec annotation (extended_resource_spec.go) — the only channel the
    # NRI/proxy runtime contexts can recover batch requests from
    annotations = dict(annotations or {})
    annotations[ANNOTATION_EXTENDED_RESOURCE_SPEC] = \
        encode_extended_resource_spec(requests, limits)
    return PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(uid=uid, name=uid, namespace="default",
                            labels={LABEL_POD_QOS: qos},
                            annotations=annotations),
        requests=requests, limits=limits,
        qos_label=qos, priority=5500),
        cgroup_dir=cgroup_dir or f"kubepods/besteffort/pod{uid}")


@pytest.fixture
def env(tmp_path):
    host = FakeHost(str(tmp_path), num_cpus=8)
    informer = StatesInformer()
    executor = Executor(host)
    hooks = default_hook_server(informer)
    server = NriServer(hooks, executor)
    return host, informer, executor, hooks, server


def test_configure_negotiates_event_mask(env):
    *_, server = env
    resp = server.configure(pb.NriConfigureRequest(
        runtime_name="containerd", runtime_version="1.7"))
    assert list(resp.events) == list(EVENTS)
    # runtime narrows the subscription
    resp = server.configure(pb.NriConfigureRequest(
        config=json.dumps({"events": ["RunPodSandbox"]})))
    assert list(resp.events) == ["RunPodSandbox"]
    # malformed config keeps defaults
    resp = server.configure(pb.NriConfigureRequest(config="not json"))
    assert list(resp.events) == list(EVENTS)


def test_pod_to_nri_synthesizes_spec_annotation(env):
    """A typed pod that never saw the webhook (no spec annotation) still
    crosses the in-process wire with its batch requests intact: pod_to_nri
    synthesizes the annotation so _pod_meta can recover them."""
    *_, server = env
    meta = make_pod("u0")
    del meta.pod.meta.annotations[ANNOTATION_EXTENDED_RESOURCE_SPEC]
    wire = pod_to_nri(meta)
    assert ANNOTATION_EXTENDED_RESOURCE_SPEC in wire.annotations
    resp = server.create_container(pb.NriCreateContainerRequest(
        pod=wire, container=pb.NriContainer(id="c0", name="main")))
    # batchresource saw the recovered 2000m request
    assert resp.adjustment.resources.cpu_shares == 2048


def test_run_pod_sandbox_applies_pod_cgroup_writes(env):
    host, _informer, _executor, _hooks, server = env
    meta = make_pod("u1")
    host.make_cgroup(meta.cgroup_dir)
    server.run_pod_sandbox(pb.NriRunPodSandboxRequest(pod=pod_to_nri(meta)))
    # groupidentity wrote bvt for the BE pod directly (NriDone path)
    assert host.read_cgroup(meta.cgroup_dir, "cpu.bvt_warp_ns") == "-1"


def test_create_container_returns_adjustment(env):
    host, _informer, _executor, _hooks, server = env
    meta = make_pod("u2", annotations={
        ANNOTATION_RESOURCE_STATUS: json.dumps(
            {"cpuset": "2-3", "numaNodes": [0]})})
    resp = server.create_container(pb.NriCreateContainerRequest(
        pod=pod_to_nri(meta),
        container=pb.NriContainer(id="c1", name="main")))
    adj = resp.adjustment
    assert adj.resources.cpuset_cpus == "2-3"
    assert adj.resources.cpuset_mems == "0"
    # batchresource: 2000m -> shares 2048, quota 200000, memory 1GiB
    assert adj.resources.cpu_shares == 2048
    assert adj.resources.cpu_quota == 200000
    assert adj.resources.memory_limit == 1024 << 20
    # nothing written host-side: the runtime owns applying the adjustment
    assert _try_read(host, meta.cgroup_dir, "cpuset.cpus") is None


def test_update_container_returns_update(env):
    *_, server = env
    meta = make_pod("u3")
    resp = server.update_container(pb.NriUpdateContainerRequest(
        pod=pod_to_nri(meta),
        container=pb.NriContainer(id="c9", name="main")))
    assert len(resp.updates) == 1
    assert resp.updates[0].container_id == "c9"
    assert resp.updates[0].resources.cpu_shares == 2048


def test_synchronize_converges_existing_containers(env):
    *_, server = env
    meta = make_pod("u4")
    req = pb.NriSynchronizeRequest()
    req.pods.append(pod_to_nri(meta, pod_id="sb4"))
    req.containers.append(pb.NriContainer(
        id="c4", name="main", pod_sandbox_id="sb4"))
    # a container of an unknown sandbox is skipped
    req.containers.append(pb.NriContainer(
        id="orphan", name="x", pod_sandbox_id="nope"))
    resp = server.synchronize(req)
    assert [u.container_id for u in resp.updates] == ["c4"]


def test_failure_policy(env):
    host, _informer, executor, _hooks, _server = env

    class BoomHook:
        name = "boom"
        stages = (Stage.PRE_CREATE_CONTAINER,)

        def apply(self, ctx: HookContext) -> None:
            raise RuntimeError("boom")

    meta = make_pod("u5")
    req = pb.NriCreateContainerRequest(pod=pod_to_nri(meta),
                                       container=pb.NriContainer(id="c"))
    ignore = NriServer(HookServer([BoomHook()]), executor,
                       failure_policy=POLICY_IGNORE)
    resp = ignore.create_container(req)  # swallowed, empty adjustment
    assert not resp.adjustment.env and not resp.adjustment.resources.unified

    fail = NriServer(HookServer([BoomHook()]), executor,
                     failure_policy=POLICY_FAIL)
    with pytest.raises(RuntimeError):
        fail.create_container(req)


def test_nri_over_real_socket(env, tmp_path):
    host, _informer, _executor, _hooks, server = env
    sock = str(tmp_path / "nri.sock")
    rpc = server.serve(sock)
    try:
        client = RpcClient(sock)
        resp = client.call("Configure", pb.NriConfigureRequest(),
                           pb.NriConfigureResponse)
        assert "CreateContainer" in list(resp.events)
        meta = make_pod("u6")
        host.make_cgroup(meta.cgroup_dir)
        client.call("RunPodSandbox",
                    pb.NriRunPodSandboxRequest(pod=pod_to_nri(meta)),
                    pb.NriEmpty)
        assert host.read_cgroup(meta.cgroup_dir, "cpu.bvt_warp_ns") == "-1"
        resp = client.call(
            "CreateContainer",
            pb.NriCreateContainerRequest(pod=pod_to_nri(meta),
                                         container=pb.NriContainer(id="c")),
            pb.NriCreateContainerResponse)
        assert resp.adjustment.resources.cpu_shares == 2048
    finally:
        rpc.close()


# --- the three delivery modes produce identical cgroup state ---------------

def _try_read(host, cgroup_dir, resource):
    try:
        return host.read_cgroup(cgroup_dir, resource)
    except (FileNotFoundError, KeyError):
        return None


def _apply_nri_resources(host, cgroup_dir, res: pb.NriLinuxResources) -> None:
    """The runtime side of NRI: fold an adjustment into cgroup files (what
    containerd does with a ContainerAdjustment)."""
    if res.cpu_shares:
        host.write_cgroup(cgroup_dir, "cpu.shares", str(res.cpu_shares))
    if res.cpu_quota:
        host.write_cgroup(cgroup_dir, "cpu.cfs_quota_us", str(res.cpu_quota))
    if res.cpuset_cpus:
        host.write_cgroup(cgroup_dir, "cpuset.cpus", res.cpuset_cpus)
    if res.cpuset_mems:
        host.write_cgroup(cgroup_dir, "cpuset.mems", res.cpuset_mems)
    if res.memory_limit:
        host.write_cgroup(cgroup_dir, "memory.limit_in_bytes",
                          str(res.memory_limit))
    for k, v in res.unified.items():
        host.write_cgroup(cgroup_dir, k, v)


FILES = ("cpu.bvt_warp_ns", "cpu.shares", "cpu.cfs_quota_us",
         "memory.limit_in_bytes", "cpuset.cpus")


def _read_state(host, cgroup_dir):
    return {f: _try_read(host, cgroup_dir, f) for f in FILES}


def test_three_delivery_modes_converge(tmp_path):
    """One pod, three transports, identical cgroup end state."""
    pod_annotations = {ANNOTATION_RESOURCE_STATUS: json.dumps(
        {"cpuset": "4-5", "numaNodes": [1]})}
    states = {}
    for mode in ("nri", "proxy", "reconciler"):
        host = FakeHost(str(tmp_path / mode), num_cpus=8)
        informer = StatesInformer()
        executor = Executor(host)
        hooks = default_hook_server(informer)
        meta = make_pod("p1", annotations=pod_annotations)
        host.make_cgroup(meta.cgroup_dir)

        if mode == "nri":
            server = NriServer(hooks, executor)
            server.run_pod_sandbox(
                pb.NriRunPodSandboxRequest(pod=pod_to_nri(meta)))
            resp = server.create_container(pb.NriCreateContainerRequest(
                pod=pod_to_nri(meta), container=pb.NriContainer(id="c")))
            _apply_nri_resources(host, meta.cgroup_dir,
                                 resp.adjustment.resources)
        elif mode == "proxy":
            from koordinator_tpu.koordlet.proxyserver import ProxyHookService
            from koordinator_tpu.runtimeproxy import api_pb2 as ppb
            svc = ProxyHookService(hooks)
            req = ppb.PodSandboxHookRequest(cgroup_parent=meta.cgroup_dir)
            req.pod_meta.name = meta.pod.meta.name
            req.pod_meta.uid = meta.pod.meta.uid
            for k, v in meta.pod.meta.labels.items():
                req.labels[k] = v
            for k, v in meta.pod.meta.annotations.items():
                req.annotations[k] = v
            sresp = svc._pod_hook("PreRunPodSandboxHook", req)
            # the proxy merges resources into the CRI request; the runtime
            # then realizes them as cgroup writes
            creq = ppb.ContainerResourceHookRequest(
                pod_cgroup_parent=meta.cgroup_dir)
            creq.pod_meta.name = meta.pod.meta.name
            creq.pod_meta.uid = meta.pod.meta.uid
            for k, v in meta.pod.meta.labels.items():
                creq.pod_labels[k] = v
            for k, v in meta.pod.meta.annotations.items():
                creq.pod_annotations[k] = v
            cresp = svc._container_hook("PreCreateContainerHook", creq)
            for r in (sresp.resources, cresp.container_resources):
                if r.cpu_shares:
                    host.write_cgroup(meta.cgroup_dir, "cpu.shares",
                                      str(r.cpu_shares))
                if r.cpu_quota:
                    host.write_cgroup(meta.cgroup_dir, "cpu.cfs_quota_us",
                                      str(r.cpu_quota))
                if r.cpuset_cpus:
                    host.write_cgroup(meta.cgroup_dir, "cpuset.cpus",
                                      r.cpuset_cpus)
                if r.cpuset_mems:
                    host.write_cgroup(meta.cgroup_dir, "cpuset.mems",
                                      r.cpuset_mems)
                if r.memory_limit_in_bytes:
                    host.write_cgroup(meta.cgroup_dir,
                                      "memory.limit_in_bytes",
                                      str(r.memory_limit_in_bytes))
                for k, v in r.unified.items():
                    host.write_cgroup(meta.cgroup_dir, k, v)
        else:
            informer.set_pods([meta])
            Reconciler(informer, hooks, executor).reconcile_all()

        states[mode] = _read_state(host, meta.cgroup_dir)

    assert states["nri"] == states["proxy"] == states["reconciler"]
    # and the state is the hooks' output, not vacuously all-None
    assert states["nri"]["cpu.bvt_warp_ns"] == "-1"
    assert states["nri"]["cpu.shares"] == "2048"
    assert states["nri"]["cpuset.cpus"] == "4-5"
