"""Incremental node/device topology churn (VERDICT r3 #7): the
NodeTopologyDelta path must produce exactly the rows a full rebuild
would, flow through the syncer as O(K) ingests instead of O(N)
rebuilds, and absorb 1%% churn of a 10k-node cluster far faster than
the rebuild it replaces."""

import time

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.snapshot import (
    SnapshotBuilder,
    SnapshotStore,
)
from koordinator_tpu.snapshot.informers import ClusterInformerHub, SnapshotSyncer

NOW = 1e9


def mk_node(name, cpu=32000.0, mem=65536.0, labels=None, taints=(),
            zones=0, unschedulable=False):
    topo = None
    if zones:
        topo = api.NodeResourceTopology(zones=[
            api.NUMAZone(cpus_milli=cpu / zones, memory_mib=mem / zones)
            for _ in range(zones)])
    return api.Node(meta=api.ObjectMeta(name=name, labels=labels or {}),
                    allocatable={RK.CPU: cpu, RK.MEMORY: mem},
                    taints=list(taints), topology=topo,
                    unschedulable=unschedulable)


def mk_metric(name, cpu_used):
    return api.NodeMetric(node_name=name, update_time=NOW,
                          node_usage={RK.CPU: cpu_used})


def mk_device(name, minors=2, mem=16384.0):
    return api.Device(node_name=name, devices=[
        api.DeviceInfo(type="gpu", minor=m, health=True,
                       resources={RK.GPU_MEMORY: mem}, numa_node=m % 2)
        for m in range(minors)])


def seed_builder(b):
    b.add_node(mk_node("n0", labels={"zone": "a"}))
    b.add_node(mk_node("n1", labels={"zone": "b"},
                       taints=[api.Taint(key="ded", effect="NoSchedule")]))
    b.add_node(mk_node("n2", labels={"zone": "a"}, zones=2))
    b.set_node_metric(mk_metric("n0", 4000.0))
    b.set_node_metric(mk_metric("n2", 2000.0))
    b.add_device(mk_device("n2"))


def node_row(snap, i):
    """Every per-node column of row i, as plain numpy (id columns
    excluded — compared semantically)."""
    n, d = snap.nodes, snap.devices
    return {
        "alloc": np.asarray(n.allocatable[i]),
        "req": np.asarray(n.requested[i]),
        "sched": bool(np.asarray(n.schedulable[i])),
        "numa_cap": np.asarray(n.numa_cap[i]),
        "numa_free": np.asarray(n.numa_free[i]),
        "numa_valid": np.asarray(n.numa_valid[i]),
        "policy": int(np.asarray(n.numa_policy[i])),
        "amp": float(np.asarray(n.cpu_amplification[i])),
        "fresh": bool(np.asarray(n.metric_fresh[i])),
        "usage": np.asarray(n.usage[i]),
        "gpu_total": np.asarray(d.gpu_total[i]),
        "gpu_free": np.asarray(d.gpu_free[i]),
        "gpu_valid": np.asarray(d.gpu_valid[i]),
        "gpu_numa": np.asarray(d.gpu_numa[i]),
    }


def assert_rows_equal(a, b):
    for key in a:
        np.testing.assert_allclose(a[key], b[key], err_msg=key,
                                   rtol=0, atol=0)


def test_topology_delta_rows_match_full_rebuild():
    """add + update + remove via topology_delta == a from-scratch
    rebuild of the same final state, row for row (by node name)."""
    b = SnapshotBuilder(max_nodes=8, max_gpu_inst=4)
    seed_builder(b)
    snap, _ = b.build(now=NOW)

    # churn: add n3 (with device), update n1 (new labels, untainted,
    # cordoned), remove n0
    n3 = mk_node("n3", cpu=64000.0, labels={"zone": "c"})
    n1b = mk_node("n1", labels={"zone": "c"}, unschedulable=True)
    b.add_node(n3)
    b.add_device(mk_device("n3", minors=1))
    b.add_node(n1b)
    b.remove_node("n0")
    delta = b.topology_delta(["n3", "n1", "n0"], now=NOW, pad_to=4)
    from koordinator_tpu.snapshot.delta import apply_topology_delta
    got = apply_topology_delta(snap, delta)

    # the same end state, built from scratch
    b2 = SnapshotBuilder(max_nodes=8, max_gpu_inst=4)
    b2.add_node(mk_node("n1", labels={"zone": "c"}, unschedulable=True))
    b2.add_node(mk_node("n2", labels={"zone": "a"}, zones=2))
    b2.add_node(n3)
    b2.set_node_metric(mk_metric("n2", 2000.0))
    b2.add_device(mk_device("n2"))
    b2.add_device(mk_device("n3", minors=1))
    want, _ = b2.build(now=NOW)

    for name in ("n1", "n2", "n3"):
        assert_rows_equal(node_row(got, b.node_index[name]),
                          node_row(want, b2.node_index[name]))
    # the removed node's row is zeroed and unschedulable
    removed = node_row(got, 0)
    assert not removed["sched"] and not removed["fresh"]
    assert removed["alloc"].sum() == 0

    # group ids stay a consistent partition: n1 joined n3's label set,
    # n2 keeps its own; the freed taint group id is simply unused
    lg = np.asarray(got.nodes.label_group)
    assert lg[b.node_index["n1"]] == lg[b.node_index["n3"]]
    assert lg[b.node_index["n2"]] != lg[b.node_index["n1"]]
    tg = np.asarray(got.nodes.taint_group)
    assert tg[b.node_index["n1"]] == 0  # untainted now


def test_same_pass_replacement_never_zeroes_the_reused_row():
    """Regression: remove 'a' + add 'b' in ONE delta window reuses a's
    row — the delta must carry ONLY b's row for it (duplicate scatter
    targets are nondeterministic in jnp), so b is never published
    zeroed."""
    b = SnapshotBuilder(max_nodes=2)
    b.add_node(mk_node("a"))
    b.add_node(mk_node("keep"))
    snap, _ = b.build(now=NOW)
    row = b.remove_node("a")
    assert b.add_node(mk_node("b", cpu=48000.0)) == row
    delta = b.topology_delta(["a", "b"], now=NOW, pad_to=4)
    tgt = [int(i) for i in np.asarray(delta.idx) if i >= 0]
    assert tgt.count(row) == 1  # no duplicate target
    from koordinator_tpu.snapshot.delta import apply_topology_delta
    got = apply_topology_delta(snap, delta)
    assert bool(np.asarray(got.nodes.schedulable)[row])
    assert float(np.asarray(got.nodes.allocatable)[row, int(RK.CPU)]) \
        == 48000.0


def test_incremental_taint_reaches_pod_batch_matrices():
    """Regression: a never-before-seen taint arriving via the
    incremental path must be enforced by the NEXT pod batch — ctx holds
    the LIVE group tables, not a build-time copy."""
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.snapshot.delta import apply_topology_delta

    b = SnapshotBuilder(max_nodes=2)
    b.add_node(mk_node("plain", cpu=1000.0))   # too small for the pod
    b.add_node(mk_node("big", cpu=64000.0))
    snap, ctx = b.build(now=NOW)

    # 'big' gets a fresh NoSchedule taint AFTER the build
    b.add_node(mk_node("big", cpu=64000.0,
                       taints=[api.Taint(key="ded",
                                         effect="NoSchedule")]))
    snap = apply_topology_delta(snap,
                                b.topology_delta(["big"], now=NOW,
                                                 pad_to=2))
    pod = api.Pod(meta=api.ObjectMeta(name="p"), priority=9000,
                  requests={RK.CPU: 4000.0, RK.MEMORY: 512.0})
    batch = b.build_pod_batch([pod], ctx)
    assert batch.has_taints  # the new group is modeled
    res = core.schedule_batch(snap, batch, LoadAwareConfig.make(),
                              num_rounds=2, k_choices=2)
    # the only node that fits is tainted and the pod tolerates nothing
    assert int(np.asarray(res.assignment)[0]) == -1


def test_reservation_hosting_nodes_force_the_rebuild_path():
    """Regression: topology rows cannot carry reservation holds, and a
    removed node may still be referenced by ReservationState.node row
    indices — churn touching a reservation-hosting node must take the
    rebuild path (topology_delta raises; the syncer falls back)."""
    b = SnapshotBuilder(max_nodes=4)
    b.add_node(mk_node("host"))
    b.add_node(mk_node("other"))
    b.add_reservation(api.Reservation(
        meta=api.ObjectMeta(name="r"), node_name="host",
        phase="Available", requests={RK.CPU: 2000.0}))
    b.build(now=NOW)
    b.add_node(mk_node("host", cpu=48000.0))  # update in place
    with pytest.raises(ValueError, match="reservation"):
        b.topology_delta(["host"], now=NOW, pad_to=2)
    # churn on nodes WITHOUT reservations still works
    b.add_node(mk_node("fresh"))
    delta = b.topology_delta(["fresh"], now=NOW, pad_to=2)
    assert int(np.asarray(delta.idx)[0]) == b.node_index["fresh"]

    # syncer route: the ValueError lands as a full rebuild, not a crash
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=4, delta_pad=2)
    hub.upsert_node(mk_node("host"))
    hub.upsert_reservation(api.Reservation(
        meta=api.ObjectMeta(name="r"), node_name="host",
        phase="Available", requests={RK.CPU: 2000.0}))
    assert syncer.sync(now=NOW) == "full"
    hub.delete_node("host")  # reservation CR deletion lags
    assert syncer.sync(now=NOW) == "full"
    assert syncer.topology_ingests == 0


def test_replacement_at_full_capacity_stays_incremental():
    """Regression: removals are processed before adds, so a same-window
    node replacement at max_nodes capacity keeps the O(K) path instead
    of tripping a spurious capacity error."""
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=2, delta_pad=4)
    hub.upsert_node(mk_node("aaa"))
    hub.upsert_node(mk_node("bbb"))
    assert syncer.sync(now=NOW) == "full"
    # 'aa-new' sorts BEFORE 'bbb': without removals-first ordering the
    # add would hit the capacity ceiling before the remove frees a row
    hub.delete_node("bbb")
    hub.upsert_node(mk_node("aa-new", cpu=48000.0))
    assert syncer.sync(now=NOW) == "topology"
    assert syncer.full_rebuilds == 1
    snap = store.current()
    i_new = syncer.builder.node_index["aa-new"]
    assert float(np.asarray(snap.nodes.allocatable)[i_new, int(RK.CPU)]) \
        == 48000.0


def test_freed_rows_are_reused():
    b = SnapshotBuilder(max_nodes=2)
    b.add_node(mk_node("a"))
    b.add_node(mk_node("b"))
    freed = b.remove_node("a")
    # at capacity: the new node must land on the freed row
    assert b.add_node(mk_node("c")) == freed
    with pytest.raises(ValueError):
        b.add_node(mk_node("d"))


def test_syncer_routes_node_churn_as_topology_ingest():
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=8, delta_pad=4,
                            max_gpu_inst=2)
    hub.upsert_node(mk_node("n0"))
    hub.upsert_node(mk_node("n1"))
    hub.set_node_metric(mk_metric("n0", 1000.0))
    assert syncer.sync(now=NOW) == "full"
    v0 = store.version

    # node add: O(K) topology ingest, not a rebuild
    hub.upsert_node(mk_node("n2", cpu=48000.0))
    assert syncer.sync(now=NOW) == "topology"
    assert syncer.full_rebuilds == 1 and syncer.topology_ingests == 1
    assert store.version == v0 + 1
    snap = store.current()
    i2 = syncer.builder.node_index["n2"]
    assert float(np.asarray(snap.nodes.allocatable)[i2, int(RK.CPU)]) \
        == 48000.0
    assert bool(np.asarray(snap.nodes.schedulable)[i2])

    # node delete: zeroing row
    hub.delete_node("n0")
    assert syncer.sync(now=NOW) == "topology"
    snap = store.current()
    assert not np.asarray(snap.nodes.schedulable)[0]
    assert "n0" not in syncer.builder.node_index

    # device CR churn rides the same path
    hub.set_device(mk_device("n2"))
    assert syncer.sync(now=NOW) == "topology"
    snap = store.current()
    assert np.asarray(snap.devices.gpu_valid)[i2].sum() == 2
    # metric churn alone is still the metric delta
    hub.set_node_metric(mk_metric("n1", 500.0))
    assert syncer.sync(now=NOW) == "delta"
    # pod churn still rebuilds (requested/spread state lives there)
    hub.upsert_pod(api.Pod(meta=api.ObjectMeta(name="p", uid="u"),
                           node_name="n1", phase="Running",
                           requests={RK.CPU: 100.0}))
    assert syncer.sync(now=NOW) == "full"


def test_scheduling_lands_on_incrementally_added_node():
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig

    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=4, delta_pad=2)
    hub.upsert_node(mk_node("small", cpu=1000.0, mem=1024.0))
    syncer.sync(now=NOW)
    hub.upsert_node(mk_node("big", cpu=64000.0, mem=131072.0))
    assert syncer.sync(now=NOW) == "topology"

    pods = [api.Pod(meta=api.ObjectMeta(name=f"p{j}"), priority=9000,
                    requests={RK.CPU: 4000.0, RK.MEMORY: 1024.0})
            for j in range(4)]
    batch = syncer.builder.build_pod_batch(pods, syncer.ctx)
    res = core.schedule_batch(store.current(), batch,
                              LoadAwareConfig.make(), num_rounds=2,
                              k_choices=2)
    a = np.asarray(res.assignment)
    big = syncer.builder.node_index["big"]
    assert (a == big).all()  # only the new node fits 4000m pods


def test_10k_churn_is_o_k_not_o_n():
    """1%% node churn of a 10k-node cluster must ingest via the
    topology path and cost a small fraction of the full rebuild."""
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=10_000, delta_pad=128)
    for i in range(10_000):
        hub.upsert_node(mk_node(f"n{i}", labels={"zone": f"z{i % 16}"}))
    t0 = time.perf_counter()
    assert syncer.sync(now=NOW) == "full"
    full_s = time.perf_counter() - t0

    # warm the delta program (first call compiles)
    hub.upsert_node(mk_node("n0", cpu=48000.0,
                            labels={"zone": "z0"}))
    assert syncer.sync(now=NOW) == "topology"

    # 1% churn = 100 changed rows: 25 nodes replaced (50 dirty names:
    # the removed and the new), 50 updated in place
    for i in range(25):
        hub.delete_node(f"n{100 + i}")
        hub.upsert_node(mk_node(f"new{i}", labels={"zone": "z9"}))
    for i in range(50):
        hub.upsert_node(mk_node(f"n{i}", cpu=96000.0,
                                labels={"zone": f"z{i % 16}"}))
    t0 = time.perf_counter()
    assert syncer.sync(now=NOW) == "topology"
    churn_s = time.perf_counter() - t0
    assert syncer.full_rebuilds == 1
    # the latency bound VERDICT asks to pin: O(K) ingest must beat the
    # O(N) rebuild by a wide margin (and stay interactive in absolute
    # terms)
    assert churn_s < full_s / 3, (churn_s, full_s)
    assert churn_s < 2.0, churn_s
    snap = store.current()
    i_new = syncer.builder.node_index["new0"]
    assert bool(np.asarray(snap.nodes.schedulable)[i_new])
    # the 25 freed rows were all reused by the 25 new nodes (compact:
    # capacity did not grow), and the removed names are gone
    assert "n100" not in syncer.builder.node_index
    assert len(syncer.builder.node_index) == 10_000
    assert not syncer.builder._free_rows
    assert int(np.asarray(snap.nodes.schedulable).sum()) == 10_000
