"""The north-star bench's multi-device path on the virtual 8-CPU mesh.

`bench.py` shards the node axis over the mesh when >1 device is visible
(parallel/mesh.py); the driver runs it on real hardware, this test proves
the sharded program compiles, executes, and places every pod on 8 virtual
devices (conftest forces the 8-device CPU platform).
"""

import importlib
import json
import os

import jax


def test_bench_runs_sharded_on_8_device_mesh(capsys, monkeypatch):
    assert len(jax.devices()) == 8
    monkeypatch.setenv("BENCH_NODES", "800")
    monkeypatch.setenv("BENCH_PODS", "4000")
    monkeypatch.setenv("BENCH_CHUNK", "500")
    import bench
    importlib.reload(bench)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert result["devices"] == 8
    assert result["placed"] == 4000
    assert result["value"] > 0
