"""The north-star bench's multi-device path on the virtual 8-CPU mesh.

`bench.py` shards the node axis over the mesh when >1 device is visible
(parallel/mesh.py); the driver runs it on real hardware, this test proves
the sharded program compiles, executes, and places every pod on 8 virtual
devices (conftest forces the 8-device CPU platform).
"""

import importlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np


def test_bench_runs_sharded_on_8_device_mesh(capsys, monkeypatch):
    assert len(jax.devices()) == 8
    monkeypatch.setenv("BENCH_NODES", "800")
    monkeypatch.setenv("BENCH_PODS", "4000")
    monkeypatch.setenv("BENCH_CHUNK", "500")
    # extras (configs 2-5 + full gate) run at their own scales; the
    # full-gate sharded path has its own test below
    monkeypatch.setenv("BENCH_EXTRAS", "0")
    import bench
    importlib.reload(bench)
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert result["devices"] == 8
    # multi-device lines stamp their mesh shape (1D node mesh here)
    assert result["mesh"] == {"nodes": 8}
    assert result["placed"] == 4000
    assert result["value"] > 0
    # the slim canonical line is self-describing: device-resident tail,
    # cascade off (the byte-stable canonical protocol)
    assert result["tail_mode"] == "device"
    assert result["cascade"] is False


def test_bench_full_gate_sharded(capsys, monkeypatch):
    """The FULL-gate flagship path (NUMA + GPU + taints + spread +
    anti/affinity all compiled in) on the 8-device mesh, with the
    topology counts carried across chunks."""
    monkeypatch.setenv("BENCH_NODES", "800")
    monkeypatch.setenv("BENCH_PODS", "4000")
    monkeypatch.setenv("BENCH_FULL_CHUNK", "500")
    import bench
    importlib.reload(bench)
    result = bench.run_northstar(full_gate=True)
    assert result["devices"] == 8
    assert result["mesh"] == {"nodes": 8}
    # tight topology constraints leave stragglers; the bulk must place
    assert result["placed"] > 3000
    assert result["metric"].endswith("full_gate")
    assert result["never_retried"] == 0
    # full-gate runs through the gate cascade + device tail by default
    assert result["cascade"] is True
    assert result["tail_mode"] == "device"


def test_topology_delta_ingests_into_a_sharded_store():
    """Node churn must stay O(K) on a MESH deployment too: the jitted
    topology scatter runs against node columns sharded over the
    8-device mesh (GSPMD handles the scatter placement), and the
    patched row is visible to a subsequent sharded schedule step."""
    from koordinator_tpu.api import types as api
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.parallel import make_mesh, snapshot_sharding
    from koordinator_tpu.snapshot import SnapshotStore
    from koordinator_tpu.snapshot.builder import SnapshotBuilder

    mesh = make_mesh(jax.devices())
    store = SnapshotStore(sharding=snapshot_sharding(mesh))
    b = SnapshotBuilder(max_nodes=16)
    for i in range(16):
        b.add_node(api.Node(meta=api.ObjectMeta(name=f"n{i}"),
                            allocatable={RK.CPU: 16000.0,
                                         RK.MEMORY: 32768.0}))
    snap, ctx = b.build(now=1e9)
    store.publish(snap)

    b.add_node(api.Node(meta=api.ObjectMeta(name="n3"),
                        allocatable={RK.CPU: 96000.0,
                                     RK.MEMORY: 262144.0}))
    with mesh:
        store.ingest(b.topology_delta(["n3"], now=1e9, pad_to=4))
    got = store.current()
    assert float(np.asarray(got.nodes.allocatable)[3, int(RK.CPU)]) \
        == 96000.0

    # a sharded schedule step sees the patched capacity
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig

    pods = [api.Pod(meta=api.ObjectMeta(name=f"p{j}"), priority=9000,
                    requests={RK.CPU: 20000.0, RK.MEMORY: 4096.0})
            for j in range(2)]
    batch = b.build_pod_batch(pods, ctx)
    with mesh:
        res = core.schedule_batch(got, batch, LoadAwareConfig.make(),
                                  num_rounds=2, k_choices=2)
    a = np.asarray(res.assignment)
    assert (a == 3).all()  # only the upgraded node fits 20-core pods


def test_anti_affinity_holds_across_chunks():
    """Regression for the cross-chunk count rule: carriers of one anti
    group scheduled in DIFFERENT chunks still land in distinct domains,
    because the bench threads core.charge_domain_counts output into the
    next chunk's count0 (core.domain_machinery's cross-batch contract).
    """
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    n_nodes, n_zones = 16, 4
    snap = synthetic.synthetic_cluster(n_nodes, seed=0)
    zone_of_node = (np.arange(n_nodes) % n_zones).astype(np.int32)

    def carriers(num):
        pods = synthetic.synthetic_pods(num, seed=3, prod_frac=1.0)
        return pods.replace(
            anti_id=np.zeros((num,), np.int32),
            anti_member=np.ones((num, 1), bool),
            anti_carrier=np.ones((num, 1), bool),
            anti_domain=zone_of_node[None, :].copy(),
            anti_count0=np.zeros((1, n_zones), np.float32),
            anti_carrier_count0=np.zeros((1, n_zones), np.float32),
            has_anti=True)

    counts = (jnp.zeros((1, n_zones), jnp.float32),
              jnp.zeros((1, n_zones), jnp.float32))
    zones = []
    for _ in range(2):  # two chunks of 2 carriers each
        batch = carriers(2).replace(anti_count0=counts[0],
                                    anti_carrier_count0=counts[1])
        res = core.schedule_batch(snap, batch, LoadAwareConfig.make(),
                                  num_rounds=2, k_choices=4,
                                  enable_numa=False)
        a = np.asarray(res.assignment)
        assert (a >= 0).all()
        zones.extend(zone_of_node[a].tolist())
        snap = res.snapshot
        counts = (
            core.charge_domain_counts(counts[0], batch.anti_domain,
                                      batch.anti_member, res.assignment),
            core.charge_domain_counts(counts[1], batch.anti_domain,
                                      batch.anti_carrier, res.assignment),
        )
    # 4 carriers over 4 zones: all distinct IFF the second chunk saw the
    # first chunk's charges
    assert len(set(zones)) == 4, zones
    assert np.asarray(counts[0]).sum() == 4.0
