"""Delta ingest + forget/un-assume (snapshot/delta.py;
scheduler_adapter.go assume/forget; SURVEY §7 hard part (e) — snapshot
freshness within the cycle budget).

Invariants:
- applying a metric delta produces EXACTLY the columns a full rebuild
  would (the two paths share builder._metric_row);
- forget is the inverse of the schedule commit: capacity flows back and a
  retry succeeds where the stale snapshot would have rejected;
- a 10k-node ingest tick fits far inside the 2 s cycle budget.
"""

import time

import numpy as np

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import Node, NodeMetric, ObjectMeta, Pod, Reservation
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot import SnapshotBuilder, SnapshotStore
from koordinator_tpu.snapshot.delta import apply_metric_delta, forget_pods

NOW = 1_700_000_000.0
CFG = loadaware.LoadAwareConfig.make()


def make_builder(n=4, cpu=10_000.0, mem=20_480.0):
    b = SnapshotBuilder(max_nodes=n)
    for i in range(n):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: cpu, RK.MEMORY: mem}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW - 5,
                                     node_usage={RK.CPU: 500.0,
                                                 RK.MEMORY: 1024.0}))
    return b


def test_metric_delta_matches_full_rebuild():
    b = make_builder()
    snap, _ = b.build(now=NOW)
    # two nodes report new metrics
    b.set_node_metric(NodeMetric(node_name="n1", update_time=NOW + 5,
                                 node_usage={RK.CPU: 4_000.0,
                                             RK.MEMORY: 8_192.0}))
    b.set_node_metric(NodeMetric(node_name="n3", update_time=NOW + 5,
                                 node_usage={RK.CPU: 9_999.0}))
    delta = b.metric_delta(["n1", "n3"], now=NOW + 6, pad_to=4)
    patched = apply_metric_delta(snap, delta)
    rebuilt, _ = b.build(now=NOW + 6)
    for field in ("usage", "prod_usage", "agg_usage", "metric_fresh",
                  "has_agg", "assigned_estimated", "assigned_correction",
                  "prod_assigned_estimated", "prod_assigned_correction"):
        np.testing.assert_allclose(
            np.asarray(getattr(patched.nodes, field)),
            np.asarray(getattr(rebuilt.nodes, field)),
            err_msg=field)


def test_metric_delta_expired_marks_stale():
    b = make_builder()
    snap, _ = b.build(now=NOW)
    assert bool(np.asarray(snap.nodes.metric_fresh)[2])
    # n2's metric ages out -> the delta marks it unfresh
    delta = b.metric_delta(["n2"], now=NOW + 10_000, pad_to=2)
    patched = apply_metric_delta(snap, delta)
    fresh = np.asarray(patched.nodes.metric_fresh)
    assert not fresh[2] and fresh[0] and fresh[1] and fresh[3]


def test_store_ingest_bumps_version_without_rebuild():
    b = make_builder()
    snap, _ = b.build(now=NOW)
    store = SnapshotStore()
    store.publish(snap)
    v0 = store.version
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW + 1,
                                 node_usage={RK.CPU: 7_000.0}))
    store.ingest(b.metric_delta(["n0"], now=NOW + 2, pad_to=2))
    assert store.version == v0 + 1
    got = np.asarray(store.current().nodes.usage)[0, int(RK.CPU)]
    np.testing.assert_allclose(got, 7_000.0)


def test_forget_returns_capacity_and_allows_retry():
    # fill a node, forget the pod, the same request fits again
    b = make_builder(n=1, cpu=4_000.0)
    snap, ctx = b.build(now=NOW)
    pod = Pod(meta=ObjectMeta(name="p"),
              requests={RK.CPU: 3_000.0, RK.MEMORY: 2_048.0}, priority=9000)
    batch = b.build_pod_batch([pod], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=2)
    assert int(res.assignment[0]) == 0
    # without forget the next identical pod cannot fit
    res2 = core.schedule_batch(res.snapshot, batch, CFG, num_rounds=2)
    assert int(res2.assignment[0]) == -1
    # bind failed -> forget -> retry fits
    reverted = forget_pods(res.snapshot, batch, res,
                           np.asarray([True]))
    np.testing.assert_allclose(np.asarray(reverted.nodes.requested),
                               np.asarray(snap.nodes.requested))
    res3 = core.schedule_batch(reverted, batch, CFG, num_rounds=2)
    assert int(res3.assignment[0]) == 0


def test_forget_restores_reservation_consumer():
    b = make_builder(n=1)
    b.add_reservation(Reservation(
        meta=ObjectMeta(name="r0"),
        requests={RK.CPU: 4_000.0, RK.MEMORY: 4_096.0},
        owner_label_selector={"team": "a"}, allocate_once=True,
        node_name="n0", phase="Available"))
    snap, ctx = b.build(now=NOW)
    owner = Pod(meta=ObjectMeta(name="o", labels={"team": "a"}),
                requests={RK.CPU: 2_000.0, RK.MEMORY: 2_048.0},
                priority=9000)
    batch = b.build_pod_batch([owner], ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=2)
    assert int(res.assignment[0]) == 0
    assert int(res.res_slot[0]) == 0
    rv = res.snapshot.reservations
    assert not bool(np.asarray(rv.valid)[0])  # AllocateOnce consumed
    reverted = forget_pods(res.snapshot, batch, res, np.asarray([True]))
    rv2 = reverted.reservations
    assert bool(np.asarray(rv2.valid)[0])     # slot re-opened
    np.testing.assert_allclose(np.asarray(rv2.free)[0, int(RK.CPU)],
                               4_000.0)
    # node requested unchanged by the consumer round-trip
    np.testing.assert_allclose(np.asarray(reverted.nodes.requested),
                               np.asarray(snap.nodes.requested))


def test_stale_and_duplicate_deltas_noop_idempotently():
    """The replay guard (ISSUE 13 satellite): a delta whose version is
    <= the applied one must NOT scatter — before this guard, replaying
    v1 after v2 silently overwrote n0's fresher usage with the stale
    row."""
    from koordinator_tpu.snapshot.delta import DeltaRejectReason

    b = make_builder()
    snap, _ = b.build(now=NOW)
    store = SnapshotStore()
    store.publish(snap)
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW + 1,
                                 node_usage={RK.CPU: 1_000.0}))
    d1 = b.metric_delta(["n0"], now=NOW + 1, pad_to=2)
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW + 2,
                                 node_usage={RK.CPU: 2_000.0}))
    d2 = b.metric_delta(["n0"], now=NOW + 2, pad_to=2)
    assert int(np.asarray(d2.source_version)) \
        > int(np.asarray(d1.source_version))

    store.ingest(d2)
    assert store.take_delta_rejection() is None
    v_after = store.version
    fresh_usage = np.asarray(store.current().nodes.usage).copy()

    # out-of-order replay of d1: idempotent no-op with a typed reason
    out = store.ingest(d1)
    assert store.take_delta_rejection() is DeltaRejectReason.STALE_VERSION
    assert store.version == v_after
    np.testing.assert_array_equal(np.asarray(out.nodes.usage),
                                  fresh_usage)
    # exact duplicate of d2: same, but named a duplicate
    store.ingest(d2)
    assert store.take_delta_rejection() \
        is DeltaRejectReason.DUPLICATE_VERSION
    assert store.version == v_after and store.delta_rejections == 2
    np.testing.assert_array_equal(
        np.asarray(store.current().nodes.usage)[0, int(RK.CPU)], 2_000.0)


def test_publish_opens_a_new_delta_epoch():
    """A restarted producer restarts its sequence at 1; the full publish
    resets the high-water mark so the fresh sequence is not rejected
    against the previous epoch."""
    b = make_builder()
    snap, _ = b.build(now=NOW)
    store = SnapshotStore()
    store.publish(snap)
    for _ in range(3):
        b.set_node_metric(NodeMetric(node_name="n0",
                                     update_time=NOW + 1,
                                     node_usage={RK.CPU: 100.0}))
        store.ingest(b.metric_delta(["n0"], now=NOW + 1, pad_to=2))
    assert store.applied_delta_version == 3
    store.publish(snap)  # rebuild: new epoch
    assert store.applied_delta_version == 0
    b2 = make_builder()  # restarted producer: sequence restarts at 1
    b2.set_node_metric(NodeMetric(node_name="n0", update_time=NOW + 5,
                                  node_usage={RK.CPU: 4_242.0}))
    store.ingest(b2.metric_delta(["n0"], now=NOW + 6, pad_to=2))
    assert store.take_delta_rejection() is None
    np.testing.assert_allclose(
        np.asarray(store.current().nodes.usage)[0, int(RK.CPU)], 4_242.0)


def test_service_ingest_surfaces_rejection_metric():
    from koordinator_tpu.metrics import Registry
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics

    reg = Registry()
    svc = SchedulerService(metrics=SchedulerMetrics(reg))
    b = make_builder()
    snap, _ = b.build(now=NOW)
    svc.publish(snap)
    d1 = b.metric_delta(["n0"], now=NOW + 1, pad_to=2)
    d2 = b.metric_delta(["n1"], now=NOW + 2, pad_to=2)
    svc.ingest(d2)
    v = svc.last_committed_version
    assert svc.ingest(d1) == v  # stale: version unchanged
    exposed = reg.expose()
    assert 'scheduler_delta_rejected{reason="stale_version"} 1' in exposed


def test_unversioned_delta_always_applies():
    """The sidecar wire format carries no source_version yet; a delta
    with source_version=None must keep the pre-guard semantics."""
    b = make_builder()
    snap, _ = b.build(now=NOW)
    store = SnapshotStore()
    store.publish(snap)
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW + 1,
                                 node_usage={RK.CPU: 777.0}))
    delta = b.metric_delta(["n0"], now=NOW + 2, pad_to=2)
    delta = delta.replace(source_version=None)
    for _ in range(2):  # replays apply too — no guard without a version
        store.ingest(delta)
        assert store.take_delta_rejection() is None
    np.testing.assert_allclose(
        np.asarray(store.current().nodes.usage)[0, int(RK.CPU)], 777.0)


def test_ingest_10k_nodes_fits_cycle_budget():
    n = 10_000
    b = SnapshotBuilder(max_nodes=n)
    for i in range(n):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 32_000.0, RK.MEMORY: 65_536.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW - 5,
                                     node_usage={RK.CPU: 1_000.0}))
    snap, _ = b.build(now=NOW)
    store = SnapshotStore()
    store.publish(snap)
    # a realistic tick: 256 nodes report between cycles
    names = [f"n{i}" for i in range(0, 2560, 10)]
    for name in names[:16]:
        b.set_node_metric(NodeMetric(node_name=name, update_time=NOW + 1,
                                     node_usage={RK.CPU: 5_000.0}))
    delta = b.metric_delta(names, now=NOW + 2, pad_to=256)
    store.ingest(delta)  # warm-up compiles the scatter program
    t0 = time.perf_counter()
    for tick in range(5):
        # fresh versions per tick: the replay guard would otherwise
        # no-op every repeat and the loop would time nothing
        out = store.ingest(delta.replace(
            source_version=np.asarray(delta.source_version) + 1 + tick))
    np.asarray(out.nodes.usage)  # force materialization
    per_tick = (time.perf_counter() - t0) / 5
    # SURVEY §7: the whole scheduling cycle has a 2 s budget; ingest must
    # be a rounding error within it
    assert per_tick < 2.0, f"ingest tick took {per_tick:.3f}s"