"""The promoted sharded flagship path (ISSUE 11): spec-derived
shardings, node-axis padding, the 2D pods x nodes mesh option, the
explicit shard_map kernels, and full-gate placement conformance against
the single-device oracle.

Fast tests run tiny slim-gate programs (cheap compiles); the 4-device
full-gate conformance run is slow-marked — the same ground gates every
push as a dedicated tools/ci.sh stage (tools/mesh_flagship_smoke.py at
2 devices).
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.parallel import (
    NODE_AXIS,
    POD_AXIS,
    batch_sharding,
    make_mesh,
    mesh_axis_sizes,
    pad_batch_nodes,
    pad_nodes_to_mesh,
    padded_node_count,
    shard_batch,
    shard_snapshot,
    shardops,
    snapshot_sharding,
    struct_sharding,
)
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.cascade import stage1_mask, static_gates
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.snapshot.schema import STRUCT_SPECS
from koordinator_tpu.utils import synthetic


def test_make_mesh_shapes():
    mesh1 = make_mesh(jax.devices())
    assert mesh_axis_sizes(mesh1) == {"nodes": 8}
    mesh2 = make_mesh(jax.devices(), pods_axis=2)
    assert mesh_axis_sizes(mesh2) == {"pods": 2, "nodes": 4}
    assert mesh2.axis_names == (POD_AXIS, NODE_AXIS)
    with pytest.raises(ValueError):
        make_mesh(jax.devices(), pods_axis=3)  # 3 does not divide 8


def test_snapshot_sharding_derived_from_specs():
    """Every snapshot leaf whose registered spec leads with N is
    node-sharded; every other leaf replicates — the layout is a pure
    function of the koordshape field tables, so a new field cannot
    silently get the wrong placement."""
    mesh = make_mesh(jax.devices())
    sh = snapshot_sharding(mesh)
    for group, struct in (("nodes", "NodeState"), ("devices", "DeviceState"),
                          ("quotas", "QuotaState"), ("gangs", "GangState"),
                          ("reservations", "ReservationState")):
        sub = getattr(sh, group)
        for fname, spec in STRUCT_SPECS[struct].items():
            if "[" not in spec:
                continue  # symbolic-int property
            dims = spec[spec.index("[") + 1:spec.rindex("]")].split(",")
            lead = dims[0].split("~")[0].strip() if dims else ""
            want = NODE_AXIS if lead == "N" else None
            got = getattr(sub, fname).spec
            assert (got[0] if len(got) else None) == want, \
                (group, fname, got)
    assert sh.version.spec == jax.sharding.PartitionSpec()


def test_result_sharding_derived():
    mesh = make_mesh(jax.devices())
    rs = struct_sharding("ScheduleResult", mesh)
    assert rs.assignment.spec == jax.sharding.PartitionSpec()
    assert rs.snapshot.nodes.requested.spec[0] == NODE_AXIS


def _anti_pods(num, n_nodes, n_zones, seed=3):
    """Slim pods + one hand-built hostname-free anti group over zone
    domains — real [*, N] domain matrices without the full gate set's
    compile cost."""
    zone_of_node = (np.arange(n_nodes) % n_zones).astype(np.int32)
    pods = synthetic.synthetic_pods(num, seed=seed, prod_frac=1.0)
    return pods.replace(
        anti_id=np.zeros((num,), np.int32),
        anti_member=np.ones((num, 1), bool),
        anti_carrier=np.ones((num, 1), bool),
        anti_domain=zone_of_node[None, :].copy(),
        anti_count0=np.zeros((1, n_zones), np.float32),
        anti_carrier_count0=np.zeros((1, n_zones), np.float32),
        has_anti=True)


def test_pad_boundary_indivisible_nodes():
    """The fast boundary pin: a mesh-size-indivisible node count goes
    through pad_nodes_to_mesh/pad_batch_nodes, and the sharded program
    places bit-identically to the unpadded single-device oracle; pad
    rows are provably unschedulable and never charged."""
    mesh = make_mesh(jax.devices())  # 8-way node axis
    n_real = 13
    n_pad = padded_node_count(n_real, mesh)
    assert n_pad == 16
    snap_h = synthetic.synthetic_cluster(n_real, seed=0)
    pods = _anti_pods(6, n_real, n_zones=4)
    cfg = LoadAwareConfig.make()

    res1 = core.schedule_batch(snap_h, pods, cfg, num_rounds=2,
                               k_choices=4, enable_numa=False,
                               enable_devices=False)
    a1 = np.asarray(res1.assignment)
    assert (a1 >= 0).any()

    padded = pad_nodes_to_mesh(snap_h, mesh)
    assert padded.num_nodes == n_pad
    pods_p = pad_batch_nodes(pods, n_pad)
    assert pods_p.anti_domain.shape == (1, n_pad)
    assert (np.asarray(pods_p.anti_domain)[:, n_real:] == -1).all()
    snap_d = shard_snapshot(padded, mesh)
    with mesh:
        res8 = core.schedule_batch(snap_d, pods_p, cfg, num_rounds=2,
                                   k_choices=4, enable_numa=False,
                                   enable_devices=False)
    a8 = np.asarray(res8.assignment)
    assert np.array_equal(a8, a1)
    assert a8.max() < n_real  # pad rows unassigned
    assert core.overcommit_ok(res8.snapshot, n_real)
    assert not np.asarray(res8.snapshot.nodes.requested)[n_real:].any()

    # the stage-1 mask kills pad columns (the pad-row contract)
    static_ok, _ = static_gates(snap_d.nodes, pods_p, cfg)
    mask = np.asarray(stage1_mask(snap_d, pods_p, static_ok))
    assert not mask[:, n_real:].any()


def test_pad_noop_and_consistency_checks():
    mesh = make_mesh(jax.devices())
    snap = synthetic.synthetic_cluster(16, seed=0)
    assert pad_nodes_to_mesh(snap, mesh) is snap  # divisible: no-op
    pods = synthetic.synthetic_pods(4, seed=1)
    # slim [1, 1] compile-out domain matrices: nothing to pad
    assert pad_batch_nodes(pods, 16) is pods
    bad = pods.replace(anti_domain=np.zeros((1, 24), np.int32))
    with pytest.raises(ValueError):
        pad_batch_nodes(bad, 16)  # extent beyond the padded count


def test_overcommit_ok_detects_charged_pad_row():
    snap = synthetic.synthetic_cluster(8, seed=0)
    assert core.overcommit_ok(snap, 6)
    req = np.asarray(snap.nodes.requested).copy()
    req[7, 0] = 1.0  # a pad row got charged: must fail loudly
    assert not core.overcommit_ok(
        snap.replace(nodes=snap.nodes.replace(requested=req)), 6)


def test_shard_local_topk_matches_lax_top_k_with_ties():
    """The ICI merge kernel is bit-identical to lax.top_k, ties
    included (lexicographic value-desc / index-asc order)."""
    mesh = make_mesh(jax.devices())
    rng = np.random.default_rng(0)
    x = rng.integers(0, 4, size=(16, 64)).astype(np.float32)  # heavy ties
    x[3] = -1.0  # an all-infeasible row
    for k in (1, 5, 8):
        v0, i0 = jax.lax.top_k(jnp.asarray(x), k)
        v1, i1 = jax.jit(
            lambda a, k=k: shardops.shard_local_topk(mesh, a, k))(
                jnp.asarray(x))
        assert np.array_equal(np.asarray(v0), np.asarray(v1)), k
        assert np.array_equal(np.asarray(i0), np.asarray(i1)), k
    with pytest.raises(ValueError):
        shardops.shard_local_topk(mesh, jnp.asarray(x), 9)  # k > local
    with pytest.raises(ValueError):
        shardops.shard_local_topk(mesh, jnp.asarray(x[:, :60]), 4)


def test_stage1_mask_sharded_conformance():
    mesh = make_mesh(jax.devices())
    snap = synthetic.synthetic_cluster(16, seed=0, num_quotas=4)
    pods = synthetic.synthetic_pods(12, seed=1, num_quotas=4)
    cfg = LoadAwareConfig.make()
    snap_d = shard_snapshot(snap, mesh)
    static_ok, _ = static_gates(snap_d.nodes, pods, cfg)
    g = np.asarray(stage1_mask(snap_d, pods, static_ok))
    s = np.asarray(jax.jit(
        lambda sn, pd, so: shardops.stage1_mask_sharded(mesh, sn, pd, so)
    )(snap_d, pods, static_ok))
    assert np.array_equal(g, s)


def test_2d_pods_nodes_mesh_conformance():
    """The 2D mesh option is layout, not semantics: a 2x2 pods x nodes
    mesh with the batch sharded over the pods axis places bit-
    identically to the single-device program."""
    mesh = make_mesh(jax.devices()[:4], pods_axis=2)
    snap_h = synthetic.synthetic_cluster(16, seed=0)
    pods = _anti_pods(8, 16, n_zones=4)
    cfg = LoadAwareConfig.make()
    res1 = core.schedule_batch(snap_h, pods, cfg, num_rounds=2,
                               k_choices=4, enable_numa=False,
                               enable_devices=False)
    sh = batch_sharding(pods, mesh)
    assert sh.requests.spec[0] == POD_AXIS
    assert sh.anti_domain.spec == jax.sharding.PartitionSpec(None,
                                                             NODE_AXIS)
    assert sh.anti_count0.spec == jax.sharding.PartitionSpec()
    with mesh:
        res2 = core.schedule_batch(shard_snapshot(snap_h, mesh),
                                   shard_batch(pods, mesh), cfg,
                                   num_rounds=2, k_choices=4,
                                   enable_numa=False,
                                   enable_devices=False)
    assert np.array_equal(np.asarray(res2.assignment),
                          np.asarray(res1.assignment))


@pytest.mark.slow
def test_full_gate_sharded_conformance_4dev(monkeypatch):
    """The ISSUE 11 conformance pin at test scale: the full-gate
    flagship on a 4-device virtual CPU mesh (node count indivisible by
    4, so padding rides the hot path) and on one device from the same
    seed place BIT-IDENTICALLY (exact top-k path), the overcommit
    invariant holds on real rows, and the multichip line is stamped
    with its mesh shape. Slow-marked: tools/ci.sh runs the same check
    at 2 devices as a dedicated stage on every push."""
    monkeypatch.setenv("BENCH_NODES", "205")
    monkeypatch.setenv("BENCH_PODS", "2000")
    monkeypatch.setenv("BENCH_FULL_CHUNK", "500")
    monkeypatch.setenv("BENCH_MAX_TAIL_PASSES", "4")
    monkeypatch.setenv("BENCH_EXTRAS", "0")
    import bench
    importlib.reload(bench)

    monkeypatch.setenv("BENCH_DEVICES", "4")
    multi = bench.run_northstar(full_gate=True)
    monkeypatch.setenv("BENCH_DEVICES", "1")
    single = bench.run_northstar(full_gate=True)

    assert multi["devices"] == 4 and single["devices"] == 1
    assert multi["mesh"] == {"nodes": 4}
    assert "mesh" not in single
    assert multi["cascade"] is True and multi["tail_mode"] == "device"
    a_m = multi["arrays"]["assignment"]
    a_s = single["arrays"]["assignment"]
    assert (a_m >= 0).sum() > 1000
    assert np.array_equal(a_m, a_s)
    n_real = multi["arrays"]["num_nodes"]
    assert n_real == 205 and a_m.max() < n_real
    req = multi["arrays"]["requested"]
    assert req.shape[0] == 208  # padded to the 4-way node axis
    assert core.overcommit_arrays_ok(req, multi["arrays"]["allocatable"],
                                     n_real)
