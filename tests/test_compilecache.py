"""The warm-start seam (ISSUE 17): cache-key invalidation pins,
manifest provenance (corrupt/stale state discarded loudly, never
served), and the compile-counter-backed zero-recompile pins — a second
run, a restart recovery, and a mesh-shrink failover against a warmed
cache dir must compile zero programs.

The persistent cache is STRICTLY OPT-IN (tests/conftest.py keeps it
disabled: XLA:CPU artifacts segfault across live-migrating hosts).
Every test here activates it only against a fresh tmp dir — artifacts
are written and read by THIS process on THIS machine — and the fixture
detaches the process-global config afterwards.
"""

import json
import os
import types

import numpy as np
import pytest

import jax

from koordinator_tpu.compilecache import counters, keys, precompile
from koordinator_tpu.compilecache.cache import (
    CompileCache,
    _reset_jax_persistent_cache,
)
from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler.frameworkext import (
    DegradationLadder,
    SchedulerService,
)
from koordinator_tpu.scheduler.journal import CommitJournal
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.snapshot import schema
from koordinator_tpu.utils import synthetic

N, P = 16, 32


@pytest.fixture()
def cache_dir(tmp_path):
    """A fresh cache dir; teardown re-disables the process-global
    persistent cache (the conftest invariant) and drops jax's
    once-per-process cache singleton so later tests can't read it.

    Setup clears the in-process executable cache: a program an EARLIER
    test already jitted would otherwise be reused by this test's cold
    run without ever being written to this test's dir — and the warm
    run would then miss on it."""
    jax.clear_caches()
    yield str(tmp_path / "cc")
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_persistent_cache()


def service_inputs(seed=0):
    snap = synthetic.synthetic_cluster(N, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.synthetic_pods(P, seed=seed + 3, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


def make_service(cache, **kw):
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, guards=False,
                           compile_cache=cache, **kw)
    svc._sleep = lambda _s: None
    return svc


SMALL = {"P": 16, "N": 8, "G": 4, "Q": 4}


def small_ws(**kw):
    kw.setdefault("sizes", dict(SMALL))
    kw.setdefault("devices", 1)
    kw.setdefault("cascade_forms", (False,))
    kw.setdefault("tail", None)
    return precompile.WorkSet(**kw)


# --- key derivation & invalidation pins -----------------------------------

def test_fingerprint_is_deterministic():
    assert keys.contract_fingerprint() == keys.contract_fingerprint()


def test_contract_modules_in_sync_with_shapecheck():
    """The fingerprint must digest the SAME fully populated registry
    the shape gate checks — a module registered in one list but not
    the other silently weakens one of the two."""
    from tools import shapecheck
    assert set(keys.CONTRACT_MODULES) == set(shapecheck.CONTRACT_MODULES)


def test_contract_spec_edit_changes_fingerprint():
    base = keys.contract_fingerprint()
    contracts = dict(schema.SHAPE_CONTRACTS)
    name = sorted(contracts)[0]
    c = contracts[name]
    contracts[name] = types.SimpleNamespace(
        args=c.args, returns=c.returns, static=c.static,
        callables=c.callables, pad=(c.pad or "") + " (edited)")
    assert keys.contract_fingerprint(contracts=contracts) != base


def test_struct_field_dtype_edit_changes_fingerprint():
    base = keys.contract_fingerprint()
    structs = dict(schema.STRUCT_SPECS)
    ns = dict(structs["NodeState"])
    assert ns["usage"].startswith("f32[")
    ns["usage"] = "f16[" + ns["usage"].split("[", 1)[1]
    structs["NodeState"] = ns
    assert keys.contract_fingerprint(structs=structs) != base


def test_cache_key_folds_every_axis():
    fp = "a" * 64
    base = dict(program="cycle", inputs_digest="d0", statics={"k": 4},
                mesh_axes={"node": 2}, backend="cpu",
                jax_version="0.0.t", fingerprint=fp)
    k0 = keys.cache_key(**base)
    assert keys.cache_key(**base) == k0  # pure
    for field, other in [("program", "tail"), ("inputs_digest", "d1"),
                         ("statics", {"k": 8}),
                         ("mesh_axes", {"node": 4}),
                         ("mesh_axes", None), ("backend", "tpu"),
                         ("jax_version", "0.0.u"),
                         ("fingerprint", "b" * 64)]:
        assert keys.cache_key(**dict(base, **{field: other})) != k0, field


def test_callable_statics_key_on_dotted_name_not_repr():
    """A step_fn static must not bust the cache per process: its canon
    form carries the dotted name, never the object address."""
    c1 = keys._canon({"step": service_inputs})
    c2 = keys._canon({"step": service_inputs})
    assert c1 == c2 and "0x" not in c1 and "service_inputs" in c1


def test_abstract_digest_sees_shape_dtype_and_path():
    a = jax.ShapeDtypeStruct((4, 2), np.dtype("float32"))
    b = jax.ShapeDtypeStruct((4, 3), np.dtype("float32"))
    c = jax.ShapeDtypeStruct((4, 2), np.dtype("int32"))
    d0 = keys.abstract_digest({"x": a})
    assert keys.abstract_digest({"x": a}) == d0
    assert keys.abstract_digest({"x": b}) != d0  # shape
    assert keys.abstract_digest({"x": c}) != d0  # dtype
    assert keys.abstract_digest({"y": a}) != d0  # tree path


# --- manifest provenance ---------------------------------------------------

def test_corrupt_manifest_set_aside_and_discarded_loudly(cache_dir):
    os.makedirs(cache_dir)
    cache = CompileCache(cache_dir, fingerprint="a" * 64)
    with open(cache.manifest_path, "w") as f:
        f.write("{torn json")
    cache.activate()
    try:
        assert cache.manifest["entries"] == {}
        assert cache.discarded and "corrupt" in cache.discarded[0][1]
        aside = [p for p in os.listdir(cache_dir) if ".corrupt." in p]
        assert aside, "the torn file must be kept as evidence"
    finally:
        cache.deactivate()


def test_stale_fingerprint_entries_discarded_never_served(cache_dir):
    c1 = CompileCache(cache_dir, fingerprint="a" * 64).activate()
    try:
        assert c1.ensure("prog", lambda: "exe", key="k1") == "miss"
        assert c1.lookup("k1") is not None
    finally:
        c1.deactivate()
    # contract fingerprint moved -> the entry is dropped, loudly
    c2 = CompileCache(cache_dir, fingerprint="b" * 64).activate()
    try:
        assert c2.lookup("k1") is None
        assert c2.manifest["entries"] == {}
        assert any("fingerprint" in reason for _, reason in c2.discarded)
    finally:
        c2.deactivate()
    # same fingerprint -> still trusted
    c3 = CompileCache(cache_dir, fingerprint="a" * 64).activate()
    try:
        assert c3.lookup("k1") is not None and not c3.discarded
    finally:
        c3.deactivate()


def test_jax_version_and_backend_staleness(cache_dir):
    c1 = CompileCache(cache_dir, fingerprint="a" * 64).activate()
    try:
        c1.ensure("prog", lambda: "exe", key="k1")
    finally:
        c1.deactivate()
    with open(os.path.join(cache_dir, "manifest.json")) as f:
        raw = json.load(f)
    raw["entries"]["k1"]["jax_version"] = "0.0.0"
    with open(os.path.join(cache_dir, "manifest.json"), "w") as f:
        json.dump(raw, f)
    c2 = CompileCache(cache_dir, fingerprint="a" * 64).activate()
    try:
        assert c2.lookup("k1") is None
        assert any("jax 0.0.0" in reason for _, reason in c2.discarded)
    finally:
        c2.deactivate()


def test_ensure_memoizes_per_key(cache_dir):
    cache = CompileCache(cache_dir, fingerprint="a" * 64).activate()
    try:
        calls = {"n": 0}

        def build():
            calls["n"] += 1
            return object()

        assert cache.ensure("prog", build, key="k") == "miss"
        assert cache.ensure("prog", build, key="k") == "hit"
        assert calls["n"] == 1
        assert cache.hits == 1 and cache.misses == 1
        assert cache.stats()["entries"] == 1
    finally:
        cache.deactivate()


# --- compile-counter-backed warm-start pins --------------------------------

def test_jax_event_names_still_fire(cache_dir):
    """Pin the jax.monitoring event names counters.py listens on: with
    a cache dir active, a fresh compile fires a persistent-cache MISS;
    the same computation after clear_caches() fires a HIT."""
    cache = CompileCache(cache_dir).activate()
    try:
        x = np.arange(7.0, dtype=np.float32)
        with counters.watch() as w1:
            jax.jit(lambda v: v * 3 + 1)(x).block_until_ready()
        assert w1.cache_misses >= 1 and w1.backend_compiles >= 1
        assert w1.compile_seconds > 0
        jax.clear_caches()
        with counters.watch() as w2:
            jax.jit(lambda v: v * 3 + 1)(x).block_until_ready()
        assert w2.cache_hits >= 1 and w2.cache_misses == 0
    finally:
        cache.deactivate()


def test_precompile_second_run_compiles_nothing(cache_dir):
    """The headline pin: warm the (small) working set cold, then warm
    it again through a FRESH handle after clear_caches() — every
    program must come back from the persistent cache with zero XLA
    compilations."""
    ws = small_ws()
    c1 = CompileCache(cache_dir).activate()
    try:
        r1 = precompile.warm(c1, ws)
        assert r1["programs"] >= 1 and r1["miss"] == r1["programs"]
    finally:
        c1.deactivate()
    jax.clear_caches()
    c2 = CompileCache(cache_dir).activate()
    try:
        with counters.watch() as w:
            r2 = precompile.warm(c2, ws)
        assert r2["programs"] == r1["programs"]
        assert r2["miss"] == 0 and r2["warm"] == r2["programs"]
        assert w.cache_misses == 0, \
            "second warm() run must compile zero programs"
        assert c2.hits == r2["programs"] and c2.misses == 0
    finally:
        c2.deactivate()


def test_enumerator_covers_the_shrunk_mesh_ladder():
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    specs = precompile.enumerate_programs(
        small_ws(devices=2), fingerprint="a" * 64)
    rungs = sorted({s.meta["devices"] for s in specs})
    assert rungs == [1, 2], "device loss must fail over onto an " \
        "already-enumerated rung"
    assert len({s.key for s in specs}) == len(specs), \
        "every (program, rung) keys distinctly"


def test_service_warm_start_and_recovery_compile_nothing(tmp_path,
                                                         cache_dir):
    """End to end: a journaled service scheduling cold populates the
    cache; a restarted service over the same dir schedules AND
    recover()-replays with zero XLA compilations, bit-identical."""
    snap, pods = service_inputs(5)
    jpath = str(tmp_path / "j.bin")

    c1 = CompileCache(cache_dir)
    svc = make_service(c1, journal=CommitJournal(jpath))
    try:
        svc.publish(snap)
        want = np.asarray(svc.schedule(pods).assignment)
        assert c1.misses >= 1  # cold: the cycle program was built
    finally:
        c1.deactivate()

    # "restart": drop every in-process executable, fresh handles
    jax.clear_caches()
    c2 = CompileCache(cache_dir)
    svc2 = make_service(c2, journal=CommitJournal(jpath))
    try:
        svc2.publish(snap)
        rep = svc2.recover({1: pods})
        assert rep["compiled_programs"] == 0, \
            "recovery against a warmed cache must not compile"
        assert rep["replay_seconds"] >= 0 and rep["compile_seconds"] >= 0
        got = np.asarray(rep["results"][1].assignment)
        np.testing.assert_array_equal(got, want)
        assert c2.hits >= 1 and c2.misses == 0
        m = svc2.metrics
        assert m.compile_cache_hits.value() >= 1
        assert m.compile_cache_misses.value() == 0
    finally:
        c2.deactivate()


def test_mesh_shrink_rung_reuses_cached_executable(cache_dir):
    """The failover pin: a service landing on the mesh-shrink rung
    against a dir warmed by a PREVIOUS process run (modeled by
    clear_caches + fresh handles) dispatches the padded/sharded
    program with zero XLA compilations."""
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    snap, pods = service_inputs(7)

    c1 = CompileCache(cache_dir)
    svc = make_service(c1)
    try:
        svc.ladder.level = DegradationLadder.L_MESH_SHRINK
        svc.publish(snap)
        want = np.asarray(svc.schedule(pods).assignment)
        assert c1.misses >= 1
    finally:
        c1.deactivate()

    jax.clear_caches()
    c2 = CompileCache(cache_dir)
    svc2 = make_service(c2)
    try:
        svc2.ladder.level = DegradationLadder.L_MESH_SHRINK
        svc2.publish(snap)
        with counters.watch() as w:
            got = np.asarray(svc2.schedule(pods).assignment)
        assert w.cache_misses == 0, \
            "the mesh-shrink failover must reuse the cached executable"
        np.testing.assert_array_equal(got, want)
    finally:
        c2.deactivate()
