"""The crash-recovery seam (ISSUE 14): commit-journal record/replay
semantics, store checkpoint/restore, and the service-level
interrupt -> restart -> bit-identical-resume path.

The SIGKILL realism (a real uncatchable kill at every named crash
point, in a child process) lives in tools/crash_smoke.py as a CI
stage; the slow-marked test at the bottom runs that same matrix so
`pytest -m slow` covers it without double-paying in the fast battery.
"""

import os

import numpy as np
import pytest

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import Node, NodeMetric, ObjectMeta
from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler.frameworkext import (
    DegradationLadder,
    SchedulerService,
)
from koordinator_tpu.scheduler.journal import (
    CommitJournal,
    JournalConflict,
    JournalCorruption,
    JournalRecord,
    JournalTail,
    batch_digest,
)
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.snapshot import SnapshotBuilder
from koordinator_tpu.snapshot.store import SnapshotStore
from koordinator_tpu.utils import synthetic

N, P = 32, 64


def rec(epoch=1, chunk=0, n_chunks=2, base=1, watermark=0, digest=7,
        assignment=(0, 1, 2, 3)):
    return JournalRecord(epoch=epoch, chunk=chunk, n_chunks=n_chunks,
                        base_version=base, delta_watermark=watermark,
                        batch_digest=digest,
                        assignment=np.asarray(assignment, np.int32))


# --- journal record/replay semantics ---------------------------------------

def test_roundtrip_and_resume_bookkeeping(tmp_path):
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    assert j.next_epoch() == 1  # fresh journal
    j.append(rec(chunk=0))
    j.append(rec(chunk=1, assignment=(4, -1, 6, 7)))
    # incomplete? no: n_chunks=2 and chunks {0, 1} present -> complete
    assert j.epoch_complete(1)
    assert j.next_epoch() == 2
    j.append(rec(epoch=2, chunk=0, n_chunks=3))
    assert not j.epoch_complete(2)
    assert j.next_epoch() == 2  # interrupted epoch RESUMES

    j2 = CommitJournal(path)  # reload from disk
    assert j2.tail_reason is JournalTail.CLEAN
    assert j2.epochs() == [1, 2]
    got = j2.records_for(1)
    assert sorted(got) == [0, 1]
    np.testing.assert_array_equal(got[1].assignment, [4, -1, 6, 7])
    assert got[0].base_version == 1 and got[0].batch_digest == 7
    assert j2.n_chunks_of(2) == 3 and j2.base_version_of(1) == 1


def test_duplicate_identical_record_is_a_noop(tmp_path):
    j = CommitJournal(str(tmp_path / "j.bin"))
    wrote = j.append(rec())
    assert wrote > 0
    size = os.path.getsize(j.path)
    assert j.append(rec()) == 0  # idempotent replay
    assert os.path.getsize(j.path) == size
    assert j.appended_records == 1


def test_conflicting_duplicate_fails_loudly(tmp_path):
    j = CommitJournal(str(tmp_path / "j.bin"))
    j.append(rec())
    with pytest.raises(JournalConflict):
        j.append(rec(assignment=(9, 9, 9, 9)))


def test_torn_tail_discarded_with_typed_reason(tmp_path):
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    j.append(rec(chunk=0))
    j.append(rec(chunk=1))
    # SIGKILL mid-append leaves a truncated record: simulate by
    # shearing bytes off the tail
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)
    j2 = CommitJournal(path)
    assert j2.tail_reason is JournalTail.TORN_PAYLOAD
    assert sorted(j2.records_for(1)) == [0]  # the torn record is GONE
    # shear into the header of the next record
    j2.append(rec(chunk=1))
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - j2.appended_bytes + 4)
    j3 = CommitJournal(path)
    assert j3.tail_reason is JournalTail.TORN_HEADER
    # appending after a torn tail truncates it away and lands cleanly
    j3.append(rec(chunk=1))
    j4 = CommitJournal(path)
    assert j4.tail_reason is JournalTail.CLEAN
    assert sorted(j4.records_for(1)) == [0, 1]


def test_checksum_mismatch_fails_loudly(tmp_path):
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    j.append(rec(chunk=0))
    j.append(rec(chunk=1))
    # flip one payload byte of the FIRST record: not a torn tail, so
    # the load must refuse the journal rather than replay garbage
    with open(path, "r+b") as f:
        f.seek(14)
        byte = f.read(1)
        f.seek(14)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(JournalCorruption):
        CommitJournal(path)


def test_batch_digest_pins_the_resubmitted_batch():
    pods = synthetic.synthetic_pods(P, seed=3)
    other = synthetic.synthetic_pods(P, seed=4)
    assert batch_digest(pods) == batch_digest(pods)
    assert batch_digest(pods) != batch_digest(other)
    # the digest covers EVERY batch column, not just requests/valid:
    # same requests + different gang ids is a DIFFERENT batch
    gid = np.asarray(pods.gang_id).copy()
    gid[0] += 1
    assert batch_digest(pods.replace(gang_id=gid)) != batch_digest(pods)


def test_divergent_n_chunks_refused_before_any_write(tmp_path):
    """The conflict check runs BEFORE the durable write: a divergent
    record must never land on disk and make the journal unloadable."""
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    j.append(rec(chunk=0, n_chunks=2))
    size = os.path.getsize(path)
    with pytest.raises(JournalConflict, match="n_chunks"):
        j.append(rec(chunk=1, n_chunks=3))
    assert os.path.getsize(path) == size  # nothing half-written
    CommitJournal(path)  # and the file still loads


def test_abandon_tombstone_closes_an_epoch(tmp_path):
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    j.append(rec(epoch=1, chunk=0, n_chunks=4))
    assert j.next_epoch() == 1  # incomplete: would resume
    assert j.abandon(1) > 0
    assert j.abandon(1) == 0  # idempotent
    assert j.records_for(1) == {} and j.epochs() == []
    assert j.next_epoch() == 2
    with pytest.raises(JournalConflict, match="abandoned"):
        j.append(rec(epoch=1, chunk=1, n_chunks=4))
    # the tombstone is DURABLE: a reload stays closed
    j2 = CommitJournal(path)
    assert j2.next_epoch() == 2 and j2.records_for(1) == {}


# --- store checkpoint / restore --------------------------------------------

def build_store_inputs():
    b = SnapshotBuilder(max_nodes=8)
    for i in range(8):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 8_000.0,
                                     RK.MEMORY: 16_384.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=100.0,
                                     node_usage={RK.CPU: 500.0}))
    return b


def test_checkpoint_restore_roundtrip(tmp_path):
    ck = str(tmp_path / "store.ck")
    b = build_store_inputs()
    snap, _ = b.build(now=105.0)
    store = SnapshotStore(checkpoint_path=ck, checkpoint_every=1)
    store.publish(snap)
    store.ingest(b.metric_delta(["n1"], now=106.0, pad_to=2))
    assert store.maybe_checkpoint()
    want_usage = np.asarray(store.current().nodes.usage)

    fresh = SnapshotStore(checkpoint_path=ck)
    assert fresh.restore()
    assert fresh.version == store.version
    assert fresh.applied_delta_version == store.applied_delta_version
    np.testing.assert_array_equal(
        np.asarray(fresh.current().nodes.usage), want_usage)
    np.testing.assert_array_equal(
        np.asarray(fresh.current().nodes.allocatable),
        np.asarray(store.current().nodes.allocatable))


def test_restore_refuses_corrupt_or_missing_checkpoint(tmp_path):
    ck = str(tmp_path / "store.ck")
    store = SnapshotStore(checkpoint_path=ck)
    assert not store.restore()  # missing -> False, nothing touched
    b = build_store_inputs()
    snap, _ = b.build(now=105.0)
    store.publish(snap)
    store.checkpoint()
    with open(ck, "r+b") as f:
        f.seek(64)
        f.write(b"\xde\xad")
    assert not SnapshotStore(checkpoint_path=ck).restore()


def test_delta_replay_rides_the_restored_watermark(tmp_path):
    """The restart story for deltas: a producer replaying its log has
    already-applied deltas no-op in the version guard, later ones
    apply; resume_delta_version keeps a RESTARTED producer's fresh
    deltas above the watermark."""
    ck = str(tmp_path / "store.ck")
    b = build_store_inputs()
    snap, _ = b.build(now=105.0)
    store = SnapshotStore(checkpoint_path=ck)
    store.publish(snap)
    d1 = b.metric_delta(["n1"], now=106.0, pad_to=2)
    d2 = b.metric_delta(["n2"], now=107.0, pad_to=2)
    store.ingest(d1)
    store.ingest(d2)
    store.checkpoint()

    fresh = SnapshotStore(checkpoint_path=ck)
    assert fresh.restore()
    v = fresh.version
    fresh.ingest(d1)  # replayed log: both must no-op idempotently
    fresh.ingest(d2)
    assert fresh.version == v and fresh.delta_rejections == 2
    # a RESTARTED producer fast-forwards past the watermark, so its
    # next delta is accepted instead of rejected as a replay
    b2 = build_store_inputs()
    b2.set_node_metric(NodeMetric(node_name="n3", update_time=108.0,
                                  node_usage={RK.CPU: 900.0}))
    b2.resume_delta_version(fresh.applied_delta_version)
    d3 = b2.metric_delta(["n3"], now=108.0, pad_to=2)
    fresh.ingest(d3)
    assert fresh.version == v + 1
    assert fresh.applied_delta_version == 3


# --- service integration: interrupt -> restart -> bit-identical resume -----

def make_service(**kw):
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, guards=False, **kw)
    svc._sleep = lambda _s: None
    return svc


def slim_inputs(seed=0):
    snap = synthetic.synthetic_cluster(N, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.synthetic_pods(P, seed=seed + 3, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


class Boom(Exception):
    """An in-process stand-in for the crash (the REAL SIGKILL path is
    tools/crash_smoke.py)."""


def test_interrupted_chunked_batch_resumes_bit_identical(tmp_path):
    snap, pods = slim_inputs(1)
    # oracle: the uninterrupted chunked run
    oracle = make_service()
    oracle.ladder.level = DegradationLadder.L_CHUNKED
    oracle.ladder.chunk_splits = 1
    oracle.publish(snap)
    want = np.asarray(oracle.schedule(pods).assignment)
    want_req = np.asarray(oracle.store.current().nodes.requested)

    path = str(tmp_path / "j.bin")
    hits = {"n": 0}

    def crash_before_second_append(point):
        if point == "post_dispatch_pre_append":
            hits["n"] += 1
            if hits["n"] == 2:
                raise Boom()

    svc = make_service(journal=CommitJournal(
        path, crash_hook=crash_before_second_append))
    svc.max_cycle_attempts = 1
    svc.ladder.level = DegradationLadder.L_CHUNKED
    svc.ladder.chunk_splits = 1
    svc.publish(snap)
    with pytest.raises(Boom):
        svc.schedule(pods)
    assert sorted(svc.journal.records_for(1)) == [0]

    # "restart": a fresh service over the same journal; the store is
    # re-published by the edge (no checkpoint in this test)
    svc2 = make_service(journal=CommitJournal(path))
    assert svc2.epoch == 1  # the interrupted epoch resumes
    svc2.publish(snap)
    res = svc2.schedule(pods)
    got = np.asarray(res.assignment)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_allclose(
        np.asarray(svc2.store.current().nodes.requested), want_req)
    # chunk 0 was REPLAYED (asserted identical, not re-appended),
    # chunk 1 scheduled fresh: exactly one record per (epoch, chunk)
    assert svc2.metrics.recovery_replayed.value() == 1
    assert sorted(svc2.journal.records_for(1)) == [0, 1]
    assert svc2.journal.appended_records == 1
    assert svc2.epoch == 2


def test_resume_refuses_a_different_batch(tmp_path):
    snap, pods = slim_inputs(2)
    path = str(tmp_path / "j.bin")
    hits = {"n": 0}

    def crash_second(point):
        if point == "post_dispatch_pre_append":
            hits["n"] += 1
            if hits["n"] == 2:
                raise Boom()

    svc = make_service(journal=CommitJournal(path,
                                             crash_hook=crash_second))
    svc.max_cycle_attempts = 1
    svc.ladder.level = DegradationLadder.L_CHUNKED
    svc.ladder.chunk_splits = 1
    svc.publish(snap)
    with pytest.raises(Boom):
        svc.schedule(pods)

    svc2 = make_service(journal=CommitJournal(path))
    svc2.publish(snap)
    _, other = slim_inputs(9)
    with pytest.raises(JournalConflict, match="digest"):
        svc2.schedule(other)


def test_abandon_interrupted_epoch_unwedges_the_service(tmp_path):
    """A terminally-failed batch must not wedge the service forever:
    abandon_interrupted_epoch() closes the poisoned epoch durably and
    a DIFFERENT batch then schedules normally."""
    snap, pods = slim_inputs(5)
    path = str(tmp_path / "j.bin")
    hits = {"n": 0}

    def crash_second(point):
        if point == "post_dispatch_pre_append":
            hits["n"] += 1
            if hits["n"] == 2:
                raise Boom()

    svc = make_service(journal=CommitJournal(path,
                                             crash_hook=crash_second))
    svc.max_cycle_attempts = 1
    svc.ladder.level = DegradationLadder.L_CHUNKED
    svc.ladder.chunk_splits = 1
    svc.publish(snap)
    with pytest.raises(Boom):
        svc.schedule(pods)
    svc.journal.crash_hook = None
    _, other = slim_inputs(9)
    with pytest.raises(JournalConflict):
        svc.schedule(other)
    assert svc.abandon_interrupted_epoch()
    assert not svc.abandon_interrupted_epoch()  # nothing left
    res = np.asarray(svc.schedule(other).assignment)  # unwedged
    assert svc.journal.epoch_complete(2)
    oracle = make_service()
    oracle.ladder.level = DegradationLadder.L_CHUNKED
    oracle.ladder.chunk_splits = 1
    oracle.publish(snap)
    np.testing.assert_array_equal(
        res, np.asarray(oracle.schedule(other).assignment))


def test_raced_ingest_between_retries_abandons_and_reruns(tmp_path):
    """A delta landing between retry attempts (the backoff sleeps
    outside the commit lock BY DESIGN) moves the store version under
    the journaled chunks. That must stay a recoverable transient —
    the in-process epoch is abandoned and the batch re-runs whole
    against the fresher snapshot — never a terminal JournalConflict."""
    from koordinator_tpu.api.extension import NUM_RESOURCES
    from koordinator_tpu.snapshot.delta import NodeMetricDelta
    from koordinator_tpu.snapshot.schema import NUM_AGG
    from koordinator_tpu.testing import faults

    snap, pods = slim_inputs(6)
    r = NUM_RESOURCES
    noop_delta = NodeMetricDelta(
        idx=np.full((1,), -1, np.int32),
        metric_fresh=np.zeros((1,), bool),
        usage=np.zeros((1, r), np.float32),
        prod_usage=np.zeros((1, r), np.float32),
        agg_usage=np.zeros((1, NUM_AGG, r), np.float32),
        has_agg=np.zeros((1,), bool),
        assigned_estimated=np.zeros((1, r), np.float32),
        assigned_correction=np.zeros((1, r), np.float32),
        prod_assigned_estimated=np.zeros((1, r), np.float32),
        prod_assigned_correction=np.zeros((1, r), np.float32))

    svc = make_service(journal=CommitJournal(str(tmp_path / "j.bin")))
    svc.ladder.level = DegradationLadder.L_CHUNKED
    svc.ladder.chunk_splits = 1
    # chunk 0 commits, then the SECOND program call fails transiently;
    # the backoff sleep is where the racing ingest lands
    inj = faults.FaultInjector(1)
    svc.fault_injection = inj.xla_transient(fail_attempts={2})
    svc._sleep = lambda _s: svc.ingest(noop_delta)
    svc.publish(snap)
    res = svc.schedule(pods)  # must complete, not raise
    assert svc.journal.abandoned == {1}
    assert svc.journal.epoch_complete(2) and svc.epoch == 3
    oracle = make_service()
    oracle.ladder.level = DegradationLadder.L_CHUNKED
    oracle.ladder.chunk_splits = 1
    oracle.publish(snap)
    oracle.ingest(noop_delta)
    np.testing.assert_array_equal(
        np.asarray(res.assignment),
        np.asarray(oracle.schedule(pods).assignment))


def test_journal_metrics_and_single_program_epochs(tmp_path):
    """A non-chunked cycle is a 1-chunk epoch: one record, appended
    BEFORE the publish, and the journal metrics count it."""
    snap, pods = slim_inputs(3)
    svc = make_service(journal=CommitJournal(str(tmp_path / "j.bin")))
    svc.publish(snap)
    svc.schedule(pods)
    svc.schedule(pods)
    assert svc.journal.epochs() == [1, 2]
    assert svc.journal.n_chunks_of(1) == 1
    assert svc.metrics.journal_appends.value() == 2
    assert svc.metrics.journal_bytes.value() == svc.journal.appended_bytes
    assert svc.summary()["journaled"] and svc.summary()["epoch"] == 3
    # journaling must not perturb placements: a journal-free service
    # schedules bit-identically
    bare = make_service()
    bare.publish(snap)
    np.testing.assert_array_equal(
        np.asarray(bare.schedule(pods).assignment),
        np.asarray(svc.journal.records_for(1)[0].assignment))


def test_single_program_epoch_replays_on_a_chunked_service(tmp_path):
    """The journaled layout pins replay in BOTH directions: an epoch
    journaled as n_chunks=1 (crash between its append and publish)
    must replay as the single program even when the restarted service
    sits on the chunked rung — running it chunked would journal
    conflicting n_chunks records."""
    snap, pods = slim_inputs(7)
    path = str(tmp_path / "j.bin")

    def crash_post_append(point):
        if point == "post_append_pre_publish":
            raise Boom()

    svc = make_service(journal=CommitJournal(
        path, crash_hook=crash_post_append))
    svc.max_cycle_attempts = 1
    svc.publish(snap)
    with pytest.raises(Boom):
        svc.schedule(pods)
    assert svc.journal.n_chunks_of(1) == 1

    # the restarted service sits on the CHUNKED rung; recover() must
    # still replay the epoch as the single program it was journaled as
    svc2 = make_service(journal=CommitJournal(path))
    svc2.ladder.level = DegradationLadder.L_CHUNKED
    svc2.ladder.chunk_splits = 2
    svc2.publish(snap)
    report = svc2.recover({1: pods})  # no JournalConflict
    assert report["records_replayed"] == 1
    assert svc2.metrics.recovery_replayed.value() == 1
    assert svc2.journal.n_chunks_of(1) == 1  # layout unchanged
    oracle = make_service()
    oracle.publish(snap)
    np.testing.assert_array_equal(
        np.asarray(report["results"][1].assignment),
        np.asarray(oracle.schedule(pods).assignment))


def test_prune_drops_dead_epochs_and_keeps_the_last(tmp_path):
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    for e, base in ((1, 1), (2, 2), (3, 3)):
        j.append(rec(epoch=e, chunk=0, n_chunks=2, base=base))
        j.append(rec(epoch=e, chunk=1, n_chunks=2, base=base))
    j.append(rec(epoch=4, chunk=0, n_chunks=1, base=4))
    j.abandon(2)
    size = os.path.getsize(path)
    # checkpoint at store version 3: epochs 1 (complete, base 1 < 3)
    # and 2 (abandoned) are dead; 3 could still replay; 4 is last
    assert j.prune(3) == 2
    assert os.path.getsize(path) < size
    assert j.epochs() == [3, 4] and j.next_epoch() == 5
    j2 = CommitJournal(path)  # the pruned file reloads cleanly
    assert j2.tail_reason is JournalTail.CLEAN
    assert j2.epochs() == [3, 4] and j2.next_epoch() == 5
    assert sorted(j2.records_for(3)) == [0, 1]
    assert j.prune(3) == 0  # idempotent: nothing dead left


def test_prune_keeps_the_last_epochs_tombstone(tmp_path):
    path = str(tmp_path / "j.bin")
    j = CommitJournal(path)
    j.append(rec(epoch=1, chunk=0, n_chunks=2, base=1))
    j.append(rec(epoch=1, chunk=1, n_chunks=2, base=1))
    j.append(rec(epoch=2, chunk=0, n_chunks=4, base=2))
    j.abandon(2)
    assert j.prune(10) == 1  # epoch 1 dead; 2 kept (last), as tombstone
    j2 = CommitJournal(path)
    assert j2.abandoned == {2} and j2.next_epoch() == 3
    assert j2.records_for(2) == {}


def test_service_prunes_after_checkpoint(tmp_path):
    snap, pods = slim_inputs(8)
    store = SnapshotStore(checkpoint_path=str(tmp_path / "store.ck"),
                          checkpoint_every=1)
    svc = make_service(journal=CommitJournal(str(tmp_path / "j.bin")),
                       store=store)
    svc.publish(snap)
    for _ in range(4):
        svc.schedule(pods)
    # every completed epoch below the checkpoint watermark is pruned;
    # only the most recent survives for monotonic numbering
    assert svc.journal.epochs() == [4]
    assert svc.epoch == 5


@pytest.mark.slow
def test_crash_smoke_matrix():
    """The same kill-injected matrix tools/crash_smoke.py runs as a CI
    stage (SIGKILL at every named crash point; restart recovery
    bit-identical to the no-crash oracle)."""
    import tools.crash_smoke as crash

    assert crash.main([]) == 0
