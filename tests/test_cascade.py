"""The Filter->Score gate cascade + device-resident tail equivalence
suite (scheduler/cascade.py, ops/feasibility.py, core.tail_*).

Two conformance oracles, both pinned BIT-identical:
- `cascade=False` is the oracle for `cascade=True`: stage 1 folds only
  pairs the exact round gates would reject anyway (monotone batch-start
  state), and stage 2's prefix-narrowed heavy gates are pass-through
  beyond the packing prefixes — so placements, scores, and the whole
  post-commit snapshot must match exactly.
- the host-driven tail orchestration (bench tail_mode=host) is the
  oracle for `core.tail_compaction_loop`: the device lax.while_loop
  runs the SAME `core.tail_pass` under the same retry-budget semantics,
  so final placements, pass counts, and straggler stats must match —
  with the host paying one readback per adaptive decision and the
  device loop exactly one at the end.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops import feasibility
from koordinator_tpu.scheduler import cascade, core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

P, N, CHUNK = 512, 96, 256

KW = dict(num_rounds=2, k_choices=8, score_dims=(0, 1), tie_break=True,
          quota_depth=2, fit_dims=(0, 1, 2, 3), enable_numa=True,
          enable_devices=True)


def _sparse_workload(seed=1):
    """Full-gate pods whose constrained classes stay WELL below the
    chunk width, so the packed prefixes are proper (< CHUNK) and the
    cascade's narrowed heavy gates actually slice (a workload whose
    prefixes equal the chunk would vacuously pass the equivalence)."""
    pods = synthetic.full_gate_pods(P, N, seed=seed, num_quotas=8,
                                    num_gangs=8, n_anti_groups=4,
                                    anti_members=8, n_aff_groups=2,
                                    aff_members=6, spread_frac=0.08,
                                    numa_bind_frac=0.12,
                                    gpu_pod_frac=0.08)
    packed, prefixes, masks = synthetic.pack_gate_prefixes(pods, CHUNK)
    assert prefixes["numa"] < CHUNK and prefixes["gpu"] < CHUNK
    return packed, prefixes, masks


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _assert_results_equal(a, b):
    for f in core.PER_POD_RESULT_FIELDS + ("gang_failed",):
        np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                      np.asarray(getattr(b, f)), err_msg=f)
    _assert_trees_equal(a.snapshot, b.snapshot)


def test_cascade_on_off_bit_identical_full_gate():
    """The acceptance pin: cascade on vs off on the full-gate fixture
    cluster, with every packing contract engaged so both cascade layers
    (stage-1 mask AND narrowed heavy gates) are exercised."""
    pods, prefixes, _ = _sparse_workload()
    snap = synthetic.full_gate_cluster(N, seed=0, num_quotas=8,
                                       num_gangs=8)
    cfg = LoadAwareConfig.make()
    kw = dict(KW, topo_prefix=prefixes["topo"],
              dom_classes=synthetic.dom_classes(pods),
              numa_prefix=prefixes["numa"], gpu_prefix=prefixes["gpu"])
    batch = synthetic.slice_batch(pods, 0, CHUNK)
    off = core.schedule_batch(snap, batch, cfg, cascade=False, **kw)
    on = core.schedule_batch(snap, batch, cfg, cascade=True, **kw)
    _assert_results_equal(off, on)
    assert int((on.assignment >= 0).sum()) > 0


def test_cascade_across_carried_chunks():
    """Chunked scheduling with carried topology counts (the bench sweep
    contract): both modes must agree chunk by chunk AND leave identical
    carried counts."""
    pods, prefixes, _ = _sparse_workload(seed=5)
    snap_a = synthetic.full_gate_cluster(N, seed=4, num_quotas=8,
                                         num_gangs=8)
    snap_b = snap_a
    cfg = LoadAwareConfig.make()
    kw = dict(KW, topo_prefix=prefixes["topo"],
              dom_classes=synthetic.dom_classes(pods),
              numa_prefix=prefixes["numa"], gpu_prefix=prefixes["gpu"])
    counts_a = tuple(jnp.asarray(getattr(pods, f))
                     for f in core.COUNT_FIELDS)
    counts_b = counts_a
    for s in range(0, P, CHUNK):
        batch = synthetic.slice_batch(pods, s, CHUNK)
        batch_a = batch.replace(**dict(zip(core.COUNT_FIELDS, counts_a)))
        batch_b = batch.replace(**dict(zip(core.COUNT_FIELDS, counts_b)))
        res_a = core.schedule_batch(snap_a, batch_a, cfg, cascade=False,
                                    **kw)
        res_b = core.schedule_batch(snap_b, batch_b, cfg, cascade=True,
                                    **kw)
        np.testing.assert_array_equal(np.asarray(res_a.assignment),
                                      np.asarray(res_b.assignment))
        counts_a = core.charge_all_counts(counts_a, batch_a,
                                          res_a.assignment)
        counts_b = core.charge_all_counts(counts_b, batch_b,
                                          res_b.assignment)
        snap_a, snap_b = res_a.snapshot, res_b.snapshot
    for a, b in zip(counts_a, counts_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stage1_mask_is_sound():
    """Every placement the full machinery produces survives the
    stage-1 mask — the prune removes only provably-dead pairs — and a
    quota already at its ceiling kills its pods' rows. (Reuses the
    packed program the equivalence tests compiled: the mask contract is
    the same either way, and a fresh full-width compile would buy no
    coverage.)"""
    pods, prefixes, _ = _sparse_workload(seed=7)
    snap = synthetic.full_gate_cluster(N, seed=6, num_quotas=8,
                                       num_gangs=8)
    cfg = LoadAwareConfig.make()
    kw = dict(KW, topo_prefix=prefixes["topo"],
              dom_classes=synthetic.dom_classes(pods),
              numa_prefix=prefixes["numa"], gpu_prefix=prefixes["gpu"])
    batch = synthetic.slice_batch(pods, 0, CHUNK)
    static_ok, _ = cascade.static_gates(snap.nodes, batch, cfg)
    mask = np.asarray(cascade.stage1_mask(snap, batch, static_ok,
                                          fit_dims=(0, 1, 2, 3),
                                          quota_depth=2))
    res = core.schedule_batch(snap, batch, cfg, cascade=False, **kw)
    assign = np.asarray(res.assignment)
    slot = np.asarray(res.res_slot)
    # reservation-slot placements are exempt by contract (consumers
    # draw from the slot's hold, not the node's open pool)
    node_placed = (assign >= 0) & (slot < 0)
    rows = np.flatnonzero(node_placed)
    assert rows.size > 0
    assert mask[rows, assign[rows]].all()

    # exhausted quota: used == runtime at the pod's own level -> the
    # whole row dies in the ceiling gate
    q = snap.quotas
    used = np.asarray(q.used).copy()
    qid = int(np.asarray(batch.quota_id)[0])
    assert qid >= 0
    used[qid] = np.asarray(q.runtime)[qid]
    ok = np.asarray(feasibility.quota_ceiling_ok(
        q.replace(used=used), batch, quota_depth=2,
        fit_dims=(0, 1, 2, 3)))
    hit = np.asarray(batch.quota_id) == qid
    req = np.asarray(batch.requests)[:, :4]
    # only dims with a FINITE runtime can hit the ceiling (batch-tier
    # dims carry runtime inf in this tree and legitimately pass)
    finite = np.isfinite(np.asarray(q.runtime)[qid][:4])
    blocked = hit & (req[:, finite] > 0.5).any(axis=1)
    assert blocked.any()
    assert not ok[blocked].any()
    assert ok[~hit].all()


def _overcommitted_tail_setup(seed=2, n_nodes=16):
    """A tight cluster with EVERYTHING still unplaced: the tail loop
    doesn't care how the straggler pool arose, so starting from
    assign = -1 skips a sweep compile the fixture would otherwise pay.
    512 pods against 16 nodes overcommits hard enough that the pool
    stops improving before it drains — the adaptive stop path."""
    snap = synthetic.full_gate_cluster(n_nodes, seed=0, num_quotas=8,
                                       num_gangs=8)
    pods = synthetic.full_gate_pods(P, n_nodes, seed=seed, num_quotas=8,
                                    num_gangs=8)
    packed, prefixes, masks = synthetic.pack_gate_prefixes(pods, CHUNK)
    cfg = LoadAwareConfig.make()
    counts = tuple(jnp.asarray(getattr(packed, f))
                   for f in core.COUNT_FIELDS)
    assign = jnp.full((P,), -1, jnp.int32)
    left0 = int(np.asarray(packed.valid).sum())
    assert left0 > 0
    return snap, counts, assign, packed, masks, cfg, left0


def _blocking_stats(valid, assign, tried):
    """The oracle's per-pass host readback (the deliberate cost the
    device loop deletes — in bench tail_mode=host this is the
    HS006-marked np.asarray)."""
    bad = valid & (np.asarray(assign) < 0)
    return int(bad.sum()), int((bad & ~np.asarray(tried)).sum())


def _host_tail(tail_step, snap, counts, assign, pods, cfg, *,
               tail_chunk, min_passes, max_passes, topo_prefix=None,
               topo_mask=None):
    """The bench tail_mode=host orchestration, verbatim semantics:
    mandatory passes, then adaptive passes while the count improves or
    never-retried windows remain — one readback per decision."""
    valid = np.asarray(pods.valid)
    left0 = int((valid & (np.asarray(assign) < 0)).sum())
    tried = jnp.zeros((pods.valid.shape[0],), bool)
    passes, hist = 0, []
    for _ in range(min(min_passes, max_passes)):
        snap, counts, assign, tried = core.tail_pass(
            tail_step, snap, counts, assign, tried, pods, cfg,
            tail_chunk=tail_chunk, topo_prefix=topo_prefix,
            topo_mask=topo_mask)
        passes += 1
        hist.append(_blocking_stats(valid, assign, tried))
    left = hist[-1][0] if hist else left0
    prev = hist[-2][0] if passes >= 2 else left0
    improved = left < prev
    nr = hist[-1][1] if hist else left0
    while passes < max_passes and left > 0 and (improved or nr > 0):
        snap, counts, assign, tried = core.tail_pass(
            tail_step, snap, counts, assign, tried, pods, cfg,
            tail_chunk=tail_chunk, topo_prefix=topo_prefix,
            topo_mask=topo_mask)
        passes += 1
        new_left, nr = _blocking_stats(valid, assign, tried)
        improved = new_left < left
        left = new_left
    return snap, counts, assign, (left0, left, nr, passes)


def test_device_tail_matches_host_tail():
    """core.tail_compaction_loop (lax.while_loop, one stats readback)
    vs the host-driven orchestration: identical final placements,
    snapshots, and [after_sweep, final, never_retried, passes] stats.
    Runs WITH the budgeted constrained (topo_prefix) selection — the
    superset of the plain path; one loop compile instead of two keeps
    the suite tier-1 fast (the budget-cap/never-retried behavior is
    pinned end-to-end by test_bench_straggler_overflow_warns, which
    drives the device loop through bench.py with the cap at 2)."""
    snap, counts, assign, packed, masks, cfg, left0 = \
        _overcommitted_tail_setup()
    tail_step = functools.partial(core.schedule_batch, num_rounds=4,
                                  k_choices=8, score_dims=(0, 1),
                                  tie_break=True, quota_depth=2,
                                  fit_dims=(0, 1, 2, 3),
                                  enable_numa=True, enable_devices=True)
    topo_kw = dict(topo_prefix=48, topo_mask=jnp.asarray(masks["topo"]))
    # max_passes=3 walks every control edge (mandatory, adaptive
    # continue, budget stop) while keeping the host oracle's eager
    # passes cheap; the cap-strands-never-retried behavior is pinned
    # end-to-end by test_bench_straggler_overflow_warns (device mode)
    hs, hc, ha, hstats = _host_tail(
        tail_step, snap, counts, assign, packed, cfg, tail_chunk=64,
        min_passes=2, max_passes=3, **topo_kw)
    loop = jax.jit(functools.partial(
        core.tail_compaction_loop, tail_step, tail_chunk=64,
        min_passes=2, max_passes=3, **topo_kw))
    ds, dc, da, dstats = loop(snap, counts, assign, packed, cfg)
    dstats = tuple(int(x) for x in np.asarray(dstats))
    assert dstats == hstats
    assert dstats[0] == left0
    np.testing.assert_array_equal(np.asarray(ha), np.asarray(da))
    _assert_trees_equal(hs, ds)
    _assert_trees_equal(hc, dc)


def test_cascade_no_prefix_identical():
    """Cascade on/off equivalence WITHOUT packing contracts (the
    service-caller shape): the heavy gates stay full width —
    `dev_pg == numa_pn == p` — and only the stage-1 fit/quota fold is
    in play. Runs at the tail fixture's shapes so the cascade=False
    side is the program the host-tail oracle already compiled."""
    snap, counts, assign, packed, masks, cfg, left0 = \
        _overcommitted_tail_setup()
    step = functools.partial(core.schedule_batch, num_rounds=4,
                             k_choices=8, score_dims=(0, 1),
                             tie_break=True, quota_depth=2,
                             fit_dims=(0, 1, 2, 3), enable_numa=True,
                             enable_devices=True)
    batch = synthetic.slice_batch(packed, 0, 64).replace(
        **dict(zip(core.COUNT_FIELDS, counts)))
    # cascade omitted (not `cascade=False`): an explicitly-passed
    # static kwarg keys a separate jit-cache entry, and the default
    # form is the one the host-tail oracle above already compiled
    off = step(snap, batch, cfg)
    on = step(snap, batch, cfg, cascade=True)
    _assert_results_equal(off, on)


def test_candidate_mask_sharding_spec():
    """The [P, N] cascade mask follows node columns on the mesh (pods
    replicated, nodes sharded) — the sharding every [.., N] snapshot
    column uses."""
    from koordinator_tpu.parallel import candidate_mask_sharding, make_mesh
    mesh = make_mesh(jax.devices())
    s = candidate_mask_sharding(mesh)
    spec = s.spec
    assert tuple(spec) == (None, "nodes")
    mask = jax.device_put(jnp.ones((16, 800), bool), s)
    assert mask.sharding.is_equivalent_to(s, 2)


def test_tail_loop_zero_pending_batch():
    """Boundary: a ZERO-pending batch through tail_compaction_loop.
    The forced min_passes still run (the warm-path contract), but each
    pass gathers an all-invalid retry batch and must be a no-op: stats
    [0, 0, 0, min_passes], untouched assignment/counts, and a snapshot
    identical up to the version counter."""
    n_nodes, p = 4, 16
    snap = synthetic.synthetic_cluster(n_nodes, seed=3)
    pods = synthetic.synthetic_pods(p, seed=4)
    pods = pods.replace(valid=jnp.zeros((p,), bool))   # nothing pending
    cfg = LoadAwareConfig.make()
    counts = tuple(jnp.asarray(getattr(pods, f))
                   for f in core.COUNT_FIELDS)
    assign = jnp.full((p,), -1, jnp.int32)
    # the slimmest full program that still walks the loop's control
    # edges (the boundary under test is the loop, not the gates)
    step = functools.partial(core.schedule_batch, num_rounds=1,
                             k_choices=1, quota_depth=1,
                             enable_numa=False, enable_devices=False)
    loop = jax.jit(functools.partial(
        core.tail_compaction_loop, step, tail_chunk=8, min_passes=2,
        max_passes=4, charge_counts=False))
    snap2, counts2, assign2, stats = loop(snap, counts, assign, pods,
                                          cfg)
    assert [int(x) for x in np.asarray(stats)] == [0, 0, 0, 2]
    np.testing.assert_array_equal(np.asarray(assign2), np.asarray(assign))
    _assert_trees_equal(counts2, counts)
    # the no-op passes must not move any capacity; only the version
    # counter advances (one bump per schedule_batch call)
    _assert_trees_equal(
        snap2.replace(version=jnp.zeros_like(snap2.version)),
        snap.replace(version=jnp.zeros_like(snap.version)))


def test_prefix_larger_than_batch_identical():
    """Boundary: a batch SMALLER than the declared packing prefixes.
    stage1_mask and every stage-2 slice clamp the prefix to the batch
    width (pc = pn = pg = P), so oversized prefixes must be bit-
    identical to the unprefixed program — cascade off AND on."""
    n_nodes, p = 8, 32
    snap = synthetic.synthetic_cluster(n_nodes, seed=5)
    pods = synthetic.synthetic_pods(p, seed=6)
    cfg = LoadAwareConfig.make()
    kw = dict(num_rounds=1, k_choices=2, quota_depth=1)
    big = dict(topo_prefix=4 * p, numa_prefix=4 * p, gpu_prefix=4 * p)
    base = core.schedule_batch(snap, pods, cfg, **kw)
    clamped = core.schedule_batch(snap, pods, cfg, **kw, **big)
    _assert_results_equal(base, clamped)
    cas = core.schedule_batch(snap, pods, cfg, cascade=True, **kw)
    cas_clamped = core.schedule_batch(snap, pods, cfg, cascade=True,
                                      **kw, **big)
    _assert_results_equal(cas, cas_clamped)
    # the cascade conformance holds at this boundary too
    _assert_results_equal(base, cas)
