"""Adversarial scale semantics: strict gangs spanning batch/chunk
boundaries (the Permit wait carried in gangs.assumed across scan steps,
coscheduling core.go:311-341) and the bench tail-retry capacity bound
surfacing instead of silently under-reporting."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from koordinator_tpu.api.types import Node, NodeMetric, ObjectMeta, Pod, PodGroup
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot import SnapshotBuilder
from koordinator_tpu.snapshot.delta import forget_pods

NOW = 1e9


def _cluster(b, n_nodes=2, cpu=32000):
    for i in range(n_nodes):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: cpu, RK.MEMORY: 65536}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))


def _members(ctx_builder, gang, count, start=0, cpu=1000.0):
    return [Pod(meta=ObjectMeta(name=f"{gang}-{start + j}"), priority=9000,
                requests={RK.CPU: cpu, RK.MEMORY: 256.0}, gang_name=gang)
            for j in range(count)]


def test_strict_gang_spanning_chunks_completes():
    """A 6-member strict gang split 3+3 over two successive batches (the
    bench CHUNK boundary): the first batch's members stay ASSUMED below
    quorum because members are still outstanding, and the second batch
    completes the gang."""
    b = SnapshotBuilder(max_nodes=2, max_gangs=1)
    _cluster(b)
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=6,
                        total_member=6))
    snap, ctx = b.build(now=NOW)
    cfg = loadaware.LoadAwareConfig.make()

    chunk1 = b.build_pod_batch(_members(b, "g", 3), ctx)
    res1 = core.schedule_batch(snap, chunk1, cfg, num_rounds=4)
    a1 = np.asarray(res1.assignment)
    assert np.all(a1 >= 0), "partial members must HOLD (Permit wait), " \
        f"not roll back, got {a1}"
    assert np.asarray(res1.snapshot.gangs.assumed)[0] == 3
    # their capacity is charged while they wait at the barrier
    assert np.asarray(res1.snapshot.nodes.requested)[:, 0].sum() == \
        pytest.approx(3000.0)

    assert not np.asarray(res1.gang_failed)[0], \
        "a gang with outstanding members is not yet failed"

    chunk2 = b.build_pod_batch(_members(b, "g", 3, start=3), ctx)
    res2 = core.schedule_batch(res1.snapshot, chunk2, cfg, num_rounds=4)
    a2 = np.asarray(res2.assignment)
    assert np.all(a2 >= 0)
    assert np.asarray(res2.snapshot.gangs.assumed)[0] == 6


def test_strict_gang_single_batch_still_all_or_nothing():
    """When the WHOLE gang is attempted in one batch (no members
    outstanding) and cannot fit, rollback stays immediate — the
    chunk-spanning hold must not weaken the single-batch barrier."""
    b = SnapshotBuilder(max_nodes=2, max_gangs=1)
    _cluster(b, cpu=8000)
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=5,
                        total_member=5))
    snap, ctx = b.build(now=NOW)
    pods = _members(b, "g", 5, cpu=6000.0)
    res = core.schedule_batch(snap, b.build_pod_batch(pods, ctx),
                              loadaware.LoadAwareConfig.make(), num_rounds=4)
    assert np.all(np.asarray(res.assignment) == -1)
    assert np.asarray(res.snapshot.gangs.assumed)[0] == 0
    # the proven failure is signalled to the host
    assert np.asarray(res.gang_failed)[0]


def test_strict_gang_hold_reclaimed_by_unassume():
    """If the rest of a spanning gang never fits, the held members'
    charges flow back through the forget/un-assume path (the Permit
    wait-expiry rollback: GangDirectory.expire_waits -> store.forget)."""
    b = SnapshotBuilder(max_nodes=2, max_gangs=1)
    _cluster(b, cpu=4000)
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=6,
                        total_member=6))
    snap, ctx = b.build(now=NOW)
    cfg = loadaware.LoadAwareConfig.make()

    chunk1 = b.build_pod_batch(_members(b, "g", 3, cpu=2000.0), ctx)
    res1 = core.schedule_batch(snap, chunk1, cfg, num_rounds=4)
    assert np.all(np.asarray(res1.assignment) >= 0)
    # chunk 2 members can never fit (8000 CPU total, 6000 held)
    chunk2 = b.build_pod_batch(
        _members(b, "g", 3, start=3, cpu=3000.0), ctx)
    res2 = core.schedule_batch(res1.snapshot, chunk2, cfg, num_rounds=4)
    assert np.all(np.asarray(res2.assignment) == -1)
    # the 3 held members still charge the nodes while waiting
    assert np.asarray(res2.snapshot.nodes.requested)[:, 0].sum() == \
        pytest.approx(6000.0)
    # every member has now been attempted and the gang is short: the
    # result PROVES the failure so the host need not wait for the timeout
    assert np.asarray(res2.gang_failed)[0]

    # the proven failure (or, for gangs whose members never reappear, the
    # Permit wait expiry) triggers the un-assume of the held members
    import jax.numpy as jnp
    mask = jnp.asarray(np.ones(chunk1.valid.shape, bool))
    after = forget_pods(res2.snapshot, chunk1, res1, mask)
    assert np.asarray(after.nodes.requested)[:, 0].sum() == pytest.approx(0.0)
    assert np.asarray(after.gangs.assumed)[0] == 0


def test_service_gang_failed_hook_reclaims_held_members():
    """Production loop: the SchedulerService surfaces gang_failed to its
    hook, and the hook un-assumes the earlier batch's held members
    through the store — capacity returns without the Permit timeout."""
    from koordinator_tpu.scheduler.frameworkext import SchedulerService

    b = SnapshotBuilder(max_nodes=2, max_gangs=1)
    _cluster(b, cpu=4000)
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=6,
                        total_member=6))
    snap, ctx = b.build(now=NOW)
    svc = SchedulerService(num_rounds=4)
    svc.publish(snap)

    retained = []  # the gang controller retains (batch, result) per gang

    def on_gang_failed(gids, _result):
        assert list(gids) == [0]
        import jax.numpy as jnp
        for batch, res in retained:
            mask = jnp.asarray(batch.gang_id == 0) & batch.valid & \
                (res.assignment >= 0)
            svc.store.update(lambda s: forget_pods(s, batch, res, mask))

    svc.on_gang_failed = on_gang_failed

    chunk1 = b.build_pod_batch(_members(b, "g", 3, cpu=2000.0), ctx)
    res1 = svc.schedule(chunk1)
    retained.append((chunk1, res1))
    assert np.all(np.asarray(res1.assignment) >= 0)
    assert svc.last_gang_failed is not None and not svc.last_gang_failed[0]

    chunk2 = b.build_pod_batch(_members(b, "g", 3, start=3, cpu=3000.0), ctx)
    res2 = svc.schedule(chunk2)
    assert np.all(np.asarray(res2.assignment) == -1)
    assert svc.last_gang_failed[0]
    # the hook ran: held capacity flowed back into the live snapshot
    cur = svc.store.current()
    assert np.asarray(cur.nodes.requested)[:, 0].sum() == pytest.approx(0.0)
    assert np.asarray(cur.gangs.assumed)[0] == 0


def test_bench_straggler_overflow_warns():
    """More stragglers than the CAPPED adaptive tail can retry: the
    bench must SAY so (stderr warning + JSON fields), not silently
    report the overflow unschedulable (r2 verdict weak #4). With the
    adaptive tail only the BENCH_MAX_TAIL_PASSES cap can strand
    never-retried pods, so the cap is pinned low here."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               # the parent test process forces an 8-device virtual CPU
               # platform; this single-chip smoke must not inherit it (2
               # nodes cannot shard 8 ways)
               XLA_FLAGS="",
               BENCH_NODES="2", BENCH_PODS="200", BENCH_CHUNK="20",
               BENCH_MAX_TAIL_PASSES="2", BENCH_EXTRAS="0")
    # generous: the subprocess pays its own XLA compile, and a cold/evicted
    # compilation cache under a loaded host has been seen past 420s
    out = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=560, env=env)
    assert out.returncode == 0, out.stderr
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    result = json.loads(line)
    assert result["tail_passes"] == 2
    assert result["stragglers_after_sweep"] > 40  # 2 passes x chunk 20
    assert result["never_retried"] > 0
    assert "were never retried" in out.stderr
