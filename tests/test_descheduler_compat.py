"""Upstream-compat descheduler plugin set (plugin.go:62-130 registry):
lifetime/failed/restarts/duplicates evictors, taint + topology-spread
violation, and the request-based nodeutilization pair."""

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.descheduler import COMPAT_PLUGINS, RecordingEvictor
from koordinator_tpu.descheduler.compat import (
    HighNodeUtilization,
    LowNodeUtilization,
    PodLifeTime,
    RemoveDuplicates,
    RemoveFailedPods,
    RemovePodsHavingTooManyRestarts,
    RemovePodsViolatingNodeTaints,
    RemovePodsViolatingTopologySpreadConstraint,
)


def mk_pod(name, node="n0", **kw):
    kw.setdefault("phase", "Running")
    return api.Pod(meta=api.ObjectMeta(name=name, uid=name),
                   node_name=node, **kw)


def mk_node(name, labels=None, taints=(), cpu=16000.0):
    return api.Node(meta=api.ObjectMeta(name=name, labels=labels or {}),
                    allocatable={RK.CPU: cpu, RK.MEMORY: 32768.0},
                    taints=list(taints))


def evicted_names(ev):
    return [e.pod.meta.name for e in ev.evictions]


def test_registry_has_the_upstream_set():
    for name in ("PodLifeTime", "RemoveFailedPods", "RemoveDuplicates",
                 "RemovePodsHavingTooManyRestarts",
                 "RemovePodsViolatingNodeAffinity",
                 "RemovePodsViolatingNodeTaints",
                 "RemovePodsViolatingTopologySpreadConstraint",
                 "LowNodeUtilization", "HighNodeUtilization"):
        assert name in COMPAT_PLUGINS


def test_pod_lifetime_and_states():
    ev = RecordingEvictor()
    pods = {"n0": [mk_pod("old", start_time=100.0),
                   mk_pod("young", start_time=900.0),
                   mk_pod("old-pending", start_time=100.0,
                          phase="Pending"),
                   mk_pod("unknown-age", start_time=0.0)]}
    p = PodLifeTime(ev, lambda: pods, now_fn=lambda: 1000.0,
                    max_pod_life_time_seconds=500.0, states=("Running",))
    p.deschedule([mk_node("n0")])
    assert evicted_names(ev) == ["old"]


def test_remove_failed_pods_min_age():
    ev = RecordingEvictor()
    pods = {"n0": [mk_pod("failed-old", phase="Failed", start_time=100.0),
                   mk_pod("failed-new", phase="Failed", start_time=990.0),
                   mk_pod("running", phase="Running")]}
    p = RemoveFailedPods(ev, lambda: pods, now_fn=lambda: 1000.0,
                         min_pod_lifetime_seconds=100.0)
    p.deschedule([mk_node("n0")])
    assert evicted_names(ev) == ["failed-old"]


def test_too_many_restarts():
    ev = RecordingEvictor()
    pods = {"n0": [mk_pod("crashy", restart_count=120),
                   mk_pod("stable", restart_count=3)]}
    p = RemovePodsHavingTooManyRestarts(ev, lambda: pods,
                                        pod_restart_threshold=100)
    p.deschedule([mk_node("n0")])
    assert evicted_names(ev) == ["crashy"]


def test_remove_duplicates_keeps_one_per_owner_per_node():
    ev = RecordingEvictor()
    pods = {"n0": [mk_pod(f"web-{i}", owner_workload="default/web")
                   for i in range(3)] + [mk_pod("db-0",
                                                owner_workload="default/db")],
            "n1": [mk_pod("web-3", node="n1",
                          owner_workload="default/web")]}
    RemoveDuplicates(ev, lambda: pods).deschedule(
        [mk_node("n0"), mk_node("n1")])
    # one web replica survives on n0; the lone n1 replica untouched
    assert evicted_names(ev) == ["web-1", "web-2"]


def test_taint_violation_respects_tolerations():
    ev = RecordingEvictor()
    taint = api.Taint(key="dedicated", value="ml", effect="NoSchedule")
    pods = {"n0": [
        mk_pod("tolerant",
               tolerations=[api.Toleration(key="dedicated", value="ml")]),
        mk_pod("exists-tolerant",
               tolerations=[api.Toleration(key="dedicated")]),
        mk_pod("violator"),
    ]}
    RemovePodsViolatingNodeTaints(ev, lambda: pods).deschedule(
        [mk_node("n0", taints=[taint])])
    assert evicted_names(ev) == ["violator"]
    # PreferNoSchedule is soft: nobody evicted
    ev2 = RecordingEvictor()
    RemovePodsViolatingNodeTaints(ev2, lambda: pods).deschedule(
        [mk_node("n0", taints=[api.Taint(key="x", effect="PreferNoSchedule")])])
    assert not ev2.evictions


def test_topology_spread_evicts_excess_skew():
    ev = RecordingEvictor()
    nodes = [mk_node("a1", {"zone": "a"}), mk_node("b1", {"zone": "b"})]
    mk = lambda name, node: mk_pod(name, node=node,  # noqa: E731
                                   owner_workload="default/web",
                                   spread_topology_key="zone",
                                   spread_max_skew=1)
    pods = {"a1": [mk(f"w{i}", "a1") for i in range(4)],
            "b1": [mk("w9", "b1")]}
    RemovePodsViolatingTopologySpreadConstraint(
        ev, lambda: pods).deschedule(nodes)
    # zone a has 4, zone b has 1: one move repairs the skew to {3, 2}
    assert len(ev.evictions) == 1
    assert ev.evictions[0].pod.node_name == "a1"


def test_topology_spread_ignores_unschedulable_empty_domains():
    """A zone provided only by a cordoned node must not drag the floor to
    zero (it can never receive pods, so evicting toward it is churn)."""
    ev = RecordingEvictor()
    cordoned = mk_node("c1", {"zone": "c"})
    cordoned.unschedulable = True
    nodes = [mk_node("a1", {"zone": "a"}), mk_node("b1", {"zone": "b"}),
             cordoned]
    mk = lambda name, node: mk_pod(name, node=node,  # noqa: E731
                                   owner_workload="default/web",
                                   spread_topology_key="zone",
                                   spread_max_skew=1)
    pods = {"a1": [mk("w0", "a1"), mk("w1", "a1")],
            "b1": [mk("w2", "b1")]}
    RemovePodsViolatingTopologySpreadConstraint(
        ev, lambda: pods).deschedule(nodes)
    assert not ev.evictions, "skew {2,1} within maxSkew=1 once the " \
        "cordoned-only zone is excluded"


def test_topology_spread_filters_before_budgeting():
    """Unevictable pods must not absorb the eviction budget: with the
    excess at the head of the list protected, the evictable ones behind
    them are chosen."""
    ev = RecordingEvictor()
    nodes = [mk_node("a1", {"zone": "a"}), mk_node("b1", {"zone": "b"})]

    def mk(name, node, protected=False):
        anns = {"scheduling.koordinator.sh/preemptible": "false"} \
            if protected else {}
        return api.Pod(meta=api.ObjectMeta(name=name, uid=name,
                                           annotations=anns),
                       node_name=node, phase="Running",
                       owner_workload="default/web",
                       spread_topology_key="zone", spread_max_skew=1)

    pods = {"a1": [mk("prot0", "a1", True), mk("prot1", "a1", True),
                   mk("free0", "a1"), mk("free1", "a1")],
            "b1": [mk("w", "b1")]}
    RemovePodsViolatingTopologySpreadConstraint(
        ev, lambda: pods).deschedule(nodes)
    # one move repairs {4,1} -> {3,2}; it must hit an evictable pod
    assert [e.pod.meta.name for e in ev.evictions] == ["free0"]


def test_low_node_utilization_request_based():
    ev = RecordingEvictor()
    nodes = [mk_node("hot"), mk_node("cold")]
    pods = {"hot": [mk_pod(f"p{i}", node="hot", priority=1000 + i,
                           requests={RK.CPU: 4000.0, RK.MEMORY: 1024.0})
                    for i in range(4)],
            "cold": []}
    p = LowNodeUtilization(ev, lambda: pods, thresholds=20.0,
                           target_thresholds=70.0,
                           max_evictions_per_node=2)
    p.balance(nodes)
    # hot = 100% cpu requested, cold = 0%: evict 2 lowest-priority pods
    assert evicted_names(ev) == ["p0", "p1"]

    # no underutilized target -> nothing moves
    ev2 = RecordingEvictor()
    pods2 = {"hot": pods["hot"],
             "cold": [mk_pod("filler", node="cold",
                             requests={RK.CPU: 8000.0,
                                       RK.MEMORY: 16384.0})]}
    LowNodeUtilization(ev2, lambda: pods2, thresholds=20.0,
                       target_thresholds=70.0).balance(nodes)
    assert not ev2.evictions


def test_high_node_utilization_drains_underutilized():
    ev = RecordingEvictor()
    nodes = [mk_node("sparse"), mk_node("packed")]
    pods = {"sparse": [mk_pod("loner", node="sparse",
                              requests={RK.CPU: 1000.0,
                                        RK.MEMORY: 512.0})],
            "packed": [mk_pod("big", node="packed",
                              requests={RK.CPU: 12000.0,
                                        RK.MEMORY: 16384.0})]}
    HighNodeUtilization(ev, lambda: pods, thresholds=20.0).balance(nodes)
    assert evicted_names(ev) == ["loner"]
