"""Device health guards (scheduler/guards.py): packed-word layout, one
mask row per defect class, quarantine semantics, and the fused
guarded_schedule_batch's bit-identity on healthy inputs.

The full chaos matrix (detection + quarantine + service-up + clean-row
oracle conformance per fault class) runs as the dedicated
tools/chaos_smoke.py CI stage; tests here pin the kernel-level
contracts the stage builds on.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from koordinator_tpu.scheduler import core, guards
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.testing import faults
from koordinator_tpu.utils import synthetic

N, P = 32, 64
CFG = loadaware.LoadAwareConfig.make()


def make_inputs(seed=0):
    snap = synthetic.full_gate_cluster(N, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.full_gate_pods(P, N, seed=seed + 7, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


# --- packed-word layout ----------------------------------------------------

def test_word_layout_is_stable():
    """The bit positions are wire format for dashboards and the chaos
    matrix: moving one silently re-labels every alert."""
    assert guards.NODE_METRIC_NONFINITE == 1 << 0
    assert guards.NODE_BAD_ALLOCATABLE == 1 << 1
    assert guards.NODE_BAD_REQUESTED == 1 << 2
    assert guards.NODE_OVERCOMMIT == 1 << 3
    assert guards.NODE_NUMA_INVALID == 1 << 4
    assert guards.POD_NONFINITE == 1 << 8
    assert guards.POD_NEGATIVE == 1 << 9
    assert guards.POD_ID_RANGE == 1 << 10
    assert guards.POD_DOMAIN_RANGE == 1 << 11
    # every bit named exactly once; decode round-trips
    assert len(guards.DEFECT_NAMES) == 9
    word = guards.NODE_OVERCOMMIT | guards.POD_ID_RANGE
    assert guards.decode_health_word(word) == ("node_overcommit",
                                               "pod_id_range")
    assert guards.decode_health_word(0) == ()


def test_healthy_inputs_scan_clean():
    snap, pods = make_inputs()
    w, node_bad = guards.snapshot_health(snap)
    assert int(np.asarray(w)) == guards.HEALTH_OK
    assert not np.asarray(node_bad).any()
    w, pod_bad = guards.batch_health(snap, pods)
    assert int(np.asarray(w)) == guards.HEALTH_OK
    assert not np.asarray(pod_bad).any()


# --- one defect class at a time -------------------------------------------

@pytest.mark.parametrize("kind", faults.SNAPSHOT_FAULTS)
def test_snapshot_defect_sets_its_bit_and_rows(kind):
    snap, _ = make_inputs(2)
    inj = faults.FaultInjector(11)
    bad_snap, rows = inj.corrupt_snapshot(snap, kind, n_rows=3)
    w, mask = guards.snapshot_health(bad_snap)
    w, mask = int(np.asarray(w)), np.asarray(mask)
    assert w & faults.EXPECTED_BIT[kind], guards.decode_health_word(w)
    assert set(np.where(mask)[0]) == set(rows.tolist())


@pytest.mark.parametrize("kind", faults.BATCH_FAULTS)
def test_batch_defect_sets_its_bit_and_rows(kind):
    snap, pods = make_inputs(3)
    inj = faults.FaultInjector(13)
    bad_pods, rows = inj.corrupt_batch(pods, kind, n_rows=3)
    w, mask = guards.batch_health(snap, bad_pods)
    w, mask = int(np.asarray(w)), np.asarray(mask)
    assert w & faults.EXPECTED_BIT[kind], guards.decode_health_word(w)
    assert set(rows.tolist()) <= set(np.where(mask)[0].tolist())


def test_id_range_allows_the_none_sentinel():
    """-1 is 'no gang / no quota / match-all selector' everywhere; the
    guard must not quarantine the whole unconstrained workload."""
    snap, pods = make_inputs(4)
    neg1 = jnp.full_like(pods.gang_id, -1)
    pods = pods.replace(gang_id=neg1, quota_id=neg1, selector_id=neg1)
    w, mask = guards.batch_health(snap, pods)
    assert not (int(np.asarray(w)) & guards.POD_ID_RANGE)
    assert not np.asarray(mask).any()


# --- quarantine semantics --------------------------------------------------

def test_apply_quarantine_is_bitwise_identity_on_false_masks():
    snap, pods = make_inputs(5)
    q_snap, q_pods = guards.apply_quarantine(
        snap, pods, jnp.zeros((N,), bool), jnp.zeros((P,), bool))
    for field in ("allocatable", "requested", "usage", "numa_free"):
        np.testing.assert_array_equal(
            np.asarray(getattr(q_snap.nodes, field)),
            np.asarray(getattr(snap.nodes, field)), err_msg=field)
    np.testing.assert_array_equal(np.asarray(q_pods.requests),
                                  np.asarray(pods.requests))
    np.testing.assert_array_equal(np.asarray(q_pods.valid),
                                  np.asarray(pods.valid))


def test_apply_quarantine_scrubs_and_pins_out_bad_rows():
    snap, pods = make_inputs(6)
    inj = faults.FaultInjector(17)
    bad_snap, rows = inj.corrupt_snapshot(snap, "nan_metric_column")
    node_bad = np.zeros((N,), bool)
    node_bad[rows] = True
    q_snap, _ = guards.apply_quarantine(
        bad_snap, pods, jnp.asarray(node_bad), jnp.zeros((P,), bool))
    sched = np.asarray(q_snap.nodes.schedulable)
    assert not sched[rows].any()
    assert np.isfinite(np.asarray(q_snap.nodes.usage)).all()
    # healthy rows untouched, bitwise
    keep = ~node_bad
    np.testing.assert_array_equal(
        np.asarray(q_snap.nodes.usage)[keep],
        np.asarray(bad_snap.nodes.usage)[keep])


def test_quarantine_scrubs_bad_domain_group_to_minus_one():
    snap, pods = make_inputs(7)
    inj = faults.FaultInjector(19)
    bad_pods, carriers = inj.corrupt_batch(pods, "bad_domain_index")
    w, mask = guards.batch_health(snap, bad_pods)
    _, q_pods = guards.apply_quarantine(snap, bad_pods,
                                        jnp.zeros((N,), bool), mask)
    dom = np.asarray(q_pods.spread_domain)
    d = np.asarray(q_pods.spread_count0).shape[1]
    assert ((dom >= -1) & (dom < d)).all(), "scrub left an OOB entry"
    assert not np.asarray(q_pods.valid)[carriers].any()


# --- the fused program -----------------------------------------------------

def test_guarded_schedule_batch_bit_identical_when_healthy():
    snap, pods = make_inputs(8)
    res0 = core.schedule_batch(snap, pods, CFG, num_rounds=2, k_choices=4)
    res1, health, node_bad, pod_bad = guards.guarded_schedule_batch(
        snap, pods, CFG, num_rounds=2, k_choices=4)
    h = np.asarray(health)
    assert h.dtype == np.uint32 and h.shape == (3,)
    assert int(h[0]) == 0 and int(h[1]) == 0 and int(h[2]) == 0
    for field in core.PER_POD_RESULT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(res0, field)),
            np.asarray(getattr(res1, field)), err_msg=field)


def test_guarded_schedule_matches_masked_oracle_under_faults():
    """The acceptance pin at kernel level: placements of the guarded
    program on corrupted inputs equal the plain program on CLEAN inputs
    with the corrupted rows masked manually — corruption never leaks
    into clean rows."""
    snap, pods = make_inputs(9)
    inj = faults.FaultInjector(23)
    bad_snap, n_rows = inj.corrupt_snapshot(snap, "nan_metric_column",
                                            n_rows=2)
    bad_pods, p_rows = inj.corrupt_batch(pods, "nan_pod_request",
                                         n_rows=3)
    res, health, _nb, _pb = guards.guarded_schedule_batch(
        bad_snap, bad_pods, CFG, num_rounds=2, k_choices=4)
    sched = np.asarray(snap.nodes.schedulable).copy()
    sched[n_rows] = False
    valid = np.asarray(pods.valid).copy()
    valid[p_rows] = False
    oracle = core.schedule_batch(
        snap.replace(nodes=snap.nodes.replace(
            schedulable=jnp.asarray(sched))),
        pods.replace(valid=jnp.asarray(valid)),
        CFG, num_rounds=2, k_choices=4)
    np.testing.assert_array_equal(np.asarray(res.assignment),
                                  np.asarray(oracle.assignment))
    word = int(np.asarray(health)[0])
    assert word & guards.NODE_METRIC_NONFINITE
    assert word & guards.POD_NONFINITE
