"""Golden equivalence of the device LowNodeLoad plan vs the host/numpy
plugin (BASELINE config 5): same pods, same order, across randomized
clusters, threshold modes, node_fit, and eviction caps."""

from typing import Dict, List

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.descheduler import (
    DeviceLowNodeLoad,
    EvictionLimiter,
    LowNodeLoad,
    LowNodeLoadArgs,
    RecordingEvictor,
)

NOW = 1e9


def random_cluster(seed: int, n_nodes: int = 40, hot_frac: float = 0.3):
    """Nodes with random usage; pods on hot-ish nodes with a mix of
    reported usage, request-fallback, and daemonset pods."""
    rng = np.random.default_rng(seed)
    nodes, metrics, by_node = [], {}, {}
    for i in range(n_nodes):
        name = f"n{i}"
        cpu, mem = 64000.0, 65536.0
        nodes.append(api.Node(meta=api.ObjectMeta(name=name),
                              allocatable={RK.CPU: cpu, RK.MEMORY: mem}))
        cpu_pct = rng.uniform(5, 95)
        mem_pct = rng.uniform(5, 95)
        pods, pms = [], []
        if cpu_pct > 55 or mem_pct > 55:
            for j in range(rng.integers(1, 6)):
                pod = api.Pod(
                    meta=api.ObjectMeta(name=f"{name}-p{j}",
                                        namespace=f"ns{j % 3}"),
                    requests={RK.CPU: float(rng.integers(1, 8) * 500),
                              RK.MEMORY: float(rng.integers(1, 8) * 512)},
                    node_name=name,
                    is_daemonset=bool(rng.uniform() < 0.15))
                pods.append(pod)
                if rng.uniform() < 0.7:  # 30% fall back to requests
                    pms.append(api.PodMetricInfo(
                        namespace=pod.meta.namespace, name=pod.meta.name,
                        usage={RK.CPU: float(rng.uniform(200, 6000)),
                               RK.MEMORY: float(rng.uniform(200, 6000))}))
        metrics[name] = api.NodeMetric(
            node_name=name, update_time=NOW,
            node_usage={RK.CPU: cpu * cpu_pct / 100,
                        RK.MEMORY: mem * mem_pct / 100},
            pods_metric=pms)
        by_node[name] = pods
    return nodes, metrics, by_node


def plan_names(plugin, nodes, metrics, by_node):
    return [p.meta.namespaced_name
            for p in plugin.balance_once(nodes, metrics, by_node, NOW)]


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
@pytest.mark.parametrize("deviation,node_fit", [
    (False, True), (False, False), (True, True)])
def test_device_plan_matches_host(seed, deviation, node_fit):
    nodes, metrics, by_node = random_cluster(seed)
    args = dict(consecutive_abnormalities=1,
                use_deviation_thresholds=deviation, node_fit=node_fit,
                dry_run=True)
    host = LowNodeLoad(LowNodeLoadArgs(**args))
    dev = DeviceLowNodeLoad(LowNodeLoadArgs(**args))
    got_host = plan_names(host, nodes, metrics, by_node)
    got_dev = plan_names(dev, nodes, metrics, by_node)
    assert got_dev == got_host


def test_device_plan_honors_per_cycle_cap():
    nodes, metrics, by_node = random_cluster(7)
    args = LowNodeLoadArgs(consecutive_abnormalities=1)
    host_ev = RecordingEvictor(EvictionLimiter(max_per_cycle=3))
    dev_ev = RecordingEvictor(EvictionLimiter(max_per_cycle=3))
    host = LowNodeLoad(args, host_ev)
    dev = DeviceLowNodeLoad(args, dev_ev)
    host.balance_once(nodes, metrics, by_node, NOW)
    got_dev = dev.balance_once(nodes, metrics, by_node, NOW)
    assert len(got_dev) <= 3
    assert ([e.pod.meta.namespaced_name for e in dev_ev.evictions]
            == [e.pod.meta.namespaced_name for e in host_ev.evictions])


def test_dry_run_ignores_the_limiter_like_the_host():
    """The host plugin never consults the evictor in dry_run; the
    device cap must not truncate a dry-run plan either."""
    nodes, metrics, by_node = random_cluster(11)
    args = LowNodeLoadArgs(consecutive_abnormalities=1, dry_run=True)
    host = LowNodeLoad(args, RecordingEvictor(
        EvictionLimiter(max_per_cycle=1)))
    dev = DeviceLowNodeLoad(args, RecordingEvictor(
        EvictionLimiter(max_per_cycle=1)))
    got_host = plan_names(host, nodes, metrics, by_node)
    got_dev = plan_names(dev, nodes, metrics, by_node)
    assert got_dev == got_host
    assert len(got_host) > 1  # the cap would have truncated to 1


def test_pod_usage_from_expired_metrics_still_counts():
    """The host builds pod_usage from EVERY NodeMetric (no expiry
    check); only node classification is freshness-gated. A pod whose
    usage arrives via a stale metric must sort/deplete identically on
    both paths."""
    nodes, metrics, by_node = random_cluster(13)
    # move one hot pod's usage report into an expired metric of another
    # node (the migrated-pod shape the host path tolerates)
    donor = next(n for n in metrics if by_node[n])
    pod = by_node[donor][0]
    stale_holder = next(n for n in metrics if n != donor)
    m = metrics[stale_holder]
    metrics[stale_holder] = api.NodeMetric(
        node_name=m.node_name, update_time=NOW - 10_000,
        node_usage=m.node_usage,
        pods_metric=[api.PodMetricInfo(
            namespace=pod.meta.namespace, name=pod.meta.name,
            usage={RK.CPU: 9999.0, RK.MEMORY: 9999.0})])
    args = dict(consecutive_abnormalities=1, dry_run=True)
    got_host = plan_names(LowNodeLoad(LowNodeLoadArgs(**args)),
                          nodes, metrics, by_node)
    got_dev = plan_names(DeviceLowNodeLoad(LowNodeLoadArgs(**args)),
                         nodes, metrics, by_node)
    assert got_dev == got_host


@pytest.mark.parametrize("seed", [3, 9, 21])
@pytest.mark.parametrize("caps", [
    dict(max_per_node=1),
    dict(max_per_namespace=1),
    dict(max_per_node=2, max_per_namespace=2, max_per_cycle=5),
])
def test_per_node_and_ns_caps_match_host(seed, caps):
    """Per-node / per-namespace / per-cycle caps run ON DEVICE (the
    scan kernel replays the limiter's skip-and-continue), golden-equal
    to the host loop — including the non-prefix acceptance shape where
    a capped pod is skipped and a later pod on the same node evicts."""
    nodes, metrics, by_node = random_cluster(seed)
    args = LowNodeLoadArgs(consecutive_abnormalities=1)
    host_ev = RecordingEvictor(EvictionLimiter(**caps))
    dev_ev = RecordingEvictor(EvictionLimiter(**caps))
    host = LowNodeLoad(args, host_ev)
    dev = DeviceLowNodeLoad(args, dev_ev)
    host.balance_once(nodes, metrics, by_node, NOW)
    got = dev.balance_once(nodes, metrics, by_node, NOW)
    assert ([e.pod.meta.namespaced_name for e in dev_ev.evictions]
            == [e.pod.meta.namespaced_name for e in host_ev.evictions])
    # the returned selection is exactly what the evictor accepted
    assert ([p.meta.namespaced_name for p in got]
            == [e.pod.meta.namespaced_name for e in dev_ev.evictions])


def test_capped_plan_seeds_mid_cycle_limiter_state():
    """A second balance call WITHOUT a limiter reset must respect the
    counts the first call consumed, exactly like the host loop."""
    nodes, metrics, by_node = random_cluster(5)
    caps = dict(max_per_node=1, max_per_namespace=2, max_per_cycle=6)
    host_ev = RecordingEvictor(EvictionLimiter(**caps))
    dev_ev = RecordingEvictor(EvictionLimiter(**caps))
    host = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1),
                       host_ev)
    dev = DeviceLowNodeLoad(
        LowNodeLoadArgs(consecutive_abnormalities=1), dev_ev)
    for _ in range(2):   # no reset between calls
        host.balance_once(nodes, metrics, by_node, NOW)
        dev.balance_once(nodes, metrics, by_node, NOW)
    assert ([e.pod.meta.namespaced_name for e in dev_ev.evictions]
            == [e.pod.meta.namespaced_name for e in host_ev.evictions])


def test_custom_evictor_refusals_filter_the_selection():
    """An evictor that refuses pods outside the limiter model: the
    device wrapper must drop refused pods from `selected` (the host
    loop's behavior), not report them as evicted."""
    nodes, metrics, by_node = random_cluster(7)

    class PickyEvictor(RecordingEvictor):
        def evict(self, pod, reason):
            if pod.meta.name.endswith("p0"):
                return False
            return super().evict(pod, reason)

    dev = DeviceLowNodeLoad(
        LowNodeLoadArgs(consecutive_abnormalities=1), PickyEvictor())
    got = dev.balance_once(nodes, metrics, by_node, NOW)
    assert got, "workload must actually evict something"
    assert all(not p.meta.name.endswith("p0") for p in got)
    assert ([p.meta.namespaced_name for p in got]
            == [e.pod.meta.namespaced_name
                for e in dev.evictor.evictions])


def test_scale_regression_2k_nodes():
    """In-suite scale guard (VERDICT r3 weak #6): a 2k-node balance
    plan must complete promptly on the device path and still match the
    host plan exactly — the 10k-node number is bench config 5, this
    pins the regression surface inside the suite."""
    import time

    nodes, metrics, by_node = random_cluster(21, n_nodes=2000)
    args = dict(consecutive_abnormalities=1, dry_run=True)
    host = LowNodeLoad(LowNodeLoadArgs(**args))
    dev = DeviceLowNodeLoad(LowNodeLoadArgs(**args))
    got_host = plan_names(host, nodes, metrics, by_node)
    dev.balance_once(nodes, metrics, by_node, NOW)  # warm/compile
    dev2 = DeviceLowNodeLoad(LowNodeLoadArgs(**args))
    t0 = time.perf_counter()
    got_dev = plan_names(dev2, nodes, metrics, by_node)
    elapsed = time.perf_counter() - t0
    assert got_dev == got_host
    assert len(got_dev) > 100  # a real plan, not a degenerate no-op
    # generous for CI noise; the host loop at this scale is ~2x slower
    # and the 10k bench line pins the real number
    assert elapsed < 3.0, elapsed


def test_budget_exhaustion_is_a_global_prefix():
    """One tiny destination: the budget runs dry mid-plan and nothing
    later is planned anywhere — the monotone-prefix property the device
    formulation rests on, asserted against the host loop."""
    nodes = [api.Node(meta=api.ObjectMeta(name="dst"),
                      allocatable={RK.CPU: 64000.0, RK.MEMORY: 65536.0})]
    # underutilized (below 45/60) but with bounded headroom: cpu budget
    # = (65 - 40)% of 64000 = 16000m < the 18000m of hot-pod demand
    metrics = {"dst": api.NodeMetric(
        node_name="dst", update_time=NOW,
        node_usage={RK.CPU: 64000.0 * 0.40, RK.MEMORY: 65536.0 * 0.50})}
    by_node: Dict[str, List[api.Pod]] = {"dst": []}
    for i in range(3):
        name = f"hot{i}"
        nodes.append(api.Node(
            meta=api.ObjectMeta(name=name),
            allocatable={RK.CPU: 64000.0, RK.MEMORY: 65536.0}))
        pods = [api.Pod(meta=api.ObjectMeta(name=f"{name}-p{j}",
                                            namespace="d"),
                        requests={RK.CPU: 1500.0, RK.MEMORY: 1024.0},
                        node_name=name)
                for j in range(4)]
        metrics[name] = api.NodeMetric(
            node_name=name, update_time=NOW,
            node_usage={RK.CPU: 64000.0 * 0.9, RK.MEMORY: 65536.0 * 0.5})
        by_node[name] = pods
    args = dict(consecutive_abnormalities=1, dry_run=True,
                node_fit=False)
    got_host = plan_names(LowNodeLoad(LowNodeLoadArgs(**args)),
                          nodes, metrics, by_node)
    got_dev = plan_names(DeviceLowNodeLoad(LowNodeLoadArgs(**args)),
                         nodes, metrics, by_node)
    assert got_dev == got_host
    # the tiny dst headroom (~2% cpu) cannot absorb every hot pod
    total = sum(len(p) for n, p in by_node.items() if n != "dst")
    assert 0 < len(got_host) < total
