"""Sequential NumPy oracle re-implementing the reference scheduler semantics.

This is an independent, readable re-statement of the Go behavior
(load_aware.go:123-397, elasticquota plugin.go:211-257, coscheduling
core.go:220-341) used as the golden model for the batched JAX kernels:
pods are scheduled ONE AT A TIME in priority order, exactly like the
reference's scheduleOne loop, with plain dict/float math.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from koordinator_tpu.api.extension import NUM_RESOURCES, PriorityClass, ResourceKind
from koordinator_tpu.snapshot.builder import estimate_pod, round_half_away
from koordinator_tpu.api.types import Node, NodeMetric, Pod

MAX_NODE_SCORE = 100


@dataclasses.dataclass
class OracleArgs:
    resource_weights: Dict[ResourceKind, float]
    usage_thresholds: Dict[ResourceKind, float]
    prod_usage_thresholds: Dict[ResourceKind, float]
    agg_usage_thresholds: Dict[ResourceKind, float]
    filter_agg_type: str = ""
    score_agg_type: str = ""
    score_according_prod_usage: bool = False

    @staticmethod
    def default() -> "OracleArgs":
        return OracleArgs(
            resource_weights={ResourceKind.CPU: 1, ResourceKind.MEMORY: 1},
            usage_thresholds={ResourceKind.CPU: 65, ResourceKind.MEMORY: 95},
            prod_usage_thresholds={},
            agg_usage_thresholds={},
        )


@dataclasses.dataclass
class OracleNode:
    """Host-side per-node scheduler state."""

    node: Node
    metric: Optional[NodeMetric]
    metric_fresh: bool
    requested: np.ndarray                    # [R]
    assigned_estimated: np.ndarray           # [R]
    assigned_correction: np.ndarray          # [R]
    prod_assigned_estimated: np.ndarray      # [R]
    prod_assigned_correction: np.ndarray     # [R]
    prod_usage: np.ndarray                   # [R]

    def alloc_vec(self) -> np.ndarray:
        from koordinator_tpu.snapshot.builder import resource_vec
        return resource_vec(self.node.allocatable)


def usage_vec(metric: Optional[NodeMetric], agg_type: str) -> Optional[np.ndarray]:
    from koordinator_tpu.snapshot.builder import resource_vec
    if metric is None:
        return None
    if agg_type:
        rl = metric.aggregated_usage(agg_type)
        return None if rl is None else resource_vec(rl)
    return resource_vec(metric.node_usage)


def oracle_filter(on: OracleNode, pod: Pod, args: OracleArgs) -> bool:
    """Plugin.Filter (load_aware.go:123-254)."""
    if pod.is_daemonset:
        return True
    if on.metric is None or not on.metric_fresh:
        return True
    alloc = on.alloc_vec()
    is_prod = pod.priority_class is PriorityClass.PROD
    if args.prod_usage_thresholds and is_prod:
        for kind, thr in args.prod_usage_thresholds.items():
            if thr == 0 or alloc[int(kind)] == 0:
                continue
            pct = round_half_away(on.prod_usage[int(kind)] / alloc[int(kind)] * 100)
            if pct >= thr:
                return False
        return True
    if args.filter_agg_type:
        thresholds = args.agg_usage_thresholds
        used = usage_vec(on.metric, args.filter_agg_type)
        if used is None:
            return True
    else:
        thresholds = args.usage_thresholds
        used = usage_vec(on.metric, "")
    for kind, thr in thresholds.items():
        if thr == 0 or alloc[int(kind)] == 0:
            continue
        pct = round_half_away(used[int(kind)] / alloc[int(kind)] * 100)
        if pct >= thr:
            return False
    return True


def oracle_score(on: OracleNode, pod: Pod, args: OracleArgs) -> float:
    """Plugin.Score (load_aware.go:269-335) + scorer (:378-397)."""
    if on.metric is None or not on.metric_fresh:
        return 0.0
    alloc = on.alloc_vec()
    est = estimate_pod(pod, weights=args.resource_weights)
    prod_scored = (args.score_according_prod_usage
                   and pod.priority_class is PriorityClass.PROD)
    if prod_scored:
        estimated = (est + on.prod_assigned_estimated
                     + np.maximum(on.prod_usage - on.prod_assigned_correction, 0))
    else:
        src = usage_vec(on.metric, args.score_agg_type)
        src = np.zeros(NUM_RESOURCES, np.float64) if src is None else src.astype(np.float64)
        corrected = src - np.where(src >= on.assigned_correction,
                                   on.assigned_correction, 0)
        estimated = est + on.assigned_estimated + corrected

    score_sum, weight_sum = 0.0, 0.0
    for kind, w in args.resource_weights.items():
        cap, used = alloc[int(kind)], estimated[int(kind)]
        if cap == 0 or used > cap:
            s = 0
        else:
            s = math.floor((cap - used) * MAX_NODE_SCORE / cap)
        score_sum += s * w
        weight_sum += w
    return math.floor(score_sum / weight_sum)


@dataclasses.dataclass
class OracleQuota:
    name: str
    parent: Optional[str]
    runtime: np.ndarray   # [R] entitlement
    used: np.ndarray      # [R]


class OracleScheduler:
    """Sequential scheduler: fit + LoadAware + quota gate + gang rollback
    + the vanilla topology gates (hard taints, hard spread, required
    (anti-)affinity both directions) evaluated per pod in strict
    sequence — the reference semantics the batched program must match at
    chunk size 1."""

    def __init__(self, nodes: List[OracleNode], args: OracleArgs,
                 quotas: Optional[Dict[str, OracleQuota]] = None,
                 gang_min: Optional[Dict[str, int]] = None,
                 gang_members: Optional[Dict[str, int]] = None,
                 running_pods: Optional[List[Tuple[Pod, int]]] = None):
        self.nodes = nodes
        self.args = args
        self.quotas = quotas or {}
        self.gang_min = gang_min or {}
        self.gang_members = gang_members or {}
        self.gang_placed: Dict[str, List[Tuple[int, int]]] = {}
        # (pod, node index) of running + sequentially-assumed pods — the
        # view the topology gates read
        self.cluster_pods: List[Tuple[Pod, int]] = list(running_pods or [])

    def _topology_ok(self, pod: Pod, node_idx: int) -> bool:
        """ONE sequential reference implementation validates both the
        device kernels (through this oracle) and the preemption
        nominator: node_admits + constraints_admit from
        scheduler/preemption.py ARE the sequential semantics."""
        from koordinator_tpu.scheduler.preemption import (
            constraints_admit,
            node_admits,
        )

        node = self.nodes[node_idx].node
        if not node_admits(pod, node):
            return False
        pods_by_node: Dict[str, List[Pod]] = {}
        for p, ni in self.cluster_pods:
            pods_by_node.setdefault(self.nodes[ni].node.meta.name,
                                    []).append(p)
        return constraints_admit(pod, node,
                                 [on.node for on in self.nodes],
                                 pods_by_node, frozenset())

    def _spread_counts(self, pod: Pod):
        """[(constraint, per-domain counts, max count)] for EVERY
        carried constraint — computed ONCE per pod; the per-node penalty
        looks the node's domain up and SUMS over constraints. Mirrors
        core.py spread_penalty (per-group normalization, summed over the
        carrier matrix)."""
        out = []
        for c in pod.spread_constraints:
            counts: Dict[str, int] = {}
            for n in self.nodes:
                d = n.node.meta.labels.get(c.topology_key)
                if d is not None:
                    counts.setdefault(d, 0)
            for p, ni in self.cluster_pods:
                d = self.nodes[ni].node.meta.labels.get(c.topology_key)
                if d is not None and _matches(p, pod.meta.namespace,
                                              c.label_selector):
                    counts[d] = counts.get(d, 0) + 1
            out.append((c, counts, max(counts.values(), default=0)))
        return out or None

    def _quota_chain(self, name: str) -> List[OracleQuota]:
        chain = []
        while name:
            q = self.quotas.get(name)
            if q is None:
                break
            chain.append(q)
            name = q.parent or ""
        return chain

    def schedule_one(self, pod: Pod, pod_idx: int) -> int:
        from koordinator_tpu.snapshot.builder import resource_vec
        req = resource_vec(pod.requests)
        # gang quorum prefilter
        if pod.gang_name:
            if self.gang_members.get(pod.gang_name, 0) < \
                    self.gang_min.get(pod.gang_name, 1):
                return -1
        # quota admission
        for q in self._quota_chain(pod.quota_name):
            if np.any(q.used + req > q.runtime + 0.5):
                return -1
        best_node, best_score = -1, -1.0
        spread_info = self._spread_counts(pod)
        for i, on in enumerate(self.nodes):
            if on.node.unschedulable:
                continue
            if pod.node_selector and any(
                    on.node.meta.labels.get(k) != v
                    for k, v in pod.node_selector.items()):
                continue
            if np.any(on.requested + req > on.alloc_vec() + 0.5):
                continue
            if not oracle_filter(on, pod, self.args):
                continue
            if not self._topology_ok(pod, i):
                continue
            s = oracle_score(on, pod, self.args)
            if spread_info is not None:
                penalty = 0.0
                for c, counts, max_c in spread_info:
                    dom = on.node.meta.labels.get(c.topology_key)
                    if dom is not None:
                        penalty += counts.get(dom, 0) / max(max_c, 1.0) \
                            * 100.0
                s = max(s - penalty, 0.0)
            if s > best_score:
                best_node, best_score = i, s
        if best_node < 0:
            return -1
        # assume (Reserve): requested + podAssignCache estimate
        on = self.nodes[best_node]
        on.requested = on.requested + req
        est = estimate_pod(pod, weights=self.args.resource_weights)
        on.assigned_estimated = on.assigned_estimated + est
        if pod.priority_class is PriorityClass.PROD:
            on.prod_assigned_estimated = on.prod_assigned_estimated + est
        for q in self._quota_chain(pod.quota_name):
            q.used = q.used + req
        if pod.gang_name:
            self.gang_placed.setdefault(pod.gang_name, []).append(
                (pod_idx, best_node))
        self.cluster_pods.append((pod, best_node))
        return best_node

    def schedule(self, pods: List[Pod]) -> np.ndarray:
        """Priority-desc, index-asc order; strict-gang rollback at the end."""
        from koordinator_tpu.snapshot.builder import resource_vec
        order = sorted(range(len(pods)),
                       key=lambda i: (-(pods[i].priority or 0), i))
        out = np.full((len(pods),), -1, np.int64)
        for i in order:
            out[i] = self.schedule_one(pods[i], i)
        # strict gang all-or-nothing rollback
        for gang, placed in self.gang_placed.items():
            prior = 0
            if len(placed) + prior < self.gang_min.get(gang, 1):
                for pod_idx, node_idx in placed:
                    self.cluster_pods = [
                        (p, n) for p, n in self.cluster_pods
                        if p is not pods[pod_idx]]
                    on = self.nodes[node_idx]
                    pod = pods[pod_idx]
                    req = resource_vec(pod.requests)
                    est = estimate_pod(pod, weights=self.args.resource_weights)
                    on.requested = on.requested - req
                    on.assigned_estimated = on.assigned_estimated - est
                    if pod.priority_class is PriorityClass.PROD:
                        on.prod_assigned_estimated = \
                            on.prod_assigned_estimated - est
                    for q in self._quota_chain(pod.quota_name):
                        q.used = q.used - req
                    out[pod_idx] = -1
        return out


def _matches(p: Pod, ns: str, selector) -> bool:
    """One selector matcher (the builder's semantics)."""
    from koordinator_tpu.snapshot.builder import SnapshotBuilder
    return SnapshotBuilder._matches(p, ns, selector)


def make_oracle_nodes(builder, now: Optional[float] = None) -> List[OracleNode]:
    """Construct oracle state from the same SnapshotBuilder inputs, reusing
    the builder's columnar output so both sides see identical preprocessing
    of metrics/assign-cache (that part is itself unit-tested separately)."""
    state, _ = builder.build_nodes(now)
    out = []
    for i, node in enumerate(builder.nodes):
        metric = builder.metrics.get(node.meta.name)
        out.append(OracleNode(
            node=node,
            metric=metric,
            metric_fresh=bool(state.metric_fresh[i]),
            requested=np.array(state.requested[i], np.float64),
            assigned_estimated=np.array(state.assigned_estimated[i], np.float64),
            assigned_correction=np.array(state.assigned_correction[i], np.float64),
            prod_assigned_estimated=np.array(state.prod_assigned_estimated[i], np.float64),
            prod_assigned_correction=np.array(state.prod_assigned_correction[i], np.float64),
            prod_usage=np.array(state.prod_usage[i], np.float64),
        ))
    return out
