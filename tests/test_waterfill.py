"""Golden tests: water-filling runtime kernel vs a sequential oracle
re-implementing runtime_quota_calculator.go redistribution semantics."""

import numpy as np
import pytest

from koordinator_tpu.api.extension import NUM_RESOURCES, ResourceKind as RK
from koordinator_tpu.api.types import ElasticQuota, Node, ObjectMeta
from koordinator_tpu.ops import waterfill
from koordinator_tpu.snapshot.builder import SnapshotBuilder


def oracle_redistribute(children, total):
    """quotaTree.redistribution (runtime_quota_calculator.go:111-141) +
    iterationForRedistribution (:144-168), one resource dim. Each recursion
    re-partitions ONLY the excess returned by children that hit their
    request; the rounding remainder of a round is dropped."""
    runtimes = {}
    adjusting, tot_w = [], 0.0
    to_partition = total
    for c in children:
        mn = c["min"]
        if c["demand"] > mn:
            adjusting.append(c)
            tot_w += c["weight"]
            rt = mn
        else:
            rt = c["demand"] if c["allow_lent"] else mn
        runtimes[c["name"]] = rt
        to_partition -= rt

    while to_partition > 0 and tot_w > 0 and adjusting:
        nxt, nxt_w, returned = [], 0.0, 0.0
        for c in adjusting:
            delta = np.floor(c["weight"] * to_partition / tot_w + 0.5)
            rt = runtimes[c["name"]] + delta
            if rt < c["demand"]:
                nxt.append(c)
                nxt_w += c["weight"]
                runtimes[c["name"]] = rt
            else:
                returned += rt - c["demand"]
                runtimes[c["name"]] = c["demand"]
        to_partition = returned
        adjusting, tot_w = nxt, nxt_w
    return runtimes


def build_forest(rng, num_children=6, two_level=True):
    b = SnapshotBuilder(max_nodes=1, max_quotas=32)
    b.add_node(Node(meta=ObjectMeta(name="n0"), allocatable={}))
    total = 100000.0
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"),
                             max={RK.CPU: total}, is_parent=True))
    spec = {"root": {"max": total, "parent": None}}
    for i in range(num_children):
        mx = float(rng.integers(10, 60) * 1000)
        mn = float(rng.integers(0, 10) * 1000)
        w = float(rng.integers(1, 10) * 1000)
        allow = bool(rng.uniform() < 0.8)
        b.add_quota(ElasticQuota(
            meta=ObjectMeta(name=f"c{i}"), parent="root",
            min={RK.CPU: mn}, max={RK.CPU: mx},
            shared_weight={RK.CPU: w},
            allow_lent_resource=allow))
        spec[f"c{i}"] = {"min": mn, "max": mx, "weight": w,
                         "allow_lent": allow, "parent": "root"}
    return b, spec, total


@pytest.mark.parametrize("seed", range(6))
def test_waterfill_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    b, spec, total = build_forest(rng)
    snap, _ = b.build(now=0.0)

    # random demand per child
    demand = np.array(snap.quotas.demand).copy()
    names = [q.meta.name for q in b.quotas]
    child_specs = []
    for i, name in enumerate(names):
        if name == "root":
            demand[i, int(RK.CPU)] = 0.0
            continue
        d = float(rng.integers(0, 80) * 1000)
        demand[i, int(RK.CPU)] = d
        s = spec[name]
        child_specs.append({
            "name": name, "min": s["min"],
            "demand": min(d, s["max"]),
            "weight": s["weight"], "allow_lent": s["allow_lent"]})
    quotas = snap.quotas.replace(demand=demand)

    cluster_total = np.zeros((NUM_RESOURCES,), np.float32)
    cluster_total[int(RK.CPU)] = total
    runtime = np.asarray(waterfill.compute_runtime(quotas, cluster_total))

    want = oracle_redistribute(child_specs, total)
    for i, name in enumerate(names):
        if name == "root":
            assert runtime[i, int(RK.CPU)] == pytest.approx(total)
            continue
        got = runtime[i, int(RK.CPU)]
        assert got == pytest.approx(want[name], abs=1.5), (
            name, got, want[name])


def test_waterfill_respects_min_when_not_lending():
    """allowLentResource=false keeps runtime at min even with zero demand
    (redistribution else-branch, runtime_quota_calculator.go:131-137)."""
    b = SnapshotBuilder(max_nodes=1, max_quotas=8)
    b.add_node(Node(meta=ObjectMeta(name="n0"), allocatable={}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"),
                             max={RK.CPU: 10000.0}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="hoarder"), parent="root",
                             min={RK.CPU: 4000.0}, max={RK.CPU: 8000.0},
                             allow_lent_resource=False))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="hungry"), parent="root",
                             min={RK.CPU: 0.0}, max={RK.CPU: 10000.0},
                             shared_weight={RK.CPU: 1.0}))
    snap, _ = b.build(now=0.0)
    demand = np.array(snap.quotas.demand)
    demand[2, int(RK.CPU)] = 10000.0  # hungry wants everything
    quotas = snap.quotas.replace(demand=demand)
    total = np.zeros((NUM_RESOURCES,), np.float32)
    total[int(RK.CPU)] = 10000.0
    runtime = np.asarray(waterfill.compute_runtime(quotas, total))
    assert runtime[1, int(RK.CPU)] == pytest.approx(4000.0)  # kept min
    assert runtime[2, int(RK.CPU)] == pytest.approx(6000.0)  # the rest


def test_demand_clamped_by_child_max_before_parent():
    """A child's runaway demand is capped at its max before it reaches the
    parent (limitedRequest propagation, group_quota_manager.go:184-214), so
    it cannot starve its parent's siblings."""
    b = SnapshotBuilder(max_nodes=1, max_quotas=8)
    b.add_node(Node(meta=ObjectMeta(name="n0"), allocatable={}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"),
                             max={RK.CPU: 100000.0}))
    # mid is a parent whose only child has max 10k
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="mid"), parent="root",
                             max={RK.CPU: 100000.0},
                             shared_weight={RK.CPU: 1.0}, is_parent=True))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="capped"), parent="mid",
                             max={RK.CPU: 10000.0},
                             shared_weight={RK.CPU: 1.0}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="sib"), parent="root",
                             max={RK.CPU: 100000.0},
                             shared_weight={RK.CPU: 1.0}))
    snap, _ = b.build(now=0.0)
    demand = np.array(snap.quotas.demand)
    names = [q.meta.name for q in b.quotas]
    demand[names.index("capped"), int(RK.CPU)] = 100000.0  # wants 10x its max
    demand[names.index("sib"), int(RK.CPU)] = 100000.0
    quotas = snap.quotas.replace(demand=demand)
    total = np.zeros((NUM_RESOURCES,), np.float32)
    total[int(RK.CPU)] = 100000.0
    runtime = np.asarray(waterfill.compute_runtime(quotas, total))
    # mid's limitedRequest is 10k (child clamp), so sib gets the other 90k
    assert runtime[names.index("mid"), int(RK.CPU)] == pytest.approx(10000.0)
    assert runtime[names.index("sib"), int(RK.CPU)] == pytest.approx(90000.0)
    assert runtime[names.index("capped"), int(RK.CPU)] == pytest.approx(10000.0)


def test_non_lending_child_floors_parent_demand():
    """allowLentResource=false floors the subtree request at min during
    propagation (recursiveUpdateGroupTreeWithDeltaRequest min floor)."""
    b = SnapshotBuilder(max_nodes=1, max_quotas=8)
    b.add_node(Node(meta=ObjectMeta(name="n0"), allocatable={}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"),
                             max={RK.CPU: 100000.0}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="mid"), parent="root",
                             max={RK.CPU: 100000.0},
                             shared_weight={RK.CPU: 1.0}, is_parent=True))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="hoard"), parent="mid",
                             min={RK.CPU: 30000.0}, max={RK.CPU: 50000.0},
                             allow_lent_resource=False,
                             shared_weight={RK.CPU: 1.0}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="sib"), parent="root",
                             max={RK.CPU: 100000.0},
                             shared_weight={RK.CPU: 1.0}))
    snap, _ = b.build(now=0.0)
    demand = np.array(snap.quotas.demand)
    names = [q.meta.name for q in b.quotas]
    demand[names.index("sib"), int(RK.CPU)] = 100000.0  # hoard demands nothing
    quotas = snap.quotas.replace(demand=demand)
    total = np.zeros((NUM_RESOURCES,), np.float32)
    total[int(RK.CPU)] = 100000.0
    runtime = np.asarray(waterfill.compute_runtime(quotas, total))
    # hoard's 30k min is kept inside mid's subtree request even at 0 demand
    assert runtime[names.index("mid"), int(RK.CPU)] == pytest.approx(30000.0)
    assert runtime[names.index("sib"), int(RK.CPU)] == pytest.approx(70000.0)


def test_demand_fold_and_runtime_gate_end_to_end():
    """add_pending_demand -> compute_runtime -> schedule_batch admission."""
    import jax.numpy as jnp

    from koordinator_tpu.api.extension import ResourceKind
    from koordinator_tpu.api.types import NodeMetric, Pod
    from koordinator_tpu.ops.quota_demand import add_pending_demand
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins import loadaware

    b = SnapshotBuilder(max_nodes=2, max_quotas=8)
    for i in range(2):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 100000, RK.MEMORY: 1 << 20}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=1.0,
                                     node_usage={}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"),
                             max={RK.CPU: 30000.0, RK.MEMORY: 1 << 30}))
    # two siblings with equal weight, no min: fair share = half each
    for name in ("a", "b"):
        b.add_quota(ElasticQuota(meta=ObjectMeta(name=name), parent="root",
                                 max={RK.CPU: 30000.0, RK.MEMORY: 1 << 30},
                                 shared_weight={RK.CPU: 1.0, RK.MEMORY: 1.0}))
    snap, ctx = b.build(now=1.0)
    pods = [Pod(meta=ObjectMeta(name=f"pa{j}"), priority=9000,
                requests={RK.CPU: 5000.0, RK.MEMORY: 64.0}, quota_name="a")
            for j in range(4)]
    pods += [Pod(meta=ObjectMeta(name=f"pb{j}"), priority=8000,
                 requests={RK.CPU: 5000.0, RK.MEMORY: 64.0}, quota_name="b")
             for j in range(4)]
    batch = b.build_pod_batch(pods, ctx)

    quotas = add_pending_demand(snap.quotas, batch)
    total = np.zeros((NUM_RESOURCES,), np.float32)
    total[int(RK.CPU)] = 30000.0
    total[int(RK.MEMORY)] = float(1 << 30)
    runtime = waterfill.compute_runtime(quotas, total)
    snap = snap.replace(quotas=quotas.replace(runtime=runtime))

    res = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make(),
                              num_rounds=2)
    a = np.asarray(res.assignment)
    # fair share 15000 CPU each -> 3 pods per quota (demand 20000 each)
    assert (a[:4] >= 0).sum() == 3
    assert (a[4:] >= 0).sum() == 3
