"""Default priority preemption (upstream PostFilter; complements the
quota-scoped preemption in plugins/quota_revoke.py)."""

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import Node, NodeMetric, ObjectMeta, Pod
from koordinator_tpu.scheduler.preemption import (
    find_preemption,
    select_victims_on_node,
)
from koordinator_tpu.snapshot.builder import SnapshotBuilder, resource_vec


def mk_pod(name, prio, cpu, preemptible=True):
    anns = {} if preemptible else {
        "scheduling.koordinator.sh/preemptible": "false"}
    return Pod(meta=ObjectMeta(name=name, annotations=anns),
               priority=prio, requests={RK.CPU: cpu, RK.MEMORY: 256.0})


def test_minimal_victim_set_with_reprieve():
    alloc = resource_vec({RK.CPU: 8000.0, RK.MEMORY: 16384.0})
    running = [mk_pod("low-a", 5000, 3000.0),
               mk_pod("low-b", 5500, 3000.0),
               mk_pod("peer", 9100, 2000.0)]
    preemptor = mk_pod("prod", 9500, 3000.0)
    victims = select_victims_on_node(preemptor, alloc, running)
    # 2000 (peer kept) + 3000 needed: freeing ONE 3000m victim suffices;
    # reprieve keeps the more important (5500) candidate
    assert victims is not None
    assert [v.meta.name for v in victims] == ["low-a"]


def test_non_preemptible_and_higher_priority_protected():
    alloc = resource_vec({RK.CPU: 4000.0, RK.MEMORY: 16384.0})
    running = [mk_pod("protected", 5000, 4000.0, preemptible=False)]
    assert select_victims_on_node(mk_pod("p", 9000, 2000.0), alloc,
                                  running) is None
    running2 = [mk_pod("higher", 9600, 4000.0)]
    assert select_victims_on_node(mk_pod("p", 9000, 2000.0), alloc,
                                  running2) is None


def test_pick_node_prefers_cheapest_victims():
    nodes = [Node(meta=ObjectMeta(name="a"),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0}),
             Node(meta=ObjectMeta(name="b"),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})]
    pods_by_node = {
        "a": [mk_pod("mid", 7000, 8000.0)],      # victim priority 7000
        "b": [mk_pod("batch", 5000, 8000.0)],    # victim priority 5000
    }
    got = find_preemption(mk_pod("prod", 9500, 4000.0), nodes,
                          pods_by_node)
    assert got is not None and got.node_name == "b"
    assert [v.meta.name for v in got.victims] == ["batch"]


def test_preemption_feeds_next_batch():
    """End-to-end: unschedulable -> preempt -> evict victims -> rebuild
    -> the preemptor lands on the nominated node."""
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig

    def build(running):
        b = SnapshotBuilder(max_nodes=1)
        b.add_node(Node(meta=ObjectMeta(name="n0"),
                        allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0}))
        b.set_node_metric(NodeMetric(node_name="n0", update_time=1e9,
                                     node_usage={}))
        for p in running:
            p.phase = "Running"
            p.node_name = "n0"
            b.add_running_pod(p)
        return b

    victim = mk_pod("be", 5000, 6000.0)
    preemptor = mk_pod("prod", 9500, 4000.0)
    b = build([victim])
    snap, ctx = b.build(now=1e9)
    res = core.schedule_batch(snap, b.build_pod_batch([preemptor], ctx),
                              LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) == -1  # full node
    nom = find_preemption(preemptor,
                          [Node(meta=ObjectMeta(name="n0"),
                                allocatable={RK.CPU: 8000.0,
                                             RK.MEMORY: 16384.0})],
                          {"n0": [victim]})
    assert nom and [v.meta.name for v in nom.victims] == ["be"]
    b2 = build([])  # victims evicted
    snap2, ctx2 = b2.build(now=1e9)
    res2 = core.schedule_batch(snap2, b2.build_pod_batch([preemptor],
                                                         ctx2),
                               LoadAwareConfig.make())
    assert int(np.asarray(res2.assignment)[0]) == 0


def test_find_preemption_honors_pod_level_gates():
    """Regression: never nominate a node the next batch's gates will
    reject — victims must not die for an impossible nomination."""
    from koordinator_tpu.api.types import Taint, Toleration

    nodes = [Node(meta=ObjectMeta(name="wrong-zone",
                                  labels={"zone": "b"}),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0}),
             Node(meta=ObjectMeta(name="tainted",
                                  labels={"zone": "a"}),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0},
                  taints=[Taint(key="x", effect="NoSchedule")]),
             Node(meta=ObjectMeta(name="good", labels={"zone": "a"}),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})]
    pods_by_node = {n.meta.name: [mk_pod(f"v-{n.meta.name}", 5000, 8000.0)]
                    for n in nodes}
    preemptor = mk_pod("prod", 9500, 4000.0)
    preemptor.node_selector = {"zone": "a"}
    got = find_preemption(preemptor, nodes, pods_by_node)
    assert got is not None and got.node_name == "good"
    # tolerating the taint widens the choice to both zone-a nodes
    preemptor.tolerations = [Toleration(key="x")]
    got2 = find_preemption(preemptor, nodes, pods_by_node)
    assert got2 is not None and got2.node_name in ("tainted", "good")


def test_preemption_post_filter_in_error_chain():
    """The chain wiring: an unschedulable prod pod dispatched through
    the error handlers produces a nomination from the cluster view."""
    from koordinator_tpu.scheduler.errorhandler import (
        ErrorHandlerDispatcher,
        QueuedPodInfo,
        SchedulingError,
        make_preemption_post_filter,
    )

    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})
    victim = mk_pod("be", 5000, 8000.0)
    nominations = []
    dispatcher = ErrorHandlerDispatcher()
    dispatcher.register(post=make_preemption_post_filter(
        lambda: [node], lambda: {"n0": [victim]},
        lambda pod, nom: nominations.append((pod.meta.name, nom))))
    dispatcher.error(QueuedPodInfo(pod=mk_pod("prod", 9500, 4000.0)),
                     SchedulingError("no node fits"))
    assert len(nominations) == 1
    name, nom = nominations[0]
    assert name == "prod" and nom.node_name == "n0"
    assert [v.meta.name for v in nom.victims] == ["be"]
    # a priority-LESS pod (None) never preempts
    dispatcher.error(QueuedPodInfo(pod=mk_pod("free", None, 100.0)),
                     SchedulingError("no node fits"))
    assert len(nominations) == 1


def test_priority_zero_preempts_negative_victims():
    """Regression (ADVICE r3): upstream's PostFilter runs for ANY
    unschedulable pod with a priority — a priority-0 pod legitimately
    preempts negative-priority victims; only a pod with no priority at
    all skips the dry run."""
    from koordinator_tpu.scheduler.errorhandler import (
        ErrorHandlerDispatcher,
        QueuedPodInfo,
        SchedulingError,
        make_preemption_post_filter,
    )

    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})
    victim = mk_pod("neg", -10, 8000.0)
    nominations = []
    d = ErrorHandlerDispatcher()
    d.register(post=make_preemption_post_filter(
        lambda: [node], lambda: {"n0": [victim]},
        lambda pod, nom: nominations.append(nom)))
    d.error(QueuedPodInfo(pod=mk_pod("zero", 0, 4000.0)),
            SchedulingError("no node fits"))
    assert len(nominations) == 1
    assert [v.meta.name for v in nominations[0].victims] == ["neg"]


def test_zone_fit_rechecked_for_bind_preemptors():
    """A CPU-bind preemptor's nomination must survive the single-NUMA
    gate the next batch re-runs: evicting flat-fit victims that free no
    ZONE capacity is never nominated; evicting the zone-hogging bound
    victim is."""
    from koordinator_tpu.api.types import NodeResourceTopology, NUMAZone

    topo = NodeResourceTopology(zones=[
        NUMAZone(cpus_milli=8000.0, memory_mib=16384.0),
        NUMAZone(cpus_milli=8000.0, memory_mib=16384.0)])
    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 16000.0, RK.MEMORY: 32768.0},
                topology=topo)
    # both zones hogged by BOUND lower-priority pods; an UNBOUND victim
    # holds flat capacity only
    bound0 = mk_pod("bound0", 5000, 6000.0)
    bound0.required_cpu_bind = True
    bound0.allocated_numa_zone = 0
    bound1 = mk_pod("bound1", 5500, 6000.0)
    bound1.required_cpu_bind = True
    bound1.allocated_numa_zone = 1
    flat = mk_pod("flat", 4000, 4000.0)
    preemptor = mk_pod("prod", 9500, 5000.0)
    preemptor.required_cpu_bind = True
    got = find_preemption(preemptor, [node],
                          {"n0": [bound0, bound1, flat]})
    # flat eviction alone frees 4000m flat but NO zone room (zones hold
    # 6000/8000 each; 5000m bind needs 5000 free in ONE zone) — the
    # minimal set must evict a BOUND pod; reprieve keeps the more
    # important bound1, so bound0 goes (flat stays: resources fit)
    assert got is not None
    assert "bound0" in [v.meta.name for v in got.victims]
    # an unbound preemptor of the same size needs no zone: the
    # resources-only reprieve keeps the MOST important candidates
    # (bound1 5500, then flat fits too) and evicts bound0 — no zone
    # logic engages
    got2 = find_preemption(mk_pod("prod2", 9500, 5000.0), [node],
                           {"n0": [bound0, bound1, flat]})
    assert got2 is not None
    assert [v.meta.name for v in got2.victims] == ["bound0"]


def test_gpu_instance_fit_rechecked_when_devices_known():
    """With the Device CRs provided, a GPU preemptor's nomination must
    survive the per-instance gate: shared-GPU survivors block a
    full-instance preemptor even when aggregate GPU capacity fits."""
    from koordinator_tpu.api.types import Device, DeviceInfo

    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 64000.0, RK.MEMORY: 65536.0,
                             RK.GPU_CORE: 200.0,
                             RK.GPU_MEMORY: 32768.0})
    device = Device(node_name="n0", devices=[
        DeviceInfo(type="gpu", minor=m, health=True,
                   resources={RK.GPU_MEMORY: 16384.0}) for m in (0, 1)])
    # a HIGH-priority shared pod holds 50% of each instance: aggregate
    # free = 100% (one full GPU's worth) but no single instance is free
    holder = mk_pod("holder", 9600, 1000.0)
    holder.requests[RK.GPU_CORE] = 100.0
    holder.gpu_memory_ratio = 100.0
    holder.allocated_gpu_minors = [0, 1]
    # a cheap non-GPU victim exists — evicting it cannot help the GPU
    be = mk_pod("be", 5000, 1000.0)
    preemptor = mk_pod("train", 9500, 1000.0)
    preemptor.requests[RK.GPU_CORE] = 100.0
    preemptor.gpu_memory_ratio = 100.0
    got = find_preemption(preemptor, [node], {"n0": [holder, be]},
                          devices={"n0": device})
    assert got is None  # no eviction of `be` frees an instance
    # a lower-priority holder IS evictable: nomination frees instances
    holder.priority = 5500
    got2 = find_preemption(preemptor, [node], {"n0": [holder, be]},
                           devices={"n0": device})
    assert got2 is not None
    assert [v.meta.name for v in got2.victims] == ["holder"]
    # without the devices mapping the instance gate is skipped
    # (documented narrowing): with flat pressure forcing an eviction,
    # the shared-GPU blockage goes unseen and `be` is nominated anyway
    holder.priority = 9600
    tight = Node(meta=ObjectMeta(name="n0"),
                 allocatable={RK.CPU: 2500.0, RK.MEMORY: 65536.0,
                              RK.GPU_CORE: 200.0,
                              RK.GPU_MEMORY: 32768.0})
    got3 = find_preemption(preemptor, [tight], {"n0": [holder, be]})
    assert got3 is not None
    assert [v.meta.name for v in got3.victims] == ["be"]
    # the SAME scenario with devices known is (correctly) refused
    assert find_preemption(preemptor, [tight], {"n0": [holder, be]},
                           devices={"n0": device}) is None


def test_zone_instance_agreement_for_bind_gpu_preemptors():
    """A bind+GPU preemptor needs ONE zone holding both the cpus and
    the free instance (the hint-merge mirror): cpu room in zone 0 with
    the free GPU in zone 1 is refused; freeing zone 0's GPU via a
    victim is nominated. Also pins the max_zones clamp: a zone beyond
    the builder's capacity never admits."""
    from koordinator_tpu.api.types import (
        Device,
        DeviceInfo,
        NodeResourceTopology,
        NUMAZone,
    )
    from koordinator_tpu.scheduler.preemption import zone_admits

    topo = NodeResourceTopology(zones=[
        NUMAZone(cpus_milli=16000.0, memory_mib=32768.0),
        NUMAZone(cpus_milli=2000.0, memory_mib=32768.0)])
    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 18000.0, RK.MEMORY: 65536.0,
                             RK.GPU_CORE: 200.0,
                             RK.GPU_MEMORY: 32768.0},
                topology=topo)
    device = Device(node_name="n0", devices=[
        DeviceInfo(type="gpu", minor=0, health=True, numa_node=0,
                   resources={RK.GPU_MEMORY: 16384.0}),
        DeviceInfo(type="gpu", minor=1, health=True, numa_node=1,
                   resources={RK.GPU_MEMORY: 16384.0})])
    # zone-0's GPU held by a LOW-priority bind pod; zone 1 has a free
    # GPU but no cpu room for the preemptor
    holder = mk_pod("holder", 5000, 1000.0)
    holder.requests[RK.GPU_CORE] = 100.0
    holder.gpu_memory_ratio = 100.0
    holder.allocated_gpu_minors = [0]
    holder.required_cpu_bind = True
    holder.allocated_numa_zone = 0
    preemptor = mk_pod("train", 9500, 8000.0)
    preemptor.requests[RK.GPU_CORE] = 100.0
    preemptor.gpu_memory_ratio = 100.0
    preemptor.required_cpu_bind = True
    got = find_preemption(preemptor, [node], {"n0": [holder]},
                          devices={"n0": device})
    # evicting holder frees zone-0's GPU, making zone 0 satisfy BOTH
    assert got is not None
    assert [v.meta.name for v in got.victims] == ["holder"]
    # with the holder protected, no zone satisfies both -> refused
    holder.priority = 9600
    assert find_preemption(preemptor, [node], {"n0": [holder]},
                           devices={"n0": device}) is None
    # max_zones clamp: room only in zone index 4 (beyond the builder's
    # 4-zone snapshot capacity) must not admit a bind preemptor
    topo6 = NodeResourceTopology(zones=[
        NUMAZone(cpus_milli=100.0, memory_mib=128.0)] * 4 + [
        NUMAZone(cpus_milli=16000.0, memory_mib=32768.0)])
    node6 = Node(meta=ObjectMeta(name="n6"),
                 allocatable={RK.CPU: 16400.0, RK.MEMORY: 33280.0},
                 topology=topo6)
    assert not zone_admits(mk_bind_pod(), node6, [])


def mk_bind_pod():
    p = mk_pod("bind", 9500, 8000.0)
    p.required_cpu_bind = True
    return p


def test_amplified_cpu_charging_in_victim_selection():
    """Regression (ADVICE r3): on a node whose webhook published
    amplified allocatable, a CPU-bind preemptor/victim charges
    request x ratio — the host dry run agrees with the device gate, so
    a nomination is never made for a node the amplified commit would
    reject."""
    import json

    from koordinator_tpu.api.types import NodeResourceTopology, NUMAZone

    amp_ann = {"node.koordinator.sh/resource-amplification-ratio":
               json.dumps({"cpu": 2.0})}
    # amplified allocatable: 8000m raw published as 16000m; zones stay
    # RAW (a bind preemptor needs a zone to exist at all)
    node = Node(meta=ObjectMeta(name="n0", annotations=amp_ann),
                allocatable={RK.CPU: 16000.0, RK.MEMORY: 16384.0},
                topology=NodeResourceTopology(zones=[
                    NUMAZone(cpus_milli=8000.0, memory_mib=8192.0),
                    NUMAZone(cpus_milli=8000.0, memory_mib=8192.0)]))
    # bind preemptor wants 6000m -> charges 12000m amplified
    preemptor = mk_pod("prod", 9500, 6000.0)
    preemptor.required_cpu_bind = True
    # bind victim holds 3000m -> charges 6000m; shared victim 4000m raw
    bind_victim = mk_pod("bind-be", 5000, 3000.0)
    bind_victim.required_cpu_bind = True
    shared = mk_pod("shared-be", 5500, 4000.0)
    got = find_preemption(preemptor, [node],
                          {"n0": [bind_victim, shared]})
    # amplified math: need 12000 of 16000 -> must free >= 2000 amplified.
    # Reprieve keeps the MORE important candidate (shared 5500, 4000
    # charged) -> 12000+4000 fits exactly; evicting bind-be (6000
    # charged) suffices. Raw math would have kept both (6000+3000+4000
    # <= 16000) and nominated with NO victims.
    assert got is not None
    assert [v.meta.name for v in got.victims] == ["bind-be"]


def test_constraints_admit_blocks_impossible_nomination():
    """Regression: the topology gates are rechecked against the
    POST-eviction view — a node whose surviving pods still violate the
    preemptor's anti term is never nominated."""
    from koordinator_tpu.api.types import PodAffinityTerm

    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "db"}, anti=True)
    nodes = [Node(meta=ObjectMeta(name="n0", labels={"zone": "a"}),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0}),
             Node(meta=ObjectMeta(name="n1", labels={"zone": "b"}),
                  allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})]
    # n0: a cheap victim AND a higher-priority db pod that survives;
    # n1: an expensive victim but no db pod
    db = mk_pod("db", 9600, 1000.0)
    db.meta.labels["app"] = "db"
    pods_by_node = {"n0": [mk_pod("cheap", 5000, 7000.0), db],
                    "n1": [mk_pod("mid", 7000, 8000.0)]}
    preemptor = mk_pod("prod", 9500, 6000.0)
    preemptor.pod_affinity = [term]
    got = find_preemption(preemptor, nodes, pods_by_node)
    # n0 would be cheaper but the surviving db pod shares its zone
    assert got is not None and got.node_name == "n1"


def test_infra_errors_never_preempt():
    from koordinator_tpu.scheduler.errorhandler import (
        ErrorHandlerDispatcher,
        QueuedPodInfo,
        SchedulingError,
        make_preemption_post_filter,
    )

    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})
    nominations = []
    d = ErrorHandlerDispatcher()
    d.register(post=make_preemption_post_filter(
        lambda: [node], lambda: {"n0": [mk_pod("be", 5000, 8000.0)]},
        lambda pod, nom: nominations.append(nom)))
    d.error(QueuedPodInfo(pod=mk_pod("prod", 9500, 4000.0)),
            SchedulingError("etcd timeout", unschedulable=False))
    assert nominations == []


def test_quota_preemption_honors_fine_fit():
    """Quota-scoped victim selection accepts the same fine_fit hook as
    default preemption: a bind preemptor whose zone never frees is
    refused even when flat node+quota math passes."""
    from koordinator_tpu.api.types import NodeResourceTopology, NUMAZone
    from koordinator_tpu.scheduler.plugins.quota_revoke import (
        select_victims_on_node as quota_select,
    )
    from koordinator_tpu.scheduler.preemption import fine_grained_admits
    from koordinator_tpu.snapshot.builder import resource_vec as rv

    node = Node(meta=ObjectMeta(name="n0"),
                allocatable={RK.CPU: 16000.0, RK.MEMORY: 32768.0},
                topology=NodeResourceTopology(zones=[
                    NUMAZone(cpus_milli=4000.0, memory_mib=16384.0),
                    NUMAZone(cpus_milli=4000.0, memory_mib=16384.0)]))
    victim = mk_pod("v", 5000, 4000.0)
    victim.quota_name = "q"
    preemptor = mk_pod("prod", 9500, 6000.0)  # > any zone's 4000m
    preemptor.quota_name = "q"
    preemptor.required_cpu_bind = True
    fine = lambda survivors: fine_grained_admits(
        preemptor, node, None, survivors, devices_known=False)
    # runtime tight enough that the victim MUST go for flat math
    runtime = rv({RK.CPU: 7000.0, RK.MEMORY: 64000.0})
    got = quota_select(preemptor, rv(node.allocatable), [victim],
                       rv({RK.CPU: 4000.0}), runtime, fine_fit=fine)
    assert got is None  # no zone can ever hold 6000m bind cpus
    # an unbound twin under the same flat pressure evicts the victim
    preemptor.required_cpu_bind = False
    got2 = quota_select(preemptor, rv(node.allocatable), [victim],
                        rv({RK.CPU: 4000.0}), runtime, fine_fit=fine)
    assert got2 is not None
    assert [v.meta.name for v in got2.victims] == ["v"]


def test_quota_preemption_honors_preemptible_annotation():
    from koordinator_tpu.scheduler.plugins.quota_revoke import (
        select_victims_on_node as quota_select,
    )
    from koordinator_tpu.snapshot.builder import resource_vec as rv

    protected = mk_pod("keep", 5000, 6000.0, preemptible=False)
    protected.quota_name = "q"
    preemptor = mk_pod("prod", 9500, 4000.0)
    preemptor.quota_name = "q"
    got = quota_select(preemptor,
                       rv({RK.CPU: 8000.0, RK.MEMORY: 16384.0}),
                       [protected],
                       rv({RK.CPU: 6000.0}),
                       rv({RK.CPU: 64000.0, RK.MEMORY: 64000.0}))
    assert got is None


def test_topology_blocked_preemption_evicts_the_blocker():
    """Regression: a preemptor blocked SOLELY by anti-affinity against a
    lower-priority preemptible pod evicts that pod (upstream reruns the
    Filter inside victim selection)."""
    from koordinator_tpu.api.types import PodAffinityTerm

    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "be"}, anti=True)
    nodes = [Node(meta=ObjectMeta(name="n0", labels={"zone": "a"}),
                  allocatable={RK.CPU: 64000.0, RK.MEMORY: 65536.0})]
    blocker = mk_pod("be-0", 5000, 1000.0)
    blocker.meta.labels["app"] = "be"
    preemptor = mk_pod("prod", 9500, 1000.0)  # resources trivially fit
    preemptor.pod_affinity = [term]
    got = find_preemption(preemptor, nodes, {"n0": [blocker]})
    assert got is not None and got.node_name == "n0"
    assert [v.meta.name for v in got.victims] == ["be-0"]
