"""The extended metricsadvisor collector set: pagecache, kidled cold
memory, host applications, node storage, accelerator devices.

Hermetic over FakeHost (the reference's NewFileTestUtil strategy,
SURVEY.md 4). Reference behaviors asserted here:
 - pagecache: MemTotal-MemFree node usage + raw pod cgroup usage
   (collectors/pagecache/page_cache_collector.go, meminfo.go:107-110)
 - coldmemory: kidled gating + hot-page usage = with_cache - cold
   (collectors/coldmemoryresource/cold_page_kidled.go, cold_page.go:23-28)
 - hostapplication: NodeSLO-driven cgroup sampling with first-sample skip
   (collectors/hostapplication/host_app_collector.go:87-140)
 - nodestorageinfo: disk/partition maps in KV + io counter-delta rates
   (collectors/nodestorageinfo/node_info_collector.go:65-88)
 - device: per-minor node series + pid->pod attribution
   (metricsadvisor/devices/gpu/collector_gpu_linux.go)
"""

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import PriorityClass, QoSClass, ResourceKind
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet.metricsadvisor import (
    ColdPageCollector,
    DeviceCollector,
    DeviceUsage,
    HostAppCollector,
    NodeStorageInfoCollector,
    PageCacheCollector,
    default_advisor,
)
from koordinator_tpu.koordlet.statesinformer import (
    CollectPolicy,
    NodeMetricReporter,
    PodMeta,
    StatesInformer,
    host_app_cgroup_dir,
)
from koordinator_tpu.koordlet.testing import FakeHost


@pytest.fixture
def host(tmp_path):
    return FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)


def _make_pod(uid, qos="LS"):
    return PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(uid=uid, name=uid, namespace="default"),
        requests={ResourceKind.CPU: 1000.0, ResourceKind.MEMORY: 1024.0},
        qos_label=qos))


@pytest.fixture
def informer():
    inf = StatesInformer()
    inf.set_node(api.Node(meta=api.ObjectMeta(name="node-1")))
    return inf


# --- pagecache ---------------------------------------------------------------

def test_pagecache_node_and_pod(host, informer):
    cache = mc.MetricCache()
    pod = _make_pod("pod-a")
    host.make_cgroup(pod.cgroup_dir)
    # usage 3GiB of which 1GiB inactive file: pagecache series keeps the raw
    # value, unlike POD_MEMORY_USAGE which subtracts it
    host.set_cgroup_memory(pod.cgroup_dir, 3 << 30, inactive_file=1 << 30)
    informer.set_pods([pod])
    host.set_meminfo(available=12 << 30)

    PageCacheCollector(host, cache, informer).collect(1.0)
    # node: MemTotal - MemFree (FakeHost seeds MemFree = available)
    assert cache.query(mc.NODE_MEMORY_USAGE_WITH_PAGE_CACHE, 0, 2,
                       agg="latest") == float(4 << 30)
    assert cache.query(mc.POD_MEMORY_USAGE_WITH_PAGE_CACHE, 0, 2,
                       {"pod_uid": "pod-a"}, "latest") == float(3 << 30)


# --- kidled cold memory ------------------------------------------------------

def test_coldpage_inert_without_kidled(host, informer):
    cache = mc.MetricCache()
    ColdPageCollector(host, cache, informer).collect(1.0)
    assert cache.query(mc.COLD_PAGE_BYTES, 0, 2, agg="latest") is None


def test_coldpage_node_pod_hostapp(host, informer):
    cache = mc.MetricCache()
    host.enable_kidled()
    host.set_meminfo(available=12 << 30)   # with_page_cache = 4GiB
    host.set_cold_pages("", 1 << 30)
    pod = _make_pod("pod-a")
    host.make_cgroup(pod.cgroup_dir)
    host.set_cold_pages(pod.cgroup_dir, 256 << 20)
    informer.set_pods([pod])
    app = api.HostApplication(name="nginx", qos=QoSClass.LS)
    host.make_cgroup(host_app_cgroup_dir(app))
    host.set_cold_pages(host_app_cgroup_dir(app), 64 << 20)
    informer.set_node_slo(api.NodeSLO(host_applications=[app]))

    c = ColdPageCollector(host, cache, informer)
    c.collect(1.0)
    # arming wrote the scan period (kidled_start defaults)
    assert host.read(host.path("sys", "kernel", "mm", "kidled",
                               "scan_period_in_seconds")) == "5"
    assert cache.query(mc.COLD_PAGE_BYTES, 0, 2,
                       agg="latest") == float(1 << 30)
    # hot usage = 4GiB with-cache - 1GiB cold = 3GiB
    assert cache.query(mc.NODE_MEMORY_WITH_HOT_PAGE_USAGE, 0, 2,
                       agg="latest") == float(3 << 30)
    assert cache.query(mc.COLD_PAGE_BYTES, 0, 2,
                       {"pod_uid": "pod-a"}, "latest") == float(256 << 20)
    assert cache.query(mc.COLD_PAGE_BYTES, 0, 2,
                       {"app": "nginx"}, "latest") == float(64 << 20)


# --- host applications -------------------------------------------------------

def test_host_app_cgroup_dir_derivation():
    assert host_app_cgroup_dir(
        api.HostApplication(name="a", qos=QoSClass.LS)) \
        == "host-latency-sensitive/a"
    assert host_app_cgroup_dir(
        api.HostApplication(name="b", qos=QoSClass.BE)) == "host-best-effort/b"
    assert host_app_cgroup_dir(api.HostApplication(name="c")) == "c"
    assert host_app_cgroup_dir(
        api.HostApplication(name="d", cgroup_dir="kubepods/burstable/x")) \
        == "kubepods/burstable/x"


def test_host_app_collector_cpu_delta_and_memory(host, informer):
    cache = mc.MetricCache()
    app = api.HostApplication(name="nginx", qos=QoSClass.LS,
                              priority_class=PriorityClass.PROD)
    d = host_app_cgroup_dir(app)
    host.make_cgroup(d)
    informer.set_node_slo(api.NodeSLO(host_applications=[app]))
    c = HostAppCollector(host, cache, informer)

    c.collect(0.0)  # first sample: cpu skipped, memory recorded
    assert cache.query(mc.HOST_APP_CPU_USAGE, 0, 1,
                       {"app": "nginx"}, "latest") is None
    # 2 cores for 10s
    host.set_cgroup_cpu_ns(d, 20_000_000_000)
    host.set_cgroup_memory(d, 2 << 30, inactive_file=1 << 30)
    c.collect(10.0)
    assert cache.query(mc.HOST_APP_CPU_USAGE, 0, 11, {"app": "nginx"},
                       "latest") == pytest.approx(2.0)
    # working set subtracts inactive file
    assert cache.query(mc.HOST_APP_MEMORY_USAGE, 0, 11, {"app": "nginx"},
                       "latest") == float(1 << 30)


def test_host_app_metrics_in_nodemetric_report(host, informer):
    cache = mc.MetricCache()
    app = api.HostApplication(name="nginx", qos=QoSClass.LS,
                              priority_class=PriorityClass.PROD)
    d = host_app_cgroup_dir(app)
    host.make_cgroup(d)
    informer.set_node_slo(api.NodeSLO(host_applications=[app]))
    adv = default_advisor(host, cache, informer)
    host.set_cgroup_memory(d, 1 << 30)
    adv.collect_once(now=0.0)
    host.advance_cpu(busy_ticks=4000, idle_ticks=4000)
    host.set_cgroup_cpu_ns(d, 10_000_000_000)
    adv.collect_once(now=10.0)

    nm = NodeMetricReporter(informer, cache, CollectPolicy()).collect(now=10.0)
    assert nm is not None
    assert len(nm.host_app_metric) == 1
    ham = nm.host_app_metric[0]
    assert ham.name == "nginx"
    assert ham.priority_class is PriorityClass.PROD
    assert ham.qos is QoSClass.LS
    assert ham.usage[ResourceKind.CPU] == pytest.approx(1000.0)  # milli
    assert ham.usage[ResourceKind.MEMORY] == pytest.approx(1024.0)  # MiB


# --- node storage ------------------------------------------------------------

def test_storage_info_kv_and_io_rates(host):
    cache = mc.MetricCache()
    host.add_disk("sda")
    host.set_diskstats([
        {"device": "sda", "read_sectors": 0, "write_sectors": 0,
         "io_ticks_ms": 0},
        {"device": "sda1", "read_sectors": 0, "write_sectors": 0,
         "io_ticks_ms": 0},
    ])
    c = NodeStorageInfoCollector(host, cache)
    c.collect(0.0)
    info = cache.get_kv(mc.NODE_LOCAL_STORAGE_KEY)
    assert info["disks"] == ["sda"]
    assert info["partition_disk"] == {"sda1": "sda"}

    # 10s later: 2048 sectors read (1MiB), 4096 written (2MiB), 5000ms busy
    host.set_diskstats([
        {"device": "sda", "read_sectors": 2048, "write_sectors": 4096,
         "io_ticks_ms": 5000},
        {"device": "sda1", "read_sectors": 2048, "write_sectors": 4096,
         "io_ticks_ms": 5000},
    ])
    c.collect(10.0)
    labels = {"device": "sda"}
    assert cache.query(mc.NODE_DISK_IO_UTIL, 0, 11, labels,
                       "latest") == pytest.approx(50.0)
    assert cache.query(mc.NODE_DISK_READ_BPS, 0, 11, labels,
                       "latest") == pytest.approx((1 << 20) / 10.0)
    assert cache.query(mc.NODE_DISK_WRITE_BPS, 0, 11, labels,
                       "latest") == pytest.approx((2 << 20) / 10.0)
    # partitions produce no per-device series
    assert cache.query(mc.NODE_DISK_IO_UTIL, 0, 11, {"device": "sda1"},
                       "latest") is None

    # counter reset (device re-added): clamp at 0, never negative
    host.set_diskstats([
        {"device": "sda", "read_sectors": 0, "write_sectors": 0,
         "io_ticks_ms": 0},
    ])
    c.collect(20.0)
    assert cache.query(mc.NODE_DISK_IO_UTIL, 15, 21, labels, "latest") == 0.0
    assert cache.query(mc.NODE_DISK_READ_BPS, 15, 21, labels, "latest") == 0.0


# --- devices -----------------------------------------------------------------

def test_device_collector_node_and_pod_attribution(host, informer):
    cache = mc.MetricCache()
    pod_a, pod_b = _make_pod("pod-a"), _make_pod("pod-b")
    # processes live in container LEAF cgroups under the pod dir — the pod
    # cgroup itself is an interior node with empty cgroup.procs (v2
    # no-internal-process rule); attribution must walk the subtree
    for p, pids in ((pod_a, [100, 101]), (pod_b, [200])):
        host.make_cgroup(p.cgroup_dir)
        host.set_cgroup_procs(p.cgroup_dir, [])
        ctr = p.cgroup_dir + "/ctr0"
        host.make_cgroup(ctr)
        host.set_cgroup_procs(ctr, pids)
    informer.set_pods([pod_a, pod_b])

    def reader():
        return [
            DeviceUsage(minor=0, core_usage=80.0, memory_used=8 << 30,
                        memory_total=16 << 30,
                        procs={100: (50.0, 4 << 30), 101: (20.0, 2 << 30),
                               200: (10.0, 2 << 30),
                               999: (77.0, 1 << 30)}),  # unknown pid dropped
            DeviceUsage(minor=1, core_usage=5.0, memory_used=1 << 30,
                        procs={200: (5.0, 1 << 30)}),
        ]

    DeviceCollector(host, cache, informer, reader).collect(1.0)
    assert cache.query(mc.GPU_CORE_USAGE, 0, 2, {"minor": "0"},
                       "latest") == 80.0
    assert cache.query(mc.GPU_MEMORY_USED, 0, 2, {"minor": "1"},
                       "latest") == float(1 << 30)
    assert cache.query(mc.GPU_MEMORY_TOTAL, 0, 2, {"minor": "0"},
                       "latest") == float(16 << 30)
    # minor 1 reported no capacity -> no total series
    assert cache.query(mc.GPU_MEMORY_TOTAL, 0, 2, {"minor": "1"},
                       "latest") is None
    # pod-a on minor 0: 50+20 core, 6GiB
    assert cache.query(mc.POD_GPU_CORE_USAGE, 0, 2,
                       {"pod_uid": "pod-a", "minor": "0"}, "latest") == 70.0
    assert cache.query(mc.POD_GPU_MEMORY_USED, 0, 2,
                       {"pod_uid": "pod-a", "minor": "0"},
                       "latest") == float(6 << 30)
    # pod-b appears on both minors
    assert cache.query(mc.POD_GPU_CORE_USAGE, 0, 2,
                       {"pod_uid": "pod-b", "minor": "1"}, "latest") == 5.0
    # unknown pid attributed nowhere
    assert cache.query_all(mc.POD_GPU_CORE_USAGE, 0, 2, "count") \
        .keys().__len__() == 3


# --- podthrottled + nodeinfo -------------------------------------------


def test_pod_throttled_ratio_delta(host, informer):
    cache = mc.MetricCache()
    pod = _make_pod("pod-a")
    host.make_cgroup(pod.cgroup_dir)
    informer.set_pods([pod])
    from koordinator_tpu.koordlet.metricsadvisor import PodThrottledCollector
    c = PodThrottledCollector(host, cache, informer)

    host.set_cgroup_throttled(pod.cgroup_dir, nr_periods=100, nr_throttled=10)
    c.collect(0.0)  # baseline primed
    assert cache.query(mc.POD_CPU_THROTTLED_RATIO, 0, 1,
                       {"pod_uid": "pod-a"}, "latest") is None
    # 100 more periods, 25 of them throttled
    host.set_cgroup_throttled(pod.cgroup_dir, nr_periods=200, nr_throttled=35)
    c.collect(10.0)
    assert cache.query(mc.POD_CPU_THROTTLED_RATIO, 0, 11,
                       {"pod_uid": "pod-a"}, "latest") \
        == pytest.approx(0.25)
    # pod gone -> tracker pruned
    informer.set_pods([])
    c.collect(20.0)
    assert c._prev == {}


def test_node_info_kv(host):
    cache = mc.MetricCache()
    host.set_cpu_model("AMD EPYC 7B12")
    from koordinator_tpu.koordlet.metricsadvisor import NodeInfoCollector
    NodeInfoCollector(host, cache).collect(0.0)
    info = cache.get_kv(mc.NODE_CPU_INFO_KEY)
    assert info["model"] == "AMD EPYC 7B12"
    assert info["cpus"] == 8 and info["cores"] == 4
    assert info["numa_nodes"] == 1


# --- collector isolation -------------------------------------------------


def test_raising_collector_does_not_kill_the_loop(host, informer):
    """One collector throwing (driver reset, vanished file race) must not
    stop the others — the reference runs collectors in separate goroutines
    (metrics_advisor.go:72-102)."""
    cache = mc.MetricCache()

    class Boom:
        name = "boom"

        def collect(self, now):
            raise RuntimeError("device fell off the bus")

    from koordinator_tpu.koordlet.metricsadvisor import (
        Advisor,
        NodeResourceCollector,
    )
    adv = Advisor([Boom(), NodeResourceCollector(host, cache)])
    adv.collect_once(now=0.0)
    host.advance_cpu(busy_ticks=4000, idle_ticks=4000)
    adv.collect_once(now=10.0)
    assert cache.query(mc.NODE_CPU_USAGE, 0, 11, agg="latest") is not None
    assert isinstance(adv.last_errors["boom"], RuntimeError)
