"""bench_configs smoke: the BASELINE config measurements stay runnable
(the full sweep runs on real hardware; here the cheap configs prove the
harness on the CPU test platform)."""

import json

import bench_configs


def test_config_1_emits_json(capsys):
    bench_configs.config_1_spark()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "baseline_cfg1_spark_32x10"
    assert out["placed"] == 32


def test_config_5_descheduler_emits_json(capsys):
    bench_configs.config_5_descheduler()
    lines = capsys.readouterr().out.strip().splitlines()
    out = json.loads(lines[-2])
    assert out["metric"] == "baseline_cfg5_descheduler_10k"
    assert out["nodes"] == 10_000
    assert out["evictions_planned"] > 0
    capped = json.loads(lines[-1])
    assert capped["metric"] == "baseline_cfg5_descheduler_10k_capped"
    # the ns cap binds: 2000 of the ~9k uncapped evictions survive
    assert 0 < capped["evictions_planned"] <= 2000
    assert capped["evictions_planned"] < out["evictions_planned"]
