"""Node webhooks, ConfigMap validation, and the scheduler error-handler
chain (reference: pkg/webhook/node, pkg/webhook/cm/plugins/sloconfig,
frameworkext/errorhandler_dispatcher.go + eventhandlers/
reservation_handler.go)."""

import json

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    ANNOTATION_NODE_AMPLIFICATION_RATIOS,
    ANNOTATION_NODE_RAW_ALLOCATABLE,
    ResourceKind as RK,
)
from koordinator_tpu.scheduler.errorhandler import (
    ErrorHandlerDispatcher,
    QueuedPodInfo,
    SchedulingError,
    dispatch_batch_errors,
    make_reservation_error_filter,
    reserve_pod_for,
    set_reservation_scheduled,
    set_reservation_unschedulable,
)
from koordinator_tpu.webhook import (
    NodeMutator,
    validate_node,
    validate_slo_configmap,
)


# --- node mutating (resource amplification) ---------------------------------

def mk_node(cpu=32000.0, mem=65536.0, anns=None):
    return api.Node(meta=api.ObjectMeta(name="n1", annotations=anns or {}),
                    allocatable={RK.CPU: cpu, RK.MEMORY: mem})


def test_amplification_stashes_raw_and_scales():
    node = mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 2.0}'})
    assert NodeMutator().admit(node)
    assert node.allocatable[RK.CPU] == 64000.0
    assert node.allocatable[RK.MEMORY] == 65536.0  # no ratio -> untouched
    raw = json.loads(node.meta.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE])
    assert raw["cpu"] == 32000.0


def test_amplification_is_idempotent_via_raw_stash():
    node = mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 2.0}'})
    m = NodeMutator()
    m.admit(node)
    # a second admission (e.g. status update) must NOT compound 2x again
    old = api.Node(meta=api.ObjectMeta(name="n1"),
                   allocatable=dict(node.allocatable))
    m.admit(node, old_node=old)
    assert node.allocatable[RK.CPU] == 64000.0


def test_amplification_restashes_on_kubelet_change():
    node = mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 2.0}'})
    m = NodeMutator()
    m.admit(node)
    # kubelet re-reports allocatable (reserved resources changed)
    old = api.Node(meta=api.ObjectMeta(name="n1"),
                   allocatable=dict(node.allocatable))
    node.allocatable[RK.CPU] = 16000.0
    m.admit(node, old_node=old)
    assert node.allocatable[RK.CPU] == 32000.0  # 16000 * 2 from NEW raw


def test_clearing_ratio_restores_raw_and_drops_stash():
    node = mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 2.0}'})
    m = NodeMutator()
    m.admit(node)
    assert node.allocatable[RK.CPU] == 64000.0
    del node.meta.annotations[ANNOTATION_NODE_AMPLIFICATION_RATIOS]
    m.admit(node)
    assert ANNOTATION_NODE_RAW_ALLOCATABLE not in node.meta.annotations
    # un-amplified: the scheduler stops seeing 2x capacity
    assert node.allocatable[RK.CPU] == 32000.0


def test_malformed_annotation_rejects_not_crashes():
    from koordinator_tpu.webhook.node_webhook import AdmissionError
    m = NodeMutator()
    for bad in ('not json', '{"bogus": 2.0}', '{"cpu": "abc"}'):
        node = mk_node(anns={ANNOTATION_NODE_AMPLIFICATION_RATIOS: bad})
        with pytest.raises(AdmissionError):
            m.admit(node)


def test_ratio_exactly_one_still_reports_stash_write():
    node = mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 1.0}'})
    # the stash annotation IS part of the patch even though no value scales
    assert NodeMutator().admit(node) is True
    assert ANNOTATION_NODE_RAW_ALLOCATABLE in node.meta.annotations


def test_validate_node_rejects_bad_ratios():
    ok, errs = validate_node(mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 0.5}'}))
    assert not ok and "must be >= 1" in errs[0]
    ok, _ = validate_node(mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: 'not json'}))
    assert not ok
    ok, _ = validate_node(mk_node(anns={
        ANNOTATION_NODE_AMPLIFICATION_RATIOS: '{"cpu": 1.5}'}))
    assert ok


# --- ConfigMap validation ----------------------------------------------------

def test_valid_configmap_passes():
    ok, errs = validate_slo_configmap({
        "colocation-config": json.dumps({
            "enable": True, "cpuReclaimThresholdPercent": 65,
            "nodeConfigs": [{"nodeSelector": {"pool": "batch"},
                             "cpuReclaimThresholdPercent": 70}]}),
        "resource-threshold-config": json.dumps({
            "enable": True, "cpuSuppressThresholdPercent": 65,
            "cpuEvictBEUsageThresholdPercent": 80}),
        "cpu-burst-config": json.dumps({
            "policy": "auto", "cpuBurstPercent": 1000}),
        "resource-qos-config": json.dumps({
            "LS": {"groupIdentity": 2}, "BE": {"groupIdentity": -1}}),
        "system-config": json.dumps({"watermarkScaleFactor": 150}),
    })
    assert ok, errs


def test_configmap_rejects_out_of_range_and_unknown():
    ok, errs = validate_slo_configmap({
        "colocation-config": json.dumps({
            "cpuReclaimThresholdPercent": 150}),
    })
    assert not ok and any("out of [0,100]" in e for e in errs)

    ok, errs = validate_slo_configmap({"no-such-config": "{}"})
    assert not ok and "unknown config key" in errs[0]

    ok, errs = validate_slo_configmap({
        "cpu-burst-config": json.dumps({"policy": "warp-speed"})})
    assert not ok and any("unknown policy" in e for e in errs)

    ok, errs = validate_slo_configmap({
        "resource-qos-config": json.dumps({"LS": {"groupIdentity": 7}})})
    assert not ok and any("out of [-1,2]" in e for e in errs)

    ok, errs = validate_slo_configmap({
        "colocation-config": "{{{not json"})
    assert not ok and any("unparseable" in e for e in errs)


def test_configmap_rejects_empty_override_selector():
    ok, errs = validate_slo_configmap({
        "resource-threshold-config": json.dumps({
            "nodeStrategies": [{"cpuSuppressThresholdPercent": 50}]})})
    assert not ok and any("empty node selector" in e for e in errs)


# --- error-handler chain -----------------------------------------------------

def test_dispatcher_pre_claims_default_post_order():
    calls = []
    d = ErrorHandlerDispatcher(
        default_handler=lambda p, e: calls.append("default"))
    d.register(pre=lambda p, e: (calls.append("pre1"), False)[1])
    d.register(pre=lambda p, e: (calls.append("pre2-claim"), True)[1])
    d.register(post=lambda p, e: (calls.append("post"), True)[1])
    d.error(QueuedPodInfo(pod=api.Pod()), SchedulingError("x"))
    # pre2 claimed -> default skipped; post still runs (defer semantics)
    assert calls == ["pre1", "pre2-claim", "post"]

    calls.clear()
    d2 = ErrorHandlerDispatcher(
        default_handler=lambda p, e: calls.append("default"))
    d2.register(pre=lambda p, e: False)
    d2.register(post=lambda p, e: (calls.append("post"), True)[1])
    d2.error(QueuedPodInfo(pod=api.Pod()), SchedulingError("x"))
    assert calls == ["default", "post"]


def test_reservation_filter_writes_unschedulable_and_requeues():
    r = api.Reservation(meta=api.ObjectMeta(name="rsv-a", uid="u1"),
                        requests={RK.CPU: 4000.0})
    requeued = []
    filt = make_reservation_error_filter(
        get_reservation={"rsv-a": r}.get, requeue=requeued.append,
        clock=lambda: 100.0)
    d = ErrorHandlerDispatcher(default_handler=lambda p, e: pytest.fail(
        "default must not run for a claimed reserve pod"))
    d.register(pre=filt)

    pod = reserve_pod_for(r)
    d.error(QueuedPodInfo(pod=pod), SchedulingError("no fit"))
    assert requeued == [r]
    cond = r.conditions[0]
    assert (cond.type, cond.status, cond.reason) == \
        ("Scheduled", "False", api.REASON_RESERVATION_UNSCHEDULABLE)
    assert "no fit" in cond.message and cond.last_probe_time == 100.0

    # second failure refreshes probe time, no duplicate condition
    filt2 = make_reservation_error_filter(
        get_reservation={"rsv-a": r}.get, clock=lambda: 200.0)
    filt2(QueuedPodInfo(pod=pod), SchedulingError("still no fit"))
    assert len(r.conditions) == 1
    assert r.conditions[0].last_probe_time == 200.0
    assert r.conditions[0].last_transition_time == 100.0


def test_reservation_filter_aborts_when_already_bound():
    r = api.Reservation(meta=api.ObjectMeta(name="rsv-a"), node_name="n3")
    requeued = []
    filt = make_reservation_error_filter(
        get_reservation={"rsv-a": r}.get, requeue=requeued.append)
    claimed = filt(QueuedPodInfo(pod=reserve_pod_for(r)),
                   SchedulingError("stale"))
    assert claimed and not requeued and not r.conditions


def test_reservation_scheduled_transitions_condition():
    r = api.Reservation(meta=api.ObjectMeta(name="rsv-a"))
    set_reservation_unschedulable(r, "no fit", now=1.0)
    set_reservation_scheduled(r, "n2", now=2.0)
    cond = r.conditions[0]
    assert cond.status == "True" and cond.last_transition_time == 2.0
    assert r.node_name == "n2"
    # repeated success bumps probe only
    set_reservation_scheduled(r, "n2", now=3.0)
    assert cond.last_transition_time == 2.0 and cond.last_probe_time == 3.0


def test_dispatch_batch_errors_only_unplaced_valid_rows():
    pods = [api.Pod(meta=api.ObjectMeta(name=f"p{i}")) for i in range(3)]
    seen = []
    d = ErrorHandlerDispatcher(
        default_handler=lambda pi, e: seen.append(pi.pod.meta.name))
    assignment = np.array([2, -1, -1, -1])   # row 3 is padding
    valid = np.array([True, True, False, True])
    n = dispatch_batch_errors(d, assignment, valid, pods)
    assert n == 1 and seen == ["p1"]


def test_service_schedule_feeds_error_chain():
    """End to end: an unplaceable pod in a real batch reaches a registered
    error filter through SchedulerService.schedule(typed_pods=...)."""
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.snapshot import SnapshotBuilder

    b = SnapshotBuilder(max_nodes=2)
    node = api.Node(meta=api.ObjectMeta(name="n0"),
                    allocatable={RK.CPU: 1000.0, RK.MEMORY: 1024.0})
    b.add_node(node)
    b.set_node_metric(api.NodeMetric(node_name="n0", update_time=1e9,
                                     node_usage={RK.CPU: 0.0,
                                                 RK.MEMORY: 0.0}))
    snap, ctx = b.build(now=1e9)
    svc = SchedulerService()
    svc.publish(snap)
    failed = []
    svc.error_dispatcher.register(
        pre=lambda pi, e: (failed.append(pi.pod.meta.name), True)[1])
    ok_pod = api.Pod(meta=api.ObjectMeta(name="fits"),
                     requests={RK.CPU: 100.0, RK.MEMORY: 64.0})
    huge = api.Pod(meta=api.ObjectMeta(name="huge"),
                   requests={RK.CPU: 10_000_000.0, RK.MEMORY: 1024.0})
    res = svc.schedule(b.build_pod_batch([ok_pod, huge], ctx),
                       typed_pods=[ok_pod, huge])
    a = np.asarray(res.assignment)
    assert a[0] >= 0 and a[1] < 0
    assert failed == ["huge"]
