"""Feature gates, typed config args, and the frameworkext seam (monitor,
debug tables, service endpoints, scheduler service) — SURVEY.md 2.1/2.7."""

import json
import urllib.request

import numpy as np
import pytest

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.features import (
    DEFAULT_FEATURE_GATE,
    FeatureGate,
    FeatureSpec,
)
from koordinator_tpu.scheduler.config_args import (
    DeviceShareArgs,
    LoadAwareSchedulingArgs,
    MostAllocated,
    NodeNUMAResourceArgs,
    SchedulerProfile,
)
from koordinator_tpu.scheduler.frameworkext import (
    DebugFlags,
    SchedulerMonitor,
    SchedulerService,
    ServiceRegistry,
    ServicesServer,
    debug_score_table,
)
from koordinator_tpu.utils import synthetic


# --- feature gates ----------------------------------------------------------


def test_feature_gate_defaults_and_parse():
    gate = FeatureGate({"A": FeatureSpec(default=True),
                        "B": FeatureSpec(default=False),
                        "L": FeatureSpec(default=True,
                                         lock_to_default=True)})
    assert gate.enabled("A") and not gate.enabled("B")
    gate.parse("A=false, B=true")
    assert not gate.enabled("A") and gate.enabled("B")
    with pytest.raises(KeyError):
        gate.enabled("nope")
    with pytest.raises(ValueError):
        gate.parse("A=maybe")
    with pytest.raises(ValueError):
        gate.set("L", False)


def test_default_gate_catalog():
    assert DEFAULT_FEATURE_GATE.enabled("BECPUSuppress")
    assert not DEFAULT_FEATURE_GATE.enabled("Libpfm4")
    assert not DEFAULT_FEATURE_GATE.enabled("ResizePod")
    assert len(list(DEFAULT_FEATURE_GATE.known())) >= 35


# --- typed args -------------------------------------------------------------


def test_args_defaults_validate_clean():
    assert SchedulerProfile().validate() == []


def test_args_validation_rejects_bad_values():
    bad = SchedulerProfile(
        load_aware=LoadAwareSchedulingArgs(
            usage_thresholds={RK.CPU: 150.0},
            filter_agg_type="p42"),
        numa=NodeNUMAResourceArgs(default_cpu_bind_policy="Bogus"),
        device_share=DeviceShareArgs(scoring_strategy="Weird"))
    errs = bad.validate()
    assert len(errs) == 4
    with pytest.raises(ValueError):
        bad.schedule_options()


def test_profile_lowers_to_schedule_options():
    prof = SchedulerProfile(
        numa=NodeNUMAResourceArgs(numa_scoring_strategy=MostAllocated),
        device_share=DeviceShareArgs(scoring_strategy=MostAllocated))
    opts = prof.schedule_options()
    assert opts == {"numa_strategy": "most", "device_strategy": "most"}
    cfg = prof.load_aware_config()
    assert float(cfg.usage_thresholds[int(RK.CPU)]) == 65.0


# --- monitor ----------------------------------------------------------------


def test_monitor_flags_slow_cycles():
    mon = SchedulerMonitor(timeout_seconds=1.0)
    t = mon.start_cycle(now=0.0)
    assert mon.overdue(now=2.5) == [t]
    assert mon.complete_cycle(t, now=3.0) == 3.0
    assert mon.timeouts == 1
    t2 = mon.start_cycle(now=10.0)
    mon.complete_cycle(t2, now=10.2)
    assert mon.timeouts == 1 and mon.overdue(now=10.5) == []


# --- scheduler service + endpoints ------------------------------------------


def test_scheduler_service_end_to_end():
    service = SchedulerService(num_rounds=2, k_choices=4)
    snap = synthetic.synthetic_cluster(32, num_quotas=4)
    service.publish(snap)
    pods = synthetic.synthetic_pods(64, num_quotas=4)
    res = service.schedule(pods)
    placed = int((np.asarray(res.assignment) >= 0).sum())
    assert placed > 0
    assert service.summary()["podsPlaced"] == placed
    assert service.store.version == 2  # publish + post-commit update
    # second batch schedules against the committed state
    res2 = service.schedule(synthetic.synthetic_pods(64, seed=9,
                                                     num_quotas=4))
    assert service.batches == 2


def test_amplification_derived_from_scheduled_snapshot():
    """Regression (ADVICE r3): the amplified-CPU auto-detection keys
    off the snapshot the batch actually READS — writers that bypass
    service.publish() and put snapshots straight into the shared store
    (SnapshotSyncer._rebuild, embedded compositions) still flip the
    gate on."""
    import numpy as np

    service = SchedulerService(num_rounds=1, k_choices=4)
    snap = synthetic.synthetic_cluster(16)
    amp = np.array(snap.nodes.cpu_amplification)
    amp[3] = 1.5
    snap_amp = snap.replace(nodes=snap.nodes.replace(
        cpu_amplification=amp))
    # bypass service.publish on purpose
    service.store.publish(snap_amp)
    service.schedule(synthetic.synthetic_pods(8))
    assert service.schedule_kwargs["enable_amplification"] is True
    # a ratio-1 snapshot published the same way turns it back off
    service.store.publish(synthetic.synthetic_cluster(16, seed=3))
    service.schedule(synthetic.synthetic_pods(8, seed=1))
    assert service.schedule_kwargs["enable_amplification"] is False
    # an explicit constructor kwarg always wins
    svc2 = SchedulerService(num_rounds=1, k_choices=4,
                            enable_amplification=False)
    svc2.store.publish(snap_amp)
    svc2.schedule(synthetic.synthetic_pods(8))
    assert svc2.schedule_kwargs["enable_amplification"] is False


def test_debug_score_table_renders():
    snap = synthetic.synthetic_cluster(8)
    pods = synthetic.synthetic_pods(3)
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    table = debug_score_table(snap, pods, LoadAwareConfig.make(), top_n=3,
                              pod_names=["a", "b", "c"])
    lines = table.splitlines()
    assert lines[0].startswith("pod")
    assert len(lines) == 5 and "node" in lines[2]


def test_services_http_endpoints():
    registry = ServiceRegistry()
    registry.register("gang", lambda: {"gangs": 3})
    flags = DebugFlags()
    server = ServicesServer(registry, flags)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/apis/v1/plugins") as r:
            assert json.load(r)["plugins"] == ["gang"]
        with urllib.request.urlopen(f"{base}/apis/v1/plugins/gang") as r:
            assert json.load(r) == {"gangs": 3}
        req = urllib.request.Request(f"{base}/debug/flags/s", data=b"5",
                                     method="PUT")
        with urllib.request.urlopen(req) as r:
            assert json.load(r)["scoreTopN"] == 5
        assert flags.score_top_n == 5
    finally:
        server.close()


def test_debug_filter_table_and_http_toggle():
    """The /debug/flags/f counterpart (DebugFiltersSetter): per-gate
    rejection counts per pod, toggled over HTTP."""
    from koordinator_tpu.scheduler.frameworkext import debug_filter_table
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig

    snap = synthetic.synthetic_cluster(8)
    pods = synthetic.synthetic_pods(3)
    table = debug_filter_table(snap, pods, LoadAwareConfig.make(),
                               pod_names=["a", "b", "c"])
    lines = table.splitlines()
    assert lines[0].startswith("pod") and len(lines) == 5
    assert all("fit:" in ln for ln in lines[2:])
    registry = ServiceRegistry()
    flags = DebugFlags()
    server = ServicesServer(registry, flags)
    try:
        base = f"http://127.0.0.1:{server.port}"
        req = urllib.request.Request(f"{base}/debug/flags/f", data=b"true",
                                     method="PUT")
        with urllib.request.urlopen(req) as r:
            assert json.load(r)["filterDump"] is True
        assert flags.filter_dump is True
    finally:
        server.close()


def test_debug_filter_table_covers_topology_gates():
    """The filter table mirrors the taint/spread/affinity gates too."""
    from koordinator_tpu.api.types import (
        Node, NodeMetric, ObjectMeta, Pod, PodAffinityTerm, Taint,
        TopologySpreadConstraint,
    )
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.scheduler.frameworkext import debug_filter_table
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.snapshot import SnapshotBuilder

    b = SnapshotBuilder(max_nodes=3)
    for i in range(3):
        b.add_node(Node(
            meta=ObjectMeta(name=f"n{i}", labels={"zone": f"z{i % 2}"}),
            taints=[Taint(key="x", effect="NoSchedule")] if i == 2 else [],
            allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=1e9,
                                     node_usage={}))
    b.add_running_pod(Pod(meta=ObjectMeta(name="r", namespace="d",
                                          labels={"app": "x"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n0"))
    snap, ctx = b.build(now=1e9)
    pods = [Pod(meta=ObjectMeta(name="p", namespace="d",
                                labels={"app": "x"}),
                priority=9000, requests={RK.CPU: 100.0},
                spread_constraints=[TopologySpreadConstraint(
                    topology_key="zone", label_selector={"app": "x"})],
                pod_affinity=[PodAffinityTerm(
                    topology_key="zone", label_selector={"app": "x"},
                    anti=True)])]
    table = debug_filter_table(snap, b.build_pod_batch(pods, ctx),
                               LoadAwareConfig.make(), pod_names=["p"])
    assert "TaintToleration:-1" in table
    assert "PodTopologySpread:-1" in table
    assert "fit:1/3" in table
    # anti row: rebuild with only the anti term so its rejection is not
    # shadowed by spread (gates subtract in order)
    pods2 = [Pod(meta=ObjectMeta(name="q", namespace="d",
                                 labels={"app": "x"}),
                 priority=9000, requests={RK.CPU: 100.0},
                 pod_affinity=[PodAffinityTerm(
                     topology_key="zone", label_selector={"app": "x"},
                     anti=True)])]
    t2 = debug_filter_table(snap, b.build_pod_batch(pods2, ctx),
                            LoadAwareConfig.make(), pod_names=["q"])
    assert "InterPodAntiAffinity:-" in t2
    # affinity row: a follower of a nonexistent app is rejected everywhere
    pods3 = [Pod(meta=ObjectMeta(name="r", namespace="d",
                                 labels={"app": "y"}),
                 priority=9000, requests={RK.CPU: 100.0},
                 pod_affinity=[PodAffinityTerm(
                     topology_key="zone",
                     label_selector={"app": "nothing"})])]
    t3 = debug_filter_table(snap, b.build_pod_batch(pods3, ctx),
                            LoadAwareConfig.make(), pod_names=["r"])
    assert "InterPodAffinity:-" in t3 and "fit:0/3" in t3


# --- auto-pack (batching-layer specializations on the service path) ---------


def test_service_auto_pack_returns_results_in_caller_order():
    """The service derives dom_classes + prefix packing per batch and
    must hand every per-pod result array back in the CALLER's pod
    order: pods with distinguishable outcomes (impossible requests,
    reservation owners, NUMA binds) keep those outcomes at their
    original rows."""
    n, p = 256, 1024
    service = SchedulerService(num_rounds=2, k_choices=4)
    snap = synthetic.full_gate_cluster(n, num_quotas=8, num_gangs=8)
    service.publish(snap)
    pods = synthetic.full_gate_pods(p, n, seed=33, num_quotas=8,
                                    num_gangs=8)
    # pin sentinel rows at known ORIGINAL indices (unpacked order):
    # scattered impossible pods that packing will reorder
    reqs = np.asarray(pods.requests).copy()
    impossible = np.array([5, 300, 777, 1000])
    reqs[impossible] = 1e9
    pods = pods.replace(requests=reqs)
    res = service.schedule(pods)
    a = np.asarray(res.assignment)
    assert (a[impossible] == -1).all(), \
        "impossible pods must be unschedulable at their ORIGINAL rows"
    placed = int((a >= 0).sum())
    assert placed > 0
    # reservation consumption reported at the owners' original rows
    slot = np.asarray(res.res_slot)
    owner = np.asarray(pods.reservation_owner)
    assert (slot[owner < 0] < 0).all(), \
        "non-owner rows must never report a consumed slot"
    if (slot >= 0).any():
        rows = np.flatnonzero(slot >= 0)
        assert (owner[rows] == slot[rows]).all(), \
            "consumed slot ids must match the owner ids at those rows"
    # NUMA zone reports land on CPU-bind rows only
    zone = np.asarray(res.numa_zone)
    assert (zone[~np.asarray(pods.numa_single)] < 0).all()


def test_service_auto_pack_matches_unpacked_on_uncontended_cluster():
    """With ample capacity both configurations place every valid pod;
    auto_pack must not change that (only tie-breaks may differ)."""
    n, p = 512, 1024
    pods = synthetic.full_gate_pods(p, n, seed=41, num_quotas=8,
                                    num_gangs=8)
    results = {}
    for auto in (True, False):
        service = SchedulerService(num_rounds=2, k_choices=8,
                                   auto_pack=auto)
        service.publish(synthetic.full_gate_cluster(
            n, num_quotas=8, num_gangs=8))
        res = service.schedule(pods)
        results[auto] = np.asarray(res.assignment)
    placed_on = int((results[True] >= 0).sum())
    placed_off = int((results[False] >= 0).sum())
    # tight contention-free bound: the two programs may break ties
    # differently but must place essentially the same pod set
    assert abs(placed_on - placed_off) <= p // 100, (placed_on,
                                                    placed_off)
    assert placed_on > p // 2


def test_service_auto_pack_skips_small_batches():
    service = SchedulerService(num_rounds=1, k_choices=4)
    snap = synthetic.full_gate_cluster(64, num_quotas=4, num_gangs=4)
    pods = synthetic.full_gate_pods(256, 64, seed=3, num_quotas=4,
                                    num_gangs=4)
    packed, kwargs, inv = service._prepare_batch(snap, pods)
    assert inv is None  # below AUTO_PACK_MIN_BATCH: no reorder
    assert "dom_classes" in kwargs  # classes are free — always derived
    assert packed is pods


def test_builder_same_key_groups_form_one_domain_class():
    """The real informer/builder flow: two spread constraints sharing
    topologyKey "zone" (distinct selectors) produce byte-identical
    domain rows, so dom_classes batches them into one class — the
    static structure the service's auto-pack derivation hands to
    schedule_batch — and the scheduled placements respect both groups'
    skew bounds."""
    from koordinator_tpu.api import types as api
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )

    now = 1e9
    zones = ["z0", "z0", "z1", "z1"]
    hub, store = ClusterInformerHub(), SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=4)
    service = SchedulerService(store=store, num_rounds=2, k_choices=4)
    syncer.attach_scheduler(service)
    for i, z in enumerate(zones):
        hub.upsert_node(api.Node(
            meta=api.ObjectMeta(name=f"n{i}", labels={"zone": z}),
            allocatable={RK.CPU: 32000.0, RK.MEMORY: 65536.0}))
        hub.set_node_metric(api.NodeMetric(node_name=f"n{i}",
                                           update_time=now,
                                           node_usage={}))
    assert syncer.sync(now=now) == "full"
    pods = []
    for app in ("a", "b"):
        c = api.TopologySpreadConstraint(
            max_skew=1, topology_key="zone",
            label_selector={"app": app})
        for j in range(4):
            pods.append(api.Pod(
                meta=api.ObjectMeta(name=f"{app}{j}", uid=f"{app}{j}",
                                    namespace="d",
                                    labels={"app": app}),
                priority=9000 - j, requests={RK.CPU: 1000.0},
                spread_constraints=[c]))
    batch = syncer.build_pod_batch(pods)
    assert batch.has_spread
    classes = synthetic.dom_classes(batch)
    # both zone-keyed groups share one class (identical domain rows)
    assert any(len(c) == 2 for c in classes[0]), classes[0]
    res = service.schedule(batch, typed_pods=pods)
    a = np.asarray(res.assignment)
    assert (a >= 0).all()
    # each app independently balanced across the two zones
    for app_rows in (range(0, 4), range(4, 8)):
        zs = [zones[a[j]] for j in app_rows]
        assert abs(zs.count("z0") - zs.count("z1")) <= 1, zs
