"""Metrics layer: registry semantics, text exposition, and the series each
component emits (reference: pkg/koordlet/metrics/metrics_test.go,
pkg/slo-controller/metrics/metrics_test.go — assert series values after
driving the component)."""

import numpy as np
import pytest

from koordinator_tpu.metrics import (
    Counter, Gauge, Histogram, Registry, global_registry, kernel_timer,
)


# --- registry core ----------------------------------------------------------

def test_counter_gauge_histogram_basics():
    r = Registry()
    c = r.counter("requests", "total requests", labels=("code",))
    c.labels("200").inc()
    c.labels("200").inc(2)
    c.labels("500").inc()
    assert c.value("200") == 3
    assert c.value("500") == 1
    with pytest.raises(ValueError):
        c.labels("200").inc(-1)

    g = r.gauge("temperature")
    g.set(42.5)
    g.add(-2.5)
    assert g.value() == 40.0

    h = r.histogram("latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    assert h.count() == 3
    assert h.sum() == pytest.approx(5.55)


def test_registry_dedupes_and_rejects_shape_change():
    r = Registry()
    a = r.counter("x", labels=("l",))
    b = r.counter("x", labels=("l",))
    assert a is b
    with pytest.raises(ValueError):
        r.counter("x", labels=("other",))
    with pytest.raises(ValueError):
        r.gauge("x", labels=("l",))
    # histogram bucket spec is part of the shape
    h = r.histogram("h", buckets=(0.1, 1.0))
    assert r.histogram("h", buckets=(0.1, 1.0)) is h
    with pytest.raises(ValueError):
        r.histogram("h", buckets=(0.5, 5.0))


def test_bound_kind_mismatch_raises():
    r = Registry()
    c = r.counter("c", labels=("l",))
    with pytest.raises(TypeError):
        c.labels("a").observe(1.0)
    h = r.histogram("hh", labels=("l",))
    with pytest.raises(TypeError):
        h.labels("a").get()


def test_text_exposition_format():
    r = Registry(prefix="koord")
    c = r.counter("evictions", "evictions by reason", labels=("reason",))
    c.labels("memory").inc(3)
    g = r.gauge("version")
    g.set(7)
    h = r.histogram("cycle_seconds", buckets=(1.0,))
    h.observe(0.5)
    text = r.expose()
    assert '# TYPE koord_evictions counter' in text
    assert 'koord_evictions{reason="memory"} 3' in text
    assert 'koord_version 7' in text
    assert 'koord_cycle_seconds_bucket{le="1.0"} 1' in text
    assert 'koord_cycle_seconds_bucket{le="+Inf"} 1' in text
    assert 'koord_cycle_seconds_count 1' in text
    assert 'koord_cycle_seconds_sum 0.5' in text


def test_label_escaping():
    r = Registry()
    c = r.counter("odd", labels=("v",))
    c.labels('he said "hi"\n').inc()
    text = r.expose()
    assert r'he said \"hi\"\n' in text


def test_kernel_timer_records_and_annotates():
    r = Registry()
    h = r.histogram("kernel_seconds", labels=("op",))
    import jax.numpy as jnp
    with kernel_timer(h, "koord/test_kernel",  # koordlint: disable=OB001
                      labels=("matmul",)):
        x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
        np.asarray(x)
    assert h.count("matmul") == 1
    assert h.sum("matmul") > 0


# --- scheduler series -------------------------------------------------------

def test_scheduler_service_emits_series():
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
    from koordinator_tpu.snapshot.store import SnapshotStore
    from koordinator_tpu.utils import synthetic

    reg = Registry()
    m = SchedulerMetrics(reg)
    snap = synthetic.synthetic_cluster(64, num_quotas=0)
    pods = synthetic.synthetic_pods(32)
    store = SnapshotStore()
    store.publish(snap)
    svc = SchedulerService(store=store, metrics=m, num_rounds=2,
                           k_choices=4)
    res = svc.schedule(pods)
    placed = int((np.asarray(res.assignment) >= 0).sum())
    assert m.pods_scheduled.value("placed") == placed
    assert m.pods_scheduled.value("placed") + \
        m.pods_scheduled.value("unschedulable") == 32
    assert m.cycle_seconds.count() == 1
    assert m.kernel_seconds.count() == 1
    assert m.kernel_seconds.sum() > 0
    assert m.snapshot_version.value() >= 1
    # watchdog timeout series exists and is 0 (no slow cycle)
    assert m.scheduling_timeout.value("default") == 0


def test_scheduler_monitor_timeout_series():
    from koordinator_tpu.scheduler.frameworkext import SchedulerMonitor
    from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics

    reg = Registry()
    m = SchedulerMetrics(reg)
    mon = SchedulerMonitor(timeout_seconds=1.0, metrics=m)
    t = mon.start_cycle(now=0.0)
    mon.complete_cycle(t, now=5.0)
    assert m.scheduling_timeout.value("default") == 1


def test_metrics_http_exposition():
    import urllib.request
    from koordinator_tpu.scheduler.frameworkext import (
        DebugFlags, ServiceRegistry, ServicesServer,
    )

    reg = Registry()
    reg.counter("koordlet_pod_eviction", labels=("node", "reason")) \
        .labels("n0", "memory").inc()
    srv = ServicesServer(ServiceRegistry(), DebugFlags(),
                         metrics_registry=reg)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics") as resp:
            body = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
    finally:
        srv.close()
    assert 'koordlet_pod_eviction{node="n0",reason="memory"} 1' in body


# --- koordlet series --------------------------------------------------------

@pytest.fixture
def koordlet_env(tmp_path):
    from koordinator_tpu.api import types as api
    from koordinator_tpu.api.extension import ResourceKind
    from koordinator_tpu.koordlet.agent import Daemon, DaemonConfig
    from koordinator_tpu.koordlet.metrics_defs import KoordletMetrics
    from koordinator_tpu.koordlet.statesinformer import PodMeta
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)
    reg = Registry()
    m = KoordletMetrics(reg)
    d = Daemon(host, DaemonConfig(qos_interval_seconds=1.0), metrics=m)
    d.informer.set_node(api.Node(
        meta=api.ObjectMeta(name="node-a"),
        allocatable={ResourceKind.CPU: 8000,
                     ResourceKind.MEMORY: 16 * 1024}))
    slo = api.NodeSLO(node_name="node-a")
    slo.threshold.enable = True
    d.informer.set_node_slo(slo)
    ls = PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(name="ls", uid="u1"),
        requests={ResourceKind.CPU: 2000},
        limits={ResourceKind.CPU: 2000},
        qos_label="LS", priority=9500), cgroup_dir="kubepods/podu1")
    be = PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(name="be", uid="u2"),
        requests={ResourceKind.BATCH_CPU: 2000},
        qos_label="BE", priority=5500),
        cgroup_dir="kubepods/besteffort/podu2")
    host.make_cgroup("kubepods/podu1")
    host.make_cgroup("kubepods/besteffort/podu2")
    d.informer.set_pods([ls, be])
    return host, d, m


def test_koordlet_node_series(koordlet_env):
    host, d, m = koordlet_env
    d.tick(now=0)
    host.advance_cpu(400, 400)
    d.tick(now=10)
    assert m.start_time.value("node-a") == 0
    assert m.node_resource_allocatable.value("node-a", "cpu", "core") == 8
    assert m.node_resource_allocatable.value(
        "node-a", "memory", "MiB") == 16 * 1024
    assert m.node_used_cpu_cores.value("node-a") > 0
    # suppress ran (SLO defaults enable threshold) -> BE series present
    assert m.be_suppress_cpu_cores.value("node-a", "cpuset") >= 1


def test_koordlet_eviction_series(koordlet_env):
    from koordinator_tpu.koordlet.qosmanager import RecordingEvictor
    _host, d, m = koordlet_env
    assert isinstance(d.evictor, RecordingEvictor)
    d.tick(now=0)  # binds the evictor to the node name
    pods = d.informer.get_all_pods()
    d.evictor(pods[0], "evictPodsByNodeMemoryUsage")
    d.evictor(pods[0], "evictPodsByNodeMemoryUsage")  # dedupe
    assert m.pod_eviction.value(
        "node-a", "evictPodsByNodeMemoryUsage") == 1


def test_koordlet_psi_series(koordlet_env):
    host, d, m = koordlet_env
    # through the real collector path: fake kernel PSI -> cache -> series
    host.set_psi("kubepods/podu1", "cpu", 12.5)
    d.tick(now=10)
    # cgroup kubepods/podu1 resolves to the owning pod's UID
    assert m.pod_psi.value("node-a", "u1", "cpu", "avg10", "some") == 12.5


def test_koordlet_cpi_series(koordlet_env):
    from koordinator_tpu.koordlet import metriccache as mc
    _host, d, m = koordlet_env
    labels = {"pod_uid": "u1", "container": "c1"}
    d.metric_cache.append(mc.CONTAINER_CPI_CYCLES, 9.0, 3000.0, labels)
    d.metric_cache.append(mc.CONTAINER_CPI_INSTRUCTIONS, 9.0, 1500.0, labels)
    d.tick(now=10)
    assert m.container_cpi.value("node-a", "u1", "c1", "cpi") == 2.0


# --- slo-controller series --------------------------------------------------

def test_slo_controller_series():
    from koordinator_tpu.api import types as api
    from koordinator_tpu.slo_controller.metrics_defs import SloControllerMetrics
    from koordinator_tpu.slo_controller.nodemetric import NodeMetricController
    from koordinator_tpu.slo_controller.nodeslo import (
        SLOControllerConfig, render_node_slo,
    )

    reg = Registry()
    stats = SloControllerMetrics(reg)
    ctrl = NodeMetricController(stats=stats)
    ctrl.reconcile([api.Node(meta=api.ObjectMeta(name="n0"))])
    assert stats.nodemetric_reconcile_count.value("succeeded") == 1
    policy = ctrl.parse_policy(300.0, 30.0)
    assert policy.report_interval_seconds == 30.0
    assert stats.nodemetric_spec_parse_count.value("succeeded") == 1
    with pytest.raises(ValueError):
        ctrl.parse_policy(300.0, -1.0)
    assert stats.nodemetric_spec_parse_count.value("failed") == 1

    render_node_slo(SLOControllerConfig(), "n0", stats=stats)
    assert stats.nodeslo_reconcile_count.value("succeeded") == 1


def test_noderesource_series():
    import numpy as np
    from koordinator_tpu.slo_controller.metrics_defs import SloControllerMetrics
    from koordinator_tpu.slo_controller.noderesource import (
        NodeResourceController, NodeResourceInputs,
    )

    n = 2
    z = np.zeros((n, 2), np.float32)
    inputs = NodeResourceInputs(
        capacity=np.full((n, 2), 1000.0, np.float32),
        allocatable=np.full((n, 2), 1000.0, np.float32),
        system_used=z.copy(), system_reserved=z.copy(),
        hp_request=np.full((n, 2), 200.0, np.float32),
        hp_used=np.full((n, 2), 100.0, np.float32),
        hp_max_used_req=np.full((n, 2), 200.0, np.float32),
        prod_reclaimable=z.copy(),
        metric_age_seconds=np.zeros((n,), np.float32),
        valid=np.ones((n,), bool),
        names=["n0", "n1"])
    reg = Registry()
    stats = SloControllerMetrics(reg)
    ctrl = NodeResourceController(stats=stats)
    out = ctrl.reconcile(inputs)
    assert stats.node_resource_reconcile_count.value("succeeded") == 1
    assert stats.node_resource_run_plugin_status.value(
        "batchresource", "succeeded") == 1
    v = stats.node_extended_resource_allocatable.value("n0", "batch-cpu", "")
    assert v == float(out["batch"][0, 0])


# --- descheduler series -----------------------------------------------------

def test_descheduler_eviction_series():
    from koordinator_tpu.api import types as api
    from koordinator_tpu.descheduler.framework import (
        EvictionLimiter, RecordingEvictor,
    )
    from koordinator_tpu.descheduler.metrics_defs import DeschedulerMetrics

    reg = Registry()
    stats = DeschedulerMetrics(reg)
    ev = RecordingEvictor(EvictionLimiter(max_per_cycle=1), stats=stats,
                          strategy="LowNodeLoad")
    p1 = api.Pod(meta=api.ObjectMeta(name="a", namespace="ns"),
                 node_name="n0")
    p2 = api.Pod(meta=api.ObjectMeta(name="b", namespace="ns"),
                 node_name="n0")
    assert ev.evict(p1, "hot node")
    assert not ev.evict(p2, "hot node")  # limiter refuses
    assert stats.pods_evicted.value("success", "LowNodeLoad", "n0") == 1
    assert stats.pods_evicted.value("error", "LowNodeLoad", "n0") == 1


def test_migration_job_phase_series():
    from koordinator_tpu.api import types as api
    from koordinator_tpu.descheduler.framework import RecordingEvictor
    from koordinator_tpu.descheduler.metrics_defs import DeschedulerMetrics
    from koordinator_tpu.descheduler.migration import MigrationController

    reg = Registry()
    stats = DeschedulerMetrics(reg)
    pod = api.Pod(meta=api.ObjectMeta(name="p", namespace="ns"),
                  node_name="n0")
    ctrl = MigrationController(RecordingEvictor(), stats=stats,
                               get_pod=lambda _k: pod)
    ctrl.submit_for_pod(pod, reason="rebalance")
    ctrl.reconcile_once(now=0.0)
    assert stats.migration_jobs.value("Running") == 1
    assert stats.migration_jobs.value("Succeeded") == 1


def test_global_registry_is_shared():
    r1 = global_registry()
    r2 = global_registry()
    assert r1 is r2
