"""koordcost runtime plane: SLO burn-rate windows, the memwatch leak
sentinel, and the service health() snapshot.

The SLO tracker is driven through REAL metric families (a private
Registry per test) — the point of the design is that burn rates are
derived from the same histograms the dashboards read, so the tests
feed those histograms, never a private API.
"""

import jax
import jax.numpy as jnp
import pytest

from koordinator_tpu.metrics import Registry
from koordinator_tpu.obs import phases as obs_phases
from koordinator_tpu.obs.memwatch import MemorySample, MemWatch, \
    sample_devices
from koordinator_tpu.obs.slo import DEFAULT_OBJECTIVES, SloObjective, \
    SloTracker
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.utils import synthetic


def _metrics():
    return SchedulerMetrics(Registry())


# --- SloTracker ---------------------------------------------------------

LATENCY = SloObjective(name="cycle_latency_p99", kind="latency",
                       budget=0.25, threshold_s=1.0)  # a bucket bound
PLACEMENT = SloObjective(name="placement_success", kind="placement",
                         budget=0.10)


def test_objective_validation():
    with pytest.raises(ValueError):
        SloObjective(name="x", kind="weather", budget=0.1)
    with pytest.raises(ValueError):
        SloObjective(name="x", kind="latency", budget=0.0)
    with pytest.raises(ValueError):
        SloTracker(_metrics(), objectives=())
    with pytest.raises(ValueError):
        SloTracker(_metrics(), windows=(0,))


def test_latency_burn_rate_over_windows():
    m = _metrics()
    t = SloTracker(m, objectives=(LATENCY,), windows=(4, 8))

    def cycle(seconds):
        m.cycle_phase_seconds.labels(obs_phases.SPAN_CYCLE).observe(
            seconds)
        t.observe_cycle()

    for _ in range(4):
        cycle(0.01)
    status = t.status()
    obj = status["objectives"]["cycle_latency_p99"]
    assert status["ok"] and obj["burn_rate"] == {"4c": 0.0, "8c": 0.0}
    assert obj["budget_remaining"] == 1.0

    # four straight slow cycles: the short window saturates (4 bad / 4
    # total = 1.0 bad fraction, /0.25 budget = burn 4.0) while the long
    # window dilutes to half that — the multi-window idiom
    for _ in range(4):
        cycle(2.5)
    status = t.status()
    obj = status["objectives"]["cycle_latency_p99"]
    assert not status["ok"]
    assert obj["burn_rate"]["4c"] == pytest.approx(4.0)
    assert obj["burn_rate"]["8c"] == pytest.approx(2.0)
    # verdict window burned 2x budget: nothing left
    assert obj["budget_remaining"] == 0.0
    assert status["budget_remaining"] == 0.0
    # gauges published through the same catalog
    assert m.slo_burn_rate.value("cycle_latency_p99", "4c") \
        == pytest.approx(4.0)
    assert m.slo_budget_remaining.value("cycle_latency_p99") == 0.0


def test_latency_falls_back_to_untraced_cycle_histogram():
    m = _metrics()
    t = SloTracker(m, objectives=(LATENCY,), windows=(4,))
    # an untraced service records no cycle spans — the plain cycle
    # histogram is the same measurement and must feed the objective
    m.cycle_seconds.observe(0.02)
    t.observe_cycle()
    obj = t.status()["objectives"]["cycle_latency_p99"]
    assert obj["events_total"] == 1.0 and obj["events_bad"] == 0.0


def test_placement_burn_rate():
    m = _metrics()
    t = SloTracker(m, objectives=(PLACEMENT,), windows=(4,))
    m.pods_scheduled.labels("placed").inc(95)
    m.pods_scheduled.labels("unschedulable").inc(5)
    t.observe_cycle()
    obj = t.status()["objectives"]["placement_success"]
    # 5% unschedulable against a 10% budget: half the budget burning
    assert obj["burn_rate"]["4c"] == pytest.approx(0.5)
    assert obj["ok"] and obj["events_bad"] == 5.0


def test_status_schema_and_defaults():
    t = SloTracker(_metrics())
    status = t.status()  # before any cycle: vacuously green
    assert status["ok"] and status["budget_remaining"] == 1.0
    assert status["windows"] == ["8c", "64c"]
    assert set(status["objectives"]) == {o.name
                                         for o in DEFAULT_OBJECTIVES}
    for obj in status["objectives"].values():
        assert set(obj) == {"kind", "budget", "ok", "burn_rate",
                            "budget_remaining", "events_total",
                            "events_bad"}


# --- MemWatch -----------------------------------------------------------

def _fake_sampler(series):
    """A sampler yielding the next bytes_in_use from `series` each
    call (sticking at the last value)."""
    it = iter(series)
    state = {"cur": series[0]}

    def sampler():
        try:
            state["cur"] = next(it)
        except StopIteration:
            pass
        return {"tpu:0": MemorySample(
            device="tpu:0", bytes_in_use=state["cur"],
            peak_bytes=state["cur"], limit_bytes=1 << 30,
            source="memory_stats")}

    return sampler


def test_leak_sentinel_fires_on_sustained_growth():
    mb = 1 << 20
    grow = [i * 2 * mb for i in range(1, 9)]
    m = _metrics()
    w = MemWatch(leak_window=4, metrics=m, sampler=_fake_sampler(grow))
    fired = []
    for _ in range(8):
        w.sample()
        fired.extend(w.observe_cycle())
    # fires once per sustained climb (window clears after firing), not
    # once per growing cycle
    assert fired == ["tpu:0", "tpu:0"]
    assert w.snapshot()["leak_events"] == 2
    assert m.memwatch_leak_events.value("tpu:0") == 2.0
    # gauges track the freshest sample and the high-water mark
    assert m.hbm_bytes_in_use.value("tpu:0") == float(grow[-1])
    assert m.hbm_bytes_peak.value("tpu:0") == float(grow[-1])


def test_leak_sentinel_quiet_on_plateau_and_jitter():
    mb = 1 << 20
    # plateau: growth not strictly monotonic across the window
    flat = [100 * mb, 102 * mb, 102 * mb, 104 * mb, 103 * mb, 105 * mb]
    w = MemWatch(leak_window=3, sampler=_fake_sampler(flat))
    for _ in range(len(flat)):
        w.sample()
        assert w.observe_cycle() == []
    # monotonic but under the growth floor: allocator jitter, not a leak
    tiny = [100 * mb + i * 1024 for i in range(8)]
    w = MemWatch(leak_window=3, sampler=_fake_sampler(tiny))
    for _ in range(len(tiny)):
        w.sample()
        assert w.observe_cycle() == []
    assert w.snapshot()["leak_events"] == 0


def test_snapshot_headroom_and_window_validation():
    w = MemWatch(leak_window=2,
                 sampler=_fake_sampler([5 << 20]))
    w.sample()
    snap = w.snapshot()
    assert snap["headroom_bytes"] == (1 << 30) - (5 << 20)
    assert snap["devices"]["tpu:0"]["source"] == "memory_stats"
    with pytest.raises(ValueError):
        MemWatch(leak_window=1)


def test_sample_devices_cpu_fallback_counts_live_buffers():
    keep = jax.device_put(jnp.zeros((1024,), jnp.float32))
    try:
        samples = sample_devices()
        assert samples  # one per visible device (8-device CPU mesh)
        holder = f"{keep.devices().pop().platform}:" \
                 f"{keep.devices().pop().id}"
        s = samples[holder]
        # CPU reports no allocator stats: the live-buffer walk answers,
        # with no peak/limit (and therefore no headroom claim)
        assert s.source == "live_buffers"
        assert s.bytes_in_use >= keep.nbytes
        assert s.limit_bytes is None
    finally:
        del keep


# --- SchedulerService.health() ------------------------------------------

def _service(**kw):
    from koordinator_tpu.scheduler.frameworkext import SchedulerService

    svc = SchedulerService(metrics=_metrics(), num_rounds=1,
                           k_choices=4, **kw)
    svc._sleep = lambda _s: None
    snap = synthetic.synthetic_cluster(16, num_quotas=4)
    pods = synthetic.synthetic_pods(16, num_quotas=4)
    return svc, snap, pods


@pytest.mark.slow
def test_health_reports_slo_and_memory_on_a_traced_service():
    """Marked slow: tools/soak_service.py asserts the same green
    health() across a full soak as its own ci.sh stage."""
    svc, snap, pods = _service(trace=True, memwatch=True, slo=True)
    svc.publish(snap)
    for _ in range(2):
        svc.schedule(pods)
    health = svc.health()
    assert health["ok"] is True
    assert health["rung"] == "normal"
    assert health["slo"]["objectives"]["cycle_latency_p99"][
        "events_total"] == 2.0
    assert health["budgetRemaining"] == 1.0
    assert health["leakEvents"] == 0
    # CPU fallback: live-buffer telemetry present, no headroom claim
    assert health["memory"]["devices"]
    assert health["hbmHeadroomBytes"] is None
    assert health["snapshotVersion"] == svc.store.version
    assert health["lastCycleSeconds"] >= 0.0


@pytest.mark.slow
def test_health_disabled_is_vacuously_green_and_free():
    svc, snap, pods = _service()
    assert svc.memwatch is None and svc.slo is None
    svc.publish(snap)
    svc.schedule(pods)
    health = svc.health()
    assert health["ok"] is True
    assert health["slo"] is None and health["memory"] is None
    assert health["budgetRemaining"] is None
    assert health["hbmHeadroomBytes"] is None
