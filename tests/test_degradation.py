"""Typed failure classification, monotonic backoff, and the degradation
ladder (errorhandler.py + frameworkext.DegradationLadder +
SchedulerService integration).

The full chaos matrix is the tools/chaos_smoke.py CI stage; a
slow-marked test here runs the same matrix so `pytest -m slow` covers
it without double-paying in the fast battery.
"""

import numpy as np
import pytest

from koordinator_tpu.api.types import ObjectMeta, Pod
from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler.errorhandler import (
    Backoff,
    ErrorHandlerDispatcher,
    FailureClass,
    GuardTripError,
    RetryPolicy,
    TRANSIENT_CLASSES,
    WatchdogStall,
    classify_failure,
    dispatch_batch_errors,
)
from koordinator_tpu.scheduler.frameworkext import (
    DegradationLadder,
    SchedulerService,
)
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.testing import faults
from koordinator_tpu.utils import synthetic

N, P = 32, 64


# --- classify_failure ------------------------------------------------------

@pytest.mark.parametrize("message,expected", [
    ("RESOURCE_EXHAUSTED: Out of memory allocating 1GB",
     FailureClass.RESOURCE_EXHAUSTED),
    ("Internal: out of memory on device", FailureClass.RESOURCE_EXHAUSTED),
    ("UNAVAILABLE: device lost; socket closed", FailureClass.DEVICE_LOST),
    ("INTERNAL: Mosaic lowering failed", FailureClass.XLA_INTERNAL),
    ("DATA_LOSS: checkpoint corrupt", FailureClass.XLA_INTERNAL),
])
def test_classifier_message_vocabulary(message, expected):
    assert classify_failure(RuntimeError(message)) is expected
    # the real XLA exception type carries the same vocabulary
    assert classify_failure(faults.make_xla_error(message)) is expected


def test_classifier_unrecognized_text():
    # a plain exception with no vocabulary is UNKNOWN...
    assert classify_failure(RuntimeError("something else entirely")) \
        is FailureClass.UNKNOWN
    # ...but the same text on an XlaRuntimeError is still an XLA
    # runtime failure (the mro-name fallback)
    assert classify_failure(
        faults.make_xla_error("something else entirely")) \
        is FailureClass.XLA_INTERNAL


def test_classifier_typed_exceptions_win():
    assert classify_failure(GuardTripError(0x8)) is FailureClass.GUARD_TRIP
    assert classify_failure(WatchdogStall("cycle over budget")) \
        is FailureClass.WATCHDOG_STALL
    assert classify_failure(TimeoutError()) is FailureClass.WATCHDOG_STALL
    # an XlaRuntimeError with unrecognized text is still an XLA failure
    assert classify_failure(faults.make_xla_error("weird new status")) \
        is FailureClass.XLA_INTERNAL


def test_oom_is_not_transient():
    """Retrying the identical program after an OOM OOMs identically —
    only degrading (chunk halving) helps, so the ladder must see it
    immediately."""
    assert FailureClass.RESOURCE_EXHAUSTED not in TRANSIENT_CLASSES
    assert FailureClass.XLA_INTERNAL in TRANSIENT_CLASSES


# --- Backoff: monotonic bookkeeping (ISSUE 13 satellite) -------------------

def test_backoff_delays_grow_and_stay_bounded():
    b = Backoff(RetryPolicy(max_attempts=5, base_seconds=0.1,
                            multiplier=2.0, max_seconds=0.5,
                            jitter_frac=0.25), clock=lambda: 0.0, seed=1)
    delays = [b.next_delay() for _ in range(5)]
    assert b.exhausted()
    for i, d in enumerate(delays):
        nominal = min(0.1 * 2.0 ** i, 0.5)
        assert 0.0 <= d <= nominal * 1.25 + 1e-9
        assert d >= nominal * 0.75 - 1e-9
    # the jittered sequence trends upward overall
    assert delays[-1] > delays[0]


def test_backoff_never_negative_under_clock_steps():
    """The pin behind the time.monotonic switch: a clock that jumps
    BACKWARD mid-retry (the wall-clock NTP/DST failure mode) must not
    produce a negative window — delays derive from the attempt count,
    and remaining() clamps at zero."""
    now = {"t": 1000.0}
    b = Backoff(RetryPolicy(base_seconds=0.2), clock=lambda: now["t"],
                seed=2)
    d = b.next_delay()
    assert d >= 0.0
    assert b.remaining() > 0.0
    now["t"] -= 3600.0  # the clock steps an hour backwards
    assert b.remaining() >= 0.0  # never negative
    assert b.next_delay() >= 0.0
    now["t"] += 7200.0  # and far forwards: window simply expired
    assert b.remaining() == 0.0


def test_backoff_reset_restores_the_budget():
    b = Backoff(RetryPolicy(max_attempts=2), clock=lambda: 0.0)
    b.next_delay()
    b.next_delay()
    assert b.exhausted()
    b.reset()
    assert not b.exhausted() and b.remaining() == 0.0


# --- DegradationLadder unit transitions ------------------------------------

def test_ladder_oom_jumps_to_chunking_and_halves():
    lad = DegradationLadder(max_chunk_splits=3)
    assert lad.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    assert (lad.level, lad.chunk_splits) == (DegradationLadder.L_CHUNKED, 1)
    assert lad.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    assert lad.chunk_splits == 2
    lad.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    assert lad.chunk_splits == 3
    # the ladder is finite: past max splits there is no lower rung
    assert not lad.on_failure(FailureClass.RESOURCE_EXHAUSTED,
                              probing=False)


def test_ladder_device_lost_jumps_to_single_device():
    """Without survivor visibility (or with < 2 survivors) the mesh is
    abandoned — the conservative pre-ISSUE-14 behavior stays."""
    lad = DegradationLadder()
    assert lad.on_failure(FailureClass.DEVICE_LOST, probing=False)
    assert lad.level == DegradationLadder.L_SINGLE_DEVICE
    assert not lad.on_failure(FailureClass.DEVICE_LOST, probing=False)
    lad2 = DegradationLadder()
    assert lad2.on_failure(FailureClass.DEVICE_LOST, probing=False,
                           survivors=1)
    assert lad2.level == DegradationLadder.L_SINGLE_DEVICE


def test_ladder_device_lost_with_survivors_shrinks_the_mesh():
    """>= 2 survivors earn the mesh-shrink rung; a SECOND device loss
    there falls to single_device (monotone)."""
    lad = DegradationLadder()
    assert lad.on_failure(FailureClass.DEVICE_LOST, probing=False,
                          survivors=7)
    assert lad.level == DegradationLadder.L_MESH_SHRINK
    assert lad.state().mesh_shrink and not lad.state().single_device
    assert lad.on_failure(FailureClass.DEVICE_LOST, probing=False,
                          survivors=6)
    assert lad.level == DegradationLadder.L_SINGLE_DEVICE
    # chunking in force is KEPT across the shrink
    lad2 = DegradationLadder()
    lad2.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    lad2.on_failure(FailureClass.DEVICE_LOST, probing=False, survivors=4)
    assert lad2.state().label() == "mesh_shrink/2^1"


def test_ladder_generic_failures_skip_the_mesh_shrink_rung():
    """mesh_shrink is the DEVICE_LOST rung: a generic failure past
    chunking goes straight to single_device (shrinking a mesh with no
    lost device is meaningless)."""
    lad = DegradationLadder()
    lad.level = DegradationLadder.L_CHUNKED
    lad.chunk_splits = 1
    assert lad.on_failure(FailureClass.XLA_INTERNAL, probing=False)
    assert lad.level == DegradationLadder.L_SINGLE_DEVICE


def test_ladder_probe_from_mesh_shrink_restores_the_full_mesh():
    lad = DegradationLadder(probe_after=1)
    lad.on_failure(FailureClass.DEVICE_LOST, probing=False, survivors=3)
    lad.on_success(False, lad.state())
    state, probing = lad.begin_cycle()
    # chunk-free mesh_shrink probes past the chunked rung entirely
    assert probing and state.level == DegradationLadder.L_NO_CASCADE
    lad2 = DegradationLadder(probe_after=1)
    lad2.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    lad2.on_failure(FailureClass.DEVICE_LOST, probing=False, survivors=3)
    lad2.on_success(False, lad2.state())
    state2, probing2 = lad2.begin_cycle()
    assert probing2 and state2.label() == "chunked/2^1"
    # single_device probes to mesh_shrink first (gentler re-entry)
    lad3 = DegradationLadder(probe_after=1)
    lad3.on_failure(FailureClass.DEVICE_LOST, probing=False)
    lad3.on_success(False, lad3.state())
    state3, probing3 = lad3.begin_cycle()
    assert probing3 and state3.level == DegradationLadder.L_MESH_SHRINK


def test_ladder_generic_failures_step_one_rung():
    lad = DegradationLadder()
    path = []
    while lad.on_failure(FailureClass.XLA_INTERNAL, probing=False):
        path.append(lad.state().label())
    assert path == ["no_cascade", "chunked/2^1", "single_device/2^1"]


def test_ladder_probes_up_after_clean_streak():
    lad = DegradationLadder(probe_after=3)
    lad.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    lad.on_failure(FailureClass.RESOURCE_EXHAUSTED, probing=False)
    assert lad.state().label() == "chunked/2^2"
    labels = []
    for _ in range(30):
        state, probing = lad.begin_cycle()
        if probing:
            labels.append(state.label())
        lad.on_success(probing, state)
        if lad.level == DegradationLadder.L_NORMAL:
            break
    # one rung at a time, each earned by a fresh clean streak
    assert labels == ["chunked/2^1", "no_cascade", "normal"]
    assert lad.level == DegradationLadder.L_NORMAL


def test_ladder_failed_probe_falls_back_without_degrading():
    lad = DegradationLadder(probe_after=1)
    lad.on_failure(FailureClass.XLA_INTERNAL, probing=False)
    lad.on_success(False, lad.state())
    state, probing = lad.begin_cycle()
    assert probing and state.level == DegradationLadder.L_NORMAL
    lad.on_failure(FailureClass.XLA_INTERNAL, probing=True)
    # still at the pre-probe rung, streak restarted
    assert lad.level == DegradationLadder.L_NO_CASCADE
    assert lad.clean_streak == 0
    assert lad.begin_cycle()[1] is False


# --- error-chain drain -----------------------------------------------------

def test_dispatch_infra_mask_routes_as_infrastructure_error():
    seen = []
    d = ErrorHandlerDispatcher()
    d.set_default_handler(
        lambda info, err: seen.append((info.pod.meta.name,
                                       err.unschedulable)))
    pods = [Pod(meta=ObjectMeta(name=f"p{i}")) for i in range(3)]
    assignment = np.asarray([-1, -1, 2])
    valid = np.asarray([True, True, True])
    infra = np.asarray([True, False, True])
    n = dispatch_batch_errors(d, assignment, valid, pods,
                              infra_mask=infra)
    assert n == 2
    # p0 quarantined -> infrastructure (retry hard, never preempt);
    # p1 plain no-fit -> unschedulable; p2 placed -> not dispatched
    assert seen == [("p0", False), ("p1", True)]


# --- service integration ---------------------------------------------------

def make_service(**kw):
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, **kw)
    svc._sleep = lambda _s: None
    return svc


def slim_inputs(seed=0):
    snap = synthetic.synthetic_cluster(N, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.synthetic_pods(P, seed=seed + 3, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


def test_service_oom_degrades_to_chunked_and_conforms():
    snap, pods = slim_inputs(1)
    inj = faults.FaultInjector(5)
    svc = make_service()
    svc.publish(snap)
    svc.fault_injection = inj.oom_above(P // 2)
    res = svc.schedule(pods)
    assert svc.ladder.level == DegradationLadder.L_CHUNKED
    assert svc.metrics.failures_classified.labels(
        "resource_exhausted").get() >= 1
    assert svc.metrics.degraded_cycles.labels(
        svc.last_ladder_state.label()).get() == 1
    # chunked placements == a clean service FORCED to the same rung
    oracle = make_service()
    oracle.ladder.level = svc.ladder.level
    oracle.ladder.chunk_splits = svc.ladder.chunk_splits
    oracle.publish(snap)
    np.testing.assert_array_equal(
        np.asarray(res.assignment),
        np.asarray(oracle.schedule(pods).assignment))


def test_service_transient_retries_in_place():
    snap, pods = slim_inputs(2)
    inj = faults.FaultInjector(7)
    svc = make_service()
    svc.publish(snap)
    svc.fault_injection = inj.xla_transient(fail_attempts={1, 2})
    res = svc.schedule(pods)
    assert svc.ladder.level == DegradationLadder.L_NORMAL
    assert svc.metrics.failures_classified.labels(
        "xla_internal").get() == 2
    # after the retries the cycle is the plain program, bit-identical
    oracle = make_service()
    oracle.publish(snap)
    np.testing.assert_array_equal(
        np.asarray(res.assignment),
        np.asarray(oracle.schedule(pods).assignment))


def test_service_device_lost_resumes_on_the_shrunk_mesh():
    """ISSUE 14: a device that dies and STAYS dead (until excluded)
    must land the service on the mesh-shrink rung — scheduling over
    the survivors, bit-identical to the healthy program — and probe-up
    must restore the full mesh."""
    import jax

    if jax.device_count() < 3:
        pytest.skip("needs >= 3 devices (conftest forces 8 on CPU)")
    snap, pods = slim_inputs(11)
    inj = faults.FaultInjector(3)
    svc = make_service()
    svc.ladder.probe_after = 1
    svc.fault_injection = inj.lost_device_until_shrunk(after_calls=0)
    survivors = jax.devices()[:-1]
    svc.device_health = lambda: survivors
    svc.publish(snap)
    res = svc.schedule(pods)
    assert svc.ladder.level == DegradationLadder.L_MESH_SHRINK
    assert svc.metrics.mesh_shrink_events.value() == 1
    assert svc.metrics.mesh_size.value() == len(survivors)
    assert svc.summary()["meshSize"] == len(survivors)
    # placements on the shrunk mesh == the no-fault oracle at the same
    # rung == (by the PR 4 mesh conformance) the plain program
    oracle = make_service()
    oracle.ladder.level = DegradationLadder.L_MESH_SHRINK
    oracle.publish(snap)
    np.testing.assert_array_equal(
        np.asarray(res.assignment),
        np.asarray(oracle.schedule(pods).assignment))
    # the committed snapshot keeps REAL shapes (unpadded): the store
    # must not grow pad rows from the shrunk-mesh cycle
    assert int(np.asarray(
        svc.store.current().nodes.schedulable).shape[0]) == N
    # device heals -> probe-up restores the full mesh
    svc.fault_injection = None
    svc.device_health = None
    for _ in range(6):
        svc.schedule(pods)
        if svc.ladder.level < DegradationLadder.L_MESH_SHRINK:
            break
    assert svc.ladder.level < DegradationLadder.L_MESH_SHRINK
    assert svc.metrics.mesh_size.value() == jax.device_count()


def test_service_watchdog_stall_degrades_next_cycle():
    snap, pods = slim_inputs(3)
    svc = make_service()
    svc.publish(snap)
    faults.FaultInjector.stall_watchdog(svc)
    svc.schedule(pods)
    assert svc.monitor.timeouts >= 1
    assert svc.ladder.level == DegradationLadder.L_NO_CASCADE
    svc.monitor.timeout = 30.0
    svc.schedule(pods)  # next cycle runs degraded and completes
    assert svc.metrics.degraded_cycles.labels("no_cascade").get() == 1


def test_service_exhausted_ladder_raises_the_classified_failure():
    snap, pods = slim_inputs(4)
    svc = make_service(max_cycle_attempts=20)
    svc.publish(snap)
    svc.fault_injection = faults.FaultInjector(9).oom_above(0)  # every width
    with pytest.raises(Exception) as exc_info:
        svc.schedule(pods)
    assert classify_failure(exc_info.value) \
        is FailureClass.RESOURCE_EXHAUSTED
    # the ladder bottomed out trying: chunking reached its max
    assert svc.ladder.chunk_splits == svc.ladder.max_chunk_splits


def test_summary_exposes_resilience_state():
    snap, pods = slim_inputs(5)
    svc = make_service()
    svc.publish(snap)
    svc.schedule(pods)
    s = svc.summary()
    assert s["degradationLevel"] == "normal"
    assert s["ladderTransitions"] == 0
    assert s["lastHealthWord"] == 0


def test_service_never_retries_past_the_commit():
    """A failure AFTER the snapshot commit (the on_assumed user hook)
    must propagate, never re-enter the retry loop: re-running the cycle
    would schedule the same batch against its own post-commit snapshot
    and double-charge every placement."""
    from koordinator_tpu.api.types import ObjectMeta as OM, Pod as P_

    snap, pods = slim_inputs(6)
    svc = make_service()
    svc.publish(snap)
    calls = {"n": 0}

    def exploding_hook(_assignment, _typed, _result):
        calls["n"] += 1
        raise RuntimeError("assume cache wiring broke")  # class UNKNOWN

    svc.on_assumed = exploding_hook
    typed = [P_(meta=OM(name=f"p{i}")) for i in range(P)]
    requested_before = np.asarray(svc.store.current().nodes.requested)
    with pytest.raises(RuntimeError, match="assume cache wiring broke"):
        svc.schedule(pods, typed_pods=typed)
    # exactly ONE program ran (no transient retry), and exactly one
    # commit landed — not a double-charge
    assert calls["n"] == 1
    requested_after = np.asarray(svc.store.current().nodes.requested)
    assert (requested_after >= requested_before - 1e-3).all()
    svc.on_assumed = None
    oracle = make_service()
    oracle.publish(snap)
    oracle.schedule(pods)
    np.testing.assert_allclose(
        requested_after,
        np.asarray(oracle.store.current().nodes.requested))


def test_quarantine_converges_for_capacity_defects():
    """An overcommitted row is clamped by the scrub, so the COMMITTED
    snapshot no longer trips the guard: one fault = one trip, not a
    per-cycle alarm storm in a long-lived service."""
    snap, pods = slim_inputs(7)
    inj = faults.FaultInjector(31)
    bad_snap, rows = inj.corrupt_snapshot(snap, "overcommit_row")
    svc = make_service()
    svc.publish(bad_snap)
    svc.schedule(pods)
    assert svc.last_health_word != 0
    trips = svc.metrics.guard_trips.labels("node_overcommit").get()
    svc.schedule(pods)
    assert svc.last_health_word == 0, "guard re-tripped on the " \
        "already-quarantined snapshot"
    assert svc.metrics.guard_trips.labels("node_overcommit").get() == trips
    # the node STAYS quarantined until a fresh publish
    assert not np.asarray(svc.store.current().nodes.schedulable)[rows].any()


# --- the full chaos matrix, slow-marked ------------------------------------

@pytest.mark.slow
def test_full_chaos_matrix():
    """The same matrix tools/chaos_smoke.py runs as a CI stage (per
    fault class: detected, quarantined, service up, clean rows
    bit-identical to the oracle)."""
    import tools.chaos_smoke as chaos

    assert chaos.main([]) == 0
