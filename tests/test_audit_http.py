"""Audit HTTP query handler (auditor.go:130 HttpHandler, gated by
AuditEventsHTTPHandler): token-paginated reverse reads, TTL/cap cursor
GC, and the reference's 400/409 statuses."""

import json
import urllib.error
import urllib.request

import pytest

from koordinator_tpu.koordlet.audit import Auditor, AuditQueryServer


@pytest.fixture
def auditor():
    a = Auditor(log_dir=None, ring_size=64)
    for i in range(10):
        a.info("executor", "write", f"cgroup/{i}")
    return a


def test_pagination_reverse_order(auditor):
    srv = AuditQueryServer(auditor, default_limit=4)
    try:
        code, page1 = srv.handle(size="4")
        assert code == 200 and len(page1["events"]) == 4
        # newest first
        assert page1["events"][0]["target"] == "cgroup/9"
        assert not page1["eof"]
        token = page1["pageToken"]
        code, page2 = srv.handle(size="4", page_token=token)
        assert page2["events"][0]["target"] == "cgroup/5"
        code, page3 = srv.handle(size="4", page_token=token)
        assert len(page3["events"]) == 2 and page3["eof"]
        # a consumed-to-EOF cursor is gone
        code, _ = srv.handle(size="4", page_token=token)
        assert code == 409
    finally:
        srv.close()


def test_size_cap_and_bad_token(auditor):
    srv = AuditQueryServer(auditor, max_limit=100)
    try:
        code, out = srv.handle(size="1000")
        assert code == 400 and "exceeds" in out["error"]
        code, out = srv.handle(page_token="nope")
        assert code == 409
        code, out = srv.handle(size="abc")
        assert code == 400
        # non-positive sizes would bypass the cap / never reach eof
        code, _ = srv.handle(size="-1")
        assert code == 400
        code, _ = srv.handle(size="0")
        assert code == 400
    finally:
        srv.close()


def test_cursor_ttl_and_cap(auditor):
    srv = AuditQueryServer(auditor, default_limit=2, reader_ttl=10.0,
                           max_readers=2)
    try:
        _, p1 = srv.handle(size="2", now=0.0)
        # TTL expiry
        code, _ = srv.handle(size="2", page_token=p1["pageToken"], now=20.0)
        assert code == 409
        # cap: 3 fresh cursors, oldest evicted
        _, a = srv.handle(size="2", now=30.0)
        _, b = srv.handle(size="2", now=31.0)
        _, c = srv.handle(size="2", now=32.0)
        code, _ = srv.handle(size="2", page_token=a["pageToken"], now=33.0)
        assert code == 409, "oldest cursor past max_readers must be evicted"
        code, _ = srv.handle(size="2", page_token=c["pageToken"], now=33.0)
        assert code == 200
    finally:
        srv.close()


def test_over_real_http(auditor):
    srv = AuditQueryServer(auditor)
    try:
        url = f"http://127.0.0.1:{srv.port}/events?size=3"
        with urllib.request.urlopen(url, timeout=5) as r:
            out = json.loads(r.read())
        assert len(out["events"]) == 3
        assert out["events"][0]["target"] == "cgroup/9"
        url2 = (f"http://127.0.0.1:{srv.port}/events?size=3"
                f"&pageToken={out['pageToken']}")
        with urllib.request.urlopen(url2, timeout=5) as r:
            out2 = json.loads(r.read())
        assert out2["events"][0]["target"] == "cgroup/6"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/events?pageToken=bad",
                timeout=5)
        assert ei.value.code == 409
    finally:
        srv.close()


def test_daemon_wires_audit_server(tmp_path):
    from koordinator_tpu.api import types as api
    from koordinator_tpu.koordlet.agent import Daemon, DaemonConfig
    from koordinator_tpu.koordlet.testing import FakeHost

    a = Auditor(log_dir=None, ring_size=16)
    a.info("boot", "start", "daemon")
    d = Daemon(FakeHost(str(tmp_path)), DaemonConfig(audit_http_port=0),
               auditor=a)
    assert d.audit_server is not None
    url = f"http://127.0.0.1:{d.audit_server.port}/apis/v1/audit"
    with urllib.request.urlopen(url, timeout=5) as r:
        out = json.loads(r.read())
    assert out["events"][0]["operation"] == "start"
    d.audit_server.close()
