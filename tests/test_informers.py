"""Informer/indexer plane + snapshot syncer: event fan-out, incremental
indexes, and the metric-delta vs full-rebuild freshness split (pkg/client
informers + frameworkext eventhandlers; SURVEY §7 hard part (e))."""

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot.builder import SnapshotBuilder
from koordinator_tpu.snapshot import (
    ClusterInformerHub,
    SnapshotStore,
    SnapshotSyncer,
)

NOW = 1e9


def mk_node(name, cpu=32000.0):
    return api.Node(meta=api.ObjectMeta(name=name, labels={"pool": "x"}),
                    allocatable={RK.CPU: cpu, RK.MEMORY: 65536.0})


def mk_metric(name, cpu_used=1000.0):
    return api.NodeMetric(node_name=name, update_time=NOW,
                          node_usage={RK.CPU: cpu_used, RK.MEMORY: 1024.0})


def test_indexes_follow_pod_lifecycle():
    hub = ClusterInformerHub()
    pod = api.Pod(meta=api.ObjectMeta(uid="u1", name="p1"),
                  node_name="n0", owner_workload="default/web")
    hub.upsert_pod(pod)
    assert [p.meta.uid for p in hub.pods_on_node("n0")] == ["u1"]
    assert [p.meta.uid for p in hub.pods_of_owner("default/web")] == ["u1"]

    moved = api.Pod(meta=api.ObjectMeta(uid="u1", name="p1"),
                    node_name="n1", owner_workload="default/web")
    hub.upsert_pod(moved)
    assert hub.pods_on_node("n0") == []
    assert [p.meta.uid for p in hub.pods_on_node("n1")] == ["u1"]

    hub.delete_pod("u1")
    assert hub.pods_on_node("n1") == []
    assert hub.pods_of_owner("default/web") == []


def test_event_fanout_and_versions():
    hub = ClusterInformerHub()
    events = []
    hub.subscribe("node", lambda ev, o: events.append((ev, o.meta.name)))
    v0 = hub.resource_version
    hub.upsert_node(mk_node("n0"))
    hub.upsert_node(mk_node("n0"))
    hub.delete_node("n0")
    assert events == [("add", "n0"), ("update", "n0"), ("delete", "n0")]
    assert hub.resource_version == v0 + 3


def test_syncer_full_then_delta_then_rebuild_on_shape_change():
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=4, delta_pad=4)
    for i in range(2):
        hub.upsert_node(mk_node(f"n{i}"))
        hub.set_node_metric(mk_metric(f"n{i}"))
    assert syncer.sync(now=NOW) == "full"
    v1 = store.version

    # metric churn only -> O(K) delta, same shapes, version bumps
    hub.set_node_metric(mk_metric("n0", cpu_used=9000.0))
    assert syncer.sync(now=NOW) == "delta"
    assert store.version > v1
    snap = store.current()
    used = np.asarray(snap.nodes.usage)
    # n0's usage row reflects the new metric
    assert used[:2, 0].max() == pytest.approx(9000.0)

    assert syncer.sync(now=NOW) == "noop"

    # a new node patches its rows incrementally (NodeTopologyDelta)
    hub.upsert_node(mk_node("n2"))
    hub.set_node_metric(mk_metric("n2"))
    assert syncer.sync(now=NOW) == "topology"
    assert np.asarray(store.current().nodes.schedulable).sum() == 3
    assert syncer.full_rebuilds == 1 and syncer.topology_ingests == 1

    # non-node shape churn (a running pod) still rebuilds
    hub.upsert_pod(api.Pod(meta=api.ObjectMeta(name="p", uid="u"),
                           node_name="n0", phase="Running",
                           requests={RK.CPU: 100.0}))
    assert syncer.sync(now=NOW) == "full"
    assert syncer.full_rebuilds == 2 and syncer.delta_ingests == 1


def test_syncer_metric_overflow_falls_back_to_rebuild():
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=8, delta_pad=2)
    for i in range(6):
        hub.upsert_node(mk_node(f"n{i}"))
        hub.set_node_metric(mk_metric(f"n{i}"))
    syncer.sync(now=NOW)
    # 3 dirty metrics > pad 2: rebuild, never truncate
    for i in range(3):
        hub.set_node_metric(mk_metric(f"n{i}", cpu_used=5000.0))
    assert syncer.sync(now=NOW) == "full"


def test_hub_feeds_scheduler_end_to_end():
    """The full ingest plane: hub -> syncer -> store -> schedule_batch."""
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=2)
    hub.upsert_node(mk_node("n0"))
    hub.set_node_metric(mk_metric("n0"))
    syncer.sync(now=NOW)

    pod = api.Pod(meta=api.ObjectMeta(name="p0"),
                  requests={RK.CPU: 1000.0, RK.MEMORY: 256.0},
                  priority=9000)
    batch = syncer.builder.build_pod_batch([pod], syncer.ctx)
    res = core.schedule_batch(store.current(), batch,
                              loadaware.LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) == 0


def test_hub_is_a_manager_cluster_source(tmp_path):
    """The hub satisfies cmd/manager's ClusterSource protocol."""
    from koordinator_tpu.cmd import manager as cmd_manager

    hub = ClusterInformerHub()
    hub.upsert_node(mk_node("n0"))
    hub.set_node_metric(mk_metric("n0"))
    hub.upsert_quota_profile(api.ElasticQuotaProfile(
        meta=api.ObjectMeta(name="p"), quota_name="root",
        node_selector={"pool": "x"}))
    proc = cmd_manager.ManagerProcess(
        cmd_manager.ManagerConfig(lease_file=str(tmp_path / "m.lease")),
        hub)
    proc.tick(now=NOW)
    node = hub.nodes()[0]
    assert node.allocatable.get(RK.BATCH_CPU, 0) > 0
    assert "root" in proc.quota_reconciler.quotas


def test_quota_summary_service_payload():
    """The elastic-quota service payload from the live snapshot
    (frameworkext services: /apis/v1/plugins/elasticquota)."""
    import urllib.request

    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.api.types import (
        ElasticQuota, Node, NodeMetric, ObjectMeta,
    )
    from koordinator_tpu.scheduler.frameworkext import (
        DebugFlags,
        ServiceRegistry,
        ServicesServer,
    )
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )

    hub = ClusterInformerHub()
    hub.upsert_node(Node(meta=ObjectMeta(name="n0"),
                         allocatable={RK.CPU: 8000.0,
                                      RK.MEMORY: 16384.0}))
    hub.set_node_metric(NodeMetric(node_name="n0", update_time=1e9,
                                   node_usage={}))
    q = ElasticQuota(meta=ObjectMeta(name="team-a"))
    q.min = {RK.CPU: 2000.0}
    q.max = {RK.CPU: 4000.0}
    hub.upsert_quota(q)
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=1)
    syncer.sync(now=1e9)
    summary = syncer.quota_summary()
    assert "team-a" in summary
    assert summary["team-a"]["min"][int(RK.CPU)] == 2000.0
    # and it plugs into the services engine like any provider
    registry = ServiceRegistry()
    registry.register("elasticquota", syncer.quota_summary)
    server = ServicesServer(registry, DebugFlags())
    try:
        url = (f"http://127.0.0.1:{server.port}"
               f"/apis/v1/plugins/elasticquota")
        with urllib.request.urlopen(url) as r:
            import json as _json
            body = _json.load(r)
        assert body["team-a"]["min"][int(RK.CPU)] == 2000.0
    finally:
        server.close()


def test_device_summary_service_payload():
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.api.types import (
        Device, DeviceInfo, Node, NodeMetric, ObjectMeta,
    )
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )

    hub = ClusterInformerHub()
    hub.upsert_node(Node(meta=ObjectMeta(name="g0"),
                         allocatable={RK.CPU: 8000.0,
                                      RK.MEMORY: 16384.0}))
    hub.set_node_metric(NodeMetric(node_name="g0", update_time=1e9,
                                   node_usage={}))
    hub.set_device(Device(node_name="g0", devices=[
        DeviceInfo(minor=m, type="gpu",
                   resources={RK.GPU_CORE: 100.0,
                              RK.GPU_MEMORY: 16000.0})
        for m in range(2)]))
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=1, max_gpu_inst=2)
    syncer.sync(now=1e9)
    summary = syncer.device_summary()
    assert summary["g0"]["gpuTotal"]["memoryMiB"] == 32000.0  # 2 x 16000
    assert summary["g0"]["gpuTotal"]["count"] == 2
    assert len(summary["g0"]["instances"]) == 2
    assert summary["g0"]["instances"][0]["coreFree"] == 100.0


# --- assume cache: device commits survive host recomputes -----------------
# (scheduler cache assume + podAssignCache; scheduler_adapter.go. ADVICE r4
# medium: an O(K) topology ingest recomputing a touched node's row from
# host-side running pods alone silently dropped device-side commit charges.)

def _assume_wiring(max_nodes=4, delta_pad=2):
    from koordinator_tpu.scheduler.frameworkext import SchedulerService

    hub, store = ClusterInformerHub(), SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=max_nodes,
                            delta_pad=delta_pad)
    service = SchedulerService(store=store, num_rounds=2, k_choices=2)
    syncer.attach_scheduler(service)
    return hub, store, syncer, service


def _place_one(hub, syncer, service, cpu=4000.0, quota_name=""):
    pod = api.Pod(meta=api.ObjectMeta(name="pp", uid="pp"), priority=9500,
                  quota_name=quota_name,
                  requests={RK.CPU: cpu, RK.MEMORY: 4096.0})
    batch = syncer.builder.build_pod_batch([pod], syncer.ctx)
    res = service.schedule(batch, typed_pods=[pod])
    ni = int(np.asarray(res.assignment)[0])
    assert ni >= 0
    name = next(n for n, i in syncer.builder.node_index.items() if i == ni)
    return pod, res, ni, name


def test_topology_ingest_keeps_assumed_charges():
    """A label-only node update (O(K) topology path) must recompute the
    row WITH the in-flight assumed pod's requested charge."""
    hub, store, syncer, service = _assume_wiring()
    for n in ("n0", "n1"):
        hub.upsert_node(mk_node(n))
        hub.set_node_metric(mk_metric(n))
    assert syncer.sync(now=NOW) == "full"
    pod, res, ni, name = _place_one(hub, syncer, service)
    assert len(hub.assumed_entries()) == 1
    assert np.asarray(store.current().nodes.requested)[ni, 0] \
        == pytest.approx(4000.0)

    updated = mk_node(name)
    updated.meta.labels = dict(updated.meta.labels, tier="gold")
    hub.upsert_node(updated)
    assert syncer.sync(now=NOW) == "topology"
    assert np.asarray(store.current().nodes.requested)[ni, 0] \
        == pytest.approx(4000.0)
    # golden: the incremental row equals what a full rebuild produces
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="q")))
    assert syncer.sync(now=NOW) == "full"
    assert np.asarray(store.current().nodes.requested)[
        syncer.builder.node_index[name], 0] == pytest.approx(4000.0)


def test_identity_unchanged_heartbeat_is_filtered():
    """A node re-upsert with identical identity (a pure status
    heartbeat) must not dirty the topology path at all (ADVICE r4:
    heartbeats would otherwise overflow delta_pad every window)."""
    hub, store, syncer, _ = _assume_wiring()
    hub.upsert_node(mk_node("n0"))
    hub.set_node_metric(mk_metric("n0"))
    assert syncer.sync(now=NOW) == "full"
    hub.upsert_node(mk_node("n0"))   # identical identity
    assert syncer.sync(now=NOW) == "noop"
    assert syncer.topology_ingests == 0


def test_watch_catchup_counts_charge_once():
    """When the watch delivers the bound pod, the assume entry clears
    and the rebuild counts the charge exactly once — including a
    bound-but-not-yet-Running pod (upstream NodeInfo semantics) and the
    pod's quota used."""
    hub, store, syncer, service = _assume_wiring()
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="tenant"),
                                      min={RK.CPU: 8000.0}))
    for n in ("n0", "n1"):
        hub.upsert_node(mk_node(n))
        hub.set_node_metric(mk_metric(n))
    assert syncer.sync(now=NOW) == "full"
    pod, res, ni, name = _place_one(hub, syncer, service,
                                    quota_name="tenant")
    qi = syncer.builder.quota_index["tenant"]
    assert np.asarray(store.current().quotas.used)[qi, 0] \
        == pytest.approx(4000.0)

    # watch catches up: bound but still Pending -> assume entry clears,
    # rebuild keeps the charge through the watched object
    bound = api.Pod(meta=api.ObjectMeta(name="pp", uid="pp"),
                    priority=9500, node_name=name, phase="Pending",
                    quota_name="tenant",
                    requests={RK.CPU: 4000.0, RK.MEMORY: 4096.0})
    hub.upsert_pod(bound)
    assert hub.assumed_entries() == []
    assert syncer.sync(now=NOW) == "full"
    ni2 = syncer.builder.node_index[name]
    assert np.asarray(store.current().nodes.requested)[ni2, 0] \
        == pytest.approx(4000.0)
    assert np.asarray(store.current().quotas.used)[
        syncer.builder.quota_index["tenant"], 0] == pytest.approx(4000.0)


def test_forget_assumed_releases_charge_everywhere():
    """store.forget + hub.forget_assumed: the device returns the charge
    and the next host recompute agrees (no resurrection)."""
    hub, store, syncer, service = _assume_wiring()
    for n in ("n0", "n1"):
        hub.upsert_node(mk_node(n))
        hub.set_node_metric(mk_metric(n))
    assert syncer.sync(now=NOW) == "full"
    pod, res, ni, name = _place_one(hub, syncer, service)
    batch = syncer.builder.build_pod_batch([pod], syncer.ctx)
    store.forget(batch, res, np.array([True]))
    hub.forget_assumed("pp")
    assert np.asarray(store.current().nodes.requested)[ni, 0] \
        == pytest.approx(0.0)
    updated = mk_node(name)
    updated.meta.labels = dict(updated.meta.labels, redo="1")
    hub.upsert_node(updated)
    assert syncer.sync(now=NOW) == "topology"
    assert np.asarray(store.current().nodes.requested)[ni, 0] \
        == pytest.approx(0.0)


def test_rebuild_counts_assumed_gang_members():
    """A rebuild must not forget a gang's held members: assumed members
    count into GangState.assumed (members already assumed/bound)."""
    hub, store, syncer, service = _assume_wiring()
    hub.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                      min_member=2, total_member=2))
    for n in ("n0", "n1"):
        hub.upsert_node(mk_node(n))
        hub.set_node_metric(mk_metric(n))
    assert syncer.sync(now=NOW) == "full"
    pod = api.Pod(meta=api.ObjectMeta(name="m0", uid="m0"), priority=9500,
                  gang_name="g", requests={RK.CPU: 1000.0,
                                           RK.MEMORY: 1024.0})
    batch = syncer.builder.build_pod_batch([pod], syncer.ctx)
    res = service.schedule(batch, typed_pods=[pod])
    assert int(np.asarray(res.assignment)[0]) >= 0
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="q")))
    assert syncer.sync(now=NOW) == "full"
    gi = syncer.builder.gang_index["g"]
    assert int(np.asarray(store.current().gangs.assumed)[gi]) == 1


def test_assume_ttl_expires_lost_binds():
    """An assume whose bind outcome never arrives expires after the
    TTL (the k8s assumed-pod expiry) — no permanent phantom capacity."""
    hub, store, syncer, service = _assume_wiring()
    syncer.assume_ttl = 900.0
    for n in ("n0", "n1"):
        hub.upsert_node(mk_node(n))
        hub.set_node_metric(mk_metric(n))
    assert syncer.sync(now=NOW) == "full"
    pod, res, ni, name = _place_one(hub, syncer, service)
    # _record_assumes stamps wall-clock time; normalize for the test
    entry, _ = hub._assumed["pp"]
    hub._assumed["pp"] = (entry, NOW)
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="q")))
    assert syncer.sync(now=NOW + 10) == "full"
    assert np.asarray(store.current().nodes.requested)[
        syncer.builder.node_index[name], 0] == pytest.approx(4000.0)
    # past the TTL the entry expires and the next recompute drops it
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="q2")))
    assert syncer.sync(now=NOW + 1000) == "full"
    assert hub.assumed_entries() == []
    assert np.asarray(store.current().nodes.requested)[
        syncer.builder.node_index[name], 0] == pytest.approx(0.0)


def test_estimation_survives_watch_catchup():
    """When the watch delivers the bound pod the capacity charge moves
    to the watched object, but the recently-assigned ESTIMATION entry
    must survive for the report-interval window (podAssignCache,
    load_aware.go:260-267) and then age out."""
    hub, store, syncer, service = _assume_wiring()
    hub.upsert_node(mk_node("n0"))
    hub.set_node_metric(mk_metric("n0"))
    assert syncer.sync(now=NOW) == "full"
    pod, res, ni, name = _place_one(hub, syncer, service)
    entry, _ = hub._assumed["pp"]
    hub._assumed["pp"] = (entry, NOW)
    bound = api.Pod(meta=api.ObjectMeta(name="pp", uid="pp"),
                    priority=9500, node_name=name, phase="Pending",
                    requests={RK.CPU: 4000.0, RK.MEMORY: 4096.0})
    hub.upsert_pod(bound)
    assert hub.assumed_entries() == []
    assert len(hub.estimation_entries()) == 1
    assert syncer.sync(now=NOW + 10) == "full"
    est = np.asarray(store.current().nodes.assigned_estimated)
    assert est[syncer.builder.node_index[name], 0] > 0
    # the estimation window closes after estimation_ttl
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="q")))
    assert syncer.sync(now=NOW + 500) == "full"
    assert hub.estimation_entries() == []


def test_reservation_owner_update_retires_assumed_consumer():
    """A Reservation CR update whose current_owners lists an assumed
    consumer retires the assume entry — the hold is never charged for
    the same consumer twice."""
    hub = ClusterInformerHub()
    consumer = api.Pod(meta=api.ObjectMeta(name="c", uid="c-uid"),
                       node_name="n0", reservation_name="resv",
                       requests={RK.CPU: 1000.0})
    hub.note_assumed(consumer, timestamp=NOW)
    assert len(hub.assumed_entries()) == 1
    hub.upsert_reservation(api.Reservation(
        meta=api.ObjectMeta(name="resv"), node_name="n0",
        phase="Available", requests={RK.CPU: 4000.0},
        allocated={RK.CPU: 1000.0}, current_owners=("c-uid",)))
    assert hub.assumed_entries() == []
    assert len(hub.estimation_entries()) == 1  # estimation window stays


def test_assumed_consumer_of_retired_reservation_charges_node():
    """An assumed consumer whose reservation is no longer Available (or
    already lists it in current_owners) has no hold absorbing its
    charge — it must hit node requested like any assumed pod."""
    b = SnapshotBuilder(max_nodes=2)
    b.add_node(mk_node("n0"))
    b.add_reservation(api.Reservation(
        meta=api.ObjectMeta(name="resv"), node_name="n0",
        phase="Succeeded", requests={RK.CPU: 4000.0}))
    consumer = api.Pod(meta=api.ObjectMeta(name="c", uid="c"),
                       node_name="n0", reservation_name="resv",
                       requests={RK.CPU: 1000.0})
    b.set_assumed_pods([(consumer, NOW)])
    snap, _ = b.build(now=NOW)
    # Succeeded reservation charges nothing; the consumer must
    assert np.asarray(snap.nodes.requested)[0, 0] == 1000.0

    # Available + current_owners: the CR's allocated carries the share,
    # the consumer charges requested like a running consumer would
    b2 = SnapshotBuilder(max_nodes=2)
    b2.add_node(mk_node("n0"))
    b2.add_reservation(api.Reservation(
        meta=api.ObjectMeta(name="resv"), node_name="n0",
        phase="Available", requests={RK.CPU: 4000.0},
        allocated={RK.CPU: 1000.0}, current_owners=("c",)))
    b2.set_assumed_pods([(consumer, NOW)])
    snap2, _ = b2.build(now=NOW)
    # consumer 1000 + remaining hold 3000 = full reservation footprint
    assert np.asarray(snap2.nodes.requested)[0, 0] == 4000.0

    # Available, NOT yet accounted: hold absorbs it — requested stays
    # the full reservation, free drops by the consumer
    b3 = SnapshotBuilder(max_nodes=2)
    b3.add_node(mk_node("n0"))
    b3.add_reservation(api.Reservation(
        meta=api.ObjectMeta(name="resv"), node_name="n0",
        phase="Available", requests={RK.CPU: 4000.0}))
    b3.set_assumed_pods([(consumer, NOW)])
    snap3, _ = b3.build(now=NOW)
    assert np.asarray(snap3.nodes.requested)[0, 0] == 4000.0
    assert np.asarray(snap3.reservations.free)[0, 0] == 3000.0
