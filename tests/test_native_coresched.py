"""Core-scheduling prctl shim + cookie manager.

The native path (prctl PR_SCHED_CORE, core_sched_linux.go:40-176) needs a
kernel with CONFIG_SCHED_CORE — skip-guarded. The NativeCoreSched cookie
manager's group/reference-pid logic is hermetic via an injected fake ops
object and the FakeHost cgroup tree.
"""

import subprocess
import sys

import pytest

from koordinator_tpu import native
from koordinator_tpu.koordlet.runtimehooks import NativeCoreSched
from koordinator_tpu.koordlet.testing import FakeHost


def test_shim_builds_and_loads():
    subprocess.run(["make", "-C", "koordinator_tpu/native", "-s"],
                   check=True, timeout=120)
    # loading must succeed regardless of kernel support...
    native.CoreSched()
    # ...and the support probe must answer without raising
    assert native.core_sched_supported() in (True, False)


def test_real_cookie_roundtrip_in_subprocess():
    """CREATE then GET on a scratch process: cookie becomes nonzero.
    Runs in a child so the test runner never carries a cookie itself."""
    if not native.core_sched_supported():
        pytest.skip("kernel lacks PR_SCHED_CORE")
    code = (
        "from koordinator_tpu import native\n"
        "cs = native.CoreSched()\n"
        "assert cs.get(0) == 0\n"
        "cs.create(0)\n"
        "assert cs.get(0) != 0\n"
        "print('COOKIE_OK')\n"
    )
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=60)
    assert "COOKIE_OK" in out.stdout, out.stderr


class FakeOps:
    """Records prctl verbs; cookies modeled as group ints."""

    def __init__(self):
        self.cookies = {}          # pid -> cookie
        self.next_cookie = 1
        self.calls = []
        self.dead = set()

    def get(self, pid):
        if pid in self.dead:
            raise OSError(3, "No such process")
        return self.cookies.get(pid, 0)

    def create(self, pid, scope=native.SCOPE_PROCESS):
        if pid in self.dead:
            raise OSError(3, "No such process")
        self.calls.append(("create", pid))
        self.cookies[pid] = self.next_cookie
        self.next_cookie += 1

    def assign(self, pid_from, pids_to, scope=native.SCOPE_PROCESS):
        if pid_from in self.dead:
            raise OSError(3, "No such process")
        self.calls.append(("assign", pid_from, tuple(pids_to)))
        failed = []
        for p in pids_to:
            if p in self.dead:
                failed.append(p)
            else:
                self.cookies[p] = self.cookies.get(pid_from, 0)
        return tuple(failed)


@pytest.fixture
def host(tmp_path):
    return FakeHost(str(tmp_path))


def _pod_cgroup(host, name, pids):
    d = f"kubepods/besteffort/pod{name}"
    host.make_cgroup(d)
    ctr = d + "/ctr0"
    host.make_cgroup(ctr)
    host.set_cgroup_procs(ctr, pids)
    return d


def test_group_shares_one_cookie_across_pods(host):
    ops = FakeOps()
    cs = NativeCoreSched(host, ops)
    d1 = _pod_cgroup(host, "a", [100, 101])
    d2 = _pod_cgroup(host, "b", [200])

    cs.assign_cookie(d1, "qos/BE")
    cs.assign_cookie(d2, "qos/BE")
    # one CREATE for the group; second pod got the same cookie via assign
    assert [c for c in ops.calls if c[0] == "create"] == [("create", 100)]
    assert ops.cookies[100] == ops.cookies[101] == ops.cookies[200] == 1


def test_distinct_groups_get_distinct_cookies(host):
    ops = FakeOps()
    cs = NativeCoreSched(host, ops)
    d1 = _pod_cgroup(host, "a", [100])
    d2 = _pod_cgroup(host, "b", [200])
    cs.assign_cookie(d1, "qos/BE")
    cs.assign_cookie(d2, "qos/LS")
    assert ops.cookies[100] != ops.cookies[200]


def test_dead_reference_pid_rekeys_group(host):
    ops = FakeOps()
    cs = NativeCoreSched(host, ops)
    d1 = _pod_cgroup(host, "a", [100])
    cs.assign_cookie(d1, "qos/BE")
    assert ops.cookies[100] == 1

    # reference pid 100 dies; a new pod arrives in the group
    ops.dead.add(100)
    d2 = _pod_cgroup(host, "b", [200, 201])
    cs.assign_cookie(d2, "qos/BE")
    # re-keyed: fresh cookie created on the new pod's first pid
    assert ops.cookies[200] == ops.cookies[201] == 2
    assert cs._group_ref["qos/BE"] == (200, 2)


def test_recycled_reference_pid_does_not_leak_foreign_cookie(host):
    """If the dead reference pid's number is reused by a process holding a
    DIFFERENT cookie (e.g. another group's pod), the manager must re-key
    rather than stamp the foreign cookie onto this group."""
    ops = FakeOps()
    cs = NativeCoreSched(host, ops)
    d_be = _pod_cgroup(host, "be", [100])
    d_ls = _pod_cgroup(host, "ls", [300])
    cs.assign_cookie(d_be, "qos/BE")   # cookie 1 on pid 100
    cs.assign_cookie(d_ls, "qos/LS")   # cookie 2 on pid 300

    # pid 100 dies and is recycled by a process in the LS group
    ops.cookies[100] = ops.cookies[300]
    d_be2 = _pod_cgroup(host, "be2", [150])
    cs.assign_cookie(d_be2, "qos/BE")
    # BE re-keyed with a fresh cookie — NOT the LS cookie
    assert ops.cookies[150] not in (ops.cookies[300], 0)
    assert cs._group_ref["qos/BE"] == (150, ops.cookies[150])


def test_empty_cgroup_is_a_noop(host):
    ops = FakeOps()
    cs = NativeCoreSched(host, ops)
    d = f"kubepods/besteffort/podempty"
    host.make_cgroup(d)
    host.set_cgroup_procs(d, [])
    cs.assign_cookie(d, "qos/BE")
    assert ops.calls == []
