"""End-to-end colocation suite — the hermetic analogue of the reference's
kind-cluster e2e (test/e2e/scheduling + slocontroller, SURVEY.md 4): every
component cooperates across one story, with the fake host FS standing in
for the kernel and the virtual CPU mesh for multi-chip.

Story: raw pods are admitted and mutated into BE batch pods; a quota
profile provisions the tree; the TPU scheduler places the workload
(including a NUMA-bound multi-GPU trainer) against overcommitted batch
capacity computed by the slo-controller from koordlet's NodeMetric; bind
annotations flow through the runtime proxy into cgroup writes on the fake
host; a hot node is rebalanced through the descheduler's
reservation-first migration.
"""

import json

import numpy as np
import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import (
    LABEL_POD_QOS,
    QoSClass,
    ResourceKind as RK,
)
from koordinator_tpu.descheduler import (
    LowNodeLoad,
    LowNodeLoadArgs,
    MigrationController,
    MigrationControllerArgs,
    RecordingEvictor,
)
from koordinator_tpu.koordlet.agent import Daemon, DaemonConfig
from koordinator_tpu.koordlet.statesinformer import PodMeta
from koordinator_tpu.koordlet.testing import FakeHost
from koordinator_tpu.quota_controller import QuotaProfileReconciler
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.bind import (
    device_allocation_annotation,
    resource_status_annotation,
)
from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.slo_controller.noderesource import (
    NodeResourceController,
)
from koordinator_tpu.snapshot import SnapshotBuilder
from koordinator_tpu.webhook import PodMutator, QuotaTopology, validate_pod


def mk_nodes(n=4, cpu=64000.0, mem=256 * 1024.0):
    return [api.Node(meta=api.ObjectMeta(name=f"n{i}", labels={"pool": "colo"}),
                     allocatable={RK.CPU: cpu, RK.MEMORY: mem})
            for i in range(n)]


def fresh_metric(name, cpu_used, mem_used, pods=()):
    return api.NodeMetric(node_name=name, update_time=1e9,
                          node_usage={RK.CPU: cpu_used, RK.MEMORY: mem_used},
                          pods_metric=list(pods))


def test_colocation_pipeline_admission_to_batch_capacity():
    """webhook -> quota tree -> slo-controller overcommit -> TPU placement
    of BE pods on batch resources."""
    nodes = mk_nodes()
    # slo-controller: NodeMetric usage -> batch-cpu/batch-memory allocatable
    from koordinator_tpu.slo_controller.noderesource import build_inputs

    ctl = NodeResourceController()
    metrics = {n.meta.name: fresh_metric(n.meta.name, 8000.0, 32 * 1024.0)
               for n in nodes}
    out = ctl.reconcile(build_inputs(nodes, metrics, {}, now=1e9))
    assert out["sync_mask"].all()
    for i, n in enumerate(nodes):
        assert out["batch"][i, 0] > 0
        n.allocatable[RK.BATCH_CPU] = float(out["batch"][i, 0])
        n.allocatable[RK.BATCH_MEMORY] = float(out["batch"][i, 1])

    # quota tree from a profile over the pool
    topo = QuotaTopology()
    root = QuotaProfileReconciler(topo).reconcile(
        api.ElasticQuotaProfile(meta=api.ObjectMeta(name="colo"),
                                quota_name="colo-root",
                                node_selector={"pool": "colo"}),
        nodes)
    assert root.min[RK.CPU] == sum(n.allocatable[RK.CPU] for n in nodes)

    # admission: mutate raw spark pods into BE batch pods
    mutator = PodMutator(
        [api.ClusterColocationProfile(
            meta=api.ObjectMeta(name="colo"), selector={"app": "spark"},
            qos_class="BE", priority_class_name="koord-batch")],
        priority_classes={"koord-batch": 5500})
    pods = []
    for j in range(32):
        p = api.Pod(meta=api.ObjectMeta(name=f"spark-{j}",
                                        labels={"app": "spark"}),
                    requests={RK.CPU: 4000.0, RK.MEMORY: 8192.0},
                    quota_name="colo-root")
        mutator.mutate(p)
        ok, errs = validate_pod(p)
        assert ok, errs
        assert p.qos is QoSClass.BE and RK.BATCH_CPU in p.requests
        pods.append(p)

    # schedule through the sidecar service
    b = SnapshotBuilder(max_nodes=4, max_quotas=4)
    for n in nodes:
        b.add_node(n)
    for m in metrics.values():
        b.set_node_metric(m)
    b.add_quota(root)
    snap, ctx = b.build(now=1e9)
    service = SchedulerService(num_rounds=3, k_choices=4)
    service.publish(snap)
    res = service.schedule(b.build_pod_batch(pods, ctx))
    a = np.asarray(res.assignment)
    assert (a >= 0).all(), "all BE pods place on batch capacity"
    req = np.asarray(res.snapshot.nodes.requested)
    alloc = np.asarray(res.snapshot.nodes.allocatable)
    assert (req <= alloc + 1.0).all()


def test_numa_gpu_trainer_to_cgroup_writes(tmp_path):
    """scheduler -> bind annotations -> koordlet reconciler -> cgroup
    files on the fake host."""
    b = SnapshotBuilder(max_nodes=2, max_gpu_inst=4)
    for i in range(2):
        b.add_node(api.Node(
            meta=api.ObjectMeta(name=f"n{i}"),
            allocatable={RK.CPU: 16000.0, RK.MEMORY: 64 * 1024.0},
            topology=api.NodeResourceTopology(node_name=f"n{i}", zones=[
                api.NUMAZone(8000.0, 32 * 1024.0),
                api.NUMAZone(8000.0, 32 * 1024.0)])))
        b.set_node_metric(fresh_metric(f"n{i}", 1000.0, 4096.0))
        b.add_device(api.Device(node_name=f"n{i}", devices=[
            api.DeviceInfo(minor=m, type="gpu",
                           resources={RK.GPU_CORE: 100.0,
                                      RK.GPU_MEMORY: 80 * 1024.0},
                           numa_node=m // 2)
            for m in range(4)]))
    trainer = api.Pod(
        meta=api.ObjectMeta(name="train", uid="u-train",
                            labels={LABEL_POD_QOS: "LSR"}),
        requests={RK.CPU: 4000.0, RK.MEMORY: 8192.0, RK.GPU_CORE: 200.0},
        priority=9100, qos_label="LSR", gpu_memory_ratio=200.0,
        required_cpu_bind=True)
    snap, ctx = b.build(now=1e9)
    res = core.schedule_batch(snap, b.build_pod_batch([trainer], ctx),
                              LoadAwareConfig.make())
    node = int(np.asarray(res.assignment)[0])
    assert node >= 0
    zone = int(np.asarray(res.numa_zone)[0])
    assert zone >= 0
    trainer.meta.annotations.update(
        resource_status_annotation(res, 0))
    trainer.meta.annotations.update(device_allocation_annotation(
        snap, b.build_pod_batch([trainer], ctx), res, 0))

    # the node agent levels the pod's cgroup from the annotations
    host = FakeHost(str(tmp_path))
    daemon = Daemon(host, DaemonConfig())
    meta = PodMeta(pod=trainer)
    host.make_cgroup(meta.cgroup_dir)
    daemon.informer.set_pods([meta])
    daemon.tick(now=10)  # past the QoS interval so the reconciler runs
    minors = [d["minor"] for d in json.loads(
        trainer.meta.annotations[
            "scheduling.koordinator.sh/device-allocated"])["gpu"]]
    assert all(m // 2 == zone for m in minors)
    # LSR group identity reached the cgroup
    assert host.read_cgroup(meta.cgroup_dir, "cpu.bvt_warp_ns") == "2"
    # zone binding reached cpuset.mems
    assert host.read_cgroup(meta.cgroup_dir, "cpuset.mems") == str(zone)


def test_rebalance_loop_hot_node_to_migration():
    """NodeMetric hot node -> LowNodeLoad victims -> reservation-first
    migration with replacement scheduled by the TPU core."""
    nodes = mk_nodes(4, cpu=32000.0, mem=64 * 1024.0)
    running = [api.Pod(meta=api.ObjectMeta(name=f"r{i}"),
                       requests={RK.CPU: 6000.0, RK.MEMORY: 4096.0},
                       priority=9100, node_name="n0",
                       owner_workload="default/rs", workload_replicas=10)
               for i in range(4)]
    metrics = {"n0": fresh_metric(
        "n0", 28000.0, 20000.0,
        pods=[api.PodMetricInfo(namespace="default", name=p.meta.name,
                                usage={RK.CPU: 6500.0, RK.MEMORY: 4096.0})
              for p in running])}
    for i in range(1, 4):
        metrics[f"n{i}"] = fresh_metric(f"n{i}", 2000.0, 4000.0)

    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1,
                                         dry_run=True))
    victims = plugin.balance_once(nodes, metrics, {"n0": running}, now=1e9)
    assert victims

    ev = RecordingEvictor()
    directory = {p.meta.namespaced_name: p for p in running}
    ready = {}

    def reserve(pod):
        b = SnapshotBuilder(max_nodes=4)
        for nd in nodes:
            b.add_node(nd)
        for m in metrics.values():
            b.set_node_metric(m)
        for p in running:
            b.add_running_pod(p)
        snap, ctx = b.build(now=1e9)
        rp = api.Pod(meta=api.ObjectMeta(name=f"resv-{pod.meta.name}"),
                     requests=dict(pod.requests), priority=9100)
        r = core.schedule_batch(snap, b.build_pod_batch([rp], ctx),
                                LoadAwareConfig.make())
        assert int(np.asarray(r.assignment)[0]) >= 1  # off the hot node
        ready[rp.meta.name] = True
        return rp.meta.name

    mc = MigrationController(
        ev, MigrationControllerArgs(max_migrating_per_node=None),
        reserve=reserve, reservation_available=ready.get,
        get_pod=directory.get)
    for v in victims:
        mc.submit_for_pod(v, "hot node", now=0.0)
    for r in range(1, 8):
        mc.reconcile_once(now=float(r))
        if all(j.phase in ("Succeeded", "Failed")
               for j in mc.jobs.values()):
            break
    assert len(ev.evictions) == len(victims)
    assert all(j.phase == "Succeeded" for j in mc.jobs.values())


def test_full_loop_agent_to_scheduled_pod(tmp_path):
    """The complete plane: koordlet measures the REAL (fake-FS) kernel ->
    NodeMetric -> informer hub -> manager computes batch overcommit ->
    syncer publishes the device snapshot -> a BE pod schedules onto
    capacity that exists only because the agent reported low usage."""
    import time as _time

    from koordinator_tpu.cmd import manager as cmd_manager
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )

    now = _time.time()
    # 1. the agent samples the kernel and reports a NodeMetric
    host = FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)
    daemon = Daemon(host, DaemonConfig(report_interval_seconds=10.0))
    node = api.Node(meta=api.ObjectMeta(name="n0", labels={"pool": "colo"}),
                    allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})
    daemon.informer.set_node(node)
    daemon.tick(now=now)
    host.advance_cpu(busy_ticks=2000, idle_ticks=6000)  # 2 of 8 cores busy
    host.set_meminfo(available=12 << 30)
    nm = daemon.tick(now=now + 15)
    assert nm is not None and nm.node_usage[RK.CPU] > 0

    # 2. the edge feeds the hub; the manager computes batch capacity
    hub = ClusterInformerHub()
    hub.upsert_node(node)
    nm.update_time = now + 15
    hub.set_node_metric(nm)
    mgr = cmd_manager.ManagerProcess(
        cmd_manager.ManagerConfig(lease_file=str(tmp_path / "m.lease")),
        hub)
    mgr.tick(now=now + 15)
    assert node.allocatable[RK.BATCH_CPU] > 0
    hub.upsert_node(node)  # batch capacity republished

    # 3. the syncer publishes the device snapshot; a BE pod schedules
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=2)
    assert syncer.sync(now=now + 15) == "full"
    service = SchedulerService(store=store)
    syncer.register_services(service.registry)
    assert "elasticquota" in service.registry.names()
    be = api.Pod(meta=api.ObjectMeta(name="spark-0"), qos_label="BE",
                 priority=5500,
                 requests={RK.BATCH_CPU: 1000.0, RK.BATCH_MEMORY: 512.0})
    batch = syncer.builder.build_pod_batch([be], syncer.ctx)
    res = service.schedule(batch, typed_pods=[be])
    assert int(np.asarray(res.assignment)[0]) == 0, \
        "BE pod must land on the overcommitted capacity the agent enabled"


def test_e2e_preemption_nominates_and_places(tmp_path):
    """Unschedulable prod pod -> error chain -> preemption nomination
    from the hub's cluster view -> victims evicted -> next sync places
    the preemptor on the nominated node."""
    import time as _time

    from koordinator_tpu.scheduler.errorhandler import (
        make_preemption_post_filter,
    )
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )

    now = _time.time()
    hub = ClusterInformerHub()
    node = api.Node(meta=api.ObjectMeta(name="n0"),
                    allocatable={RK.CPU: 8000.0, RK.MEMORY: 16384.0})
    hub.upsert_node(node)
    hub.set_node_metric(api.NodeMetric(node_name="n0", update_time=now,
                                       node_usage={}))
    be = api.Pod(meta=api.ObjectMeta(name="be-0", uid="be-0"),
                 priority=5000, phase="Running", node_name="n0",
                 requests={RK.CPU: 6000.0, RK.MEMORY: 512.0})
    hub.upsert_pod(be)
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=1)
    syncer.sync(now=now)
    service = SchedulerService(store=store)
    nominations = []
    service.error_dispatcher.register(post=make_preemption_post_filter(
        lambda: hub.read_all()["nodes"],
        lambda: hub.read_all()["pods_by_node"],
        lambda pod, nom: nominations.append((pod, nom)),
        get_devices=hub.devices_by_node))

    prod = api.Pod(meta=api.ObjectMeta(name="prod-0"), priority=9500,
                   requests={RK.CPU: 5000.0, RK.MEMORY: 512.0})
    batch = syncer.builder.build_pod_batch([prod], syncer.ctx)
    res = service.schedule(batch, typed_pods=[prod])
    assert int(np.asarray(res.assignment)[0]) == -1
    assert len(nominations) == 1
    pod, nom = nominations[0]
    assert nom.node_name == "n0"
    # the eviction edge removes the victims; next sync frees the capacity
    for v in nom.victims:
        hub.delete_pod(v.meta.uid)
    assert syncer.sync(now=now + 1) == "full"
    batch2 = syncer.builder.build_pod_batch([prod], syncer.ctx)
    res2 = service.schedule(batch2, typed_pods=[prod])
    assert int(np.asarray(res2.assignment)[0]) == 0


def test_e2e_scale_up_under_pressure_then_device_rebalance():
    """Round-4 story: a full cluster rejects incoming prod pods; the
    autoscaler's scale-up arrives as an O(K) topology ingest (no
    rebuild) and the retried pods land on the new capacity; the
    DEVICE LowNodeLoad plan then rebalances the original hot node
    through reservation-first migration."""
    from koordinator_tpu.descheduler import DeviceLowNodeLoad
    from koordinator_tpu.snapshot import SnapshotStore
    from koordinator_tpu.snapshot.informers import (
        ClusterInformerHub,
        SnapshotSyncer,
    )

    now = 1e9
    hub, store = ClusterInformerHub(), SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=4, delta_pad=2)
    service = SchedulerService(store=store, num_rounds=2, k_choices=2)

    # a small full cluster: one node, mostly used
    hub.upsert_node(api.Node(meta=api.ObjectMeta(name="n0"),
                             allocatable={RK.CPU: 16000.0,
                                          RK.MEMORY: 32768.0}))
    hub.set_node_metric(fresh_metric("n0", 14000.0, 24000.0))
    assert syncer.sync(now=now) == "full"

    wave = [api.Pod(meta=api.ObjectMeta(name=f"w{j}"), priority=9000,
                    requests={RK.CPU: 8000.0, RK.MEMORY: 8192.0})
            for j in range(4)]
    res = service.schedule(syncer.builder.build_pod_batch(
        wave, syncer.ctx, max_pods=4))
    a1 = np.asarray(res.assignment)
    unplaced = [wave[j] for j in range(4) if a1[j] < 0]
    assert len(unplaced) >= 3  # the cluster is genuinely full

    # scale-up: two big nodes arrive -> O(K) topology ingest, NOT a
    # rebuild; the retried pods land on the fresh capacity
    for name in ("big0", "big1"):
        hub.upsert_node(api.Node(meta=api.ObjectMeta(name=name),
                                 allocatable={RK.CPU: 64000.0,
                                              RK.MEMORY: 131072.0}))
    assert syncer.sync(now=now) == "topology"
    assert syncer.full_rebuilds == 1
    res2 = service.schedule(syncer.builder.build_pod_batch(
        unplaced, syncer.ctx, max_pods=4))
    a2 = np.asarray(res2.assignment)[:len(unplaced)]
    big = {syncer.builder.node_index["big0"],
           syncer.builder.node_index["big1"]}
    assert (a2 >= 0).all() and set(a2.tolist()) <= big

    # the hot node rebalances via the DEVICE plan -> migration evicts
    running = [api.Pod(meta=api.ObjectMeta(name=f"r{i}", uid=f"r{i}"),
                       requests={RK.CPU: 3000.0, RK.MEMORY: 4096.0},
                       priority=9100, node_name="n0",
                       owner_workload="default/rs", workload_replicas=10)
               for i in range(4)]
    metrics = {
        "n0": fresh_metric("n0", 15000.0, 26000.0,
                           pods=[api.PodMetricInfo(
                               namespace="default", name=p.meta.name,
                               usage={RK.CPU: 3500.0, RK.MEMORY: 4096.0})
                               for p in running]),
        "big0": fresh_metric("big0", 6000.0, 16000.0),
        "big1": fresh_metric("big1", 6000.0, 16000.0),
    }
    nodes_t = [hub.get_node(n) for n in ("n0", "big0", "big1")]
    plugin = DeviceLowNodeLoad(
        LowNodeLoadArgs(consecutive_abnormalities=1, dry_run=True))
    victims = plugin.balance_once(nodes_t, metrics, {"n0": running},
                                  now=now)
    assert victims  # the hot node sheds load through the device plan

    ev = RecordingEvictor()
    directory = {p.meta.namespaced_name: p for p in running}
    ready = {}

    def reserve(pod):
        rp = api.Pod(meta=api.ObjectMeta(name=f"resv-{pod.meta.name}"),
                     requests=dict(pod.requests), priority=9100)
        r = service.schedule(syncer.builder.build_pod_batch(
            [rp], syncer.ctx, max_pods=4))
        tgt = int(np.asarray(r.assignment)[0])
        assert tgt in big  # replacement capacity off the hot node
        ready[rp.meta.name] = True
        return rp.meta.name

    mc = MigrationController(
        ev, MigrationControllerArgs(max_migrating_per_node=None),
        reserve=reserve, reservation_available=ready.get,
        get_pod=directory.get)
    for v in victims:
        mc.submit_for_pod(v, "hot node", now=0.0)
    for r in range(1, 8):
        mc.reconcile_once(now=float(r))
        if all(j.phase in ("Succeeded", "Failed")
               for j in mc.jobs.values()):
            break
    assert len(ev.evictions) == len(victims)
    assert all(j.phase == "Succeeded" for j in mc.jobs.values())


def test_e2e_gpu_preemption_respects_surviving_instances():
    """GPU preemption with the DEFAULT device wiring
    (SnapshotSyncer.register_preemption): a node whose surviving GPU
    instances cannot host the preemptor is never nominated, even when
    its flat aggregate capacity and a cheap victim would pass the
    coarse math (upstream selectVictimsOnNode re-runs the full Filter;
    /root/reference/pkg/scheduler/plugins/elasticquota/preempt.go)."""
    import time as _time

    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )

    now = _time.time()
    hub = ClusterInformerHub()
    for name in ("gA", "gB"):
        hub.upsert_node(api.Node(meta=api.ObjectMeta(name=name),
                                 allocatable={RK.CPU: 32000.0,
                                              RK.MEMORY: 65536.0}))
        hub.set_node_metric(api.NodeMetric(node_name=name,
                                           update_time=now,
                                           node_usage={}))
        hub.set_device(api.Device(node_name=name, devices=[
            api.DeviceInfo(minor=m, type="gpu",
                           resources={RK.GPU_CORE: 100.0,
                                      RK.GPU_MEMORY: 16000.0})
            for m in range(2)]))
    # gA: two HIGH-priority GPU pods at 50% of EACH instance — flat
    # free is a whole GPU but no single instance can host one — plus a
    # cheap low-priority CPU victim whose eviction frees no GPU
    for m in range(2):
        hub.upsert_pod(api.Pod(
            meta=api.ObjectMeta(name=f"hi{m}", uid=f"hi{m}"),
            priority=9900, phase="Running", node_name="gA",
            requests={RK.GPU_CORE: 50.0, RK.GPU_MEMORY: 8000.0},
            allocated_gpu_minors=(m,)))
    hub.upsert_pod(api.Pod(
        meta=api.ObjectMeta(name="cheap", uid="cheap"),
        priority=5000, phase="Running", node_name="gA",
        requests={RK.CPU: 2000.0, RK.MEMORY: 1024.0}))
    # gB: two LOW-priority GPU pods fully holding one instance each —
    # evicting one frees a whole GPU
    for m in range(2):
        hub.upsert_pod(api.Pod(
            meta=api.ObjectMeta(name=f"lo{m}", uid=f"lo{m}"),
            priority=5000, phase="Running", node_name="gB",
            requests={RK.GPU_CORE: 100.0, RK.GPU_MEMORY: 16000.0},
            allocated_gpu_minors=(m,)))

    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=2, max_gpu_inst=2)
    syncer.sync(now=now)
    service = SchedulerService(store=store, enable_devices=True)
    syncer.attach_scheduler(service)
    nominations = []
    syncer.register_preemption(
        service, lambda pod, nom: nominations.append((pod, nom)))

    preemptor = api.Pod(meta=api.ObjectMeta(name="train", uid="train"),
                        priority=9500,
                        requests={RK.CPU: 1000.0, RK.MEMORY: 1024.0,
                                  RK.GPU_CORE: 100.0,
                                  RK.GPU_MEMORY: 16000.0})
    batch = syncer.builder.build_pod_batch([preemptor], syncer.ctx)
    res = service.schedule(batch, typed_pods=[preemptor])
    assert int(np.asarray(res.assignment)[0]) == -1  # no free instance
    assert len(nominations) == 1
    _, nom = nominations[0]
    # gA's surviving instances can never host a full GPU: the default
    # device wiring must reject it; gB frees one by evicting a lo pod
    assert nom.node_name == "gB"
    assert len(nom.victims) == 1
    assert nom.victims[0].meta.name.startswith("lo")

    # the handshake completes: evict the victim, resync, re-schedule
    hub.delete_pod(nom.victims[0].meta.uid)
    syncer.sync(now=now + 1)
    batch2 = syncer.builder.build_pod_batch([preemptor], syncer.ctx)
    res2 = service.schedule(batch2, typed_pods=[preemptor])
    assert int(np.asarray(res2.assignment)[0]) \
        == syncer.builder.node_index["gB"]


def test_e2e_service_path_carries_topology_counts_across_calls():
    """Cross-call topology counts on the SERVICE path (the bench
    threads counts explicitly through its scan carry; the service flow
    relies on the builder recomputing count0 from running + ASSUMED
    pods — core.py's cross-batch count contract). One spread group and
    one anti group scheduled across SEPARATE SchedulerService.schedule
    calls must see every earlier call's assumes in their counts, and
    the final placement must equal the single-run sequential oracle."""
    from koordinator_tpu.scheduler.frameworkext import SchedulerService
    from koordinator_tpu.snapshot import (
        ClusterInformerHub,
        SnapshotStore,
        SnapshotSyncer,
    )
    from koordinator_tpu.snapshot.builder import SnapshotBuilder
    from oracle import OracleArgs, OracleScheduler, make_oracle_nodes

    now = 1e9
    zones = ["z0", "z0", "z1", "z1"]

    def make_nodes():
        return [api.Node(meta=api.ObjectMeta(
            name=f"n{i}", labels={"zone": z, "host": f"n{i}"}),
            allocatable={RK.CPU: 16000.0 + i * 1000.0,
                         RK.MEMORY: 65536.0})
            for i, z in enumerate(zones)]

    spread = api.TopologySpreadConstraint(
        max_skew=1, topology_key="zone", label_selector={"app": "web"})
    anti = api.PodAffinityTerm(topology_key="host",
                               label_selector={"app": "kv"}, anti=True)
    pods = []
    for j in range(6):
        prio = 9300 - j * 10
        cpu = 900.0 + j * 41.0
        if j % 2 == 0:
            pods.append(api.Pod(
                meta=api.ObjectMeta(name=f"web{j}", uid=f"web{j}",
                                    namespace="d",
                                    labels={"app": "web"}),
                priority=prio, requests={RK.CPU: cpu},
                spread_constraints=[spread]))
        else:
            pods.append(api.Pod(
                meta=api.ObjectMeta(name=f"kv{j}", uid=f"kv{j}",
                                    namespace="d", labels={"app": "kv"}),
                priority=prio, requests={RK.CPU: cpu},
                pod_affinity=[anti]))

    # oracle: all six sequentially in one run
    ob = SnapshotBuilder(max_nodes=4)
    for n in make_nodes():
        ob.add_node(n)
        ob.set_node_metric(api.NodeMetric(node_name=n.meta.name,
                                          update_time=now, node_usage={}))
    oracle = OracleScheduler(make_oracle_nodes(ob, now=now),
                             OracleArgs.default())
    want = oracle.schedule(pods)
    assert (want >= 0).all()

    # service path: one schedule() call per pod, no manual count
    # threading — the assume cache carries the counts between calls
    hub, store = ClusterInformerHub(), SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=4)
    service = SchedulerService(store=store, num_rounds=2, k_choices=2)
    syncer.attach_scheduler(service)
    for n in make_nodes():
        hub.upsert_node(n)
        hub.set_node_metric(api.NodeMetric(node_name=n.meta.name,
                                           update_time=now,
                                           node_usage={}))
    assert syncer.sync(now=now) == "full"
    got = []
    for j, pod in enumerate(pods):
        batch = syncer.build_pod_batch([pod])
        if j == 4:
            # the last WEB call must see both earlier web assumes in
            # its spread counts (a group only materializes in batches
            # whose pods carry it — kv batches compile the gate out)
            assert float(np.asarray(batch.spread_count0).sum()) == 2.0
        if j == 5:
            # the last KV call must see both earlier kv carriers
            assert float(
                np.asarray(batch.anti_carrier_count0).sum()) == 2.0
        res = service.schedule(batch, typed_pods=[pod])
        got.append(int(np.asarray(res.assignment)[0]))
    assert got == [int(a) for a in want]
    # the constraints held: kv pods on distinct hosts, web zone skew <= 1
    kv_nodes = [got[j] for j in (1, 3, 5)]
    assert len(set(kv_nodes)) == 3
    web_zones = [zones[got[j]] for j in (0, 2, 4)]
    assert abs(web_zones.count("z0") - web_zones.count("z1")) <= 1
