"""koordlint test battery: framework behavior (baseline, runner exit
codes, proto stamping) plus one positive and one negative fixture tree
per analyzer (tests/fixtures/lint/).

The linter is stdlib-only, so everything here runs without touching the
device runtime; the repo-wide gate test shells out exactly the way CI
does (`python -m tools.lint`).
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from tools.lint.framework import Baseline, Project
from tools.lint.runner import REPO_ROOT, run_lint

FIXTURES = os.path.join(REPO_ROOT, "tests", "fixtures", "lint")


def fixture_findings(analyzer: str, tree: str, empty_baseline):
    root = os.path.join(FIXTURES, analyzer.replace("-", "_"), tree)
    assert os.path.isdir(root), f"missing fixture tree {root}"
    new, suppressed = run_lint(root, analyzers=[_name(analyzer)],
                               baseline_path=str(empty_baseline))
    assert not suppressed
    return new


_ANALYZER_NAMES = {
    "determinism": "determinism",
    "host_sync": "host-sync-in-jit",
    "recompile": "recompilation-hazard",
    "donation": "donation-aliasing",
    "lock_discipline": "lock-discipline",
    "metric_names": "metric-registry",
    "proto_drift": "proto-drift",
    "race": "race-guard",
    "robustness": "robustness",
    "shape_contract": "shape-contract",
    "tail_readback": "tail-readback",
    "pad_soundness": "pad-soundness",
    "trace_phases": "trace-phases",
}


def _name(fixture_dir: str) -> str:
    return _ANALYZER_NAMES[fixture_dir.replace("-", "_")]


@pytest.fixture()
def empty_baseline(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text('{"suppressions": []}')
    return p


# --- per-analyzer positive/negative cases --------------------------------

@pytest.mark.parametrize("fixture_dir,expected_codes", [
    ("host_sync", {"HS001", "HS002", "HS003", "HS004", "HS005"}),
    ("recompile", {"RC001", "RC002", "RC003", "RC004", "RC005"}),
    ("donation", {"DA001"}),
    ("lock_discipline", {"LK001", "LK002", "LK003", "LK004", "LK005"}),
    ("metric_names", {"MN001", "MN002", "MN003", "MN004"}),
    ("proto_drift", {"PD001", "PD002", "PD003"}),
    ("race", {"GB001", "GB002", "GB003", "GB004", "GB005"}),
    ("robustness", {"RB001"}),
    ("shape_contract", {"SH001", "SH002", "SH003", "SH004", "SH005"}),
    ("tail_readback", {"HS006"}),
    ("pad_soundness", {"PS001", "PS002", "PS003", "PS004", "PS005"}),
    ("determinism", {"ND001"}),
    ("trace_phases", {"OB001"}),
])
def test_positive_fixture(fixture_dir, expected_codes, empty_baseline):
    findings = fixture_findings(fixture_dir, "pos", empty_baseline)
    got = {f.code for f in findings}
    assert expected_codes <= got, (
        f"{fixture_dir}/pos: expected codes {sorted(expected_codes)}, "
        f"got {sorted(got)}: {[f.render() for f in findings]}")


@pytest.mark.parametrize("fixture_dir", sorted(_ANALYZER_NAMES))
def test_negative_fixture(fixture_dir, empty_baseline):
    findings = fixture_findings(fixture_dir, "neg", empty_baseline)
    assert findings == [], \
        f"{fixture_dir}/neg should be clean: " \
        f"{[f.render() for f in findings]}"


# --- targeted analyzer behavior ------------------------------------------

def test_host_sync_reports_deep_callee_site(empty_baseline):
    findings = fixture_findings("host_sync", "pos", empty_baseline)
    items = [f for f in findings if f.code == "HS001"]
    assert items and all("deep" in f.key for f in items), \
        "the .item() sink sits two calls below the entry and must be " \
        "attributed to the function that contains it"


_TAIL_LOOP_SRC = (
    "import numpy as np\n"
    "\n"
    "def adaptive(step, snap, stats, budget):\n"
    "    left = 1\n"
    "    passes = 0\n"
    "    while passes < budget and left > 0:\n"
    "        snap, stats = retry_pass(step, snap)\n"
    "        left = int(np.asarray(stats)[0]){marker}\n"
    "        passes += 1\n"
    "    return snap\n"
    "\n"
    "def retry_pass(step, snap):\n"
    "    return step(snap)\n")


def test_tail_readback_inline_disable(tmp_path, empty_baseline):
    """`# koordlint: disable=HS006` on the finding's line suppresses it
    in place (the bench host-tail conformance oracle relies on this);
    the analyzer name works as the token too, and the marker only
    covers its OWN line."""
    (tmp_path / "m.py").write_text(_TAIL_LOOP_SRC.format(marker=""))
    new, _ = run_lint(str(tmp_path), analyzers=["tail-readback"],
                      baseline_path=str(empty_baseline))
    assert [f.code for f in new] == ["HS006"], [f.render() for f in new]

    for token in ("HS006", "tail-readback",
                  # trailing prose after the code must not defeat the
                  # marker (tokens split on whitespace AND commas)
                  "HS006 measured oracle"):
        (tmp_path / "m.py").write_text(_TAIL_LOOP_SRC.format(
            marker=f"  # koordlint: disable={token}"))
        new, suppressed = run_lint(str(tmp_path),
                                   analyzers=["tail-readback"],
                                   baseline_path=str(empty_baseline))
        assert new == [] and suppressed == [], \
            (token, [f.render() for f in new])

    # a marker on an UNRELATED line must not suppress the finding
    (tmp_path / "m.py").write_text(
        "# koordlint: disable=HS006\n" + _TAIL_LOOP_SRC.format(marker=""))
    new, _ = run_lint(str(tmp_path), analyzers=["tail-readback"],
                      baseline_path=str(empty_baseline))
    assert [f.code for f in new] == ["HS006"]


def test_disable_file_pragma_fixtures(empty_baseline):
    """`# koordlint: disable-file=CODE` on a comment line silences that
    code file-wide (neg tree); a marker naming a DIFFERENT code, or one
    hiding inside a string literal, silences nothing (pos tree)."""
    root = os.path.join(FIXTURES, "disable_file", "pos")
    new, _ = run_lint(root, analyzers=["tail-readback"],
                      baseline_path=str(empty_baseline))
    assert [f.code for f in new] == ["HS006"], \
        [f.render() for f in new]
    root = os.path.join(FIXTURES, "disable_file", "neg")
    new, suppressed = run_lint(root, analyzers=["tail-readback"],
                               baseline_path=str(empty_baseline))
    assert new == [] and suppressed == [], [f.render() for f in new]


def test_disable_file_accepts_analyzer_name(tmp_path, empty_baseline):
    """The analyzer name works as a file-level token too, from any
    comment line in the file (not just line 1)."""
    (tmp_path / "m.py").write_text(
        _TAIL_LOOP_SRC.format(marker="")
        + "\n# koordlint: disable-file=tail-readback\n")
    new, _ = run_lint(str(tmp_path), analyzers=["tail-readback"],
                      baseline_path=str(empty_baseline))
    assert new == [], [f.render() for f in new]


def test_tail_readback_ignores_plain_data_walks(tmp_path,
                                                empty_baseline):
    """np.asarray in a loop with no retry/tail vocabulary anywhere is
    an ordinary data walk, not the flagged bug class."""
    (tmp_path / "m.py").write_text(
        "import numpy as np\n"
        "\n"
        "def column_sums(rows):\n"
        "    out = []\n"
        "    for r in rows:\n"
        "        out.append(np.asarray(r).sum())\n"
        "    return out\n")
    new, _ = run_lint(str(tmp_path), analyzers=["tail-readback"],
                      baseline_path=str(empty_baseline))
    assert new == [], [f.render() for f in new]


def test_donation_loop_wraparound(empty_baseline):
    findings = fixture_findings("donation", "pos", empty_baseline)
    lines = {f.line for f in findings}
    assert len(findings) >= 2 and len(lines) >= 2, \
        "both the straight-line read and the loop re-donation must fire"


def test_donation_assignment_form_tracks_the_alias(tmp_path,
                                                   empty_baseline):
    """g = jax.jit(f, donate_argnums=...): donation belongs to calls
    through g; direct f(...) calls are plain and must not be flagged."""
    (tmp_path / "m.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "def sweep(state):\n"
        "    return state + 1\n"
        "\n"
        "sweep_d = jax.jit(sweep, donate_argnums=(0,))\n"
        "\n"
        "def plain(state):\n"
        "    out = sweep(state)\n"
        "    return out, jnp.sum(state)\n"   # fine: sweep doesn't donate
        "\n"
        "def donating(state):\n"
        "    out = sweep_d(state)\n"
        "    return out, jnp.sum(state)\n")  # DA001: read after donation
    new, _ = run_lint(str(tmp_path), analyzers=["donation-aliasing"],
                      baseline_path=str(empty_baseline))
    assert len(new) == 1 and "donating" in new[0].key, \
        [f.render() for f in new]


def test_donation_read_after_loop_exit(tmp_path, empty_baseline):
    """A rebind at loop top saves the next iteration but not the
    post-loop read of the LAST iteration's donated buffer."""
    (tmp_path / "m.py").write_text(
        "import functools\n"
        "import jax\n"
        "\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def step(state):\n"
        "    return state + 1\n"
        "\n"
        "def drive(batches, state):\n"
        "    for b in batches:\n"
        "        state = prep(b)\n"
        "        out = step(state)\n"
        "    return state\n"                # DA001: donated on loop exit
        "\n"
        "def prep(b):\n"
        "    return b\n")
    new, _ = run_lint(str(tmp_path), analyzers=["donation-aliasing"],
                      baseline_path=str(empty_baseline))
    assert len(new) == 1 and new[0].code == "DA001", \
        [f.render() for f in new]


def test_lock_cycle_reported_once(empty_baseline):
    findings = fixture_findings("lock_discipline", "pos", empty_baseline)
    cycles = [f for f in findings if f.code == "LK001"]
    assert len(cycles) == 1, [f.render() for f in cycles]
    assert "_a" in cycles[0].message and "_b" in cycles[0].message


def test_metric_duplicate_names_resolved_through_constants(empty_baseline):
    findings = fixture_findings("metric_names", "pos", empty_baseline)
    dups = [f for f in findings if f.code == "MN001"]
    assert len(dups) == 1 and "comp_good_total" in dups[0].message


# --- framework: baseline, fingerprints, runner ---------------------------

def test_baseline_suppresses_known_findings(tmp_path, empty_baseline):
    root = os.path.join(FIXTURES, "donation", "pos")
    new, _ = run_lint(root, analyzers=["donation-aliasing"],
                      baseline_path=str(empty_baseline))
    assert new
    bl = tmp_path / "frozen.json"
    Baseline(path=str(bl)).save(new)
    new2, suppressed = run_lint(root, analyzers=["donation-aliasing"],
                                baseline_path=str(bl))
    assert new2 == [] and len(suppressed) == len(new)


@pytest.mark.parametrize("fixture_dir", sorted(_ANALYZER_NAMES))
def test_fingerprints_stable_under_line_drift(fixture_dir, tmp_path,
                                              empty_baseline):
    """Every analyzer's fingerprints must survive unrelated line drift,
    or baselined findings resurface as CI-failing 'new' ones."""
    src = os.path.join(FIXTURES, fixture_dir, "pos")
    root = tmp_path / "tree"
    shutil.copytree(src, root)
    before, _ = run_lint(str(root), analyzers=[_name(fixture_dir)],
                         baseline_path=str(empty_baseline))
    for py in sorted(root.rglob("*.py")):
        py.write_text("# padding comment\n" * 7 + py.read_text())
    after, _ = run_lint(str(root), analyzers=[_name(fixture_dir)],
                        baseline_path=str(empty_baseline))
    assert {f.fingerprint for f in before} == \
        {f.fingerprint for f in after}, \
        "baseline fingerprints must not embed line numbers"


def test_parse_error_is_a_finding(tmp_path, empty_baseline):
    (tmp_path / "bad.py").write_text("def broken(:\n")
    new, _ = run_lint(str(tmp_path), analyzers=["proto-drift"],
                      baseline_path=str(empty_baseline))
    assert any(f.code == "KL000" for f in new)


def test_unknown_analyzer_rejected(empty_baseline):
    with pytest.raises(KeyError):
        run_lint(FIXTURES, analyzers=["no-such-pass"],
                 baseline_path=str(empty_baseline))


def test_fixture_trees_excluded_from_default_scan():
    project = Project(REPO_ROOT)
    assert not any(m.relpath.startswith("tests/fixtures/")
                   for m in project.modules), \
        "fixture violations must never count against the repo"


# --- the CI gate itself --------------------------------------------------

def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args],
        cwd=cwd, capture_output=True, text=True, timeout=600)


def test_cli_green_on_repo_with_empty_baseline():
    baseline = os.path.join(REPO_ROOT, "tools", "lint", "baseline.json")
    with open(baseline) as f:
        assert json.load(f)["suppressions"] == [], \
            "the lint must stay green with an EMPTY baseline"
    proc = _run_cli("-q")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_red_on_introduced_violation(tmp_path):
    root = tmp_path / "tree"
    shutil.copytree(os.path.join(FIXTURES, "host_sync", "pos"), root)
    bl = tmp_path / "b.json"
    bl.write_text('{"suppressions": []}')
    proc = _run_cli("--root", str(root), "--baseline", str(bl))
    assert proc.returncode == 1
    assert "HS00" in proc.stdout


def test_cli_stamp_protos_roundtrip(tmp_path):
    root = tmp_path / "tree"
    root.mkdir()
    proto = root / "x.proto"
    proto.write_text('syntax = "proto3";\nmessage X {}\n')
    pb2 = root / "x_pb2.py"
    pb2.write_text("# source: x.proto\nX = None\n")
    bl = tmp_path / "b.json"
    bl.write_text('{"suppressions": []}')
    proc = _run_cli("--root", str(root), "--baseline", str(bl),
                    "--analyzers", "proto-drift")
    assert proc.returncode == 1 and "PD001" in proc.stdout
    stamp = _run_cli("--root", str(root), "--stamp-protos")
    assert stamp.returncode == 0 and "x_pb2.py" in stamp.stdout
    proc2 = _run_cli("--root", str(root), "--baseline", str(bl),
                     "--analyzers", "proto-drift")
    assert proc2.returncode == 0, proc2.stdout
    # drift the proto: the stale stamp must fail again
    proto.write_text('syntax = "proto3";\nmessage X { bool ok = 1; }\n')
    proc3 = _run_cli("--root", str(root), "--baseline", str(bl),
                     "--analyzers", "proto-drift")
    assert proc3.returncode == 1 and "PD002" in proc3.stdout


def test_repo_pb2_stamps_current():
    """The checked-in pb2 stamps must match their protos (the in-repo
    instance of the proto-drift invariant)."""
    new, suppressed = run_lint(
        REPO_ROOT, analyzers=["proto-drift"],
        baseline_path=os.path.join(REPO_ROOT, "tools", "lint",
                                   "baseline.json"))
    assert new == [] and suppressed == [], \
        [f.render() for f in new + suppressed]


# --- satellite: bench stamped-capture staleness --------------------------

def test_bench_stale_capture_flag(tmp_path, monkeypatch, capsys):
    import datetime

    import bench

    art = tmp_path / "cap.json"
    monkeypatch.setattr(bench, "CAPTURE_ARTIFACT", str(art))

    def write_artifact(age_seconds, n_lines=1):
        at = (datetime.datetime.now(datetime.timezone.utc)
              - datetime.timedelta(seconds=age_seconds)).isoformat()
        art.write_text(json.dumps(
            {"captured_at": at,
             "lines": [{"metric": f"m{i}", "value": 1.0}
                       for i in range(n_lines)]}))

    write_artifact(30)
    assert bench.surface_stamped_capture()
    fresh = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert fresh["stamped_capture"] is True
    assert fresh["stale_capture"] is False

    # EVERY stamped line of a multi-line artifact carries the full
    # provenance set — the r05 tail surfaced 10 h-old captures whose
    # metric lines had no stale marker
    write_artifact(4 * 3600, n_lines=3)   # older than the 1 h default
    assert bench.surface_stamped_capture()
    out_lines = [json.loads(l) for l in
                 capsys.readouterr().out.strip().splitlines()]
    assert len(out_lines) == 3
    for stale in out_lines:
        assert stale["stamped_capture"] is True
        assert stale["stale_capture"] is True
        assert stale["stamped_age_seconds"] >= 3600

    # threshold is configurable
    monkeypatch.setenv("BENCH_STAMP_STALE_AFTER", str(10 * 3600))
    write_artifact(4 * 3600)
    assert bench.surface_stamped_capture()
    ok = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert ok["stale_capture"] is False
