"""Topology-manager policy merge tests: the four policies over conflicting
provider hints, mirroring the reference's policy_{none,best_effort,
restricted,single_numa_node}_test.go scenarios on the batched mask-
reduction formulation (scheduler/topologymanager.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.scheduler import topologymanager as tm


def hints(free_rows, req, valid=None):
    """Single-pod capacity hints: free_rows [[cpu, mem] per zone]."""
    free = jnp.asarray([free_rows], jnp.float32)
    r = jnp.asarray([req], jnp.float32)
    v = (jnp.ones(free.shape[:2], bool) if valid is None
         else jnp.asarray([valid]))
    return tm.capacity_hints(free, r, v)


def resolve1(fit, pref, policy, free_cpu, valid=None, strategy="most"):
    v = (jnp.ones((1, len(free_cpu)), bool) if valid is None
         else jnp.asarray([valid]))
    aff, admit, engaged = tm.resolve(
        fit, pref, jnp.asarray([policy], jnp.int32),
        jnp.asarray([free_cpu], jnp.float32), v, strategy)
    return np.asarray(aff[0]), bool(admit[0]), bool(engaged[0])


def test_policy_code_parses_both_casings():
    assert tm.policy_code("BestEffort") == tm.POLICY_BEST_EFFORT
    assert tm.policy_code("best-effort") == tm.POLICY_BEST_EFFORT
    assert tm.policy_code("Restricted") == tm.POLICY_RESTRICTED
    assert tm.policy_code("SingleNUMANode") == tm.POLICY_SINGLE_NUMA_NODE
    assert tm.policy_code("") == tm.POLICY_NONE
    assert tm.policy_code("bogus") == tm.POLICY_NONE


def test_mask_table_row_id_is_bitmask_value():
    masks, pop = tm.mask_table(3)
    assert masks.shape == (8, 3)
    assert not masks[0].any()
    assert masks[5].tolist() == [True, False, True]  # 0b101
    assert pop.tolist() == [0, 1, 1, 2, 1, 2, 2, 3]


def test_capacity_hints_minimal_mask_is_preferred():
    # fits in zone 0 alone -> single-zone masks preferred, wider fit too
    fit, pref = hints([[4000, 8192], [4000, 8192]], [2000, 4096])
    fit, pref = np.asarray(fit[0]), np.asarray(pref[0])
    assert fit[0b01] and fit[0b10] and fit[0b11]
    assert pref[0b01] and pref[0b10] and not pref[0b11]
    # needs both zones -> only the pair mask fits, and it is minimal
    fit2, pref2 = hints([[1500, 8192], [1500, 8192]], [2000, 4096])
    fit2, pref2 = np.asarray(fit2[0]), np.asarray(pref2[0])
    assert not fit2[0b01] and not fit2[0b10] and fit2[0b11]
    assert pref2[0b11]


def test_capacity_hints_no_request_is_dont_care():
    fit, pref = hints([[100, 100], [100, 100]], [0, 0])
    assert np.asarray(fit).all() and np.asarray(pref).all()


def test_merge_requires_all_providers(
):
    # CPU fits only zone 0; GPU only zone 1 -> no single-zone merged fit;
    # the pair mask fits (cpu across both, gpu count in {1}) but is not
    # preferred for the cpu provider
    cfit, cpref = hints([[2000, 4096], [0, 0]], [2000, 4096])
    gfit, gpref = tm.count_hints(jnp.asarray([[0, 1]], jnp.int32),
                                 jnp.asarray([1], jnp.int32))
    fit, pref = tm.merge_hints([(cfit, cpref), (gfit, gpref)])
    fit, pref = np.asarray(fit[0]), np.asarray(pref[0])
    assert not fit[0b01]      # gpu missing in zone 0
    assert not fit[0b10]      # cpu missing in zone 1
    # the pair IS a merged fit (cpu from zone 0, gpu in zone 1) but not
    # preferred: each provider's minimal mask is a different single zone
    assert fit[0b11] and not pref[0b11]


def test_merge_agreeing_providers_single_zone():
    cfit, cpref = hints([[4000, 8192], [4000, 8192]], [2000, 4096])
    gfit, gpref = tm.count_hints(jnp.asarray([[2, 0]], jnp.int32),
                                 jnp.asarray([1], jnp.int32))
    fit, pref = tm.merge_hints([(cfit, cpref), (gfit, gpref)])
    fit, pref = np.asarray(fit[0]), np.asarray(pref[0])
    assert fit[0b01] and pref[0b01]
    assert not fit[0b10]      # no gpu in zone 1
    aff, admit, _ = resolve1(jnp.asarray([fit]), jnp.asarray([pref]),
                             tm.POLICY_SINGLE_NUMA_NODE, [4000, 4000])
    assert admit and aff.tolist() == [True, False]


# --- per-policy admission (policy_*_test.go semantics) ----------------------


def cross_zone_case():
    """A pod that fits only across BOTH zones (no preferred single zone)."""
    return hints([[1500, 8192], [1500, 8192]], [2000, 4096])


def test_none_policy_admits_and_does_not_engage():
    fit, pref = cross_zone_case()
    aff, admit, engaged = resolve1(fit, pref, tm.POLICY_NONE, [1500, 1500])
    assert admit and not engaged
    assert aff.tolist() == [True, True]


def test_best_effort_admits_cross_zone():
    fit, pref = cross_zone_case()
    aff, admit, engaged = resolve1(fit, pref, tm.POLICY_BEST_EFFORT,
                                   [1500, 1500])
    assert admit and engaged
    assert aff.tolist() == [True, True]


def test_restricted_admits_only_preferred():
    # cross-zone IS minimal here -> preferred -> restricted admits
    fit, pref = cross_zone_case()
    _, admit, _ = resolve1(fit, pref, tm.POLICY_RESTRICTED, [1500, 1500])
    assert admit
    # conflicting providers: fits exist, none preferred -> rejected
    cfit, cpref = hints([[4000, 8192], [4000, 8192]], [2000, 4096])
    gfit, gpref = tm.count_hints(jnp.asarray([[0, 0]], jnp.int32),
                                 jnp.asarray([1], jnp.int32))
    # gpu fits nowhere: merged has no fit at all -> admit (capacity gates
    # reject instead, keeping policy/capacity failures distinct)
    fit2, pref2 = tm.merge_hints([(cfit, cpref), (gfit, gpref)])
    _, admit2, _ = resolve1(fit2, pref2, tm.POLICY_RESTRICTED, [4000, 4000])
    assert admit2
    # cpu prefers single zones, gpu needs both zones (one instance each):
    # the only merged fits are non-preferred for cpu -> restricted rejects
    gfit3, gpref3 = tm.count_hints(jnp.asarray([[1, 1]], jnp.int32),
                                   jnp.asarray([2], jnp.int32))
    fit3, pref3 = tm.merge_hints([(cfit, cpref), (gfit3, gpref3)])
    fit3np = np.asarray(fit3[0])
    assert fit3np[0b11] and not np.asarray(pref3[0])[0b11]
    _, admit3, _ = resolve1(fit3, pref3, tm.POLICY_RESTRICTED, [4000, 4000])
    assert not admit3


def test_single_numa_node_requires_one_zone():
    # fits zone 0 alone -> admitted, affinity is exactly that zone
    fit, pref = hints([[4000, 8192], [1000, 1024]], [2000, 4096])
    aff, admit, _ = resolve1(fit, pref, tm.POLICY_SINGLE_NUMA_NODE,
                             [4000, 1000])
    assert admit and aff.tolist() == [True, False]
    # cross-zone only -> rejected even though best-effort would admit
    fit2, pref2 = cross_zone_case()
    _, admit2, _ = resolve1(fit2, pref2, tm.POLICY_SINGLE_NUMA_NODE,
                            [1500, 1500])
    assert not admit2


def test_strategy_orders_equal_single_zones():
    # both zones fit; most-allocated packs the least-free zone
    fit, pref = hints([[4000, 8192], [3000, 8192]], [1000, 1024])
    aff_most, _, _ = resolve1(fit, pref, tm.POLICY_SINGLE_NUMA_NODE,
                              [4000, 3000], strategy="most")
    assert aff_most.tolist() == [False, True]
    aff_least, _, _ = resolve1(fit, pref, tm.POLICY_SINGLE_NUMA_NODE,
                               [4000, 3000], strategy="least")
    assert aff_least.tolist() == [True, False]


# --- greedy take ------------------------------------------------------------


def test_greedy_take_single_zone():
    free = jnp.asarray([[[4000, 8192], [4000, 8192]]], jnp.float32)
    req = jnp.asarray([[2000, 4096]], jnp.float32)
    aff = jnp.asarray([[True, False]])
    take, filled = tm.greedy_take(free, req, aff)
    assert bool(filled[0])
    assert np.asarray(take[0]).tolist() == [[2000, 4096], [0, 0]]


def test_greedy_take_spills_in_strategy_order():
    free = jnp.asarray([[[1000, 1024], [3000, 8192]]], jnp.float32)
    req = jnp.asarray([[3500, 2048]], jnp.float32)
    aff = jnp.asarray([[True, True]])
    # most-allocated: fill the least-free zone (0) first, spill to 1
    take, filled = tm.greedy_take(free, req, aff, strategy="most")
    assert bool(filled[0])
    t = np.asarray(take[0])
    assert t[0].tolist() == [1000, 1024]
    assert t[1].tolist() == [2500, 1024]
    # least-allocated: fill the freest zone (1) first
    take2, _ = tm.greedy_take(free, req, aff, strategy="least")
    t2 = np.asarray(take2[0])
    assert t2[1].tolist() == [3000, 2048]
    assert t2[0].tolist() == [500, 0]


def test_greedy_take_unfilled_when_short():
    free = jnp.asarray([[[1000, 1024], [1000, 1024]]], jnp.float32)
    req = jnp.asarray([[3000, 1024]], jnp.float32)
    aff = jnp.asarray([[True, True]])
    take, filled = tm.greedy_take(free, req, aff)
    assert not bool(filled[0])
    # never takes more than free
    assert np.asarray(take).max() <= 1024 + 1e-6


def test_greedy_take_respects_affinity():
    free = jnp.asarray([[[4000, 8192], [4000, 8192]]], jnp.float32)
    req = jnp.asarray([[2000, 1024]], jnp.float32)
    aff = jnp.asarray([[False, True]])
    take, filled = tm.greedy_take(free, req, aff)
    assert bool(filled[0])
    assert np.asarray(take[0, 0]).tolist() == [0, 0]
