"""Descheduler tests: LowNodeLoad classification/eviction-selection and the
PodMigrationJob controller with arbitration (SURVEY.md 2.4; reference
low_node_load_test.go / controller_test.go scenarios)."""

from typing import Dict, List

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.descheduler import (
    EvictionLimiter,
    LowNodeLoad,
    LowNodeLoadArgs,
    MigrationController,
    MigrationControllerArgs,
    RecordingEvictor,
)


def mk_node(name, cpu=64000.0, mem=65536.0):
    return api.Node(meta=api.ObjectMeta(name=name),
                    allocatable={RK.CPU: cpu, RK.MEMORY: mem})


def mk_metric(name, cpu_pct, mem_pct, cpu=64000.0, mem=65536.0,
              pods=(), update=1e9):
    return api.NodeMetric(
        node_name=name, update_time=update,
        node_usage={RK.CPU: cpu * cpu_pct / 100,
                    RK.MEMORY: mem * mem_pct / 100},
        pods_metric=list(pods))


def mk_pod(name, node, cpu=2000.0, mem=2048.0, ns="default", **kw):
    return api.Pod(meta=api.ObjectMeta(name=name, namespace=ns),
                   requests={RK.CPU: cpu, RK.MEMORY: mem},
                   node_name=node, **kw)


def pod_metric(pod, cpu, mem):
    return api.PodMetricInfo(namespace=pod.meta.namespace,
                             name=pod.meta.name,
                             usage={RK.CPU: cpu, RK.MEMORY: mem})


def test_classification_low_high_and_expired():
    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1))
    nodes = [mk_node("low"), mk_node("hot"), mk_node("mid"), mk_node("stale")]
    metrics = {
        "low": mk_metric("low", 10, 10),
        "hot": mk_metric("hot", 90, 50),      # cpu above high=65
        "mid": mk_metric("mid", 50, 70),      # between thresholds
        "stale": mk_metric("stale", 95, 95, update=1e9 - 10_000),
    }
    _, _, low, high, _ = plugin.classify(nodes, metrics, now=1e9)
    assert low.tolist() == [True, False, False, False]
    assert high.tolist() == [False, True, False, False]


def test_anomaly_gating_requires_consecutive_detections():
    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=3),
                         RecordingEvictor())
    nodes = [mk_node("low"), mk_node("hot")]
    hot_pods = [mk_pod(f"p{i}", "hot") for i in range(4)]
    metrics = {"low": mk_metric("low", 10, 10),
               "hot": mk_metric("hot", 90, 50,
                                pods=[pod_metric(p, 8000, 2000)
                                      for p in hot_pods])}
    by_node = {"hot": hot_pods, "low": []}
    assert plugin.balance_once(nodes, metrics, by_node, now=1e9) == []
    assert plugin.balance_once(nodes, metrics, by_node, now=1e9) == []
    assert len(plugin.balance_once(nodes, metrics, by_node, now=1e9)) > 0
    # a normal reading resets the streak
    plugin2 = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=2),
                          RecordingEvictor())
    plugin2.balance_once(nodes, metrics, by_node, now=1e9)
    cool = {"low": metrics["low"], "hot": mk_metric("hot", 10, 10)}
    plugin2.balance_once(nodes, cool, by_node, now=1e9)
    assert plugin2.balance_once(nodes, metrics, by_node, now=1e9) == []


def test_balance_evicts_until_under_high_threshold():
    ev = RecordingEvictor()
    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1), ev)
    nodes = [mk_node("low"), mk_node("hot")]
    # hot at 90% cpu = 57600m; high threshold 65% = 41600m -> must shed
    # 16000m; pods use 8000m each -> exactly 2 evictions
    hot_pods = [mk_pod(f"p{i}", "hot", cpu=8000.0) for i in range(6)]
    metrics = {"low": mk_metric("low", 10, 10),
               "hot": mk_metric("hot", 90, 40,
                                pods=[pod_metric(p, 8000, 2000)
                                      for p in hot_pods])}
    selected = plugin.balance_once(nodes, metrics,
                                   {"hot": hot_pods, "low": []}, now=1e9)
    assert len(selected) == 2
    assert len(ev.evictions) == 2


def test_balance_budget_limited_by_destination_headroom():
    ev = RecordingEvictor()
    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1), ev)
    # destination is small: headroom = 65% of 8000m - 800m used = 4400m
    nodes = [mk_node("low", cpu=8000.0, mem=8192.0), mk_node("hot")]
    hot_pods = [mk_pod(f"p{i}", "hot", cpu=4000.0, mem=1024.0)
                for i in range(8)]
    metrics = {"low": mk_metric("low", 10, 10, cpu=8000.0, mem=8192.0),
               "hot": mk_metric("hot", 90, 40,
                                pods=[pod_metric(p, 4000, 1024)
                                      for p in hot_pods])}
    selected = plugin.balance_once(nodes, metrics,
                                   {"hot": hot_pods, "low": []}, now=1e9)
    # budget is checked BEFORE each eviction (evictPods): the first
    # (4000m) leaves 400m > 0, the second drives it negative and stops —
    # 2 of the 8 candidates move, not all
    assert len(selected) == 2


def test_balance_node_fit_and_daemonset_excluded():
    ev = RecordingEvictor()
    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1), ev)
    nodes = [mk_node("low", cpu=4000.0, mem=4096.0), mk_node("hot")]
    big = mk_pod("big", "hot", cpu=30000.0)      # never fits destination
    ds = mk_pod("ds", "hot", cpu=8000.0, is_daemonset=True)
    ok = mk_pod("ok", "hot", cpu=3000.0, mem=1024.0)
    metrics = {"low": mk_metric("low", 5, 5, cpu=4000.0, mem=4096.0),
               "hot": mk_metric("hot", 95, 40, pods=[
                   pod_metric(big, 30000, 2000), pod_metric(ds, 8000, 2000),
                   pod_metric(ok, 3000, 1024)])}
    selected = plugin.balance_once(
        nodes, metrics, {"hot": [big, ds, ok], "low": []}, now=1e9)
    assert [p.meta.name for p in selected] == ["ok"]


def test_cycle_runner_drives_lownodeload_and_resets_limiter():
    ev = RecordingEvictor(EvictionLimiter(max_per_cycle=1))
    nodes = [mk_node("low"), mk_node("hot")]
    hot_pods = [mk_pod(f"p{i}", "hot", cpu=8000.0) for i in range(6)]
    metrics = {"low": mk_metric("low", 10, 10),
               "hot": mk_metric("hot", 90, 40,
                                pods=[pod_metric(p, 8000, 2000)
                                      for p in hot_pods])}
    from koordinator_tpu.descheduler import CycleRunner
    plugin = LowNodeLoad(LowNodeLoadArgs(consecutive_abnormalities=1), ev,
                         get_metrics=lambda: metrics,
                         get_pods_by_node=lambda: {"hot": hot_pods,
                                                   "low": []},
                         now_fn=lambda: 1e9)
    runner = CycleRunner(balance_plugins=[plugin], limiters=[ev.limiter])
    runner.run_once(nodes)
    runner.run_once(nodes)
    # the per-cycle cap (1) resets between cycles: 2 total, not 1
    assert len(ev.evictions) == 2


def test_migration_ttl_releases_reservation():
    pods = [mk_pod("a", "n1")]
    released = []
    mc = MigrationController(RecordingEvictor(),
                             MigrationControllerArgs(ttl_seconds=10.0),
                             reserve=lambda p: "resv-a",
                             reservation_available=lambda n: False,
                             release_reservation=released.append,
                             get_pod=PodDirectory(pods).get)
    mc.submit_for_pod(pods[0], now=0.0)
    mc.reconcile_once(now=5.0)
    mc.reconcile_once(now=20.0)
    assert released == ["resv-a"]


def test_eviction_limiter():
    lim = EvictionLimiter(max_per_cycle=3, max_per_node=2,
                          max_per_namespace=2)
    ev = RecordingEvictor(lim)
    pods = [mk_pod("a", "n1"), mk_pod("b", "n1"), mk_pod("c", "n1"),
            mk_pod("d", "n2", ns="other")]
    results = [ev.evict(p, "r") for p in pods]
    # third on n1 refused (per-node), then per-cycle admits d
    assert results == [True, True, False, True]
    lim.reset()
    assert ev.evict(mk_pod("e", "n1"), "r")


# --- migration controller ---------------------------------------------------


class PodDirectory:
    def __init__(self, pods: List[api.Pod]):
        self.by_key = {p.meta.namespaced_name: p for p in pods}

    def get(self, key):
        return self.by_key.get(key)


def test_migration_lifecycle_reservation_first():
    pods = [mk_pod("a", "n1", owner_workload="default/rs", workload_replicas=10)]
    directory = PodDirectory(pods)
    ev = RecordingEvictor()
    ready: Dict[str, bool] = {}

    def reserve(pod):
        name = f"resv-{pod.meta.name}"
        ready[name] = False
        return name

    mc = MigrationController(ev, MigrationControllerArgs(),
                             reserve=reserve,
                             reservation_available=lambda n: ready[n],
                             get_pod=directory.get)
    job = mc.submit_for_pod(pods[0], reason="rebalance", now=0.0)
    mc.reconcile_once(now=1.0)
    assert job.phase == "Running" and job.reservation_name == "resv-a"
    assert ev.evictions == []          # waiting on replacement capacity
    ready["resv-a"] = True
    mc.reconcile_once(now=2.0)
    assert job.phase == "Succeeded"
    assert [e.pod.meta.name for e in ev.evictions] == ["a"]


def test_migration_ttl_expiry():
    pods = [mk_pod("a", "n1")]
    mc = MigrationController(RecordingEvictor(),
                             MigrationControllerArgs(ttl_seconds=10.0),
                             reserve=lambda p: "r",
                             reservation_available=lambda n: False,
                             get_pod=PodDirectory(pods).get)
    job = mc.submit_for_pod(pods[0], now=0.0)
    mc.reconcile_once(now=5.0)
    assert job.phase == "Running"
    mc.reconcile_once(now=20.0)
    assert job.phase == "Failed" and job.reason == "timeout"


def test_arbitrator_max_migrating_per_node():
    pods = [mk_pod(f"p{i}", "n1") for i in range(4)]
    directory = PodDirectory(pods)
    mc = MigrationController(
        RecordingEvictor(),
        MigrationControllerArgs(max_migrating_per_node=2,
                                default_mode="EvictDirectly"),
        reservation_available=lambda n: True,
        get_pod=directory.get)
    jobs = [mc.submit_for_pod(p, now=0.0) for p in pods]
    # freeze running jobs by refusing evictions (limiter at 0)
    mc.evictor = RecordingEvictor(EvictionLimiter(max_per_cycle=0))
    mc.reconcile_once(now=1.0)
    phases = [j.phase for j in jobs]
    assert phases.count("Running") == 2 and phases.count("Pending") == 2


def test_arbitrator_max_unavailable_per_workload():
    pods = [mk_pod(f"p{i}", f"n{i}", owner_workload="default/rs",
                   workload_replicas=10) for i in range(4)]
    directory = PodDirectory(pods)
    # 10 replicas x 30% = 3 max unavailable; 2 already unavailable ->
    # only 1 migration admitted
    mc = MigrationController(
        RecordingEvictor(EvictionLimiter(max_per_cycle=0)),
        MigrationControllerArgs(max_migrating_per_workload=1.0,
                                max_unavailable_per_workload=0.3,
                                default_mode="EvictDirectly"),
        get_pod=directory.get,
        unavailable_per_workload=lambda: {"default/rs": 2})
    jobs = [mc.submit_for_pod(p, now=0.0) for p in pods]
    mc.reconcile_once(now=1.0)
    phases = [j.phase for j in jobs]
    assert phases.count("Running") == 1


def test_arbitrator_sort_spreads_workloads():
    pods = ([mk_pod(f"a{i}", f"n{i}", owner_workload="default/a",
                    workload_replicas=100) for i in range(2)]
            + [mk_pod("b0", "nb", owner_workload="default/b",
                      workload_replicas=100)])
    directory = PodDirectory(pods)
    mc = MigrationController(
        RecordingEvictor(EvictionLimiter(max_per_cycle=0)),
        MigrationControllerArgs(max_migrating_per_node=None,
                                max_migrating_per_workload=1,
                                max_unavailable_per_workload=None,
                                default_mode="EvictDirectly"),
        get_pod=directory.get)
    jobs = [mc.submit_for_pod(p, now=0.0) for p in pods]
    mc.reconcile_once(now=1.0)
    # workload a admits one job (its second is over the per-workload cap);
    # workload b's job must still be admitted despite queue position
    assert jobs[0].phase == "Running"
    assert jobs[1].phase == "Pending"
    assert jobs[2].phase == "Running"
