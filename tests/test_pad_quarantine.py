"""Pad x quarantine x mesh-shrink interplay (ISSUE 16, koordpad): the
PAD-ROW CONTRACT (mesh.pad_nodes_to_mesh docstring) must survive the
guard path and the degradation ladder's pad -> unpad -> repad cycle at
mesh-indivisible node counts.

Three ways a pad row could leak that the kernel tests alone don't pin:
the health scan could flag it (spurious quarantine churn every cycle),
the quarantine scrub could rewrite its declared fill (breaking the
fills tools/padcheck.py asserts), or a shrink-repad round trip could
smear real-row state into the pad band. Each gets a bitwise pin here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from koordinator_tpu.parallel import (
    make_mesh,
    pad_nodes_to_mesh,
    padded_node_count,
    unpad_nodes,
)
from koordinator_tpu.scheduler import core, guards
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic

N_REAL = 13  # indivisible by every mesh size we pad to
CFG = LoadAwareConfig.make()
SLIM = dict(num_rounds=2, k_choices=4, enable_numa=False,
            enable_devices=False)


def make_padded(seed=0, num_pods=6):
    mesh = make_mesh(jax.devices())  # 8-way node axis: 13 -> 16
    snap = synthetic.synthetic_cluster(N_REAL, seed=seed)
    pods = synthetic.synthetic_pods(num_pods, seed=seed + 7, prod_frac=1.0)
    padded = pad_nodes_to_mesh(snap, mesh)
    assert padded.num_nodes == padded_node_count(N_REAL, mesh) == 16
    return mesh, snap, pods, padded


def assert_trees_equal(a, b, msg=""):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb), msg
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=msg)


def assert_pad_rows_inert(snap, n_real):
    """The three load-bearing pad fills: never chosen, never charged."""
    assert not np.asarray(snap.nodes.schedulable)[n_real:].any()
    assert not np.asarray(snap.nodes.allocatable)[n_real:].any()
    assert not np.asarray(snap.nodes.requested)[n_real:].any()


# --- the health scan on padded snapshots ------------------------------------

def test_pad_rows_scan_healthy():
    """Pad rows must never trip the guard word: a spurious bit would
    quarantine (and re-count) the pad band on every ladder cycle."""
    _, _, pods, padded = make_padded()
    word, node_bad = guards.snapshot_health(padded)
    assert int(np.asarray(word)) == guards.HEALTH_OK, \
        guards.decode_health_word(int(np.asarray(word)))
    assert not np.asarray(node_bad).any()
    word, pod_bad = guards.batch_health(padded, pods)
    assert int(np.asarray(word)) == guards.HEALTH_OK
    assert not np.asarray(pod_bad).any()


# --- quarantine vs the pad band ---------------------------------------------

def test_quarantine_passthrough_includes_pad_band():
    """All-false masks are a bit-identical pass-through on the PADDED
    snapshot too — declared pad fills included."""
    _, _, pods, padded = make_padded(1)
    n_pad = padded.num_nodes
    q_snap, q_pods = guards.apply_quarantine(
        padded, pods, jnp.zeros((n_pad,), bool),
        jnp.zeros((pods.num_pods,), bool))
    assert_trees_equal(q_snap, padded, "quarantine pass-through (snap)")
    assert_trees_equal(q_pods, pods, "quarantine pass-through (pods)")


def test_quarantining_pad_rows_is_a_noop():
    """Every scrubbed field's declared pad fill is a fixed point of the
    scrub (zero stays zero, schedulable stays False, cpu_amplification
    is never scrubbed), so flagging the pad band changes nothing —
    quarantine can't corrupt the fills padcheck asserts."""
    _, _, pods, padded = make_padded(2)
    n_pad = padded.num_nodes
    pad_only = np.zeros((n_pad,), bool)
    pad_only[N_REAL:] = True
    q_snap, q_pods = guards.apply_quarantine(
        padded, pods, jnp.asarray(pad_only),
        jnp.zeros((pods.num_pods,), bool))
    assert_trees_equal(q_snap, padded, "pad-only quarantine (snap)")
    assert_trees_equal(q_pods, pods, "pad-only quarantine (pods)")


def test_quarantined_real_row_leaves_pads_inert_and_uncharged():
    """Quarantine a real node on the padded snapshot, schedule, and pin
    the full contract: the quarantined node and every pad row stay
    unassigned and uncharged, and overcommit holds on the real rows."""
    _, snap, pods, padded = make_padded(3)
    n_pad = padded.num_nodes
    node_bad = np.zeros((n_pad,), bool)
    node_bad[2] = True
    q_snap, q_pods = guards.apply_quarantine(
        padded, pods, jnp.asarray(node_bad),
        jnp.zeros((pods.num_pods,), bool))
    assert_pad_rows_inert(q_snap, N_REAL)

    res = core.schedule_batch(q_snap, q_pods, CFG, **SLIM)
    a = np.asarray(res.assignment)
    assert (a >= 0).any()            # the cluster still schedules
    assert not (a == 2).any()        # never the quarantined node
    assert a.max() < N_REAL          # never a pad row
    assert core.overcommit_ok(res.snapshot, N_REAL)
    assert not np.asarray(res.snapshot.nodes.requested)[N_REAL:].any()


# --- the shrink ladder's pad -> unpad -> repad cycle ------------------------

def test_unpad_roundtrip_is_bitwise_identity():
    _, snap, _, padded = make_padded(4)
    assert_trees_equal(unpad_nodes(padded, N_REAL), snap,
                       "unpad(pad(snap)) round trip")
    with pytest.raises(ValueError):
        unpad_nodes(snap, N_REAL + 1)  # cannot unpad upward


def test_mesh_shrink_repad_matches_oracle_and_stays_uncharged():
    """The DegradationLadder flow at an indivisible count: pad to the
    full mesh, unpad (commit shapes), repad to a 2-device survivor mesh
    (13 -> 14), schedule — placement matches the unpadded oracle and
    the new, smaller pad band is still inert."""
    _, snap, pods, padded = make_padded(5)
    mesh2 = make_mesh(jax.devices()[:2])
    committed = unpad_nodes(padded, N_REAL)
    repadded = pad_nodes_to_mesh(committed, mesh2)
    assert repadded.num_nodes == padded_node_count(N_REAL, mesh2) == 14
    assert_pad_rows_inert(repadded, N_REAL)

    res1 = core.schedule_batch(snap, pods, CFG, **SLIM)
    with mesh2:
        res2 = core.schedule_batch(repadded, pods, CFG, **SLIM)
    assert np.array_equal(np.asarray(res2.assignment),
                          np.asarray(res1.assignment))
    assert core.overcommit_ok(res2.snapshot, N_REAL)
    assert not np.asarray(res2.snapshot.nodes.requested)[N_REAL:].any()


def test_guarded_schedule_on_repadded_snapshot_quarantines_real_only():
    """End to end through the fused guard kernel on a shrink-repadded
    snapshot with one genuinely sick real node: the guard flags exactly
    that node (never the pad band), and the committed result keeps the
    pad rows uncharged."""
    _, snap, pods, _ = make_padded(6)
    mesh2 = make_mesh(jax.devices()[:2])
    repadded = pad_nodes_to_mesh(snap, mesh2)
    usage = np.asarray(repadded.nodes.usage).copy()
    usage[1, 0] = np.nan  # a real node goes sick mid-cycle
    sick = repadded.replace(nodes=repadded.nodes.replace(usage=usage))

    with mesh2:
        res, health, node_bad, pod_bad = guards.guarded_schedule_batch(
            sick, pods, CFG, **SLIM)
    word = int(np.asarray(health)[0])
    assert word & guards.NODE_METRIC_NONFINITE, \
        guards.decode_health_word(word)
    node_bad = np.asarray(node_bad)
    assert node_bad[1]
    assert not node_bad[N_REAL:].any()  # the pad band never quarantines
    assert not np.asarray(pod_bad).any()

    a = np.asarray(res.assignment)
    assert (a >= 0).any()
    assert not (a == 1).any()
    assert a.max() < N_REAL
    assert core.overcommit_ok(res.snapshot, N_REAL)
    assert not np.asarray(res.snapshot.nodes.requested)[N_REAL:].any()
    # committing back through unpad drops the (still pristine) pad band
    assert unpad_nodes(res.snapshot, N_REAL).num_nodes == N_REAL
