"""Host ingest-plane race test (SURVEY §5 'keep race tests on the host
ingest layer'): concurrent informer writers, a syncing snapshotter, and
a scheduling reader must never corrupt state — the functional snapshot
makes device state immune, so the risk surface is the hub caches,
indexes, and the store's version chain."""

import threading

import numpy as np

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot import (
    ClusterInformerHub,
    SnapshotStore,
    SnapshotSyncer,
)

NOW = 1e9
N_NODES = 8


def test_concurrent_writers_syncer_and_reader():
    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=N_NODES, delta_pad=8)
    for i in range(N_NODES):
        hub.upsert_node(api.Node(
            meta=api.ObjectMeta(name=f"n{i}"),
            allocatable={RK.CPU: 32000.0, RK.MEMORY: 65536.0}))
        hub.set_node_metric(api.NodeMetric(
            node_name=f"n{i}", update_time=NOW,
            node_usage={RK.CPU: 1000.0, RK.MEMORY: 512.0}))
    syncer.sync(now=NOW)
    cfg = loadaware.LoadAwareConfig.make()
    errors = []
    stop = threading.Event()

    def metric_writer(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                i = int(rng.integers(N_NODES))
                hub.set_node_metric(api.NodeMetric(
                    node_name=f"n{i}", update_time=NOW,
                    node_usage={RK.CPU: float(rng.uniform(0, 16000)),
                                RK.MEMORY: 512.0}))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def pod_writer():
        try:
            j = 0
            while not stop.is_set():
                uid = f"u{j % 50}"
                hub.upsert_pod(api.Pod(
                    meta=api.ObjectMeta(uid=uid, name=uid),
                    node_name=f"n{j % N_NODES}",
                    owner_workload="default/w", phase="Running",
                    requests={RK.CPU: 100.0, RK.MEMORY: 64.0}))
                if j % 3 == 0:
                    hub.delete_pod(f"u{(j // 3) % 50}")
                j += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def sync_loop():
        try:
            while not stop.is_set():
                syncer.sync(now=NOW)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader_loop():
        last_version = -1
        try:
            while not stop.is_set():
                v = store.version
                snap = store.current()
                # the version chain only moves forward
                assert v >= last_version, f"version went back: {v}"
                last_version = v
                req = np.asarray(snap.nodes.requested)
                assert (req >= -1e-3).all()
                pbn = hub.pods_by_node()
                for pods in pbn.values():
                    assert all(p.meta.uid for p in pods)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    import time

    # phase 1: pod churn + metric churn — every sync is a full rebuild
    # (shape dirty), racing builders against readers
    pod_stop = threading.Event()

    def pod_writer_guarded():
        try:
            j = 0
            while not stop.is_set() and not pod_stop.is_set():
                uid = f"u{j % 50}"
                hub.upsert_pod(api.Pod(
                    meta=api.ObjectMeta(uid=uid, name=uid),
                    node_name=f"n{j % N_NODES}",
                    owner_workload="default/w", phase="Running",
                    requests={RK.CPU: 100.0, RK.MEMORY: 64.0}))
                if j % 3 == 0:
                    hub.delete_pod(f"u{(j // 3) % 50}")
                j += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    del pod_writer  # replaced by the guarded variant
    threads = [threading.Thread(target=metric_writer, args=(s,))
               for s in (1, 2)]
    threads += [threading.Thread(target=pod_writer_guarded),
                threading.Thread(target=sync_loop),
                threading.Thread(target=reader_loop)]
    for t in threads:
        t.start()
    time.sleep(1.2)
    # phase 2: quiesce pods, keep metric writers going — syncs now take
    # the O(K) DELTA path (store.ingest) under concurrent readers, the
    # actual risk surface of the freshness split
    pod_stop.set()
    time.sleep(1.2)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    assert not errors, errors
    assert syncer.delta_ingests > 0, \
        "the metric-only phase must exercise the delta-ingest path"
    assert syncer.full_rebuilds > 0

    # quiesce: one final sync must reflect the final hub state exactly
    syncer.sync(now=NOW)
    final = store.current()
    metrics = hub.node_metrics()
    usage = np.asarray(final.nodes.usage)
    for i in range(N_NODES):
        assert usage[i, 0] == np.float32(
            metrics[f"n{i}"].node_usage[RK.CPU])

    # and the snapshot still schedules
    pod = api.Pod(meta=api.ObjectMeta(name="probe"),
                  requests={RK.CPU: 100.0, RK.MEMORY: 64.0}, priority=9000)
    batch = syncer.builder.build_pod_batch([pod], syncer.ctx)
    res = core.schedule_batch(final, batch, cfg)
    assert int(np.asarray(res.assignment)[0]) >= 0


def test_concurrent_topology_churn_and_summary_readers():
    """The round-4 risk surface: the incremental topology path mutates
    builder.node_index while summary providers iterate it (the
    _view_lock pairs the index with the snapshot) and node writers
    churn the hub. No RuntimeError('dictionary changed size'), no
    partial states, and the end state must match the hub exactly."""
    import time

    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=32, delta_pad=8)
    for i in range(8):
        hub.upsert_node(api.Node(
            meta=api.ObjectMeta(name=f"base{i}"),
            allocatable={RK.CPU: 32000.0, RK.MEMORY: 65536.0}))
    syncer.sync(now=NOW)
    errors = []
    stop = threading.Event()

    def node_churner(seed):
        rng = np.random.default_rng(seed)
        try:
            j = 0
            while not stop.is_set():
                # 3 names per churner (6 total) stays under delta_pad=8
                # so the steady state actually exercises the O(K) path
                # instead of tripping the overflow rebuild every pass
                name = f"dyn{seed}-{j % 3}"
                if rng.uniform() < 0.6:
                    hub.upsert_node(api.Node(
                        meta=api.ObjectMeta(name=name),
                        allocatable={RK.CPU: float(
                            rng.choice([16000, 48000])),
                            RK.MEMORY: 65536.0}))
                else:
                    hub.delete_node(name)
                j += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def sync_loop():
        try:
            while not stop.is_set():
                syncer.sync(now=NOW)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def summary_reader():
        try:
            while not stop.is_set():
                # iterates builder indexes against store.current()
                # under the view lock — must never see a torn pair
                syncer.quota_summary()
                syncer.device_summary()
                snap = store.current()
                assert np.asarray(snap.nodes.allocatable).shape[0] == 32
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=node_churner, args=(s,))
               for s in (3, 4)]
    threads += [threading.Thread(target=sync_loop),
                threading.Thread(target=summary_reader)]
    for t in threads:
        t.start()
    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    assert not errors, errors
    assert syncer.topology_ingests > 0, \
        "the churn must exercise the O(K) topology path"

    # quiesce: the final synced state mirrors the hub node set
    syncer.sync(now=NOW)
    final = store.current()
    sched = np.asarray(final.nodes.schedulable)
    hub_names = {n.meta.name for n in hub.nodes()}
    assert set(syncer.builder.node_index) == hub_names
    assert int(sched.sum()) == len(hub_names)
    for name, idx in syncer.builder.node_index.items():
        want = hub.get_node(name).allocatable[RK.CPU]
        got = float(np.asarray(final.nodes.allocatable)[idx, 0])
        assert got == np.float32(want), (name, got, want)


def test_schedule_vs_sync_commit_guard_race():
    """The round-5 serialization contract: with a scheduler ATTACHED,
    syncer publishes ride the service's commit lock, so a rebuild can
    never land between a batch's snapshot read and its post-commit
    publish (lost update), and the assume hook always resolves result
    rows against the builder generation the batch scheduled on. Under
    concurrent schedule / identity-churn / metric-churn / sync threads,
    the device snapshot must end EXACTLY consistent with the host view:
    requested == the charges of hub-known placed pods."""
    from koordinator_tpu.scheduler.frameworkext import SchedulerService

    hub = ClusterInformerHub()
    store = SnapshotStore()
    syncer = SnapshotSyncer(hub, store, max_nodes=N_NODES, delta_pad=8)
    service = SchedulerService(store=store, num_rounds=2, k_choices=2)
    syncer.attach_scheduler(service)
    for i in range(N_NODES):
        hub.upsert_node(api.Node(
            meta=api.ObjectMeta(name=f"n{i}"),
            allocatable={RK.CPU: 64000.0, RK.MEMORY: 131072.0}))
        hub.set_node_metric(api.NodeMetric(
            node_name=f"n{i}", update_time=NOW,
            node_usage={RK.CPU: 1000.0, RK.MEMORY: 1024.0}))
    assert syncer.sync(now=NOW) == "full"

    stop = threading.Event()
    errors = []
    placed_uids = []

    def scheduler_loop():
        try:
            j = 0
            while not stop.is_set() and j < 60:
                pod = api.Pod(
                    meta=api.ObjectMeta(name=f"p{j}", uid=f"p{j}"),
                    priority=9000,
                    requests={RK.CPU: 500.0, RK.MEMORY: 256.0})
                batch = syncer.build_pod_batch([pod])
                res = service.schedule(batch, typed_pods=[pod])
                if int(np.asarray(res.assignment)[0]) >= 0:
                    placed_uids.append(pod.meta.uid)
                j += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def node_churner():
        try:
            j = 0
            while not stop.is_set():
                # identity churn (labels change) -> O(K) topology path
                hub.upsert_node(api.Node(
                    meta=api.ObjectMeta(name=f"n{j % N_NODES}",
                                        labels={"gen": str(j)}),
                    allocatable={RK.CPU: 64000.0, RK.MEMORY: 131072.0}))
                j += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def metric_churner():
        try:
            j = 0
            while not stop.is_set():
                # metric churn -> the O(K) delta-ingest publish path
                hub.set_node_metric(api.NodeMetric(
                    node_name=f"n{j % N_NODES}", update_time=NOW,
                    node_usage={RK.CPU: 1000.0 + j % 7,
                                RK.MEMORY: 1024.0}))
                j += 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def sync_loop():
        try:
            while not stop.is_set():
                syncer.sync(now=NOW)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=scheduler_loop, daemon=True),
               threading.Thread(target=node_churner, daemon=True),
               threading.Thread(target=metric_churner, daemon=True),
               threading.Thread(target=sync_loop, daemon=True)]
    for t in threads:
        t.start()
    threads[0].join(timeout=240)  # the scheduler loop is finite
    stop.set()
    for t in threads[1:]:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "deadlocked"
    assert errors == [], errors

    # the race must have exercised the claimed surfaces: pods placed,
    # and the O(K) ingest paths actually ran (not just full rebuilds)
    assert placed_uids
    assert syncer.topology_ingests > 0 or syncer.delta_ingests > 0

    # quiesce: force one final full rebuild from the hub truth
    hub.upsert_quota(api.ElasticQuota(meta=api.ObjectMeta(name="q")))
    assert syncer.sync(now=NOW) == "full"
    # every placed pod still lives in the assume cache (nothing was
    # watch-bound), so device requested must equal their charges
    want = 500.0 * len(placed_uids)
    got = float(np.asarray(
        store.current().nodes.requested)[:N_NODES, 0].sum())
    assert got == want, (got, want, len(placed_uids))
    assert {p.meta.uid for p, _ in hub.assumed_entries()} \
        == set(placed_uids)
