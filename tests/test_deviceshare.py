"""DeviceShare plugin tests: per-instance request math, instance packing,
multi-GPU whole-instance allocation, aux (rdma/fpga) VF fragmentation, and
builder restore — mirroring the reference's device_allocator_test.go /
devicehandler_gpu_test.go scenarios (SURVEY.md 2.1 DeviceShare)."""

import numpy as np
import pytest

from koordinator_tpu.api.extension import ResourceKind
from koordinator_tpu.api.types import Device, DeviceInfo, Node, NodeMetric, ObjectMeta, Pod
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.snapshot.builder import SnapshotBuilder, gpu_per_instance_host
from koordinator_tpu.utils import synthetic

GC, GM = ResourceKind.GPU_CORE, ResourceKind.GPU_MEMORY
RD, FP = ResourceKind.RDMA, ResourceKind.FPGA
CPU, MEM = ResourceKind.CPU, ResourceKind.MEMORY


def make_builder(num_nodes=2, gpus=4, gpu_mem=1000.0, aux=0, **kw):
    b = SnapshotBuilder(max_nodes=num_nodes, max_gpu_inst=gpus,
                        max_aux_inst=aux, **kw)
    for i in range(num_nodes):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={CPU: 32000.0, MEM: 64000.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=1e9,
                                     node_usage={CPU: 1000.0, MEM: 1000.0}))
        infos = [DeviceInfo(minor=m, type="gpu",
                            resources={GC: 100.0, GM: gpu_mem},
                            numa_node=m * 2 // max(gpus, 1), pcie_id=f"p{m//2}")
                 for m in range(gpus)]
        infos += [DeviceInfo(minor=m, type="rdma", resources={RD: 100.0})
                  for m in range(aux)]
        b.add_device(Device(node_name=f"n{i}", devices=infos))
    return b


def gpu_pod(name, core=0.0, mem=0.0, ratio=0.0, prio=9000, **kw):
    req = {CPU: 1000.0, MEM: 1000.0}
    if core:
        req[GC] = core
    if mem:
        req[GM] = mem
    return Pod(meta=ObjectMeta(name=name), requests=req, priority=prio,
               gpu_memory_ratio=ratio, **kw)


def schedule(b, pods, now=1e9, **kw):
    snap, ctx = b.build(now=now)
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, LoadAwareConfig.make(),
                              num_rounds=3, k_choices=4, **kw)
    return (np.asarray(res.assignment), np.asarray(res.gpu_take),
            np.asarray(res.aux_inst), res)


# --- per-instance request math (devicehandler_gpu.go:40-98) -----------------


def test_per_instance_shared():
    count, per = gpu_per_instance_host(1000.0, gpu_pod("p", core=50, ratio=50))
    assert count == 1
    assert per.tolist() == [50.0, 500.0, 50.0]


def test_per_instance_multi_device():
    # ratio 400 -> 4 whole GPUs, request split per instance
    count, per = gpu_per_instance_host(
        1000.0, gpu_pod("p", core=400, ratio=400))
    assert count == 4
    assert per.tolist() == [100.0, 1000.0, 100.0]


def test_per_instance_memory_specified_wins():
    # explicit gpu-memory converts to ratio against the node's GPU memory
    count, per = gpu_per_instance_host(1000.0, gpu_pod("p", core=50, mem=250))
    assert count == 1
    assert per.tolist() == [50.0, 250.0, 25.0]


def test_per_instance_non_divisible_ratio_single():
    # ratio > 100 not divisible by 100 stays a single-instance request
    # (cannot fit any instance -> unschedulable), devicehandler_gpu.go:55
    count, per = gpu_per_instance_host(1000.0, gpu_pod("p", ratio=150))
    assert count == 1
    assert per[2] == 150.0


# --- instance packing -------------------------------------------------------


def test_shared_pods_pack_instances_exactly():
    # one node, 2 GPUs; three 60%-pods: only two fit (one per instance)
    b = make_builder(num_nodes=1, gpus=2)
    pods = [gpu_pod(f"p{i}", core=60, ratio=60, prio=9000 - i)
            for i in range(3)]
    a, take, _, res = schedule(b, pods)
    assert (a >= 0).sum() == 2
    # priority order: p0, p1 placed, p2 rejected
    assert a[0] == 0 and a[1] == 0 and a[2] == -1
    # each on a distinct instance
    assert (take[0] & take[1]).sum() == 0
    free = np.asarray(res.snapshot.devices.gpu_free)
    assert np.allclose(free[0, :, 0], [40.0, 40.0])


def test_least_allocated_spreads_most_packs():
    # 2 GPUs, one pre-used at 50%: least-allocated picks the free one,
    # most-allocated packs the used one (scoring.go strategies)
    for strategy, want_inst in (("least", 1), ("most", 0)):
        b = make_builder(num_nodes=1, gpus=2)
        running = gpu_pod("r", core=50, ratio=50)
        running.node_name = "n0"
        running.allocated_gpu_minors = (0,)
        b.add_running_pod(running)
        a, take, _, _ = schedule(
            b, [gpu_pod("p", core=30, ratio=30)], device_strategy=strategy)
        assert a[0] == 0
        assert take[0].nonzero()[0].tolist() == [want_inst], strategy


def test_multi_gpu_whole_instances():
    # 4 GPUs, one partially used: a 4-GPU pod cannot fit, a 3-GPU pod takes
    # the three untouched instances
    b = make_builder(num_nodes=1, gpus=4)
    running = gpu_pod("r", core=10, ratio=10)
    running.node_name = "n0"
    running.allocated_gpu_minors = (2,)
    b.add_running_pod(running)
    a, take, _, _ = schedule(b, [gpu_pod("p4", core=400, ratio=400)])
    assert a[0] == -1
    a, take, _, _ = schedule(b, [gpu_pod("p3", core=300, ratio=300)])
    assert a[0] == 0
    assert take[0].nonzero()[0].tolist() == [0, 1, 3]


def test_gpu_capacity_conservation_and_no_overcommit():
    snap = synthetic.synthetic_cluster(32, gpu_node_frac=0.6, seed=3)
    pods = synthetic.synthetic_pods(128, gpu_pod_frac=0.7, seed=4)
    res = core.schedule_batch(snap, pods, LoadAwareConfig.make(),
                              num_rounds=3, k_choices=4)
    a = np.asarray(res.assignment)
    take = np.asarray(res.gpu_take)
    ratio = np.asarray(pods.gpu_ratio)
    placed_gpu = (a >= 0) & (ratio > 0)
    count = np.where(ratio > 100, ratio // 100, 1).astype(int)
    assert (take.sum(1)[placed_gpu] == count[placed_gpu]).all()
    assert (take.sum(1)[~placed_gpu] == 0).all()
    free = np.asarray(res.snapshot.devices.gpu_free)
    free0 = np.asarray(snap.devices.gpu_free)
    assert (free >= -0.5).all()
    assert np.isclose((free0 - free)[..., 0].sum(),
                      (ratio * placed_gpu).sum())
    # unplaced GPU pods imply genuine exhaustion OR non-GPU gates binding;
    # at minimum every placed pod's instances were valid
    valid = np.asarray(snap.devices.gpu_valid)
    assert not (take & ~valid[np.clip(a, 0, 31)]).any()


def test_ratio_only_pod_unschedulable_without_gpus():
    # a gpu-memory-ratio-only request must NOT silently place on a
    # device-less snapshot (zero instance capacity)
    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=0)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={CPU: 32000.0, MEM: 64000.0}))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=1e9,
                                 node_usage={CPU: 100.0, MEM: 100.0}))
    a, take, _, _ = schedule(b, [gpu_pod("p", ratio=50)])
    assert a[0] == -1


def test_gpu_pod_rejected_on_gpuless_node():
    # node 0 has GPUs, node 1 none: GPU pods all land on node 0
    b = SnapshotBuilder(max_nodes=2, max_gpu_inst=2)
    for i in range(2):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={CPU: 32000.0, MEM: 64000.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=1e9,
                                     node_usage={CPU: 100.0, MEM: 100.0}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=0, type="gpu", resources={GC: 100.0, GM: 1000.0})]))
    a, _, _, _ = schedule(b, [gpu_pod("p", core=50, ratio=50)])
    assert a[0] == 0


def test_memory_request_ratio_depends_on_node():
    # 600MiB request = 60% of a 1000MiB GPU but 120% (infeasible) of a
    # 500MiB GPU (fillGPUTotalMem per-node conversion)
    b = SnapshotBuilder(max_nodes=2, max_gpu_inst=1)
    for i, gmem in enumerate((500.0, 1000.0)):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={CPU: 32000.0, MEM: 64000.0}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=1e9,
                                     node_usage={CPU: 100.0, MEM: 100.0}))
        b.add_device(Device(node_name=f"n{i}", devices=[
            DeviceInfo(minor=0, type="gpu",
                       resources={GC: 100.0, GM: gmem})]))
    a, take, _, _ = schedule(b, [gpu_pod("p", core=10, mem=600.0)])
    assert a[0] == 1


def test_multi_gpu_numa_alignment():
    # 4 GPUs split over zones 0/1 (2 each): a NUMA-bound 4-GPU pod cannot
    # align, a NUMA-bound 2-GPU pod takes both instances of ONE zone
    b = make_builder(num_nodes=1, gpus=4)
    b.nodes[0].topology = _topo()
    p4 = gpu_pod("p4", core=400, ratio=400, required_cpu_bind=True)
    a, take, _, _ = schedule(b, [p4])
    assert a[0] == -1
    p2 = gpu_pod("p2", core=200, ratio=200, required_cpu_bind=True)
    a, take, _, res = schedule(b, [p2])
    assert a[0] == 0
    minors = take[0].nonzero()[0].tolist()
    assert minors in ([0, 1], [2, 3])
    zone = int(np.asarray(res.numa_zone)[0])
    # instances belong to the committed zone (gpu_numa = m*2//4 -> 0,0,1,1)
    assert all(m * 2 // 4 == zone for m in minors)


def test_zone_choice_merges_gpu_hint():
    # zone choice must intersect the deviceshare NUMA hint: after zone 0's
    # GPUs are taken, a bound GPU pod lands on zone 1 (not stranded by the
    # CPU-preferring zone pick)
    b = make_builder(num_nodes=1, gpus=4)
    b.nodes[0].topology = _topo()
    pods = [gpu_pod(f"p{i}", core=200, ratio=200, prio=9000 - i,
                    required_cpu_bind=True) for i in range(2)]
    a, take, _, res = schedule(b, pods)
    zone = np.asarray(res.numa_zone)
    assert (a >= 0).all()
    assert sorted(zone.tolist()) == [0, 1]
    for j in range(2):
        assert all(m * 2 // 4 == zone[j] for m in take[j].nonzero()[0])


def test_numa_disabled_does_not_strand_bound_gpu_pods():
    # enable_numa=False drops the device zone constraint instead of
    # tightening it against the -1 sentinel
    b = make_builder(num_nodes=1, gpus=2)
    p = gpu_pod("p", core=50, ratio=50, required_cpu_bind=True)
    a, take, _, _ = schedule(b, [p], enable_numa=False)
    assert a[0] == 0 and take[0].sum() == 1


def _topo():
    from koordinator_tpu.api.types import NodeResourceTopology, NUMAZone
    return NodeResourceTopology(
        node_name="n0",
        zones=[NUMAZone(cpus_milli=16000.0, memory_mib=32000.0),
               NUMAZone(cpus_milli=16000.0, memory_mib=32000.0)])


# --- aux pools (rdma VF packing) --------------------------------------------


def test_rdma_vf_fragmentation():
    # one node, 2 VFs of 100: 60+60 pack one per VF; a third 60 must be
    # rejected even though aggregate free (80) would fit it
    b = make_builder(num_nodes=1, gpus=0, aux=2)
    pods = []
    for i in range(3):
        p = Pod(meta=ObjectMeta(name=f"p{i}"),
                requests={CPU: 1000.0, MEM: 1000.0, RD: 60.0},
                priority=9000 - i)
        pods.append(p)
    a, _, aux_inst, res = schedule(b, pods)
    assert (a >= 0).tolist() == [True, True, False]
    assert aux_inst[0, 0] != aux_inst[1, 0]
    free = np.asarray(res.snapshot.devices.aux_free)
    assert np.allclose(sorted(free[0, 0].tolist()), [40.0, 40.0])


# --- builder restore --------------------------------------------------------


def test_builder_indexes_columns_by_minor():
    # Device CR listed out of minor order: columns must follow minors so
    # running-pod restore by minor hits the right physical GPU
    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=2)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={CPU: 32000.0, MEM: 64000.0}))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=1e9,
                                 node_usage={CPU: 100.0, MEM: 100.0}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=1, type="gpu", resources={GC: 100.0, GM: 1000.0},
                   numa_node=1),
        DeviceInfo(minor=0, type="gpu", resources={GC: 100.0, GM: 1000.0},
                   numa_node=0)]))
    running = gpu_pod("r", core=100, ratio=100)
    running.node_name = "n0"
    running.allocated_gpu_minors = (1,)
    b.add_running_pod(running)
    snap, _ = b.build(now=1e9)
    free = np.asarray(snap.devices.gpu_free)
    numa = np.asarray(snap.devices.gpu_numa)
    assert free[0, 0, 0] == 100.0 and free[0, 1, 0] == 0.0
    assert numa[0].tolist() == [0, 1]
    # duplicate / out-of-range minors are rejected loudly
    b2 = SnapshotBuilder(max_nodes=1, max_gpu_inst=1)
    b2.add_node(Node(meta=ObjectMeta(name="n0"),
                     allocatable={CPU: 1000.0, MEM: 1000.0}))
    b2.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=3, type="gpu", resources={GC: 100.0, GM: 10.0})]))
    with pytest.raises(ValueError):
        b2.build(now=1e9)


def test_builder_rejects_heterogeneous_gpu_memory():
    # gpu_total is the per-node memory<->ratio conversion basis; two GPU
    # sizes on one node have no single basis, so the build must fail loudly
    # instead of silently keeping whichever DeviceInfo came last
    b = SnapshotBuilder(max_nodes=1, max_gpu_inst=2)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={CPU: 32000.0, MEM: 64000.0}))
    b.add_device(Device(node_name="n0", devices=[
        DeviceInfo(minor=0, type="gpu", resources={GC: 100.0, GM: 1000.0}),
        DeviceInfo(minor=1, type="gpu", resources={GC: 100.0, GM: 2000.0})]))
    with pytest.raises(ValueError, match="heterogeneous GPU memory"):
        b.build(now=1e9)


def test_builder_restores_running_allocations():
    b = make_builder(num_nodes=1, gpus=2)
    running = gpu_pod("r", core=200, ratio=200)
    running.node_name = "n0"
    running.allocated_gpu_minors = (0, 1)
    b.add_running_pod(running)
    snap, _ = b.build(now=1e9)
    free = np.asarray(snap.devices.gpu_free)
    assert np.allclose(free[0, :, 0], [0.0, 0.0])
    # node is full: another GPU pod cannot schedule
    a, _, _, _ = schedule(b, [gpu_pod("p", core=50, ratio=50)])
    assert a[0] == -1


# --- chunk-1 equivalence against greedy sequential expectation --------------


@pytest.mark.parametrize("seed", [0, 1])
def test_chunk1_matches_batch_capacity(seed):
    """Scheduling GPU pods one at a time (exact sequential semantics) and
    as one batch must place the same TOTAL demand when instances are
    interchangeable (identity may differ; capacity must not)."""
    snap = synthetic.synthetic_cluster(16, gpu_node_frac=1.0, seed=seed,
                                       gpus_per_node=4)
    pods = synthetic.synthetic_pods(48, gpu_pod_frac=1.0, seed=seed + 10)
    cfg = LoadAwareConfig.make()
    res_b = core.schedule_batch(snap, pods, cfg, num_rounds=4, k_choices=4)
    placed_b = (np.asarray(res_b.assignment) >= 0)

    s = snap
    placed_seq = np.zeros(48, bool)
    order = np.argsort(-np.asarray(pods.priority), kind="stable")
    for i in order:
        one = synthetic.slice_batch(pods, int(i), 1)
        r = core.schedule_batch(s, one, cfg, num_rounds=1, k_choices=4)
        s = r.snapshot
        placed_seq[i] = bool(np.asarray(r.assignment)[0] >= 0)
    ratio = np.asarray(pods.gpu_ratio)
    count = np.where(ratio > 100, ratio // 100, 1)
    # batched conflict resolution may differ in WHICH pods land, but total
    # placed GPU demand must match sequential within one multi-GPU pod
    assert abs((count * placed_b).sum() - (count * placed_seq).sum()) \
        <= count.max()
