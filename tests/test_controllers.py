"""Reservation lifecycle, gang directory, NodeMetric controller, koordlet
reporters, sysreconcile/blkio strategies, and descheduler compat plugins
(SURVEY.md 2.1-2.4 remaining inventory)."""

import os

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import QoSClass, ResourceKind as RK
from koordinator_tpu.descheduler import RecordingEvictor
from koordinator_tpu.descheduler.compat import (
    RemovePodsOnUnschedulableNodes,
    RemovePodsViolatingNodeSelector,
    default_evictor_filter,
)
from koordinator_tpu.scheduler.controllers import (
    GangDirectory,
    ReservationController,
)
from koordinator_tpu.slo_controller.nodemetric import NodeMetricController


# --- reservation lifecycle --------------------------------------------------


def test_reservation_phase_transitions_and_gc():
    ctl = ReservationController(gc_seconds=100.0)
    r = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=1.0,
                        ttl_seconds=50.0, requests={RK.CPU: 100.0})
    assert ctl.reconcile([r], now=1.0)[0].phase == "Pending"
    r.node_name = "n0"
    assert ctl.reconcile([r], now=2.0)[0].phase == "Available"
    # TTL expiry
    assert ctl.reconcile([r], now=60.0)[0].phase == "Expired"
    # GC after terminal hold period
    assert ctl.reconcile([r], now=100.0) == [r]
    assert ctl.reconcile([r], now=200.0) == []


def test_reservation_zero_ttl_never_expires():
    ctl = ReservationController()
    r = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=1.0,
                        ttl_seconds=0.0, node_name="n0",
                        requests={RK.CPU: 1.0})
    assert ctl.reconcile([r], now=1e12)[0].phase == "Available"


def test_reservation_allocate_once_succeeds_when_consumed():
    ctl = ReservationController()
    r = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=0.0,
                        node_name="n0", allocate_once=True,
                        requests={RK.CPU: 100.0},
                        allocated={RK.CPU: 100.0})
    assert ctl.reconcile([r], now=1.0)[0].phase == "Succeeded"


# --- gang directory ---------------------------------------------------------


def test_gang_quorum_and_wait_timeout():
    d = GangDirectory(default_wait_time_seconds=60.0)
    g = d.add_pod("ml/gang", "p0", min_member=3)
    d.add_pod("ml/gang", "p1")
    assert not g.quorum
    d.add_pod("ml/gang", "p2")
    assert g.quorum and g.total_member == 3
    d.mark_assumed("ml/gang", "p0", now=0.0)
    d.mark_assumed("ml/gang", "p1", now=5.0)
    assert d.expire_waits(now=30.0) == []       # within wait time
    assert d.expire_waits(now=100.0) == ["ml/gang"]
    assert d.assumed_count("ml/gang") == 0 and g.timeout_count == 1
    # satisfied gangs never time out
    for uid in ("p0", "p1", "p2"):
        d.mark_assumed("ml/gang", uid, now=200.0)
    assert d.expire_waits(now=1000.0) == []


def test_gang_pod_group_sync_and_removal():
    d = GangDirectory()
    d.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                    min_member=2, mode="NonStrict",
                                    wait_time_seconds=30.0))
    # the CR spec is authoritative: a pod annotation cannot lower quorum
    d.add_pod("g", "p0", min_member=1)
    rows = d.to_pod_groups()
    assert rows[0].min_member == 2 and rows[0].mode == "NonStrict"
    # CR-backed record survives member churn; annotation gangs do not
    d.remove_pod("g", "p0")
    assert d.gangs["g"].min_member == 2
    d.delete_pod_group("g")
    d.add_pod("anno", "p0", min_member=3)
    d.remove_pod("anno", "p0")
    assert d.gangs == {}


def test_reservation_external_delete_does_not_poison_gc():
    ctl = ReservationController(gc_seconds=100.0)
    r1 = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=1.0,
                         ttl_seconds=5.0, node_name="n0",
                         requests={RK.CPU: 1.0})
    ctl.reconcile([r1], now=10.0)          # expired, tracked
    ctl.reconcile([], now=20.0)            # externally deleted
    r2 = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=500.0,
                         ttl_seconds=5.0, node_name="n0",
                         requests={RK.CPU: 1.0})
    # same-named successor must get its own full terminal hold period
    assert ctl.reconcile([r2], now=510.0) == [r2]
    assert ctl.reconcile([r2], now=550.0) == [r2]


# --- nodemetric controller --------------------------------------------------


def test_nodemetric_controller_lifecycle():
    ctl = NodeMetricController()
    nodes = [api.Node(meta=api.ObjectMeta(name=f"n{i}")) for i in range(2)]
    rows = ctl.reconcile(nodes)
    assert [m.node_name for m in rows] == ["n0", "n1"]
    assert rows[0].report_interval_seconds == 60.0
    ctl.observe_status(api.NodeMetric(node_name="n0", update_time=123.0,
                                      node_usage={RK.CPU: 10.0}))
    assert ctl.metrics["n0"].update_time == 123.0
    rows = ctl.reconcile(nodes[:1])
    assert len(rows) == 1 and "n1" not in ctl.metrics


# --- koordlet reporters + strategies ----------------------------------------


def test_topology_and_device_reporters(tmp_path):
    from koordinator_tpu.koordlet.statesinformer import (
        DeviceReporter,
        StatesInformer,
        TopologyReporter,
    )
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), num_cpus=8, numa_nodes=2)
    informer = StatesInformer()
    topo = TopologyReporter(host, informer, "n0").report()
    assert len(topo.zones) == 2
    assert sum(z.cpus_milli for z in topo.zones) == 8000.0
    assert informer.get_topology() is topo

    inventory = [api.DeviceInfo(minor=m, type="gpu",
                                resources={RK.GPU_CORE: 100.0})
                 for m in range(4)]
    device = DeviceReporter(lambda: inventory, informer, "n0").report()
    assert len(device.devices) == 4
    assert informer.get_device() is device


def test_sysreconcile_and_blkio(tmp_path):
    from koordinator_tpu.koordlet.qosmanager import (
        BlkIOReconcile,
        SystemReconcile,
    )
    from koordinator_tpu.koordlet.resourceexecutor import Executor
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), mem_bytes=16 << 30)
    os.makedirs(os.path.join(host.proc_root, "sys", "vm"), exist_ok=True)
    for tier in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        os.makedirs(os.path.join(host.cgroup_root, "blkio", tier),
                    exist_ok=True)
    informer = StatesInformer()
    informer.set_node_slo(api.NodeSLO(
        node_name="n0",
        system=api.SystemStrategy(min_free_kbytes_factor=100.0,
                                  watermark_scale_factor=150.0)))
    executor = Executor(host)
    SystemReconcile(informer, executor).reconcile(now=0.0)
    vm = os.path.join(host.proc_root, "sys", "vm")
    # 16GiB = 16777216 KiB; factor 100/10000 -> 167772
    assert open(os.path.join(vm, "min_free_kbytes")).read() == "167772"
    assert open(os.path.join(vm, "watermark_scale_factor")).read() == "150"

    BlkIOReconcile(informer, executor).reconcile(now=0.0)
    assert host.read_cgroup("kubepods/besteffort", "blkio.weight") == "100"
    assert host.read_cgroup("kubepods/burstable", "blkio.weight") == "500"


def test_gated_strategies_off_by_default(tmp_path):
    from koordinator_tpu.features import FeatureGate, FeatureSpec
    from koordinator_tpu.koordlet.qosmanager import (
        BlkIOReconcile,
        RecordingEvictor,
        SystemReconcile,
        default_qos_manager,
    )
    from koordinator_tpu.koordlet.metriccache import MetricCache
    from koordinator_tpu.koordlet.resourceexecutor import Executor
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path))
    informer = StatesInformer()
    mgr = default_qos_manager(informer, MetricCache(), Executor(host),
                              RecordingEvictor())
    kinds = {type(s) for s in mgr.strategies}
    assert SystemReconcile not in kinds and BlkIOReconcile not in kinds
    gate = FeatureGate({"SystemConfig": FeatureSpec(default=True),
                        "BlkIOReconcile": FeatureSpec(default=True)})
    mgr_on = default_qos_manager(informer, MetricCache(), Executor(host),
                                 RecordingEvictor(), feature_gate=gate)
    kinds_on = {type(s) for s in mgr_on.strategies}
    assert SystemReconcile in kinds_on and BlkIOReconcile in kinds_on


def test_cpus_per_core_multi_socket(tmp_path):
    # core_id repeats across sockets: SMT width must not double
    from koordinator_tpu.koordlet.statesinformer import (
        StatesInformer,
        TopologyReporter,
    )
    from koordinator_tpu.koordlet.system import ProcessorInfo
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), num_cpus=8, numa_nodes=2)
    cpus = [ProcessorInfo(cpu_id=i, core_id=(i // 2) % 2,
                          socket_id=i // 4, node_id=i // 4)
            for i in range(8)]
    host.cpu_topology = lambda: cpus
    topo = TopologyReporter(host, StatesInformer(), "n0").report()
    assert topo.cpus_per_core == 2


# --- descheduler compat plugins ---------------------------------------------


def mk_pod(name, node, **kw):
    return api.Pod(meta=api.ObjectMeta(name=name), node_name=node, **kw)


def test_default_evictor_filter():
    f = default_evictor_filter(priority_threshold=9000)
    assert f(mk_pod("ok", "n", priority=5000))
    assert not f(mk_pod("ds", "n", is_daemonset=True))
    assert not f(mk_pod("sys", "n", qos_label="SYSTEM"))
    assert not f(mk_pod("hi", "n", priority=9500))
    shielded = mk_pod("s", "n")
    shielded.meta.annotations[
        "scheduling.koordinator.sh/preemptible"] = "false"
    assert not f(shielded)


def test_remove_pods_violating_node_selector():
    ev = RecordingEvictor()
    moved = mk_pod("moved", "n0", node_selector={"pool": "ml"})
    fine = mk_pod("fine", "n0", node_selector={"pool": "web"})
    plugin = RemovePodsViolatingNodeSelector(
        ev, lambda: {"n0": [moved, fine]})
    plugin.deschedule([api.Node(meta=api.ObjectMeta(
        name="n0", labels={"pool": "web"}))])
    assert [e.pod.meta.name for e in ev.evictions] == ["moved"]


def test_remove_pods_on_unschedulable_nodes():
    ev = RecordingEvictor()
    plugin = RemovePodsOnUnschedulableNodes(
        ev, lambda: {"n0": [mk_pod("a", "n0")], "n1": [mk_pod("b", "n1")]})
    plugin.deschedule([
        api.Node(meta=api.ObjectMeta(name="n0"), unschedulable=True),
        api.Node(meta=api.ObjectMeta(name="n1"))])
    assert [e.pod.meta.name for e in ev.evictions] == ["a"]


# --- gang match policies + gang groups (coscheduling.go:55-61) --------------


def test_gang_match_policy_only_waiting():
    """only-waiting counts just the members still at the Permit barrier
    (core.go:163-165): binding a member removes it from the count."""
    d = GangDirectory()
    d.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                    min_member=2,
                                    match_policy="only-waiting"))
    for uid in ("p0", "p1"):
        d.add_pod("g", uid)
        d.mark_assumed("g", uid, now=0.0)
    g = d.gangs["g"]
    assert g.satisfied
    d.mark_bound("g", "p0")
    assert not g.satisfied          # 1 waiting < minMember 2
    d.add_pod("g", "p2")
    d.mark_assumed("g", "p2", now=1.0)
    assert g.satisfied              # p1 + p2 waiting


def test_gang_match_policy_waiting_and_running():
    """waiting-and-running counts every assumed member, bound or not, but
    does NOT latch: losing a member drops satisfaction (core.go:166-167)."""
    d = GangDirectory()
    d.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                    min_member=2,
                                    match_policy="waiting-and-running"))
    for uid in ("p0", "p1"):
        d.add_pod("g", uid)
        d.mark_assumed("g", uid, now=0.0)
    g = d.gangs["g"]
    d.mark_bound("g", "p0")
    assert g.satisfied              # bound still counts
    d.remove_pod("g", "p1")
    assert not g.satisfied          # member gone, no latch


def test_gang_match_policy_once_satisfied_latches():
    """The default policy latches forever once minMember was reached
    (gang.go:59-62): later member churn cannot unsatisfy the gang, and a
    latched gang never Permit-times-out."""
    d = GangDirectory()
    d.add_pod("g", "p0", min_member=2)
    d.add_pod("g", "p1")
    d.mark_assumed("g", "p0", now=0.0)
    d.mark_assumed("g", "p1", now=0.0)
    g = d.gangs["g"]
    assert g.satisfied and g.once_satisfied
    d.remove_pod("g", "p1")
    assert g.satisfied              # latch holds below minMember
    d.mark_assumed("g", "p2", now=10.0)
    assert d.expire_waits(now=10_000.0) == []


def test_gang_annotation_spec_parsing():
    """The full pod-annotation gang protocol (TryInitByPodConfig,
    gang.go:120-175): mode, match policy, waiting-time, groups; illegal
    values fall back to defaults."""
    from koordinator_tpu.api import extension as ext

    d = GangDirectory(default_wait_time_seconds=600.0)
    g = d.add_pod("ml/a", "p0", annotations={
        ext.ANNOTATION_GANG_NAME: "ml/a",
        ext.ANNOTATION_GANG_MIN_NUM: "2",
        ext.ANNOTATION_GANG_MODE: "NonStrict",
        ext.ANNOTATION_GANG_MATCH_POLICY: "only-waiting",
        ext.ANNOTATION_GANG_WAIT_TIME: "120",
        ext.ANNOTATION_GANG_GROUPS: '["ml/a", "ml/b"]',
    })
    assert (g.min_member, g.mode, g.match_policy) == \
        (2, "NonStrict", "only-waiting")
    assert g.wait_time_seconds == 120.0
    assert g.gang_group == ("ml/a", "ml/b")
    # illegal values: defaults win, the gang still forms
    bad = d.add_pod("ml/bad", "q0", annotations={
        ext.ANNOTATION_GANG_NAME: "ml/bad",
        ext.ANNOTATION_GANG_MIN_NUM: "zero",
        ext.ANNOTATION_GANG_MODE: "Sloppy",
        ext.ANNOTATION_GANG_MATCH_POLICY: "sometimes",
        ext.ANNOTATION_GANG_GROUPS: "not-json",
    })
    assert (bad.min_member, bad.mode, bad.match_policy) == \
        (1, "Strict", "once-satisfied")
    assert bad.gang_group == ("ml/bad",)
    # no gang declared -> None
    assert ext.parse_gang_annotations({}) is None


def test_gang_group_bind_barrier_and_group_rejection():
    """Gangs bundled by AnnotationGangGroups bind only together, and a
    Permit timeout rejects the WHOLE group (rejectGangGroupById), sparing
    already-bound members."""
    from koordinator_tpu.api import extension as ext

    d = GangDirectory(default_wait_time_seconds=60.0)
    anno_a = {ext.ANNOTATION_GANG_NAME: "a",
              ext.ANNOTATION_GANG_MIN_NUM: "1",
              ext.ANNOTATION_GANG_GROUPS: '["a", "b"]'}
    anno_b = {ext.ANNOTATION_GANG_NAME: "b",
              ext.ANNOTATION_GANG_MIN_NUM: "2",
              ext.ANNOTATION_GANG_GROUPS: '["a", "b"]'}
    d.add_pod("a", "a0", annotations=anno_a)
    d.add_pod("b", "b0", annotations=anno_b)
    d.add_pod("b", "b1", annotations=anno_b)
    d.mark_assumed("a", "a0", now=0.0)
    assert d.gangs["a"].satisfied
    assert not d.group_satisfied("a")       # sibling b not satisfied
    d.mark_assumed("b", "b0", now=1.0)
    d.mark_assumed("b", "b1", now=1.0)
    assert d.group_satisfied("a") and d.group_satisfied("b")
    # fresh group where b never completes: a's member is released too
    d2 = GangDirectory(default_wait_time_seconds=60.0)
    d2.add_pod("a", "a0", annotations=anno_a)
    d2.add_pod("b", "b0", annotations=anno_b)
    d2.add_pod("b", "b1", annotations=anno_b)
    # a latched (min 1) but b waits with one member; the group can't bind
    d2.gangs["a"].match_policy = "waiting-and-running"  # avoid latch skip
    d2.mark_assumed("a", "a0", now=0.0)
    d2.mark_bound("a", "a0")  # wrong in real flow (group gate), but proves
    # bound members survive group rejection below
    d2.mark_assumed("b", "b0", now=0.0)
    timed = d2.expire_waits(now=100.0)
    assert "b" in timed
    assert d2.assumed_count("b") == 0
    # a's bound member survives; its waiting set was empty
    assert d2.gangs["a"].assumed == {"a0"}


def test_gang_timer_resets_when_no_members_waiting():
    """Regression: a stale first_assumed_at must not instantly expire the
    next waiter. Deleting (or binding) the last waiting member clears the
    pending-timeout timer."""
    d = GangDirectory()
    d.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                    min_member=2, wait_time_seconds=60.0))
    d.add_pod("g", "p0")
    d.mark_assumed("g", "p0", now=0.0)
    d.remove_pod("g", "p0")            # waiter gone -> timer gone
    d.add_pod("g", "p1")
    d.mark_assumed("g", "p1", now=100.5)
    assert d.expire_waits(now=101.0) == []   # p1 waited 0.5s, not 100.5s
    assert d.expire_waits(now=161.0) == ["g"]
    # same via bind: only-waiting gang whose sole assumed member bound
    d2 = GangDirectory()
    d2.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="h"),
                                     min_member=2, wait_time_seconds=60.0,
                                     match_policy="only-waiting"))
    d2.add_pod("h", "q0")
    d2.mark_assumed("h", "q0", now=0.0)
    d2.mark_bound("h", "q0")
    d2.add_pod("h", "q1")
    d2.mark_assumed("h", "q1", now=100.0)
    assert d2.expire_waits(now=120.0) == []


def test_gang_groups_always_include_own_name():
    """Regression: groups='[\"b\"]' on gang a must still put a in its own
    group, or expiry could never release a's waiters."""
    from koordinator_tpu.api import extension as ext

    d = GangDirectory(default_wait_time_seconds=60.0)
    g = d.add_pod("a", "a0", annotations={
        ext.ANNOTATION_GANG_NAME: "a",
        ext.ANNOTATION_GANG_MIN_NUM: "2",
        ext.ANNOTATION_GANG_GROUPS: '["b"]'})
    assert g.gang_group == ("a", "b")
    d.mark_assumed("a", "a0", now=0.0)
    assert d.expire_waits(now=100.0) == ["a"]
    assert d.assumed_count("a") == 0


def test_gang_timer_rearms_when_satisfaction_drops():
    """Regression: satisfaction dropping (bind under only-waiting, member
    loss under waiting-and-running) after the timer cleared must re-arm
    the Permit timer so stranded waiters still expire."""
    d = GangDirectory()
    d.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                    min_member=2, wait_time_seconds=60.0,
                                    match_policy="only-waiting"))
    d.add_pod("g", "p0")
    d.add_pod("g", "p1")
    d.mark_assumed("g", "p0", now=0.0)
    d.mark_assumed("g", "p1", now=10.0)
    assert d.gangs["g"].first_assumed_at is None   # satisfied clears timer
    d.mark_bound("g", "p0")                        # satisfaction drops
    assert d.gangs["g"].first_assumed_at is not None
    assert d.expire_waits(now=1_000.0) == ["g"]    # p1 is released
    assert d.gangs["g"].assumed == {"p0"}
    # member-loss variant under waiting-and-running
    d2 = GangDirectory()
    d2.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="h"),
                                     min_member=2, wait_time_seconds=60.0,
                                     match_policy="waiting-and-running"))
    d2.add_pod("h", "q0")
    d2.add_pod("h", "q1")
    d2.mark_assumed("h", "q0", now=0.0)
    d2.mark_assumed("h", "q1", now=5.0)
    d2.remove_pod("h", "q0")
    assert d2.gangs["h"].first_assumed_at is not None
    assert d2.expire_waits(now=1_000.0) == ["h"]
