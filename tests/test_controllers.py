"""Reservation lifecycle, gang directory, NodeMetric controller, koordlet
reporters, sysreconcile/blkio strategies, and descheduler compat plugins
(SURVEY.md 2.1-2.4 remaining inventory)."""

import os

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import QoSClass, ResourceKind as RK
from koordinator_tpu.descheduler import RecordingEvictor
from koordinator_tpu.descheduler.compat import (
    RemovePodsOnUnschedulableNodes,
    RemovePodsViolatingNodeSelector,
    default_evictor_filter,
)
from koordinator_tpu.scheduler.controllers import (
    GangDirectory,
    ReservationController,
)
from koordinator_tpu.slo_controller.nodemetric import NodeMetricController


# --- reservation lifecycle --------------------------------------------------


def test_reservation_phase_transitions_and_gc():
    ctl = ReservationController(gc_seconds=100.0)
    r = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=1.0,
                        ttl_seconds=50.0, requests={RK.CPU: 100.0})
    assert ctl.reconcile([r], now=1.0)[0].phase == "Pending"
    r.node_name = "n0"
    assert ctl.reconcile([r], now=2.0)[0].phase == "Available"
    # TTL expiry
    assert ctl.reconcile([r], now=60.0)[0].phase == "Expired"
    # GC after terminal hold period
    assert ctl.reconcile([r], now=100.0) == [r]
    assert ctl.reconcile([r], now=200.0) == []


def test_reservation_zero_ttl_never_expires():
    ctl = ReservationController()
    r = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=1.0,
                        ttl_seconds=0.0, node_name="n0",
                        requests={RK.CPU: 1.0})
    assert ctl.reconcile([r], now=1e12)[0].phase == "Available"


def test_reservation_allocate_once_succeeds_when_consumed():
    ctl = ReservationController()
    r = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=0.0,
                        node_name="n0", allocate_once=True,
                        requests={RK.CPU: 100.0},
                        allocated={RK.CPU: 100.0})
    assert ctl.reconcile([r], now=1.0)[0].phase == "Succeeded"


# --- gang directory ---------------------------------------------------------


def test_gang_quorum_and_wait_timeout():
    d = GangDirectory(default_wait_time_seconds=60.0)
    g = d.add_pod("ml/gang", "p0", min_member=3)
    d.add_pod("ml/gang", "p1")
    assert not g.quorum
    d.add_pod("ml/gang", "p2")
    assert g.quorum and g.total_member == 3
    d.mark_assumed("ml/gang", "p0", now=0.0)
    d.mark_assumed("ml/gang", "p1", now=5.0)
    assert d.expire_waits(now=30.0) == []       # within wait time
    assert d.expire_waits(now=100.0) == ["ml/gang"]
    assert d.assumed_count("ml/gang") == 0 and g.timeout_count == 1
    # satisfied gangs never time out
    for uid in ("p0", "p1", "p2"):
        d.mark_assumed("ml/gang", uid, now=200.0)
    assert d.expire_waits(now=1000.0) == []


def test_gang_pod_group_sync_and_removal():
    d = GangDirectory()
    d.upsert_pod_group(api.PodGroup(meta=api.ObjectMeta(name="g"),
                                    min_member=2, mode="NonStrict",
                                    wait_time_seconds=30.0))
    # the CR spec is authoritative: a pod annotation cannot lower quorum
    d.add_pod("g", "p0", min_member=1)
    rows = d.to_pod_groups()
    assert rows[0].min_member == 2 and rows[0].mode == "NonStrict"
    # CR-backed record survives member churn; annotation gangs do not
    d.remove_pod("g", "p0")
    assert d.gangs["g"].min_member == 2
    d.delete_pod_group("g")
    d.add_pod("anno", "p0", min_member=3)
    d.remove_pod("anno", "p0")
    assert d.gangs == {}


def test_reservation_external_delete_does_not_poison_gc():
    ctl = ReservationController(gc_seconds=100.0)
    r1 = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=1.0,
                         ttl_seconds=5.0, node_name="n0",
                         requests={RK.CPU: 1.0})
    ctl.reconcile([r1], now=10.0)          # expired, tracked
    ctl.reconcile([], now=20.0)            # externally deleted
    r2 = api.Reservation(meta=api.ObjectMeta(name="r"), create_time=500.0,
                         ttl_seconds=5.0, node_name="n0",
                         requests={RK.CPU: 1.0})
    # same-named successor must get its own full terminal hold period
    assert ctl.reconcile([r2], now=510.0) == [r2]
    assert ctl.reconcile([r2], now=550.0) == [r2]


# --- nodemetric controller --------------------------------------------------


def test_nodemetric_controller_lifecycle():
    ctl = NodeMetricController()
    nodes = [api.Node(meta=api.ObjectMeta(name=f"n{i}")) for i in range(2)]
    rows = ctl.reconcile(nodes)
    assert [m.node_name for m in rows] == ["n0", "n1"]
    assert rows[0].report_interval_seconds == 60.0
    ctl.observe_status(api.NodeMetric(node_name="n0", update_time=123.0,
                                      node_usage={RK.CPU: 10.0}))
    assert ctl.metrics["n0"].update_time == 123.0
    rows = ctl.reconcile(nodes[:1])
    assert len(rows) == 1 and "n1" not in ctl.metrics


# --- koordlet reporters + strategies ----------------------------------------


def test_topology_and_device_reporters(tmp_path):
    from koordinator_tpu.koordlet.statesinformer import (
        DeviceReporter,
        StatesInformer,
        TopologyReporter,
    )
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), num_cpus=8, numa_nodes=2)
    informer = StatesInformer()
    topo = TopologyReporter(host, informer, "n0").report()
    assert len(topo.zones) == 2
    assert sum(z.cpus_milli for z in topo.zones) == 8000.0
    assert informer.get_topology() is topo

    inventory = [api.DeviceInfo(minor=m, type="gpu",
                                resources={RK.GPU_CORE: 100.0})
                 for m in range(4)]
    device = DeviceReporter(lambda: inventory, informer, "n0").report()
    assert len(device.devices) == 4
    assert informer.get_device() is device


def test_sysreconcile_and_blkio(tmp_path):
    from koordinator_tpu.koordlet.qosmanager import (
        BlkIOReconcile,
        SystemReconcile,
    )
    from koordinator_tpu.koordlet.resourceexecutor import Executor
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), mem_bytes=16 << 30)
    os.makedirs(os.path.join(host.proc_root, "sys", "vm"), exist_ok=True)
    for tier in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        os.makedirs(os.path.join(host.cgroup_root, "blkio", tier),
                    exist_ok=True)
    informer = StatesInformer()
    informer.set_node_slo(api.NodeSLO(
        node_name="n0",
        system=api.SystemStrategy(min_free_kbytes_factor=100.0,
                                  watermark_scale_factor=150.0)))
    executor = Executor(host)
    SystemReconcile(informer, executor).reconcile(now=0.0)
    vm = os.path.join(host.proc_root, "sys", "vm")
    # 16GiB = 16777216 KiB; factor 100/10000 -> 167772
    assert open(os.path.join(vm, "min_free_kbytes")).read() == "167772"
    assert open(os.path.join(vm, "watermark_scale_factor")).read() == "150"

    BlkIOReconcile(informer, executor).reconcile(now=0.0)
    assert host.read_cgroup("kubepods/besteffort", "blkio.weight") == "100"
    assert host.read_cgroup("kubepods/burstable", "blkio.weight") == "500"


def test_gated_strategies_off_by_default(tmp_path):
    from koordinator_tpu.features import FeatureGate, FeatureSpec
    from koordinator_tpu.koordlet.qosmanager import (
        BlkIOReconcile,
        RecordingEvictor,
        SystemReconcile,
        default_qos_manager,
    )
    from koordinator_tpu.koordlet.metriccache import MetricCache
    from koordinator_tpu.koordlet.resourceexecutor import Executor
    from koordinator_tpu.koordlet.statesinformer import StatesInformer
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path))
    informer = StatesInformer()
    mgr = default_qos_manager(informer, MetricCache(), Executor(host),
                              RecordingEvictor())
    kinds = {type(s) for s in mgr.strategies}
    assert SystemReconcile not in kinds and BlkIOReconcile not in kinds
    gate = FeatureGate({"SystemConfig": FeatureSpec(default=True),
                        "BlkIOReconcile": FeatureSpec(default=True)})
    mgr_on = default_qos_manager(informer, MetricCache(), Executor(host),
                                 RecordingEvictor(), feature_gate=gate)
    kinds_on = {type(s) for s in mgr_on.strategies}
    assert SystemReconcile in kinds_on and BlkIOReconcile in kinds_on


def test_cpus_per_core_multi_socket(tmp_path):
    # core_id repeats across sockets: SMT width must not double
    from koordinator_tpu.koordlet.statesinformer import (
        StatesInformer,
        TopologyReporter,
    )
    from koordinator_tpu.koordlet.system import ProcessorInfo
    from koordinator_tpu.koordlet.testing import FakeHost

    host = FakeHost(str(tmp_path), num_cpus=8, numa_nodes=2)
    cpus = [ProcessorInfo(cpu_id=i, core_id=(i // 2) % 2,
                          socket_id=i // 4, node_id=i // 4)
            for i in range(8)]
    host.cpu_topology = lambda: cpus
    topo = TopologyReporter(host, StatesInformer(), "n0").report()
    assert topo.cpus_per_core == 2


# --- descheduler compat plugins ---------------------------------------------


def mk_pod(name, node, **kw):
    return api.Pod(meta=api.ObjectMeta(name=name), node_name=node, **kw)


def test_default_evictor_filter():
    f = default_evictor_filter(priority_threshold=9000)
    assert f(mk_pod("ok", "n", priority=5000))
    assert not f(mk_pod("ds", "n", is_daemonset=True))
    assert not f(mk_pod("sys", "n", qos_label="SYSTEM"))
    assert not f(mk_pod("hi", "n", priority=9500))
    shielded = mk_pod("s", "n")
    shielded.meta.annotations[
        "scheduling.koordinator.sh/preemptible"] = "false"
    assert not f(shielded)


def test_remove_pods_violating_node_selector():
    ev = RecordingEvictor()
    moved = mk_pod("moved", "n0", node_selector={"pool": "ml"})
    fine = mk_pod("fine", "n0", node_selector={"pool": "web"})
    plugin = RemovePodsViolatingNodeSelector(
        ev, lambda: {"n0": [moved, fine]})
    plugin.deschedule([api.Node(meta=api.ObjectMeta(
        name="n0", labels={"pool": "web"}))])
    assert [e.pod.meta.name for e in ev.evictions] == ["moved"]


def test_remove_pods_on_unschedulable_nodes():
    ev = RecordingEvictor()
    plugin = RemovePodsOnUnschedulableNodes(
        ev, lambda: {"n0": [mk_pod("a", "n0")], "n1": [mk_pod("b", "n1")]})
    plugin.deschedule([
        api.Node(meta=api.ObjectMeta(name="n0"), unschedulable=True),
        api.Node(meta=api.ObjectMeta(name="n1"))])
    assert [e.pod.meta.name for e in ev.evictions] == ["a"]
