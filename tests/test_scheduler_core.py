"""schedule_batch golden + invariant tests.

- chunk-size-1 equivalence: feeding pods one at a time through the batched
  kernel (carrying the snapshot between calls) must reproduce the sequential
  oracle exactly — the batched commit degenerates to scheduleOne.
- full-batch invariants: no node/quota overcommit, priority wins contention,
  strict gangs are all-or-nothing.
"""

import numpy as np
import pytest

from koordinator_tpu.api.extension import ResourceKind as RK
from koordinator_tpu.api.types import (
    ElasticQuota, Node, NodeMetric, ObjectMeta, Pod, PodGroup,
)
from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.snapshot.builder import SnapshotBuilder

from oracle import OracleArgs, OracleQuota, OracleScheduler, make_oracle_nodes

NOW = 1_700_000_000.0


def small_cluster(rng, num_nodes=12):
    b = SnapshotBuilder(max_nodes=num_nodes)
    for i in range(num_nodes):
        cpu = float(rng.choice([8000, 16000]))
        mem = float(rng.choice([16, 32])) * 1024
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: cpu, RK.MEMORY: mem}))
        b.set_node_metric(NodeMetric(
            node_name=f"n{i}", update_time=NOW - 2,
            node_usage={RK.CPU: float(rng.integers(0, cpu // 200) * 100),
                        RK.MEMORY: float(rng.integers(0, mem // 512) * 256)}))
    return b


def rand_pods(rng, count):
    return [Pod(meta=ObjectMeta(name=f"p{j}"),
                requests={RK.CPU: float(rng.integers(2, 12) * 500),
                          RK.MEMORY: float(rng.integers(2, 16) * 512)},
                priority=int(rng.choice([9100, 7100, 5100])))
            for j in range(count)]


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_chunk1_sequential_equivalence(seed):
    rng = np.random.default_rng(seed)
    b = small_cluster(rng)
    pods = rand_pods(rng, 30)
    snap, ctx = b.build(now=NOW)
    cfg = loadaware.LoadAwareConfig.make()

    # oracle runs in priority order; feed chunks of 1 in the same order
    order = sorted(range(len(pods)), key=lambda i: (-(pods[i].priority or 0), i))
    got = np.full((len(pods),), -1, np.int64)
    cur = snap
    for i in order:
        batch = b.build_pod_batch([pods[i]], ctx)
        res = core.schedule_batch(cur, batch, cfg, num_rounds=1)
        got[i] = int(res.assignment[0])
        cur = res.snapshot

    oracle = OracleScheduler(make_oracle_nodes(b, NOW), OracleArgs.default())
    want = oracle.schedule(pods)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("seed", [0, 7])
def test_full_batch_invariants(seed):
    rng = np.random.default_rng(seed)
    b = small_cluster(rng)
    pods = rand_pods(rng, 80)  # oversubscribed on purpose
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(pods, ctx)
    cfg = loadaware.LoadAwareConfig.make()
    res = core.schedule_batch(snap, batch, cfg, num_rounds=6)
    a = np.asarray(res.assignment)
    req = np.asarray(res.snapshot.nodes.requested)
    alloc = np.asarray(snap.nodes.allocatable)

    # 1. committed `requested` equals initial + sum of placed pod requests
    expect = np.asarray(snap.nodes.requested).copy()
    for j, pod in enumerate(pods):
        if a[j] >= 0:
            expect[a[j], int(RK.CPU)] += pod.requests[RK.CPU]
            expect[a[j], int(RK.MEMORY)] += pod.requests[RK.MEMORY]
    np.testing.assert_allclose(req, expect, atol=1.0)

    # 2. no overcommit anywhere
    assert np.all(req <= alloc + 1.0)

    # 3. every unplaced pod truly has no allowed node left in the final
    #    state: it must fail fit or the LoadAware gate everywhere
    from koordinator_tpu.scheduler.plugins import loadaware as la
    final_mask = np.asarray(la.filter_mask(res.snapshot.nodes, batch, cfg))
    reqs = np.asarray(batch.requests)
    for j in np.where(a < 0)[0]:
        fits = np.all(req + reqs[j][None, :] <= alloc + 0.5, axis=1)
        allowed = fits & final_mask[j]
        assert not allowed.any(), (
            f"pod {j} unplaced but node(s) {np.where(allowed)[0]} would "
            f"still admit it")

    # 4. priority respected under contention: count scheduled per class
    prio = np.array([p.priority for p in pods])
    if (a < 0).any() and (a >= 0).any():
        # the lowest scheduled priority must not beat an unscheduled
        # higher-priority pod that requested strictly less of everything
        for j in np.where(a < 0)[0]:
            for k in np.where(a >= 0)[0]:
                if prio[j] > prio[k]:
                    dominated = (pods[j].requests[RK.CPU] <= pods[k].requests[RK.CPU]
                                 and pods[j].requests[RK.MEMORY] <= pods[k].requests[RK.MEMORY])
                    assert not dominated, (
                        f"pod {j} (prio {prio[j]}) unscheduled but dominated "
                        f"pod {k} (prio {prio[k]}) was scheduled")


def test_quota_gate_and_accounting():
    b = SnapshotBuilder(max_nodes=4, max_quotas=4)
    for i in range(4):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 64000, RK.MEMORY: 65536}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={RK.CPU: 0.0}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="root"), is_parent=True,
                             max={RK.CPU: 20000, RK.MEMORY: 1 << 30}))
    b.add_quota(ElasticQuota(meta=ObjectMeta(name="team-a"), parent="root",
                             max={RK.CPU: 12000, RK.MEMORY: 1 << 30}))
    snap, ctx = b.build(now=NOW)
    # runtime == max for this test (water-filling comes separately)
    runtime = np.asarray(snap.quotas.runtime).copy()
    runtime[0] = [20000, 1 << 30] + [np.inf] * 9
    runtime[1] = [12000, 1 << 30] + [np.inf] * 9
    snap = snap.replace(quotas=snap.quotas.replace(runtime=runtime))

    pods = [Pod(meta=ObjectMeta(name=f"p{j}"), priority=9000 - j,
                requests={RK.CPU: 4000.0, RK.MEMORY: 1024.0},
                quota_name="team-a") for j in range(6)]
    batch = b.build_pod_batch(pods, ctx)
    cfg = loadaware.LoadAwareConfig.make()
    res = core.schedule_batch(snap, batch, cfg, num_rounds=4)
    a = np.asarray(res.assignment)
    # team-a runtime 12000 CPU admits exactly 3 pods of 4000
    assert (a >= 0).sum() == 3
    # highest-priority pods won
    assert set(np.where(a >= 0)[0]) == {0, 1, 2}
    used = np.asarray(res.snapshot.quotas.used)
    assert used[1, 0] == pytest.approx(12000)
    assert used[0, 0] == pytest.approx(12000)  # propagated to parent


def test_gang_all_or_nothing():
    b = SnapshotBuilder(max_nodes=2, max_gangs=2)
    for i in range(2):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}"),
                        allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    b.add_gang(PodGroup(meta=ObjectMeta(name="gang-big"), min_member=5,
                        total_member=5))
    b.add_gang(PodGroup(meta=ObjectMeta(name="gang-fit"), min_member=2,
                        total_member=2))
    snap, ctx = b.build(now=NOW)
    # 5 members x 6000 CPU cannot all fit on 2 x 8000 nodes -> rollback
    pods = ([Pod(meta=ObjectMeta(name=f"big{j}"), priority=9000,
                 requests={RK.CPU: 6000.0, RK.MEMORY: 512.0},
                 gang_name="gang-big") for j in range(5)]
            + [Pod(meta=ObjectMeta(name=f"fit{j}"), priority=5000,
                   requests={RK.CPU: 1000.0, RK.MEMORY: 512.0},
                   gang_name="gang-fit") for j in range(2)])
    batch = b.build_pod_batch(pods, ctx)
    cfg = loadaware.LoadAwareConfig.make()
    res = core.schedule_batch(snap, batch, cfg, num_rounds=4)
    a = np.asarray(res.assignment)
    assert np.all(a[:5] == -1), f"strict gang must roll back, got {a}"
    assert np.all(a[5:] >= 0), "small gang should be placed"
    assumed = np.asarray(res.snapshot.gangs.assumed)
    assert assumed[0] == 0 and assumed[1] == 2
    # rollback restored node accounting
    req = np.asarray(res.snapshot.nodes.requested)
    assert req[:, 0].sum() == pytest.approx(2000.0)


def test_gang_quorum_prefilter():
    """Gangs below quorum (member_count < minMember) are rejected up front
    (coscheduling PreFilter, core.go:220-274)."""
    b = SnapshotBuilder(max_nodes=1, max_gangs=1)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={RK.CPU: 64000, RK.MEMORY: 65536}))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW, node_usage={}))
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=4, total_member=2))
    snap, ctx = b.build(now=NOW)
    pods = [Pod(meta=ObjectMeta(name=f"p{j}"), priority=9000,
                requests={RK.CPU: 100.0}, gang_name="g") for j in range(2)]
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make())
    assert np.all(np.asarray(res.assignment) == -1)


def test_node_selector_gate():
    b = SnapshotBuilder(max_nodes=2)
    b.add_node(Node(meta=ObjectMeta(name="gpu-node", labels={"pool": "gpu"}),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    b.add_node(Node(meta=ObjectMeta(name="cpu-node", labels={"pool": "cpu"}),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    for n in ("gpu-node", "cpu-node"):
        b.set_node_metric(NodeMetric(node_name=n, update_time=NOW, node_usage={}))
    snap, ctx = b.build(now=NOW)
    pods = [Pod(meta=ObjectMeta(name="wants-gpu"), priority=9000,
                requests={RK.CPU: 100.0}, node_selector={"pool": "gpu"})]
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make())
    assert int(res.assignment[0]) == 0


def test_gang_satisfied_latch_bypasses_gates():
    """A once-satisfied gang short-circuits quorum PreFilter and the
    all-or-nothing rollback (core.go:236,286): members schedule
    individually even when the gang is below quorum or partially fails."""
    b = SnapshotBuilder(max_nodes=1, max_gangs=1)
    b.add_node(Node(meta=ObjectMeta(name="n0"),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    b.set_node_metric(NodeMetric(node_name="n0", update_time=NOW,
                                 node_usage={}))
    # below quorum (2 members seen < minMember 4) AND strict — without the
    # latch both members would be rejected up front
    b.add_gang(PodGroup(meta=ObjectMeta(name="g"), min_member=4,
                        total_member=2), satisfied=True)
    snap, ctx = b.build(now=NOW)
    pods = [Pod(meta=ObjectMeta(name=f"p{j}"), priority=9000,
                requests={RK.CPU: 6000.0}, gang_name="g")
            for j in range(2)]
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make(),
                              num_rounds=3)
    a = np.asarray(res.assignment)
    # only one fits (6000+6000 > 8000) — and it STAYS placed: a satisfied
    # strict gang is exempt from group rollback
    assert (a >= 0).sum() == 1


def test_taint_toleration_filter_and_prefer():
    """TaintToleration (the vanilla-framework gate the reference's
    extender wraps): NoSchedule taints reject non-tolerating pods,
    tolerations admit, PreferNoSchedule only demotes."""
    from koordinator_tpu.api.types import Taint, Toleration

    b = SnapshotBuilder(max_nodes=3)
    b.add_node(Node(meta=ObjectMeta(name="tainted"),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384},
                    taints=[Taint(key="gpu", value="true",
                                  effect="NoSchedule")]))
    b.add_node(Node(meta=ObjectMeta(name="soft"),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384},
                    taints=[Taint(key="maint", value="",
                                  effect="PreferNoSchedule")]))
    b.add_node(Node(meta=ObjectMeta(name="clean"),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    for nm in ("tainted", "soft", "clean"):
        b.set_node_metric(NodeMetric(node_name=nm, update_time=NOW,
                                     node_usage={}))
    snap, ctx = b.build(now=NOW)
    plain = Pod(meta=ObjectMeta(name="plain"), priority=9000,
                requests={RK.CPU: 100.0})
    tolerant = Pod(meta=ObjectMeta(name="tolerant"), priority=9000,
                   requests={RK.CPU: 100.0},
                   tolerations=[Toleration(key="gpu", value="true",
                                           effect="NoSchedule")],
                   node_selector={})
    batch = b.build_pod_batch([plain, tolerant], ctx)
    res = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make())
    a = np.asarray(res.assignment)
    # plain avoids the NoSchedule node AND prefers clean over soft
    assert a[0] == 2, a
    assert a[1] in (0, 1, 2)  # tolerant may land anywhere

    # only the tainted node has capacity -> plain is unschedulable,
    # tolerant lands there
    b2 = SnapshotBuilder(max_nodes=1)
    b2.add_node(Node(meta=ObjectMeta(name="tainted"),
                     allocatable={RK.CPU: 8000, RK.MEMORY: 16384},
                     taints=[Taint(key="gpu", value="true",
                                   effect="NoSchedule")]))
    b2.set_node_metric(NodeMetric(node_name="tainted", update_time=NOW,
                                  node_usage={}))
    snap2, ctx2 = b2.build(now=NOW)
    batch2 = b2.build_pod_batch(
        [Pod(meta=ObjectMeta(name="plain"), priority=9000,
             requests={RK.CPU: 100.0}),
         Pod(meta=ObjectMeta(name="tolerant"), priority=9000,
             requests={RK.CPU: 100.0},
             tolerations=[Toleration(key="gpu")])], ctx2)
    res2 = core.schedule_batch(snap2, batch2,
                               loadaware.LoadAwareConfig.make())
    a2 = np.asarray(res2.assignment)
    assert a2[0] == -1 and a2[1] == 0, a2


def test_prefer_no_schedule_demotes_never_filters():
    """Regression: the PreferNoSchedule penalty must not push a feasible
    node below the infeasible sentinel — a busy soft-tainted node is
    still chosen when it is the only option."""
    from koordinator_tpu.api.types import Taint

    b = SnapshotBuilder(max_nodes=1)
    b.add_node(Node(meta=ObjectMeta(name="soft"),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384},
                    taints=[Taint(key="maint",
                                  effect="PreferNoSchedule")]))
    # busy (but under the 65% filter threshold) -> low loadaware score;
    # an unclamped penalty would sink it below the -0.5 trying gate
    b.set_node_metric(NodeMetric(node_name="soft", update_time=NOW,
                                 node_usage={RK.CPU: 5000.0,
                                             RK.MEMORY: 10000.0}))
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(
        [Pod(meta=ObjectMeta(name="p"), priority=9000,
             requests={RK.CPU: 100.0})], ctx)
    res = core.schedule_batch(snap, batch, loadaware.LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) == 0


def test_blanket_toleration_tolerates_everything():
    """Regression: the empty-key (operator Exists) toleration critical
    DaemonSets carry must pass every taint."""
    from koordinator_tpu.api.types import Taint, Toleration

    assert Toleration().tolerates(Taint(key="any", value="x",
                                        effect="NoSchedule"))
    assert Toleration(effect="NoSchedule").tolerates(
        Taint(key="k", effect="NoSchedule"))
    assert not Toleration(effect="NoExecute").tolerates(
        Taint(key="k", effect="NoSchedule"))


def test_node_affinity_expressions():
    """Required nodeAffinity expressions (In/NotIn/Exists/Gt) gate like
    the equality selector, ANDed with it (upstream NodeAffinity)."""
    from koordinator_tpu.api.types import NodeSelectorRequirement as Req

    b = SnapshotBuilder(max_nodes=3)
    b.add_node(Node(meta=ObjectMeta(name="a",
                                    labels={"zone": "z1", "gen": "7"}),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    b.add_node(Node(meta=ObjectMeta(name="b",
                                    labels={"zone": "z2", "gen": "9"}),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    b.add_node(Node(meta=ObjectMeta(name="c", labels={"zone": "z3"}),
                    allocatable={RK.CPU: 8000, RK.MEMORY: 16384}))
    for nm in ("a", "b", "c"):
        b.set_node_metric(NodeMetric(node_name=nm, update_time=NOW,
                                     node_usage={}))
    snap, ctx = b.build(now=NOW)
    pods = [
        Pod(meta=ObjectMeta(name="in"), priority=9000,
            requests={RK.CPU: 100.0},
            node_affinity=[Req(key="zone", operator="In",
                               values=["z1", "z2"]),
                           Req(key="gen", operator="Gt", values=["8"])]),
        Pod(meta=ObjectMeta(name="notin"), priority=9000,
            requests={RK.CPU: 100.0},
            node_affinity=[Req(key="zone", operator="NotIn",
                               values=["z1", "z2"])]),
        Pod(meta=ObjectMeta(name="nogen"), priority=9000,
            requests={RK.CPU: 100.0},
            node_affinity=[Req(key="gen", operator="DoesNotExist")]),
    ]
    res = core.schedule_batch(snap, b.build_pod_batch(pods, ctx),
                              loadaware.LoadAwareConfig.make())
    a = np.asarray(res.assignment)
    assert a[0] == 1   # zone in {z1,z2} AND gen > 8 -> only b
    assert a[1] == 2   # NotIn z1/z2 -> c
    assert a[2] == 2   # no gen label -> c


def test_topology_spread_hard_constraint():
    """PodTopologySpread DoNotSchedule: maxSkew 1 over a zone key spreads
    members across domains; nodes lacking the key are rejected; existing
    matching pods count toward their domains."""
    from koordinator_tpu.api.types import TopologySpreadConstraint as TSC

    b = SnapshotBuilder(max_nodes=4)
    for i, zone in enumerate(("z1", "z1", "z2", None)):
        labels = {"zone": zone} if zone else {}
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}", labels=labels),
                        allocatable={RK.CPU: 64000, RK.MEMORY: 65536}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    # one member already running in z1
    b.add_running_pod(Pod(meta=ObjectMeta(name="r0", namespace="d",
                                          labels={"app": "web"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n0"))
    snap, ctx = b.build(now=NOW)
    tsc = TSC(max_skew=1, topology_key="zone",
              label_selector={"app": "web"})
    members = [Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                   labels={"app": "web"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   spread_constraints=[tsc]) for j in range(3)]
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=4)
    a = np.asarray(res.assignment)
    assert (a >= 0).all(), a
    assert (a != 3).all()          # keyless node rejected
    zones = np.where(np.isin(a, [0, 1]), "z1", "z2")
    # initial: z1=1, z2=0; after 3 more with skew 1 -> z1=2, z2=2
    z1 = int((zones == "z1").sum()) + 1
    z2 = int((zones == "z2").sum())
    assert abs(z1 - z2) <= 1, (z1, z2)


def test_topology_spread_rejects_when_skew_impossible():
    """All capacity in one domain: members beyond skew stay pending."""
    from koordinator_tpu.api.types import TopologySpreadConstraint as TSC

    b = SnapshotBuilder(max_nodes=2)
    for i, zone in enumerate(("z1", "z2")):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}",
                                        labels={"zone": zone}),
                        allocatable={RK.CPU: 8000 if i == 0 else 200,
                                     RK.MEMORY: 16384}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    snap, ctx = b.build(now=NOW)
    tsc = TSC(max_skew=1, topology_key="zone",
              label_selector={"app": "web"})
    members = [Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                   labels={"app": "web"}),
                   priority=9000, requests={RK.CPU: 500.0},
                   spread_constraints=[tsc]) for j in range(4)]
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=6)
    a = np.asarray(res.assignment)
    # z2 fits nothing (200m < 500m): z1 can take at most skew+0 = 1
    assert (a == 1).sum() == 0
    assert (a == 0).sum() == 1, a
    assert (a == -1).sum() == 3


def test_topology_spread_counts_assumed_across_batches():
    """Regression: a second batch must see the first batch's assumed
    placements in its spread counts (the builder counts running AND
    assumed pods, like every other capacity path)."""
    from koordinator_tpu.api.types import TopologySpreadConstraint as TSC

    def fresh_builder():
        b = SnapshotBuilder(max_nodes=2)
        for i, zone in enumerate(("z1", "z2")):
            b.add_node(Node(meta=ObjectMeta(name=f"n{i}",
                                            labels={"zone": zone}),
                            allocatable={RK.CPU: 64000,
                                         RK.MEMORY: 65536}))
            b.set_node_metric(NodeMetric(node_name=f"n{i}",
                                         update_time=NOW, node_usage={}))
        return b

    tsc = TSC(max_skew=1, topology_key="zone",
              label_selector={"app": "web"})

    def member(j):
        return Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                   labels={"app": "web"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   spread_constraints=[tsc])

    b = fresh_builder()
    snap, ctx = b.build(now=NOW)
    res1 = core.schedule_batch(snap, b.build_pod_batch([member(0)], ctx),
                               loadaware.LoadAwareConfig.make())
    first = int(np.asarray(res1.assignment)[0])
    assert first >= 0
    # batch 2 via a rebuilt snapshot carrying the assume
    b2 = fresh_builder()
    b2.add_assigned(member(0), f"n{first}", timestamp=NOW)
    snap2, ctx2 = b2.build(now=NOW)
    batch2 = b2.build_pod_batch([member(1)], ctx2)
    assert np.asarray(batch2.spread_count0).sum() == 1.0
    res2 = core.schedule_batch(snap2, batch2,
                               loadaware.LoadAwareConfig.make())
    second = int(np.asarray(res2.assignment)[0])
    assert second >= 0 and second != first  # spread to the other zone


def test_topology_spread_min_ignores_unreachable_domains():
    """Regression: a domain the group's pods can never enter (their own
    node selector excludes it) must not pin the skew minimum at zero
    (upstream nodeAffinityPolicy=Honor)."""
    from koordinator_tpu.api.types import TopologySpreadConstraint as TSC

    b = SnapshotBuilder(max_nodes=3)
    for i, zone in enumerate(("z1", "z2", "z3")):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}",
                                        labels={"zone": zone,
                                                "pool": "gpu" if zone == "z3"
                                                else "cpu"}),
                        allocatable={RK.CPU: 64000, RK.MEMORY: 65536}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    snap, ctx = b.build(now=NOW)
    tsc = TSC(max_skew=1, topology_key="zone",
              label_selector={"app": "web"})
    members = [Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                   labels={"app": "web"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   node_selector={"pool": "cpu"},
                   spread_constraints=[tsc]) for j in range(4)]
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=6)
    a = np.asarray(res.assignment)
    # z3 (gpu pool) is unreachable; 4 members split 2/2 over z1/z2 —
    # with z3 wrongly pinning the min, only 2 would ever place
    assert (a >= 0).all(), a
    assert sorted(((a == 0).sum(), (a == 1).sum())) == [2, 2]


# --- inter-pod affinity / anti-affinity -------------------------------------


def _zone_cluster(zones=("z1", "z2", "z3"), cpu=64000.0):
    b = SnapshotBuilder(max_nodes=len(zones))
    for i, z in enumerate(zones):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}", labels={"zone": z}),
                        allocatable={RK.CPU: cpu, RK.MEMORY: 65536}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    return b


def test_anti_affinity_mutual_one_per_domain():
    """Mutually anti-affine replicas land one per zone; the surplus
    member stays pending."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "etcd"}, anti=True)
    members = [Pod(meta=ObjectMeta(name=f"e{j}", namespace="d",
                                   labels={"app": "etcd"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   pod_affinity=[term]) for j in range(4)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=5)
    a = np.asarray(res.assignment)
    placed = a[a >= 0]
    assert len(placed) == 3 and len(set(placed.tolist())) == 3, a
    assert (a == -1).sum() == 1


def test_multi_term_anti_affinity_gates_every_term():
    """Round-4: a pod carrying TWO required anti terms (different
    topology keys / selectors) must avoid BOTH — the carrier matrix
    gates each carried group, not just the first (the old first-term
    narrowing). Cross-checked against the sequential reference
    (preemption.constraints_admit, which always handled multi-term)."""
    from koordinator_tpu.api.types import PodAffinityTerm
    from koordinator_tpu.scheduler.preemption import constraints_admit

    b = SnapshotBuilder(max_nodes=4)
    nodes = []
    for i, (zone, rack) in enumerate(
            [("z1", "r1"), ("z1", "r2"), ("z2", "r1"), ("z2", "r2")]):
        n = Node(meta=ObjectMeta(name=f"n{i}",
                                 labels={"zone": zone, "rack": rack}),
                 allocatable={RK.CPU: 64000.0, RK.MEMORY: 65536})
        nodes.append(n)
        b.add_node(n)
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    # db occupies zone z1 (n0); cache occupies rack r1 (n2)
    db = Pod(meta=ObjectMeta(name="db", namespace="d",
                             labels={"app": "db"}),
             requests={RK.CPU: 100.0}, phase="Running", node_name="n0")
    cache = Pod(meta=ObjectMeta(name="cache", namespace="d",
                                labels={"app": "cache"}),
                requests={RK.CPU: 100.0}, phase="Running",
                node_name="n2")
    b.add_running_pod(db)
    b.add_running_pod(cache)
    terms = [PodAffinityTerm(topology_key="zone",
                             label_selector={"app": "db"}, anti=True),
             PodAffinityTerm(topology_key="rack",
                             label_selector={"app": "cache"}, anti=True)]
    pod = Pod(meta=ObjectMeta(name="p", namespace="d"),
              priority=9000, requests={RK.CPU: 100.0},
              pod_affinity=terms)
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch([pod], ctx)
    res = core.schedule_batch(snap, batch,
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=4)
    got = int(np.asarray(res.assignment)[0])
    # n0/n1 share zone z1 (db); n0/n2 share rack r1 (cache): only n3
    # (z2, r2) violates neither — the first-term-only gate would have
    # allowed n1 as well
    assert got == 3, got
    # sequential reference agreement, node by node
    pods_by_node = {"n0": [db], "n2": [cache]}
    for i, n in enumerate(nodes):
        want = constraints_admit(pod, n, nodes, pods_by_node,
                                 removed_ids=frozenset())
        assert want == (i == 3), (i, want)


def test_anti_term_overload_degrades_one_pod_not_the_batch():
    """A pod whose anti terms alone overflow the group cap degrades to
    unschedulable; the rest of the batch still builds and schedules
    (never abort everyone for one pathological spec)."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    terms = [PodAffinityTerm(topology_key=f"k{t}",
                             label_selector={"app": f"a{t}"}, anti=True)
             for t in range(12)]  # > max_spread_groups (8)
    monster = Pod(meta=ObjectMeta(name="monster", namespace="d"),
                  priority=9000, requests={RK.CPU: 100.0},
                  pod_affinity=terms)
    normal = Pod(meta=ObjectMeta(name="normal", namespace="d"),
                 priority=9000, requests={RK.CPU: 100.0})
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch([monster, normal], ctx)
    assert not bool(np.asarray(batch.valid)[0])
    assert bool(np.asarray(batch.valid)[1])
    res = core.schedule_batch(snap, batch,
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=2)
    a = np.asarray(res.assignment)
    assert a[0] == -1 and a[1] >= 0


def test_anti_affinity_against_other_app():
    """An anti term targeting ANOTHER app's pods avoids its zones but
    members do not exclude each other."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    b.add_running_pod(Pod(meta=ObjectMeta(name="noisy", namespace="d",
                                          labels={"app": "noisy"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n0"))
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "noisy"}, anti=True)
    members = [Pod(meta=ObjectMeta(name=f"q{j}", namespace="d",
                                   labels={"app": "quiet"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   pod_affinity=[term]) for j in range(3)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=4)
    a = np.asarray(res.assignment)
    assert (a >= 0).all() and (a != 0).all(), a  # all avoid noisy's zone


def test_pod_affinity_colocates_with_bootstrap():
    """Self-matching required affinity: the first member opens a domain,
    the rest follow it (upstream's self-affinity special case)."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"group": "batch-job"})
    members = [Pod(meta=ObjectMeta(name=f"m{j}", namespace="d",
                                   labels={"group": "batch-job"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   pod_affinity=[term]) for j in range(4)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=6)
    a = np.asarray(res.assignment)
    assert (a >= 0).all(), a
    assert len(set(a.tolist())) == 1   # all co-located


def test_pod_affinity_follows_existing_pod():
    """Affinity toward an existing app lands in its domain; no
    bootstrap when the group does not self-match."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    b.add_running_pod(Pod(meta=ObjectMeta(name="db", namespace="d",
                                          labels={"app": "db"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n1"))
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "db"})
    web = Pod(meta=ObjectMeta(name="web", namespace="d",
                              labels={"app": "web"}),
              priority=9000, requests={RK.CPU: 100.0},
              pod_affinity=[term])
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch([web], ctx),
                              loadaware.LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) == 1


def test_anti_affinity_heterogeneous_batch_labels():
    """Regression: membership is per-pod selector match, not inherited
    from the group's first pod. w0 (app=web) CARRIES the anti-etcd term:
    its own zone excludes etcd (direction b), and the etcd members still
    mutually exclude (direction a) — so exactly two of three etcd fit
    the remaining zones."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "etcd"}, anti=True)
    batch = [Pod(meta=ObjectMeta(name="w0", namespace="d",
                                 labels={"app": "web"}),
                 priority=9500, requests={RK.CPU: 100.0},
                 pod_affinity=[term])]
    batch += [Pod(meta=ObjectMeta(name=f"e{j}", namespace="d",
                                  labels={"app": "etcd"}),
                  priority=9000, requests={RK.CPU: 100.0},
                  pod_affinity=[term]) for j in range(3)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(batch, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=5)
    a = np.asarray(res.assignment)
    assert a[0] >= 0
    etcd = a[1:]
    placed = etcd[etcd >= 0]
    assert len(placed) == 2 and len(set(placed.tolist())) == 2
    assert (placed != a[0]).all()     # never in the carrier's zone
    assert (etcd == -1).sum() == 1


def test_anti_affinity_sees_same_batch_non_member_placement():
    """Regression: a matching pod scheduled in the SAME batch without
    the term still forbids its domain to the gated pods."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "noisy"}, anti=True)
    batch = [Pod(meta=ObjectMeta(name="noisy", namespace="d",
                                 labels={"app": "noisy"}),
                 priority=9500, requests={RK.CPU: 100.0})]
    batch += [Pod(meta=ObjectMeta(name=f"q{j}", namespace="d",
                                  labels={"app": "quiet"}),
                  priority=9000, requests={RK.CPU: 100.0},
                  pod_affinity=[term]) for j in range(2)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(batch, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=5)
    a = np.asarray(res.assignment)
    assert a[0] >= 0
    assert (a[1:] >= 0).all()
    assert (a[1:] != a[0]).all(), a   # quiet avoid noisy's zone


def test_existing_pod_anti_term_binds_incoming():
    """Regression: a RUNNING pod's required anti term forbids matching
    incoming pods from its domain (satisfyExistingPodsAntiAffinity)."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "web"}, anti=True)
    b.add_running_pod(Pod(meta=ObjectMeta(name="etcd-0", namespace="d",
                                          labels={"app": "etcd"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n0", pod_affinity=[term]))
    web = Pod(meta=ObjectMeta(name="web-0", namespace="d",
                              labels={"app": "web"}),
              priority=9000, requests={RK.CPU: 100.0})
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch([web], ctx),
                              loadaware.LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) in (1, 2)  # not n0


def test_anti_affinity_admits_keyless_nodes():
    """Regression: a node without the topology key can host the pod —
    no topology pair can exist there (upstream admits)."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = SnapshotBuilder(max_nodes=2)
    b.add_node(Node(meta=ObjectMeta(name="z", labels={"zone": "z1"}),
                    allocatable={RK.CPU: 300.0, RK.MEMORY: 65536}))
    b.add_node(Node(meta=ObjectMeta(name="keyless"),
                    allocatable={RK.CPU: 64000, RK.MEMORY: 65536}))
    for nm in ("z", "keyless"):
        b.set_node_metric(NodeMetric(node_name=nm, update_time=NOW,
                                     node_usage={}))
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "e"}, anti=True)
    members = [Pod(meta=ObjectMeta(name=f"e{j}", namespace="d",
                                   labels={"app": "e"}),
                   priority=9000, requests={RK.CPU: 200.0},
                   pod_affinity=[term]) for j in range(2)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=4)
    a = np.asarray(res.assignment)
    assert (a >= 0).all(), a   # second member lands on the keyless node


def test_affinity_bootstrap_not_pinned_to_stuck_member():
    """Regression: when the highest-priority member is unschedulable,
    another member still bootstraps the group."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster(cpu=4000.0)
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"g": "job"})
    huge = Pod(meta=ObjectMeta(name="huge", namespace="d",
                               labels={"g": "job"}),
               priority=9500, requests={RK.CPU: 99000.0},
               pod_affinity=[term])
    small = [Pod(meta=ObjectMeta(name=f"s{j}", namespace="d",
                                 labels={"g": "job"}),
                 priority=9000, requests={RK.CPU: 500.0},
                 pod_affinity=[term]) for j in range(2)]
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch([huge] + small, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=5)
    a = np.asarray(res.assignment)
    assert a[0] == -1               # huge can never fit
    assert (a[1:] >= 0).all(), a    # the rest bootstrap and co-locate
    assert a[1] == a[2]


def test_same_batch_carrier_anti_term_binds_matching_pod():
    """Regression: a batch pod's own anti term forbids its landing
    domain to matching pods placed LATER in the same batch."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster(zones=("z1",))
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "noisy"}, anti=True)
    quiet = Pod(meta=ObjectMeta(name="quiet", namespace="d",
                                labels={"app": "quiet"}),
                priority=9500, requests={RK.CPU: 100.0},
                pod_affinity=[term])
    noisy = Pod(meta=ObjectMeta(name="noisy", namespace="d",
                                labels={"app": "noisy"}),
                priority=9000, requests={RK.CPU: 100.0})
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch([quiet, noisy], ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=4)
    a = np.asarray(res.assignment)
    assert a[0] == 0 and a[1] == -1, a  # noisy pending, not co-located


def test_carrier_gating_blocks_only_carrier_domains():
    """Regression: a pod matching a carrier's selector is blocked only
    from CARRIER domains, not from every domain holding other matching
    pods."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster(zones=("z1", "z2"))
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "web"}, anti=True)
    b.add_running_pod(Pod(meta=ObjectMeta(name="etcd", namespace="d",
                                          labels={"app": "etcd"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n0", pod_affinity=[term]))
    b.add_running_pod(Pod(meta=ObjectMeta(name="web-old", namespace="d",
                                          labels={"app": "web"}),
                          requests={RK.CPU: 100.0}, phase="Running",
                          node_name="n1"))
    web_new = Pod(meta=ObjectMeta(name="web-new", namespace="d",
                                  labels={"app": "web"}),
                  priority=9000, requests={RK.CPU: 100.0})
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch([web_new], ctx),
                              loadaware.LoadAwareConfig.make())
    # z1 holds the carrier -> forbidden; z2 holds only web-old -> fine
    assert int(np.asarray(res.assignment)[0]) == 1


def test_irrelevant_existing_anti_terms_do_not_exhaust_cap():
    """Regression: cluster-wide anti-term diversity must not DoS the
    batch builder — only terms a batch pod matches materialize."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = _zone_cluster()
    for i in range(12):  # > max_spread_groups distinct terms
        b.add_running_pod(Pod(
            meta=ObjectMeta(name=f"svc{i}", namespace="d",
                            labels={"app": f"svc{i}"}),
            requests={RK.CPU: 10.0}, phase="Running", node_name="n0",
            pod_affinity=[PodAffinityTerm(
                topology_key="zone",
                label_selector={"app": f"svc{i}"}, anti=True)]))
    plain = Pod(meta=ObjectMeta(name="plain", namespace="d",
                                labels={"app": "web"}),
                priority=9000, requests={RK.CPU: 100.0})
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch([plain], ctx)   # must not raise
    assert not batch.has_anti
    res = core.schedule_batch(snap, batch,
                              loadaware.LoadAwareConfig.make())
    assert int(np.asarray(res.assignment)[0]) >= 0


def test_single_domain_cap_still_gates():
    """Regression: max_spread_domains=1 with one group used to collide
    with the [1, 1] degenerate sentinel and silently disable the gate."""
    from koordinator_tpu.api.types import PodAffinityTerm

    b = SnapshotBuilder(max_nodes=2, max_spread_domains=1)
    for i in range(2):
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}",
                                        labels={"zone": "z1"}),
                        allocatable={RK.CPU: 64000, RK.MEMORY: 65536}))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=NOW,
                                     node_usage={}))
    term = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "e"}, anti=True)
    members = [Pod(meta=ObjectMeta(name=f"e{j}", namespace="d",
                                   labels={"app": "e"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   pod_affinity=[term]) for j in range(2)]
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(members, ctx)
    assert batch.has_anti
    res = core.schedule_batch(snap, batch,
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=3)
    a = np.asarray(res.assignment)
    # one zone only -> exactly one member fits, the other stays pending
    assert (a >= 0).sum() == 1 and (a == -1).sum() == 1, a


def test_chunk1_equivalence_with_topology_gates():
    """Chunk-1 equivalence for the vanilla topology gates: feeding pods
    one at a time through the batched kernel (rebuilding via the builder
    so the domain counts carry) reproduces the sequential oracle with
    taints, spread, and (anti-)affinity."""
    from koordinator_tpu.api.types import (
        PodAffinityTerm, Taint, Toleration, TopologySpreadConstraint,
    )
    from oracle import OracleArgs, OracleScheduler

    zones = ["z0", "z0", "z1", "z1", "z2", "z2"]
    racks = ["r0", "r1", "r0", "r1", "r0", "r1"]
    taints = [[], [Taint(key="ded", value="x", effect="NoSchedule")],
              [], [], [], []]

    def make_nodes():
        out = []
        for i, (z, r) in enumerate(zip(zones, racks)):
            out.append(Node(meta=ObjectMeta(name=f"n{i}",
                                            labels={"zone": z,
                                                    "rack": r}),
                            allocatable={RK.CPU: 8000.0 + i * 4000.0,
                                         RK.MEMORY: 65536.0},
                            taints=list(taints[i])))
        return out

    spread = TopologySpreadConstraint(max_skew=1, topology_key="zone",
                                     label_selector={"app": "web"})
    anti = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "kv"}, anti=True)
    # a SECOND anti term for the multi-term kind (rack vs web)
    anti2 = PodAffinityTerm(topology_key="rack",
                            label_selector={"app": "web"}, anti=True)
    tol = [Toleration(key="ded", value="x", effect="NoSchedule")]
    pods = []
    for j in range(12):
        kind = j % 4
        prio = 9000 + (12 - j) * 13    # distinct priorities: stable order
        cpu = 700.0 + j * 31.0         # distinct requests: no score ties
        if kind == 0:
            # web pods land on j in {0, 4, 8}: j % 8 keeps SOME of them
            # tolerant so the taint-x-spread interplay stays covered
            pods.append(Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                            labels={"app": "web"}),
                            priority=prio, requests={RK.CPU: cpu},
                            spread_constraints=[spread],
                            tolerations=tol if j % 8 else []))
        elif kind == 1:
            pods.append(Pod(meta=ObjectMeta(name=f"k{j}", namespace="d",
                                            labels={"app": "kv"}),
                            priority=prio, requests={RK.CPU: cpu},
                            pod_affinity=[anti]))
        elif kind == 2:
            # MULTI-TERM carrier: both anti terms must hold at once
            pods.append(Pod(meta=ObjectMeta(name=f"m{j}", namespace="d",
                                            labels={"app": "kv"}),
                            priority=prio, requests={RK.CPU: cpu},
                            pod_affinity=[anti, anti2]))
        else:
            pods.append(Pod(meta=ObjectMeta(name=f"p{j}", namespace="d",
                                            labels={"app": "plain"}),
                            priority=prio, requests={RK.CPU: cpu},
                            tolerations=tol))

    # oracle: sequential, priority order (state built the same way the
    # existing golden tests do — through make_oracle_nodes)
    ob = SnapshotBuilder(max_nodes=len(zones))
    for n in make_nodes():
        ob.add_node(n)
        ob.set_node_metric(NodeMetric(node_name=n.meta.name,
                                      update_time=NOW, node_usage={}))
    oracle = OracleScheduler(make_oracle_nodes(ob, now=NOW),
                             OracleArgs.default())
    want = oracle.schedule(pods)

    # device: one pod per batch in the same order, builder-rebuilt so
    # assumed pods feed every count surface
    order = sorted(range(len(pods)),
                   key=lambda i: (-(pods[i].priority or 0), i))
    assigned = []  # (pod, node_name)
    got = np.full((len(pods),), -1, np.int64)
    for i in order:
        b = SnapshotBuilder(max_nodes=len(zones))
        for n in make_nodes():
            b.add_node(n)
            b.set_node_metric(NodeMetric(node_name=n.meta.name,
                                         update_time=NOW, node_usage={}))
        for p, node_name in assigned:
            b.add_assigned(p, node_name, timestamp=NOW)
        snap, ctx = b.build(now=NOW)
        res = core.schedule_batch(snap, b.build_pod_batch([pods[i]], ctx),
                                  loadaware.LoadAwareConfig.make(),
                                  num_rounds=2)
        a = int(np.asarray(res.assignment)[0])
        got[i] = a
        if a >= 0:
            assigned.append((pods[i], f"n{a}"))
    np.testing.assert_array_equal(got, want)


def test_chunk1_equivalence_with_running_pods():
    """Regression: the oracle's running-pod seed and the builder's
    running-pod ingest agree — an existing kv pod forbids its zone to
    anti-affine members on both paths."""
    from koordinator_tpu.api.types import PodAffinityTerm
    from oracle import OracleArgs, OracleScheduler, make_oracle_nodes

    anti = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "kv"}, anti=True)

    def make_nodes():
        return [Node(meta=ObjectMeta(name=f"n{i}",
                                     labels={"zone": f"z{i}"}),
                     allocatable={RK.CPU: 8000.0 + i * 1000.0,
                                  RK.MEMORY: 65536.0})
                for i in range(3)]

    running = Pod(meta=ObjectMeta(name="kv-old", namespace="d",
                                  labels={"app": "kv"}),
                  requests={RK.CPU: 500.0}, phase="Running",
                  node_name="n2")
    members = [Pod(meta=ObjectMeta(name=f"kv-{j}", namespace="d",
                                   labels={"app": "kv"}),
                   priority=9000 + j * 7,
                   requests={RK.CPU: 600.0 + j * 11.0},
                   pod_affinity=[anti]) for j in range(3)]

    ob = SnapshotBuilder(max_nodes=3)
    for n in make_nodes():
        ob.add_node(n)
        ob.set_node_metric(NodeMetric(node_name=n.meta.name,
                                      update_time=NOW, node_usage={}))
    ob.add_running_pod(running)
    oracle = OracleScheduler(make_oracle_nodes(ob, now=NOW),
                             OracleArgs.default(),
                             running_pods=[(running, 2)])
    want = oracle.schedule(members)

    b = SnapshotBuilder(max_nodes=3)
    for n in make_nodes():
        b.add_node(n)
        b.set_node_metric(NodeMetric(node_name=n.meta.name,
                                     update_time=NOW, node_usage={}))
    b.add_running_pod(running)
    snap, ctx = b.build(now=NOW)
    res = core.schedule_batch(snap, b.build_pod_batch(members, ctx),
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=4)
    got = np.asarray(res.assignment)
    np.testing.assert_array_equal(np.sort(got), np.sort(np.asarray(want)))
    assert (got != 2).all() and (np.asarray(want) != 2).all()


def test_schedule_anyway_spread_scores_without_filtering():
    """ScheduleAnyway constraints never filter (keyless nodes included)
    but prefer emptier domains; contrast with DoNotSchedule."""
    from koordinator_tpu.api.types import TopologySpreadConstraint as TSC

    def cluster():
        b = SnapshotBuilder(max_nodes=3)
        for i, zone in enumerate(("z1", "z1", None)):
            labels = {"zone": zone} if zone else {}
            b.add_node(Node(meta=ObjectMeta(name=f"n{i}", labels=labels),
                            allocatable={RK.CPU: 64000,
                                         RK.MEMORY: 65536}))
            b.set_node_metric(NodeMetric(node_name=f"n{i}",
                                         update_time=NOW, node_usage={}))
        return b

    soft = TSC(max_skew=1, topology_key="zone",
               when_unsatisfiable="ScheduleAnyway",
               label_selector={"app": "web"})
    members = [Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                   labels={"app": "web"}),
                   priority=9000, requests={RK.CPU: 100.0},
                   spread_constraints=[soft]) for j in range(4)]
    b = cluster()
    snap, ctx = b.build(now=NOW)
    batch = b.build_pod_batch(members, ctx)
    assert batch.has_spread
    res = core.schedule_batch(snap, batch,
                              loadaware.LoadAwareConfig.make(),
                              num_rounds=5)
    a = np.asarray(res.assignment)
    # soft: ALL place (even though hard skew over one z1 domain would
    # strand some, and the keyless node stays usable)
    assert (a >= 0).all(), a

    # the preference still pushes members toward the emptier domain:
    # seed one member in z1 and one chunk-1 member must not pile on
    b2 = cluster()
    b2.add_running_pod(Pod(meta=ObjectMeta(name="r", namespace="d",
                                           labels={"app": "web"}),
                           requests={RK.CPU: 100.0}, phase="Running",
                           node_name="n0"))
    snap2, ctx2 = b2.build(now=NOW)
    one = b2.build_pod_batch([members[0]], ctx2)
    res2 = core.schedule_batch(snap2, one,
                               loadaware.LoadAwareConfig.make())
    assert int(np.asarray(res2.assignment)[0]) == 2  # keyless = empty


def test_chunk1_equivalence_multi_spread_affinity():
    """Chunk-1 equivalence for MULTI-constraint spread (zone + hostname
    carried together, the upstream default profile) and MULTI-term
    required affinity (two terms that must both hold): the batched
    carrier-matrix gates reproduce the sequential oracle, whose
    constraints_admit already enforces every carried constraint."""
    from koordinator_tpu.api.types import (
        PodAffinityTerm, TopologySpreadConstraint,
    )
    from oracle import OracleArgs, OracleScheduler

    zones = ["z0", "z0", "z1", "z1", "z2", "z2"]
    racks = ["r0", "r1", "r0", "r1", "r0", "r1"]

    def make_nodes():
        out = []
        for i, (z, r) in enumerate(zip(zones, racks)):
            out.append(Node(meta=ObjectMeta(
                name=f"n{i}", labels={"zone": z, "rack": r,
                                      "host": f"n{i}"}),
                allocatable={RK.CPU: 8000.0 + i * 4000.0,
                             RK.MEMORY: 65536.0}))
        return out

    spread_zone = TopologySpreadConstraint(
        max_skew=1, topology_key="zone", label_selector={"app": "web"})
    spread_host = TopologySpreadConstraint(
        max_skew=1, topology_key="host", label_selector={"app": "web"})
    aff_db = PodAffinityTerm(topology_key="zone",
                             label_selector={"tier": "db"})
    aff_cache = PodAffinityTerm(topology_key="zone",
                                label_selector={"app": "cache"})
    aff_duo_zone = PodAffinityTerm(topology_key="zone",
                                   label_selector={"app": "duo"})
    aff_duo_rack = PodAffinityTerm(topology_key="rack",
                                   label_selector={"app": "duo"})

    # running targets: db in z0 AND z1, cache only in z1 — the
    # two-term svc pods must take the INTERSECTION (z1)
    running = [
        (Pod(meta=ObjectMeta(name="db0", namespace="d",
                             labels={"tier": "db"}),
             requests={RK.CPU: 100.0}, phase="Running",
             node_name="n0"), "n0"),
        (Pod(meta=ObjectMeta(name="db1", namespace="d",
                             labels={"tier": "db"}),
             requests={RK.CPU: 100.0}, phase="Running",
             node_name="n2"), "n2"),
        (Pod(meta=ObjectMeta(name="cache0", namespace="d",
                             labels={"app": "cache"}),
             requests={RK.CPU: 100.0}, phase="Running",
             node_name="n3"), "n3"),
    ]

    pods = []
    for j in range(14):
        kind = j % 4
        prio = 9000 + (14 - j) * 13
        cpu = 650.0 + j * 37.0
        if kind in (0, 1):
            # multi-constraint spread: zone AND hostname together
            pods.append(Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                            labels={"app": "web"}),
                            priority=prio, requests={RK.CPU: cpu},
                            spread_constraints=[spread_zone,
                                                spread_host]))
        elif kind == 2:
            # multi-term affinity: near db AND near cache
            pods.append(Pod(meta=ObjectMeta(name=f"s{j}", namespace="d",
                                            labels={"app": "svc"}),
                            priority=prio, requests={RK.CPU: cpu},
                            pod_affinity=[aff_db, aff_cache]))
        else:
            # multi-term SELF affinity: zone and rack must both match,
            # bootstrap opens both with the first member
            pods.append(Pod(meta=ObjectMeta(name=f"d{j}", namespace="d",
                                            labels={"app": "duo"}),
                            priority=prio, requests={RK.CPU: cpu},
                            pod_affinity=[aff_duo_zone, aff_duo_rack]))

    ob = SnapshotBuilder(max_nodes=len(zones))
    for n in make_nodes():
        ob.add_node(n)
        ob.set_node_metric(NodeMetric(node_name=n.meta.name,
                                      update_time=NOW, node_usage={}))
    name_to_idx = {f"n{i}": i for i in range(len(zones))}
    oracle = OracleScheduler(
        make_oracle_nodes(ob, now=NOW), OracleArgs.default(),
        running_pods=[(p, name_to_idx[nn]) for p, nn in running])
    want = oracle.schedule(pods)
    # the workload must actually exercise the gates: the svc pods land
    # in the intersection zone and the web pods respect hostname skew
    for j, a in enumerate(want):
        if j % 4 == 2 and a >= 0:
            assert zones[a] == "z1", (j, a)

    order = sorted(range(len(pods)),
                   key=lambda i: (-(pods[i].priority or 0), i))
    assigned = []
    got = np.full((len(pods),), -1, np.int64)
    for i in order:
        b = SnapshotBuilder(max_nodes=len(zones))
        for n in make_nodes():
            b.add_node(n)
            b.set_node_metric(NodeMetric(node_name=n.meta.name,
                                         update_time=NOW, node_usage={}))
        for p, node_name in running:
            b.add_running_pod(p)
        for p, node_name in assigned:
            b.add_assigned(p, node_name, timestamp=NOW)
        snap, ctx = b.build(now=NOW)
        res = core.schedule_batch(snap, b.build_pod_batch([pods[i]], ctx),
                                  loadaware.LoadAwareConfig.make(),
                                  num_rounds=2)
        a = int(np.asarray(res.assignment)[0])
        got[i] = a
        if a >= 0:
            assigned.append((pods[i], f"n{a}"))
    np.testing.assert_array_equal(got, want)
