"""Webhook tests: ClusterColocationProfile pod mutation/validation and the
ElasticQuota topology guard (SURVEY.md 2.3; reference
cluster_colocation_profile_test.go / quota_topology_test.go scenarios)."""

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import QoSClass, ResourceKind as RK
from koordinator_tpu.webhook import (
    PodMutator,
    QuotaTopology,
    ROOT_QUOTA_NAME,
    validate_pod,
)
from koordinator_tpu.webhook.elasticquota import QuotaTopologyError


def be_profile(**kw):
    return api.ClusterColocationProfile(
        meta=api.ObjectMeta(name="colocation"),
        selector={"app": "batch-job"},
        labels={"koordinator.sh/mutated": "true"},
        qos_class="BE",
        priority_class_name="koord-batch",
        koordinator_priority=1111,
        scheduler_name="koord-scheduler",
        **kw)


def batch_pod(**kw):
    return api.Pod(meta=api.ObjectMeta(name="p", labels={"app": "batch-job"}),
                   requests={RK.CPU: 4000.0, RK.MEMORY: 4096.0},
                   limits={RK.CPU: 8000.0, RK.MEMORY: 8192.0}, **kw)


def mk_mutator(profile=None, **kw):
    return PodMutator([profile or be_profile()],
                      priority_classes={"koord-batch": 5500},
                      **kw)


# --- mutation ---------------------------------------------------------------


def test_profile_mutation_full_stack():
    pod = batch_pod()
    assert mk_mutator().mutate(pod)
    assert pod.meta.labels["koordinator.sh/mutated"] == "true"
    assert pod.qos_label == "BE" and pod.qos is QoSClass.BE
    assert pod.priority == 5500
    assert pod.meta.labels["koordinator.sh/priority"] == "1111"
    assert pod.scheduler_name == "koord-scheduler"
    # batch priority translates cpu/memory to batch resources, erasing
    # the native entries
    assert RK.CPU not in pod.requests and RK.MEMORY not in pod.requests
    assert pod.requests[RK.BATCH_CPU] == 4000.0
    assert pod.requests[RK.BATCH_MEMORY] == 4096.0
    assert pod.limits[RK.BATCH_CPU] == 8000.0


def test_profile_selector_and_operation_gate():
    pod = api.Pod(meta=api.ObjectMeta(name="p", labels={"app": "web"}),
                  requests={RK.CPU: 1000.0})
    assert not mk_mutator().mutate(pod)
    assert pod.qos_label == ""
    assert not mk_mutator().mutate(batch_pod(), operation="Update")


def test_profile_namespace_selector():
    prof = be_profile(namespace_selector={"team": "ml"})
    m = PodMutator([prof], namespaces={"mlns": {"team": "ml"},
                                       "other": {"team": "web"}},
                   priority_classes={"koord-batch": 5500})
    pod_in = batch_pod()
    pod_in.meta.namespace = "mlns"
    pod_out = batch_pod()
    pod_out.meta.namespace = "other"
    assert m.mutate(pod_in)
    assert not m.mutate(pod_out)


def test_priority_class_name_resolves_out_of_band_values():
    # a koord-batch PriorityClass whose k8s value sits OUTSIDE the
    # koordinator batch band still resolves to BATCH via the name, so
    # resource translation and validation agree
    pod = batch_pod()
    PodMutator([be_profile()],
               priority_classes={"koord-batch": 2000}).mutate(pod)
    assert pod.priority == 2000
    assert RK.BATCH_CPU in pod.requests
    ok, errs = validate_pod(pod)
    assert ok, errs


def test_unrelated_priority_class_name_not_koordinator():
    # a cluster PriorityClass merely NAMED "batch" must not resolve to the
    # koordinator Batch class (only koord-* names do)
    from koordinator_tpu.api.extension import PriorityClass, priority_class_of
    assert priority_class_of(800000, "", "batch") is PriorityClass.NONE
    assert priority_class_of(800000, "", "koord-batch") is PriorityClass.BATCH


def test_key_mapping_skips_missing_sources():
    prof = be_profile(label_keys_mapping={"absent": "copied"})
    pod = batch_pod()
    mk_mutator(prof).mutate(pod)
    assert "copied" not in pod.meta.labels


def test_probability_gating():
    # percent 50 with rng always above -> profile skipped, but the
    # resource translation still runs for already-batch pods
    prof = be_profile(probability=0.5)
    m = mk_mutator(prof, rng=lambda: 0.99)
    pod = batch_pod()
    m.mutate(pod)
    assert pod.qos_label == ""
    m2 = mk_mutator(be_profile(probability=0.5), rng=lambda: 0.01)
    pod2 = batch_pod()
    m2.mutate(pod2)
    assert pod2.qos_label == "BE"


def test_limit_only_gets_request():
    prof = be_profile()
    m = mk_mutator(prof)
    pod = api.Pod(meta=api.ObjectMeta(name="p", labels={"app": "batch-job"}),
                  limits={RK.CPU: 2000.0})
    m.mutate(pod)
    assert pod.requests[RK.BATCH_CPU] == 2000.0


def test_skip_update_resources():
    prof = be_profile(skip_update_resources=True)
    pod = batch_pod()
    mk_mutator(prof).mutate(pod)
    assert pod.qos_label == "BE"
    assert RK.CPU in pod.requests  # translation skipped


# --- validation -------------------------------------------------------------


def test_validate_forbidden_combinations():
    ok, errs = validate_pod(api.Pod(qos_label="BE", priority=9100))
    assert not ok and "cannot be used in combination" in errs[0]
    ok, _ = validate_pod(api.Pod(qos_label="BE", priority=5100,
                                 requests={RK.BATCH_CPU: 100.0}))
    assert ok
    ok, _ = validate_pod(api.Pod(qos_label="LSR", priority=5100,
                                 requests={RK.CPU: 1000.0}))
    assert not ok


def test_validate_batch_resources_require_be():
    ok, errs = validate_pod(api.Pod(qos_label="LS", priority=5100,
                                    requests={RK.BATCH_CPU: 100.0}))
    assert not ok and "QoS BE" in errs[0]


def test_validate_lsr_integer_cpu():
    base = dict(qos_label="LSR", priority=9100)
    ok, _ = validate_pod(api.Pod(requests={RK.CPU: 2000.0}, **base))
    assert ok
    ok, errs = validate_pod(api.Pod(requests={RK.CPU: 2500.0}, **base))
    assert not ok and "integer" in errs[0]
    ok, errs = validate_pod(api.Pod(requests={}, **base))
    assert not ok and "must declare" in errs[0]


def test_validate_immutable_on_update():
    old = api.Pod(qos_label="LS", priority=9100)
    new = api.Pod(qos_label="BE", priority=5100,
                  requests={RK.BATCH_CPU: 10.0})
    ok, errs = validate_pod(new, old)
    assert not ok
    assert any("immutable" in e for e in errs)


# --- quota topology ---------------------------------------------------------


def quota(name, parent="", minq=None, maxq=None, **kw):
    return api.ElasticQuota(meta=api.ObjectMeta(name=name), parent=parent,
                            min=minq or {}, max=maxq or {}, **kw)


def test_quota_defaults_and_add():
    qt = QuotaTopology()
    q = quota("a", maxq={RK.CPU: 100.0}, minq={RK.CPU: 10.0})
    qt.valid_add(q)
    assert q.parent == ROOT_QUOTA_NAME
    assert q.shared_weight == {RK.CPU: 100.0}


def test_quota_min_greater_than_max_rejected():
    qt = QuotaTopology()
    with pytest.raises(QuotaTopologyError):
        qt.valid_add(quota("bad", minq={RK.CPU: 200.0},
                           maxq={RK.CPU: 100.0}))


def test_quota_parent_must_be_parent_and_tree_inherits():
    qt = QuotaTopology()
    parent = quota("parent", minq={RK.CPU: 100.0}, maxq={RK.CPU: 200.0},
                   is_parent=True, tree_id="t1")
    qt.valid_add(parent)
    child = quota("child", parent="parent", minq={RK.CPU: 50.0},
                  maxq={RK.CPU: 200.0})
    qt.valid_add(child)
    assert child.tree_id == "t1"
    leaf = quota("leaf", parent="child", maxq={RK.CPU: 10.0})
    with pytest.raises(QuotaTopologyError):  # child.is_parent is False
        qt.valid_add(leaf)


def test_quota_max_keys_must_match_parent():
    qt = QuotaTopology()
    qt.valid_add(quota("parent", minq={RK.CPU: 100.0},
                       maxq={RK.CPU: 200.0}, is_parent=True))
    with pytest.raises(QuotaTopologyError):
        qt.valid_add(quota("child", parent="parent",
                           maxq={RK.CPU: 50.0, RK.MEMORY: 10.0}))


def test_quota_sibling_min_sum_capped_by_parent():
    qt = QuotaTopology()
    qt.valid_add(quota("parent", minq={RK.CPU: 100.0},
                       maxq={RK.CPU: 200.0}, is_parent=True))
    qt.valid_add(quota("a", parent="parent", minq={RK.CPU: 70.0},
                       maxq={RK.CPU: 200.0}))
    with pytest.raises(QuotaTopologyError):
        qt.valid_add(quota("b", parent="parent", minq={RK.CPU: 40.0},
                           maxq={RK.CPU: 200.0}))
    # allowForceUpdate bypasses the min-sum check
    qt.valid_add(quota("b", parent="parent", minq={RK.CPU: 40.0},
                       maxq={RK.CPU: 200.0}, allow_force_update=True))


def test_quota_namespace_binding_exclusive():
    qt = QuotaTopology()
    qt.valid_add(quota("a", maxq={RK.CPU: 10.0}, namespaces=["ns1"]))
    with pytest.raises(QuotaTopologyError):
        qt.valid_add(quota("b", maxq={RK.CPU: 10.0}, namespaces=["ns1"]))


def test_quota_delete_guards():
    pods = {"a": 0, "parent": 0}
    qt = QuotaTopology(pod_counter=lambda n: pods.get(n, 0))
    qt.valid_add(quota("parent", minq={RK.CPU: 100.0},
                       maxq={RK.CPU: 200.0}, is_parent=True))
    qt.valid_add(quota("a", parent="parent", minq={RK.CPU: 10.0},
                       maxq={RK.CPU: 200.0}))
    with pytest.raises(QuotaTopologyError):  # has children
        qt.valid_delete("parent")
    pods["a"] = 3
    with pytest.raises(QuotaTopologyError):  # has pods
        qt.valid_delete("a")
    pods["a"] = 0
    qt.valid_delete("a")
    qt.valid_delete("parent")
    with pytest.raises(QuotaTopologyError):  # protected names
        qt.valid_delete(ROOT_QUOTA_NAME)


def test_quota_update_parent_with_pods_forbidden():
    pods = {"c": 2}
    qt = QuotaTopology(pod_counter=lambda n: pods.get(n, 0))
    qt.valid_add(quota("p1", minq={RK.CPU: 100.0}, maxq={RK.CPU: 200.0},
                       is_parent=True))
    qt.valid_add(quota("p2", minq={RK.CPU: 100.0}, maxq={RK.CPU: 200.0},
                       is_parent=True))
    qt.valid_add(quota("c", parent="p1", minq={RK.CPU: 10.0},
                       maxq={RK.CPU: 200.0}))
    moved = quota("c", parent="p2", minq={RK.CPU: 10.0},
                  maxq={RK.CPU: 200.0})
    with pytest.raises(QuotaTopologyError):
        qt.valid_update(moved)
