"""Property-based invariant sweep: randomized clusters and pod batches
through the full device program, asserting the conservation laws that
must hold for EVERY seed (the batched analogue of the reference's
sequential-scheduler guarantees). The shapes stay constant so all seeds
share one compiled program."""

import numpy as np
import pytest

from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins import loadaware
from koordinator_tpu.utils import synthetic

NUM_NODES = 64
NUM_PODS = 200
NUM_QUOTAS = 8
NUM_GANGS = 8

CFG = loadaware.LoadAwareConfig.make()


def run(seed):
    snap = synthetic.synthetic_cluster(
        NUM_NODES, num_quotas=NUM_QUOTAS, num_gangs=NUM_GANGS,
        gang_min_member=4, seed=seed, gpu_node_frac=0.25, gpus_per_node=4)
    pods = synthetic.synthetic_pods(
        NUM_PODS, seed=seed + 1000, num_quotas=NUM_QUOTAS,
        num_gangs=NUM_GANGS, gang_min_member=4, gpu_pod_frac=0.1)
    res = core.schedule_batch(snap, pods, CFG, num_rounds=3, k_choices=8)
    return snap, pods, res


@pytest.mark.parametrize("seed", range(8))
def test_scheduling_invariants(seed):
    snap, pods, res = run(seed)
    assign = np.asarray(res.assignment)
    valid = np.asarray(pods.valid)
    requests = np.asarray(pods.requests)
    n_nodes = np.asarray(snap.nodes.allocatable).shape[0]

    # 1. assignments are in range and only for valid pods
    placed = assign >= 0
    assert (assign[~valid] == -1).all(), "padding rows must stay unplaced"
    assert (assign[placed] < n_nodes).all()

    # 2. node conservation: post-commit requested == pre + sum of placed
    #    pods' requests, and never exceeds allocatable on the fit dims
    before = np.asarray(snap.nodes.requested)
    after = np.asarray(res.snapshot.nodes.requested)
    res_slot = np.asarray(res.res_slot)
    expect = before.copy()
    for i in np.where(placed & valid)[0]:
        if res_slot[i] >= 0:
            continue  # consumers draw from the reservation's hold
        expect[assign[i]] += requests[i]
    np.testing.assert_allclose(after, expect, rtol=1e-5, atol=1e-2)
    alloc = np.asarray(res.snapshot.nodes.allocatable)
    for d in range(4):
        over = after[:, d] - alloc[:, d]
        assert (over <= 1e-2).all(), \
            f"seed {seed}: dim {d} overcommitted by {over.max()}"

    # 3. quota conservation: used grows by EXACTLY the placed requests of
    #    each quota's pods, propagated to their ancestors, and never
    #    exceeds max
    used0 = np.asarray(snap.quotas.used)
    used = np.asarray(res.snapshot.quotas.used)
    qmax = np.asarray(res.snapshot.quotas.max)
    assert (used <= qmax + 1e-2).all(), f"seed {seed}: quota max violated"
    anc = np.asarray(snap.quotas.depth_ancestor)
    quota_id = np.asarray(pods.quota_id)
    expect_used = used0.copy()
    for i in np.where(placed & valid & (quota_id >= 0))[0]:
        for d in range(anc.shape[1]):
            a = anc[quota_id[i], d]
            if a >= 0:
                expect_used[a] += requests[i]
    np.testing.assert_allclose(used, expect_used, rtol=1e-5, atol=1e-2,
                               err_msg=f"seed {seed}: quota accounting")

    # 4. strict gang all-or-nothing relative to assumed state: each gang
    #    either reaches quorum (assumed) or placed nothing this batch
    gang_id = np.asarray(pods.gang_id)
    assumed0 = np.asarray(snap.gangs.assumed)
    assumed1 = np.asarray(res.snapshot.gangs.assumed)
    min_member = np.asarray(snap.gangs.min_member)
    strict = np.asarray(snap.gangs.strict)
    member_count = np.asarray(snap.gangs.member_count)
    gang_failed = np.asarray(res.gang_failed)
    for g in range(NUM_GANGS):
        members = (gang_id == g) & valid
        if not members.any():
            continue
        placed_g = int((placed & members).sum())
        attempted = int(members.sum())
        outstanding = max(0, int(member_count[g]) - int(assumed0[g])
                          - attempted)
        total = int(assumed0[g]) + placed_g
        if strict[g] and outstanding == 0 and total < int(min_member[g]):
            assert placed_g == 0, \
                f"seed {seed}: gang {g} kept a partial placement"
            assert gang_failed[g]
        assert assumed1[g] == int(assumed0[g]) + placed_g

    # 5. NUMA: single-NUMA pods that placed on a zone never drive a
    #    zone's free below zero
    numa_free = np.asarray(res.snapshot.nodes.numa_free)
    assert (numa_free >= -1e-2).all()

    # 6. device instances: fractional sharing is legal, but no instance
    #    pool goes negative and totals bound every free column
    gpu_free = np.asarray(res.snapshot.devices.gpu_free)
    gpu_total = np.asarray(res.snapshot.devices.gpu_total)
    assert (gpu_free >= -1e-2).all(), f"seed {seed}: GPU pool negative"
    assert (gpu_free <= gpu_total[:, None, :] + 1e-2).all(), \
        f"seed {seed}: GPU free above capacity"
    aux_free = np.asarray(res.snapshot.devices.aux_free)
    assert (aux_free >= -1e-2).all() and (aux_free <= 100.0 + 1e-2).all()


def test_invariants_hold_on_sharded_mesh():
    """The same conservation laws over the 8-virtual-device mesh: the
    node axis shards over ICI and the collectives must not change any
    accounting."""
    import jax

    from koordinator_tpu.parallel import mesh as meshlib

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    snap = synthetic.synthetic_cluster(
        NUM_NODES, num_quotas=NUM_QUOTAS, num_gangs=NUM_GANGS,
        gang_min_member=4, seed=3)
    pods = synthetic.synthetic_pods(
        NUM_PODS, seed=1003, num_quotas=NUM_QUOTAS, num_gangs=NUM_GANGS,
        gang_min_member=4)
    mesh = meshlib.make_mesh(jax.devices())
    sharded = meshlib.shard_snapshot(snap, mesh)
    with mesh:
        res = core.schedule_batch(sharded, pods, CFG, num_rounds=3,
                                  k_choices=8)
    # identical program on one device must agree on the accounting sums
    res1 = core.schedule_batch(snap, pods, CFG, num_rounds=3, k_choices=8)
    a_mesh = np.asarray(res.assignment)
    a_one = np.asarray(res1.assignment)
    assert int((a_mesh >= 0).sum()) == int((a_one >= 0).sum())
    np.testing.assert_allclose(
        np.asarray(res.snapshot.nodes.requested).sum(axis=0),
        np.asarray(res1.snapshot.nodes.requested).sum(axis=0),
        rtol=1e-5, atol=1e-2)
    alloc = np.asarray(res.snapshot.nodes.allocatable)
    after = np.asarray(res.snapshot.nodes.requested)
    for d in range(4):
        assert (after[:, d] - alloc[:, d] <= 1e-2).all()


def test_resubmit_carries_state():
    """Scheduling the same batch twice against the carried snapshot must
    keep every invariant — the second pass sees less capacity."""
    snap, pods, res1 = run(99)
    res2 = core.schedule_batch(res1.snapshot, pods, CFG, num_rounds=3,
                               k_choices=8)
    a1 = np.asarray(res1.assignment)
    a2 = np.asarray(res2.assignment)
    alloc = np.asarray(res2.snapshot.nodes.allocatable)
    after = np.asarray(res2.snapshot.nodes.requested)
    for d in range(4):
        assert (after[:, d] - alloc[:, d] <= 1e-2).all()
    # capacity consumed by round 1 bounds round 2
    assert int((a2 >= 0).sum()) <= int((a1 >= 0).sum())


# --- topology-gate invariant sweep (taints/spread/affinity) -----------------


@pytest.mark.parametrize("seed", range(6))
def test_topology_gate_invariants(seed):
    """Randomized zones/taints/membership through the builder path; the
    vanilla-gate guarantees must hold for every seed: no untolerated
    NoSchedule placement, spread skew bounded over eligible domains,
    mutual anti-affinity one-per-domain, affinity members co-domained
    with a match."""
    from koordinator_tpu.api.extension import ResourceKind as RK
    from koordinator_tpu.api.types import (
        Node, NodeMetric, ObjectMeta, Pod, PodAffinityTerm, Taint,
        Toleration, TopologySpreadConstraint,
    )
    from koordinator_tpu.snapshot.builder import SnapshotBuilder

    rng = np.random.default_rng(seed)
    n_nodes = 12
    zones = [f"z{int(z)}" for z in rng.integers(0, 4, n_nodes)]
    racks = [f"r{i % 3}" for i in range(n_nodes)]
    tainted = rng.random(n_nodes) < 0.3
    b = SnapshotBuilder(max_nodes=n_nodes)
    for i in range(n_nodes):
        taints = [Taint(key="dedicated", value="infra",
                        effect="NoSchedule")] if tainted[i] else []
        b.add_node(Node(meta=ObjectMeta(name=f"n{i}",
                                        labels={"zone": zones[i],
                                                "rack": racks[i]}),
                        allocatable={RK.CPU: 32000.0,
                                     RK.MEMORY: 65536.0},
                        taints=taints))
        b.set_node_metric(NodeMetric(node_name=f"n{i}", update_time=1e9,
                                     node_usage={}))
    snap, ctx = b.build(now=1e9)

    spread = TopologySpreadConstraint(max_skew=1, topology_key="zone",
                                      label_selector={"app": "web"})
    anti = PodAffinityTerm(topology_key="zone",
                           label_selector={"app": "etcd"}, anti=True)
    # a SECOND carried anti term for some etcd pods (multi-term gating)
    anti_web = PodAffinityTerm(topology_key="rack",
                               label_selector={"app": "web"}, anti=True)
    aff = PodAffinityTerm(topology_key="zone",
                          label_selector={"app": "job"})
    tol = [Toleration(key="dedicated", value="infra",
                      effect="NoSchedule")]
    pods = []
    roles = rng.integers(0, 4, 24)
    etcd_count = 0
    for j, role in enumerate(roles):
        tolerant = bool(rng.random() < 0.5)
        kw = dict(priority=9000 + int(rng.integers(0, 500)),
                  requests={RK.CPU: 500.0, RK.MEMORY: 512.0},
                  tolerations=tol if tolerant else [])
        if role == 0:
            pods.append(Pod(meta=ObjectMeta(name=f"w{j}", namespace="d",
                                            labels={"app": "web"}),
                            spread_constraints=[spread], **kw))
        elif role == 1:
            # every other etcd pod carries BOTH terms — deterministic,
            # so the 3b non-vacuity guard cannot depend on rng draws
            two_terms = etcd_count % 2 == 1
            etcd_count += 1
            pods.append(Pod(meta=ObjectMeta(name=f"e{j}", namespace="d",
                                            labels={"app": "etcd"}),
                            pod_affinity=[anti, anti_web] if two_terms
                            else [anti], **kw))
        elif role == 2:
            pods.append(Pod(meta=ObjectMeta(name=f"j{j}", namespace="d",
                                            labels={"app": "job"}),
                            pod_affinity=[aff], **kw))
        else:
            pods.append(Pod(meta=ObjectMeta(name=f"p{j}", namespace="d",
                                            labels={"app": "plain"}),
                            **kw))
    batch = b.build_pod_batch(pods, ctx)
    res = core.schedule_batch(snap, batch, CFG, num_rounds=5)
    a = np.asarray(res.assignment)

    # 1. taints
    for j, pod in enumerate(pods):
        if a[j] >= 0 and tainted[a[j]]:
            assert pod.tolerations, \
                f"seed {seed}: pod {j} on tainted node untolerated"
    # 2. spread skew over eligible domains (initial counts are zero)
    web = [j for j, p in enumerate(pods)
           if p.meta.labels["app"] == "web"]
    placed_zones = [zones[a[j]] for j in web if a[j] >= 0]
    # every zone is eligible: dvalid honors the group's own node
    # constraints (none here); taints don't narrow eligibility, matching
    # upstream's default nodeTaintsPolicy=Ignore
    eligible = set(zones)
    if placed_zones:
        counts = {z: placed_zones.count(z) for z in eligible}
        assert max(counts.values()) - min(counts.values()) <= 1, \
            f"seed {seed}: skew violated {counts}"
    # 3. mutual anti: one etcd per zone
    etcd_zones = [zones[a[j]] for j, p in enumerate(pods)
                  if p.meta.labels["app"] == "etcd" and a[j] >= 0]
    assert len(etcd_zones) == len(set(etcd_zones)), \
        f"seed {seed}: anti-affine pods co-domained {etcd_zones}"
    # 3b. the SECOND carried term binds too: a two-term etcd pod never
    # shares a rack with any placed web pod. Identified by term CONTENT
    # (not list length), with a non-vacuity guard: the scenario must
    # actually place both sides or the assertion proves nothing.
    web_racks = {racks[a[j]] for j, p in enumerate(pods)
                 if p.meta.labels["app"] == "web" and a[j] >= 0}
    two_term = [j for j, p in enumerate(pods)
                if anti_web in p.pod_affinity]
    # non-vacuity: at least one two-term pod PLACED and a web rack
    # occupied, so the loop below actually checks something (the
    # deterministic single-pod case is
    # test_scheduler_core.test_multi_term_anti_affinity_gates_every_term)
    assert any(a[j] >= 0 for j in two_term) and web_racks, \
        f"seed {seed}: 3b is vacuous (retune the workload)"
    for j in two_term:
        if a[j] >= 0:
            assert racks[a[j]] not in web_racks, \
                f"seed {seed}: second anti term violated (pod {j})"
    # 4. affinity: every placed job shares a zone with another job
    job_zones = [zones[a[j]] for j, p in enumerate(pods)
                 if p.meta.labels["app"] == "job" and a[j] >= 0]
    if len(job_zones) > 1:
        assert len(set(job_zones)) == 1, \
            f"seed {seed}: affinity group split {job_zones}"
