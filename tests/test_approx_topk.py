"""Placement-quality bound for approx_topk (VERDICT r3 #8).

The bench's hot path selects each pod's k candidate nodes with
jax.lax.approx_max_k (TPU-optimized partial reduction, default recall
target 0.95) instead of exact lax.top_k. The choice list is a heuristic
preference order and missed candidates are recovered by later rounds
and the adaptive tail retries, so bounded recall costs placement
QUALITY (a pod occasionally takes its 2nd-best node), not correctness.

The DOCUMENTED bound these tests pin, on whatever platform runs them:

  - placements: placed_approx >= 0.99 x placed_exact
  - quality:    sum(chosen_score of placed) >= 0.95 x exact score-sum

On CPU, XLA lowers approx_max_k to the exact reduction, so this suite
additionally pins bit-identical assignments there — i.e. the bound is
about the TPU partial-reduction mode; run `BENCH_APPROX=0 python
bench.py` next to the default on real hardware to measure the live
delta (both placed counts and scores land in the emitted JSON).
"""

import jax
import numpy as np
import pytest

from koordinator_tpu.scheduler import core
from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
from koordinator_tpu.utils import synthetic


def run(approx: bool, num_rounds=2, k_choices=8):
    """One contended small-shape schedule (pods ~2x node headroom so
    the top-k choice list actually matters)."""
    snap = synthetic.synthetic_cluster(64, num_quotas=8, seed=5)
    pods = synthetic.synthetic_pods(512, num_quotas=8, seed=6)
    res = core.schedule_batch(snap, pods, LoadAwareConfig.make(),
                              num_rounds=num_rounds, k_choices=k_choices,
                              approx_topk=approx, tie_break=True,
                              enable_numa=False)
    a = np.asarray(res.assignment)
    placed = a >= 0
    score_sum = float(np.asarray(res.chosen_score)[placed].sum())
    return a, int(placed.sum()), score_sum


def test_approx_topk_placement_quality_bound():
    a_exact, placed_exact, score_exact = run(approx=False)
    a_approx, placed_approx, score_approx = run(approx=True)
    assert placed_exact > 0
    # the documented bound (see module docstring)
    assert placed_approx >= 0.99 * placed_exact, (placed_approx,
                                                  placed_exact)
    assert score_approx >= 0.95 * score_exact, (score_approx,
                                                score_exact)


def test_cpu_lowering_is_exact():
    """On CPU approx_max_k IS top_k — pin that, so the bound above is
    understood as a statement about the TPU partial reduction."""
    if jax.devices()[0].platform != "cpu":
        pytest.skip("cpu-lowering check")
    a_exact, _, _ = run(approx=False)
    a_approx, _, _ = run(approx=True)
    np.testing.assert_array_equal(a_approx, a_exact)


def test_recall_misses_fall_through_to_later_rounds():
    """The recovery mechanism the bound relies on: dropping 2 of the 8
    choices outright (a 25%% loss — five times approx_max_k's ~5%%
    expected recall miss) costs under 3%% of single-batch placements
    once rounds retry, showing missed candidates overwhelmingly cost
    score, not placements — and the bench's k=32 tail passes close the
    remainder. (A drastic handicap like k=2 DOES cost placements in a
    single batch; the bound here calibrates the regime approx_max_k
    actually operates in.)"""
    _, placed_full, _ = run(approx=False, num_rounds=4, k_choices=8)
    _, placed_narrow, _ = run(approx=False, num_rounds=4, k_choices=6)
    assert placed_narrow >= 0.97 * placed_full, (placed_narrow,
                                                 placed_full)
