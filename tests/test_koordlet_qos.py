"""qosmanager strategies + runtimehooks + prediction + pleg + audit, all
against the fake host tree (SURVEY.md 3.3, 3.4)."""

import json
import os

import pytest

from koordinator_tpu.api import types as api
from koordinator_tpu.api.extension import QoSClass, ResourceKind
from koordinator_tpu.koordlet import metriccache as mc
from koordinator_tpu.koordlet import pleg as plegmod
from koordinator_tpu.koordlet.audit import Auditor
from koordinator_tpu.koordlet.prediction import (
    DecayedHistogram,
    PeakPredictServer,
    PredictConfig,
)
from koordinator_tpu.koordlet.qosmanager import (
    BE_ROOT,
    BlkIOReconcile,
    CPUBurst,
    CPUEvict,
    CPUSuppress,
    CPUSuppressConfig,
    CgroupReconcile,
    MemoryEvict,
    RecordingEvictor,
    ResctrlReconcile,
    suppress_cpuset_policy,
)
from koordinator_tpu.koordlet.resourceexecutor import Executor
from koordinator_tpu.koordlet.runtimehooks import (
    ANNOTATION_RESOURCE_STATUS,
    FakeCoreSched,
    HookContext,
    Reconciler,
    Stage,
    default_hook_server,
)
from koordinator_tpu.koordlet.statesinformer import PodMeta, StatesInformer
from koordinator_tpu.koordlet.system import ProcessorInfo, parse_cpuset
from koordinator_tpu.koordlet.testing import FakeHost


def make_pod(uid, qos="LS", priority=9500, cpu_milli=1000.0, mem_mib=1024.0,
             limits=None, annotations=None):
    return PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(uid=uid, name=uid, namespace="default",
                            annotations=annotations or {}),
        requests={ResourceKind.CPU: cpu_milli, ResourceKind.MEMORY: mem_mib},
        limits=limits or {},
        qos_label=qos, priority=priority))


def make_be_pod(uid, batch_cpu=1000.0, batch_mem=1024.0, priority=5500):
    return PodMeta(pod=api.Pod(
        meta=api.ObjectMeta(uid=uid, name=uid),
        requests={ResourceKind.BATCH_CPU: batch_cpu,
                  ResourceKind.BATCH_MEMORY: batch_mem},
        limits={ResourceKind.BATCH_CPU: batch_cpu,
                ResourceKind.BATCH_MEMORY: batch_mem},
        qos_label="BE", priority=priority))


@pytest.fixture
def env(tmp_path):
    host = FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)
    informer = StatesInformer()
    cache = mc.MetricCache()
    executor = Executor(host)
    informer.set_node(api.Node(
        meta=api.ObjectMeta(name="node-1"),
        allocatable={ResourceKind.CPU: 8000.0,
                     ResourceKind.MEMORY: 16384.0}))
    slo = api.NodeSLO(node_name="node-1")
    slo.threshold.enable = True
    informer.set_node_slo(slo)
    return host, informer, cache, executor


# --- suppress ---------------------------------------------------------------

def test_suppress_cpuset_policy_packs_cores():
    procs = [ProcessorInfo(cpu_id=i, core_id=i // 2, socket_id=0, node_id=0)
             for i in range(8)]
    # full physical cores first, in core order
    assert suppress_cpuset_policy(4, procs) == [0, 1, 2, 3]
    # excluded (LSR-pinned) cpus are avoided
    assert suppress_cpuset_policy(2, procs, exclude=[0, 1, 2, 3]) == [4, 5]
    # not enough cpus -> capped to the full available set
    assert suppress_cpuset_policy(9, procs) == list(range(8))


def test_suppress_cpuset_policy_prefers_bigger_numa_bucket():
    procs = ([ProcessorInfo(i, i // 2, 0, 0) for i in range(4)]
             + [ProcessorInfo(4 + i, 2 + i // 2, 1, 1) for i in range(8)])
    got = suppress_cpuset_policy(4, procs)
    assert got == [4, 5, 6, 7]  # larger node-1 bucket wins


def test_cpusuppress_cpuset(env):
    host, informer, cache, executor = env
    be = make_be_pod("be-1")
    host.make_cgroup(be.cgroup_dir)
    informer.set_pods([be])
    # node used 6 of 8 cores, BE itself 1, system 1 => nonBE pods = 4
    for t in (0.0, 30.0):
        cache.append(mc.NODE_CPU_USAGE, t, 6.0)
        cache.append(mc.BE_CPU_USAGE, t, 1.0)
        cache.append(mc.SYS_CPU_USAGE, t, 1.0)
    CPUSuppress(informer, cache, executor).reconcile(now=30.0)
    # suppress = 8*0.65 - 4 - 1 = 0.2 -> floored to MIN 1 core
    got = parse_cpuset(host.read_cgroup(BE_ROOT, "cpuset.cpus"))
    assert len(got) == 1
    assert parse_cpuset(host.read_cgroup(be.cgroup_dir, "cpuset.cpus")) == got


def test_cpusuppress_cfs_quota(env):
    host, informer, cache, executor = env
    informer.set_pods([])
    for t in (0.0, 30.0):
        cache.append(mc.NODE_CPU_USAGE, t, 2.0)   # mostly idle
        cache.append(mc.BE_CPU_USAGE, t, 0.5)
        cache.append(mc.SYS_CPU_USAGE, t, 0.5)
    CPUSuppress(informer, cache, executor,
                CPUSuppressConfig(policy="cfsQuota")).reconcile(now=30.0)
    # suppress = 8*0.65 - 1.0 - 0.5 = 3.7 cores -> quota 370000
    assert host.read_cgroup(BE_ROOT, "cpu.cfs_quota_us") == "370000"


def test_cpusuppress_avoids_lsr_cpus(env):
    host, informer, cache, executor = env
    lsr = make_pod("lsr-1", qos="LSR")
    host.make_cgroup(lsr.cgroup_dir, {"cpuset.cpus": "0-3"})
    informer.set_pods([lsr])
    for t in (0.0, 30.0):
        cache.append(mc.NODE_CPU_USAGE, t, 1.0)
        cache.append(mc.BE_CPU_USAGE, t, 0.5)
        cache.append(mc.SYS_CPU_USAGE, t, 0.5)
    CPUSuppress(informer, cache, executor).reconcile(now=30.0)
    got = parse_cpuset(host.read_cgroup(BE_ROOT, "cpuset.cpus"))
    assert got and not set(got) & {0, 1, 2, 3}


def test_cpusuppress_disabled_no_write(env):
    host, informer, cache, executor = env
    informer.get_node_slo().threshold.enable = False
    before = host.read_cgroup(BE_ROOT, "cpuset.cpus")
    cache.append(mc.NODE_CPU_USAGE, 0.0, 6.0)
    CPUSuppress(informer, cache, executor).reconcile(now=1.0)
    assert host.read_cgroup(BE_ROOT, "cpuset.cpus") == before


# --- burst ------------------------------------------------------------------

def test_cpuburst_grants_and_scales(env):
    host, informer, cache, executor = env
    slo = informer.get_node_slo()
    slo.cpu_burst.policy = "auto"
    pod = make_pod("ls-1", limits={ResourceKind.CPU: 2000.0})
    host.make_cgroup(pod.cgroup_dir, {"cpu.cfs_quota_us": "200000"})
    informer.set_pods([pod])
    cache.append(mc.NODE_CPU_USAGE, 0.0, 1.0)  # idle node
    cache.append(mc.PSI_CPU_SOME_AVG10, 0.0, 25.0,
                 {"cgroup": pod.cgroup_dir})   # throttled
    CPUBurst(informer, cache, executor).reconcile(now=1.0)
    # burst = 2 cores * 1000% = 20 cores * period
    assert host.read_cgroup(pod.cgroup_dir, "cpu.cfs_burst_us") == "2000000"
    # quota scaled up 1.2x
    assert host.read_cgroup(pod.cgroup_dir, "cpu.cfs_quota_us") == "240000"

    # overloaded node resets quota to base
    cache.append(mc.NODE_CPU_USAGE, 2.0, 7.9)
    CPUBurst(informer, cache, executor).reconcile(now=2.0)
    assert host.read_cgroup(pod.cgroup_dir, "cpu.cfs_quota_us") == "200000"


def test_cpuburst_cap(env):
    host, informer, cache, executor = env
    slo = informer.get_node_slo()
    slo.cpu_burst.policy = "cfsQuotaBurstOnly"
    slo.cpu_burst.cfs_quota_burst_percent = 110.0
    pod = make_pod("ls-1", limits={ResourceKind.CPU: 1000.0})
    host.make_cgroup(pod.cgroup_dir, {"cpu.cfs_quota_us": "100000"})
    informer.set_pods([pod])
    cache.append(mc.NODE_CPU_USAGE, 0.0, 0.5)
    cache.append(mc.PSI_CPU_SOME_AVG10, 0.0, 25.0,
                 {"cgroup": pod.cgroup_dir})
    CPUBurst(informer, cache, executor).reconcile(now=1.0)
    assert host.read_cgroup(pod.cgroup_dir, "cpu.cfs_quota_us") == "110000"
    # cpuBurstOnly knob not applied in cfsQuotaBurstOnly mode
    assert host.read_cgroup(pod.cgroup_dir, "cpu.cfs_burst_us") == "0"


# --- evict ------------------------------------------------------------------

def test_cpuevict_releases_lowest_priority_first(env):
    host, informer, cache, executor = env
    slo = informer.get_node_slo()
    slo.threshold.cpu_evict_satisfaction_lower_percent = 30.0
    b1 = make_be_pod("be-1", batch_cpu=4000.0, priority=5100)
    b2 = make_be_pod("be-2", batch_cpu=4000.0, priority=5900)
    for m in (b1, b2):
        host.make_cgroup(m.cgroup_dir)
    informer.set_pods([b1, b2])
    # suppressed BE limit: 1 core over 8000 milli requested => satisfaction
    # 12.5% < 30%
    host.write_cgroup(BE_ROOT, "cpu.cfs_quota_us", "100000")
    for t in (0.0, 100.0):
        cache.append(mc.BE_CPU_USAGE, t, 0.95)  # pressing the 1-core limit
    ev = RecordingEvictor()
    CPUEvict(informer, cache, executor, ev).reconcile(now=100.0)
    assert [p.pod.meta.uid for p, _ in ev.evicted] == ["be-1"]


def test_memoryevict_until_lower_percent(env):
    host, informer, cache, executor = env
    slo = informer.get_node_slo()
    slo.threshold.memory_evict_threshold_percent = 70.0
    slo.threshold.memory_evict_lower_percent = 65.0
    b1 = make_be_pod("be-1", batch_mem=2048.0, priority=5100)
    b2 = make_be_pod("be-2", batch_mem=2048.0, priority=5900)
    informer.set_pods([b1, b2])
    # 12 GiB used of 16 GiB = 75% > 70%; target release to 65% => 1.6 GiB
    cache.append(mc.NODE_MEMORY_USAGE, 0.0, float(12 << 30))
    cache.append(mc.POD_MEMORY_USAGE, 0.0, float(2 << 30), {"pod_uid": "be-1"})
    cache.append(mc.POD_MEMORY_USAGE, 0.0, float(2 << 30), {"pod_uid": "be-2"})
    ev = RecordingEvictor()
    MemoryEvict(informer, cache, ev).reconcile(now=1.0)
    assert [p.pod.meta.uid for p, _ in ev.evicted] == ["be-1"]


def test_memoryevict_below_threshold_noop(env):
    host, informer, cache, executor = env
    informer.get_node_slo().threshold.memory_evict_threshold_percent = 70.0
    informer.set_pods([make_be_pod("be-1")])
    cache.append(mc.NODE_MEMORY_USAGE, 0.0, float(4 << 30))
    ev = RecordingEvictor()
    MemoryEvict(informer, cache, ev).reconcile(now=1.0)
    assert ev.evicted == []


# --- resctrl + cgroup reconcile --------------------------------------------

def test_resctrl_schemata_per_tier(env):
    host, informer, cache, executor = env
    host.init_resctrl(l3_mask="fff")
    slo = informer.get_node_slo()
    slo.resource_qos.tiers = {
        "LS": {"catRangeEndPercent": 100.0, "mbaPercent": 100.0},
        "BE": {"catRangeEndPercent": 30.0, "mbaPercent": 40.0},
    }
    ResctrlReconcile(informer, executor).reconcile(now=1.0)
    assert host.resctrl_schemata("BE") == {"L3": "0=f", "MB": "0=40"}
    assert host.resctrl_schemata("LS") == {"L3": "0=fff", "MB": "0=100"}


def test_cgroup_reconcile_memory_protection(env):
    host, informer, cache, executor = env
    slo = informer.get_node_slo()
    slo.resource_qos.tiers = {"LS": {"memoryMinPercent": 50.0,
                                     "memoryLowPercent": 75.0}}
    pod = make_pod("ls-1", mem_mib=1024.0)
    host.make_cgroup(pod.cgroup_dir)
    informer.set_pods([pod])
    CgroupReconcile(informer, executor).reconcile(now=1.0)
    assert host.read_cgroup(pod.cgroup_dir, "memory.min") == str(512 << 20)
    assert host.read_cgroup(pod.cgroup_dir, "memory.low") == str(768 << 20)


# --- runtimehooks -----------------------------------------------------------

def test_hooks_group_identity_and_batch(env):
    host, informer, cache, executor = env
    server = default_hook_server(informer)
    be = make_be_pod("be-1", batch_cpu=2000.0, batch_mem=2048.0)
    ctx = HookContext(pod=be, stage=Stage.PRE_RUN_POD_SANDBOX)
    server.run_hooks(Stage.PRE_RUN_POD_SANDBOX, ctx)
    writes = {(u.resource): u.value for u in ctx.cgroup_updates}
    assert writes["cpu.bvt_warp_ns"] == "-1"
    assert writes["cpu.shares"] == str(int(2000 * 1024 / 1000))
    assert writes["cpu.cfs_quota_us"] == "200000"
    assert writes["memory.limit_in_bytes"] == str(2048 << 20)


def test_hooks_cpuset_annotation_and_reconciler(env):
    host, informer, cache, executor = env
    status = json.dumps({"cpuset": "2-3", "numaNodes": [0]})
    pod = make_pod("lsr-1", qos="LSR",
                   annotations={ANNOTATION_RESOURCE_STATUS: status})
    host.make_cgroup(pod.cgroup_dir)
    informer.set_pods([pod])
    core = FakeCoreSched()
    server = default_hook_server(informer, core)
    Reconciler(informer, server, executor).reconcile_all()
    assert host.read_cgroup(pod.cgroup_dir, "cpuset.cpus") == "2-3"
    assert host.read_cgroup(pod.cgroup_dir, "cpuset.mems") == "0"
    assert host.read_cgroup(pod.cgroup_dir, "cpu.bvt_warp_ns") == "2"
    assert core.assignments[pod.cgroup_dir] == "qos/LSR"


def test_hooks_gpu_env():
    from koordinator_tpu.koordlet.runtimehooks import (
        ANNOTATION_DEVICE_ALLOCATED,
        GPUEnvHook,
    )
    pod = make_pod("g-1", annotations={
        ANNOTATION_DEVICE_ALLOCATED: json.dumps(
            {"gpu": [{"minor": 0}, {"minor": 3}]})})
    ctx = HookContext(pod=pod, stage=Stage.PRE_CREATE_CONTAINER)
    GPUEnvHook().apply(ctx)
    assert ctx.env["NVIDIA_VISIBLE_DEVICES"] == "0,3"


# --- prediction -------------------------------------------------------------

def test_histogram_percentile_and_decay():
    h = DecayedHistogram(0.01, half_life_seconds=3600.0)
    for _ in range(100):
        h.add(1.0, ts=0.0)
    assert h.percentile(0.5) == pytest.approx(1.0, rel=0.06)
    # a much-later single sample at 4.0 dominates decayed history
    for _ in range(2):
        h.add(4.0, ts=20 * 3600.0)
    assert h.percentile(0.5) == pytest.approx(4.0, rel=0.06)


def test_prediction_prod_reclaimable_and_checkpoint(env, tmp_path):
    host, informer, cache, executor = env
    pod = make_pod("prod-1", cpu_milli=4000.0, mem_mib=4096.0, priority=9500)
    informer.set_pods([pod])
    cfg = PredictConfig(cold_start_seconds=0.0,
                        checkpoint_path=str(tmp_path / "ckpt.json"))
    srv = PeakPredictServer(informer, cache, cfg)
    for t in range(10):
        cache.append(mc.POD_CPU_USAGE, float(t), 1.0, {"pod_uid": "prod-1"})
        cache.append(mc.POD_MEMORY_USAGE, float(t), float(1 << 30),
                     {"pod_uid": "prod-1"})
        srv.train_once(now=float(t))
    srv.pod_start["prod-1"] = -10.0
    rec = srv.prod_reclaimable(now=10.0)
    # request 4 cores, peak ~1 core * 1.1 margin -> ~2.9 reclaimable
    assert rec[ResourceKind.CPU] == pytest.approx(2900.0, rel=0.1)
    assert rec[ResourceKind.MEMORY] == pytest.approx(4096 - 1024 * 1.1,
                                                     rel=0.1)
    # checkpoint roundtrip preserves prediction
    srv.checkpoint()
    srv2 = PeakPredictServer(informer, cache, cfg)
    assert srv2.restore()
    assert srv2.prediction("prod-1")["p95"]["cpu"] == pytest.approx(
        srv.prediction("prod-1")["p95"]["cpu"])


def test_prediction_gc():
    informer = StatesInformer()
    cache = mc.MetricCache()
    srv = PeakPredictServer(informer, cache)
    srv._model("pod-a")
    srv._model("priority/PROD")
    srv.gc(live_uids=[])
    assert "pod-a" not in srv.models
    assert "priority/PROD" in srv.models  # aggregates survive


# --- pleg -------------------------------------------------------------------

def test_pleg_polling_events(tmp_path):
    host = FakeHost(str(tmp_path))
    p = plegmod.Pleg.for_host(host, use_inotify=False)
    got = []
    p.subscribe(got.append)
    host.make_cgroup("kubepods/besteffort/pod12ab-34")
    events = p.poll_once()
    assert any(e.type is plegmod.EventType.POD_ADDED
               and e.pod_uid == "12ab-34" for e in events)
    # container arrival inside the pod dir
    host.make_cgroup("kubepods/besteffort/pod12ab-34/ctr1")
    events = p.poll_once()
    assert any(e.type is plegmod.EventType.CONTAINER_ADDED for e in events)
    assert got, "subscriber received events"


def test_pleg_inotify_if_available(tmp_path):
    host = FakeHost(str(tmp_path))
    p = plegmod.Pleg.for_host(host, use_inotify=True)
    if not isinstance(p.watcher, plegmod.InotifyWatcher):
        pytest.skip("inotify unavailable")
    os.makedirs(os.path.join(host.cgroup_root, "cpu/kubepods/podcc-dd"),
                exist_ok=True)
    events = p.watcher.poll(timeout=1.0)
    assert any(e.pod_uid == "cc-dd" for e in events)


# --- audit ------------------------------------------------------------------

def test_audit_ring_and_rotation(tmp_path):
    a = Auditor(log_dir=str(tmp_path), ring_size=5, max_file_bytes=200,
                max_files=3)
    for i in range(20):
        a.record("info", "test", "write", f"target-{i}")
    got = a.query(component="test", limit=3)
    assert [e.target for e in got] == ["target-19", "target-18", "target-17"]
    assert len(a.query()) == 5  # ring bound
    a.close()
    files = sorted(os.listdir(tmp_path))
    assert "audit.log" in files and any(f.startswith("audit.log.")
                                        for f in files)


# --- daemon wiring ----------------------------------------------------------

def test_daemon_full_cycle(tmp_path):
    from koordinator_tpu.koordlet.agent import Daemon, DaemonConfig
    host = FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)
    d = Daemon(host, DaemonConfig(qos_interval_seconds=5.0,
                                  report_interval_seconds=10.0))
    d.informer.set_node(api.Node(
        meta=api.ObjectMeta(name="node-1"),
        allocatable={ResourceKind.CPU: 8000.0,
                     ResourceKind.MEMORY: 16384.0}))
    slo = api.NodeSLO(node_name="node-1")
    slo.threshold.enable = True
    d.informer.set_node_slo(slo)
    be = make_be_pod("be-1")
    host.make_cgroup(be.cgroup_dir)
    d.informer.set_pods([be])

    d.tick(now=0.0)
    host.advance_cpu(busy_ticks=6000, idle_ticks=2000)  # 6 of 8 cores busy
    host.set_cgroup_cpu_ns(be.cgroup_dir, 10_000_000_000)
    report = d.tick(now=10.0)
    # report produced on the interval, BE cpuset suppressed, hooks applied
    assert report is not None and report.node_name == "node-1"
    assert report.node_usage[ResourceKind.CPU] > 0
    assert host.read_cgroup(be.cgroup_dir, "cpu.bvt_warp_ns") == "-1"
    suppressed = parse_cpuset(host.read_cgroup(BE_ROOT, "cpuset.cpus"))
    assert len(suppressed) < 8


def test_histogram_wallclock_timestamps():
    """Real epoch timestamps must not overflow the decay scale."""
    import time as _time
    h = DecayedHistogram(0.01, half_life_seconds=12 * 3600.0)
    now = _time.time()
    for i in range(100):
        h.add(2.0, ts=now + i)
    assert h.percentile(0.9) == pytest.approx(2.0, rel=0.06)
    # and a huge forward jump still renormalizes instead of overflowing
    h.add(2.0, ts=now + 365 * 86400.0)
    assert h.percentile(0.9) == pytest.approx(2.0, rel=0.06)


def test_suppress_policy_caps_to_available():
    procs = [ProcessorInfo(cpu_id=i, core_id=i // 2, socket_id=0, node_id=0)
             for i in range(8)]
    # want 5 but only 2 grantable after exclusion -> grant the 2
    got = suppress_cpuset_policy(5, procs, exclude=[0, 1, 2, 3, 4, 5])
    assert got == [6, 7]


def test_evictor_dedup_and_drain():
    ev = RecordingEvictor()
    pod = make_be_pod("be-1")
    ev(pod, "r1")
    ev(pod, "r1 again")
    assert len(ev.evicted) == 1
    assert len(ev.drain()) == 1
    ev(pod, "after drain")
    assert len(ev.evicted) == 1


# --- SystemQOS (apis/extension/system_qos.go) -------------------------------

def _set_system_qos(informer, spec: str):
    from koordinator_tpu.api.extension import (
        ANNOTATION_NODE_SYSTEM_QOS_RESOURCE,
    )

    node = informer.get_node()
    node.meta.annotations[ANNOTATION_NODE_SYSTEM_QOS_RESOURCE] = spec
    informer.set_node(node)


def test_parse_system_qos_resource():
    from koordinator_tpu.api.extension import (
        ANNOTATION_NODE_SYSTEM_QOS_RESOURCE,
        parse_system_qos_resource,
    )

    anno = {ANNOTATION_NODE_SYSTEM_QOS_RESOURCE:
            '{"cpuset": "0-1,6", "cpusetExclusive": false}'}
    got = parse_system_qos_resource(anno)
    assert got == {"cpuset": "0-1,6", "cpus": [0, 1, 6], "exclusive": False}
    # exclusive defaults TRUE (system_qos.go:36-39)
    got = parse_system_qos_resource(
        {ANNOTATION_NODE_SYSTEM_QOS_RESOURCE: '{"cpuset": "2"}'})
    assert got["exclusive"] is True and got["cpus"] == [2]
    assert parse_system_qos_resource({}) is None
    assert parse_system_qos_resource(
        {ANNOTATION_NODE_SYSTEM_QOS_RESOURCE: "not-json"}) is None
    assert parse_system_qos_resource(
        {ANNOTATION_NODE_SYSTEM_QOS_RESOURCE: '{"cpuset": ""}'}) is None


def test_cpusuppress_avoids_exclusive_system_qos_cpus(env):
    """BE suppress never lands on exclusive SystemQOS cores
    (cpu_suppress.go:366-376)."""
    host, informer, cache, executor = env
    _set_system_qos(informer, '{"cpuset": "0-3"}')
    informer.set_pods([])
    for t in (0.0, 30.0):
        cache.append(mc.NODE_CPU_USAGE, t, 1.0)
        cache.append(mc.BE_CPU_USAGE, t, 0.5)
        cache.append(mc.SYS_CPU_USAGE, t, 0.5)
    CPUSuppress(informer, cache, executor).reconcile(now=30.0)
    got = parse_cpuset(host.read_cgroup(BE_ROOT, "cpuset.cpus"))
    assert got and not set(got) & {0, 1, 2, 3}
    # non-exclusive system cpus are usable again
    _set_system_qos(informer, '{"cpuset": "0-3", "cpusetExclusive": false}')
    CPUSuppress(informer, cache, executor).reconcile(now=30.0)
    got = parse_cpuset(host.read_cgroup(BE_ROOT, "cpuset.cpus"))
    assert got  # policy free to use any cores now


def test_system_qos_pod_gets_system_cpuset(env):
    """SYSTEM QoS pods inherit the node system-qos cpuset
    (cpuset/rule.go:105-111)."""
    host, informer, cache, executor = env
    _set_system_qos(informer, '{"cpuset": "6-7"}')
    pod = make_pod("sysd", qos="SYSTEM")
    host.make_cgroup(pod.cgroup_dir)
    informer.set_pods([pod])
    server = default_hook_server(informer)
    ctx = HookContext(pod=pod, stage=Stage.PRE_CREATE_CONTAINER)
    server.run_hooks(Stage.PRE_CREATE_CONTAINER, ctx)
    writes = {u.resource: u.value for u in ctx.cgroup_updates}
    assert writes.get("cpuset.cpus") == "6-7"


def test_topology_reporter_excludes_system_qos(tmp_path):
    """Exclusive SystemQOS cores vanish from the reported NRT zones
    (states_noderesourcetopology.go removeSystemQOSCPUs)."""
    from koordinator_tpu.koordlet.statesinformer import TopologyReporter

    host = FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)
    informer = StatesInformer()
    informer.set_node(api.Node(meta=api.ObjectMeta(name="n0")))
    _set_system_qos(informer, '{"cpuset": "0-1"}')
    topo = TopologyReporter(host, informer, "n0").report()
    total_cpu = sum(z.cpus_milli for z in topo.zones)
    assert total_cpu == 6000.0
    for z in topo.zones:
        assert not (z.cpuset & 0b11)  # cpus 0,1 masked out


# --- PVC informer + blkio block throttles (states_pvc.go, blkio) ------------

def test_pvc_informer_and_blkio_blocks(env):
    import os

    host, informer, cache, executor = env
    for tier in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        os.makedirs(os.path.join(host.cgroup_root, "blkio", tier),
                    exist_ok=True)
    informer.set_pvcs([api.PersistentVolumeClaim(
        meta=api.ObjectMeta(name="data", namespace="default"),
        volume_name="pv-123")])
    assert informer.get_volume_name("default", "data") == "pv-123"
    assert informer.get_volume_name("default", "missing") == ""
    slo = informer.get_node_slo()
    slo.blkio_blocks = [
        api.BlockCfg(name="default/data", block_type="podvolume",
                     read_iops=500, io_weight_percent=60),
        api.BlockCfg(name="/dev/sdb", block_type="device", write_bps=1 << 20),
        api.BlockCfg(name="default/unbound", block_type="podvolume",
                     read_iops=100),  # unresolvable -> skipped
    ]
    informer.set_node_slo(slo)
    BlkIOReconcile(informer, executor).reconcile(now=0.0)
    assert host.read_cgroup(BE_ROOT,
                            "blkio.throttle.read_iops_device") == "pv-123 500"
    assert host.read_cgroup(BE_ROOT,
                            "blkio.cost.weight") == "pv-123 60"
    assert host.read_cgroup(
        BE_ROOT, "blkio.throttle.write_bps_device") == f"/dev/sdb {1 << 20}"


def test_blkio_removed_block_resets_throttle(env):
    """Regression: dropping a block from the SLO (or zeroing its limit)
    must reset the previously written kernel limit, not leave it live."""
    import os

    host, informer, cache, executor = env
    for tier in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        os.makedirs(os.path.join(host.cgroup_root, "blkio", tier),
                    exist_ok=True)
    slo = informer.get_node_slo()
    slo.blkio_blocks = [api.BlockCfg(name="/dev/sdb", read_iops=500,
                                     io_weight_percent=60)]
    informer.set_node_slo(slo)
    r = BlkIOReconcile(informer, executor)
    r.reconcile(now=0.0)
    assert host.read_cgroup(
        BE_ROOT, "blkio.throttle.read_iops_device") == "/dev/sdb 500"
    slo.blkio_blocks = []
    informer.set_node_slo(slo)
    r.reconcile(now=10.0)
    assert host.read_cgroup(
        BE_ROOT, "blkio.throttle.read_iops_device") == "/dev/sdb 0"
    assert host.read_cgroup(BE_ROOT, "blkio.cost.weight") == "/dev/sdb 100"


# --- kubelet /pods pull + CPU share pools -----------------------------------

def test_kubelet_stub_pull_and_units(tmp_path):
    """The agent pulls pods from the kubelet /pods endpoint
    (kubelet_stub.go:69-80) with bearer auth, converting quantities to
    native units (cpu milli, memory MiB)."""
    import http.server
    import threading

    from koordinator_tpu.koordlet.kubelet_stub import (
        KubeletStub,
        PodsPuller,
    )

    podlist = {"items": [{
        "metadata": {"name": "w-1", "namespace": "default", "uid": "u1",
                     "labels": {"koordinator.sh/qosClass": "BE"}},
        "spec": {"priority": 5500, "nodeName": "n0", "containers": [
            {"resources": {
                "requests": {"cpu": "500m", "memory": "512Mi",
                             "kubernetes.io/batch-cpu": "1000",
                             "kubernetes.io/batch-memory": "1073741824"},
                "limits": {"cpu": "1"}}},
            {"resources": {"requests": {"cpu": "250m"}}},
        ]},
        "status": {"phase": "Running"},
    }]}
    seen = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            seen["path"] = self.path
            seen["auth"] = self.headers.get("Authorization", "")
            body = json.dumps(podlist).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        stub = KubeletStub(addr="127.0.0.1", port=srv.server_port,
                           scheme="http", token="tok")
        informer = StatesInformer()
        puller = PodsPuller(stub, informer)
        assert puller.sync()
        assert seen["path"] == "/pods/" and seen["auth"] == "Bearer tok"
        pods = informer.get_all_pods()
        assert len(pods) == 1
        p = pods[0].pod
        assert p.requests[ResourceKind.CPU] == 750.0          # 500m + 250m
        assert p.requests[ResourceKind.MEMORY] == 512.0       # MiB
        assert p.requests[ResourceKind.BATCH_CPU] == 1000.0   # already milli
        assert p.requests[ResourceKind.BATCH_MEMORY] == 1024.0  # bytes->MiB
        assert p.limits[ResourceKind.CPU] == 1000.0
        assert p.qos == QoSClass.BE and p.phase == "Running"
        # pull failure keeps last good state
        srv.shutdown()
        assert not puller.sync()
        assert puller.last_error and len(informer.get_all_pods()) == 1
    finally:
        srv.shutdown()


def test_share_pool_reported_and_applied(tmp_path):
    """LSE/LSR-pinned + exclusive SystemQOS cpus leave the share pool;
    LS pods without a fine-grained assignment get the pool cpuset
    (states_noderesourcetopology.go share pools + rule.go LS branch)."""
    from koordinator_tpu.koordlet.statesinformer import TopologyReporter

    host = FakeHost(str(tmp_path), num_cpus=8, mem_bytes=16 << 30)
    informer = StatesInformer()
    node = api.Node(meta=api.ObjectMeta(name="n0", annotations={
        "node.koordinator.sh/system-qos-resource": '{"cpuset": "0"}'}))
    informer.set_node(node)
    lsr = make_pod("lsr-1", qos="LSR", annotations={
        ANNOTATION_RESOURCE_STATUS: json.dumps({"cpuset": "2-3"})})
    informer.set_pods([lsr])
    topo = TopologyReporter(host, informer, "n0").report()
    assert topo.ls_share_pool == "1,4-7"
    # LS pod -> pool cpuset through the hook
    ls = make_pod("ls-1", qos="LS")
    server = default_hook_server(informer)
    ctx = HookContext(pod=ls, stage=Stage.PRE_CREATE_CONTAINER)
    server.run_hooks(Stage.PRE_CREATE_CONTAINER, ctx)
    writes = {u.resource: u.value for u in ctx.cgroup_updates}
    assert writes.get("cpuset.cpus") == "1,4-7"
    # LSR pod keeps its pinned assignment, not the pool
    ctx2 = HookContext(pod=lsr, stage=Stage.PRE_CREATE_CONTAINER)
    server.run_hooks(Stage.PRE_CREATE_CONTAINER, ctx2)
    writes2 = {u.resource: u.value for u in ctx2.cgroup_updates}
    assert writes2.get("cpuset.cpus") == "2-3"


def test_blkio_slo_withdrawal_resets_applied_limits(env):
    """Regression: clearing the NodeSLO entirely still resets limits the
    strategy applied earlier."""
    import os

    host, informer, cache, executor = env
    for tier in ("kubepods", "kubepods/burstable", "kubepods/besteffort"):
        os.makedirs(os.path.join(host.cgroup_root, "blkio", tier),
                    exist_ok=True)
    slo = informer.get_node_slo()
    slo.blkio_blocks = [api.BlockCfg(name="/dev/sdb", read_iops=500)]
    informer.set_node_slo(slo)
    r = BlkIOReconcile(informer, executor)
    r.reconcile(now=0.0)
    informer.set_node_slo(None)
    r.reconcile(now=10.0)
    assert host.read_cgroup(
        BE_ROOT, "blkio.throttle.read_iops_device") == "/dev/sdb 0"


def test_kubelet_pull_combined_gpu_requests():
    """koordinator.sh/gpu and nvidia.com/gpu translate to gpu-core +
    memory-ratio (deviceshare utils.go:110-125)."""
    from koordinator_tpu.koordlet.kubelet_stub import pod_from_manifest

    pod = pod_from_manifest({
        "metadata": {"name": "g", "namespace": "d", "uid": "u"},
        "spec": {"containers": [
            {"resources": {"requests": {"koordinator.sh/gpu": "50",
                                        "cpu": "1"}}},
            {"resources": {"requests": {"nvidia.com/gpu": "2"}}},
        ]},
        "status": {},
    })
    assert pod.requests[ResourceKind.GPU_CORE] == 50.0 + 200.0
    assert pod.gpu_memory_ratio == 250.0
    assert pod.requests[ResourceKind.CPU] == 1000.0


def test_kubelet_pull_combined_gpu_limits_and_suffixes():
    """Regression: limits-only combined GPU authoring still models the
    memory share, and suffixed quantities don't abort the pull."""
    from koordinator_tpu.koordlet.kubelet_stub import pod_from_manifest

    pod = pod_from_manifest({
        "metadata": {"name": "g", "namespace": "d", "uid": "u"},
        "spec": {"containers": [
            {"resources": {"limits": {"koordinator.sh/gpu": "50"}}}]},
        "status": {},
    })
    assert pod.gpu_memory_ratio == 50.0
    assert pod.limits[ResourceKind.GPU_CORE] == 50.0
    # requests default to limits for extended resources: BOTH halves
    assert pod.requests[ResourceKind.GPU_CORE] == 50.0
    # malformed/suffixed combined quantity falls back to 0, no raise
    pod2 = pod_from_manifest({
        "metadata": {"name": "h", "namespace": "d", "uid": "u2"},
        "spec": {"containers": [
            {"resources": {"requests": {"koordinator.sh/gpu": "bad",
                                        "cpu": "1"}}}]},
        "status": {},
    })
    assert pod2.requests[ResourceKind.CPU] == 1000.0


def test_kubelet_pull_init_containers_and_overhead():
    """Regression (ADVICE r3): pod footprint follows the k8s effective
    request rule max(sum(containers), each initContainer) + overhead —
    an init-heavy pod no longer under-reports to qosmanager/reporters."""
    from koordinator_tpu.koordlet.kubelet_stub import pod_from_manifest

    pod = pod_from_manifest({
        "metadata": {"name": "i", "namespace": "d", "uid": "u"},
        "spec": {
            "containers": [
                {"resources": {"requests": {"cpu": "1",
                                            "memory": "256Mi"}}},
                {"resources": {"requests": {"cpu": "1"}}},
            ],
            "initContainers": [
                # bigger than the main set on cpu (4 > 2), smaller on mem
                {"resources": {"requests": {"cpu": "4",
                                            "memory": "128Mi"}}},
            ],
            "overhead": {"cpu": "250m", "memory": "64Mi"},
        },
        "status": {},
    })
    # cpu: max(2000, 4000) + 250 ; memory: max(256, 128) + 64
    assert pod.requests[ResourceKind.CPU] == 4250.0
    assert pod.requests[ResourceKind.MEMORY] == 320.0
    # overhead never fabricates a limit for an unlimited pod
    assert ResourceKind.CPU not in pod.limits
    # a small init container changes nothing
    pod2 = pod_from_manifest({
        "metadata": {"name": "j", "namespace": "d", "uid": "u2"},
        "spec": {
            "containers": [{"resources": {"requests": {"cpu": "2"}}}],
            "initContainers": [
                {"resources": {"requests": {"cpu": "1"}}}],
        },
        "status": {},
    })
    assert pod2.requests[ResourceKind.CPU] == 2000.0


def test_kubelet_pull_sidecar_containers_sum():
    """A native sidecar (initContainer restartPolicy: Always) runs
    ALONGSIDE the main set: it sums with the containers instead of
    folding into the per-init max, and a later regular init charges its
    own request plus the sidecars already started."""
    from koordinator_tpu.koordlet.kubelet_stub import pod_from_manifest

    pod = pod_from_manifest({
        "metadata": {"name": "s", "namespace": "d", "uid": "u"},
        "spec": {
            "containers": [
                {"resources": {"requests": {"cpu": "1"},
                               "limits": {"cpu": "2"}}}],
            "initContainers": [
                {"restartPolicy": "Always",
                 "resources": {"requests": {"cpu": "1"},
                               "limits": {"cpu": "1"}}},
                # starts after the sidecar: peak = 3 + 1 sidecar = 4
                {"resources": {"requests": {"cpu": "3"}}},
            ],
            "overhead": {"cpu": "500m"},
        },
        "status": {},
    })
    # requests: max(main 1000 + sidecar 1000, init 3000 + sidecar 1000)
    #           + overhead 500
    assert pod.requests[ResourceKind.CPU] == 4500.0
    # limits exist (main 2000 + sidecar 1000) so overhead adds there too
    assert pod.limits[ResourceKind.CPU] == 3500.0
