#!/usr/bin/env bash
# Minimal CI gate: koordlint first (fast, stdlib-only — fails in
# seconds on a hygiene regression), then the tier-1 pytest battery from
# ROADMAP.md on the CPU backend. Exit code is the first failing stage's.
set -euo pipefail
cd "$(dirname "$0")/.."

# under GitHub Actions, findings come out as ::error workflow commands
# so the runner turns them into inline PR annotations at the flagged
# line; on a desk the human text format stays
LINT_FORMAT=${GITHUB_ACTIONS:+--format github}

echo "=== koordlint (python -m tools.lint) ==="
python -m tools.lint ${LINT_FORMAT}

echo "=== koordlint self-lint (--root tools) ==="
# the analyzers obey their own rules: the tools tree is linted as a
# standalone root (same empty-baseline bar as the repo scan)
python -m tools.lint --root tools ${LINT_FORMAT}

echo "=== koordshape Tier B (device-free eval_shape gate) ==="
JAX_PLATFORMS=cpu python tools/shapecheck.py

echo "=== koordshape mutation smoke (gate liveness) ==="
# flip one dtype in a TEMP COPY of ops/feasibility.py and assert the
# gate fails on it — a shapecheck that can't catch the seeded mutation
# is a green-but-dead gate
JAX_PLATFORMS=cpu python tools/shapecheck.py --self-test-mutation

echo "=== koordpad Tier B (differential pad-inertness gate) ==="
# every contract runs concretely twice (zero-pad vs declared-fill pads):
# real regions must be bit-identical, output pad bands must hold their
# declared fills (tools/padcheck.py)
JAX_PLATFORMS=cpu python tools/padcheck.py

echo "=== koordpad dual-tier mutation smoke (gate liveness) ==="
# one seeded pad leak per tier in a TEMP COPY: a dropped schedulable
# conjunction only the differential run can see (padcheck must FAIL),
# and a dropped -1-index clamp only the static pass can see (the
# pad-soundness lint must flag PS002)
JAX_PLATFORMS=cpu python tools/padcheck.py --self-test-mutation

echo "=== koordrace Tier B (deterministic interleaving gate) ==="
# the guarded concurrent classes (store ingest/update/read, journal
# append/prune/reload, tracer span storm, metrics observe/export) run
# under a seeded deterministic scheduler across rr + random +
# bounded-preemption schedules; same seed must replay the same
# schedule (tools/racecheck.py)
JAX_PLATFORMS=cpu python tools/racecheck.py

echo "=== koordrace dual-tier mutation smoke (gate liveness) ==="
# one seeded lock drop per tier in a TEMP COPY: ingest's version guard
# on a fresh lock only the interleaving explorer can see (racecheck
# must FAIL, race-guard lint must pass), and a cold-path MetricCache
# unlock only the guarded-by contracts can see (GB001 must fire,
# racecheck must pass) — complementarity, not redundancy
JAX_PLATFORMS=cpu python tools/racecheck.py --self-test-mutation

echo "=== full-gate cascade smoke (2k pods x 200 nodes, CPU) ==="
# correctness + straggler-count assertions, not wall-clock: cascade
# on/off conformance, device-tail drain, single-stats-readback
# consistency (tools/cascade_smoke.py) — the cascade path runs on
# every push even when no test touches it
JAX_PLATFORMS=cpu python tools/cascade_smoke.py

echo "=== sharded full-gate mesh smoke (2-device virtual CPU mesh) ==="
# the multichip flagship path on a 2-device virtual mesh: bit-identical
# placements vs the single-device oracle, pad rows provably dead, the
# overcommit invariant on real rows, and structural HLO pins (stage-1
# collective-free, schedule step carries the ICI top-k merge) — never
# wall-clock (tools/mesh_flagship_smoke.py)
python tools/mesh_flagship_smoke.py

echo "=== chaos smoke (fault-injection matrix, CPU) ==="
# every fault class in koordinator_tpu/testing/faults.py: detected
# (guard word bit / FailureClass / typed delta reason), quarantined,
# service completes the cycle, and clean-row placements bit-identical
# to the no-fault oracle (tools/chaos_smoke.py) — correctness only,
# never wall-clock
JAX_PLATFORMS=cpu python tools/chaos_smoke.py

echo "=== crash smoke (kill-injected recovery matrix, CPU) ==="
# every named crash point in koordinator_tpu/testing/faults.py
# CRASH_POINTS: a child service is SIGKILLed at the point mid-batch,
# the restarted service recovers via checkpoint restore + commit-
# journal replay, and final placements must be BIT-IDENTICAL to the
# no-crash oracle — exactly one journal record per (epoch, chunk),
# torn tails surfaced with a typed reason (tools/crash_smoke.py)
JAX_PLATFORMS=cpu python tools/crash_smoke.py

echo "=== koordtrace smoke (observability contract, CPU) ==="
# a journaled, traced service on a small full-gate workload: every
# committed cycle carries the full host span skeleton under one cycle
# id, the Chrome dump is valid trace-event JSON (Perfetto-loadable),
# fault-injected cycles carry quarantine/retry/backoff/ladder records,
# every span name resolves against obs/phases.py, and journal_append
# span attrs join to the commit journal (tools/trace_smoke.py)
JAX_PLATFORMS=cpu python tools/trace_smoke.py

echo "=== koordcost drift gate (static cost/memory baseline, CPU) ==="
# every contracted kernel + the flagship cascade forms lowered and
# priced (flops, bytes accessed, donation-aware static peak, per-phase
# attribution, packed-representation bytes) and compared against
# perf/COST_BASELINE.json with loud provenance — any move beyond
# tolerance without a restamp fails with COST DRIFT (tools/costcheck.py)
JAX_PLATFORMS=cpu python tools/costcheck.py

echo "=== koordcost mutation smoke (gate liveness + complementarity) ==="
# a seeded bf16->f32 upcast in the packable path in a TEMP COPY: the
# cost gate must FAIL on the bytes drift while koordlint and shapecheck
# — hygiene and shapes, not bytes — must PASS the mutated tree
JAX_PLATFORMS=cpu python tools/costcheck.py --self-test-mutation

echo "=== benchdiff gate (proxy-shape bench vs checked-in baseline) ==="
# the comparator's own discrimination proof (seeded noise neutral,
# planted regressions flagged), then the pinned proxy shape runs fresh
# and joins against perf/BENCH_BASELINE.json: wall-clock fields loose
# (live-migrating CI hosts), deterministic counts and BENCH_COST stamps
# exact — a regression prints BENCH REGRESSION and fails
python tools/benchdiff.py --self-test
JAX_PLATFORMS=cpu python tools/benchdiff.py --proxy-run /tmp/_bench_proxy.jsonl
JAX_PLATFORMS=cpu python tools/benchdiff.py perf/BENCH_BASELINE.json /tmp/_bench_proxy.jsonl

echo "=== warm-cache smoke (compile-cache warm-start gate, CPU) ==="
# the flagship cycle runs in three REAL child processes against ONE
# compile-cache dir: cold (compiles, populates manifest), warm (ZERO
# XLA compilations, placements bit-identical), restart recovery
# (compiled_programs == 0, replay bit-identical) — the cross-process
# warm-start contract (tools/warm_cache_smoke.py); same-host only by
# construction, the dir lives and dies inside the stage
JAX_PLATFORMS=cpu python tools/warm_cache_smoke.py

echo "=== tier-1 tests (JAX_PLATFORMS=cpu) ==="
set -o pipefail
rm -f /tmp/_t1.log
# `|| rc=$?` keeps set -e from aborting before the DOTS_PASSED
# diagnostic — the pass count matters MOST on the failure path
rc=0
timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log || rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)"
exit "$rc"
