"""koordcost bench-trajectory comparator: noise-aware improve /
regress / neutral verdicts between two bench streams.

The bench emits self-describing JSON lines (bench.py) and the round
driver wraps them in BENCH_*.json artifacts; until now the trajectory
had no reader — a slower flagship only surfaced if a human diffed the
numbers. This tool joins two streams on the protocol identity

    (metric, devices, platform, cascade, tail_mode, cache)

so a cascade-off or host-tail or cold-cache line can never be compared
against its other-protocol sibling, takes the MEDIAN per joined key
(several lines per key = several runs; the median absorbs one bad
sample), and applies per-field tolerances with a direction each:

  * wall-clock fields (`value`, `compile_s`, `warm_start_s`) carry a
    LOOSE tolerance — these CI hosts live-migrate and resize
    mid-session (observed nproc 8 -> 1), so only order-of-magnitude
    movement is signal;
  * deterministic fields (`placed`, stragglers, `tail_passes`, and the
    BENCH_COST stamps `flops`/`bytes_accessed`/`hbm_peak_bytes`) are
    EXACT or near-exact — the program is deterministic per platform,
    so any movement is a real change, however cheap the host.

Degraded / recovered / stamped-capture lines are excluded: they are
evidence, not protocol.

Regressions carry the ``BENCH REGRESSION`` marker and fail the run.

Usage:
  python tools/benchdiff.py BASELINE CANDIDATE [--tol field=rel ...]
  python tools/benchdiff.py --self-test          # seeded noise vs regression
  JAX_PLATFORMS=cpu python tools/benchdiff.py --proxy-run OUT.jsonl
  JAX_PLATFORMS=cpu python tools/benchdiff.py --stamp-proxy
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from dataclasses import dataclass
from statistics import median
from typing import Dict, List, Optional, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

MARKER = "BENCH REGRESSION"
BASELINE_PATH = os.path.join("perf", "BENCH_BASELINE.json")

KEY_FIELDS = ("metric", "devices", "platform", "cascade", "tail_mode",
              "cache")

# the CI proxy shape: small enough to compile + run in a CI stage,
# large enough that sweep chunking, the adaptive tail, and the cascade
# all engage. One definition — the stamper and the gate both call it.
PROXY_SHAPE = dict(num_pods=2_000, num_nodes=200, chunk=500,
                   metric="proxy_score_bind_2k_pods_200_nodes")


@dataclass(frozen=True)
class Field:
    """One compared field: which direction is good, and the relative
    tolerance inside which movement is noise."""

    direction: str  # "lower" | "higher"
    tolerance: float


# wall-clock loose, deterministic counts/cost stamps (near-)exact
DEFAULT_FIELDS: Dict[str, Field] = {
    "value": Field("lower", 3.0),
    "compile_s": Field("lower", 3.0),
    "warm_start_s": Field("lower", 3.0),
    "placed": Field("higher", 0.0),
    "stragglers_after_sweep": Field("lower", 0.0),
    "stragglers_final": Field("lower", 0.0),
    "tail_passes": Field("lower", 0.0),
    "flops": Field("lower", 0.01),
    "bytes_accessed": Field("lower", 0.01),
    "hbm_peak_bytes": Field("lower", 0.01),
}


def parse_stream(path: str) -> List[dict]:
    """Bench lines from either format: a JSONL file (one dict per
    line) or a driver BENCH_*.json artifact (object whose "tail"
    string embeds the emitted lines). Non-protocol lines (degraded,
    recovered, stamped re-emissions, non-dicts) are dropped."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    lines: List[dict] = []
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        raw = str(doc["tail"]).splitlines()
    elif isinstance(doc, list):
        lines = [l for l in doc if isinstance(l, dict)]
        raw = []
    elif isinstance(doc, dict):
        lines = [doc]
        raw = []
    else:
        raw = text.splitlines()
    for line in raw:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            lines.append(obj)
    return [l for l in lines
            if "metric" in l and "value" in l
            and not l.get("degraded") and not l.get("recovered")
            and not l.get("stamped_capture")]


def join_key(line: dict) -> Tuple:
    return tuple(line.get(k) for k in KEY_FIELDS)


def _group(lines: List[dict]) -> Dict[Tuple, List[dict]]:
    groups: Dict[Tuple, List[dict]] = {}
    for line in lines:
        groups.setdefault(join_key(line), []).append(line)
    return groups


def _median_fields(lines: List[dict], fields: Dict[str, Field]
                   ) -> Dict[str, float]:
    out = {}
    for name in fields:
        vals = [float(l[name]) for l in lines
                if isinstance(l.get(name), (int, float))
                and not isinstance(l.get(name), bool)]
        if vals:
            out[name] = median(vals)
    return out


def diff(baseline: List[dict], candidate: List[dict],
         fields: Optional[Dict[str, Field]] = None) -> List[dict]:
    """Per (key, field) verdicts over every joined protocol identity:
    {key, field, old, new, rel, verdict} with verdict improve /
    regress / neutral, plus one unmatched record per key present on
    only one side (informational, never failing — protocols come and
    go by design)."""
    fields = DEFAULT_FIELDS if fields is None else fields
    old_g, new_g = _group(baseline), _group(candidate)
    verdicts: List[dict] = []
    for key in sorted(set(old_g) | set(new_g), key=repr):
        label = "/".join(f"{k}={v}" for k, v in zip(KEY_FIELDS, key)
                         if v is not None)
        if key not in new_g or key not in old_g:
            verdicts.append({
                "key": label, "field": None, "old": None, "new": None,
                "rel": None,
                "verdict": "baseline-only" if key in old_g
                else "candidate-only"})
            continue
        old_m = _median_fields(old_g[key], fields)
        new_m = _median_fields(new_g[key], fields)
        for name in fields:
            if name not in old_m or name not in new_m:
                continue
            ov, nv = old_m[name], new_m[name]
            rel = (nv - ov) / max(abs(ov), 1e-12)
            spec = fields[name]
            good_delta = -rel if spec.direction == "lower" else rel
            if good_delta < -spec.tolerance:
                verdict = "regress"
            elif good_delta > spec.tolerance:
                verdict = "improve"
            else:
                verdict = "neutral"
            verdicts.append({"key": label, "field": name, "old": ov,
                             "new": nv, "rel": rel, "verdict": verdict})
    return verdicts


def report(verdicts: List[dict]) -> int:
    """Print the verdict table; return 1 iff anything regressed."""
    counts = {"improve": 0, "regress": 0, "neutral": 0}
    for v in verdicts:
        if v["field"] is None:
            print(f"benchdiff: {v['verdict']}: {v['key']}")
            continue
        counts[v["verdict"]] += 1
        if v["verdict"] == "neutral":
            continue
        tag = MARKER if v["verdict"] == "regress" else "improve"
        print(f"{tag}: {v['key']} {v['field']} "
              f"{v['old']:.4g} -> {v['new']:.4g} ({v['rel']:+.1%})")
    print(f"benchdiff: {counts['improve']} improved, "
          f"{counts['regress']} regressed, "
          f"{counts['neutral']} neutral")
    return 1 if counts["regress"] else 0


def _tol_overrides(pairs: List[str]) -> Dict[str, Field]:
    fields = dict(DEFAULT_FIELDS)
    for pair in pairs:
        name, _, tol = pair.partition("=")
        if name not in fields:
            raise SystemExit(f"benchdiff: unknown field {name!r} "
                             f"(known: {', '.join(sorted(fields))})")
        fields[name] = Field(fields[name].direction, float(tol))
    return fields


def proxy_lines() -> List[dict]:
    """Run the CI proxy shape (one slim flagship line, BENCH_COST
    stamps on) and return its emitted line."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["BENCH_COST"] = "1"
    # pin the protocol: ambient BENCH_* knobs would change the join key
    # (cache stamp, cascade, tail mode, ...) or the timed program, and
    # the baseline was stamped with none of them set
    for knob in ("BENCH_COMPILE_CACHE", "BENCH_CASCADE",
                 "BENCH_TAIL_MODE", "BENCH_DEVICES", "BENCH_MESH_PODS",
                 "BENCH_PACK_SNAPSHOT", "BENCH_TRACE", "BENCH_APPROX",
                 "BENCH_K", "BENCH_TAIL_K", "BENCH_ROUNDS",
                 "BENCH_TAIL_ROUNDS", "BENCH_TAIL_CHUNK",
                 "BENCH_MAX_TAIL_PASSES"):
        os.environ.pop(knob, None)
    import bench

    bench.ensure_platform()
    line = bench.run_northstar(full_gate=False, **PROXY_SHAPE)
    line.pop("arrays", None)
    return [line]


def _strip_host(line: dict) -> dict:
    """Host-fingerprint fields stay out of the checked-in baseline —
    the gate compares medians by field name, and a baseline pinned to
    one CI host's nproc would be misleading provenance."""
    return {k: v for k, v in line.items()
            if k not in ("cores", "host")}


def self_test() -> int:
    """Prove the comparator's discrimination on seeded synthetic
    streams: +-10% run-to-run noise must land neutral at a 30%
    tolerance, a planted 2x slowdown and a planted straggler jump must
    regress, and a planted 2x speedup must improve."""
    import random

    rng = random.Random(20)

    def lines(scale: float, stragglers: int, n: int = 9) -> List[dict]:
        return [{
            "metric": "synthetic_flagship", "devices": 1,
            "platform": "cpu", "cascade": True, "tail_mode": "device",
            "cache": "hit",
            "value": scale * rng.uniform(0.9, 1.1),
            "placed": 2000,
            "stragglers_after_sweep": stragglers,
            "tail_passes": 2,
        } for _ in range(n)]

    fields = _tol_overrides(["value=0.3"])
    base = lines(1.0, 40)

    noisy = diff(base, lines(1.0, 40), fields)
    planted = diff(base, lines(2.0, 40), fields)
    jumped = diff(base, lines(1.0, 55), fields)
    faster = diff(base, lines(0.5, 40), fields)

    def field_verdict(verdicts, name):
        return next(v["verdict"] for v in verdicts
                    if v["field"] == name)

    checks = [
        ("10% noise is neutral", field_verdict(noisy, "value"),
         "neutral"),
        ("2x slowdown regresses", field_verdict(planted, "value"),
         "regress"),
        ("straggler jump regresses",
         field_verdict(jumped, "stragglers_after_sweep"), "regress"),
        ("2x speedup improves", field_verdict(faster, "value"),
         "improve"),
        ("counts stay neutral under noise",
         field_verdict(noisy, "placed"), "neutral"),
    ]
    failed = 0
    for label, got, want in checks:
        ok = got == want
        failed += not ok
        print(f"benchdiff self-test: {label}: {got} "
              f"({'ok' if ok else f'want {want}'})")
    return 1 if failed else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("baseline", nargs="?",
                        help="baseline stream (JSONL or BENCH_*.json)")
    parser.add_argument("candidate", nargs="?",
                        help="candidate stream to compare")
    parser.add_argument("--tol", action="append", default=[],
                        metavar="FIELD=REL",
                        help="override a field's relative tolerance")
    parser.add_argument("--self-test", action="store_true",
                        help="seeded noise-vs-regression discrimination")
    parser.add_argument("--proxy-run", metavar="OUT",
                        help="run the CI proxy shape, write its line "
                             "as JSONL to OUT")
    parser.add_argument("--stamp-proxy", action="store_true",
                        help=f"run the proxy shape and rewrite "
                             f"{BASELINE_PATH}")
    args = parser.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.proxy_run or args.stamp_proxy:
        lines = [_strip_host(l) for l in proxy_lines()]
        out = args.proxy_run if args.proxy_run else \
            os.path.join(REPO_ROOT, BASELINE_PATH)
        os.makedirs(os.path.dirname(os.path.abspath(out)), exist_ok=True)
        with open(out, "w", encoding="utf-8") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")
        print(f"benchdiff: wrote {len(lines)} proxy line(s) -> {out}")
        return 0
    if not args.baseline or not args.candidate:
        parser.error("need BASELINE and CANDIDATE (or --self-test / "
                     "--proxy-run / --stamp-proxy)")
    verdicts = diff(parse_stream(args.baseline),
                    parse_stream(args.candidate),
                    _tol_overrides(args.tol))
    return report(verdicts)


if __name__ == "__main__":
    sys.exit(main())
