"""Opportunistic TPU bench capture (round-5 protocol).

Rounds 3 and 4 both lost their headline TPU evidence because the ONLY
capture window was the driver's round-end `python bench.py`, and the axon
tunnel happened to be wedged at that moment both times (BENCH_r03/r04.json
are honest CPU fallbacks).  This tool decouples capture time from round-end
time: its own watcher loop (`python tools/tpu_capture.py`, the main()
below; `--once` for a single probe+capture attempt) probes the tunnel
every few minutes for the whole round and, on the first healthy probe,
runs the FULL bench suite (BASELINE configs 1-5, the full-gate flagship, the canonical
north-star, plus a BENCH_APPROX=1 approx-top-k comparison line) and freezes
every emitted JSON line into a timestamped artifact:

    /root/repo/bench_tpu_capture.json

`bench.py` surfaces that artifact in its output tail whenever its own live
run degrades to the CPU fallback, each stamped line clearly labeled with
`"stamped_capture": true` and the capture timestamp — so a round-end outage
no longer erases evidence captured mid-round while the tunnel was healthy.

Probe/run hygiene (the round-3/4 lessons, see bench.py:_probe_once):
- probes run in a subprocess with DEVNULL stdio and a hard timeout — a
  wedged tunnel hangs trivial compiles at 0% CPU and the platform plugin
  can leave a tunnel grandchild holding captured pipes open forever;
- the bench run itself writes stdout/stderr to FILES, never pipes, and is
  killed (process group) past a hard deadline.
"""

import datetime
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
ARTIFACT = os.path.join(REPO, "bench_tpu_capture.json")
LOG = os.path.join(REPO, "tools", "tpu_capture.log")
PROBE_TIMEOUT = float(os.environ.get("CAPTURE_PROBE_TIMEOUT", "150"))
BENCH_TIMEOUT = float(os.environ.get("CAPTURE_BENCH_TIMEOUT", "3300"))
APPROX_TIMEOUT = float(os.environ.get("CAPTURE_APPROX_TIMEOUT", "1500"))
FRESH_SECONDS = float(os.environ.get("CAPTURE_FRESH_SECONDS", "7200"))


def log(msg: str) -> None:
    stamp = datetime.datetime.now(datetime.timezone.utc).isoformat()
    with open(LOG, "a") as f:
        f.write(f"[{stamp}] {msg}\n")


def probe_once(timeout: float = PROBE_TIMEOUT) -> bool:
    """One hard-timeout subprocess probe of the configured platform.

    Delegates to bench._probe_once — the probe child program is subtle
    (it must re-pin JAX_PLATFORMS inside the child or site config
    silently overrides it) and must not drift between the watcher and
    the bench's own guard."""
    import bench
    return bench._probe_once(timeout)


def _run_to_files(cmd, env, timeout, tag):
    """Run cmd with stdout/stderr redirected to files (pipes wedge when a
    tunnel grandchild inherits them); kill the whole process group on
    deadline.  Returns (returncode_or_None, stdout_text)."""
    out_path = os.path.join(REPO, "tools", f"capture_{tag}.out")
    err_path = os.path.join(REPO, "tools", f"capture_{tag}.err")
    with open(out_path, "wb") as out, open(err_path, "wb") as err:
        proc = subprocess.Popen(cmd, cwd=REPO, env=env, stdout=out,
                                stderr=err, start_new_session=True)
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait()
            rc = None
    with open(out_path) as f:
        return rc, f.read()


def _json_lines(text: str):
    lines = []
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            obj = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            lines.append(obj)
    return lines


def capture() -> bool:
    """Run the full bench suite + the BENCH_APPROX=1 comparison; write the
    artifact.  Returns True when a TPU-platform canonical line landed."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "axon")
    # pin the main run to the bench DEFAULT selection mode: an
    # inherited BENCH_APPROX would silently collapse the exact-vs-
    # approx comparison into two identical runs
    env.pop("BENCH_APPROX", None)
    # the watcher just probed; don't spend 3x180s re-probing in-bench
    env["BENCH_PROBE_ATTEMPTS"] = "2"
    env["BENCH_PROBE_TIMEOUT"] = "180"
    env["BENCH_PROBE_RETRY_DELAY"] = "45"

    log(f"capture: running full bench suite (timeout {BENCH_TIMEOUT:.0f}s)")
    rc, out = _run_to_files([sys.executable, "bench.py"], env,
                            BENCH_TIMEOUT, "bench")
    # keep only LIVE non-cpu lines: if the tunnel wedges between the
    # watcher probe and the bench's own probes, bench degrades to CPU and
    # may re-surface a PREVIOUS stamped artifact — re-ingesting those (or
    # the live cpu lines) would launder stale evidence under a fresh
    # captured_at timestamp
    lines = [l for l in _json_lines(out)
             if l.get("platform") != "cpu"
             and not l.get("stamped_capture")]
    log(f"capture: bench rc={rc} live non-cpu lines={len(lines)}")
    if rc != 0 or not lines:
        log("capture: no live TPU lines; not stamping")
        return False
    platforms = {l.get("platform") for l in lines}

    # the default canonical is EXACT top-k since round 5; the
    # comparison line runs the approx_max_k mode (bench stamps
    # approx_topk into every line either way)
    env_approx = dict(env)
    env_approx["BENCH_APPROX"] = "1"
    env_approx["BENCH_EXTRAS"] = "0"
    log("capture: running BENCH_APPROX=1 canonical comparison")
    rc2, out2 = _run_to_files([sys.executable, "bench.py"], env_approx,
                              APPROX_TIMEOUT, "approx1")
    approx_lines = [l for l in _json_lines(out2)
                    if l.get("platform") != "cpu"
                    and not l.get("stamped_capture")]
    log(f"capture: approx1 rc={rc2} live non-cpu lines={len(approx_lines)}")

    artifact = {
        "captured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(),
        "platforms": sorted(p for p in platforms if p),
        "lines": lines + approx_lines,
    }
    tmp = ARTIFACT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    os.replace(tmp, ARTIFACT)
    log(f"capture: wrote {ARTIFACT} with {len(artifact['lines'])} lines")
    return True


def artifact_fresh() -> bool:
    try:
        with open(ARTIFACT) as f:
            art = json.load(f)
        captured = datetime.datetime.fromisoformat(art["captured_at"])
        age = (datetime.datetime.now(datetime.timezone.utc)
               - captured).total_seconds()
        return age < FRESH_SECONDS and bool(art.get("lines"))
    except (OSError, ValueError, KeyError):
        return False


def main() -> int:
    once = "--once" in sys.argv
    interval = float(os.environ.get("CAPTURE_PROBE_INTERVAL", "480"))
    while True:
        if artifact_fresh():
            log("watcher: artifact fresh; sleeping long")
            if once:
                return 0
            time.sleep(FRESH_SECONDS / 2)
            continue
        healthy = probe_once()
        log(f"watcher: probe healthy={healthy}")
        if healthy:
            if capture():
                if once:
                    return 0
                # refresh later so the stamped number stays recent
                time.sleep(FRESH_SECONDS / 2)
                continue
        if once:
            return 1
        time.sleep(interval)


if __name__ == "__main__":
    sys.exit(main())
