"""Sharded full-gate flagship smoke (CI stage; dryrun_multichip(2) scale).

Runs the bench's multichip full-gate flagship (bench.run_northstar with
BENCH_DEVICES) on a 2-device virtual CPU mesh and on one device from
the SAME seeds, asserting correctness — never wall-clock:

- placements are BIT-IDENTICAL to the single-device oracle (exact
  top-k path), with an indivisible node count so the run goes through
  `parallel.pad_nodes_to_mesh` on the hot path;
- the overcommit invariant holds on the real rows and no pad row was
  ever charged or assigned (core.overcommit_ok);
- the cascade's stage-1 mask is shard-local: the shard_map kernel
  (parallel.shardops.stage1_mask_sharded) matches the global mask, pad
  columns are dead, and the compiled HLO of the jitted stage-1 over
  sharded inputs contains NO cross-device collectives — while the full
  schedule step's HLO DOES (the ICI top-k merge). Structural pins, so
  a sharding regression fails here even when results happen to agree.

Kept out of tier-1 (the slow-marked mesh conformance test covers the
same ground at 4 devices); this stage gates every push via tools/ci.sh.
"""

import os
import sys

N_DEV = int(os.environ.get("SMOKE_DEVICES", "2"))

# the virtual mesh must exist before the first backend use
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEV}").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# dryrun_multichip(2)-scale bench shapes, set before bench import (the
# module constants are read at import): 35 nodes is NOT divisible by 2,
# so the sharded run exercises the padding helper for real
os.environ.setdefault("BENCH_NODES", "35")
os.environ.setdefault("BENCH_PODS", "512")
os.environ.setdefault("BENCH_FULL_CHUNK", "256")
os.environ.setdefault("BENCH_MAX_TAIL_PASSES", "4")
os.environ["BENCH_EXTRAS"] = "0"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import bench  # noqa: E402

COLLECTIVES = ("all-gather", "all-reduce", "all-to-all",
               "collective-permute", "reduce-scatter")


def hlo_collectives(compiled) -> set:
    """The cross-device collectives named in an optimized HLO module."""
    text = compiled.as_text()
    return {c for c in COLLECTIVES if c in text}


def main() -> None:
    from koordinator_tpu.parallel import (
        make_mesh, pad_batch_nodes, pad_nodes_to_mesh, padded_node_count,
        shard_snapshot, shardops)
    from koordinator_tpu.scheduler import core
    from koordinator_tpu.scheduler.cascade import stage1_mask, static_gates
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    mesh = make_mesh(jax.devices()[:N_DEV])

    os.environ["BENCH_DEVICES"] = str(N_DEV)
    multi = bench.run_northstar(full_gate=True)
    os.environ["BENCH_DEVICES"] = "1"
    single = bench.run_northstar(full_gate=True)

    assert multi["devices"] == N_DEV and single["devices"] == 1
    assert multi["mesh"] == {"nodes": N_DEV}, multi.get("mesh")
    a_m = multi["arrays"]["assignment"]
    a_s = single["arrays"]["assignment"]
    num_nodes = multi["arrays"]["num_nodes"]
    placed = int((a_m >= 0).sum())
    assert placed > 0, "sharded flagship placed nothing"
    assert np.array_equal(a_m, a_s), (
        f"sharded placements diverged from the single-device oracle "
        f"({int((a_m != a_s).sum())}/{a_m.size} rows differ)")
    assert a_m.max() < num_nodes, "a pod landed on a pad row"
    req = multi["arrays"]["requested"]
    n_pad = padded_node_count(num_nodes, mesh)
    assert n_pad > num_nodes, "smoke shape must exercise the pad helper"
    assert req.shape[0] == n_pad, req.shape
    # the one shared invariant implementation (pad rows excluded AND
    # asserted uncharged), not a local re-derivation
    assert core.overcommit_arrays_ok(req, multi["arrays"]["allocatable"],
                                     num_nodes), \
        "sharded flagship overcommitted a node (or charged a pad row)"
    print(f"mesh smoke: {N_DEV}-device full-gate flagship conformant "
          f"({placed}/{a_m.size} placed, pad rows dead) OK")

    # --- structural sharding pins on a fresh sharded workload ------------
    snap_h = synthetic.full_gate_cluster(num_nodes, num_quotas=4,
                                         num_gangs=2, gpus_per_node=4)
    snap_p = pad_nodes_to_mesh(snap_h, mesh)
    snap = shard_snapshot(snap_p, mesh)
    pods = pad_batch_nodes(
        synthetic.full_gate_pods(256, num_nodes, num_quotas=4, num_gangs=2,
                                 n_anti_groups=4, anti_members=4,
                                 n_aff_groups=2, aff_members=4),
        snap_p.num_nodes)
    cfg = LoadAwareConfig.make()

    static_ok, _ = static_gates(snap.nodes, pods, cfg)
    mask_global = np.asarray(stage1_mask(snap, pods, static_ok))
    mask_sharded = np.asarray(jax.jit(
        lambda sn, pd, so: shardops.stage1_mask_sharded(mesh, sn, pd, so)
    )(snap, pods, static_ok))
    assert np.array_equal(mask_global, mask_sharded), \
        "shard-local stage-1 mask diverged from the global mask"
    assert not mask_global[:, num_nodes:].any(), \
        "stage-1 admitted a zero-capacity pad column"

    # stage 1 must compile COLLECTIVE-FREE over sharded inputs (it is
    # elementwise over node columns), while the full schedule step must
    # contain the ICI candidate merge — both read off the optimized HLO
    from koordinator_tpu.parallel import struct_sharding
    s1 = jax.jit(stage1_mask).lower(snap, pods, static_ok).compile()
    got = hlo_collectives(s1)
    assert not got, f"stage-1 HLO grew collectives: {sorted(got)}"
    step = jax.jit(lambda s, p, c: core.schedule_batch(
        s, p, c, num_rounds=2, k_choices=4, enable_numa=True,
        enable_devices=True, cascade=True),
        out_shardings=struct_sharding("ScheduleResult", mesh)
    ).lower(snap, pods, cfg).compile()
    got = hlo_collectives(step)
    assert got, "sharded schedule step compiled with NO collectives " \
        "(the snapshot is no longer actually sharded?)"
    print(f"mesh smoke: stage-1 collective-free, schedule step merges "
          f"over ICI ({sorted(got)}) OK")


if __name__ == "__main__":
    main()
