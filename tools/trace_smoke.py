"""koordtrace smoke: the end-to-end observability contract in CI.

On a small full-gate workload with a journaled, traced
SchedulerService this stage asserts:

  1. SKELETON   — every committed cycle records the full host span
                  skeleton (admit -> dispatch -> device_wait ->
                  guard_scan -> journal_append -> publish) under one
                  shared cycle id, plus the checkpoint epilogue;
  2. LOADABLE   — the Chrome dump is valid trace-event JSON (complete
                  X events with us timestamps, instant events marked
                  ph='i'), i.e. Perfetto-loadable;
  3. FAULTS     — a corrupted-snapshot cycle carries the quarantine
                  event (guard word + defect list in its attrs) and a
                  runtime-fault cycle carries the retry + backoff +
                  ladder_transition records;
  4. NAMES      — every recorded span name resolves against the shared
                  phase table (obs/phases.py), so the trace, the
                  `scheduler_cycle_phase_seconds{phase=...}` series,
                  and the kernel named_scope labels stay one namespace;
  5. JOIN      — journal_append span attrs carry (epoch, chunk) that
                  match the commit journal's own records — the
                  trace <-> commit-log join key.

Runs on CPU in CI (tools/ci.sh); correctness-only, never wall-clock.
Usage: JAX_PLATFORMS=cpu python tools/trace_smoke.py
"""

import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.metrics import Registry
from koordinator_tpu.obs import phases
from koordinator_tpu.scheduler.frameworkext import SchedulerService
from koordinator_tpu.scheduler.journal import CommitJournal
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.testing import faults
from koordinator_tpu.utils import synthetic

N_NODES, N_PODS = 64, 128
SEED = int(os.environ.get("TRACE_SEED", "0"))


def check(cond, what):
    if not cond:
        raise AssertionError(what)


def make_service(workdir, **kw):
    svc = SchedulerService(
        metrics=SchedulerMetrics(Registry()), num_rounds=2, k_choices=4,
        journal=CommitJournal(os.path.join(workdir, "journal.bin")),
        trace=True, **kw)
    svc._sleep = lambda _s: None  # smoke runs don't wait out backoff
    return svc


def spans_by_cycle(tracer):
    by_cycle = {}
    for r in tracer.records():
        by_cycle.setdefault(r.cycle, []).append(r)
    return by_cycle


def check_clean_cycles(workdir):
    """Two committed cycles; each carries the full skeleton under its
    own cycle id, the journal join key matches, and the Chrome dump is
    loadable."""
    svc = make_service(workdir)
    snap = synthetic.full_gate_cluster(N_NODES, seed=SEED, num_quotas=8,
                                       num_gangs=8)
    svc.publish(snap)
    for i in range(2):
        pods = synthetic.full_gate_pods(N_PODS, N_NODES, seed=SEED + i,
                                        num_quotas=8, num_gangs=8)
        res = svc.schedule(pods)
        check(int((np.asarray(res.assignment) >= 0).sum()) > 0,
              f"cycle {i} placed nothing")

    by_cycle = spans_by_cycle(svc.tracer)
    for cyc in (0, 1):
        names = {r.name for r in by_cycle.get(cyc, [])}
        missing = set(phases.CYCLE_SKELETON) - names
        check(not missing,
              f"cycle {cyc} skeleton incomplete: missing {sorted(missing)} "
              f"(got {sorted(names)})")
        check(phases.SPAN_CYCLE in names, f"cycle {cyc} has no cycle span")
        check(phases.SPAN_CHECKPOINT in names,
              f"cycle {cyc} missing the checkpoint epilogue")
    # 4. every name resolves against the table
    for r in svc.tracer.records():
        check(r.name in phases.ALL_PHASES,
              f"span {r.name!r} not in the shared phase table")
    # 5. the trace <-> commit-log join: journal_append attrs vs journal
    appends = [r for r in svc.tracer.records()
               if r.name == phases.SPAN_JOURNAL_APPEND]
    check(len(appends) == 2, f"expected 2 journal_append spans, "
                             f"got {len(appends)}")
    for r in appends:
        epoch, chunk = r.attrs.get("epoch"), r.attrs.get("chunk")
        check(epoch is not None and chunk is not None,
              f"journal_append span missing the epoch/chunk join key: "
              f"{r.attrs}")
        check(chunk in svc.journal.records_for(epoch),
              f"journal has no record for traced (epoch={epoch}, "
              f"chunk={chunk})")
    # phase metric observed from the same spans
    p50 = svc.metrics.cycle_phase_seconds.percentile(
        0.5, phases.SPAN_DISPATCH)
    check(p50 is not None and p50 >= 0,
          "cycle_phase_seconds{phase=dispatch} never observed")

    # 2. dump + validate the Chrome JSON
    out = svc.dump_trace(workdir, prefix="smoke")
    chrome_path = [p for p in out if p.endswith(".trace.json")][0]
    with open(chrome_path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    check(len(evs) >= len(svc.tracer.records()),
          "chrome dump lost records")
    for e in evs:
        check(e["ph"] in ("X", "i"), f"unexpected phase type {e['ph']!r}")
        check(isinstance(e["ts"], (int, float)), "non-numeric ts")
        if e["ph"] == "X":
            check(e["dur"] >= 0, "negative duration")
        else:
            check(e.get("s") == "t", "instant event missing scope")
    check(doc["otherData"]["dropped"] == 0, "clean run dropped spans")
    prom_path = [p for p in out if p.endswith(".prom")][0]
    with open(prom_path) as f:
        prom = f.read()
    check("scheduler_cycle_phase_seconds" in prom,
          "prom dump missing the phase histogram")
    return {"cycles": 2, "spans": len(svc.tracer.records()),
            "chrome_events": len(evs)}


def check_quarantine_cycle(workdir):
    """3. a corrupted-snapshot cycle carries the quarantine event with
    the guard word + defect attribution."""
    inj = faults.FaultInjector(SEED)
    svc = make_service(workdir)
    snap = synthetic.full_gate_cluster(N_NODES, seed=SEED + 3,
                                       num_quotas=8, num_gangs=8)
    bad_snap, rows = inj.corrupt_snapshot(snap, "nan_metric_column",
                                          n_rows=2)
    svc.publish(bad_snap)
    pods = synthetic.full_gate_pods(N_PODS, N_NODES, seed=SEED + 4,
                                    num_quotas=8, num_gangs=8)
    svc.schedule(pods)
    quars = [r for r in svc.tracer.records()
             if r.name == phases.EVENT_QUARANTINE]
    check(len(quars) == 1, f"expected 1 quarantine event, got {len(quars)}")
    q = quars[0]
    check(q.t_start_ns == q.t_end_ns, "quarantine must be an instant event")
    check(q.attrs.get("word", 0) != 0, f"quarantine attrs carry no guard "
                                       f"word: {q.attrs}")
    check(q.attrs.get("defects"), "quarantine attrs carry no defect list")
    check(q.attrs.get("bad_nodes") == len(rows),
          f"quarantine bad_nodes {q.attrs.get('bad_nodes')} != "
          f"{len(rows)} corrupted rows")
    check(q.cycle == 0, "quarantine event not attributed to its cycle")
    return {"word": hex(q.attrs["word"]), "bad_nodes": len(rows)}


def check_degraded_cycle(workdir):
    """3. a runtime-fault cycle records retry + backoff + the
    ladder_transition the failure caused, all under the cycle's id."""
    inj = faults.FaultInjector(SEED)
    svc = make_service(workdir)
    snap = synthetic.full_gate_cluster(N_NODES, seed=SEED + 7,
                                       num_quotas=8, num_gangs=8)
    svc.publish(snap)
    pods = synthetic.full_gate_pods(N_PODS, N_NODES, seed=SEED + 8,
                                    num_quotas=8, num_gangs=8)
    # cycle 0: a transient XLA failure — retried in place with backoff
    svc.fault_injection = inj.xla_transient(fail_attempts={1, 2})
    svc.schedule(pods)
    # cycle 1: persistent OOM — walks the degradation ladder
    svc.fault_injection = inj.oom_above(N_PODS // 2)
    svc.schedule(pods)
    recs = svc.tracer.records()
    retries = [r for r in recs if r.name == phases.EVENT_RETRY]
    check(len(retries) >= 2, "faulted cycles recorded no retry events")
    check(all(r.attrs.get("failure_class") for r in retries),
          "retry events carry no failure_class")
    backoffs = [r for r in recs if r.name == phases.SPAN_BACKOFF
                and r.cycle == 0]
    check(backoffs, "the transient cycle recorded no backoff span")
    check(all(r.attrs.get("delay_s") is not None for r in backoffs),
          "backoff spans carry no delay")
    trans = [r for r in recs
             if r.name == phases.EVENT_LADDER_TRANSITION
             and r.cycle == 1]
    check(trans, "degradation recorded no ladder_transition event")
    check(any(r.attrs.get("to") for r in trans),
          f"ladder_transition events carry no target rung: "
          f"{[r.attrs for r in trans]}")
    # the final (successful) attempt's cycle span says which rung ran
    cycles = [r for r in recs if r.name == phases.SPAN_CYCLE
              and r.cycle == 1]
    check(len(cycles) >= 2, "the degraded schedule() should record one "
                            "cycle span per attempt")
    check(cycles[-1].attrs.get("ladder") not in (None, "normal"),
          f"the committed attempt's cycle span does not carry the "
          f"degraded rung: {cycles[-1].attrs}")
    # every fault-path name still resolves
    for r in recs:
        check(r.name in phases.ALL_PHASES,
              f"span {r.name!r} not in the shared phase table")
    return {"retries": len(retries),
            "transitions": [r.attrs.get("to") for r in trans],
            "committed_ladder": cycles[-1].attrs.get("ladder")}


def main():
    stages = (("clean-cycles", check_clean_cycles),
              ("quarantine", check_quarantine_cycle),
              ("degraded", check_degraded_cycle))
    failures = []
    for name, fn in stages:
        workdir = tempfile.mkdtemp(prefix=f"trace_smoke_{name}_")
        try:
            verdict = fn(workdir)
            print(f"TRACE OK   {name}: {verdict}", flush=True)
        except AssertionError as exc:
            failures.append((name, str(exc)))
            print(f"TRACE FAIL {name}: {exc}", flush=True)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
    print(f"TRACE SMOKE: {len(stages) - len(failures)}/{len(stages)} "
          f"stages green (seed {SEED})", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
