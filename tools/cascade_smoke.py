"""Full-gate cascade + device-tail CI smoke (tools/ci.sh stage).

Exercises the cascade path on every push at a CI-affordable shape
(default 2k pods x 200 nodes, CPU) and asserts CORRECTNESS, not
wall-clock:

1. conformance — cascade on vs off produce IDENTICAL placements chunk
   by chunk with carried topology counts (the `cascade=False` oracle at
   CI scale, with every packing contract engaged);
2. straggler accounting — the device-resident tail drains the pool
   under its retry budget, its single packed stats readback agrees with
   the assignment vector, nothing is left never-retried, and the placed
   fraction clears a floor;
3. cascade observability — stage 1 leaves every placed pod a surviving
   candidate (the mask soundness invariant, checked against the actual
   placements).

Shapes are env-overridable (SMOKE_PODS / SMOKE_NODES / SMOKE_CHUNK) for
local iteration; the defaults are the CI protocol.
"""

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE_PODS = int(os.environ.get("SMOKE_PODS", 2_000))
SMOKE_NODES = int(os.environ.get("SMOKE_NODES", 200))
SMOKE_CHUNK = int(os.environ.get("SMOKE_CHUNK", 500))


def main() -> int:
    from koordinator_tpu.scheduler import cascade, core
    from koordinator_tpu.scheduler.plugins.loadaware import LoadAwareConfig
    from koordinator_tpu.utils import synthetic

    t0 = time.perf_counter()
    pods = synthetic.full_gate_pods(SMOKE_PODS, SMOKE_NODES, seed=1,
                                    num_quotas=8, num_gangs=8)
    packed, prefixes, masks = synthetic.pack_gate_prefixes(pods,
                                                           SMOKE_CHUNK)
    snap0 = synthetic.full_gate_cluster(SMOKE_NODES, seed=0,
                                        num_quotas=8, num_gangs=8)
    cfg = LoadAwareConfig.make()
    kw = dict(num_rounds=2, k_choices=8, score_dims=(0, 1),
              tie_break=True, quota_depth=2, fit_dims=(0, 1, 2, 3),
              enable_numa=True, enable_devices=True,
              topo_prefix=prefixes["topo"],
              dom_classes=synthetic.dom_classes(packed),
              numa_prefix=prefixes["numa"], gpu_prefix=prefixes["gpu"])

    def sweep(cascade_on):
        snap = snap0
        counts = tuple(jnp.asarray(getattr(packed, f))
                       for f in core.COUNT_FIELDS)
        assign = []
        for s in range(0, SMOKE_PODS, SMOKE_CHUNK):
            batch = synthetic.slice_batch(packed, s, SMOKE_CHUNK).replace(
                **dict(zip(core.COUNT_FIELDS, counts)))
            res = core.schedule_batch(snap, batch, cfg,
                                      cascade=cascade_on, **kw)
            counts = core.charge_all_counts(counts, batch, res.assignment)
            snap = res.snapshot
            assign.append(res.assignment)
        return snap, counts, jnp.concatenate(assign)

    # 1. conformance: cascade on == cascade off, chunk by chunk
    snap_off, _, assign_off = sweep(cascade_on=False)
    snap_on, counts_on, assign_on = sweep(cascade_on=True)
    np.testing.assert_array_equal(np.asarray(assign_off),
                                  np.asarray(assign_on))
    for a, b in zip(jax.tree_util.tree_leaves(snap_off),
                    jax.tree_util.tree_leaves(snap_on)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # 3. cascade observability: every node-placed pod survived stage 1
    batch0 = synthetic.slice_batch(packed, 0, SMOKE_CHUNK)
    static_ok, _ = cascade.static_gates(snap0.nodes, batch0, cfg)
    mask = np.asarray(cascade.stage1_mask(snap0, batch0, static_ok,
                                          fit_dims=(0, 1, 2, 3),
                                          quota_depth=2))
    a0 = np.asarray(assign_on)[:SMOKE_CHUNK]
    survivors = np.asarray(cascade.candidate_counts(jnp.asarray(mask)))
    placed_rows = np.flatnonzero(a0 >= 0)
    assert placed_rows.size, "first chunk placed nothing"
    assert (survivors[placed_rows] > 0).all(), \
        "stage 1 pruned a pod the commit placed"

    # 2. device-resident tail: drain under budget, one stats readback
    tail_step = functools.partial(
        core.schedule_batch, num_rounds=4, k_choices=32,
        score_dims=(0, 1), tie_break=True, quota_depth=2,
        fit_dims=(0, 1, 2, 3), enable_numa=True, enable_devices=True,
        cascade=True, topo_prefix=kw["topo_prefix"],
        dom_classes=kw["dom_classes"])
    loop = jax.jit(functools.partial(
        core.tail_compaction_loop, tail_step,
        tail_chunk=min(SMOKE_CHUNK, 512), min_passes=2, max_passes=10,
        topo_prefix=kw["topo_prefix"],
        topo_mask=jnp.asarray(masks["topo"])))
    snap_fin, _, assign_fin, stats = loop(
        snap_on, counts_on, assign_on, packed, cfg)
    stats = [int(x) for x in np.asarray(stats)]
    after_sweep, final, never_retried, passes = stats
    a_fin = np.asarray(assign_fin)
    recount = int((np.asarray(packed.valid) & (a_fin < 0)).sum())
    assert final == recount, \
        f"stats readback {final} disagrees with the bind log {recount}"
    assert after_sweep == int((np.asarray(packed.valid)
                               & (np.asarray(assign_on) < 0)).sum())
    assert never_retried == 0, \
        f"{never_retried} stragglers never retried (passes={passes})"
    assert passes <= 10
    placed = int((a_fin >= 0).sum())
    assert placed >= int(0.95 * SMOKE_PODS), \
        f"only {placed}/{SMOKE_PODS} placed after the tail"

    print(json.dumps({
        "smoke": "cascade", "pods": SMOKE_PODS, "nodes": SMOKE_NODES,
        "chunk": SMOKE_CHUNK, "placed": placed,
        "stragglers_after_sweep": after_sweep, "stragglers_final": final,
        "tail_passes": passes, "never_retried": never_retried,
        "prefixes": prefixes,
        "elapsed_s": round(time.perf_counter() - t0, 1)}))
    print("cascade smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
