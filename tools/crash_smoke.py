"""Crash smoke: the kill-injected recovery matrix for the commit
journal + checkpoint + restart-replay path (ISSUE 14 tentpole).

For every named crash point in `koordinator_tpu.testing.faults.
CRASH_POINTS` (at chosen hit counts, so crashes land both before any
chunk committed and mid-batch), a CHILD process runs a journaled,
checkpointed, chunked scheduling cycle and is SIGKILLed at the crash
point — a real uncatchable kill, so the on-disk journal/checkpoint
state is exactly what a power cut would leave. The parent then
"restarts the service": a fresh SchedulerService over the same journal
and checkpoint files runs `recover()` with the resubmitted batch, and
the smoke asserts:

  1. KILLED      — the child really died by SIGKILL at the armed point
                   (a child that completes means the point never fired);
  2. CONVERGED   — the recovered run's final placements are
                   BIT-IDENTICAL to an uninterrupted no-crash oracle,
                   and the post-recovery store (requested columns)
                   matches the oracle's store;
  3. EXACT       — per (epoch, chunk): every chunk appears in the
                   journal exactly once after recovery (no duplicated
                   and no lost placements — replay re-derives, never
                   re-appends), and the torn-write case surfaces its
                   typed tail reason instead of crashing the load.

Runs on CPU in CI (tools/ci.sh); correctness-only, never wall-clock.
Usage: JAX_PLATFORMS=cpu python tools/crash_smoke.py [point[:hit] ...]
Child mode (internal): ... --child <point:hit> <workdir> <seed>
"""

import os
import subprocess
import sys
import tempfile
import shutil
import signal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np

from koordinator_tpu.metrics import Registry
from koordinator_tpu.scheduler.frameworkext import (
    DegradationLadder,
    SchedulerService,
)
from koordinator_tpu.scheduler.journal import (
    CommitJournal,
    JournalConflict,
    JournalCorruption,
    JournalTail,
)
from koordinator_tpu.scheduler.metrics_defs import SchedulerMetrics
from koordinator_tpu.snapshot.store import SnapshotStore
from koordinator_tpu.testing import faults
from koordinator_tpu.utils import synthetic

N_NODES, N_PODS = 32, 64
CHUNK_SPLITS = 2  # the batch runs as 4 journaled chunks

# (crash point, hit count): hits are chosen so the matrix covers
# "nothing committed yet" (pre-append hit 1), "mid-batch" (hits 2-3 =
# between chunks), the torn write, the post-append/pre-publish window,
# and a kill DURING the post-batch checkpoint (hit 2: hit 1 is the
# checkpoint the initial publish writes)
DEFAULT_CASES = (
    ("post_dispatch_pre_append", 1),
    ("post_dispatch_pre_append", 3),
    ("mid_append_torn", 2),
    ("post_append_pre_publish", 2),
    ("mid_checkpoint", 2),
)


def make_inputs(seed: int):
    snap = synthetic.synthetic_cluster(N_NODES, seed=seed, num_quotas=4,
                                       num_gangs=4)
    pods = synthetic.synthetic_pods(N_PODS, seed=seed + 7, num_quotas=4,
                                    num_gangs=4)
    return snap, pods


def make_service(workdir: str, crash_hook=None) -> SchedulerService:
    journal = CommitJournal(os.path.join(workdir, "journal.bin"),
                            crash_hook=crash_hook)
    store = SnapshotStore(checkpoint_path=os.path.join(workdir, "store.ck"),
                          checkpoint_every=1, crash_hook=crash_hook)
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, guards=False,
                           journal=journal, store=store)
    svc._sleep = lambda _s: None
    svc.ladder.level = DegradationLadder.L_CHUNKED
    svc.ladder.chunk_splits = CHUNK_SPLITS
    return svc


def child(point: str, hit: int, workdir: str, seed: int) -> int:
    """One journaled chunked batch, armed to SIGKILL at the crash
    point. Returning at all means the point never fired -> exit 3 so
    the parent can tell 'crashed as planned' from 'never crashed'."""
    snap, pods = make_inputs(seed)
    svc = make_service(workdir, crash_hook=faults.sigkill_at(point, hit))
    svc.publish(snap)
    svc.schedule(pods)
    return 3


def oracle_run(seed: int):
    """The uninterrupted no-crash oracle: same batch, same chunking, no
    journal — final placements + the post-commit requested columns."""
    snap, pods = make_inputs(seed)
    svc = SchedulerService(metrics=SchedulerMetrics(Registry()),
                           num_rounds=2, k_choices=4, guards=False)
    svc._sleep = lambda _s: None
    svc.ladder.level = DegradationLadder.L_CHUNKED
    svc.ladder.chunk_splits = CHUNK_SPLITS
    svc.publish(snap)
    res = svc.schedule(pods)
    return (np.asarray(res.assignment),
            np.asarray(svc.store.current().nodes.requested))


def check(cond, what):
    if not cond:
        raise AssertionError(what)


def run_case(point: str, hit: int, seed: int = 0) -> dict:
    """Spawn the child, let it die at the crash point, recover in this
    process, and assert convergence. Raises AssertionError on any
    violated invariant; returns a verdict dict otherwise."""
    workdir = tempfile.mkdtemp(prefix=f"crash_{point}_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             f"{point}:{hit}", workdir, str(seed)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=900)
        check(proc.returncode == -signal.SIGKILL,
              f"{point}:{hit}: child exited {proc.returncode}, expected "
              f"SIGKILL ({-signal.SIGKILL});\nstderr tail: "
              f"{proc.stderr[-2000:]}")

        snap, pods = make_inputs(seed)
        svc = make_service(workdir)
        committed_before = sorted(svc.journal.records_for(1))
        tail = svc.journal.tail_reason
        if point == "mid_append_torn":
            check(tail is not JournalTail.CLEAN,
                  f"{point}:{hit}: mid-append kill left a clean tail")
        try:
            report = svc.recover({1: pods})
        except (JournalConflict, JournalCorruption):
            # journal-level failures are exactly what this gate exists
            # to catch — never mask them behind the fresh-publish
            # fallback below
            raise
        except RuntimeError:
            # no checkpoint survived (killed during the very first
            # one): the control-plane edge re-publishes, then replay
            svc.publish(snap)
            report = svc.recover({1: pods})
        result = report["results"].get(1)
        if result is None:
            # every journaled epoch predated the surviving checkpoint:
            # the batch itself is simply scheduled as the next epoch
            result = svc.schedule(pods)
        assign = np.asarray(result.assignment)

        oracle_assign, oracle_req = oracle_run(seed)
        check(np.array_equal(assign, oracle_assign),
              f"{point}:{hit}: recovered placements diverged from the "
              f"no-crash oracle")
        np.testing.assert_allclose(
            np.asarray(svc.store.current().nodes.requested), oracle_req,
            err_msg=f"{point}:{hit}: post-recovery store drifted")
        records = svc.journal.records_for(1)
        check(sorted(records) == list(range(2 ** CHUNK_SPLITS)),
              f"{point}:{hit}: journal chunk set {sorted(records)} is "
              f"not exactly one record per chunk")
        return {"point": point, "hit": hit,
                "committed_before_crash": committed_before,
                "tail": tail.value,
                "records_replayed": report["records_replayed"],
                "restored_checkpoint": report["restored_checkpoint"]}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv) -> int:
    if argv[:1] == ["--child"]:
        point, _, hit = argv[1].partition(":")
        return child(point, int(hit or "1"), argv[2],
                     int(argv[3]) if len(argv) > 3 else 0)
    selected = [a for a in argv if not a.startswith("-")]
    if selected:
        cases = []
        for spec in selected:
            point, _, hit = spec.partition(":")
            cases.append((point, int(hit or "1")))
    else:
        cases = list(DEFAULT_CASES)
    failures = []
    for point, hit in cases:
        try:
            verdict = run_case(point, hit)
            print(f"CRASH OK   {point}:{hit}: {verdict}", flush=True)
        except AssertionError as exc:
            failures.append((point, hit, str(exc)))
            print(f"CRASH FAIL {point}:{hit}: {exc}", flush=True)
    print(f"CRASH SMOKE: {len(cases) - len(failures)}/{len(cases)} "
          f"crash points converge bit-identical", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
