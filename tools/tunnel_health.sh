#!/bin/sh
# Lightweight tunnel-health logger: one hard-timeout probe every ~7 min,
# appended to tools/tunnel_health.log. Complements tools/tpu_capture.py
# (whose watcher sleeps long once an artifact is fresh) so a mid-round
# heal is visible within minutes.
cd "$(dirname "$0")/.." || exit 1
while true; do
  if timeout 120 python -c "
import bench
import sys
sys.exit(0 if bench._probe_once(100) else 1)
" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) healthy" >> tools/tunnel_health.log
  else
    echo "$(date -u +%FT%TZ) wedged" >> tools/tunnel_health.log
  fi
  sleep 420
done
