"""koordrace Tier B: the deterministic interleaving gate.

Where the `race-guard` koordlint pass (Tier A) proves guarded-by
contract conformance STATICALLY — every access to a `@guarded_by`
field happens under a `with` on its declared lock — this gate runs the
real concurrent classes CONCRETELY under a seeded, deterministic
thread scheduler and asserts their cross-thread invariants over many
explored interleavings:

  * a token-passing scheduler (`DetScheduler`) owns every worker
    thread: exactly one runs at a time, and control moves only at
    SWITCH POINTS — Python line events inside `koordinator_tpu/`
    files (via per-thread `sys.settrace`) and lock-contention yields.
    A seeded `random.Random` picks the next thread, so one seed IS
    one schedule: the recorded trace of (kind, from, to, location)
    switches is bit-identical across runs of the same seed, which the
    battery itself re-checks (nondeterminism here would make every
    red run unreproducible).
  * `rr` mode switches at EVERY line, round-robin — the densest
    interleaving, guaranteed to drive any two threads through each
    other's check-then-act windows; `random` mode explores sparser
    preemption; a bounded-preemption run (small-CHESS: most races
    need very few preemptions, so a tiny budget covers a huge class
    of schedules cheaply) caps forced switches per run.
  * locks under test are swapped for `InstrumentedLock`s — pure
    owner/count state machines that YIELD to the scheduler instead of
    blocking, so contention becomes exploration instead of deadlock,
    and an actual lock-order deadlock is detected (no thread makes a
    line of progress) rather than hung on.

The scenarios target the seams the guarded-by contracts protect:
ingest-vs-update-vs-read on `SnapshotStore` (the delta version guard
must apply each version EXACTLY once across racing duplicate
producers), append-vs-prune-vs-reload on `CommitJournal` under its
external commit lock, an 8-thread `Tracer` span storm over a tiny
ring (retained + dropped == appended, per-thread order preserved),
and metrics observe-vs-export exactness.

`--self-test-mutation` proves the two tiers are live AND complementary
by construction (tools/seedmut.py): dropping the store lock around
ingest's version guard must fail THIS gate while remaining invisible
to the static tier (the mutated `with threading.Lock():` is an
unresolvable context manager, which the never-guess analyzer treats
as "unknown lock held" — tools/lint/analyzers/race.py); deleting the
lock around `MetricCache.set_kv` must fail the static tier (GB001)
while THIS battery — which never touches `MetricCache` — passes.
Each defect is caught by exactly its own tier and demonstrably
missed by the other; a defect both saw would prove redundancy, not
coverage.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import tempfile
import threading
from typing import Callable, Dict, List, Optional, Tuple

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# appended (not prepended) so a mutated tree earlier on PYTHONPATH wins
if REPO_ROOT not in sys.path:
    sys.path.append(REPO_ROOT)

from tools.seedmut import (  # noqa: E402
    Mutation,
    check_gate_catches,
    check_gate_passes,
)

_PKG_DIR: Optional[str] = None


def _pkg_dir() -> str:
    """Directory of the IMPORTED koordinator_tpu package — under
    --self-test-mutation the children resolve this to the mutated temp
    tree, so switch points track whichever tree is actually running."""
    global _PKG_DIR
    if _PKG_DIR is None:
        import koordinator_tpu

        _PKG_DIR = os.path.dirname(
            os.path.abspath(koordinator_tpu.__file__)) + os.sep
    return _PKG_DIR


class DeadlockError(RuntimeError):
    """No thread can make a line of progress: every live worker is
    spinning on a lock (or the owner exited while holding one)."""


class _Worker:
    __slots__ = ("name", "fn", "index", "go", "finished", "thread")

    def __init__(self, name: str, fn: Callable[[], None], index: int):
        self.name = name
        self.fn = fn
        self.index = index
        self.go = threading.Event()
        self.finished = False
        self.thread: Optional[threading.Thread] = None


class DetScheduler:
    """Deterministic cooperative thread scheduler.

    Exactly one spawned worker holds the token at a time; the rest wait
    on per-worker Events. Token handoffs happen only at switch points,
    chosen by `mode`:

      rr        switch to the next live worker at EVERY package line —
                maximal interleaving density, zero randomness;
      random    at each package line, switch with `switch_prob` to a
                seeded-random live worker; `preempt_budget` (when set)
                bounds how many such forced preemptions one run may
                spend — contention yields and exits never consume it.

    The schedule trace (`self.trace`) records every actual handoff as
    (kind, from, to, file:line); same seed -> same trace, which
    run_all re-asserts per scenario.
    """

    _STALL_LIMIT = 20000  # contention yields with no line progress

    def __init__(self, seed: int = 0, mode: str = "random",
                 switch_prob: float = 0.25,
                 preempt_budget: Optional[int] = None):
        if mode not in ("rr", "random"):
            raise ValueError(f"unknown scheduler mode {mode!r}")
        self.mode = mode
        self.seed = seed
        self.rng = random.Random(seed)
        self.switch_prob = switch_prob
        self.preempt_budget = preempt_budget
        self.workers: List[_Worker] = []
        self.trace: List[Tuple[str, str, str, str]] = []
        self.switch_points = 0  # line events seen (potential switches)
        self.acquires = 0       # successful InstrumentedLock acquires
        self._by_ident: Dict[int, _Worker] = {}
        self._stall = 0
        self._all_done = threading.Event()
        self._errors: List[Tuple[str, BaseException]] = []
        self._pkg = _pkg_dir()

    # --- registration / run ---------------------------------------------

    def spawn(self, fn: Callable[[], None], name: str) -> None:
        self.workers.append(_Worker(name, fn, len(self.workers)))

    def run(self, timeout: float = 120.0) -> None:
        """Start every worker, hand the token to the first, and wait for
        all to finish. Re-raises the first worker exception (including
        DeadlockError from the stall detector)."""
        if not self.workers:
            return
        for w in self.workers:
            w.thread = threading.Thread(
                target=self._wrapper, args=(w,),
                name=f"racecheck-{w.name}", daemon=True)
            w.thread.start()
        self.workers[0].go.set()
        if not self._all_done.wait(timeout):
            alive = [w.name for w in self.workers if not w.finished]
            raise DeadlockError(
                f"scheduler timed out after {timeout}s; "
                f"stuck workers: {alive}")
        for w in self.workers:
            assert w.thread is not None
            w.thread.join(timeout=10)
        if self._errors:
            name, exc = self._errors[0]
            raise RuntimeError(
                f"worker {name!r} raised "
                f"{type(exc).__name__}: {exc}") from exc

    def _wrapper(self, w: _Worker) -> None:
        self._by_ident[threading.get_ident()] = w
        w.go.wait()
        sys.settrace(self._trace_call)
        try:
            w.fn()
        except BaseException as exc:  # noqa: BLE001 — reported by run()
            self._errors.append((w.name, exc))
        finally:
            sys.settrace(None)
            w.finished = True
            self._handoff_exit(w)

    # --- switch points ---------------------------------------------------

    def _trace_call(self, frame, event, arg):
        # local tracing only for package frames: stdlib / numpy / this
        # module never become switch points (returning None disables
        # line events for the whole frame)
        if event == "call" and frame.f_code.co_filename.startswith(
                self._pkg):
            return self._trace_line
        return None

    def _trace_line(self, frame, event, arg):
        if event == "line":
            self.switch_points += 1
            self._stall = 0  # a real line executed: progress
            me = self._by_ident.get(threading.get_ident())
            if me is not None and not me.finished:
                loc = (frame.f_code.co_filename[len(self._pkg):]
                       + f":{frame.f_lineno}")
                self._preempt(me, loc)
        return self._trace_line

    def _live_after(self, w: _Worker) -> List[_Worker]:
        """Live workers in cyclic registration order starting after `w`
        — the deterministic candidate order for both modes."""
        n = len(self.workers)
        return [self.workers[(w.index + k) % n] for k in range(1, n)
                if not self.workers[(w.index + k) % n].finished]

    def _preempt(self, me: _Worker, loc: str) -> None:
        others = self._live_after(me)
        if not others:
            return
        if self.mode == "rr":
            self._switch(me, others[0], "rr", loc)
            return
        if self.preempt_budget is not None and self.preempt_budget <= 0:
            return
        if self.rng.random() < self.switch_prob:
            target = others[self.rng.randrange(len(others))]
            if self.preempt_budget is not None:
                self.preempt_budget -= 1
            self._switch(me, target, "preempt", loc)

    def block_switch(self, what: str) -> None:
        """Called by a contended InstrumentedLock: yield the token so
        the owner can run. Counts toward the stall detector — if every
        live thread is doing this and none executes a real line, the
        scenario is deadlocked."""
        self._stall += 1
        if self._stall > self._STALL_LIMIT:
            raise DeadlockError(
                f"no thread progressed across {self._stall} contention "
                f"yields (last waiting on {what})")
        me = self._by_ident.get(threading.get_ident())
        if me is None:
            return  # contention outside a scheduled run: nothing to do
        others = self._live_after(me)
        if not others:
            raise DeadlockError(
                f"{me.name} waits on {what} with no other live thread "
                f"to release it")
        if self.mode == "rr":
            target = others[0]
        else:
            target = others[self.rng.randrange(len(others))]
        self._switch(me, target, "block", what)

    def note_acquire(self) -> None:
        self._stall = 0
        self.acquires += 1

    # --- token handoff ---------------------------------------------------

    def _switch(self, me: _Worker, target: _Worker, kind: str,
                loc: str) -> None:
        self.trace.append((kind, me.name, target.name, loc))
        me.go.clear()
        target.go.set()
        me.go.wait()

    def _handoff_exit(self, w: _Worker) -> None:
        nxt = self._live_after(w)
        if nxt:
            self.trace.append(("exit", w.name, nxt[0].name, ""))
            nxt[0].go.set()
        else:
            self._all_done.set()


class InstrumentedLock:
    """A scheduler-cooperative lock: a pure (owner, count) state
    machine with NO embedded threading primitive. Only the token
    holder ever touches it, so plain attribute updates are already
    atomic under the scheduler; contention yields via
    `DetScheduler.block_switch` instead of blocking, which is what
    turns lock ordering bugs into detected deadlocks and dropped-lock
    bugs into explorable interleavings. Reentrant when asked (stands
    in for RLock); a non-reentrant relock fails loudly as the real
    deadlock it would be."""

    def __init__(self, sched: DetScheduler, name: str,
                 reentrant: bool = False):
        self._sched = sched
        self.name = name
        self._reentrant = reentrant
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True) -> bool:
        me = threading.get_ident()
        while True:
            if self._owner is None:
                self._owner = me
                self._count = 1
                self._sched.note_acquire()
                return True
            if self._owner == me:
                if not self._reentrant:
                    raise DeadlockError(
                        f"non-reentrant relock of {self.name}")
                self._count += 1
                return True
            if not blocking:
                return False
            self._sched.block_switch(self.name)

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError(
                f"release of {self.name} by a non-owner thread")
        self._count -= 1
        if self._count == 0:
            self._owner = None

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


def _instrument(obj, attr: str, sched: DetScheduler, name: str,
                reentrant: bool = False) -> InstrumentedLock:
    """Swap a real lock attribute for an InstrumentedLock — the
    scenario-side seam that puts an object under the scheduler."""
    lk = InstrumentedLock(sched, name, reentrant=reentrant)
    setattr(obj, attr, lk)
    return lk


Report = Callable[[str], None]


def store_accounting_invariants(store, *, base_version: int,
                                base_watermark: int, base_rejections: int,
                                n_versions: int, n_producers: int,
                                n_updates: int, report: Report) -> None:
    """The SnapshotStore exactly-once ledger, shared between this
    deterministic battery (scenario_store) and the wall-clock thread
    soak (tools/soak_service.py --threads): `n_producers` replay the
    SAME `n_versions` version sequence, so every version must admit
    exactly once, every duplicate must reject with a typed reason, and
    the version counter must advance by exactly applies + functional
    updates — the algebra that breaks first when the store lock stops
    covering the version guard."""
    want_wm = base_watermark + n_versions
    if store.applied_delta_version != want_wm:
        report(f"delta watermark {store.applied_delta_version}, want "
               f"{want_wm} — a version was lost or double-applied")
    want_rej = base_rejections + (n_producers - 1) * n_versions
    if store.delta_rejections != want_rej:
        report(f"{store.delta_rejections - base_rejections} rejections "
               f"for {n_producers} producers x {n_versions} versions, "
               f"want {want_rej - base_rejections} — duplicate replays "
               f"slipped past the version guard")
    want_ver = base_version + n_versions + n_updates
    if store.version != want_ver:
        report(f"store version {store.version}, want {want_ver} "
               f"({base_version} base + {n_versions} applies + "
               f"{n_updates} updates)")


# --- scenario: SnapshotStore ingest vs update vs read --------------------


class _FakeDelta:
    """Duck-typed versioned delta: `delta_version` only reads
    `source_version`, and the apply kernel is monkeypatched, so the
    scenario exercises the store's REAL version-guard path without
    building a full columnar snapshot."""

    def __init__(self, version: int):
        self.source_version = version


def scenario_store(sched: DetScheduler, report: Report) -> None:
    """Two producers replay the SAME delta version sequence (a
    restarted producer racing its own ghost) against one store, while
    an updater publishes functional updates and a reader drains
    rejection reasons. The guarded-by contract on `_lock` is what
    makes the version guard atomic; the invariants below are exactly
    what breaks when it is not:

      * every version applies EXACTLY once, in increasing order,
      * rejections account for every duplicate,
      * the version counter equals 1 + applies + updates.
    """
    import koordinator_tpu.snapshot.delta as delta_mod
    from koordinator_tpu.snapshot.store import SnapshotStore

    n_versions, n_updates = 6, 4
    store = SnapshotStore()
    # bypass publish(): the device upload is irrelevant to the lock
    # discipline under test, and keeps the scenario jit-free
    store._current = object()
    store._version = 1
    _instrument(store, "_lock", sched, "store._lock")

    applies: List[int] = []
    real_apply = delta_mod.apply_metric_delta

    def fake_apply(snap, delta):
        # runs INSIDE store._lock on the healthy tree; the append is
        # the observable "the guard admitted this version" event
        applies.append(int(delta.source_version))
        return snap

    def ingest_worker():
        for v in range(1, n_versions + 1):
            store.ingest(_FakeDelta(v))

    def update_worker():
        for _ in range(n_updates):
            store.update(lambda s: s)

    def reader_worker():
        for _ in range(n_updates):
            store.take_delta_rejection()
            _ = store.version

    delta_mod.apply_metric_delta = fake_apply
    try:
        sched.spawn(ingest_worker, "ingest-a")
        sched.spawn(ingest_worker, "ingest-b")
        sched.spawn(update_worker, "update")
        sched.spawn(reader_worker, "reader")
        sched.run()
    finally:
        delta_mod.apply_metric_delta = real_apply

    want = list(range(1, n_versions + 1))
    if sorted(applies) != want:
        report(f"delta versions applied {sorted(applies)}, want each of "
               f"{want} exactly once — the version guard raced")
    elif applies != want:
        report(f"applies out of order: {applies} — watermark moved "
               f"backwards")
    store_accounting_invariants(
        store, base_version=1, base_watermark=0, base_rejections=0,
        n_versions=n_versions, n_producers=2, n_updates=n_updates,
        report=report)


# --- scenario: CommitJournal under its external commit lock --------------


def scenario_journal(sched: DetScheduler, report: Report) -> None:
    """Two appenders durably commit IDENTICAL chunk records (the
    idempotent-replay path), a pruner truncates behind a checkpoint
    watermark, and a reader walks the epoch index — every mutation
    under the one shared commit lock, exactly the external:
    guarded-by contract the journal declares. The invariant is the
    journal's reason to exist: a fresh reload of the file equals the
    in-memory index, byte-for-byte per record."""
    import numpy as np

    from koordinator_tpu.scheduler.journal import (
        CommitJournal,
        JournalRecord,
        JournalTail,
    )

    with tempfile.TemporaryDirectory(prefix="racecheck-") as td:
        j = CommitJournal(os.path.join(td, "journal.bin"))
        commit = InstrumentedLock(sched, "commit_lock")
        epochs, n_chunks = (1, 2, 3), 2

        def rec(e: int, c: int) -> JournalRecord:
            return JournalRecord(
                epoch=e, chunk=c, n_chunks=n_chunks, base_version=e,
                delta_watermark=e, batch_digest=e * 7 + c,
                assignment=np.asarray([e * 10 + c], np.int32))

        def appender():
            for e in epochs:
                for c in range(n_chunks):
                    with commit:
                        j.append(rec(e, c))

        def pruner():
            for _ in range(3):
                with commit:
                    j.prune(min_base_version=2)

        def reader():
            for _ in range(4):
                with commit:
                    for e in j.epochs():
                        j.records_for(e)
                    j.next_epoch()

        sched.spawn(appender, "append-a")
        sched.spawn(appender, "append-b")
        sched.spawn(pruner, "prune")
        sched.spawn(reader, "read")
        sched.run()

        if j.tail_reason is not JournalTail.CLEAN:
            report(f"journal tail {j.tail_reason} after clean appends")
        for e in j.epochs():
            got = j.records_for(e)
            for c, r in got.items():
                if not r.same_payload(rec(e, c)):
                    report(f"(epoch {e}, chunk {c}) payload diverged "
                           f"in memory")
        reloaded = CommitJournal(j.path)
        if reloaded.epochs() != j.epochs():
            report(f"reload sees epochs {reloaded.epochs()}, memory "
                   f"has {j.epochs()} — durable and in-memory state "
                   f"diverged")
        for e in j.epochs():
            mem, disk = j.records_for(e), reloaded.records_for(e)
            if set(mem) != set(disk) or not all(
                    mem[c].same_payload(disk[c]) for c in mem):
                report(f"epoch {e} reloads differently than the "
                       f"in-memory index")


# --- scenario: Tracer span storm -----------------------------------------


def scenario_trace(sched: DetScheduler, report: Report) -> None:
    """Eight threads close nested spans into a deliberately tiny ring:
    the guarded-by contract on the buffer is what keeps
    retained + dropped == appended exact under overflow, and the
    thread-local span stacks are what keep each thread's records in
    its own program order (checked via a per-span sequence attr)."""
    from koordinator_tpu.obs.trace import Tracer

    capacity, n_threads, n_spans = 16, 8, 4
    tracer = Tracer(capacity=capacity)
    _instrument(tracer, "_lock", sched, "tracer._lock")

    def storm(tid: int) -> Callable[[], None]:
        def run():
            for i in range(n_spans):
                with tracer.span(f"t{tid}", attrs={"seq": i},
                                 cycle=tid):
                    with tracer.span(f"t{tid}.inner"):
                        pass
        return run

    for tid in range(n_threads):
        sched.spawn(storm(tid), f"span-{tid}")
    sched.run()

    total = n_threads * n_spans * 2  # outer + inner per iteration
    recs = tracer.records()
    if len(recs) != min(total, capacity):
        report(f"ring holds {len(recs)} records, want "
               f"{min(total, capacity)}")
    if len(recs) + tracer.dropped != total:
        report(f"retained {len(recs)} + dropped {tracer.dropped} != "
               f"appended {total} — overflow accounting raced")
    for tid in range(n_threads):
        seqs = [r.attrs["seq"] for r in recs if r.name == f"t{tid}"]
        if seqs != sorted(seqs):
            report(f"thread {tid} records out of program order: {seqs}")
        inner = [r for r in recs if r.name == f"t{tid}.inner"]
        if any(r.parent != f"t{tid}" or r.cycle != tid for r in inner):
            report(f"thread {tid} inner spans lost their parent/cycle "
                   f"— span stacks leaked across threads")


# --- scenario: metrics observe vs export ---------------------------------


def scenario_metrics(sched: DetScheduler, report: Report) -> None:
    """Three observers drive a counter, a histogram, and a labeled
    gauge while an exporter renders the scrape payload and reads
    percentiles mid-flight: every count must land exactly once."""
    from koordinator_tpu.metrics import Registry

    reg = Registry()
    counter = reg.counter("racecheck_total", "racecheck counter")
    hist = reg.histogram("racecheck_seconds", "racecheck histogram",
                         buckets=(0.1, 1.0))
    gauge = reg.gauge("racecheck_inflight", "racecheck gauge",
                      labels=("worker",))
    _instrument(reg, "_lock", sched, "registry._lock")
    for m in (counter, hist, gauge):
        _instrument(m, "_lock", sched, f"{m.name}._lock")

    n_workers, n_obs = 3, 5

    def observer():
        for _ in range(n_obs):
            counter.inc()
            hist.observe(0.5)
            gauge.labels("shared").add(1.0)

    def exporter():
        for _ in range(3):
            reg.expose()
            hist.percentile(0.9)

    for k in range(n_workers):
        sched.spawn(observer, f"observe-{k}")
    sched.spawn(exporter, "export")
    sched.run()

    want = float(n_workers * n_obs)
    if counter.value() != want:
        report(f"counter {counter.value()}, want {want} — an inc was "
               f"lost to a racing read-modify-write")
    if hist.count() != want or hist.sum() != 0.5 * want:
        report(f"histogram count={hist.count()} sum={hist.sum()}, "
               f"want {want}/{0.5 * want}")
    if gauge.value("shared") != want:
        report(f"gauge {gauge.value('shared')}, want {want}")
    line = f"racecheck_total {int(want)}"
    if line not in reg.expose():
        report(f"final exposition missing {line!r}")


SCENARIOS: Dict[str, Callable[[DetScheduler, Report], None]] = {
    "store": scenario_store,
    "journal": scenario_journal,
    "trace": scenario_trace,
    "metrics": scenario_metrics,
}


# --- battery -------------------------------------------------------------


def _run_one(name: str, seed: int, mode: str,
             preempt_budget: Optional[int] = None,
             ) -> Tuple[List[str], List[Tuple[str, str, str, str]], int]:
    """One scenario under one schedule -> (failures, trace, switch
    point count). Worker exceptions and detected deadlocks become
    failures, not crashes, so one red schedule never hides another."""
    sched = DetScheduler(seed=seed, mode=mode,
                         preempt_budget=preempt_budget)
    failures: List[str] = []
    try:
        SCENARIOS[name](sched, failures.append)
    except (RuntimeError, DeadlockError) as exc:
        failures.append(f"scenario raised {type(exc).__name__}: {exc}")
    return failures, sched.trace, sched.switch_points


def run_all(seed: int = 0, verbose: bool = False,
            only: Optional[str] = None, n_seeds: int = 3) -> int:
    names = [n for n in SCENARIOS if only is None or only in n]
    if not names:
        print(f"no scenario matches {only!r}; "
              f"have {sorted(SCENARIOS)}", file=sys.stderr)
        return 2
    failures: List[str] = []
    runs = 0
    for name in names:
        schedules: List[Tuple[str, int, Optional[int]]] = [("rr", 0, None)]
        schedules += [("random", seed + i, None) for i in range(n_seeds)]
        # bounded preemption (small-CHESS): most races need only a
        # couple of forced switches, so a tiny budget is a distinct,
        # cheap slice of schedule space
        schedules.append(("random", seed + n_seeds, 4))
        for mode, s, budget in schedules:
            fails, trace, points = _run_one(name, s, mode, budget)
            runs += 1
            tag = f"{name} [{mode} seed={s}" + (
                f" budget={budget}]" if budget is not None else "]")
            for msg in fails:
                failures.append(f"{tag} {msg}")
            if verbose and not fails:
                print(f"ok   {tag}: {points} switch points, "
                      f"{len(trace)} switches")
        # determinism: the same seed must reproduce the same schedule,
        # or a red run cannot be replayed for debugging
        _, t1, _ = _run_one(name, seed, "random")
        _, t2, _ = _run_one(name, seed, "random")
        runs += 2
        if t1 != t2:
            failures.append(
                f"{name} [random seed={seed}] nondeterministic: two "
                f"runs produced different schedules "
                f"({len(t1)} vs {len(t2)} switches)")
        elif verbose:
            print(f"ok   {name} determinism: seed {seed} replays "
                  f"{len(t1)} switches identically")
    for msg in failures:
        print(f"FAIL {msg}")
    print(f"racecheck: {len(names)} scenario(s), {runs} schedule "
          f"run(s), {len(failures)} failure(s)")
    return 1 if failures else 0


# --- self-test mutations -------------------------------------------------

# Tier-B defect: ingest's version guard runs under a FRESH lock per
# call — mutual exclusion is gone, but every access still happens
# inside *a* with-block, so the static tier (which never guesses about
# unresolvable context managers) cannot see it. Only exploration can.
_STORE_MUT = Mutation(
    relpath="koordinator_tpu/snapshot/store.py",
    anchor=(
        "        with self._lock:\n"
        "            if self._current is None:\n"
        "                raise RuntimeError(\"no snapshot published yet\")\n"
        "            if ver is not None:"),
    replacement=(
        "        with threading.Lock():\n"
        "            if self._current is None:\n"
        "                raise RuntimeError(\"no snapshot published yet\")\n"
        "            if ver is not None:"),
    note="ingest's delta version guard no longer holds the store lock",
)

# Tier-A defect: a cold code path (nothing in this battery drives
# MetricCache) drops its lock entirely — invisible to any dynamic
# explorer that doesn't happen to execute it, caught unconditionally
# by the guarded-by contract check.
_METRIC_MUT = Mutation(
    relpath="koordinator_tpu/koordlet/metriccache.py",
    anchor=(
        "    def set_kv(self, key: str, value: object) -> None:\n"
        "        with self._lock:\n"
        "            self._kv[key] = value"),
    replacement=(
        "    def set_kv(self, key: str, value: object) -> None:\n"
        "        self._kv[key] = value"),
    note="MetricCache.set_kv writes the KV map with no lock",
)


def self_test_mutation() -> int:
    """Prove both tiers live and complementary: each planted defect
    must be caught by exactly its own tier and MISSED by the other."""
    # run by path, not -m: `-m` puts the CWD first on sys.path, which
    # would shadow the mutated tree seedmut prepends via PYTHONPATH
    battery = [sys.executable, os.path.abspath(__file__),
               "--seed", "7", "--seeds", "2"]
    lint = [sys.executable, "-m", "tools.lint", "--root", "{tree}",
            "--analyzers", "race-guard,lock-discipline"]
    rc = 0
    rc = max(rc, check_gate_catches(
        _STORE_MUT, battery, marker="FAIL", label="racecheck"))
    rc = max(rc, check_gate_passes(
        _STORE_MUT, lint, label="race-guard lint"))
    rc = max(rc, check_gate_catches(
        _METRIC_MUT, lint, marker="GB001", label="race-guard lint"))
    rc = max(rc, check_gate_passes(
        _METRIC_MUT, battery, label="racecheck"))
    if rc == 0:
        print("racecheck self-test: both planted defects caught by "
              "exactly their own tier (dynamic explorer + static "
              "contracts are complementary)")
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.racecheck",
        description="koordrace Tier B: deterministic interleaving "
                    "exploration of the guarded concurrent classes")
    parser.add_argument("--seed", type=lambda s: int(s, 0), default=0,
                        help="base schedule seed (default 0)")
    parser.add_argument("--seeds", type=int, default=3,
                        help="number of random schedules per scenario "
                             "(default 3; rr + bounded runs ride along)")
    parser.add_argument("--only", help="substring filter on scenario "
                                       "names")
    parser.add_argument("--self-test-mutation", action="store_true",
                        help="plant one defect per tier and prove each "
                             "is caught by exactly its own tier")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)
    if args.self_test_mutation:
        return self_test_mutation()
    return run_all(seed=args.seed, verbose=args.verbose, only=args.only,
                   n_seeds=args.seeds)


if __name__ == "__main__":
    sys.exit(main())
